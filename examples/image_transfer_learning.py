"""E2E: the "Flower Image Classification" transfer-learning config
(BASELINE #3): ImageFeaturizer (headless imported ONNX backbone) ->
train a head -> evaluate -> score new images.
ref: deep-learning/.../cntk/ImageFeaturizer.scala, notebooks/Flower
Image Classification.
"""
import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.image.featurizer import ImageFeaturizer
from synapseml_tpu.onnx import zoo


def texture_dataset(n_per_class=40, size=32, seed=0):
    """Two texture classes (the flower-photos stand-in: no egress)."""
    rng = np.random.default_rng(seed)
    imgs, labels = [], []
    for cls in (0, 1):
        for _ in range(n_per_class):
            freq = rng.integers(2, 5)
            ramp = np.arange(size) * freq * 2 * np.pi / size
            wave = np.sin(ramp) * 100 + 128
            img = np.tile(wave[None, :] if cls == 0 else wave[:, None],
                          (size, 1) if cls == 0 else (1, size))
            img = img[..., None].repeat(3, -1)
            img = img + rng.normal(0, 20, img.shape)
            imgs.append(np.clip(img, 0, 255).astype(np.uint8))
            labels.append(cls)
    idx = rng.permutation(len(imgs))
    col = np.empty(len(imgs), dtype=object)
    for i, j in enumerate(idx):
        col[i] = imgs[j]
    return col, np.asarray(labels)[idx]


def main():
    imgs, labels = texture_dataset()

    # 1. headless backbone: imported ONNX ResNet with the head cut off
    feat = ImageFeaturizer(model_bytes=zoo.tiny_resnet(image_size=32),
                           cut_output_layers=1, image_size=32,
                           input_col="image")
    feats = np.asarray(feat.transform(Table({"image": imgs}))[
        feat.output_col])
    print(f"backbone features: {feats.shape}")

    # 2. train the transfer head -> 3. evaluate
    from sklearn.linear_model import LogisticRegression

    n_train = 60
    head = LogisticRegression(max_iter=2000).fit(
        feats[:n_train], labels[:n_train])
    acc = head.score(feats[n_train:], labels[n_train:])
    print(f"transfer accuracy: {acc:.3f}")
    assert acc >= 0.85

    # 4. score fresh images end-to-end (featurize -> head)
    fresh, fresh_y = texture_dataset(n_per_class=5, seed=9)
    ff = np.asarray(feat.transform(Table({"image": fresh}))[feat.output_col])
    fresh_acc = head.score(ff, fresh_y)
    print(f"fresh-batch accuracy: {fresh_acc:.3f}")
    assert fresh_acc >= 0.8
    print("E2E image_transfer_learning: PASS")


if __name__ == "__main__":
    main()
