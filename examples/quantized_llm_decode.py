"""E2E: greedy decoding of a tiny int4-quantized decoder-only LM whose
graph uses the ORT-GenAI export idiom — MatMulNBits (blockwise int4
weights) projections, GroupQueryAttention with a KV cache and internal
rotary, and a MatMulNBits LM head — scored entirely through the ONNX
importer on device.

What this certifies (ref ONNXModel.scala:173-193 — the reference scores
whatever onnxruntime runs, and ORT-GenAI quantized LLM exports are that
family's current shape):
- the int4 weights ride the donated device-resident params pytree;
- prefill and per-token decode are TWO compiled programs sharing the
  weights, with past_key/past_value threading the [B, Hkv, T, D] cache;
- incremental decode reproduces full-sequence scoring exactly (causal
  attention + cache contract), greedy tokens match.
"""
import sys

import numpy as np

import jax
import jax.numpy as jnp

from synapseml_tpu.onnx import GraphBuilder, import_model

VOCAB, H, HQ, HKV, D, BLOCK = 64, 32, 4, 2, 8, 16
MAX_T = 32


def _pack_int4(rng, n_out, n_in):
    q = rng.integers(0, 16, (n_out, n_in)).astype(np.uint8)
    nb = n_in // BLOCK
    sc = (rng.random((n_out, nb)) * 0.08 + 0.02).astype(np.float32)
    packed = (q[:, 0::2] | (q[:, 1::2] << 4)).reshape(n_out, nb, BLOCK // 2)
    return packed, sc


def build_decoder(seq_len: int, past_t: int, rng) -> bytes:
    """One-layer decoder graph: ids -> embed -> [q/k/v int4 proj -> GQA
    (rope, cache) -> int4 out proj + residual] -> int4 LM head.
    ``past_t`` = 0 builds the prefill graph; a symbolic dim name (e.g.
    "T") builds ONE decode-step graph whose past length is free — jit
    retraces per concrete cache shape while the weights pytree is
    shared across every step."""
    g = GraphBuilder(opset=21)
    ids = g.add_input("ids", np.int64, ["B", seq_len])
    emb = g.add_initializer(
        "emb", (rng.normal(size=(VOCAB, H)) * 0.3).astype(np.float32))
    x = g.add_node("Gather", [emb, ids])                  # [B, S, H]

    def nbits(name, xin, n_out, n_in):
        pw, sc = _pack_int4(rng, n_out, n_in)
        return g.add_node(
            "MatMulNBits",
            [xin, g.add_initializer(f"{name}_w", pw),
             g.add_initializer(f"{name}_s", sc.reshape(-1))],
            domain="com.microsoft", K=n_in, N=n_out, bits=4,
            block_size=BLOCK)

    qp = nbits("q", x, HQ * D, H)
    kp = nbits("k", x, HKV * D, H)
    vp = nbits("v", x, HKV * D, H)
    cos = np.cos(np.arange(MAX_T)[:, None]
                 / 10000 ** (np.arange(D // 2) / (D // 2))).astype(
        np.float32)
    sin = np.sin(np.arange(MAX_T)[:, None]
                 / 10000 ** (np.arange(D // 2) / (D // 2))).astype(
        np.float32)
    gqa_in = [qp, kp, vp]
    if past_t:
        gqa_in += [g.add_input("past_k", np.float32,
                               ["B", HKV, past_t, D]),
                   g.add_input("past_v", np.float32,
                               ["B", HKV, past_t, D])]
    else:
        gqa_in += ["", ""]
    gqa_in += ["", "", g.add_initializer("cos", cos),
               g.add_initializer("sin", sin)]
    att, prk, prv = g.add_node(
        "GroupQueryAttention", gqa_in, outputs=["att", "prk", "prv"],
        domain="com.microsoft", num_heads=HQ, kv_num_heads=HKV,
        do_rotary=1)
    proj = nbits("o", att, H, HQ * D)
    hidden = g.add_node("Add", [x, proj])
    logits = nbits("lm", hidden, VOCAB, H)
    g.add_output(logits, np.float32, None)
    g.add_output(prk, np.float32, None)
    g.add_output(prv, np.float32, None)
    return g.to_bytes()


def main():
    b, prefill_len, gen = 2, 6, 8

    # TWO graphs sharing identical weights (same seed, same build
    # order): prefill, and one decode-step graph with a symbolic past
    # dim — each decode shape retraces the SAME program + params pytree
    g_pre = import_model(build_decoder(prefill_len, 0,
                                       np.random.default_rng(7)))
    g_dec = import_model(build_decoder(1, "T", np.random.default_rng(7)))
    dec = jax.jit(g_dec.apply)

    prompt = np.random.default_rng(1).integers(
        0, VOCAB, (b, prefill_len)).astype(np.int64)

    pre = jax.jit(g_pre.apply)
    logits, pk, pv = pre(g_pre.params, jnp.asarray(prompt))
    int4_bytes = sum(v.nbytes for k, v in g_pre.params.items()
                     if k.endswith("_w"))
    print(f"prefill: logits {np.asarray(logits).shape}, cache "
          f"{np.asarray(pk).shape}; int4 param bytes in donated "
          f"pytree: {int4_bytes}")

    # first generated token comes from the PREFILL logits; the cache
    # then covers every token except the newest, which each decode step
    # feeds (and appends to the returned present cache)
    nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
    tokens = np.concatenate([prompt, nxt[:, None].astype(np.int64)], 1)
    for _ in range(gen - 1):
        logits, pk, pv = dec(g_dec.params, jnp.asarray(tokens[:, -1:]),
                             pk, pv)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        tokens = np.concatenate([tokens, nxt[:, None].astype(np.int64)],
                                axis=1)
    print("greedy tokens:", tokens[0].tolist())

    # certification: the incremental KV-cache decode must match scoring
    # the final sequence in ONE full forward (causal + cache contract)
    g_full = import_model(build_decoder(tokens.shape[1], 0,
                                        np.random.default_rng(7)))
    full_logits = np.asarray(
        jax.jit(g_full.apply)(g_full.params, jnp.asarray(tokens))[0])
    full_greedy = full_logits.argmax(-1)
    for i in range(prefill_len, tokens.shape[1]):
        # token i was produced from position i-1's logits
        assert (tokens[:, i] == full_greedy[:, i - 1]).all(), (
            f"incremental decode diverged from full scoring at {i}")
    print("incremental == full-sequence greedy: PASS")
    print("E2E quantized_llm_decode: PASS")


if __name__ == "__main__":
    sys.exit(main())
