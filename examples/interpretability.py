"""E2E: the interpretability config (BASELINE #4): KernelSHAP over a
TPU-scored LightGBM->ONNX model and ImageLIME over an image scorer.
ref: notebooks/Interpretability - Tabular SHAP / Image Explainers,
core/src/main/scala/com/microsoft/ml/spark/explainers/.
"""
import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.explainers.local import ImageLIME, TabularSHAP
from synapseml_tpu.gbdt.estimators import LightGBMClassifier
from synapseml_tpu.onnx import ONNXModel, convert_lightgbm


def main():
    # 1. a real trained model served through the ONNX scorer
    rng = np.random.default_rng(0)
    x = rng.normal(size=(600, 3)).astype(np.float32)
    y = (2.0 * x[:, 0] - 1.0 * x[:, 1] > 0).astype(np.float64)
    model = LightGBMClassifier(num_iterations=30, num_leaves=15).fit(
        Table({"features": x, "label": y}))
    scorer = ONNXModel(model_bytes=convert_lightgbm(model),
                       feed_dict={"input": "features"})

    class OnnxScorer:
        def transform(self, t: Table) -> Table:
            feats = np.column_stack([t["f0"], t["f1"], t["f2"]]).astype(
                np.float32)
            probs = np.asarray(
                scorer.transform(Table({"features": feats}))[
                    "probabilities"])
            return t.with_column("probability", probs)

    # 2. KernelSHAP attribution: f0 must dominate, f2 must be noise
    shap = TabularSHAP(model=OnnxScorer(), input_cols=["f0", "f1", "f2"],
                       target_col="probability", target_classes=(1,),
                       num_samples=64, seed=0)
    t = Table({"f0": x[:16, 0], "f1": x[:16, 1], "f2": x[:16, 2]})
    phis = np.asarray(shap.transform(t)["output"])[:, 0, :]
    mean_abs = np.abs(phis[:, 1:]).mean(axis=0)  # col 0 is the base value
    print(f"mean |phi|: f0={mean_abs[0]:.3f} f1={mean_abs[1]:.3f} "
          f"f2={mean_abs[2]:.3f}")
    assert mean_abs[0] > mean_abs[2] * 3

    # 3. ImageLIME: the bright patch must get the credit
    class Brightness:
        def transform(self, t: Table) -> Table:
            probs = np.stack([
                np.array([im.mean()], np.float32) for im in t["image"]])
            return t.with_column("probability", probs)

    img = rng.random((16, 16, 3)).astype(np.float32) * 0.2
    img[4:12, 4:12] = 0.9
    lime = ImageLIME(model=Brightness(), input_col="image",
                     target_col="probability", target_classes=(0,),
                     num_samples=40, seed=0, cell_size=8.0)
    out = lime.transform(Table({"image": [img]}))
    coefs = np.asarray(out["output"])[0, 0]
    sp = out["superpixels"][0]
    assert int(np.argmax(coefs[:sp.max() + 1])) == int(sp[8, 8])
    print("ImageLIME: bright superpixel ranked first")
    print("E2E interpretability: PASS")


if __name__ == "__main__":
    main()
