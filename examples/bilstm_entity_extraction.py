"""E2E: the "Medical Entity Extraction" sequence-model config
(BASELINE #5): train the mesh-parallel sequence tagger on a synthetic
entity task until it learns, then run the BiLSTM-tagger ONNX graph
(the reference's exact model family) through the importer.
ref: notebooks/Medical Entity Extraction, deep-learning/.../cntk/.
"""
import numpy as np

import jax

from synapseml_tpu.dl.tagger import TaggerConfig, make_train_step, make_apply
from synapseml_tpu.onnx import import_model, zoo
from synapseml_tpu.parallel.mesh import build_mesh


def entity_batches(rng, vocab, n_tags, b, s):
    """Tokens 0..9 are 'entity' words tagged 1, the rest tagged 0;
    tag 2 marks the token after an entity (a BIO-ish structure)."""
    tokens = rng.integers(10, vocab, (b, s)).astype(np.int32)
    ent = rng.random((b, s)) < 0.2
    tokens[ent] = rng.integers(0, 10, ent.sum())
    labels = np.zeros((b, s), np.int32)
    labels[ent] = 1
    after = np.roll(ent, 1, axis=1)
    after[:, 0] = False
    labels[after & ~ent] = 2
    return tokens, labels


def main():
    mesh = build_mesh(jax.devices())
    print(f"mesh: {dict(mesh.shape)}")
    cfg = TaggerConfig.for_mesh(mesh, vocab_size=64, num_tags=4,
                                d_model=32, head_dim=8, ffn_dim=64,
                                max_seq_len=16)
    step, init_state, batch_shard = make_train_step(cfg, mesh,
                                                    learning_rate=3e-3)
    params, opt_state = init_state()
    rng = np.random.default_rng(0)
    b, s = 16, 16
    losses = []
    for i in range(200):
        tokens, labels = entity_batches(rng, cfg.vocab_size, cfg.num_tags,
                                        b, s)
        mask = np.ones((b, s), np.bool_)
        params, opt_state, loss = step(
            jax.device_put(params) if i == 0 else params, opt_state,
            jax.device_put(tokens, batch_shard),
            jax.device_put(labels, batch_shard),
            jax.device_put(mask, batch_shard))
        losses.append(float(loss))
    print(f"loss: {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0] * 0.6, "tagger failed to learn"

    # held-out tagging accuracy through the sharded apply fn
    apply_fn = make_apply(cfg, mesh)
    tokens, labels = entity_batches(rng, cfg.vocab_size, cfg.num_tags, b, s)
    logits, _ = apply_fn(params, jax.device_put(tokens, batch_shard))
    acc = (np.asarray(logits).argmax(-1) == labels).mean()
    print(f"held-out token accuracy: {acc:.3f}")
    assert acc > 0.8

    # the reference's exact model family as ONNX: BiLSTM tagger graph
    g = import_model(zoo.bilstm_tagger(vocab=64, embed=16, hidden=16,
                                       n_tags=4, seq_len=16))
    out = np.asarray(g.apply(g.params, tokens.astype(np.int64))[0])
    assert out.shape == (b, 16, 4)
    print("BiLSTM ONNX graph scored:", out.shape)

    # and the reference's native path: a recurrent CNTK v2 binary .model
    # (bidirectional PastValue/FutureValue cycles -> ONNX Scan ->
    # lax.scan) scored through CNTKModel, matching its frozen outputs
    import os

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.dl.cntk import CNTKModel

    fx = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "fixtures", "cntk_rnn.model")
    io = np.load(fx.replace(".model", "_io.npz"))
    cm = CNTKModel(model_path=fx)
    md = cm.model_metadata()
    cm.set(feed_dict={list(md["inputs"])[0]: "x"},
           fetch_dict={"y": md["outputs"][0]})
    got = np.asarray(cm.transform(Table({"x": io["input"]}))["y"])
    np.testing.assert_allclose(got, io["expected"], rtol=2e-5, atol=2e-5)
    print("recurrent CNTK .model scored:", got.shape)

    # --- the speech scenario as ONE streaming pipeline (ref:
    # SpeechToTextSDK.scala + AudioStreams.scala:94): committed WAV ->
    # endpointer -> ON-DEVICE log-mel (AudioFeaturizer's ONNX STFT/Mel
    # graph) -> recurrent CNTK OptimizedRNNStack model over the mel
    # frames -> per-utterance rows
    from synapseml_tpu.cognitive import (utterance_feature_batch,
                                         wav_to_utterance_rows)
    from synapseml_tpu.dl.cntk_format import build_optimized_rnn_model

    wav_path = os.path.join(os.path.dirname(fx), "utterances.wav")
    with open(wav_path, "rb") as fh:
        rows = wav_to_utterance_rows(fh.read())
    print(f"utterances: {rows.num_rows}")
    assert rows.num_rows == 3

    mel, hidden = 64, 16
    am = CNTKModel(model_bytes=build_optimized_rnn_model(
        mel, hidden, bidirectional=True, cell="lstm", seed=11))
    md = am.model_metadata()
    am.set(feed_dict={list(md["inputs"])[0]: "mel"},
           fetch_dict={"state": md["outputs"][0]})
    batch, n_frames = utterance_feature_batch(rows)
    states = np.asarray(am.transform(Table({"mel": batch}))["state"])
    assert states.shape == (rows.num_rows, batch.shape[1], 2 * hidden)
    for i in range(rows.num_rows):
        vec = states[i, :n_frames[i]].mean(axis=0)
        print(f"  utterance {i}: {rows['t_start'][i]:.2f}-"
              f"{rows['t_end'][i]:.2f}s {n_frames[i]} frames "
              f"state|mean|={np.abs(vec).mean():.4f}")
    print("E2E bilstm_entity_extraction: PASS")


if __name__ == "__main__":
    main()
