"""E2E: the "ONNX - Inference on Spark" notebook config (BASELINE #2).

Import a full ResNet-50 ONNX graph (and a *foreign* torch-exported
fixture) -> batched Table scoring through ONNXModel -> serve the scorer
over HTTP. ref: notebooks/ONNX - Inference on Spark.ipynb,
deep-learning/.../onnx/ONNXModel.scala
"""
import json
import os
import threading
import urllib.request

import jax
import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.io.serving import ContinuousServer, make_reply
from synapseml_tpu.onnx import ONNXModel, import_model, zoo


def main():
    # 1. the flagship graph: full-depth ResNet-50 (reduced spatial size so
    # the example runs quickly on CPU CI; the bench runs 224x224 on chip)
    blob = zoo.resnet50(num_classes=1000, image_size=32)
    model = ONNXModel(model_bytes=blob, feed_dict={"data": "images"},
                      argmax_output_col="prediction", mini_batch_size=8)
    rng = np.random.default_rng(0)
    images = rng.normal(size=(24, 3, 32, 32)).astype(np.float32)
    out = model.transform(Table({"images": images}))
    assert np.asarray(out["prediction"]).shape == (24,)
    print("ResNet-50 batch scoring (24 imgs, bucketed): ok")

    # 2. a REAL foreign file: torch.onnx-exported fixture with dynamic
    # batch dims and Shape-chain Flatten (committed bytes + expected IO)
    fx = os.path.join(os.path.dirname(__file__), os.pardir, "tests",
                      "fixtures", "torch_cnn.onnx")
    g = import_model(fx)
    io = np.load(fx.replace(".onnx", "_io.npz"))
    got = np.asarray(g.apply(g.params, io["input"])[0])
    # TPU MXU matmuls round f32 operands through bf16 at default
    # precision (~1e-3 relative); CPU reproduces torch to 1e-5
    tol = 1e-5 if jax.default_backend() == "cpu" else 3e-3
    np.testing.assert_allclose(got, io["expected"], atol=tol, rtol=tol)
    print("foreign torch-exported .onnx parity: ok")

    # 3. serve the ONNX scorer over HTTP
    def pipeline(table: Table) -> Table:
        feats = np.stack([np.asarray(v["image"], np.float32)
                          for v in table["value"]])
        scored = model.transform(Table({"images": feats}))
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply(
                {"class": int(scored["prediction"][i])})
        return table.with_column("reply", replies)

    cs = ContinuousServer("e2e_onnx", pipeline, max_batch=8).start()
    try:
        got = {}

        def client(i):
            req = urllib.request.Request(
                cs.url, json.dumps({"image": images[i].tolist()}).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                got[i] = json.loads(resp.read())["class"]

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        direct = np.asarray(out["prediction"])[:4]
        assert all(got[i] == direct[i] for i in range(4))
        print("ONNX serving round trip x4: ok")
    finally:
        cs.stop()
    print("E2E onnx_inference: PASS")


if __name__ == "__main__":
    main()
