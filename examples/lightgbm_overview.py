"""E2E: the "LightGBM - Overview" notebook config (BASELINE #1).

train -> evaluate -> save native model -> reload -> export ONNX ->
ONNXModel re-score -> live HTTP serving -> score over the wire.
Runs on any backend (CI uses CPU); `tools/ci/pipeline.yaml` executes it.
ref: notebooks/LightGBM - Overview.ipynb
"""
import json
import tempfile
import threading
import urllib.request

import numpy as np

from synapseml_tpu.data.table import Table
from synapseml_tpu.gbdt.boosting import Booster
from synapseml_tpu.gbdt.estimators import LightGBMClassifier
from synapseml_tpu.io.serving import ContinuousServer, make_reply
from synapseml_tpu.onnx import ONNXModel, convert_lightgbm


def adult_census_shaped(n=4000, seed=0):
    """Synthetic stand-in for Adult Census (14 features, income>50k)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 14)).astype(np.float32)
    x[:, 0] = rng.integers(17, 80, n)                  # age
    x[:, 4] = np.abs(rng.normal(40, 12, n))            # hours/week
    logits = (0.04 * (x[:, 0] - 38) + 0.05 * (x[:, 4] - 40)
              + x[:, 1] - 0.5 * x[:, 2] + 0.3 * x[:, 3] * x[:, 5])
    y = (logits + rng.logistic(scale=0.7, size=n) > 0).astype(np.float64)
    return x, y


def main():
    x, y = adult_census_shaped()
    cut = 3000
    train_t = Table({"features": x[:cut], "label": y[:cut]})

    # 1. train (early stopping against a validation split)
    model = LightGBMClassifier(
        num_iterations=80, num_leaves=31, learning_rate=0.1).fit(train_t)

    # 2. evaluate
    from sklearn.metrics import roc_auc_score

    auc = roc_auc_score(y[cut:], model.booster.predict(x[cut:]))
    print(f"holdout AUC: {auc:.4f}")
    assert auc > 0.85, "model quality regressed"

    # 3. save native LightGBM text format -> 4. reload
    with tempfile.NamedTemporaryFile("w", suffix=".txt",
                                     delete=False) as fh:
        fh.write(model.booster.save_string())
        path = fh.name
    with open(path) as fh:
        reloaded = Booster.load_string(fh.read())
    np.testing.assert_allclose(reloaded.predict(x[cut:]),
                               model.booster.predict(x[cut:]), atol=1e-6)
    print("native-format round trip: ok")

    # 5. export ONNX, score through ONNXModel (the notebook's ONNX leg)
    scorer = ONNXModel(model_bytes=convert_lightgbm(model),
                       feed_dict={"input": "features"})
    onnx_probs = np.asarray(
        scorer.transform(Table({"features": x[cut:]}))["probabilities"])
    np.testing.assert_allclose(onnx_probs[:, 1],
                               model.booster.predict(x[cut:]), atol=1e-5)
    print("ONNX export/rescore parity: ok")

    # 6. serve live over HTTP -> 7. score over the wire
    def pipeline(table: Table) -> Table:
        feats = np.stack([np.asarray(v["features"], np.float32)
                          for v in table["value"]])
        probs = model.booster.predict(feats)
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"p": float(probs[i])})
        return table.with_column("reply", replies)

    cs = ContinuousServer("e2e_lgbm", pipeline, max_batch=32).start()
    try:
        got = {}

        def client(i):
            req = urllib.request.Request(
                cs.url, json.dumps(
                    {"features": x[cut + i].tolist()}).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                got[i] = json.loads(resp.read())["p"]

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        direct = model.booster.predict(x[cut:cut + 8])
        for i in range(8):
            assert abs(got[i] - direct[i]) < 1e-6
        print("serving round trip x8: ok")
    finally:
        cs.stop()
    print("E2E lightgbm_overview: PASS")


if __name__ == "__main__":
    main()
