"""Benchmark driver — prints ONE JSON line.

North-star metrics (BASELINE.md / BASELINE.json):
1. ONNX ResNet-50 inference images/sec/chip through the *imported* ONNX graph
   (protobuf parse -> node lowering -> jit), the "ONNX - Inference on Spark"
   workload. Primary metric. Nominal GPU-VM baseline: 1000 img/s (T4-class,
   ORT-CUDA fp16, bs128).
2. LightGBM training rows/sec/chip on an Adult-census-scale workload
   (32561 rows x 14 features, 100 iterations, 31 leaves), the
   "LightGBM - Overview" workload. Nominal GPU-VM baseline: 1.0e6
   rows*iters/sec (lib_lightgbm CUDA on T4 trains this in ~3.3s).

Runs on whatever jax.devices() provides (the real TPU chip under the driver).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def bench_onnx_resnet50():
    """(device_resident_img_s, host_feed_img_s) through the imported graph.

    Device-resident isolates chip throughput (the ORT-CUDA analogue: data
    already in device memory); host-feed includes the host->device copy per
    batch, which on this driver rides a network tunnel to the chip and is
    bandwidth-bound — on a co-located TPU-VM host it approaches the former.

    Graph provenance: zoo.resnet50 emits real .onnx bytes through the
    same parse->lower->jit path as user files; the importer is certified
    against FOREIGN bytes by the committed torch.onnx-exported fixtures
    (tests/fixtures/torch_{cnn,gru,transformer}.onnx, frozen expected
    outputs) and by full-network ResNet-50/18 torch-twin parity
    (tests/test_onnx_foreign.py, tests/test_onnx.py).
    """
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx import ONNXModel, import_model, zoo
    from synapseml_tpu.onnx.model import routed_compute_dtype

    batch = 128
    blob = zoo.resnet50(num_classes=1000)
    images_np = np.random.default_rng(0).standard_normal(
        (batch, 3, 224, 224)).astype(np.float32)

    # -- device-resident path: jitted imported graph, input stays in HBM.
    # The N forwards run inside one fori_loop with a data dependency (the
    # accumulated sum feeds the next input) so XLA cannot hoist the body,
    # and a single scalar fetch at the end forces real completion —
    # block_until_ready is unreliable on tunneled device platforms.
    # The compute dtype is the autotuner's MEASURED verdict (lane
    # "onnx_compute_dtype") instead of the former bf16 hardcode: bf16 on
    # an MXU, f32 where bf16 is emulation theater.
    graph = import_model(blob)
    routed_dtype = routed_compute_dtype(graph, blob, batch)
    cast = jnp.bfloat16 if routed_dtype == "bfloat16" else None
    fwd_fn = graph.bind(cast_dtype=cast)
    iters = 30

    @jax.jit
    def loop(img):
        def body(i, acc):
            x = img + (acc * 0).astype(img.dtype)
            return acc + fwd_fn(x)[0].sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    images_dev = jnp.asarray(
        images_np, jnp.bfloat16 if cast is not None else jnp.float32)
    float(loop(images_dev))  # compile + warmup, forced by the value fetch
    start = time.perf_counter()
    float(loop(images_dev))
    dev_img_s = batch * iters / (time.perf_counter() - start)

    # -- host-feed path: the full ONNXModel executor incl. per-batch copy.
    # A multi-batch stream through ONE call engages the executor's
    # pipelined feed: batch N+1's host->device copy is dispatched before
    # batch N's fetch blocks (runtime/executor.py), the IOBinding-style
    # overlap. The wire format is uint8 pixels (1 byte/px — what cameras
    # and JPEG decoders hand you) with the (x - mean) * scale -> bf16
    # dequant fused on device via input_norm: on a 35 MB/s tunnel (and on
    # PCIe in co-located deployments) bytes-on-the-wire IS the metric.
    # ImageNet-ish normalization: mean 127.5, scale 1/58 per channel.
    def make_leg(model_kwargs, warm_batch):
        model = ONNXModel(model_bytes=blob, mini_batch_size=batch,
                          compute_dtype="auto", **model_kwargs)
        executor = model._executor()
        stream = np.concatenate([warm_batch] * 5, axis=0)
        executor(warm_batch)  # compile + warm the bucket
        def run():
            start = time.perf_counter()
            out = executor(stream)
            np.asarray(out[0])  # already host; guard against lazy types
            return len(stream) / (time.perf_counter() - start)
        return run, model

    images_u8 = np.random.default_rng(0).integers(
        0, 256, (batch, 3, 224, 224), dtype=np.uint8)
    leg_u8, model_u8 = make_leg(
        {"input_norm": {"data": {"mean": 127.5, "scale": 1 / 58.0}}},
        images_u8)
    # the uint8-vs-float wire choice is the autotuner's routed verdict
    # now (lane "onnx_hostfeed_wire"), not the former hardcode; the
    # losing leg still runs as the A/B companion for docs/perf.md. The
    # legs run INTERLEAVED, best-of-3 each: tunnel bandwidth drifts 2x
    # over tens of seconds, so sequential legs can invert the ordering.
    wire = model_u8.preferred_wire("data")
    leg_float, _ = make_leg({}, images_np)

    # -- async submit/drain CROSS-CALL overlap A/B: the same 5 uint8
    # batches scored (a) as 5 sequential __call__s — each blocks on its
    # own result, so the pipeline fully drains between calls, the
    # per-request shape every serving scorer and mini-batch transform
    # caller has — vs (b) executor.stream, which keeps pipeline_depth
    # submissions in flight so batch k+1's host staging and H2D overlap
    # batch k's compute and D2H drain across call boundaries
    # (runtime/executor.py). A single multi-batch __call__ already
    # pipelines internally (that path is the hostfeed metric above);
    # this pair isolates what the submit/drain API adds BETWEEN calls.
    def make_overlap_legs(model_kwargs, warm_batch):
        model = ONNXModel(model_bytes=blob, mini_batch_size=batch,
                          compute_dtype="auto", **model_kwargs)
        executor = model._executor()
        batches = [warm_batch] * 5
        executor(warm_batch)  # compile + warm the bucket
        def run_calls():
            start = time.perf_counter()
            rows = 0
            for b in batches:
                (out,) = executor(b)
                rows += len(np.asarray(out))
            return rows / (time.perf_counter() - start)
        def run_stream():
            start = time.perf_counter()
            rows = 0
            for (out,) in executor.stream((b,) for b in batches):
                rows += len(np.asarray(out))
            return rows / (time.perf_counter() - start)
        return run_calls, run_stream

    leg_calls, leg_stream = make_overlap_legs(
        {"input_norm": {"data": {"mean": 127.5, "scale": 1 / 58.0}}},
        images_u8)
    u8_img_s = float_img_s = pipe_img_s = seq_call_img_s = 0.0
    for _ in range(3):
        u8_img_s = max(u8_img_s, leg_u8())
        float_img_s = max(float_img_s, leg_float())
        seq_call_img_s = max(seq_call_img_s, leg_calls())
        pipe_img_s = max(pipe_img_s, leg_stream())
    host_img_s = u8_img_s if wire == "uint8" else float_img_s
    host_alt_img_s = float_img_s if wire == "uint8" else u8_img_s
    return (dev_img_s, host_img_s, host_alt_img_s, pipe_img_s,
            seq_call_img_s, routed_dtype, wire)


def bench_onnx_resnet50_fast():
    """CI-sized twin of bench_onnx_resnet50 (image_size=64, bs=16) with
    every serving lane ROUTED and its forced-alternate A/B measured —
    the bench-smoke group that gates the autotuner's headline win on a
    CPU runner, where the routed f32 verdict beats the old bf16
    hardcode (bf16 is emulated on host SIMD) by construction of
    MEASUREMENT, not by construction of the bench."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx import ONNXModel, import_model, zoo
    from synapseml_tpu.onnx.model import routed_compute_dtype

    batch, iters = 16, 6
    blob = zoo.resnet50(num_classes=1000, image_size=64)
    graph = import_model(blob)
    routed_dtype = routed_compute_dtype(graph, blob, batch)
    images_np = np.random.default_rng(0).standard_normal(
        (batch, 3, 64, 64)).astype(np.float32)

    def device_leg(dtype_choice):
        cast = jnp.bfloat16 if dtype_choice == "bfloat16" else None
        fwd = graph.bind(cast_dtype=cast)

        def loop(img):
            def body(i, acc):
                x = img + (acc * 0).astype(img.dtype)
                return acc + fwd(x)[0].sum().astype(jnp.float32)
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

        img = jnp.asarray(
            images_np, jnp.bfloat16 if cast is not None else jnp.float32)
        compiled = jax.jit(loop).lower(img).compile()
        _record_cost(compiled, bucket=batch, arity=1, layout="single",
                     sig=f"resnet50_fast[{dtype_choice}]")
        float(compiled(img))  # warm, forced by the value fetch

        def run():
            start = time.perf_counter()
            float(compiled(img))
            return batch * iters / (time.perf_counter() - start)
        return run

    other_dtype = "float32" if routed_dtype == "bfloat16" else "bfloat16"
    leg_routed = device_leg(routed_dtype)
    leg_other = device_leg(other_dtype)

    # hostfeed through the full auto-dtype executor, wire routed by the
    # "onnx_hostfeed_wire" lane; the losing wire runs as the A/B
    images_u8 = np.random.default_rng(0).integers(
        0, 256, (batch, 3, 64, 64), dtype=np.uint8)

    def make_leg(model_kwargs, warm_batch):
        model = ONNXModel(model_bytes=blob, mini_batch_size=batch,
                          compute_dtype="auto", **model_kwargs)
        executor = model._executor()
        stream = np.concatenate([warm_batch] * 3, axis=0)
        executor(warm_batch)

        def run():
            start = time.perf_counter()
            out = executor(stream)
            np.asarray(out[0])
            return len(stream) / (time.perf_counter() - start)
        return run, model

    leg_u8, model_u8 = make_leg(
        {"input_norm": {"data": {"mean": 127.5, "scale": 1 / 58.0}}},
        images_u8)
    leg_float, _ = make_leg({}, images_np)
    wire = model_u8.preferred_wire("data")

    r_img_s = a_img_s = u8_img_s = float_img_s = 0.0
    for _ in range(2):  # interleaved best-of: box contention drifts
        r_img_s = max(r_img_s, leg_routed())
        a_img_s = max(a_img_s, leg_other())
        u8_img_s = max(u8_img_s, leg_u8())
        float_img_s = max(float_img_s, leg_float())
    host_img_s = u8_img_s if wire == "uint8" else float_img_s
    host_alt_img_s = float_img_s if wire == "uint8" else u8_img_s
    return (r_img_s, a_img_s, routed_dtype, host_img_s, host_alt_img_s,
            wire)


def _entries_resnet50_fast():
    (r_img_s, a_img_s, routed_dtype, host_img_s, host_alt_img_s,
     wire) = _with_retries(bench_onnx_resnet50_fast)
    return [{
        "metric": "onnx_resnet50_images_per_sec_per_chip",
        "value": round(r_img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(r_img_s / GPU_IMG_BASELINE, 3),
        "detail": {"compute_dtype": routed_dtype,
                   "alternate_dtype": (
                       "float32" if routed_dtype == "bfloat16"
                       else "bfloat16"),
                   "alternate_dtype_images_per_sec": round(a_img_s, 2),
                   "image_size": 64, "batch": 16},
    }, {
        "metric": "onnx_resnet50_hostfeed_images_per_sec",
        "value": round(host_img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(host_img_s / GPU_IMG_BASELINE, 3),
        "detail": {"wire": wire,
                   "alternate_wire_images_per_sec": round(
                       host_alt_img_s, 2)},
    }]


def bench_executor_dp_scaling():
    """1-chip vs all-chips A/B through the multi-device BatchedExecutor:
    the same ResNet-50 micro-batch stream scored with ``devices=None``
    (single device) and ``devices="all"`` (each bucket dp-sharded across
    the mesh — runtime/executor.py). Inputs are DEVICE-RESIDENT bf16
    (resharding rides ICI/D2D, not the host tunnel), so the pair isolates
    how compute+dispatch scale with chip count — the per-chip headline
    metric times N is the ceiling this measures progress toward. On a
    1-device platform both legs run the identical path (speedup ~1.0,
    the zero-regression guard).

    Returns (all_devices_img_s, single_device_img_s, n_devices)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx import ONNXModel, zoo

    batch = 128
    ndev = len(jax.local_devices())
    # enough batches that per-batch dispatch overhead amortizes and the
    # fast leg still runs long enough to time; scaled with the topology
    n_batches = max(4, 4 * ndev)
    blob = zoo.resnet50(num_classes=1000)
    images = np.random.default_rng(0).standard_normal(
        (batch, 3, 224, 224)).astype(np.float32)

    def make_leg(devices):
        model = ONNXModel(model_bytes=blob, mini_batch_size=batch,
                          compute_dtype="bfloat16")
        if devices is not None:
            model.set(devices=devices)
        ex = model._executor()
        # one shared device-resident batch: every submit resharding off
        # device 0 is a D2D copy; no output aliases its shape/dtype, so
        # the executor's donation mask leaves the shared buffer alone
        img = jax.device_put(jnp.asarray(images, jnp.bfloat16),
                             jax.local_devices()[0])
        ex(img)  # compile + warm the bucket (both layouts)
        def run():
            start = time.perf_counter()
            rows = 0
            for (out,) in ex.stream((img,) for _ in range(n_batches)):
                rows += len(np.asarray(out))
            return rows / (time.perf_counter() - start)
        return run

    leg_one = make_leg(None)
    if ndev == 1:
        # one device: the legs are the same code path (the sharded layout
        # never engages) — time it once, speedup is 1.0 by construction
        one_img_s = max(leg_one() for _ in range(2))
        return one_img_s, one_img_s, ndev
    leg_all = make_leg("all")
    one_img_s = all_img_s = 0.0
    for _ in range(2):  # interleaved best-of-2: tunnel jitter
        one_img_s = max(one_img_s, leg_one())
        all_img_s = max(all_img_s, leg_all())
    return all_img_s, one_img_s, ndev


def bench_onnx_tp_scaling():
    """tp=1 vs tp=all A/B through the full ONNXModel executor path: the
    same transformer token stream scored with the weights replicated
    (``tensor_parallel=1``) and registry-placed over every chip
    (``tensor_parallel=<ndev>``, dp=1 — parallel/partition_rules.py).
    Under the default reduction-free rules + the executor's gather
    formulation both legs are BIT-identical; what this measures is the
    price of serving tp-sharded at rest (the entry all-gather) against
    the per-device HBM it buys — ``param_bytes_per_device`` max rides in
    the detail as the memory half of the trade. On a 1-device platform
    both legs run the identical path (speedup ~1.0, the zero-regression
    guard).

    Returns (tp_seq_s, one_seq_s, ndev, tp_detail)."""
    import jax

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.onnx import ONNXModel, zoo
    from synapseml_tpu.parallel.onnx_tp import param_bytes_per_device

    vocab, d, heads, ff, layers, s, bs = 1000, 128, 4, 512, 2, 32, 32
    ndev = len(jax.local_devices())
    n_batches = max(4, 2 * ndev)
    payload = zoo.transformer_encoder(vocab, d, heads, ff, layers,
                                      seq_len=s, seed=0)
    ids = np.random.default_rng(0).integers(
        0, vocab, (bs, s)).astype(np.int32)

    def make_leg(tp):
        model = ONNXModel(model_payload=payload, mini_batch_size=bs)
        model.set(feed_dict={model.graph.input_names[0]:
                             model.graph.input_names[0]})
        if tp > 1:
            model.set(devices="all", tensor_parallel=tp)
        ex = model._executor()
        # AOT warmup (not a lazy first call): records the compiled
        # flops/bytes signature into the cost table under this group's
        # tag — what perf_report joins on to attribute the roofline row
        ex.warmup([((s,), np.int32)], buckets=[bs])
        ex(ids)  # weights placed, bucket served from the AOT table

        def run():
            start = time.perf_counter()
            rows = 0
            for (out, *_rest) in ex.stream(
                    (ids,) for _ in range(n_batches)):
                rows += len(np.asarray(out))
            return rows / (time.perf_counter() - start)
        per_dev = param_bytes_per_device(ex._bound)
        return run, ex, per_dev

    leg_one, ex_one, per_dev_one = make_leg(1)
    total_bytes = sum(per_dev_one.values()) or max(
        per_dev_one.values(), default=0)
    if ndev == 1:
        one_seq_s = max(leg_one() for _ in range(2))
        detail = {"devices": 1, "tensor_parallel": 1,
                  "partition": "dp1xtp1",
                  "param_bytes_per_device_max": int(max(
                      per_dev_one.values(), default=0)),
                  "param_bytes_total": int(total_bytes)}
        ex_one.close()
        return one_seq_s, one_seq_s, ndev, detail
    leg_tp, ex_tp, per_dev_tp = make_leg(ndev)
    one_seq_s = tp_seq_s = 0.0
    for _ in range(2):  # interleaved best-of-2: scheduler jitter
        one_seq_s = max(one_seq_s, leg_one())
        tp_seq_s = max(tp_seq_s, leg_tp())
    detail = {"devices": ndev, "tensor_parallel": ndev,
              "partition": f"dp1xtp{ndev}",
              "param_bytes_per_device_max": int(max(
                  per_dev_tp.values(), default=0)),
              "param_bytes_total": int(sum(
                  v.nbytes for v in ex_one._bound[0].values())),
              "single_param_bytes_per_device": int(max(
                  per_dev_one.values(), default=0))}
    ex_one.close()
    ex_tp.close()
    return tp_seq_s, one_seq_s, ndev, detail


def bench_gbdt_train():
    """Returns (rows*iters/s of the production 'auto' routing, plus the
    FULL-LOOP pallas-vs-xla A/B at the same Adult shape — the round-3
    review required the end-to-end comparison in the committed JSON, not
    a remembered experiment; grower.resolve_hist_backend routes 'auto'
    on a cached in-context probe)."""
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier

    n, d = 32561, 14
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x @ rng.normal(size=(d,)) + 0.5 * np.sin(3 * x[:, 0]) * x[:, 1]
    y = (logits + rng.normal(scale=0.5, size=n) > 0).astype(np.int32)
    table = Table({"features": x, "label": y})

    def leg(backend):
        est = LightGBMClassifier(num_iterations=100, num_leaves=31,
                                 learning_rate=0.1, hist_backend=backend)
        est.fit(table)  # warmup: compile of binning + grower loop
        best = float("inf")
        for _ in range(3):  # best-of-3: the tunnel adds run-to-run jitter
            start = time.perf_counter()
            est.fit(table)
            best = min(best, time.perf_counter() - start)
        return n * 100 / best

    auto_rows_s = leg("auto")
    ab = {"pallas_rows_iters_per_sec": round(leg("pallas"), 0),
          "xla_rows_iters_per_sec": round(leg("xla"), 0)}
    # the router is deterministic and cached: re-asking with the fit's
    # exact shape reports what the auto leg actually ran. Derive the bin
    # width from the estimator's OWN params so the key cannot drift.
    from synapseml_tpu.gbdt.binning import BinMapper
    from synapseml_tpu.gbdt.grower import resolve_hist_backend
    bp = LightGBMClassifier(num_iterations=100, num_leaves=31,
                            learning_rate=0.1)._boost_params("binary")
    bdev = BinMapper(max_bin=bp.max_bin,
                     categorical_features=bp.categorical_features,
                     seed=bp.seed).fit(x.astype(np.float64)).total_bins
    # same fit_row_visits hint as train() passes, so this hits the SAME
    # cache entry (probe budgets are part of the key) and reports what
    # the auto leg actually ran
    ab["auto_routed_to"] = resolve_hist_backend(
        n, d, bdev, fit_row_visits=n * 100 * bp.num_leaves)
    return auto_rows_s, ab


def bench_onnx_lightgbm():
    """Device-resident rows/sec scoring a LightGBM-converted ONNX tree
    ensemble (TreeEnsembleClassifier via the GEMM formulation) — the
    reference notebook's actual workload: a 95-feature bankruptcy model
    scored through ONNXModel at mini_batch 5000+
    (ref: notebooks/ONNX - Inference on Spark.ipynb). Nominal GPU-VM
    baseline: 1.0e6 rows/sec (ORT-CUDA T4 tree scoring)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier
    from synapseml_tpu.onnx import convert_lightgbm, import_model

    rng = np.random.default_rng(0)
    xtr = rng.normal(size=(5000, 95)).astype(np.float32)
    ytr = (xtr[:, 0] + xtr[:, 3] > 0).astype(np.float64)
    model = LightGBMClassifier(num_iterations=100, num_leaves=31).fit(
        Table({"features": xtr, "label": ytr}))
    g = import_model(convert_lightgbm(model))
    fwd = g.bind()
    n, iters = 65536, 20
    x = jnp.asarray(rng.random((n, 95)).astype(np.float32))

    @jax.jit
    def loop(x):
        def body(i, acc):
            xx = x + (acc * 0).astype(x.dtype)
            _, probs = fwd(xx)
            return acc + probs.sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(loop(x))  # compile + warm, forced by the value fetch
    start = time.perf_counter()
    float(loop(x))
    return n * iters / (time.perf_counter() - start)


def bench_onnx_transformer():
    """Device-resident sequences/sec through an imported BERT-base-shaped
    ONNX encoder (12 layers, d=768, 12 heads, S=128, bf16) — the
    transformer-era counterpart of the ResNet metric, exercising the
    Gather/MatMul/Softmax/LayerNormalization lowering at scale. Nominal
    GPU-VM baseline: 500 seq/s (ORT-CUDA T4 fp16, BERT-base S=128)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx import import_model, zoo

    # bs=128: the v5e MXU only saturates past ~4k rows per matmul
    # (bs*s = 16384); bs=32 measured ~2.4k seq/s vs ~4.1k at bs=128 —
    # the round-2 "transformer MFU gap" was batch starvation, not
    # fusion (QKV packing measured *negative*; see docs/perf.md).
    vocab, bs, s, iters = 30522, 128, 128, 10
    g = import_model(zoo.transformer_encoder(
        vocab, 768, 12, 3072, 12, seq_len=s, causal=False, seed=0))
    fwd = g.bind(cast_dtype=jnp.bfloat16)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, vocab, (bs, s)),
                      jnp.int32)

    @jax.jit
    def loop(ids):
        def body(i, acc):
            x = (ids + (acc * 0).astype(jnp.int32)) % vocab
            return acc + fwd(x)[0].sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(loop(ids))  # compile + weight upload, forced by the value fetch
    start = time.perf_counter()
    float(loop(ids))
    return bs * iters / (time.perf_counter() - start)


def bench_gbdt_histogram():
    """Histogram build — the GBDT hot op (SURVEY §3.1 HOT LOOP #2): the
    Pallas VMEM-accumulator kernel vs the XLA one-hot einsum, both on the
    chip, at an Adult-census-x2 shape — ISOLATED-op timing. Production
    routing (grower.histogram) keeps the pallas kernel wherever
    available: inside the scanned boosting step it wins end-to-end
    (+88% on bench_gbdt_train) even when the isolated op here favors
    XLA — see docs/perf.md. Returns (winner, winner_rows_s, detail)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.gbdt import pallas_kernels as pk

    n, f, B, iters = 65536, 28, 256, 30
    rng = np.random.default_rng(0)
    binned = jnp.asarray(rng.integers(0, B, (n, f)), jnp.int32)
    grad = jnp.asarray(rng.normal(size=n), jnp.float32)
    hess = jnp.asarray(rng.random(n), jnp.float32)
    ones = jnp.ones(n, jnp.float32)

    def timed(hist_fn):
        @jax.jit
        def loop(b, g):
            def body(i, acc):
                gg = g + (acc * 0)  # data dependency: no hoisting
                return acc + hist_fn(b, gg)[0, 0, 0].astype(jnp.float32)
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

        float(loop(binned, grad))  # compile + warm, forced by value fetch
        start = time.perf_counter()
        float(loop(binned, grad))
        return n * iters / (time.perf_counter() - start)

    def xla_fn(b, g):
        oh = jax.nn.one_hot(b, B, dtype=jnp.float32)
        return jnp.einsum("nfb,nc->fbc", oh,
                          jnp.stack([g, hess, ones], axis=-1),
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)

    xla_rows_s = timed(xla_fn)
    detail = {"xla_rows_per_sec": round(xla_rows_s, 0),
              "pallas_available": bool(pk.available())}
    # what the production router would run AT THIS SHAPE: the measured
    # per-(rows, F, B) in-context probe (cached+persisted), NOT the
    # isolated-op winner below — the two can disagree (docs/perf.md),
    # which is exactly why 'auto' routes on the probe
    from synapseml_tpu.gbdt.grower import resolve_hist_backend
    detail["auto_routes_to"] = resolve_hist_backend(n, f, B)
    if pk.available():
        pallas_rows_s = timed(
            lambda b, g: pk.histogram_tpu(
                b, jnp.stack([g, hess, ones], axis=-1), B))
        detail["pallas_rows_per_sec"] = round(pallas_rows_s, 0)
        if pallas_rows_s > xla_rows_s:
            return "pallas", pallas_rows_s, detail
    return "xla_onehot", xla_rows_s, detail


def bench_gbdt_predict():
    """GBDT scoring — the round-15 lane: a trained booster's whole
    ensemble scored through the ROUTED predict path (the measured
    prober picks the fused Pallas traversal kernel where it verified a
    win, the XLA gather-chain scan everywhere else). Returns
    (rows/s of the production routed path, detail with the route
    decision and the forced-XLA A/B leg). Nominal GPU-VM baseline:
    1.0e6 rows/sec (lib_lightgbm CUDA T4 predict at this shape)."""
    import jax.numpy as jnp

    from synapseml_tpu.gbdt import predict_route
    from synapseml_tpu.gbdt.boosting import (
        BoostParams, _predict_stack, train)

    n_tr, d, trees = 4096, 14, 50
    rng = np.random.default_rng(0)
    xtr = rng.normal(size=(n_tr, d))
    ytr = (xtr[:, 0] + xtr[:, 1] > 0).astype(np.float64)
    b = train(BoostParams(objective="binary", num_iterations=trees,
                          num_leaves=31), xtr, ytr)
    n = 65536
    x = rng.random((n, d)).astype(np.float32)

    def leg_routed():
        b.predict_raw(x)  # compile + warm (+ the router's one-time probe)
        best = float("inf")
        for _ in range(3):
            start = time.perf_counter()
            b.predict_raw(x)
            best = min(best, time.perf_counter() - start)
        return n / best

    def leg_xla():
        stack = (jnp.asarray(b.trees_feature),
                 jnp.asarray(b.trees_threshold),
                 jnp.asarray(b.trees_left), jnp.asarray(b.trees_right),
                 jnp.asarray(b.trees_value))
        w = jnp.asarray(b.tree_weights)
        xd = jnp.asarray(x)
        compiled = _predict_stack.lower(stack, w, xd, 1,
                                        b.num_trees).compile()
        _record_cost(compiled, bucket=n, arity=7, layout="single",
                     sig=f"gbdt_predict[{b.num_trees}x{d}]")
        np.asarray(compiled(stack, w, xd))  # warm
        best = float("inf")
        for _ in range(3):
            start = time.perf_counter()
            np.asarray(compiled(stack, w, xd))
            best = min(best, time.perf_counter() - start)
        return n / best

    routed_rows_s = leg_routed()
    detail = {
        "xla_rows_per_sec": round(leg_xla(), 0),
        "trees": b.num_trees,
        # the deterministic cached verdict the routed leg actually ran
        # (count=False: an informational lookup serves nothing and must
        # not land a phantom decision in gbdt_predict_route_total)
        "routed_to": predict_route.route_predict(
            n, b.num_trees, b.trees_feature.shape[1], d, 1,
            count=False),
    }
    return routed_rows_s, detail


def bench_onnx_int8():
    """Quantized ONNX scoring — the round-15 int8 lane: a uint8-wire
    QLinearMatMul MLP (the onnxruntime QOperator export shape) scored
    through the imported graph, contraction routed by the measured
    prober (true-int8 operands into the MXU where verified exact +
    faster, the widened int32 path everywhere else). Returns (rows/s,
    detail with the observed route). Nominal GPU-VM baseline: 2.0e5
    rows/sec (ORT-CUDA T4, int8 3-layer MLP at d=256)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx.builder import GraphBuilder
    from synapseml_tpu.onnx.model import import_model
    from synapseml_tpu.runtime import telemetry

    rng = np.random.default_rng(0)
    d, layers = 256, 3
    g = GraphBuilder(opset=21)
    a = g.add_input("x", np.uint8, [None, d])
    for i in range(layers):
        w = rng.integers(-127, 127, (d, d)).astype(np.int8)
        ins = [a, g.add_initializer(f"as{i}", np.float32(0.02)),
               g.add_initializer(f"azp{i}", np.uint8(128)),
               g.add_initializer(f"w{i}", w),
               g.add_initializer(f"ws{i}", np.float32(0.01)),
               g.add_initializer(f"wzp{i}", np.int8(0)),
               g.add_initializer(f"ys{i}", np.float32(0.05)),
               g.add_initializer(f"yzp{i}", np.uint8(128))]
        a = g.add_node("QLinearMatMul", ins)
    g.add_output(a, np.uint8, [None, d])
    gi = import_model(g.to_bytes())
    fwd = gi.bind()

    n, iters = 16384, 10
    x = jnp.asarray(rng.integers(0, 255, (n, d)), jnp.uint8)

    def counts():
        return {k: v for k, v in telemetry.snapshot().get(
            "counters", {}).items() if "onnx_int8_route_total" in k}

    before = counts()

    @jax.jit
    def loop(x):
        def body(i, acc):
            xx = (x.astype(jnp.int32)
                  + (acc * 0).astype(jnp.int32)) % 256
            (out,) = fwd(xx.astype(jnp.uint8))
            return acc + out.astype(jnp.float32).sum()
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    compiled = loop.lower(x).compile()  # + the router's one-time probes
    _record_cost(compiled, bucket=n, arity=1, layout="single",
                 sig=f"onnx_int8_mlp[{layers}x{d}]")
    float(compiled(x))  # warm
    start = time.perf_counter()
    float(compiled(x))
    rows_s = n * iters / (time.perf_counter() - start)
    after = counts()
    routes = {k.split('backend="')[1].rstrip('"}'): int(v - before.get(k, 0))
              for k, v in after.items()}
    return rows_s, {"layers": layers, "d": d,
                    "route_decisions": routes}


def bench_serving_latency():
    """p50 request->pipeline->reply latency through the serving layer
    (ContinuousServer + parse/make_reply), echo pipeline — isolates the
    framework's own serving overhead, the reference's "sub-millisecond"
    continuous-mode claim (README.md:22, docs/mmlspark-serving.md:142).
    Model scoring cost is excluded: on this driver the chip sits behind
    a network tunnel, which no co-located deployment would pay."""
    from synapseml_tpu.utils.profiling import serving_echo_latency

    lat = serving_echo_latency(samples=300, warmup=50, name="bench")
    return lat[len(lat) // 2] * 1e3  # p50 ms


def bench_serving_scored_latency():
    """The same round trip with a REAL model scored per request (an
    imported-ONNX MLP on the device) — published alongside the echo p50
    so the headline cannot be read as score-inclusive (round-2 weak #4).
    On this driver every request pays a tunnel round trip to the chip;
    co-located deployments pay PCIe instead."""
    import json
    import threading
    import time as _time
    import urllib.request

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.io.serving import ContinuousServer, make_reply
    from synapseml_tpu.onnx import ONNXModel, zoo

    model = ONNXModel(model_bytes=zoo.mlp([16, 32], num_classes=4, seed=0),
                      argmax_output_col="pred")

    def pipeline(table: Table) -> Table:
        feats = np.stack([np.asarray(v["features"], np.float32)
                          for v in table["value"]])
        scored = model.transform(Table({"input": feats}))
        replies = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            replies[i] = make_reply({"pred": int(scored["pred"][i])})
        return table.with_column("reply", replies)

    # AOT-warm every pow2 bucket the varying micro-batch sizes can hit
    # (max_batch is 64 on the concurrent leg), so no jit compile lands
    # inside a timed request — workers share the warmed cache. warmup()
    # (vs the old transform-loop prewarm) also lands each bucket's
    # flops/bytes in the roofline cost table, which is what attributes
    # this group in perf_report
    model.warmup(buckets=[8, 16, 32, 64])
    for n in (1, 9, 17, 33):  # belt over braces: drive the drain path
        model.transform(Table({"input": np.zeros((n, 16), np.float32)}))

    body = json.dumps({"features": [0.1] * 16}).encode()

    def post(url):
        req = urllib.request.Request(
            url, body, {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            resp.read()

    # -- sequential leg (linger 0: a lone client must not pay a wait)
    cs = ContinuousServer("bench_scored", pipeline, max_batch=32).start()
    try:
        for _ in range(30):  # warm: compile + bucket
            post(cs.url)
        lat = []
        for _ in range(150):
            t0 = _time.perf_counter()
            post(cs.url)
            lat.append(_time.perf_counter() - t0)
        lat.sort()
        seq_p50_ms = lat[len(lat) // 2] * 1e3
    finally:
        cs.stop()

    # -- concurrent leg: ~32 clients + 8 ms linger so get_batch actually
    # coalesces and ONE device round trip amortizes over the micro-batch
    # (the reference's serving pitch is concurrent throughput,
    # ref: HTTPSourceV2.scala:475-696). Sequential p50 measures the full
    # per-request tunnel RT; this measures the architecture.
    # max_batch 64 + 4 scoring workers: the tunnel's dispatch RTT
    # dominates per-batch wall time, so N workers keep N micro-batches
    # in flight (throughput ~ N/RTT) while the collector lingers on the
    # next batch concurrently
    cs2 = ContinuousServer("bench_scored_conc", pipeline, max_batch=64,
                           batch_linger=0.008, scoring_workers=4).start()
    try:
        n_clients, per_client = 32, 12
        for _ in range(5):
            post(cs2.url)  # warm this server's path too

        def barrage():
            clats: list = []
            from synapseml_tpu.runtime.locksan import make_lock
            lock = make_lock("bench:lock")
            barrier = threading.Barrier(n_clients)

            def client():
                mine = []
                barrier.wait()
                for _ in range(per_client):
                    t0 = _time.perf_counter()
                    post(cs2.url)
                    mine.append(_time.perf_counter() - t0)
                with lock:
                    clats.extend(mine)

            # synlint: disable=RL001 - finite barrage clients: the
            # harness joins every one below; a raise fails the bench
            threads = [threading.Thread(target=client)
                       for _ in range(n_clients)]
            t_all = _time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = _time.perf_counter() - t_all
            clats.sort()
            return (clats[len(clats) // 2] * 1e3,
                    clats[int(len(clats) * 0.99)] * 1e3,
                    len(clats) / wall)

        # best-of-2 barrages: tunnel bandwidth drifts 2x run-to-run
        runs = [barrage(), barrage()]
        conc_p50_ms, conc_p99_ms, conc_rps = max(runs, key=lambda r: r[2])
        return seq_p50_ms, conc_p50_ms, conc_p99_ms, conc_rps
    finally:
        cs2.stop()


def first_batch_ms(model, table, buckets=None, example_feeds=None):
    """Metric hook for ``serving_cold_start_first_batch_ms``: wall time
    from "replica has the model bytes" to "first scored batch is back on
    the host" — warmup (AOT compile OR executable deserialization,
    runtime/compile_cache.py) plus the first real batch. This is the
    serving cold-start a restarted/autoscaled container pays before its
    readiness gate opens. Also driven cross-process by
    ``tools/ci/smoke_warm_restart.sh`` to verify a warm restart skips
    XLA compilation entirely.

    Returns ``(ms, warmup_report, scored_table)``."""
    start = time.perf_counter()
    report = model.warmup(buckets=buckets, example_feeds=example_feeds)
    out = model.transform(table)
    for col in out.columns:  # force materialization of every output
        np.asarray(out[col])
    return (time.perf_counter() - start) * 1e3, report, out


def bench_serving_cold_start():
    """Cold vs warm-cache A/B of the serving cold start: the SAME model
    bytes warmed+scored by (a) a fresh model against an empty cache dir
    (pays trace + XLA compile for every bucket) and (b) a second fresh
    model instance against the now-populated cache (deserializes the
    persisted executables — the restarted-replica path; jax's own
    persistent compilation cache rides along as layer 1). In-process
    stand-in for the cross-process restart that
    ``tools/ci/smoke_warm_restart.sh`` verifies; each leg builds a brand
    new executor so no in-process jit cache can leak between them.

    Returns (warm_ms, cold_ms, loaded, persisted, identical)."""
    import tempfile

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.onnx import ONNXModel, zoo

    # resnet18: enough graph that XLA compile dominates the cold leg the
    # way a real serving backbone does, small enough for the CPU CI
    # bench smoke. One bucket: serving replicas warm a ladder, but the
    # A/B only needs one representative compile
    blob = zoo.resnet18(num_classes=1000, image_size=64)
    # NOT cleaned up: enable_persistent_cache wires this dir into jax's
    # global compilation-cache config, and deleting a live cache dir
    # would break later compiles in this process
    cache_dir = tempfile.mkdtemp(prefix="synapseml_coldstart_")
    imgs = np.random.default_rng(0).standard_normal(
        (8, 3, 64, 64)).astype(np.float32)
    table = Table({"data": imgs})

    def leg():
        model = ONNXModel(model_bytes=blob, mini_batch_size=8)
        model.set(compile_cache_dir=cache_dir)
        return first_batch_ms(model, table, buckets=[8])

    cold_ms, cold_rep, cold_out = leg()
    warm_ms, warm_rep, warm_out = leg()
    col = [c for c in cold_out.columns if c != "data"][0]
    identical = bool(np.array_equal(np.asarray(cold_out[col]),
                                    np.asarray(warm_out[col])))
    persisted = sum(1 for e in cold_rep.entries if e.get("persisted"))
    return warm_ms, cold_ms, warm_rep.loaded, persisted, identical


def bench_synlint():
    """Static-analysis hygiene canary: run synlint (tools/analysis,
    docs/analysis.md) over the package and record total + per-pack
    finding counts, cold/warm analyzer wall time, and the result-cache
    hit rate (cold run populates a throwaway cache, warm run replays
    it). The committed JSON makes hygiene drift — a new host-sync on
    the dispatch path, an unguarded shared write, a knob-table gap —
    a diffable number per round, same as the donation-warning count.
    Never sinks the benchmark run: any analyzer failure reports -1."""
    import tempfile
    import time as _time

    try:
        from tools.analysis.cache import ResultCache
        from tools.analysis.engine import analyze_program, pack_of

        # anchor targets to the repo root, not the process cwd — run
        # from elsewhere, bare names would resolve to nothing and the
        # metric would read as a spotless 0
        root = os.path.dirname(os.path.abspath(__file__))
        paths = [os.path.join(root, p)
                 for p in ("synapseml_tpu", "tools", "bench.py")]
        with tempfile.TemporaryDirectory() as td:
            cpath = os.path.join(td, "synlint-cache.json")
            cold_cache = ResultCache(cpath)
            t0 = _time.monotonic()
            findings, _prog, _ = analyze_program(paths, root=root,
                                                 cache=cold_cache)
            cold_s = _time.monotonic() - t0
            cold_cache.save()
            t0 = _time.monotonic()
            _f, _p, warm = analyze_program(paths, root=root,
                                           cache=ResultCache(cpath))
            warm_s = _time.monotonic() - t0
        packs: dict = {}
        for f in findings:
            packs[pack_of(f.rule)] = packs.get(pack_of(f.rule), 0) + 1
        hit_rate = (warm["cache_hits"] / warm["files"]
                    if warm.get("files") else 0.0)
        out = {"synlint_findings_total": len(findings),
               "synlint_runtime_s": round(cold_s, 2),
               "synlint_warm_runtime_s": round(warm_s, 2),
               "synlint_cache_hit_rate": round(hit_rate, 3),
               "synlint_findings_by_pack": dict(sorted(packs.items()))}
        out.update(_dynsan_detail(_prog))
        return out
    except Exception:  # noqa: BLE001 - the bench must survive lint bugs
        return {"synlint_findings_total": -1, "synlint_runtime_s": -1.0}


def _dynsan_detail(prog):
    """Static<->dynamic lock-graph numbers for the committed JSON: how
    many lock-order edges the static CC002 model claims, and — when a
    locksan observed-graph artifact is around (SYNAPSEML_LOCKSAN_OUT,
    e.g. after tools/ci/smoke_locksan.sh) — how many edges the runtime
    actually saw, how many were model gaps, and how many static edges
    no smoke has ever driven (the coverage debt)."""
    try:
        from tools.analysis.rules_concurrency import static_adjacency
        from tools.analysis.rules_dynsan import cross_check, load_artifacts

        adj = static_adjacency(prog)
        out = {"dynsan_static_edges": sum(len(v) for v in adj.values())}
        obs_dir = os.environ.get("SYNAPSEML_LOCKSAN_OUT",
                                 "/tmp/locksan-smoke")
        try:
            arts = load_artifacts(obs_dir)
        except (OSError, ValueError):
            return out  # no artifact: static edge count still lands
        findings, coverage = cross_check(prog, arts)
        findings = [f for f in findings  # same filter the CLI gate uses
                    if not prog.suppressed(f.path, f.line, f.rule)]
        out.update({
            "dynsan_observed_edges": sum(len(a.get("edges", ()))
                                         for a in arts),
            "dynsan_model_gaps": sum(1 for f in findings
                                     if f.rule == "DS001"),
            "dynsan_coverage_gaps": len(coverage),
        })
        return out
    except Exception:  # noqa: BLE001 - detail ride-along, never fatal
        return {}


def _telemetry_snapshot():
    """Compact runtime-telemetry snapshot for the committed JSON —
    counters/gauges plus histogram summaries, no raw bucket arrays.
    Never sinks the benchmark: any failure reports an error marker."""
    try:
        from synapseml_tpu.runtime import telemetry

        return telemetry.snapshot(compact=True)
    except Exception as e:  # noqa: BLE001 - the bench must survive
        return {"error": repr(e)}


def _with_retries(fn, attempts=3):
    """The tunneled device occasionally drops remote_compile connections;
    a transient failure must not zero out the recorded benchmark."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            last = e
            if i + 1 < attempts:
                time.sleep(5 * (i + 1))
    raise last


# -- bench groups (docs/perf.md "Regression gate") --------------------------
# Each group runs one bench function and returns its metric entries
# ({metric, value, unit, vs_baseline, ...} dicts). The first entry of
# the first selected group is the headline; everything else rides in
# "secondary" — the same one-JSON-line shape the driver has always
# parsed. Grouping is what makes --only/--fast subset selection
# possible: CI's bench-smoke runs the bounded FAST_GROUPS set and
# gates it with tools/ci/bench_check.py instead of re-running the full
# multi-minute suite per push.

GPU_IMG_BASELINE = 1000.0
GPU_ROWS_BASELINE = 1.0e6
GPU_TREE_ROWS_BASELINE = 1.0e6
GPU_PREDICT_ROWS_BASELINE = 1.0e6  # lib_lightgbm CUDA T4 predict
GPU_INT8_ROWS_BASELINE = 2.0e5     # ORT-CUDA T4 int8 MLP d=256
GPU_SEQ_BASELINE = 500.0
SERVING_BASELINE_MS = 1.0  # the reference's "sub-millisecond" claim


def _entries_resnet50():
    (img_s, host_img_s, host_alt_img_s, pipe_img_s,
     seq_call_img_s, routed_dtype, wire) = _with_retries(
        bench_onnx_resnet50)
    return [{
        "metric": "onnx_resnet50_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / GPU_IMG_BASELINE, 3),
        "detail": {"compute_dtype": routed_dtype},
    }, {
        # the ROUTED hostfeed wire (lane "onnx_hostfeed_wire"); the
        # losing wire's A/B value rides in detail
        "metric": "onnx_resnet50_hostfeed_images_per_sec",
        "value": round(host_img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(host_img_s / GPU_IMG_BASELINE, 3),
        "detail": {"wire": wire,
                   "alternate_wire_images_per_sec": round(
                       host_alt_img_s, 2)},
    }, {
        # the async submit/drain pipeline (executor.stream) on 5
        # per-batch submissions: cross-CALL overlap of host staging
        # / H2D / compute / D2H vs the same 5 batches as sequential
        # __call__s (each drains the pipeline before the next — the
        # shape every serving scorer pays without the async API)
        "metric": "executor_pipeline_overlap_img_per_sec",
        "value": round(pipe_img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(pipe_img_s / GPU_IMG_BASELINE, 3),
        "detail": {"wire": "uint8",
                   "sequential_call_images_per_sec": round(
                       seq_call_img_s, 2)},
    }]


def _entries_dp_scaling():
    # multi-device data-parallel executor A/B: the same device-resident
    # ResNet-50 stream with buckets dp-sharded across ALL chips vs
    # pinned to one (runtime/executor.py devices=). On a 1-device
    # platform the legs coincide (speedup ~1, the zero-regression
    # guard); on a slice the ratio is the chip-count scaling of the hot
    # scoring path
    dp_img_s, dp_one_img_s, dp_ndev = _with_retries(
        bench_executor_dp_scaling)
    return [{
        "metric": "executor_dp_scaling_images_per_sec",
        "value": round(dp_img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(dp_img_s / GPU_IMG_BASELINE, 3),
        "detail": {"devices": dp_ndev,
                   "single_device_images_per_sec": round(
                       dp_one_img_s, 2),
                   "speedup": round(
                       dp_img_s / max(dp_one_img_s, 1e-9), 3)},
    }]


def _entries_onnx_tp_scaling():
    # tensor-parallel serving A/B: the same transformer stream with the
    # weights registry-placed over every chip (tp=all, dp=1) vs
    # replicated (tp=1). Bit-identical by contract (gather formulation);
    # the detail carries the memory half of the trade — max per-device
    # param bytes vs the total. On a 1-device platform the legs
    # coincide (speedup ~1, the zero-regression guard)
    tp_seq_s, one_seq_s, tp_ndev, tp_detail = _with_retries(
        bench_onnx_tp_scaling)
    detail = dict(tp_detail)
    detail["single_device_sequences_per_sec"] = round(one_seq_s, 2)
    detail["speedup"] = round(tp_seq_s / max(one_seq_s, 1e-9), 3)
    return [{
        "metric": "onnx_tp_scaling_sequences_per_sec",
        "value": round(tp_seq_s, 2),
        "unit": "sequences/sec",
        "vs_baseline": round(tp_seq_s / GPU_SEQ_BASELINE, 3),
        "detail": detail,
    }]


def _entries_gbdt_train():
    rows_s, gbdt_ab = _with_retries(bench_gbdt_train)
    return [{
        "metric": "lightgbm_train_rows_iters_per_sec_per_chip",
        "value": round(rows_s, 2),
        "unit": "rows*iters/sec",
        "vs_baseline": round(rows_s / GPU_ROWS_BASELINE, 3),
        # full-loop histogram-formulation A/B at the same shape —
        # the router picks from a cached in-context measurement
        "detail": gbdt_ab,
    }]


def _entries_onnx_lightgbm():
    tree_rows_s = _with_retries(bench_onnx_lightgbm)
    return [{
        "metric": "onnx_lightgbm_scoring_rows_per_sec_per_chip",
        "value": round(tree_rows_s, 2),
        "unit": "rows/sec",
        "vs_baseline": round(tree_rows_s / GPU_TREE_ROWS_BASELINE, 3),
    }]


def _entries_transformer():
    seq_s = _with_retries(bench_onnx_transformer)
    return [{
        "metric": "onnx_bert_base_sequences_per_sec_per_chip",
        "value": round(seq_s, 2),
        "unit": "sequences/sec",
        "vs_baseline": round(seq_s / GPU_SEQ_BASELINE, 3),
    }]


def _entries_gbdt_histogram():
    # GBDT hot-op shootout: which histogram formulation ships (pallas
    # VMEM kernel vs XLA one-hot einsum), measured on the chip each round
    hist_winner, hist_rows_s, hist_detail = _with_retries(
        bench_gbdt_histogram)
    return [{
        "metric": "gbdt_histogram_rows_per_sec_per_chip",
        "value": round(hist_rows_s, 0),
        "unit": "rows/sec",
        "vs_baseline": round(
            hist_rows_s / max(hist_detail["xla_rows_per_sec"], 1.0), 3),
        "winner": hist_winner,
        "detail": hist_detail,
    }]


def _entries_gbdt_predict():
    rows_s, detail = _with_retries(bench_gbdt_predict)
    return [{
        "metric": "gbdt_predict_rows_per_sec_per_chip",
        "value": round(rows_s, 0),
        "unit": "rows/sec",
        "vs_baseline": round(rows_s / GPU_PREDICT_ROWS_BASELINE, 3),
        "detail": detail,
    }]


def _entries_onnx_int8():
    rows_s, detail = _with_retries(bench_onnx_int8)
    return [{
        "metric": "onnx_int8_rows_per_sec_per_chip",
        "value": round(rows_s, 0),
        "unit": "rows/sec",
        "vs_baseline": round(rows_s / GPU_INT8_ROWS_BASELINE, 3),
        "detail": detail,
    }]


def _entries_serving():
    serving_p50_ms = _with_retries(bench_serving_latency)
    return [{
        "metric": "serving_roundtrip_p50_ms",
        "value": round(serving_p50_ms, 3),
        "unit": "ms",
        # higher = better for vs_baseline: baseline_ms / measured_ms
        "vs_baseline": round(SERVING_BASELINE_MS / serving_p50_ms, 3),
    }]


def _entries_serving_scored():
    (serving_scored_p50_ms, scored_conc_p50_ms, scored_conc_p99_ms,
     scored_conc_rps) = _with_retries(bench_serving_scored_latency)
    return [{
        # score-inclusive companion so the echo number cannot be
        # misread (imported-ONNX MLP scored per request; on this
        # driver each score pays a tunnel round trip to the chip)
        "metric": "serving_scored_roundtrip_p50_ms",
        "value": round(serving_scored_p50_ms, 3),
        "unit": "ms",
        "vs_baseline": round(
            SERVING_BASELINE_MS / serving_scored_p50_ms, 3),
    }, {
        # ~32 concurrent clients: micro-batch coalescing amortizes
        # the device round trip across the batch — the number that
        # reflects the serving architecture rather than the tunnel
        "metric": "serving_scored_concurrent_p50_ms",
        "value": round(scored_conc_p50_ms, 3),
        "unit": "ms",
        "vs_baseline": round(
            SERVING_BASELINE_MS / max(scored_conc_p50_ms, 1e-9), 3),
        "detail": {"clients": 32,
                   "p99_ms": round(scored_conc_p99_ms, 3),
                   "requests_per_sec": round(scored_conc_rps, 1),
                   # the architecture's number: amortized device+
                   # serving cost per request under load (p50 is
                   # dominated by the tunnel RTT a request waits
                   # for its batch's round trip)
                   "amortized_ms_per_request": round(
                       1e3 / max(scored_conc_rps, 1e-9), 2)},
    }]


def _entries_cold_start():
    # serving cold start, cold vs warm-cache A/B: warmup + first
    # scored batch of a FRESH model instance against an empty cache dir
    # (full XLA compile) vs against the persisted executable store (the
    # restarted-replica path — runtime/compile_cache.py; cross-process
    # restart verified by tools/ci/smoke_warm_restart.sh). Headline =
    # warm: the cold start a cache-volume deployment actually pays
    (cold_warm_ms, cold_cold_ms, cold_loaded, cold_persisted,
     cold_identical) = _with_retries(bench_serving_cold_start)
    return [{
        "metric": "serving_cold_start_first_batch_ms",
        "value": round(cold_warm_ms, 1),
        "unit": "ms",
        # higher = better: cold-time / warm-time = the restart
        # speedup the cache buys
        "vs_baseline": round(cold_cold_ms / max(cold_warm_ms, 1e-9), 3),
        "detail": {"cold_ms": round(cold_cold_ms, 1),
                   "warm_ms": round(cold_warm_ms, 1),
                   "speedup": round(
                       cold_cold_ms / max(cold_warm_ms, 1e-9), 2),
                   "executables_loaded": cold_loaded,
                   "executables_persisted": cold_persisted,
                   "outputs_identical_across_restart": cold_identical},
    }]


def bench_decode_serving():
    """Decode serving: mixed-length autoregressive sequences through
    DecodeScheduler (runtime/decode.py) — continuous (iteration-level)
    batching vs the same machinery restricted to static batches (a slot
    only refills once the WHOLE batch drains). Same graph, same bucket
    ladder, same KV cache; the A/B isolates the scheduling policy.
    Returns (cont_tokens_s, static_tokens_s, ttft_p50_s, ttft_p95_s,
    itl_p50_s, itl_p95_s, detail)."""
    import threading

    from synapseml_tpu.onnx import import_model, zoo
    from synapseml_tpu.runtime.decode import DecodeScheduler

    payload = zoo.tiny_decoder()
    # deterministic heavy-tailed workload — the length distribution
    # continuous batching exists for (and real traffic has): mostly
    # short interactive sequences with a long straggler per batch-
    # worth. A static batch strands its finished slots for
    # (max - mean) output steps behind the straggler; iteration-level
    # admission refills them the step after each retire.
    rng = np.random.default_rng(0)
    work = []
    for i in range(16):
        plen = int(rng.integers(4, 24))
        nout = (int(rng.integers(88, 97)) if i % 4 == 0
                else int(rng.integers(4, 9)))
        work.append(([int(x) for x in rng.integers(1, 50, plen)], nout))

    def run(static):
        sched = DecodeScheduler(
            import_model(payload),
            name="bench_static" if static else "bench_cont",
            max_batch=4, prefill_chunk=16, page_size=16, max_seq=128,
            static_batching=static)
        sched.warmup()
        sched.start()
        from synapseml_tpu.runtime.locksan import make_lock
        lock = make_lock("bench:lock")
        ttfts, itls, total = [], [], [0]

        def consume(handle, t_sub):
            last = None
            for _tok in handle:
                now = time.perf_counter()
                with lock:
                    if last is None:
                        ttfts.append(now - t_sub)
                    else:
                        itls.append(now - last)
                    total[0] += 1
                last = now

        t0 = time.perf_counter()
        threads = []
        for toks, nout in work:
            h = sched.submit(toks, nout)
            # synlint: disable=RL001 - finite per-sequence consumers:
            # joined below, and a scheduler fault fails the handle so
            # the consumer exits rather than hanging
            th = threading.Thread(target=consume,
                                  args=(h, time.perf_counter()),
                                  daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        sched.close()
        return (total[0] / max(wall, 1e-9), ttfts, itls, wall, total[0])

    cont_tps, ttfts, itls, cont_wall, cont_n = run(static=False)
    stat_tps, s_ttfts, _s_itls, stat_wall, stat_n = run(static=True)
    assert cont_n == stat_n, (cont_n, stat_n)
    detail = {
        "sequences": len(work),
        "tokens": cont_n,
        "continuous_tokens_per_sec": round(cont_tps, 1),
        "static_tokens_per_sec": round(stat_tps, 1),
        "continuous_vs_static": round(cont_tps / max(stat_tps, 1e-9), 2),
        "continuous_wall_s": round(cont_wall, 3),
        "static_wall_s": round(stat_wall, 3),
        "ttft_p95_ms": round(float(np.percentile(ttfts, 95)) * 1e3, 2),
        "static_ttft_p50_ms": round(
            float(np.percentile(s_ttfts, 50)) * 1e3, 2),
        "itl_p95_ms": round(float(np.percentile(itls, 95)) * 1e3, 2),
    }
    return (cont_tps, stat_tps,
            float(np.percentile(ttfts, 50)), float(np.percentile(ttfts, 95)),
            float(np.percentile(itls, 50)), float(np.percentile(itls, 95)),
            detail)


def _entries_decode_serving():
    (cont_tps, stat_tps, ttft_p50, _ttft_p95, itl_p50, _itl_p95,
     decode_detail) = _with_retries(bench_decode_serving)
    return [{
        "metric": "decode_serving_tokens_per_sec",
        "value": round(cont_tps, 1),
        "unit": "tokens/sec",
        # higher = better: continuous / static = what iteration-level
        # batching buys over draining whole batches (the Orca claim)
        "vs_baseline": round(cont_tps / max(stat_tps, 1e-9), 3),
        "detail": decode_detail,
    }, {
        "metric": "decode_serving_ttft_p50_ms",
        "value": round(ttft_p50 * 1e3, 3),
        "unit": "ms",
        # higher = better: static-batch TTFT / continuous TTFT — the
        # queueing delay continuous admission removes
        "vs_baseline": round(
            decode_detail["static_ttft_p50_ms"] /
            max(ttft_p50 * 1e3, 1e-9), 3),
    }, {
        "metric": "decode_serving_itl_p50_ms",
        "value": round(itl_p50 * 1e3, 3),
        "unit": "ms",
        "vs_baseline": 1.0,  # no cross-policy referent: ITL is gated
                             # by the per-step device latency itself
    }]


class BenchGroup:
    """One bench group: runner + the metadata --list prints and
    tools/perf_report.py attributes against. ``kind`` says whether the
    group exercises a device program ("device" — perf_report requires
    a captured cost signature) or only the host framework ("host" —
    the echo legs, where a roofline fraction would be a lie).
    ``fast_only`` groups are CI-sized twins of a heavy group that emit
    the SAME metric names — they run in --fast (and --only) but are
    excluded from the full-registry default run so a full run never
    reports one metric twice."""

    __slots__ = ("name", "fn", "kind", "describe", "metrics",
                 "fast_only")

    def __init__(self, name, fn, kind, describe, metrics,
                 fast_only=False):
        self.name = name
        self.fn = fn
        self.kind = kind
        self.describe = describe
        self.metrics = tuple(metrics)
        self.fast_only = fast_only


BENCH_GROUPS = [
    BenchGroup(
        "resnet50", _entries_resnet50, "device",
        "ONNX ResNet-50 imported-graph inference: device-resident, "
        "uint8-wire host feed, and the cross-call pipeline-overlap A/B",
        ("onnx_resnet50_images_per_sec_per_chip",
         "onnx_resnet50_hostfeed_images_per_sec",
         "executor_pipeline_overlap_img_per_sec")),
    BenchGroup(
        "gbdt_train", _entries_gbdt_train, "device",
        "LightGBM training on Adult-census shape via the measured "
        "pallas/xla histogram router, full-loop A/B in detail",
        ("lightgbm_train_rows_iters_per_sec_per_chip",)),
    BenchGroup(
        "dp_scaling", _entries_dp_scaling, "device",
        "same ResNet-50 stream dp-sharded across all chips vs pinned "
        "to one — the chip-count scaling of the hot scoring path",
        ("executor_dp_scaling_images_per_sec",)),
    BenchGroup(
        "onnx_tp_scaling", _entries_onnx_tp_scaling, "device",
        "transformer forward with weights registry-placed over every "
        "chip (tensor_parallel=all) vs replicated (tp=1) — the price "
        "and per-device HBM payoff of tp-sharded serving",
        ("onnx_tp_scaling_sequences_per_sec",)),
    BenchGroup(
        "onnx_lightgbm", _entries_onnx_lightgbm, "device",
        "LightGBM-converted ONNX tree ensemble scored device-resident "
        "(GEMM formulation) — the reference notebook's workload",
        ("onnx_lightgbm_scoring_rows_per_sec_per_chip",)),
    BenchGroup(
        "transformer", _entries_transformer, "device",
        "BERT-base-shaped imported ONNX encoder, bf16, bs=128 — the "
        "transformer-era counterpart of the ResNet metric",
        ("onnx_bert_base_sequences_per_sec_per_chip",)),
    BenchGroup(
        "serving", _entries_serving, "host",
        "echo round trip through ContinuousServer — isolates the "
        "serving framework's own overhead, no device program",
        ("serving_roundtrip_p50_ms",)),
    BenchGroup(
        "serving_scored", _entries_serving_scored, "device",
        "real imported-ONNX MLP scored per request, sequential and "
        "under ~32 concurrent clients with micro-batch coalescing",
        ("serving_scored_roundtrip_p50_ms",
         "serving_scored_concurrent_p50_ms")),
    BenchGroup(
        "gbdt_histogram", _entries_gbdt_histogram, "device",
        "isolated GBDT histogram hot-op shootout: Pallas VMEM kernel "
        "vs XLA one-hot einsum at Adult-x2 shape",
        ("gbdt_histogram_rows_per_sec_per_chip",)),
    BenchGroup(
        "gbdt_predict", _entries_gbdt_predict, "device",
        "trained-booster ensemble scoring through the ROUTED predict "
        "path (fused Pallas traversal vs XLA gather-chain scan), with "
        "the route decision and forced-XLA A/B in detail",
        ("gbdt_predict_rows_per_sec_per_chip",)),
    BenchGroup(
        "onnx_int8", _entries_onnx_int8, "device",
        "uint8-wire QLinearMatMul MLP through the imported graph, "
        "contraction routed by the int8 prober (true-int8 operands "
        "into the MXU vs the widened int32 path)",
        ("onnx_int8_rows_per_sec_per_chip",)),
    BenchGroup(
        "cold_start", _entries_cold_start, "device",
        "serving cold start cold-vs-warm-cache A/B: warmup + first "
        "scored batch against an empty vs populated executable store",
        ("serving_cold_start_first_batch_ms",)),
    BenchGroup(
        "decode_serving", _entries_decode_serving, "device",
        "mixed-length autoregressive decode through the continuous-"
        "batching scheduler + paged KV cache, continuous-vs-static "
        "A/B in detail (tokens/s, TTFT, ITL)",
        ("decode_serving_tokens_per_sec",
         "decode_serving_ttft_p50_ms",
         "decode_serving_itl_p50_ms")),
    BenchGroup(
        "resnet50_fast", _entries_resnet50_fast, "device",
        "CI-sized ResNet-50 (64px, bs=16) with the compute-dtype and "
        "hostfeed-wire lanes ROUTED by the autotuner, forced-alternate "
        "A/B for both verdicts in detail",
        ("onnx_resnet50_images_per_sec_per_chip",
         "onnx_resnet50_hostfeed_images_per_sec"),
        fast_only=True),
]

# the CI-bounded subset (tools/ci/pipeline.yaml bench-smoke): groups
# that finish in minutes on a CPU runner yet cover the serving framework
# overhead, a real scored round trip under concurrency, the compile-
# cache cold-start path, AND (round 15) the two routed scoring lanes —
# the surfaces a framework regression moves first. On the CPU runner
# both routers provably fall back (the detail records the decision);
# the heavy device-throughput groups stay driver-territory (the
# committed BENCH_r*.json history). onnx_tp_scaling rides along (round
# 18): on the 1-device CPU runner its legs coincide by construction,
# so the gate watches the executor-path transformer throughput itself.
FAST_GROUPS = ("serving", "serving_scored", "cold_start",
               "gbdt_predict", "onnx_int8", "resnet50_fast",
               "onnx_tp_scaling", "decode_serving")


def _finite(obj):
    """Strict RFC-8259 output: non-finite floats serialize as null (the
    loadgen --out convention — ``tools.loadgen._json_finite`` is the
    shared implementation; a bare ``NaN`` token breaks every strict
    parser downstream, starting with bench_check)."""
    try:
        from tools.loadgen import _json_finite
    except Exception:  # pragma: no cover - bench.py moved out of repo
        import math

        def _json_finite(o):
            if isinstance(o, float) and not math.isfinite(o):
                return None
            if isinstance(o, dict):
                return {k: _json_finite(v) for k, v in o.items()}
            if isinstance(o, (list, tuple)):
                return [_json_finite(v) for v in o]
            return o
    return _json_finite(obj)


def _select_groups(groups):
    """Resolve group names to BenchGroup records, honoring the
    CALLER's ordering (deduped): the first selected group's first
    entry is the headline, so ``--only cold_start,serving`` must
    headline cold_start, not whichever appears first in the
    registry."""
    by_name = {g.name: g for g in BENCH_GROUPS}
    seen = set()
    return [by_name[name] for name in groups
            if name in by_name
            and not (name in seen or seen.add(name))]


def run_bench(groups, synlint: bool = True):
    """Run the selected groups; returns the payload dict (headline +
    secondary + detail) that main() prints as one JSON line."""
    import warnings as _warnings

    selected = _select_groups(groups)
    # record-all so the executor's donation hygiene is MEASURED: any
    # "Some donated buffers were not usable" emitted anywhere in the run
    # (they fire per XLA compile, from any pipeline thread) lands in the
    # committed JSON instead of scrolling away in the log tail
    with _warnings.catch_warnings(record=True) as _rec:
        _warnings.simplefilter("always")
        entries = []
        for g in selected:
            # every cost-table signature a group's warmups compile is
            # tagged with the group name — the join key perf_report
            # uses to attribute bench groups offline (detail.cost)
            with _cost_tag_scope(g.name):
                got = g.fn()
            for e in got:
                e.setdefault("group", g.name)
            entries.extend(got)
    donation_warnings = sum(
        1 for w in _rec
        if "donated buffers were not usable" in str(w.message).lower())
    # donation hygiene canary (see _donate_mask_for): nonzero means
    # some jit site regressed to annotating non-aliasable donations;
    # synlint_findings_total counts ALL static-analysis findings
    # (baselined included — docs/analysis.md) so hygiene drift in
    # either direction shows up as a diffable number per round.
    # "telemetry" embeds the full runtime-metrics snapshot of the
    # run (runtime/telemetry.py, docs/observability.md): queue
    # depths, per-stage latency histograms (count/sum/p50/p95/p99),
    # AOT hit/miss, recompiles, batch-size distribution — so every
    # committed BENCH_r*.json carries the series the SLO scheduler
    # work will regress against
    detail = {"donated_buffers_not_usable_warnings": donation_warnings}
    if synlint:
        detail.update(bench_synlint())
    detail["telemetry"] = _telemetry_snapshot()
    # autotune lane snapshot: which formulation each registered lane
    # routed for this run (reference, candidates, per-key decisions,
    # probe count) — the join tools/perf_report.py uses to attribute
    # FORMULATION per bottleneck, and the artifact record proving the
    # fleet-shared verdict a CI box ran with
    detail["autotune"] = _autotune_snapshot()
    # roofline cost-table snapshot + group metadata: everything
    # tools/perf_report.py needs to attribute this run OFFLINE from
    # the one committed artifact (docs/perf.md "Roofline methodology")
    detail["cost"] = _cost_snapshot()
    detail["bench_groups"] = {
        g.name: {"kind": g.kind, "description": g.describe,
                 "metrics": list(g.metrics)} for g in selected}
    return _compose_payload(entries, detail)


def _cost_tag_scope(name):
    """costmodel.tag_scope when the runtime imports; inert otherwise
    (bench.py must run even where the package is trimmed)."""
    try:
        from synapseml_tpu.runtime import costmodel

        return costmodel.tag_scope(name)
    except Exception:  # noqa: BLE001
        import contextlib

        return contextlib.nullcontext()


def _record_cost(compiled, **kw):
    """costmodel.record when the runtime imports; inert otherwise.
    Bench groups that compile their program OUTSIDE the executor (the
    round-15 scoring lanes) land their flops/bytes signature here so
    the perf-report gate can attribute them like the warmup-captured
    ones."""
    try:
        import jax

        from synapseml_tpu.runtime import costmodel

        costmodel.record(compiled, device_kind=jax.devices()[0].device_kind,
                         **kw)
    except Exception:  # noqa: BLE001 - capture is best-effort
        pass


def _cost_snapshot():
    try:
        from synapseml_tpu.runtime import costmodel

        return costmodel.snapshot(force=True)
    except Exception as e:  # noqa: BLE001 - the bench must survive
        return {"error": repr(e), "entries": []}


def _autotune_snapshot():
    try:
        from synapseml_tpu.runtime import autotune

        return autotune.snapshot()
    except Exception as e:  # noqa: BLE001 - the bench must survive
        return {"error": repr(e), "lanes": {}}


def _compose_payload(entries, detail):
    """Headline = first entry; the run-level detail MERGES with (never
    replaces) the headline's own per-metric detail — `--only
    cold_start` must keep its cold/warm A/B keys alongside the
    donation/telemetry run detail."""
    payload = dict(entries[0])
    payload["secondary"] = entries[1:]
    payload["detail"] = {**payload.get("detail", {}), **detail}
    return payload


def main(argv=None) -> int:
    import argparse

    names = [g.name for g in BENCH_GROUPS]
    ap = argparse.ArgumentParser(
        description="Benchmark driver — prints ONE JSON line "
                    "(docs/perf.md).")
    ap.add_argument("--out", metavar="FILE",
                    help="also write the payload as strict RFC-8259 "
                         "JSON (non-finite floats -> null) — the file "
                         "tools/ci/bench_check.py and "
                         "tools/perf_report.py consume")
    ap.add_argument("--cost-report", metavar="FILE",
                    help="also render the ranked roofline bottleneck "
                         "report (tools/perf_report.py) from this "
                         "run's payload into FILE")
    ap.add_argument("--only", metavar="G1,G2",
                    help="run only these groups (comma-separated; see "
                         "--list). Overrides --fast. Subset runs skip "
                         "synlint (the static-analysis CI job gates it)")
    ap.add_argument("--fast", action="store_true",
                    help="bounded CI subset: " + ",".join(FAST_GROUPS))
    ap.add_argument("--list", action="store_true",
                    help="print group names and exit")
    args = ap.parse_args(argv)
    if args.list:
        for g in BENCH_GROUPS:
            print(f"{g.name}  [{g.kind}]  {g.describe}")
            print(f"  metrics: {', '.join(g.metrics)}")
        return 0
    if args.only:
        groups = [g.strip() for g in args.only.split(",") if g.strip()]
        unknown = [g for g in groups if g not in names]
        if unknown:
            print(f"unknown bench group(s): {', '.join(unknown)} "
                  f"(have: {', '.join(names)})")
            return 2
        if not groups:
            print(f"--only selected no groups (have: {', '.join(names)})")
            return 2
    elif args.fast:
        groups = list(FAST_GROUPS)
    else:
        # fast_only groups are CI twins emitting the same metric names
        # as their heavy sibling — the full run takes the heavy one
        groups = [g.name for g in BENCH_GROUPS if not g.fast_only]
    full = [g.name for g in BENCH_GROUPS if not g.fast_only]
    payload = _finite(run_bench(groups, synlint=groups == full))
    print(json.dumps(payload, allow_nan=False))
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, indent=2, allow_nan=False)
            fh.write("\n")
    if args.cost_report:
        try:
            from tools.perf_report import build_report

            _rows, md, unattributed = build_report(payload)
            with open(args.cost_report, "w", encoding="utf-8") as fh:
                fh.write(md)
            if unattributed:
                print("cost report: unattributed groups: "
                      + ", ".join(unattributed))
        except Exception as e:  # noqa: BLE001 - report is a side dish
            print(f"cost report failed: {e!r}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
