"""Benchmark driver — prints ONE JSON line.

North-star metrics (BASELINE.md / BASELINE.json):
1. ONNX ResNet-50 inference images/sec/chip through the *imported* ONNX graph
   (protobuf parse -> node lowering -> jit), the "ONNX - Inference on Spark"
   workload. Primary metric. Nominal GPU-VM baseline: 1000 img/s (T4-class,
   ORT-CUDA fp16, bs128).
2. LightGBM training rows/sec/chip on an Adult-census-scale workload
   (32561 rows x 14 features, 100 iterations, 31 leaves), the
   "LightGBM - Overview" workload. Nominal GPU-VM baseline: 1.0e6
   rows*iters/sec (lib_lightgbm CUDA on T4 trains this in ~3.3s).

Runs on whatever jax.devices() provides (the real TPU chip under the driver).
"""
from __future__ import annotations

import json
import time

import numpy as np


def bench_onnx_resnet50():
    """(device_resident_img_s, host_feed_img_s) through the imported graph.

    Device-resident isolates chip throughput (the ORT-CUDA analogue: data
    already in device memory); host-feed includes the host->device copy per
    batch, which on this driver rides a network tunnel to the chip and is
    bandwidth-bound — on a co-located TPU-VM host it approaches the former.
    """
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx import ONNXModel, import_model, zoo

    batch = 128
    blob = zoo.resnet50(num_classes=1000)
    images_np = np.random.default_rng(0).standard_normal(
        (batch, 3, 224, 224)).astype(np.float32)

    # -- device-resident path: jitted imported graph, input stays in HBM.
    # The N forwards run inside one fori_loop with a data dependency (the
    # accumulated sum feeds the next input) so XLA cannot hoist the body,
    # and a single scalar fetch at the end forces real completion —
    # block_until_ready is unreliable on tunneled device platforms.
    graph = import_model(blob)
    fwd_fn = graph.bind(cast_dtype=jnp.bfloat16)
    iters = 30

    @jax.jit
    def loop(img):
        def body(i, acc):
            x = img + (acc * 0).astype(img.dtype)
            return acc + fwd_fn(x)[0].sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    images_dev = jnp.asarray(images_np, jnp.bfloat16)
    float(loop(images_dev))  # compile + warmup, forced by the value fetch
    start = time.perf_counter()
    float(loop(images_dev))
    dev_img_s = batch * iters / (time.perf_counter() - start)

    # -- host-feed path: the full ONNXModel executor incl. per-batch copy
    model = ONNXModel(model_bytes=blob, mini_batch_size=batch,
                      compute_dtype="bfloat16")
    executor = model._executor()
    executor(images_np)
    start = time.perf_counter()
    for _ in range(5):
        out = executor(images_np)
    np.asarray(out[0])  # sync
    host_img_s = batch * 5 / (time.perf_counter() - start)
    return dev_img_s, host_img_s


def bench_gbdt_train():
    from synapseml_tpu.data.table import Table
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier

    n, d = 32561, 14
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x @ rng.normal(size=(d,)) + 0.5 * np.sin(3 * x[:, 0]) * x[:, 1]
    y = (logits + rng.normal(scale=0.5, size=n) > 0).astype(np.int32)
    table = Table({"features": x, "label": y})

    est = LightGBMClassifier(num_iterations=100, num_leaves=31,
                             learning_rate=0.1)
    est.fit(table)  # warmup: compile of binning + grower loop
    best = float("inf")
    for _ in range(3):  # best-of-3: the tunnel adds run-to-run jitter
        start = time.perf_counter()
        est.fit(table)
        best = min(best, time.perf_counter() - start)
    return n * 100 / best


def bench_onnx_lightgbm():
    """Device-resident rows/sec scoring a LightGBM-converted ONNX tree
    ensemble (TreeEnsembleClassifier via the GEMM formulation) — the
    reference notebook's actual workload: a 95-feature bankruptcy model
    scored through ONNXModel at mini_batch 5000+
    (ref: notebooks/ONNX - Inference on Spark.ipynb). Nominal GPU-VM
    baseline: 1.0e6 rows/sec (ORT-CUDA T4 tree scoring)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.data.table import Table
    from synapseml_tpu.gbdt.estimators import LightGBMClassifier
    from synapseml_tpu.onnx import convert_lightgbm, import_model

    rng = np.random.default_rng(0)
    xtr = rng.normal(size=(5000, 95)).astype(np.float32)
    ytr = (xtr[:, 0] + xtr[:, 3] > 0).astype(np.float64)
    model = LightGBMClassifier(num_iterations=100, num_leaves=31).fit(
        Table({"features": xtr, "label": ytr}))
    g = import_model(convert_lightgbm(model))
    fwd = g.bind()
    n, iters = 65536, 20
    x = jnp.asarray(rng.random((n, 95)).astype(np.float32))

    @jax.jit
    def loop(x):
        def body(i, acc):
            xx = x + (acc * 0).astype(x.dtype)
            _, probs = fwd(xx)
            return acc + probs.sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(loop(x))  # compile + warm, forced by the value fetch
    start = time.perf_counter()
    float(loop(x))
    return n * iters / (time.perf_counter() - start)


def bench_onnx_transformer():
    """Device-resident sequences/sec through an imported BERT-base-shaped
    ONNX encoder (12 layers, d=768, 12 heads, S=128, bf16) — the
    transformer-era counterpart of the ResNet metric, exercising the
    Gather/MatMul/Softmax/LayerNormalization lowering at scale. Nominal
    GPU-VM baseline: 500 seq/s (ORT-CUDA T4 fp16, BERT-base S=128)."""
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.onnx import import_model, zoo

    vocab, bs, s, iters = 30522, 32, 128, 10
    g = import_model(zoo.transformer_encoder(
        vocab, 768, 12, 3072, 12, seq_len=s, causal=False, seed=0))
    fwd = g.bind(cast_dtype=jnp.bfloat16)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, vocab, (bs, s)),
                      jnp.int32)

    @jax.jit
    def loop(ids):
        def body(i, acc):
            x = (ids + (acc * 0).astype(jnp.int32)) % vocab
            return acc + fwd(x)[0].sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0.0))

    float(loop(ids))  # compile + weight upload, forced by the value fetch
    start = time.perf_counter()
    float(loop(ids))
    return bs * iters / (time.perf_counter() - start)


def bench_serving_latency():
    """p50 request->pipeline->reply latency through the serving layer
    (ContinuousServer + parse/make_reply), echo pipeline — isolates the
    framework's own serving overhead, the reference's "sub-millisecond"
    continuous-mode claim (README.md:22, docs/mmlspark-serving.md:142).
    Model scoring cost is excluded: on this driver the chip sits behind
    a network tunnel, which no co-located deployment would pay."""
    from synapseml_tpu.utils.profiling import serving_echo_latency

    lat = serving_echo_latency(samples=300, warmup=50, name="bench")
    return lat[len(lat) // 2] * 1e3  # p50 ms


def _with_retries(fn, attempts=3):
    """The tunneled device occasionally drops remote_compile connections;
    a transient failure must not zero out the recorded benchmark."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            last = e
            if i + 1 < attempts:
                time.sleep(5 * (i + 1))
    raise last


def main():
    img_s, host_img_s = _with_retries(bench_onnx_resnet50)
    rows_s = _with_retries(bench_gbdt_train)
    tree_rows_s = _with_retries(bench_onnx_lightgbm)
    seq_s = _with_retries(bench_onnx_transformer)
    serving_p50_ms = _with_retries(bench_serving_latency)
    gpu_img_baseline = 1000.0
    gpu_rows_baseline = 1.0e6
    gpu_tree_rows_baseline = 1.0e6
    gpu_seq_baseline = 500.0
    serving_baseline_ms = 1.0  # the reference's "sub-millisecond" claim
    print(json.dumps({
        "metric": "onnx_resnet50_images_per_sec_per_chip",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / gpu_img_baseline, 3),
        "secondary": [{
            "metric": "lightgbm_train_rows_iters_per_sec_per_chip",
            "value": round(rows_s, 2),
            "unit": "rows*iters/sec",
            "vs_baseline": round(rows_s / gpu_rows_baseline, 3),
        }, {
            "metric": "onnx_resnet50_hostfeed_images_per_sec",
            "value": round(host_img_s, 2),
            "unit": "images/sec",
            "vs_baseline": round(host_img_s / gpu_img_baseline, 3),
        }, {
            "metric": "onnx_lightgbm_scoring_rows_per_sec_per_chip",
            "value": round(tree_rows_s, 2),
            "unit": "rows/sec",
            "vs_baseline": round(tree_rows_s / gpu_tree_rows_baseline, 3),
        }, {
            "metric": "onnx_bert_base_sequences_per_sec_per_chip",
            "value": round(seq_s, 2),
            "unit": "sequences/sec",
            "vs_baseline": round(seq_s / gpu_seq_baseline, 3),
        }, {
            "metric": "serving_roundtrip_p50_ms",
            "value": round(serving_p50_ms, 3),
            "unit": "ms",
            # higher = better for vs_baseline: baseline_ms / measured_ms
            "vs_baseline": round(serving_baseline_ms / serving_p50_ms, 3),
        }],
    }))


if __name__ == "__main__":
    main()
