"""Benchmark driver — prints ONE JSON line.

North-star metric (BASELINE.md): ONNX ResNet-50 inference images/sec/chip,
target >= 1x GPU-VM throughput on the "ONNX - Inference on Spark" workload.
The reference publishes no number; we take 1000 images/sec/chip as the
nominal GPU-VM (T4-class, ORT-CUDA fp16, bs128) baseline for vs_baseline.

Runs on whatever jax.devices() provides (the real TPU chip under the driver).
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from synapseml_tpu.dl.resnet import init_resnet, resnet50

    batch = 128
    model = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    variables = init_resnet(model, jax.random.PRNGKey(0), image_size=224)

    @jax.jit
    def forward(images):
        return model.apply(variables, images, train=False)

    images = jnp.asarray(
        np.random.default_rng(0).standard_normal((batch, 224, 224, 3)),
        dtype=jnp.bfloat16)

    # compile + warmup
    forward(images).block_until_ready()
    for _ in range(3):
        forward(images).block_until_ready()

    iters = 20
    start = time.perf_counter()
    for _ in range(iters):
        out = forward(images)
    out.block_until_ready()
    elapsed = time.perf_counter() - start

    images_per_sec = batch * iters / elapsed
    gpu_vm_baseline = 1000.0  # nominal GPU-VM ResNet-50 fp16 inference img/s
    print(json.dumps({
        "metric": "resnet50_inference_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / gpu_vm_baseline, 3),
    }))


if __name__ == "__main__":
    main()
