"""Auto-train conveniences + metric transformers.

Re-design of the reference's train package
(ref: core/.../train/TrainClassifier.scala:49-377, TrainRegressor.scala:20-181,
ComputeModelStatistics.scala:58-517, ComputePerInstanceStatistics.scala:45).

TrainClassifier/TrainRegressor: auto-featurize the raw table (Featurize),
reindex labels, fit any inner estimator. ComputeModelStatistics evaluates a
scored table wholly vectorized (confusion matrix / ROC-AUC via one sort, no
per-row UDFs).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasLabelCol, Param
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.featurize.assemble import Featurize
from synapseml_tpu.featurize.indexer import ValueIndexer


class TrainClassifier(Estimator, HasLabelCol):
    """Featurize + reindex labels + fit (ref: TrainClassifier.scala:49,
    fit :91)."""

    model = ComplexParam("inner classifier estimator (default: LightGBMClassifier)",
                         default=None)
    features_col = Param("assembled features column", default="TrainClassifier_features")
    number_of_features = Param("hash slots for high-cardinality columns",
                               default=256)

    def _fit(self, table: Table) -> "TrainedClassifierModel":
        inner = self.model
        if inner is None:
            from synapseml_tpu.gbdt import LightGBMClassifier
            inner = LightGBMClassifier()
        ins = [c for c in table.columns if c != self.label_col]
        featurizer = Featurize(
            input_cols=ins, output_col=self.features_col,
            num_features=int(self.number_of_features)).fit(table)
        feat_t = featurizer.transform(table)
        # label reindex (ref: TrainClassifier.scala:218 ValueIndexerModel)
        label_indexer = None
        lcol = table[self.label_col]
        if lcol.dtype == object:
            label_indexer = ValueIndexer(
                input_col=self.label_col, output_col=self.label_col).fit(table)
            feat_t = label_indexer.transform(feat_t)
        inner = inner.copy(features_col=self.features_col,
                           label_col=self.label_col)
        fitted = inner.fit(feat_t)
        return TrainedClassifierModel(
            featurizer=featurizer, label_indexer=label_indexer,
            inner_model=fitted, label_col=self.label_col)


class TrainedClassifierModel(Model, HasLabelCol):
    """ref: TrainClassifier.scala:280."""

    featurizer = ComplexParam("fitted Featurize model")
    label_indexer = ComplexParam("optional fitted label indexer", default=None)
    inner_model = ComplexParam("fitted inner classifier")

    def _transform(self, table: Table) -> Table:
        t = self.featurizer.transform(table)
        if self.label_indexer is not None and self.label_col in table:
            t = self.label_indexer.transform(t)
        return self.inner_model.transform(t)


class TrainRegressor(Estimator, HasLabelCol):
    """ref: TrainRegressor.scala:20."""

    model = ComplexParam("inner regressor estimator (default: LightGBMRegressor)",
                         default=None)
    features_col = Param("assembled features column", default="TrainRegressor_features")
    number_of_features = Param("hash slots for high-cardinality columns",
                               default=256)

    def _fit(self, table: Table) -> "TrainedRegressorModel":
        inner = self.model
        if inner is None:
            from synapseml_tpu.gbdt import LightGBMRegressor
            inner = LightGBMRegressor()
        ins = [c for c in table.columns if c != self.label_col]
        featurizer = Featurize(
            input_cols=ins, output_col=self.features_col,
            num_features=int(self.number_of_features)).fit(table)
        feat_t = featurizer.transform(table)
        inner = inner.copy(features_col=self.features_col,
                           label_col=self.label_col)
        fitted = inner.fit(feat_t)
        return TrainedRegressorModel(
            featurizer=featurizer, inner_model=fitted,
            label_col=self.label_col)


class TrainedRegressorModel(Model, HasLabelCol):
    featurizer = ComplexParam("fitted Featurize model")
    inner_model = ComplexParam("fitted inner regressor")

    def _transform(self, table: Table) -> Table:
        return self.inner_model.transform(self.featurizer.transform(table))


def _binary_auc(scores: np.ndarray, labels: np.ndarray) -> float:
    """ROC AUC via rank statistic (one sort — the vectorized analogue of the
    reference's BinaryClassificationMetrics use)."""
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(len(scores), np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    # tie-average ranks
    sorted_scores = scores[order]
    i = 0
    while i < len(sorted_scores):
        j = i
        while j + 1 < len(sorted_scores) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j + 2) / 2.0
        i = j + 1
    pos = labels > 0
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


class ComputeModelStatistics(Transformer, HasLabelCol):
    """Classification/regression metrics as a Transformer
    (ref: ComputeModelStatistics.scala:58)."""

    scores_col = Param("prediction column", default="prediction")
    scored_probabilities_col = Param("probability column (binary AUC)",
                                     default="probability")
    evaluation_metric = Param("classification | regression | auto",
                              default="auto")

    def _transform(self, table: Table) -> Table:
        y = np.asarray(table[self.label_col], np.float64)
        pred = np.asarray(table[self.scores_col], np.float64)
        mode = self.evaluation_metric
        if mode == "auto":
            mode = ("classification"
                    if len(np.unique(y)) <= max(20, int(np.sqrt(len(y))))
                    and np.allclose(y, np.round(y)) else "regression")
        if mode == "regression":
            err = pred - y
            mse = float(np.mean(err ** 2))
            ss_tot = float(np.sum((y - y.mean()) ** 2))
            return Table({
                "mean_squared_error": [mse],
                "root_mean_squared_error": [float(np.sqrt(mse))],
                "mean_absolute_error": [float(np.mean(np.abs(err)))],
                "R^2": [1.0 - float(np.sum(err ** 2)) / ss_tot if ss_tot else 0.0],
            })
        classes = np.unique(np.concatenate([y, pred]))
        k = len(classes)
        lut = {c: j for j, c in enumerate(classes)}
        yi = np.asarray([lut[v] for v in y])
        pi = np.asarray([lut[v] for v in pred])
        conf = np.zeros((k, k), np.int64)
        np.add.at(conf, (yi, pi), 1)
        acc = float((yi == pi).mean())
        # macro precision/recall (reference reports per-class + averages)
        with np.errstate(invalid="ignore", divide="ignore"):
            prec = np.diag(conf) / np.maximum(conf.sum(axis=0), 1)
            rec = np.diag(conf) / np.maximum(conf.sum(axis=1), 1)
        out = {
            "confusion_matrix": [conf],
            "accuracy": [acc],
            "precision": [float(np.nanmean(prec))],
            "recall": [float(np.nanmean(rec))],
        }
        if k == 2 and self.scored_probabilities_col in table:
            probs = table[self.scored_probabilities_col]
            p1 = (np.asarray([p[1] for p in probs], np.float64)
                  if probs.ndim == 2 or probs.dtype == object
                  else np.asarray(probs, np.float64))
            out["AUC"] = [_binary_auc(p1, yi.astype(np.float64))]
        return Table(out)


class ComputePerInstanceStatistics(Transformer, HasLabelCol):
    """Per-row residuals / log-loss (ref: ComputePerInstanceStatistics.scala:45)."""

    scores_col = Param("prediction column", default="prediction")
    scored_probabilities_col = Param("probability column", default="probability")
    evaluation_metric = Param("classification | regression | auto",
                              default="auto")
    label_values = Param(
        "ordered class values; maps non 0..k-1 labels (e.g. {-1,1}) to "
        "probability-matrix columns, as the reference does with indexed labels",
        default=None)

    def _transform(self, table: Table) -> Table:
        y = np.asarray(table[self.label_col], np.float64)
        pred = np.asarray(table[self.scores_col], np.float64)
        mode = self.evaluation_metric
        if mode == "auto":
            mode = ("classification"
                    if self.scored_probabilities_col in table else "regression")
        if mode == "regression":
            err = pred - y
            return table.with_columns({
                "L1_loss": np.abs(err),
                "L2_loss": err ** 2,
            })
        probs = table[self.scored_probabilities_col]
        mat = (np.stack(list(probs)) if probs.dtype == object
               else np.asarray(probs, np.float64))
        if self.label_values is not None:
            if len(self.label_values) > mat.shape[1]:
                raise ValueError(
                    f"label_values has {len(self.label_values)} entries but the "
                    f"probability matrix has {mat.shape[1]} columns")
            lookup = {float(v): i for i, v in enumerate(self.label_values)}
            try:
                yi = np.asarray([lookup[float(v)] for v in y], int)
            except KeyError as e:
                raise ValueError(
                    f"label {e.args[0]!r} not in label_values {self.label_values}")
        else:
            yi = y.astype(int)
            if np.any((yi != y) | (yi < 0) | (yi >= mat.shape[1])):
                raise ValueError(
                    "labels must be class indices 0..k-1; pass label_values= "
                    "to map arbitrary label values to probability columns")
        p_true = np.clip(mat[np.arange(len(yi)), yi], 1e-15, 1.0)
        return table.with_columns({
            "log_loss": -np.log(p_true),
            "correct": (pred == y).astype(np.float64),
        })
