from synapseml_tpu.train.train import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    TrainClassifier,
    TrainedClassifierModel,
    TrainedRegressorModel,
    TrainRegressor,
)

__all__ = [
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
    "TrainClassifier", "TrainedClassifierModel", "TrainedRegressorModel",
    "TrainRegressor",
]
