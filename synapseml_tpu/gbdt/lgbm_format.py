"""LightGBM native model-string serde (text format, both directions).

The reference saves/loads boosters in lib_lightgbm's text format via
``saveNativeModel``/``loadNativeModelFromFile``
(ref: lightgbm/src/main/scala/com/microsoft/ml/spark/lightgbm/booster/LightGBMBooster.scala:454-480,
LightGBMClassifier.scala loadNativeModel). This module speaks the same
format — ``tree\nversion=v3`` header, per-tree ``Tree=i`` blocks with
``split_feature``/``threshold``/``decision_type``/``left_child``/... arrays,
``feature_importances:`` and ``parameters:`` sections — so models trained
here run under lightgbm-python/SHAP tooling and vice versa.

Conventions bridged:
- LightGBM child pointers: ``c >= 0`` -> internal node ``c``; ``c < 0`` ->
  leaf ``~c``. Our Booster keeps one flat node table per tree (leaves are
  rows with ``split_feature == -1``); the walk below converts both ways.
- The training-time init score is folded into the first tree of each class
  on save (exactly what lib_lightgbm's boost_from_average does before
  serializing), and tree weights (dart/rf) are folded into leaf values, so
  ``sum of trees`` reproduces our predictions with no side channel.
- decision_type: we emit ``8`` (numerical split, missing=NaN goes right,
  matching our training semantics). Categorical splits (bit 0) load into
  the Booster's global bitset pool (``trees_cat``/``cat_bitsets``/
  ``cat_boundaries``) and save back as per-tree ``cat_boundaries``/
  ``cat_threshold`` rows; membership routes left (FindInBitset), with
  NaN/negative/out-of-range categories going right. ``default_left``
  models load but NaN feature values would take the right branch here.
"""
from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.gbdt.boosting import Booster, BoostParams


def _objective_string(p: BoostParams, k: int) -> str:
    o = p.objective
    if o in ("binary", "binary_logloss"):
        return f"binary sigmoid:{p.sigmoid:g}"
    if o in ("multiclass", "softmax"):
        return f"multiclass num_class:{k}"
    if o == "multiclassova":
        return f"multiclassova num_class:{k} sigmoid:{p.sigmoid:g}"
    if o in ("lambdarank", "rank_xendcg"):
        return o
    if o == "quantile":
        return f"quantile alpha:{p.alpha:g}"
    if o == "huber":
        return f"huber alpha:{p.alpha:g}"
    if o == "tweedie":
        return f"tweedie tweedie_variance_power:{p.tweedie_variance_power:g}"
    if o in ("regression_l1", "l1", "mae"):
        return "regression_l1"
    if o == "poisson":
        return "poisson"
    return "regression"


def _parse_objective(s: str) -> Dict[str, object]:
    toks = s.split()
    if not toks:
        return {}
    out: Dict[str, object] = {"objective": toks[0]}
    for t in toks[1:]:
        if ":" not in t:
            continue
        key, val = t.split(":", 1)
        if key == "sigmoid":
            out["sigmoid"] = float(val)
        elif key == "num_class":
            out["num_class"] = int(val)
        elif key == "alpha":
            out["alpha"] = float(val)
        elif key == "tweedie_variance_power":
            out["tweedie_variance_power"] = float(val)
    return out


def _walk_tree(feat, left, right) -> Tuple[List[int], List[int]]:
    """Preorder (internal_nodes, leaf_nodes) as node-table indices."""
    internals: List[int] = []
    leaves: List[int] = []
    stack = [0]
    while stack:
        nid = stack.pop()
        if feat[nid] < 0:
            leaves.append(nid)
        else:
            internals.append(nid)
            # preorder with left first
            stack.append(right[nid])
            stack.append(left[nid])
    return internals, leaves


def _fmt(vals, spec="{:.17g}") -> str:
    return " ".join(spec.format(v) for v in vals)


def booster_to_native_string(b: Booster) -> str:
    k = b.num_class
    t_total = b.num_trees
    if b.best_iteration >= 0:
        # lib_lightgbm's saveNativeModel truncates to the early-stopping
        # best iteration; match it so external scorers see the same model
        t_total = min(t_total, (b.best_iteration + 1) * k)
    f = b.num_features if b.num_features > 0 else (
        int(b.trees_feature.max()) + 1 if t_total else 1)
    names = b.feature_names or [f"Column_{i}" for i in range(f)]

    # feature_infos: numerical [min:max] ranges; reconstruct a loose range
    # from the thresholds actually used so lightgbm's loader accepts it
    lo = np.full(f, np.inf)
    hi = np.full(f, -np.inf)
    internal_mask = b.trees_feature >= 0
    if b.trees_cat is not None:
        # cat nodes carry set indices, not value thresholds
        internal_mask = internal_mask & (b.trees_cat < 0)
    for fi, th in zip(b.trees_feature[internal_mask],
                      b.trees_threshold[internal_mask]):
        lo[fi] = min(lo[fi], th)
        hi[fi] = max(hi[fi], th)
    infos = []
    for i in range(f):
        if np.isfinite(lo[i]):
            infos.append(f"[{lo[i] - 1:.17g}:{hi[i] + 1:.17g}]")
        else:
            infos.append("none")

    tree_blocks: List[str] = []
    for ti in range(t_total):
        feat = b.trees_feature[ti]
        thr = b.trees_threshold[ti]
        left = b.trees_left[ti]
        right = b.trees_right[ti]
        cover = b.trees_cover[ti]
        gain = b.trees_gain[ti]
        is_rf = b.params.boosting_type == "rf"
        # fold per-tree weights (dart) into leaf values so sum-of-trees is
        # the prediction; rf leaf values stay raw — the reader re-derives
        # the 1/T averaging from [boosting: rf] in the parameters section
        value = b.trees_value[ti].astype(np.float64) * (
            1.0 if is_rf else float(b.tree_weights[ti]))
        if is_rf:
            # averaging preserves a constant added to every tree
            value = value + float(b.init_score)
        elif ti < k:
            # fold the init score into the first tree of each class (what
            # lib_lightgbm's boost_from_average does before saving)
            value = value + float(b.init_score)

        internals, leaves = _walk_tree(feat, left, right)
        n_leaves = len(leaves)
        iidx = {nid: i for i, nid in enumerate(internals)}
        lidx = {nid: i for i, nid in enumerate(leaves)}

        def child_ref(c):
            return iidx[c] if feat[c] >= 0 else -(lidx[c] + 1)

        # categorical nodes: rebuild this tree's cat_boundaries /
        # cat_threshold from the global bitset pool; the node's threshold
        # column holds the per-tree cat-set index, decision_type sets bit 0
        cat = b.trees_cat[ti] if b.trees_cat is not None else None
        tree_cat_bounds = [0]
        tree_cat_words: List[int] = []
        node_thr: Dict[int, float] = {}
        node_dt: Dict[int, int] = {}
        for nid in internals:
            if cat is not None and cat[nid] >= 0:
                ci = int(cat[nid])
                lo_w = int(b.cat_boundaries[ci])
                hi_w = int(b.cat_boundaries[ci + 1])
                node_thr[nid] = float(len(tree_cat_bounds) - 1)
                node_dt[nid] = 1  # categorical split bit
                tree_cat_words.extend(int(w) for w in b.cat_bitsets[lo_w:hi_w])
                tree_cat_bounds.append(len(tree_cat_words))
            else:
                node_thr[nid] = float(thr[nid])
                node_dt[nid] = 8  # numerical, missing=NaN goes right
        num_cat = len(tree_cat_bounds) - 1

        lines = [f"Tree={ti}", f"num_leaves={n_leaves}",
                 f"num_cat={num_cat}"]
        if internals:
            lines += [
                "split_feature=" + _fmt((feat[n] for n in internals), "{:d}"),
                "split_gain=" + _fmt((max(float(gain[n]), 0.0) for n in internals)),
                "threshold=" + _fmt((node_thr[n] for n in internals)),
                "decision_type=" + _fmt((node_dt[n] for n in internals), "{:d}"),
                "left_child=" + _fmt((child_ref(left[n]) for n in internals), "{:d}"),
                "right_child=" + _fmt((child_ref(right[n]) for n in internals), "{:d}"),
            ]
        else:
            lines += ["split_feature=", "split_gain=", "threshold=",
                      "decision_type=", "left_child=", "right_child="]
        if num_cat:
            lines += [
                "cat_boundaries=" + _fmt(tree_cat_bounds, "{:d}"),
                "cat_threshold=" + _fmt(tree_cat_words, "{:d}"),
            ]
        lines += [
            "leaf_value=" + _fmt((float(value[n]) for n in leaves)),
            "leaf_weight=" + _fmt((float(cover[n]) for n in leaves)),
            "leaf_count=" + _fmt((int(cover[n]) for n in leaves), "{:d}"),
            "internal_value=" + _fmt((0.0 for _ in internals)),
            "internal_weight=" + _fmt((float(cover[n]) for n in internals)),
            "internal_count=" + _fmt((int(cover[n]) for n in internals), "{:d}"),
            "is_linear=0",
            f"shrinkage={b.params.learning_rate:g}",
        ]
        tree_blocks.append("\n".join(lines) + "\n")

    header = [
        "tree",
        "version=v3",
        f"num_class={k}",
        f"num_tree_per_iteration={k}",
        "label_index=0",
        f"max_feature_idx={f - 1}",
        f"objective={_objective_string(b.params, k)}",
    ]
    if b.params.boosting_type == "rf":
        # the literal token LightGBM's loader keys average_output_ on;
        # without it external scorers would sum instead of average
        header.append("average_output")
    header += [
        "feature_names=" + " ".join(names),
        "feature_infos=" + " ".join(infos),
        "tree_sizes=" + " ".join(str(len(tb) + 1) for tb in tree_blocks),
        "",
    ]

    imp = b.feature_importance_split
    if imp is None:
        imp = np.zeros(f)
    order = np.argsort(-np.asarray(imp), kind="stable")
    imp_lines = [f"{names[i]}={int(imp[i])}" for i in order if imp[i] > 0]

    param_lines = ["parameters:"]
    # non-standard but ignored by other parsers: keeps early-stopping
    # truncation alive across a native round trip
    if b.best_iteration >= 0:
        param_lines.append(f"[best_iteration: {b.best_iteration}]")
    for fld in dataclasses.fields(b.params):
        v = getattr(b.params, fld.name)
        if fld.name == "boosting_type":
            param_lines.append(f"[boosting: {v}]")
            continue
        if fld.name == "categorical_features":
            v = ",".join(str(i) for i in v)
        elif fld.name == "metric":
            v = "" if v is None else v
        param_lines.append(f"[{fld.name}: {v}]")
    param_lines.append("end of parameters")

    # blocks end with "\n", so joining on "\n" leaves a blank line between
    body = "\n".join(tree_blocks)
    return ("\n".join(header) + "\n"
            + body + "\n"
            + "end of trees\n\n"
            + "feature_importances:\n"
            + ("\n".join(imp_lines) + "\n" if imp_lines else "")
            + "\n" + "\n".join(param_lines) + "\n\n"
            + "pandas_categorical:null\n")


_BOOL_FIELDS = {"boost_from_average", "deterministic"}


def _parse_params_section(lines: List[str]) -> Dict[str, object]:
    fields = {f.name: f for f in dataclasses.fields(BoostParams)}
    out: Dict[str, object] = {}
    for ln in lines:
        ln = ln.strip()
        if not (ln.startswith("[") and ln.endswith("]") and ":" in ln):
            continue
        key, val = ln[1:-1].split(":", 1)
        key, val = key.strip(), val.strip()
        if key == "boosting":
            key = "boosting_type"
        if key not in fields:
            continue
        ftype = fields[key].type
        try:
            if key == "categorical_features":
                out[key] = tuple(int(x) for x in val.split(",") if x != "")
            elif key == "metric":
                out[key] = val or None
            elif key in _BOOL_FIELDS:
                out[key] = val.lower() in ("true", "1")
            elif "int" in str(ftype):
                out[key] = int(float(val))
            elif "float" in str(ftype):
                out[key] = float(val)
            else:
                out[key] = val
        except ValueError:
            continue
    return out


def booster_from_native_string(s: str) -> Booster:
    lines = s.splitlines()
    header: Dict[str, str] = {}
    i = 0
    while i < len(lines):
        ln = lines[i].strip()
        if ln.startswith("Tree="):
            break
        if ln == "average_output":
            header["average_output"] = "1"
        elif "=" in ln:
            key, val = ln.split("=", 1)
            header[key] = val
        i += 1

    k = int(header.get("num_class", "1"))
    max_feat = int(header.get("max_feature_idx", "0"))
    feature_names = header.get("feature_names", "").split() or None
    obj_info = _parse_objective(header.get("objective", "regression"))

    # split tree blocks
    blocks: List[Dict[str, str]] = []
    cur: Optional[Dict[str, str]] = None
    param_lines: List[str] = []
    in_params = False
    best_iteration = -1
    for ln in lines[i:]:
        sln = ln.strip()
        if sln.startswith("Tree="):
            cur = {}
            blocks.append(cur)
            continue
        if sln == "end of trees":
            cur = None
            continue
        if sln == "parameters:":
            in_params = True
            continue
        if sln == "end of parameters":
            in_params = False
            continue
        if in_params:
            if sln.startswith("[best_iteration:"):
                best_iteration = int(sln[1:-1].split(":", 1)[1])
            param_lines.append(sln)
            continue
        if cur is not None and "=" in sln:
            key, val = sln.split("=", 1)
            cur[key] = val

    def ints(s_):
        return np.array([int(x) for x in s_.split()], np.int32) \
            if s_.strip() else np.zeros(0, np.int32)

    def floats(s_):
        return np.array([float(x) for x in s_.split()], np.float64) \
            if s_.strip() else np.zeros(0, np.float64)

    parsed = []
    max_leaves = 1
    for tb in blocks:
        nl = int(tb.get("num_leaves", "1"))
        dt = ints(tb.get("decision_type", ""))
        missing_type = (dt >> 2) & 3
        if np.any(missing_type == 1):
            raise NotImplementedError(
                "zero-as-missing splits (missing_type=Zero) cannot be "
                "represented by this predictor; retrain without "
                "zero_as_missing or use missing_type NaN/None")
        if np.any((missing_type == 2) & ((dt >> 1) & 1 == 1)):
            warnings.warn(
                "model uses default_left with NaN missing values; this "
                "predictor routes NaN to the right child, so predictions "
                "differ from lib_lightgbm only on rows containing NaN",
                RuntimeWarning, stacklevel=2)
        if np.any((dt & 1 == 1) & (missing_type != 2)):
            warnings.warn(
                "model has categorical splits with missing_type != NaN; "
                "lib_lightgbm casts NaN to category 0 there, while this "
                "predictor routes NaN right, so predictions differ from "
                "lib_lightgbm only on rows with NaN in those features",
                RuntimeWarning, stacklevel=2)
        parsed.append(dict(
            nl=nl,
            sf=ints(tb.get("split_feature", "")),
            gain=floats(tb.get("split_gain", "")),
            thr=floats(tb.get("threshold", "")),
            lc=ints(tb.get("left_child", "")),
            rc=ints(tb.get("right_child", "")),
            lv=floats(tb.get("leaf_value", "")),
            lcount=floats(tb.get("leaf_count", "")),
            icount=floats(tb.get("internal_count", "")),
            dt=dt,
            num_cat=int(tb.get("num_cat", "0") or 0),
            cat_bounds=ints(tb.get("cat_boundaries", "")),
            cat_words=ints(tb.get("cat_threshold", "")),
        ))
        max_leaves = max(max_leaves, nl)

    t_total = len(parsed)
    m = 2 * max_leaves - 1
    tf = np.full((t_total, m), -1, np.int32)
    tt = np.zeros((t_total, m), np.float32)
    tl = np.zeros((t_total, m), np.int32)
    tr = np.zeros((t_total, m), np.int32)
    tv = np.zeros((t_total, m), np.float32)
    tc = np.zeros((t_total, m), np.float32)
    tg = np.zeros((t_total, m), np.float32)
    any_cat = any(tb["num_cat"] > 0 for tb in parsed)
    tcat = np.full((t_total, m), -1, np.int32) if any_cat else None
    g_words: List[int] = []        # global bitset word pool
    g_bounds: List[int] = [0]      # word offsets per global cat set

    for ti, tb in enumerate(parsed):
        nl = tb["nl"]
        ni = nl - 1  # internal count
        # table layout: internal i -> i, leaf j -> ni + j (root stays 0;
        # single-leaf trees have the leaf at slot 0)
        for j in range(ni):
            tf[ti, j] = tb["sf"][j]
            tg[ti, j] = tb["gain"][j] if j < len(tb["gain"]) else 0.0
            if j < len(tb["icount"]):
                tc[ti, j] = tb["icount"][j]
            is_cat = j < len(tb["dt"]) and bool(tb["dt"][j] & 1)
            if is_cat:
                # categorical: the threshold field is the per-tree cat-set
                # index into cat_boundaries/cat_threshold; re-home its
                # bitset words into the global pool
                ci = int(tb["thr"][j])
                if (ci + 1 >= len(tb["cat_bounds"])
                        or int(tb["cat_bounds"][ci + 1])
                        > len(tb["cat_words"])):
                    raise ValueError(
                        f"corrupt model: tree {ti} node {j} is a "
                        f"categorical split but cat_boundaries/"
                        f"cat_threshold rows are missing or too short")
                lo = int(tb["cat_bounds"][ci])
                hi = int(tb["cat_bounds"][ci + 1])
                tcat[ti, j] = len(g_bounds) - 1
                g_words.extend(
                    int(w) & 0xFFFFFFFF for w in tb["cat_words"][lo:hi])
                g_bounds.append(len(g_words))
                tt[ti, j] = 0.0
            else:
                tt[ti, j] = tb["thr"][j]
            lc, rc = tb["lc"][j], tb["rc"][j]
            tl[ti, j] = lc if lc >= 0 else ni + (-lc - 1)
            tr[ti, j] = rc if rc >= 0 else ni + (-rc - 1)
        for j in range(nl):
            slot = ni + j if ni else 0
            tv[ti, slot] = tb["lv"][j] if j < len(tb["lv"]) else 0.0
            if j < len(tb["lcount"]):
                tc[ti, slot] = tb["lcount"][j]

    pkw = _parse_params_section(param_lines)
    pkw.update(obj_info)
    if header.get("average_output") and "boosting_type" not in pkw:
        pkw["boosting_type"] = "rf"  # files written by other emitters may
        # carry only the header token, not a parameters section
    pkw.setdefault("num_class", k)
    if t_total and k:
        pkw["num_iterations"] = t_total // k
    known = {f.name for f in dataclasses.fields(BoostParams)}
    params = BoostParams(**{kk: vv for kk, vv in pkw.items() if kk in known})

    booster = Booster(
        trees_feature=tf, trees_threshold=tt, trees_left=tl, trees_right=tr,
        trees_value=tv, trees_cover=tc, trees_gain=tg,
        tree_weights=np.ones(t_total, np.float32),
        params=params,
        init_score=0.0,  # folded into the first trees by the writer
        num_class=k,
        best_iteration=best_iteration,
        num_features=max_feat + 1,
        feature_names=feature_names,
        trees_cat=tcat,
        cat_bitsets=(np.asarray(g_words, np.uint32) if any_cat else None),
        cat_boundaries=(np.asarray(g_bounds, np.int32) if any_cat
                        else None),
    )
    from synapseml_tpu.gbdt.boosting import _importances
    booster.feature_importance_split, booster.feature_importance_gain = (
        _importances(booster, max_feat + 1))
    return booster
