"""Pallas TPU kernels for the GBDT engine.

The histogram build is the engine's hot op (SURVEY.md §3.1 HOT LOOP #2 —
the reference spends it inside lib_lightgbm's C++). The XLA path computes
it as a fused one-hot einsum (grower.histogram); this kernel goes one step
further: the [F, 3, B] accumulator lives in VMEM across the whole row
sweep, each grid step loads one row chunk and issues F small MXU dots
(one-hot^T @ (grad, hess, count)), and HBM sees exactly one read of the
inputs and one write of the result.

Falls back transparently: callers probe :func:`available` once (compiles a
tiny kernel); anything failing — CPU backend, interpret quirks, older
jaxlib — routes to the XLA formulation.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_TN = 512  # rows per grid step (wide-feature default)

# Per-core VMEM is ~16 MiB on current TPUs; a grid step whose resident
# blocks exceed it dies inside Mosaic with an opaque allocation error.
# Both wrappers bound their block bytes against this before launching
# so oversized shapes (huge n_bins, very deep trees) fail with an
# actionable message at the call site instead.
_VMEM_BUDGET_BYTES = 16 * 1024 * 1024


def _check_vmem_budget(kernel: str, block_bytes: int) -> None:
    """Reject launches whose per-grid-step VMEM residency (with the
    pipeline's double-buffering headroom) exceeds the core budget."""
    budgeted = 2 * block_bytes  # input blocks are double-buffered
    if budgeted > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"{kernel}: per-step block residency ~{budgeted} bytes "
            f"exceeds the VMEM budget ({_VMEM_BUDGET_BYTES}); shrink "
            "the bin count / tree width or use the XLA fallback")


def _rows_per_step(n_feat: int) -> int:
    """Rows per grid step, chosen by feature width. Each step issues
    ``n_feat`` small MXU dots over the chunk; with few features a step
    does too little work to cover grid overhead, so narrow matrices take
    bigger chunks. Measured on v5e (r05): f=14 @ 1024 is 1.8x f=14 @ 512
    isolated (8.8 -> 15.8M rows/s); f=28 @ 512 stays best (26.7M)."""
    return 1024 if n_feat <= 16 else _TN


def _hist_kernel(binned_ref, data_ref, out_ref, *, n_feat: int,
                 n_bins_padded: int, tn: int):
    """binned_ref [tn, F] int32; data_ref [3, tn] f32 (pad rows are zero);
    out_ref [F, 3, Bp] f32 accumulated across the sequential grid."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    chunk = binned_ref[...]
    dat = data_ref[...]
    bins = jax.lax.broadcasted_iota(jnp.int32, (tn, n_bins_padded), 1)
    # hi/lo split: the one-hot operand is exact in bf16, so two default-
    # precision MXU passes (hi + residual) recover ~f32 accuracy at 2/3 the
    # cost of Precision.HIGHEST's three passes
    dhi = dat.astype(jnp.bfloat16).astype(jnp.float32)
    dlo = dat - dhi
    for f in range(n_feat):  # static unroll: F small, each iter two MXU dots
        ohf = (chunk[:, f][:, None] == bins).astype(jnp.float32)
        acc = (jnp.dot(dhi, ohf, preferred_element_type=jnp.float32)
               + jnp.dot(dlo, ohf, preferred_element_type=jnp.float32))
        out_ref[f, :, :] += acc


def histogram_tpu(binned: jnp.ndarray, data: jnp.ndarray,
                  n_bins: int, interpret: bool = False) -> jnp.ndarray:
    """[F, B, 3] histogram of ``data`` columns per (feature, bin).

    binned: [N, F] integer bins; data: [N, 3] f32 (already mask-weighted —
    masked rows must be zero in data, their bin values then don't matter).
    ``interpret=True`` runs the kernel body under the pallas interpreter
    (any backend) — CI numerics coverage where no TPU is attached.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = binned.shape
    tn = _rows_per_step(f)
    bp = max(128, -(-n_bins // 128) * 128)
    pad = (-n) % tn
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        data = jnp.pad(data, ((0, pad), (0, 0)))
    grid = (binned.shape[0] // tn,)
    # resident per step: binned [tn,F] i32 + data [3,tn] f32 + the full
    # [F,3,Bp] f32 accumulator (bounded vs _VMEM_BUDGET_BYTES)
    _check_vmem_budget(
        "histogram_tpu", 4 * (tn * f + 3 * tn + f * 3 * bp))

    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_feat=f, n_bins_padded=bp, tn=tn),
        out_shape=jax.ShapeDtypeStruct((f, 3, bp), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, f), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, tn), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((f, 3, bp), lambda i: (0, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(binned.astype(jnp.int32), data.T)
    return jnp.transpose(out, (0, 2, 1))[:, :n_bins, :]


_TRAV_TN = 256  # rows per traversal grid step


def _traverse_kernel(x_ref, feat_ref, thr_ref, left_ref, right_ref,
                     value_ref, out_ref, *, tn: int, m_pad: int,
                     n_feat: int, k: int, depth: int, strict: bool):
    """One (row tile, tree) grid step of the fused forest traversal.

    x_ref [tn, F] f32; tree refs [1, m_pad] (feat/left/right int32,
    thr/value f32, value pre-scaled by the tree weight); out_ref [tn, k]
    f32 accumulated across the sequential tree axis of the grid.

    Every per-row gather (``feat[node]``, ``x[row, feat]``) is a one-hot
    select + lane reduce over VMEM-resident operands — the VPU
    formulation of the gather chains the XLA path serializes through
    HBM. NaN feature values compare False on both <= and < and so go
    RIGHT, matching training's missing-bin placement; the select keeps
    NaN only in the selected lane (``where`` masks, never a dot, so a
    NaN lane cannot leak into other rows).
    """
    from jax.experimental import pallas as pl

    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = x_ref[...]
    feat = feat_ref[...]
    thr = thr_ref[...]
    left = left_ref[...]
    right = right_ref[...]
    value = value_ref[...]

    iota_m = jax.lax.broadcasted_iota(jnp.int32, (tn, m_pad), 1)
    iota_f = jax.lax.broadcasted_iota(jnp.int32, (tn, n_feat), 1)

    def step(_, node):
        sel = node == iota_m                               # [tn, m_pad]
        f_r = jnp.sum(jnp.where(sel, feat, 0), axis=1, keepdims=True)
        thr_r = jnp.sum(jnp.where(sel, thr, 0.0), axis=1, keepdims=True)
        l_r = jnp.sum(jnp.where(sel, left, 0), axis=1, keepdims=True)
        r_r = jnp.sum(jnp.where(sel, right, 0), axis=1, keepdims=True)
        sel_f = jnp.maximum(f_r, 0) == iota_f              # [tn, F]
        xv = jnp.sum(jnp.where(sel_f, x, 0.0), axis=1, keepdims=True)
        go_left = (xv < thr_r) if strict else (xv <= thr_r)
        nxt = jnp.where(go_left, l_r, r_r)
        return jnp.where(f_r < 0, node, nxt)

    node = jax.lax.fori_loop(0, depth, step,
                             jnp.zeros((tn, 1), jnp.int32))
    sel = node == iota_m
    val = jnp.sum(jnp.where(sel, value, 0.0), axis=1, keepdims=True)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (tn, k), 1)
    out_ref[...] += jnp.where(iota_k == t % k, val, 0.0)


def predict_forest_tpu(x, feat, thr, left, right, value, k: int = 1,
                       depth: Optional[int] = None, strict: bool = False,
                       interpret: bool = False) -> jnp.ndarray:
    """Fused multi-tree traversal: walk every row of ``x`` [N, F] through
    ALL ``T`` trees and accumulate ``value[final_node]`` into class column
    ``t % k`` — the whole ensemble in one kernel launch, leaf sums
    resident in VMEM (vs. a T-step gather-chain scan through HBM).

    feat/left/right [T, M] int; thr/value [T, M] f32 (``value`` already
    scaled by per-tree weights). ``strict`` compares ``x < thr``
    (isolation-forest convention); default is GBDT's ``x <= thr``.
    ``depth`` bounds the walk (defaults to M//2+1, the worst case of a
    2M+1-node tree). Returns [N, k] f32. The depth-accumulating
    isolation-forest use is this same kernel with ``value=depth_adj``,
    ``strict=True``: the accumulated "leaf value" IS the path length.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, f = x.shape
    t, m = feat.shape
    if depth is None:
        depth = m // 2 + 1
    if n == 0 or t == 0:
        return jnp.zeros((n, k), jnp.float32)
    tn = min(_TRAV_TN, max(8, -(-n // 8) * 8)) if n < _TRAV_TN else _TRAV_TN
    m_pad = max(128, -(-m // 128) * 128)
    if m_pad > m:
        # pad slots are leaves (feat -1) with value 0: unreachable, and
        # harmless even if a malformed child pointer lands on one
        feat = jnp.pad(feat, ((0, 0), (0, m_pad - m)), constant_values=-1)
        thr = jnp.pad(thr, ((0, 0), (0, m_pad - m)))
        left = jnp.pad(left, ((0, 0), (0, m_pad - m)))
        right = jnp.pad(right, ((0, 0), (0, m_pad - m)))
        value = jnp.pad(value, ((0, 0), (0, m_pad - m)))
    pad = (-n) % tn
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    grid = (x.shape[0] // tn, t)
    # resident per step: x [tn,F] f32 + five [1,m_pad] tree planes +
    # out [tn,k] f32 (bounded vs _VMEM_BUDGET_BYTES)
    _check_vmem_budget(
        "predict_forest_tpu", 4 * (tn * f + 5 * m_pad + tn * k))

    kern = functools.partial(
        _traverse_kernel, tn=tn, m_pad=m_pad, n_feat=f, k=k,
        depth=int(depth), strict=strict)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((x.shape[0], k), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, f), lambda i, t: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, t: (t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, m_pad), lambda i, t: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tn, k), lambda i, t: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x.astype(jnp.float32), feat.astype(jnp.int32),
      thr.astype(jnp.float32), left.astype(jnp.int32),
      right.astype(jnp.int32), value.astype(jnp.float32))
    return out[:n]


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """One-time probe: compile + run the kernel on tiny shapes and compare
    against the reference formulation.

    The first call usually happens while TRACING the boosting scan
    (grower.histogram); under an ambient trace, nested jit calls inline
    and their results become tracers, so the probe must escape to
    compile-time eval or it would cache a spurious False forever (the
    round-2 'pallas never ran' bug, caught by bench r3)."""
    import os

    if os.environ.get("SYNAPSEML_GBDT_PALLAS", "1") == "0":
        return False
    if jax.default_backend() != "tpu":
        return False
    try:
        # trace-safe: concrete numpy in, AOT lower+compile+execute out.
        # A plain jit call would INLINE into any ambient trace and hand
        # back tracers; the compiled executable runs for real regardless.
        rng = np.random.default_rng(0)
        binned = rng.integers(0, 7, (700, 3)).astype(np.int32)
        data = rng.normal(size=(700, 3)).astype(np.float32)
        compiled = jax.jit(
            lambda b, d: histogram_tpu(b, d, 7)).lower(
            binned, data).compile()
        got = np.asarray(compiled(binned, data))
        # reference in pure numpy (f64 accumulate: the bf16-free truth)
        oh = (binned[..., None] == np.arange(7)).astype(np.float64)
        want = np.einsum("nfb,nc->fbc", oh,
                         data.astype(np.float64)).astype(np.float32)
        return bool(np.allclose(got, want, rtol=1e-3, atol=1e-3))
    except Exception:  # noqa: BLE001 - any failure means "use XLA"
        return False
