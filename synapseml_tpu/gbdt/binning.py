"""Feature binning for the histogram GBDT engine.

The reference gets binning free from lib_lightgbm's C++ BinMapper (the JNI jar
behind lightgbm/.../dataset/DatasetAggregator.scala). TPU-native design: bin on
the host once into a uint8 matrix (max 255 bins + missing bin) — the ONLY
representation ever shipped to the device — so every downstream op (histogram
build, split application) is integer gather/scatter with static shapes.

Bin semantics follow LightGBM: quantile (equal-count) boundaries over distinct
values, a dedicated missing bin, categorical features binned by category id.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class BinInfo:
    """Per-feature binning metadata."""
    upper_bounds: np.ndarray          # [n_bins-?] float64 boundaries for numeric
    is_categorical: bool = False
    categories: Optional[np.ndarray] = None   # category value per bin
    n_bins: int = 0                   # data bins (excluding the missing bin)


class BinMapper:
    """Fit quantile bins on host data; transform to uint8 bin indices.

    Missing values map to bin ``n_bins`` (the last, dedicated missing bin).
    """

    def __init__(self, max_bin: int = 255, categorical_features: Sequence[int] = (),
                 max_cat: int = 255, subsample: int = 200_000, seed: int = 0):
        self.max_bin = int(max_bin)
        self.categorical_features = set(int(c) for c in categorical_features)
        self.max_cat = int(max_cat)
        self.subsample = subsample
        self.seed = seed
        self.bins_: List[BinInfo] = []
        self.n_features_: int = 0

    # -- fitting -------------------------------------------------------
    def fit(self, x: np.ndarray) -> "BinMapper":
        x = np.asarray(x, dtype=np.float64)
        n, f = x.shape
        self.n_features_ = f
        if n > self.subsample:
            rng = np.random.default_rng(self.seed)
            x = x[rng.choice(n, self.subsample, replace=False)]
        self.bins_ = []
        for j in range(f):
            col = x[:, j]
            if j in self.categorical_features:
                self.bins_.append(self._fit_categorical(col))
            else:
                self.bins_.append(self._fit_numeric(col))
        return self

    def _fit_numeric(self, col: np.ndarray) -> BinInfo:
        finite = col[np.isfinite(col)]
        if finite.size == 0:
            return BinInfo(upper_bounds=np.asarray([np.inf]), n_bins=1)
        distinct = np.unique(finite)
        if distinct.size <= self.max_bin:
            # boundary = midpoint between consecutive distinct values
            uppers = np.concatenate(
                [(distinct[:-1] + distinct[1:]) / 2.0, [np.inf]])
        else:
            qs = np.linspace(0, 1, self.max_bin + 1)[1:-1]
            cuts = np.unique(np.quantile(finite, qs))
            uppers = np.concatenate([cuts, [np.inf]])
        return BinInfo(upper_bounds=uppers, n_bins=len(uppers))

    def _fit_categorical(self, col: np.ndarray) -> BinInfo:
        finite = col[np.isfinite(col)]
        cats, counts = np.unique(finite.astype(np.int64), return_counts=True)
        if cats.size > self.max_cat:
            cats = cats[np.argsort(-counts)][: self.max_cat]
            cats = np.sort(cats)
        return BinInfo(upper_bounds=np.asarray([]), is_categorical=True,
                       categories=cats, n_bins=max(len(cats), 1))

    # -- transform -----------------------------------------------------
    @property
    def total_bins(self) -> int:
        """Max bins over features incl. the missing bin (device array width)."""
        return max(b.n_bins for b in self.bins_) + 1

    def missing_bin(self, j: int) -> int:
        return self.bins_[j].n_bins

    def transform(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        n, f = x.shape
        assert f == self.n_features_, (f, self.n_features_)
        out = np.empty((n, f), dtype=np.uint8 if self.total_bins <= 256 else np.uint16)
        for j in range(f):
            info = self.bins_[j]
            col = x[:, j]
            miss = ~np.isfinite(col)
            if info.is_categorical:
                idx = np.searchsorted(info.categories, col.astype(np.int64,
                                                                  casting="unsafe"))
                idx = np.clip(idx, 0, len(info.categories) - 1)
                known = np.zeros(n, dtype=bool)
                ok = ~miss
                known[ok] = info.categories[idx[ok]] == col[ok].astype(np.int64)
                b = np.where(known, idx, info.n_bins)
            else:
                b = np.searchsorted(info.upper_bounds, col, side="left")
                b = np.where(miss, info.n_bins, np.minimum(b, info.n_bins - 1))
            out[:, j] = b
        return out

    def bin_upper_value(self, j: int, b: int) -> float:
        """Numeric threshold for 'goes left if value <= threshold' at bin b."""
        info = self.bins_[j]
        if info.is_categorical:
            return float(info.categories[min(b, len(info.categories) - 1)])
        return float(info.upper_bounds[min(b, info.n_bins - 1)])

    def threshold_values(self) -> np.ndarray:
        """[F, B] array: split value for (feature, bin) pairs (device-side)."""
        bmax = self.total_bins
        out = np.full((self.n_features_, bmax), np.inf, dtype=np.float64)
        for j, info in enumerate(self.bins_):
            if info.is_categorical:
                vals = info.categories.astype(np.float64)
                out[j, :len(vals)] = vals
            else:
                out[j, :info.n_bins] = info.upper_bounds
        return out
