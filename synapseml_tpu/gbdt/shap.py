"""Path-dependent TreeSHAP for the GBDT Booster.

Parity target: the reference's per-row SHAP surface (featuresShapCol /
predict_contrib, ref: lightgbm/.../LightGBMModelMethods.scala:12-116 and
booster SHAP at lightgbm/.../booster/LightGBMBooster.scala:414), computed
natively by lib_lightgbm. This is the Lundberg & Lee path-dependent TreeSHAP
algorithm over our flat tree arrays, host-side numpy (the per-row cost is
O(T·L·D²) control flow — a poor fit for the MXU; batching via the explainers'
KernelSHAP path is the TPU-native alternative for large N).

Returns [N, F+1] — per-feature contributions plus the expected value in the
last slot, matching LightGBM's predict(..., pred_contrib=True) layout.
"""
from __future__ import annotations

import numpy as np


class _Path:
    __slots__ = ("d", "z", "o", "w")

    def __init__(self, n):
        self.d = np.empty(n, np.int64)   # feature index
        self.z = np.empty(n, np.float64)  # zero fraction
        self.o = np.empty(n, np.float64)  # one fraction
        self.w = np.empty(n, np.float64)  # permutation weight


def _extend(p: _Path, m: int, pz: float, po: float, pi: int):
    p.d[m] = pi
    p.z[m] = pz
    p.o[m] = po
    p.w[m] = 1.0 if m == 0 else 0.0
    for i in range(m - 1, -1, -1):
        p.w[i + 1] += po * p.w[i] * (i + 1) / (m + 1)
        p.w[i] = pz * p.w[i] * (m - i) / (m + 1)


def _unwind(p: _Path, m: int, i: int):
    n = p.w[m]
    o, z = p.o[i], p.z[i]
    for j in range(m - 1, -1, -1):
        if o != 0:
            t = p.w[j]
            p.w[j] = n * (m + 1) / ((j + 1) * o)
            n = t - p.w[j] * z * (m - j) / (m + 1)
        else:
            p.w[j] = p.w[j] * (m + 1) / (z * (m - j))
    for j in range(i, m):
        p.d[j] = p.d[j + 1]
        p.z[j] = p.z[j + 1]
        p.o[j] = p.o[j + 1]


def _unwound_sum(p: _Path, m: int, i: int) -> float:
    n = p.w[m]
    o, z = p.o[i], p.z[i]
    total = 0.0
    if o != 0:
        for j in range(m - 1, -1, -1):
            t = n / ((j + 1) * o)
            total += t
            n = p.w[j] - t * z * (m - j)
    else:
        for j in range(m - 1, -1, -1):
            total += p.w[j] / (z * (m - j))
    return total * (m + 1)


def _shap_recurse(feat, thr, left, right, value, cover, x, phi,
                  node, pz, po, pi, parent: _Path, m: int):
    p = _Path(m + 2)
    p.d[:m] = parent.d[:m]
    p.z[:m] = parent.z[:m]
    p.o[:m] = parent.o[:m]
    p.w[:m] = parent.w[:m]
    _extend(p, m, pz, po, pi)
    m = m + 1

    if feat[node] < 0:  # leaf
        v = value[node]
        for i in range(1, m):
            w = _unwound_sum(p, m - 1, i)
            phi[p.d[i]] += w * (p.o[i] - p.z[i]) * v
        return

    f = feat[node]
    hot, cold = (left[node], right[node]) if x[f] <= thr[node] else (
        right[node], left[node])
    iz, io = 1.0, 1.0
    k = -1
    for i in range(1, m):
        if p.d[i] == f:
            k = i
            break
    if k >= 0:
        iz, io = p.z[k], p.o[k]
        _unwind(p, m - 1, k)
        m -= 1

    c = max(cover[node], 1e-12)
    _shap_recurse(feat, thr, left, right, value, cover, x, phi,
                  hot, iz * cover[hot] / c, io, f, p, m)
    _shap_recurse(feat, thr, left, right, value, cover, x, phi,
                  cold, iz * cover[cold] / c, 0.0, f, p, m)


def _expected_value(feat, left, right, value, cover, node=0) -> float:
    if feat[node] < 0:
        return value[node]
    c = max(cover[node], 1e-12)
    return (cover[left[node]] / c * _expected_value(feat, left, right, value,
                                                    cover, left[node])
            + cover[right[node]] / c * _expected_value(feat, left, right,
                                                       value, cover,
                                                       right[node]))


def tree_shap(booster, x: np.ndarray) -> np.ndarray:
    """SHAP contributions [N, F+1] (last column = expected value)."""
    if getattr(booster, "trees_cat", None) is not None:
        raise NotImplementedError(
            "TreeSHAP is not implemented for models with categorical "
            "splits (loaded native LightGBM model)")
    x = np.asarray(x, np.float64)
    n, f = x.shape
    nf = int(getattr(booster, "num_features", -1))
    if nf > 0 and f != nf:
        # same loud contract as Booster._raw_scores — a narrow row would
        # otherwise IndexError deep in the recursion, a wide one would
        # silently drop columns
        raise ValueError(
            f"feature width mismatch: model trained on {nf} features, "
            f"got {f}")
    k = booster.num_class
    out = np.zeros((n, f + 1) if k == 1 else (n, k, f + 1), np.float64)

    for t in range(booster.num_trees):
        feat = booster.trees_feature[t].astype(np.int64)
        thr = booster.trees_threshold[t].astype(np.float64)
        left = booster.trees_left[t].astype(np.int64)
        right = booster.trees_right[t].astype(np.int64)
        value = booster.trees_value[t].astype(np.float64)
        cover = booster.trees_cover[t].astype(np.float64)
        w = float(booster.tree_weights[t])
        value = value * w
        ev = _expected_value(feat, left, right, value, cover)
        cls = t % k
        for i in range(n):
            phi = np.zeros(f + 1, np.float64)
            empty = _Path(1)
            _shap_recurse(feat, thr, left, right, value, cover, x[i], phi,
                          0, 1.0, 1.0, -1, empty, 0)
            phi[f] += ev
            if k == 1:
                out[i] += phi
            else:
                out[i, cls] += phi
    if k == 1:
        out[:, f] += booster.init_score
    else:
        out[:, :, f] += booster.init_score
    return out
