"""Leaf-wise histogram tree grower — the device-side heart of the GBDT engine.

Replaces lib_lightgbm's C++ serial tree learner + socket collectives
(ref: lightgbm/.../TrainUtils.scala trainCore:92-159 drives
LGBM_BoosterUpdateOneIter inside the native jar; SURVEY.md §2.10
tree_learner=data_parallel merges histograms via reduce-scatter over TCP).

TPU-native design — everything below runs inside ONE jitted function with
static shapes:
- rows live as a uint8-binned [N, F] matrix (see binning.py);
- histogram build is a single ``segment_sum`` over (feature, bin) ids —
  O(N·F) gather/adds, batched, no per-row host loop;
- leaf-wise growth runs as a ``lax.fori_loop`` over num_leaves-1 splits with
  per-slot state arrays; the chosen leaf/feature/bin are traced values
  (argmax), never Python control flow;
- the sibling histogram comes from parent-child subtraction (the classic
  LightGBM trick), so each split costs one masked histogram pass;
- under data parallelism the histogram is ``psum``ed over the ``dp`` mesh
  axis (ICI replaces the reference's TCP ring); every rank then takes the
  same split decisions deterministically.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class GrowerParams:
    num_leaves: int = 31
    max_bin: int = 256               # device histogram width (incl. missing bin)
    max_depth: int = 0               # 0 = unlimited
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    # histogram formulation: "auto" (static availability heuristic),
    # "pallas", or "xla". boosting.train resolves "auto" to a MEASURED
    # winner via resolve_hist_backend before tracing the boosting loop.
    hist_backend: str = "auto"
    # PV-tree voting (the reference's parallelism="voting_parallel",
    # LightGBM top_k): >0 elects that many features per split by a
    # psum'd local-gain vote and merges ONLY their histograms across the
    # mesh — per-split exchange drops from [F, B, 3] to [top_k, B, 3],
    # the lever when the dp axis rides DCN instead of ICI. 0 = exact
    # data_parallel (full-histogram psum).
    voting_top_k: int = 0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class Tree:
    """Flat tree arrays (host or device). M = 2*num_leaves - 1 nodes."""
    split_feature: jnp.ndarray   # [M] int32, -1 => leaf/unused
    threshold: jnp.ndarray       # [M] float32 raw-value threshold (<= goes left)
    threshold_bin: jnp.ndarray   # [M] int32 bin threshold (<= goes left)
    left_child: jnp.ndarray      # [M] int32
    right_child: jnp.ndarray     # [M] int32
    leaf_value: jnp.ndarray      # [M] float32 (valid where split_feature < 0)
    cover: jnp.ndarray           # [M] float32 training row count per node
    gain: jnp.ndarray            # [M] float32 split gain (internal nodes)


def _pallas_shape_ok(n: int, f: int, n_bins: int) -> bool:
    """Shape bounds that keep the pallas kernel's VMEM blocks + static
    F-unroll sane; wide-feature / huge-bin cases route to XLA."""
    return f <= 128 and n_bins <= 512 and n >= 512


def histogram(binned, grad, hess, mask, n_bins: int,
              axis_name: Optional[str] = None, backend: str = "auto"):
    """[F, B, 3] histogram of (grad, hess, count) as a one-hot contraction.

    MXU-native formulation: the bin one-hot is fused by XLA into the dot's
    operand (never materialized in HBM), so a histogram costs one pass over
    the [N, F] uint8 matrix — versus a serialized scatter-add for the
    equivalent ``segment_sum``, which measured ~100x slower per tree on
    a v5e chip. ``backend`` selects the formulation on TPU ("pallas" /
    "xla"); "auto" keeps the static availability heuristic — callers that
    can afford a probe should resolve it first (resolve_hist_backend).
    """
    n, f = binned.shape
    w = mask.astype(jnp.float32)
    data = jnp.stack([grad * w, hess * w, w], axis=-1)          # [N, 3]
    if jax.default_backend() == "tpu":
        from synapseml_tpu.gbdt import pallas_kernels

        requested = backend
        if backend == "auto" and jax.process_count() == 1:
            # per-(rows, F, B)-shape MEASURED verdict when one is cached
            # (resolve_hist_backend probes and persists them) — the
            # static availability heuristic only decides for shapes no
            # probe ever timed. Shapes are static at trace time, so this
            # host-side lookup is trace-safe. Single-process ONLY: ranks
            # of a multi-host fit can hold different cached verdicts
            # (only rank 0 probes+persists), and divergent backends trace
            # non-identical SPMD programs for one collective fit —
            # undefined under XLA multi-host. Multi-process callers get
            # the rank-deterministic heuristic unless they pre-resolve
            # via resolve_hist_backend (which broadcasts rank 0's
            # verdict), as boosting.train does.
            routed = cached_hist_route(n, f, n_bins)
            if routed is not None:
                backend = routed
        use_pallas = (backend != "xla" and pallas_kernels.available()
                      and _pallas_shape_ok(n, f, n_bins))
        # only an EXPLICIT pallas request warns: a cached auto verdict can
        # legitimately overrule itself at a shape the kernel rejects
        # (row-bucketed keys), and that silent XLA fallback is correct
        if requested == "pallas" and not use_pallas:
            import warnings
            warnings.warn(
                f"hist_backend='pallas' requested but unusable for shape "
                f"(n={n}, f={f}, bins={n_bins}) or kernel unavailable — "
                "running the XLA formulation instead", stacklevel=2)
        if use_pallas:
            # VMEM-resident accumulator kernel: one HBM pass over the rows
            hist = pallas_kernels.histogram_tpu(binned, data, n_bins)
        else:
            oh = jax.nn.one_hot(binned.astype(jnp.int32), n_bins,
                                dtype=jnp.float32)
            # HIGHEST: default MXU precision would truncate grad/hess to
            # bf16 inside the dot and perturb split decisions
            hist = jnp.einsum("nfb,nc->fbc", oh, data,
                              preferred_element_type=jnp.float32,
                              precision=lax.Precision.HIGHEST)
    else:
        # CPU/GPU: scatter-add beats materializing the one-hot
        ids = (binned.astype(jnp.int32)
               + jnp.arange(f, dtype=jnp.int32)[None, :] * n_bins)
        flat = jnp.broadcast_to(data[:, None, :], (n, f, 3)).reshape(n * f, 3)
        hist = jax.ops.segment_sum(
            flat, ids.reshape(-1), num_segments=f * n_bins
        ).reshape(f, n_bins, 3)
    if axis_name is not None:
        hist = lax.psum(hist, axis_name)
    return hist


_HIST_ROUTE_CACHE: dict = {}


def _route_cache_path():
    import os
    d = os.environ.get("SYNAPSEML_TPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "synapseml_tpu")
    return os.path.join(d, "hist_routing.json")


def _route_key_base(n: int, f: int, n_bins: int) -> str:
    """Canonical per-shape routing key (without the reduced-tier suffix).

    Versioned: a jaxlib OR in-package kernel upgrade can flip the winner,
    and a stale persisted verdict would be the "remembered experiment"
    failure mode this router exists to eliminate (v2: v1 verdicts came
    from the RTT-dominated 8-iter probe). Rows are bucketed to the next
    power of two (and clamped to the probe range) so nearby sizes share
    one verdict."""
    n_probe = int(min(max(n, 512), 65536))
    n_bucket = 1 << (n_probe - 1).bit_length()
    kind = jax.devices()[0].device_kind
    import synapseml_tpu as _pkg
    pkg_v = getattr(_pkg, "__version__", "0")
    return (f"v2|jax{jax.__version__}|pkg{pkg_v}|{kind}|"
            f"{n_bucket}|{f}|{n_bins}")


def _load_disk_routes() -> dict:
    import json
    try:
        with open(_route_cache_path()) as fh:
            return json.load(fh)
    except Exception:  # noqa: BLE001 - cache is best-effort
        return {}


# negative-lookup memo for cached_hist_route: shapes with NO measured
# verdict would otherwise re-open + re-parse the disk cache on every
# histogram trace. Cleared whenever a probe lands a new verdict —
# and TTL'd ({base: monotonic expiry}): a verdict landed on the shared
# cache volume by ANOTHER worker used to stay invisible here until a
# restart (the negative memo never re-checked disk); now an expired
# entry re-reads the file, so cross-process verdicts surface within
# SYNAPSEML_ROUTE_NEG_TTL_S (runtime/proberoute.neg_ttl_s, default 60s).
_ROUTE_NEG: dict = {}


def cached_hist_route(n: int, f: int, n_bins: int) -> Optional[str]:
    """Cache-only lookup of a measured routing verdict for this shape —
    NO probe is run (safe to call at trace time, where running device
    code would be impossible). Prefers the full-integrity verdict;
    falls back to any reduced-budget tier for the same shape. Returns
    "pallas" / "xla" / None (nothing measured yet)."""
    import time

    try:
        base = _route_key_base(n, f, n_bins)
    except Exception:  # noqa: BLE001 - no devices yet etc.
        return None
    now = time.monotonic()
    expiry = _ROUTE_NEG.get(base)
    if expiry is not None:
        if now < expiry:
            return None
        _ROUTE_NEG.pop(base, None)  # expired: re-check disk below
    got = _HIST_ROUTE_CACHE.get(base)
    if got is None:
        disk = _load_disk_routes()
        _HIST_ROUTE_CACHE.update(
            {k: v for k, v in disk.items() if k not in _HIST_ROUTE_CACHE})
        got = _HIST_ROUTE_CACHE.get(base)
    if got is None:
        reduced = base + "|b"
        for k, v in _HIST_ROUTE_CACHE.items():
            if k.startswith(reduced):
                got = v
                break
    if got is None:
        from synapseml_tpu.runtime.proberoute import neg_ttl_s

        _ROUTE_NEG[base] = now + neg_ttl_s()
    return got


# Below this many estimated fit row-visits (n * boosting steps * leaves)
# the probe costs more than the fit it routes: skip it and take the XLA
# formulation (zero-config, like lib_lightgbm's default backend). A fit at
# the threshold runs ~7 s on a v5e chip; the probe costs ~10-17 s once.
_PROBE_MIN_FIT_ROW_VISITS = 30_000_000
# Full-integrity per-timed-call probe budget (row-visits): seconds of
# sustained compute, so the verdict reflects HBM behavior, not tunnel RTT.
_PROBE_FULL_BUDGET = 25_000_000
# Never probe with less than this per call — shorter probes measure the
# dispatch round trip (round-4's bench caught an RTT-routed verdict).
_PROBE_FLOOR_BUDGET = 6_000_000


def resolve_hist_backend(n: int, f: int, n_bins: int,
                         iters: Optional[int] = None,
                         fit_row_visits: Optional[int] = None) -> str:
    """Measured histogram routing, safe under a multi-process runtime.

    ``fit_row_visits`` — the caller's estimate of total fit work
    (n * boosting steps * num_leaves). Fits too small to amortize the
    probe skip it entirely (XLA, deterministic on every rank); mid-size
    fits probe with a budget capped at ~1/8 of the fit's work (floored
    so the probe still measures compute, not RTT); big fits keep the
    full-integrity budget.

    The probe is timing-based, so two ranks probing independently could
    resolve DIFFERENT backends and compile non-identical SPMD programs
    for one collective fit (undefined under XLA multi-host). Rank 0 runs
    the probe (:func:`_resolve_hist_backend_local`) and broadcasts its
    verdict; single-process runs probe directly.
    """
    if (fit_row_visits is not None
            and fit_row_visits < _PROBE_MIN_FIT_ROW_VISITS):
        return "xla"
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        verdict = 0
        if jax.process_index() == 0:
            verdict = 1 if _resolve_hist_backend_local(
                n, f, n_bins, iters, fit_row_visits) == "pallas" else 0
        out = multihost_utils.broadcast_one_to_all(
            np.asarray([verdict], np.int32))
        return "pallas" if int(np.asarray(out)[0]) else "xla"
    return _resolve_hist_backend_local(n, f, n_bins, iters, fit_row_visits)


def _resolve_hist_backend_local(n: int, f: int, n_bins: int,
                                iters: Optional[int] = None,
                                fit_row_visits: Optional[int] = None) -> str:
    """Measure which histogram formulation wins *in context* for this
    shape and return "pallas" or "xla".

    The round-3 shootout showed the isolated op and the scanned boosting
    loop can DISAGREE (XLA one-hot wins isolated, the VMEM kernel won
    +88% end-to-end), so the probe times ``iters`` chained
    histogram+split-search steps — the production context where the
    formulation competes for HBM bandwidth with the mask/gradient traffic
    around it. Results are cached per (device kind, n-bucket, f, n_bins)
    in-process and persisted to ``~/.cache/synapseml_tpu`` so one probe
    cost (~10 s, paid at the first fit ever) covers all later runs.

    ``iters`` must put SECONDS of compute inside each timed call: on the
    tunneled chip one dispatch round trip costs 100-200 ms with ~2x
    jitter, so a short probe measures the tunnel, not the formulations
    (round-4's bench caught exactly that: an 8-iter probe routed to the
    formulation that loses the full training loop by 2x).
    """
    import json
    import os
    import time

    if jax.default_backend() != "tpu":
        return "xla"
    from synapseml_tpu.gbdt import pallas_kernels
    if not (pallas_kernels.available() and _pallas_shape_ok(n, f, n_bins)):
        return "xla"
    n_probe = int(min(max(n, 512), 65536))
    n_bucket = 1 << (n_probe - 1).bit_length()
    reduced_tier = ""
    if iters is None:
        # seconds of compute per timed call, so the winner comes from
        # sustained HBM behavior, not dispatch jitter; mid-size fits cap
        # the budget at ~1/8 of their own estimated work
        budget = _PROBE_FULL_BUDGET
        if fit_row_visits is not None:
            budget = min(_PROBE_FULL_BUDGET,
                         max(_PROBE_FLOOR_BUDGET, fit_row_visits // 8))
        iters = max(16, budget // n_bucket)
        if budget < _PROBE_FULL_BUDGET:
            # a reduced-budget verdict is lower-fidelity: key it apart
            # (power-of-2 bucketed) so a later big fit still gets its
            # full-integrity probe instead of inheriting this one
            reduced_tier = f"|b{1 << (int(budget) - 1).bit_length()}"
    key = _route_key_base(n, f, n_bins) + reduced_tier
    got = _HIST_ROUTE_CACHE.get(key)
    if got is not None:
        return got
    path = _route_cache_path()
    disk = _load_disk_routes()
    if key in disk:
        _HIST_ROUTE_CACHE[key] = disk[key]
        return disk[key]

    import numpy as np
    rng = np.random.default_rng(0)
    # match production dtype (binning.transform: uint8 only up to 256
    # bins) — probing uint8 for a uint16 workload would time half the
    # HBM traffic and wrap the bin values
    bin_dtype = jnp.uint8 if n_bins <= 256 else jnp.uint16
    binned = jnp.asarray(rng.integers(0, n_bins, (n_bucket, f)), bin_dtype)
    grad = jnp.asarray(rng.normal(size=n_bucket), jnp.float32)
    hess = jnp.asarray(rng.random(n_bucket), jnp.float32)

    def timed(backend: str) -> float:
        @jax.jit
        def loop(b, g):
            def body(i, acc):
                # data dependency threads the accumulated scalar through
                # the mask, chaining iterations like the boosting scan
                mask = (g + acc * 0) > -1e9
                h = histogram(b, g, hess, mask, n_bins, backend=backend)
                cum = jnp.cumsum(h, axis=1)  # the split-search pass
                return acc + cum[..., 0].max().astype(jnp.float32)
            return lax.fori_loop(0, iters, body, jnp.float32(0.0))

        float(loop(binned, grad))  # compile + warm (value fetch forces)
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            float(loop(binned, grad))
            best = min(best, time.perf_counter() - t0)
        return best

    try:
        winner = "pallas" if timed("pallas") <= timed("xla") else "xla"
    except Exception:  # noqa: BLE001 - probe failure must not kill a fit
        # the failure may BE the pallas leg: fall back to the formulation
        # that cannot crash, and do not persist a verdict we never timed
        _HIST_ROUTE_CACHE[key] = "xla"
        _ROUTE_NEG.clear()  # new verdict: retire stale negative lookups
        return "xla"
    _HIST_ROUTE_CACHE[key] = winner
    _ROUTE_NEG.clear()  # new verdict: retire stale negative lookups
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        disk[key] = winner
        with open(path, "w") as fh:
            json.dump(disk, fh, indent=0)
    except Exception:  # noqa: BLE001
        pass
    return winner


def _l1_threshold(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_objective(g, h, p: GrowerParams):
    gl1 = _l1_threshold(g, p.lambda_l1)
    return gl1 * gl1 / (h + p.lambda_l2 + 1e-12)


def _split_gains(hist, totals, p: GrowerParams, depth_ok,
                 constrained: bool = True):
    """Per-(feature, bin) split gains [F, B]. ``constrained=False``
    skips the min-data/min-hessian validity mask (used only as a
    voting fallback — never for an actual split decision)."""
    cum = jnp.cumsum(hist, axis=1)                     # [F, B, 3]
    gl, hl, cl = cum[..., 0], cum[..., 1], cum[..., 2]
    gt, ht, ct = totals[0], totals[1], totals[2]
    gr, hr, cr = gt - gl, ht - hl, ct - cl
    gain = (_leaf_objective(gl, hl, p) + _leaf_objective(gr, hr, p)
            - _leaf_objective(gt, ht, p))
    if not constrained:
        return jnp.where(depth_ok & (cr > 0), gain, -jnp.inf)
    valid = ((cl >= p.min_data_in_leaf) & (cr >= p.min_data_in_leaf)
             & (hl >= p.min_sum_hessian_in_leaf)
             & (hr >= p.min_sum_hessian_in_leaf))
    return jnp.where(valid & depth_ok, gain, -jnp.inf)


def best_split(hist, totals, p: GrowerParams, depth_ok):
    """Best (gain, feature, bin) for one leaf.

    hist: [F, B, 3]; totals: [3] (G, H, C). Split semantics: bin <= b left.
    """
    gain = _split_gains(hist, totals, p, depth_ok)
    flat = jnp.argmax(gain)
    f_best = (flat // gain.shape[1]).astype(jnp.int32)
    b_best = (flat % gain.shape[1]).astype(jnp.int32)
    return gain.reshape(-1)[flat], f_best, b_best


def best_split_voting(hist_local, totals, p: GrowerParams, depth_ok,
                      axis_name: str):
    """PV-tree elected best split (ref: LightGBM voting_parallel /
    Meng et al. parallel voting tree). Each shard ranks features by its
    LOCAL gains, a psum'd vote elects the global top-k, and only the
    elected features' histograms are merged (the [top_k, B, 3] psum
    replaces the full [F, B, 3] one). ``totals`` must be GLOBAL; returns
    (gain, global feature id, bin), identical on every shard."""
    f = hist_local.shape[0]
    k = int(min(p.voting_top_k, f))
    # local per-feature best gains vote from LOCAL statistics. A shard
    # whose every (feature, bin) fails the LOCAL min-data/min-hessian
    # constraints (deep leaves on wide meshes: global counts pass,
    # per-shard counts don't) would otherwise vote the arbitrary first
    # k indices — fall back to UNconstrained local gains for its
    # ranking (the actual split still applies the GLOBAL constraints).
    local_tot = hist_local[0].sum(axis=0)
    masked_f = _split_gains(hist_local, local_tot, p,
                            depth_ok).max(axis=1)            # [F]
    raw_f = _split_gains(hist_local, local_tot, p, depth_ok,
                         constrained=False).max(axis=1)
    local_gain_f = jnp.where(jnp.isfinite(masked_f.max()),
                             masked_f, raw_f)
    _, top_local = lax.top_k(local_gain_f, k)
    votes = lax.psum(
        jax.nn.one_hot(top_local, f, dtype=jnp.float32).sum(0), axis_name)
    # deterministic tie-break by feature index (same on every shard);
    # elected ids are SORTED so best_split's argmax resolves gain ties
    # in global feature order — with k == F this makes the election
    # bit-identical to data_parallel
    order_score = votes * f + jnp.arange(f, 0, -1, dtype=jnp.float32) / f
    _, elected = lax.top_k(order_score, k)                   # [k]
    elected = jnp.sort(elected)
    hist_elected = lax.psum(hist_local[elected], axis_name)  # [k, B, 3]
    gain, f_local, b_best = best_split(hist_elected, totals, p, depth_ok)
    return gain, elected[f_local].astype(jnp.int32), b_best


def build_tree(
    binned: jnp.ndarray,        # [N, F] uint8/int
    grad: jnp.ndarray,          # [N] f32
    hess: jnp.ndarray,          # [N] f32
    row_mask: jnp.ndarray,      # [N] bool (bagging / padding mask)
    threshold_values: jnp.ndarray,  # [F, B] f32 raw split values per bin
    p: GrowerParams,
    axis_name: Optional[str] = None,
) -> Tuple[Tree, jnp.ndarray]:
    """Grow one tree; returns (tree, per-row leaf slot)."""
    n, f = binned.shape
    L = p.num_leaves
    M = 2 * L - 1
    B = p.max_bin

    voting = p.voting_top_k > 0 and axis_name is not None
    # voting mode keeps per-shard histograms LOCAL (the parent-child
    # subtraction stays shard-local too) and merges only elected
    # features per split; totals are always global
    hist_axis = None if voting else axis_name
    hist0 = histogram(binned, grad, hess, row_mask, B, hist_axis,
                      backend=p.hist_backend)
    tot0 = hist0[0].sum(axis=0)                       # (G, H, C) of the root
    if voting:
        tot0 = lax.psum(tot0, axis_name)

    depth_ok0 = True if p.max_depth <= 0 else (0 < p.max_depth)
    if voting:
        g0, f0, b0 = best_split_voting(hist0, tot0, p, depth_ok0,
                                       axis_name)
    else:
        g0, f0, b0 = best_split(hist0, tot0, p, depth_ok0)

    state = dict(
        row_slot=jnp.zeros(n, jnp.int32),
        slot_node=jnp.full(L, -1, jnp.int32).at[0].set(0),
        slot_depth=jnp.zeros(L, jnp.int32),
        hist=jnp.zeros((L, f, B, 3), jnp.float32).at[0].set(hist0),
        totals=jnp.zeros((L, 3), jnp.float32).at[0].set(tot0),
        best_gain=jnp.full(L, -jnp.inf, jnp.float32).at[0].set(g0),
        best_feat=jnp.zeros(L, jnp.int32).at[0].set(f0),
        best_bin=jnp.zeros(L, jnp.int32).at[0].set(b0),
        node_feature=jnp.full(M, -1, jnp.int32),
        node_bin=jnp.zeros(M, jnp.int32),
        node_left=jnp.zeros(M, jnp.int32),
        node_right=jnp.zeros(M, jnp.int32),
        node_cover=jnp.zeros(M, jnp.float32).at[0].set(tot0[2]),
        node_gain=jnp.zeros(M, jnp.float32),
    )

    # Every per-slot state update below is a one-hot select, not a scatter:
    # single-element scatters inside the loop each cost fixed device latency
    # (~1ms of pure overhead per split on a remote chip), while a masked
    # vector select is one fused VPU pass.
    arL = jnp.arange(L)
    arM = jnp.arange(M)
    binned_T = binned.T  # [F, N]: traced-feature column reads as dynamic slices

    def _putL(arr, slot, val):
        """arr[slot] = val for [L]-indexed state; slot == L drops the write."""
        sel = arL == slot
        return jnp.where(sel, val, arr)

    def _putM(arr, idx, val):
        sel = arM == idx
        return jnp.where(sel, val, arr)

    def split_step(s, st):
        leaf = jnp.argmax(st["best_gain"]).astype(jnp.int32)
        gain = st["best_gain"][leaf]
        do = gain > p.min_gain_to_split

        feat = st["best_feat"][leaf]
        thr_bin = st["best_bin"][leaf]
        parent = st["slot_node"][leaf]
        left_id = 2 * s - 1
        right_id = 2 * s

        # record the internal node (index M = out-of-range -> dropped)
        widx = jnp.where(do, parent, M)
        st["node_feature"] = _putM(st["node_feature"], widx, feat)
        st["node_bin"] = _putM(st["node_bin"], widx, thr_bin)
        st["node_left"] = _putM(st["node_left"], widx, left_id)
        st["node_right"] = _putM(st["node_right"], widx, right_id)
        st["node_gain"] = _putM(st["node_gain"], widx, gain)

        # partition rows of the split leaf
        col = lax.dynamic_index_in_dim(
            binned_T, feat, axis=0, keepdims=False).astype(jnp.int32)
        in_leaf = st["row_slot"] == leaf
        go_right = in_leaf & (col > thr_bin)
        st["row_slot"] = jnp.where(do & go_right, s, st["row_slot"])

        # child histograms: fresh for right, subtraction for left
        mask_right = (st["row_slot"] == s) & row_mask
        hist_r = histogram(binned, grad, hess,
                           jnp.where(do, mask_right, jnp.zeros_like(mask_right)),
                           B, hist_axis, backend=p.hist_backend)
        tot_r = hist_r[0].sum(axis=0)
        if voting:
            tot_r = lax.psum(tot_r, axis_name)
        hist_l = st["hist"][leaf] - hist_r
        tot_l = st["totals"][leaf] - tot_r

        lslot = jnp.where(do, leaf, L)   # dropped when no split
        rslot = jnp.where(do, s, L)
        lsel = (arL == lslot)[:, None, None, None]
        rsel = (arL == rslot)[:, None, None, None]
        st["hist"] = jnp.where(lsel, hist_l[None], st["hist"])
        st["hist"] = jnp.where(rsel, hist_r[None], st["hist"])
        st["totals"] = jnp.where((arL == lslot)[:, None], tot_l[None],
                                 st["totals"])
        st["totals"] = jnp.where((arL == rslot)[:, None], tot_r[None],
                                 st["totals"])

        new_depth = st["slot_depth"][leaf] + 1
        st["slot_depth"] = _putL(st["slot_depth"], lslot, new_depth)
        st["slot_depth"] = _putL(st["slot_depth"], rslot, new_depth)
        st["slot_node"] = _putL(st["slot_node"], lslot, left_id)
        st["slot_node"] = _putL(st["slot_node"], rslot, right_id)
        lnode = jnp.where(do, left_id, M)
        rnode = jnp.where(do, right_id, M)
        st["node_cover"] = _putM(st["node_cover"], lnode, tot_l[2])
        st["node_cover"] = _putM(st["node_cover"], rnode, tot_r[2])

        depth_ok = True if p.max_depth <= 0 else (new_depth < p.max_depth)
        if voting:
            gl, fl, bl = best_split_voting(hist_l, tot_l, p, depth_ok,
                                           axis_name)
            gr, fr, br = best_split_voting(hist_r, tot_r, p, depth_ok,
                                           axis_name)
        else:
            gl, fl, bl = best_split(hist_l, tot_l, p, depth_ok)
            gr, fr, br = best_split(hist_r, tot_r, p, depth_ok)
        neg = jnp.float32(-jnp.inf)
        st["best_gain"] = _putL(st["best_gain"], lslot, jnp.where(do, gl, neg))
        st["best_gain"] = _putL(st["best_gain"], rslot, jnp.where(do, gr, neg))
        st["best_feat"] = _putL(st["best_feat"], lslot, fl)
        st["best_feat"] = _putL(st["best_feat"], rslot, fr)
        st["best_bin"] = _putL(st["best_bin"], lslot, bl)
        st["best_bin"] = _putL(st["best_bin"], rslot, br)
        return st

    state = lax.fori_loop(1, L, split_step, state)

    # leaf values: -ThresholdL1(G) / (H + l2)
    g = state["totals"][:, 0]
    h = state["totals"][:, 1]
    slot_value = -_l1_threshold(g, p.lambda_l1) / (h + p.lambda_l2 + 1e-12)
    slot_value = jnp.where(state["slot_node"] >= 0, slot_value, 0.0)

    # place leaf values into the node table (one-hot contraction, no scatter)
    sel = ((state["slot_node"][:, None] == arM)
           & (state["slot_node"] >= 0)[:, None])
    leaf_value = jnp.sum(sel * slot_value[:, None], axis=0)

    # raw-value thresholds for prediction on unbinned features
    thr = threshold_values[state["node_feature"].clip(0), state["node_bin"]]
    thr = jnp.where(state["node_feature"] >= 0, thr.astype(jnp.float32), 0.0)

    tree = Tree(
        split_feature=state["node_feature"],
        threshold=thr,
        threshold_bin=state["node_bin"],
        left_child=state["node_left"],
        right_child=state["node_right"],
        leaf_value=leaf_value,
        cover=state["node_cover"],
        gain=state["node_gain"],
    )
    return tree, state["row_slot"], slot_value, state["slot_node"]


def _single_tree_kernel(feat, thr, left, right, value, x):
    """One-tree call into the fused traversal kernel (T=1 stack).
    ``x``/``thr`` must already be float; used by both predict_tree
    variants when the cached route says the kernel wins here."""
    from synapseml_tpu.gbdt import pallas_kernels

    return pallas_kernels.predict_forest_tpu(
        x, feat[None, :], thr[None, :], left[None, :], right[None, :],
        value[None, :], k=1)[:, 0]


def predict_tree(tree_arrays, x, route: bool = True):
    """Vectorized traversal on raw features. x: [N, F] float.

    tree_arrays: tuple of [M] arrays (feature, threshold, left, right, value).
    NaN comparisons are False -> missing goes right (matches training, where
    the missing bin sorts after every splittable bin).

    ``route=True`` consults the predict router's CACHED verdict (no
    probe — this traces inside the boosting scan) and takes the fused
    Pallas traversal when a measured verdict says it wins at this
    shape; callers that already routed at a higher level (the stacked
    ensemble predict) pass route=False.
    """
    feat, thr, left, right, value = tree_arrays
    n = x.shape[0]
    if route and n:
        from synapseml_tpu.gbdt import predict_route

        if predict_route.cached_route(
                n, 1, feat.shape[0], x.shape[1], 1) == "pallas":
            return _single_tree_kernel(feat, thr, left, right, value, x)
    node = jnp.zeros(n, jnp.int32)
    max_depth = feat.shape[0] // 2 + 1

    def step(_, node):
        is_leaf = feat[node] < 0
        xv = x[jnp.arange(n), feat[node].clip(0)]
        nxt = jnp.where(xv <= thr[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    node = lax.fori_loop(0, max_depth, step, node)
    return value[node]


def predict_tree_binned(tree_arrays, binned, route: bool = True):
    """Traversal on pre-binned rows (training-time refit / fast path).

    Rides the same fused kernel as :func:`predict_tree` when routed:
    bin ids and bin thresholds are exact in float32 (uint8/uint16 bins
    < 2^24), so the integer ``<=`` comparison is preserved."""
    feat, thr_bin, left, right, value = tree_arrays
    n = binned.shape[0]
    if route and n:
        from synapseml_tpu.gbdt import predict_route

        if predict_route.cached_route(
                n, 1, feat.shape[0], binned.shape[1], 1) == "pallas":
            return _single_tree_kernel(
                feat, thr_bin.astype(jnp.float32), left, right, value,
                binned.astype(jnp.float32))
    node = jnp.zeros(n, jnp.int32)
    max_depth = feat.shape[0] // 2 + 1

    def step(_, node):
        is_leaf = feat[node] < 0
        xv = jnp.take_along_axis(
            binned, feat[node].clip(0)[:, None], axis=1)[:, 0].astype(jnp.int32)
        nxt = jnp.where(xv <= thr_bin[node], left[node], right[node])
        return jnp.where(is_leaf, node, nxt)

    node = lax.fori_loop(0, max_depth, step, node)
    return value[node]
