"""LightGBM-surface estimators over the TPU GBDT engine.

API parity with the reference's learners (ref:
lightgbm/.../LightGBMClassifier.scala:26-209, LightGBMRegressor.scala:38-154,
LightGBMRanker.scala:26-177, params at lightgbm/.../params/LightGBMParams.scala)
— same param names (snake_case), same output columns (rawPrediction /
probability / prediction), same model-methods surface (feature importances,
leaf prediction, SHAP) — but fitting runs the jax histogram engine instead of
JNI + socket rendezvous.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import List, Optional

import numpy as np

from synapseml_tpu.core.param import ComplexParam, Param
from synapseml_tpu.core.pipeline import Estimator, Model
from synapseml_tpu.data.table import Table
from synapseml_tpu.gbdt.boosting import Booster, BoostParams, train


class LightGBMDelegate:
    """User callback hooks around training
    (ref: lightgbm/.../LightGBMDelegate.scala:12-62).

    Subclass and override; attach via the estimator's ``delegate`` param.
    ``after_train_iteration`` fires at device-chunk boundaries (the TPU
    boosting loop runs whole ``lax.scan`` chunks on device — per-tree
    host callbacks would serialize the device pipeline), with the number
    of iterations completed so far. ``get_learning_rate`` is consulted
    once per iteration BEFORE the run to assemble a shrinkage schedule
    (it sees batch index, iteration and the previous rate — the same
    signature contract as the reference's dynamic-LR delegate).
    """

    def before_train_batch(self, batch_index: int, table: Table,
                           prev_model) -> None:
        """(ref: LightGBMDelegate.scala beforeTrainBatch:13)."""

    def after_train_batch(self, batch_index: int, table: Table,
                          model) -> None:
        """(ref: LightGBMDelegate.scala afterTrainBatch:18)."""

    def after_train_iteration(self, batch_index: int,
                              iterations_done: int) -> None:
        """(ref: LightGBMDelegate.scala afterTrainIteration:49; chunk
        granularity here)."""

    def get_learning_rate(self, batch_index: int, iteration: int,
                          previous_rate: float) -> float:
        """(ref: LightGBMDelegate.scala getLearningRate:57)."""
        return previous_rate


class _LightGBMParams:
    """Shared param surface (ref: lightgbm/.../params/LightGBMParams.scala)."""
    features_col = Param("features column (2-D) or None to use feature_cols",
                         default="features")
    feature_cols = Param("explicit list of scalar feature columns", default=None)
    label_col = Param("label column", default="label")
    weight_col = Param("sample weight column", default=None)
    validation_indicator_col = Param(
        "bool column marking validation rows", default=None)
    prediction_col = Param("prediction column", default="prediction")
    boosting_type = Param("gbdt|rf|dart|goss", default="gbdt")
    num_iterations = Param("boosting rounds", default=100)
    learning_rate = Param("shrinkage", default=0.1)
    num_leaves = Param("max leaves per tree", default=31)
    max_depth = Param("max depth, 0=unlimited", default=-1)
    lambda_l1 = Param("L1 regularization", default=0.0)
    lambda_l2 = Param("L2 regularization", default=0.0)
    min_data_in_leaf = Param("min rows per leaf", default=20)
    min_sum_hessian_in_leaf = Param("min hessian per leaf", default=1e-3)
    min_gain_to_split = Param("min split gain", default=0.0)
    max_bin = Param("histogram bins", default=255)
    bin_sample_count = Param(
        "rows sampled to construct bin boundaries (reference "
        "binSampleCount, TrainParams.scala:17); also caps the cross-host "
        "gather of the row-sharded multi-host fit", default=200_000)
    feature_fraction = Param("feature subsample per tree", default=1.0)
    bagging_fraction = Param("row subsample", default=1.0)
    bagging_freq = Param("bagging frequency", default=0)
    bagging_seed = Param(
        "independent seed for the bagging stream (reference baggingSeed); "
        "None derives it from seed", default=None)
    pos_bagging_fraction = Param(
        "per-iteration subsample of positive rows (binary only)",
        default=1.0)
    neg_bagging_fraction = Param(
        "per-iteration subsample of negative rows (binary only)",
        default=1.0)
    top_rate = Param("GOSS top rate", default=0.2)
    other_rate = Param("GOSS other rate", default=0.1)
    drop_rate = Param("DART per-tree drop probability", default=0.1)
    max_drop = Param("DART max trees dropped per iteration (<=0 = no "
                     "limit)", default=50)
    skip_drop = Param("DART probability of skipping dropout entirely",
                      default=0.5)
    uniform_drop = Param(
        "DART: True = uniform Bernoulli tree selection; False (LightGBM "
        "default) drops proportionally to current tree weight",
        default=False)
    xgboost_dart_mode = Param(
        "DART: normalize dropped rounds with lr/(k+lr) (xgboost's rule) "
        "instead of lr/(k+1)", default=False)
    boost_from_average = Param(
        "initialize scores from the label average (LightGBM "
        "boost_from_average)", default=True)
    early_stopping_round = Param("early stopping patience", default=0)
    improvement_tolerance = Param(
        "metric delta below which an iteration does not count as "
        "improved (reference improvementTolerance)", default=0.0)
    categorical_slot_indexes = Param("categorical feature slots", default=None)
    parallelism = Param(
        "distributed tree learner (ref LightGBMParams.scala:16-18): "
        "data_parallel (full-histogram dp psum) or voting_parallel "
        "(PV-tree top_k feature election; merges only elected "
        "features' histograms per split)",
        default="data_parallel",
        type_check=lambda v: v in ("data_parallel", "voting_parallel"))
    top_k = Param("voting_parallel features elected per split "
                  "(LightGBM top_k)", default=20)
    metric = Param("eval metric override", default=None)
    seed = Param("random seed", default=0)
    verbosity = Param("verbosity", default=-1)
    hist_backend = Param(
        "histogram formulation: auto (measured probe) / pallas / xla",
        default="auto",
        type_check=lambda v: v in ("auto", "pallas", "xla"))

    def _features(self, table: Table) -> np.ndarray:
        cols = self.feature_cols
        if cols:
            return np.column_stack(
                [np.asarray(table[c], np.float64) for c in cols])
        feats = table[self.features_col]
        if feats.ndim == 1 and feats.dtype == object:
            feats = np.stack([np.asarray(v, np.float64) for v in feats])
        return np.asarray(feats, np.float64)

    def _boost_params(self, objective: str, num_class: int = 1) -> BoostParams:
        return BoostParams(
            objective=objective,
            boosting_type=self.boosting_type,
            num_iterations=int(self.num_iterations),
            learning_rate=float(self.learning_rate),
            num_leaves=int(self.num_leaves),
            max_depth=max(0, int(self.max_depth)),
            lambda_l1=float(self.lambda_l1),
            lambda_l2=float(self.lambda_l2),
            min_data_in_leaf=int(self.min_data_in_leaf),
            min_sum_hessian_in_leaf=float(self.min_sum_hessian_in_leaf),
            min_gain_to_split=float(self.min_gain_to_split),
            max_bin=int(self.max_bin),
            bin_sample_count=int(self.bin_sample_count),
            feature_fraction=float(self.feature_fraction),
            bagging_fraction=float(self.bagging_fraction),
            bagging_freq=int(self.bagging_freq),
            bagging_seed=(None if self.get("bagging_seed") is None
                          else int(self.bagging_seed)),
            pos_bagging_fraction=float(self.pos_bagging_fraction),
            neg_bagging_fraction=float(self.neg_bagging_fraction),
            top_rate=float(self.top_rate),
            other_rate=float(self.other_rate),
            drop_rate=float(self.drop_rate),
            max_drop=int(self.max_drop),
            skip_drop=float(self.skip_drop),
            uniform_drop=bool(self.uniform_drop),
            xgboost_dart_mode=bool(self.xgboost_dart_mode),
            boost_from_average=bool(self.boost_from_average),
            early_stopping_round=int(self.early_stopping_round),
            improvement_tolerance=float(self.improvement_tolerance),
            num_class=num_class,
            metric=self.get("metric"),
            seed=int(self.seed),
            categorical_features=tuple(self.categorical_slot_indexes or ()),
            hist_backend=self.hist_backend,
            tree_learner=str(self.parallelism),
            voting_top_k=int(self.top_k),
        )


    def _make_model(self, model_cls, booster):
        model = model_cls(booster=booster)
        declared = model.params()
        model._paramMap.update(
            {k: v for k, v in self._paramMap.items() if k in declared})
        return model

    def _split_validation(self, table: Table):
        vcol = self.validation_indicator_col
        if vcol and vcol in table:
            mask = np.asarray(table[vcol], bool)
            return table.filter(~mask), table.filter(mask)
        return table, None


class _LightGBMModelBase(Model, _LightGBMParams):
    """Fitted model wrapper (ref model methods:
    lightgbm/.../LightGBMModelMethods.scala:12-116)."""

    def __init__(self, booster: Optional[Booster] = None, **kw):
        super().__init__(**kw)
        self.booster = booster

    def get_feature_importances(self, importance_type: str = "split") -> List[float]:
        imp = (self.booster.feature_importance_gain
               if importance_type == "gain"
               else self.booster.feature_importance_split)
        return list(np.asarray(imp, float))

    def predict_leaf(self, table: Table) -> np.ndarray:
        return self.booster.predict_leaf(self._features(table))

    def shap_values(self, table: Table) -> np.ndarray:
        from synapseml_tpu.gbdt.shap import tree_shap
        return tree_shap(self.booster, self._features(table))

    def get_feature_shaps(self, features) -> List[float]:
        """Per-feature SHAP values (+ expected value last) for ONE row,
        flattened to K*(F+1) floats for multiclass — the reference's
        flat-array contract
        (ref: LightGBMModelMethods.scala getFeatureShaps:27)."""
        from synapseml_tpu.gbdt.shap import tree_shap
        row = np.asarray(features, np.float64).reshape(1, -1)
        return list(np.asarray(tree_shap(self.booster, row)[0],
                               float).ravel())

    # booster introspection getters
    # (ref: LightGBMModelMethods.scala:55-96)
    def get_booster_best_iteration(self) -> int:
        return int(self.booster.best_iteration)

    def get_booster_num_total_iterations(self) -> int:
        return int(self.booster.num_iterations)

    def get_booster_num_total_model(self) -> int:
        return int(self.booster.num_trees)

    def get_booster_num_features(self) -> int:
        return int(self.booster.num_features)

    def get_booster_num_classes(self) -> int:
        return int(self.booster.num_class)

    def save_native_model(self, path: str):
        """Write the booster in LightGBM's native text format
        (ref: LightGBMBooster.scala:454 saveNativeModel)."""
        with open(path, "w") as f:
            f.write(self.booster.save_string())

    @classmethod
    def load_native_model(cls, path: str, **kw):
        """Load a native LightGBM text model file into a fitted model
        (ref: LightGBMClassifier.scala loadNativeModelFromFile)."""
        with open(path) as f:
            return cls(booster=Booster.load_string(f.read()), **kw)

    # serde: booster goes to a side file (native LightGBM text format)
    def _save_extra(self, path: str):
        with open(os.path.join(path, "booster.txt"), "w") as f:
            f.write(self.booster.save_string())

    def _load_extra(self, path: str):
        p = os.path.join(path, "booster.txt")
        if not os.path.exists(p):  # round-1 artifacts
            p = os.path.join(path, "booster.json")
        with open(p) as f:
            self.booster = Booster.load_string(f.read())


class _LightGBMEstimatorBase(Estimator, _LightGBMParams):
    """Batch-training driver shared by the three learners
    (ref: LightGBMBase.scala train:46-61 — randomSplit into numBatches,
    thread the booster via setModelString, before/afterTrainBatch hooks).

    ``num_batches``/``delegate`` live here, NOT on the shared param
    mixin: they are training-only knobs, and a fitted model must never
    pickle the user's callback object into its saved artifact.
    """

    num_batches = Param(
        "split training into N sequential batches, threading the booster "
        "from each into the next (ref: LightGBMBase.scala train:46-61)",
        default=0)
    delegate = ComplexParam(
        "optional LightGBMDelegate with batch/iteration/LR hooks")

    def _delegate_train_kwargs(self, batch_index: int) -> dict:
        """learning-rate schedule + iteration hook from the delegate."""
        d = self.get("delegate")
        out: dict = {}
        if d is None:
            return out
        if (type(d).get_learning_rate
                is not LightGBMDelegate.get_learning_rate):
            lrs, prev = [], float(self.learning_rate)
            for it in range(int(self.num_iterations)):
                prev = float(d.get_learning_rate(batch_index, it, prev))
                lrs.append(prev)
            out["learning_rates"] = np.asarray(lrs, np.float32)
        if (type(d).after_train_iteration
                is not LightGBMDelegate.after_train_iteration):
            out["iteration_hook"] = (
                lambda iters: d.after_train_iteration(batch_index, iters))
        return out

    def _batch_context(self, table: Table) -> dict:
        """Whole-dataset state every batch must share (e.g. the label
        mapping — a batch may not contain every class)."""
        return {}

    def _fit_single(self, table: Table, init_booster: Optional[Booster],
                    batch_index: int, ctx: dict):
        raise NotImplementedError

    def _fit(self, table: Table):
        nb = int(self.num_batches or 0)
        ctx = self._batch_context(table)
        if nb <= 1:
            return self._fit_single(table, None, 0, ctx)
        d = self.get("delegate")
        parts = table.random_split([1.0 / nb] * nb, seed=int(self.seed))
        model = None
        for bi, part in enumerate(parts):
            if part.num_rows == 0:
                continue  # tiny-table splits can leave an empty batch
            if d is not None:
                d.before_train_batch(bi, part, model)
            model = self._fit_single(
                part, model.booster if model is not None else None, bi, ctx)
            if d is not None:
                d.after_train_batch(bi, part, model)
        if model is None:
            raise ValueError("no non-empty training batch")
        return model


class LightGBMClassifier(_LightGBMEstimatorBase):
    """ref: lightgbm/.../LightGBMClassifier.scala:26-92."""

    objective = Param("binary|multiclass", default="binary")
    probability_col = Param("probability column", default="probability")
    raw_prediction_col = Param("raw margin column", default="rawPrediction")

    def _batch_context(self, table: Table) -> dict:
        # the class mapping must come from ALL batches' labels
        return {"classes": np.unique(
            np.asarray(table[self.label_col], np.float64))}

    def _fit_single(self, table: Table, init_booster, batch_index,
                    ctx) -> "LightGBMClassificationModel":
        train_t, valid_t = self._split_validation(table)
        x = self._features(train_t)
        y_raw = np.asarray(train_t[self.label_col], np.float64)
        # remap arbitrary class labels to dense 0..k-1 (the reference gets
        # this via label reindexing in TrainClassifier / native LightGBM
        # validation); predictions map back through label_values
        classes = ctx["classes"]
        y = np.searchsorted(classes, y_raw).astype(np.float64)
        num_class = len(classes)
        objective = self.objective
        if num_class > 2 and objective == "binary":
            objective = "multiclass"
        weight = (np.asarray(train_t[self.weight_col], np.float64)
                  if self.weight_col else None)
        valid = []
        if valid_t is not None and valid_t.num_rows:
            vy_raw = np.asarray(valid_t[self.label_col], np.float64)
            # rows whose label never appeared in training have no class index;
            # they are dropped from eval (scoring them is ill-defined)
            vpos = np.clip(np.searchsorted(classes, vy_raw), 0, len(classes) - 1)
            known = classes[vpos] == vy_raw
            if not known.all():
                logging.getLogger("synapseml_tpu").warning(
                    "dropping %d validation rows with labels unseen in training",
                    int((~known).sum()))
            if known.any():
                valid = [(self._features(valid_t)[known],
                          vpos[known].astype(np.float64))]
        booster = train(
            self._boost_params(objective,
                               num_class if objective != "binary" else 1),
            x, y, weight=weight, valid_sets=valid,
            init_model=init_booster,
            **self._delegate_train_kwargs(batch_index))
        model = self._make_model(LightGBMClassificationModel, booster)
        label_values = [float(c) for c in classes]
        while len(label_values) < 2:  # single-class fit still emits 2 prob cols
            label_values.append(label_values[-1] if label_values else 0.0)
        model.set(num_classes=max(num_class, 2), label_values=label_values)
        return model


class LightGBMClassificationModel(_LightGBMModelBase):
    probability_col = Param("probability column", default="probability")
    raw_prediction_col = Param("raw margin column", default="rawPrediction")
    num_classes = Param("number of classes", default=2)
    label_values = Param("original class labels in index order", default=None)

    def _transform(self, table: Table) -> Table:
        x = self._features(table)
        raw = self.booster.predict_raw(x)
        probs = self.booster.predict(x)
        if raw.ndim == 1:
            probs = np.column_stack([1 - probs, probs])
            raws = np.column_stack([-raw, raw])
        else:
            raws = raw
        pred_idx = probs.argmax(-1)
        if self.label_values is not None:
            pred = np.asarray(self.label_values, np.float64)[pred_idx]
        else:
            pred = pred_idx.astype(np.float64)
        return table.with_columns({
            self.raw_prediction_col: raws,
            self.probability_col: probs,
            self.prediction_col: pred,
        })


class LightGBMRegressor(_LightGBMEstimatorBase):
    """ref: lightgbm/.../LightGBMRegressor.scala:38-154."""

    objective = Param(
        "regression|regression_l1|huber|fair|poisson|quantile|mape|tweedie",
        default="regression")
    alpha = Param("huber/quantile alpha", default=0.9)
    tweedie_variance_power = Param("tweedie power", default=1.5)

    def _fit_single(self, table: Table, init_booster, batch_index,
                    ctx) -> "LightGBMRegressionModel":
        train_t, valid_t = self._split_validation(table)
        x = self._features(train_t)
        y = np.asarray(train_t[self.label_col], np.float64)
        weight = (np.asarray(train_t[self.weight_col], np.float64)
                  if self.weight_col else None)
        valid = []
        if valid_t is not None and valid_t.num_rows:
            valid = [(self._features(valid_t),
                      np.asarray(valid_t[self.label_col], np.float64))]
        bp = dataclasses.replace(
            self._boost_params(self.objective),
            alpha=float(self.alpha),
            tweedie_variance_power=float(self.tweedie_variance_power))
        booster = train(bp, x, y, weight=weight, valid_sets=valid,
                        init_model=init_booster,
                        **self._delegate_train_kwargs(batch_index))
        return self._make_model(LightGBMRegressionModel, booster)


class LightGBMRegressionModel(_LightGBMModelBase):
    def _transform(self, table: Table) -> Table:
        pred = self.booster.predict(self._features(table))
        return table.with_column(self.prediction_col, pred.astype(np.float64))


class LightGBMRanker(_LightGBMEstimatorBase):
    """ref: lightgbm/.../LightGBMRanker.scala:26-177."""

    objective = Param("lambdarank", default="lambdarank")
    group_col = Param("query/group id column", default="query")
    max_position = Param("NDCG truncation", default=30)
    evaluate_at = Param("eval positions", default=None)

    def _fit_single(self, table: Table, init_booster, batch_index,
                    ctx) -> "LightGBMRankerModel":
        # repartition-by-group analogue: sort so each query is contiguous
        # (ref: repartitionByGroupingColumn, lightgbm/.../LightGBMBase.scala)
        table = table.sort(self.group_col)
        train_t, valid_t = self._split_validation(table)
        x = self._features(train_t)
        y = np.asarray(train_t[self.label_col], np.float64)
        raw_group = np.asarray(train_t[self.group_col])
        _, group_ids = np.unique(raw_group, return_inverse=True)
        weight = (np.asarray(train_t[self.weight_col], np.float64)
                  if self.weight_col else None)
        valid = []
        if valid_t is not None and valid_t.num_rows:
            _, vgroup = np.unique(np.asarray(valid_t[self.group_col]),
                                  return_inverse=True)
            valid = [(self._features(valid_t),
                      np.asarray(valid_t[self.label_col], np.float64),
                      vgroup)]
        bp = dataclasses.replace(self._boost_params("lambdarank"),
                                 max_position=int(self.max_position))
        booster = train(bp, x, y, weight=weight, group=group_ids,
                        valid_sets=valid, init_model=init_booster,
                        **self._delegate_train_kwargs(batch_index))
        return self._make_model(LightGBMRankerModel, booster)


class LightGBMRankerModel(_LightGBMModelBase):
    group_col = Param("query/group id column", default="query")

    def _transform(self, table: Table) -> Table:
        pred = self.booster.predict_raw(self._features(table))
        return table.with_column(self.prediction_col, pred.astype(np.float64))
