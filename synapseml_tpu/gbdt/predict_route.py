"""Measured routing for the fused Pallas forest-traversal kernel.

``cached_hist_route``-style prober for the PREDICT side (round 15): on
first sight of a (rows, trees, nodes, features, classes) shape class on
a TPU backend, compile the fused traversal kernel
(:func:`synapseml_tpu.gbdt.pallas_kernels.predict_forest_tpu`), VERIFY
it against the XLA scan reference on synthetic trees, time both legs,
and persist the verdict ("pallas" only when the kernel is both correct
and not slower). Any probe failure, numeric mismatch, or timing
regression silently lands an "xla" verdict — scoring never degrades,
it just doesn't accelerate. ``SYNAPSEML_GBDT_PALLAS=0`` kills the lane
outright.

Route decisions are counted in ``gbdt_predict_route_total{backend=}``
(docs/observability.md) so a fleet can see which formulation actually
serves — the same honesty contract as the histogram router's
``auto_routed_to`` bench field.

Trace-safety: :func:`cached_route` never probes (safe inside an
ambient trace, where ``predict_tree`` runs under the boosting scan);
:func:`route_predict` may probe, but escapes any ambient trace the way
``pallas_kernels.available`` does — concrete numpy in, AOT
lower+compile+execute out.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.runtime import autotune
from synapseml_tpu.runtime.proberoute import RouteTable
from synapseml_tpu.runtime.proberoute import best_of as _best_of

_TABLE = RouteTable("predict_routing.json")

# probe shape clamps: enough sustained compute per timed call that the
# verdict reflects the formulations, not the dispatch tunnel (the
# histogram router's round-4 lesson), yet bounded — the probe runs
# SYNCHRONOUSLY in the first predict of a shape class, so a 4000-tree
# ensemble must not pay a 4000-step probe there; per-tree cost scales
# ~linearly in both formulations, so a clamped-T probe ranks them
_PROBE_ROWS_CAP = 16384
_PROBE_TREES_CAP = 128
_PROBE_VERIFY_RTOL = 1e-4
_PROBE_VERIFY_ATOL = 1e-5


def enabled() -> bool:
    import os

    return os.environ.get("SYNAPSEML_GBDT_PALLAS", "1") != "0"


def _shape_ok(n: int, t: int, m_pad: int, f: int, k: int) -> bool:
    """Bounds that keep the kernel's [tn, m_pad] one-hot intermediates
    and the per-tree VMEM blocks sane; anything wider routes to XLA."""
    return (n >= 1 and t >= 1 and 1 <= k <= 32
            and f <= 512 and m_pad <= 1024)


def _count(backend: str) -> None:
    try:
        from synapseml_tpu.runtime import telemetry

        telemetry.counter("gbdt_predict_route_total",
                          backend=backend).inc()
    except Exception:  # noqa: BLE001 - telemetry must never gate scoring
        pass


def _m_pad(m: int) -> int:
    return max(128, -(-m // 128) * 128)


def _key(n: int, t: int, m: int, f: int, k: int, strict: bool) -> str:
    """Shape-class key: rows and trees bucket to the next power of two
    (nearby sizes share one verdict), node width to its 128-lane pad.
    Versioned like the histogram router's — a jaxlib or in-package
    kernel upgrade must re-probe, not remember."""
    n_b = 1 << (int(min(max(n, 256), 65536)) - 1).bit_length()
    t_b = 1 << (int(min(max(t, 1), 4096)) - 1).bit_length()
    kind = jax.devices()[0].device_kind
    import synapseml_tpu as _pkg

    pkg_v = getattr(_pkg, "__version__", "0")
    return (f"pv1|jax{jax.__version__}|pkg{pkg_v}|{kind}|"
            f"n{n_b}|t{t_b}|m{_m_pad(m)}|f{f}|k{k}|"
            f"{'lt' if strict else 'le'}")


def cached_route(n: int, t: int, m: int, f: int, k: int = 1,
                 strict: bool = False) -> str:
    """Cache-only verdict — NO probe (trace-safe). "xla" unless a
    measured "pallas" verdict exists for this shape class and the lane
    is viable here at all."""
    backend = "xla"
    if enabled() and jax.default_backend() == "tpu" \
            and _shape_ok(n, t, _m_pad(m), f, k):
        if _LANE.cached(n, t, m, f, k, strict) == "pallas":
            backend = "pallas"
    _count(backend)
    return backend


def count(backend: str) -> None:
    """Count one served decision in gbdt_predict_route_total — for
    callers that route with ``count=False`` and report the backend
    that ACTUALLY served after the kernel leg's outcome is known (the
    catalog documents the label as served-by, so a dispatch-time
    kernel failure must land in the xla bucket)."""
    _count(backend)


def route_predict(n: int, t: int, m: int, f: int, k: int = 1,
                  strict: bool = False, count: bool = True) -> str:
    """Full routing: cached verdict, else the shared autotuner lane
    probes (compile+verify+time) and persists the winner — the
    routing loop, crash-memo semantics, and fallback contract all
    live in :mod:`synapseml_tpu.runtime.autotune` now. Returns
    "pallas" or "xla"; the decision is counted unless the caller
    defers counting to the observed outcome (``count=False`` +
    :func:`count`)."""
    backend = "xla"
    if enabled() and jax.default_backend() == "tpu" \
            and _shape_ok(n, t, _m_pad(m), f, k):
        backend = _LANE.route(n, t, m, f, k, strict)
    if count:
        _count(backend)
    return backend


def poison(n: int, t: int, m: int, f: int, k: int = 1,
           strict: bool = False) -> None:
    """Demote this shape class to XLA after a runtime failure of the
    kernel leg (the silent-fallback half of the contract): persisted so
    the failure is not re-paid after restart."""
    _LANE.poison(n, t, m, f, k, strict)


def _synthetic_forest(t: int, m: int, f: int,
                      seed: int = 0) -> Tuple[np.ndarray, ...]:
    """Valid random ensemble in complete-binary layout (children at
    2i+1/2i+2, leaves where those fall outside M) — structurally the
    worst-case depth the kernel's fori_loop must cover."""
    rng = np.random.default_rng(seed)
    idx = np.arange(m)
    internal = 2 * idx + 2 < m
    feat = np.where(internal[None, :],
                    rng.integers(0, f, (t, m)), -1).astype(np.int32)
    thr = np.where(internal[None, :],
                   rng.normal(size=(t, m)), 0.0).astype(np.float32)
    left = np.where(internal, 2 * idx + 1, 0).astype(np.int32)
    right = np.where(internal, 2 * idx + 2, 0).astype(np.int32)
    left = np.broadcast_to(left, (t, m)).copy()
    right = np.broadcast_to(right, (t, m)).copy()
    value = np.where(internal[None, :], 0.0,
                     rng.normal(size=(t, m))).astype(np.float32)
    return feat, thr, left, right, value


def _probe(n: int, t: int, m: int, f: int, k: int,
           strict: bool) -> str:
    """Compile + verify + time the kernel against the PRODUCTION
    fallback it would replace — boosting._predict_stack (unit weights)
    for GBDT, iforest._path_lengths for the strict/depth variant — at
    the (clamped) shape class, so a semantic change to either
    formulation de-certifies stale verdicts instead of letting routed
    and fallback results diverge. Lazy imports only: boosting/iforest
    import this module inside functions too, so no cycle. Concrete
    numpy in, AOT executables out — escapes any ambient trace exactly
    like pallas_kernels.available()."""
    from synapseml_tpu.gbdt import pallas_kernels

    n_p = int(min(max(n, 256), _PROBE_ROWS_CAP))
    t_p = int(min(max(t, 1), _PROBE_TREES_CAP))
    rng = np.random.default_rng(0)
    feat, thr, left, right, value = _synthetic_forest(t_p, m, f)
    x = rng.normal(size=(n_p, f)).astype(np.float32)
    x[rng.random(x.shape) < 0.05] = np.nan  # missing rows probe too

    stack = (feat, thr, left, right, value)
    depth = m // 2 + 1
    if strict:
        from synapseml_tpu.isolationforest.iforest import _path_lengths

        def xla_fn(xx, *s):
            # mean path * T = the kernel's accumulated total
            return (_path_lengths(s, xx, depth) * t_p)[:, None]
    else:
        from synapseml_tpu.gbdt.boosting import _predict_stack

        def xla_fn(xx, *s):
            return _predict_stack(
                s, jnp.ones((t_p,), jnp.float32), xx, k, t_p)

    pallas_c = jax.jit(lambda xx, *s: pallas_kernels.predict_forest_tpu(
        xx, *s, k=k, strict=strict)).lower(x, *stack).compile()
    xla_c = jax.jit(xla_fn).lower(x, *stack).compile()

    got = np.asarray(pallas_c(x, *stack))
    want = np.asarray(xla_c(x, *stack))
    if not np.allclose(got, want, rtol=_PROBE_VERIFY_RTOL,
                       atol=_PROBE_VERIFY_ATOL, equal_nan=True):
        return "xla"
    args = (x,) + stack
    return ("pallas" if _best_of(pallas_c, args) <= _best_of(xla_c, args)
            else "xla")


# The lane registration: _probe above stays the monkeypatchable
# whole-probe seam (tests stub it to forbid or force probing), so it
# rides the autotuner's legacy probe_hook adapter — late-bound lambdas
# so a monkeypatched predict_route._probe / predict_route._key is what
# actually runs. Key schema and verdict table are unchanged (pv1|...,
# predict_routing.json): fleet verdicts from PR 15 stay valid.
_LANE = autotune.register_lane(
    "gbdt_predict",
    key_fn=lambda *r: _key(*r),
    candidates=("xla", "pallas"),
    reference="xla",
    probe_hook=lambda *r: _probe(*r),
    table=_TABLE,
    groups=("gbdt_predict",),
)


def clear_cache() -> None:
    """Test hook: drop the in-process memo + negative memo."""
    _LANE.reset()
