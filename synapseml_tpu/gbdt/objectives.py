"""GBDT objectives: gradients/hessians + eval metrics, all jax-native.

Replaces lib_lightgbm's C++ objective zoo (driven through the reference's
param string, lightgbm/.../params/TrainParams.scala:46-64) with vectorized
jax functions so grad/hess computation fuses into the boosting update on
device. Custom objectives (the reference's FObjTrait) are plain callables
``(preds, labels, weight) -> (grad, hess)``.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jnp.ndarray
ObjectiveFn = Callable[[Array, Array, Optional[Array]], Tuple[Array, Array]]


def _weighted(grad, hess, weight):
    if weight is not None:
        grad = grad * weight
        hess = hess * weight
    return grad, hess


# -- binary -----------------------------------------------------------------

def binary_logloss_obj(preds, labels, weight=None, sigmoid: float = 1.0):
    p = jax.nn.sigmoid(sigmoid * preds)
    grad = sigmoid * (p - labels)
    hess = sigmoid * sigmoid * p * (1.0 - p)
    return _weighted(grad, hess, weight)


# -- regression -------------------------------------------------------------

def l2_obj(preds, labels, weight=None):
    return _weighted(preds - labels, jnp.ones_like(preds), weight)


def l1_obj(preds, labels, weight=None):
    return _weighted(jnp.sign(preds - labels), jnp.ones_like(preds), weight)


def huber_obj(preds, labels, weight=None, alpha: float = 0.9):
    diff = preds - labels
    grad = jnp.where(jnp.abs(diff) <= alpha, diff, alpha * jnp.sign(diff))
    return _weighted(grad, jnp.ones_like(preds), weight)


def fair_obj(preds, labels, weight=None, c: float = 1.0):
    diff = preds - labels
    grad = c * diff / (jnp.abs(diff) + c)
    hess = c * c / (jnp.abs(diff) + c) ** 2
    return _weighted(grad, hess, weight)


def poisson_obj(preds, labels, weight=None, max_delta_step: float = 0.7):
    exp_p = jnp.exp(preds)
    grad = exp_p - labels
    hess = jnp.exp(preds + max_delta_step)
    return _weighted(grad, hess, weight)


def quantile_obj(preds, labels, weight=None, alpha: float = 0.5):
    diff = labels - preds
    grad = jnp.where(diff >= 0, -alpha, 1.0 - alpha)
    return _weighted(grad, jnp.ones_like(preds), weight)


def mape_obj(preds, labels, weight=None):
    denom = jnp.maximum(jnp.abs(labels), 1.0)
    grad = jnp.sign(preds - labels) / denom
    return _weighted(grad, jnp.ones_like(preds) / denom, weight)


def tweedie_obj(preds, labels, weight=None, rho: float = 1.5):
    exp1 = jnp.exp((1.0 - rho) * preds)
    exp2 = jnp.exp((2.0 - rho) * preds)
    grad = -labels * exp1 + exp2
    hess = -labels * (1.0 - rho) * exp1 + (2.0 - rho) * exp2
    return _weighted(grad, hess, weight)


# -- multiclass (grad/hess per class; trees per class per iteration) --------

def softmax_obj(preds, labels_onehot, weight=None):
    """preds: [N, K] raw scores; labels_onehot: [N, K]."""
    p = jax.nn.softmax(preds, axis=-1)
    grad = p - labels_onehot
    hess = 2.0 * p * (1.0 - p)
    if weight is not None:
        grad = grad * weight[:, None]
        hess = hess * weight[:, None]
    return grad, hess


# -- lambdarank -------------------------------------------------------------

def lambdarank_grad(preds, labels, group_ids, max_dcg_pos: int = 30,
                    sigmoid: float = 2.0):
    """Pairwise LambdaRank gradients with |ΔNDCG| weighting.

    Dense [N,N] pair formulation masked by query groups — O(N²) per chunk,
    intended to run per-query-block where N is the padded max group size.
    preds/labels: [N]; group_ids: [N] int (same id = same query).
    """
    same = group_ids[:, None] == group_ids[None, :]
    label_diff = labels[:, None] - labels[None, :]
    pair_mask = same & (label_diff > 0)

    # per-row DCG discount by rank of preds within the group
    order = jnp.argsort(jnp.where(same, -preds[None, :], jnp.inf), axis=-1)
    ranks = jnp.argsort(order, axis=-1).diagonal()
    disc = 1.0 / jnp.log2(2.0 + jnp.minimum(ranks, max_dcg_pos).astype(jnp.float32))
    gain = (2.0 ** labels - 1.0)

    delta_ndcg = jnp.abs(
        (gain[:, None] - gain[None, :]) * (disc[:, None] - disc[None, :]))
    s = jax.nn.sigmoid(-sigmoid * (preds[:, None] - preds[None, :]))
    lam = -sigmoid * s * delta_ndcg * pair_mask
    grad = lam.sum(axis=1) - lam.sum(axis=0)
    hess_pair = (sigmoid ** 2) * s * (1 - s) * delta_ndcg * pair_mask
    hess = hess_pair.sum(axis=1) + hess_pair.sum(axis=0)
    return grad, jnp.maximum(hess, 1e-6)


def build_query_blocks(group_ids):
    """Host-side layout for block-diagonal lambdarank: rows gathered into
    [Q, G] query blocks (G = max group size). Returns
    ``(row_index [Q, G] int32, pad_mask [Q, G] bool, inv [N] int64)``
    where ``inv`` maps each flat row to its block position (for the
    gather back)."""
    import numpy as np

    group_ids = np.asarray(group_ids)
    order = np.argsort(group_ids, kind="stable")
    sorted_g = group_ids[order]
    bounds = np.nonzero(sorted_g[1:] != sorted_g[:-1])[0] + 1
    groups = np.split(order, bounds)
    gmax = max((len(g) for g in groups), default=1)
    q = len(groups)
    row_index = np.zeros((q, gmax), np.int32)
    pad_mask = np.zeros((q, gmax), bool)
    inv = np.zeros(len(group_ids), np.int64)
    for i, rows in enumerate(groups):
        row_index[i, : len(rows)] = rows
        pad_mask[i, : len(rows)] = True
        inv[rows] = i * gmax + np.arange(len(rows))
    return row_index, pad_mask, inv


def lambdarank_grad_blocked(preds, labels, row_index, pad_mask, inv,
                            max_dcg_pos: int = 30, sigmoid: float = 2.0):
    """Block-diagonal LambdaRank: O(N·G) instead of the dense O(N²) pair
    matrix — pairs only form within a query, so each [G, G] block is
    computed independently under ``vmap`` (layout from
    :func:`build_query_blocks`). Identical math to
    :func:`lambdarank_grad` on the same data.
    """
    p = preds[row_index]
    lab = labels[row_index]

    def one_query(p, lab, valid):
        pair = (valid[:, None] & valid[None, :]
                & ((lab[:, None] - lab[None, :]) > 0))
        order = jnp.argsort(jnp.where(valid, -p, jnp.inf))
        ranks = jnp.argsort(order)
        disc = 1.0 / jnp.log2(
            2.0 + jnp.minimum(ranks, max_dcg_pos).astype(jnp.float32))
        gain = (2.0 ** lab - 1.0) * valid
        delta = jnp.abs((gain[:, None] - gain[None, :])
                        * (disc[:, None] - disc[None, :]))
        s = jax.nn.sigmoid(-sigmoid * (p[:, None] - p[None, :]))
        lam = -sigmoid * s * delta * pair
        grad = lam.sum(axis=1) - lam.sum(axis=0)
        hp = (sigmoid ** 2) * s * (1 - s) * delta * pair
        hess = hp.sum(axis=1) + hp.sum(axis=0)
        return grad, hess

    g, h = jax.vmap(one_query)(p, lab, pad_mask)
    grad = g.reshape(-1)[inv]
    hess = h.reshape(-1)[inv]
    return grad, jnp.maximum(hess, 1e-6)


# -- metrics ----------------------------------------------------------------

def auc_metric(preds, labels, weight=None):
    """Weighted ROC AUC via rank statistic (ties averaged)."""
    order = jnp.argsort(preds)
    ranks = jnp.argsort(order).astype(jnp.float32) + 1.0
    pos = labels > 0
    n_pos = pos.sum()
    n_neg = (~pos).sum()
    sum_pos_ranks = jnp.where(pos, ranks, 0.0).sum()
    auc = (sum_pos_ranks - n_pos * (n_pos + 1) / 2.0) / (
        jnp.maximum(n_pos * n_neg, 1))
    return auc


def binary_logloss_metric(preds, labels, weight=None, eps: float = 1e-15):
    p = jnp.clip(jax.nn.sigmoid(preds), eps, 1 - eps)
    ll = -(labels * jnp.log(p) + (1 - labels) * jnp.log(1 - p))
    if weight is not None:
        return (ll * weight).sum() / weight.sum()
    return ll.mean()


def rmse_metric(preds, labels, weight=None):
    d2 = (preds - labels) ** 2
    if weight is not None:
        return jnp.sqrt((d2 * weight).sum() / weight.sum())
    return jnp.sqrt(d2.mean())


def mae_metric(preds, labels, weight=None):
    d = jnp.abs(preds - labels)
    if weight is not None:
        return (d * weight).sum() / weight.sum()
    return d.mean()


def multi_logloss_metric(preds, labels_int, weight=None, eps: float = 1e-15):
    logp = jax.nn.log_softmax(preds, axis=-1)
    nll = -jnp.take_along_axis(logp, labels_int[:, None], axis=-1)[:, 0]
    if weight is not None:
        return (nll * weight).sum() / weight.sum()
    return nll.mean()


def multi_error_metric(preds, labels_int, weight=None):
    err = (preds.argmax(-1) != labels_int).astype(jnp.float32)
    if weight is not None:
        return (err * weight).sum() / weight.sum()
    return err.mean()


REGRESSION_OBJECTIVES = {
    "regression": l2_obj, "regression_l2": l2_obj, "l2": l2_obj,
    "mse": l2_obj, "mean_squared_error": l2_obj,
    "regression_l1": l1_obj, "l1": l1_obj, "mae": l1_obj,
    "huber": huber_obj, "fair": fair_obj, "poisson": poisson_obj,
    "quantile": quantile_obj, "mape": mape_obj, "tweedie": tweedie_obj,
}

METRICS = {
    "auc": (auc_metric, True),
    "binary_logloss": (binary_logloss_metric, False),
    "rmse": (rmse_metric, False),
    "l2": (rmse_metric, False),
    "mae": (mae_metric, False),
    "l1": (mae_metric, False),
    "multi_logloss": (multi_logloss_metric, False),
    "multi_error": (multi_error_metric, False),
}
