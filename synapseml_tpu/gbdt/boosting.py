"""Boosting loop + Booster model for the TPU GBDT engine.

The reference's train loop lives in Scala driving native iterations
(ref: lightgbm/.../TrainUtils.scala trainCore:92-159 — iteration loop, eval
metrics, early stopping with improvement tolerance) over lib_lightgbm.
Here the loop is Python orchestration around ONE jitted iteration step
(grad/hess + bagging + tree build + score update all fused on device), and
the model is a stack of flat tree arrays scanned on device at predict time.

Boosting types: gbdt, goss (gradient one-side sampling), dart (dropout),
rf (bagged random forest) — mirroring the reference's boostingType param
(lightgbm/.../params/LightGBMParams.scala).
"""
from __future__ import annotations

import dataclasses
import json
from functools import lru_cache, partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from synapseml_tpu.gbdt import objectives as obj
from synapseml_tpu.gbdt.binning import BinMapper
from synapseml_tpu.gbdt.grower import (
    GrowerParams, Tree, build_tree, predict_tree)


@dataclasses.dataclass(frozen=True)
class BoostParams:
    objective: str = "binary"
    boosting_type: str = "gbdt"
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = 0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_bin: int = 255
    # rows sampled to construct bin boundaries (LightGBM's
    # bin_construct_sample_cnt); also the per-job gather budget of the
    # row-sharded multi-host path (train_row_sharded)
    bin_sample_count: int = 200_000
    feature_fraction: float = 1.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    pos_bagging_fraction: float = 1.0
    neg_bagging_fraction: float = 1.0
    # goss
    top_rate: float = 0.2
    other_rate: float = 0.1
    # dart
    drop_rate: float = 0.1
    max_drop: int = 50
    skip_drop: float = 0.5
    # uniform_drop=False drops trees with probability proportional to
    # their current |weight| (lib_lightgbm dart.hpp DroppingTrees);
    # True is plain Bernoulli(drop_rate) per tree
    uniform_drop: bool = False
    # xgboost_dart_mode normalizes a round that dropped kd trees with
    # lr/(kd+lr) (xgboost's dart) instead of lr/(kd+1)
    xgboost_dart_mode: bool = False
    # None derives the bagging stream from `seed` (stream-stable with
    # earlier versions); an int gives bagging its own stream, the
    # reference's baggingSeed (LightGBMParams.scala)
    bagging_seed: Optional[int] = None
    # early stopping counts an iteration as improved only if the metric
    # moved by more than this (reference improvementTolerance,
    # TrainUtils.scala:129-141: larger-better improved iff m-best>tol,
    # smaller-better iff m-best<tol)
    improvement_tolerance: float = 0.0
    # multiclass
    num_class: int = 1
    sigmoid: float = 1.0
    alpha: float = 0.9            # huber / quantile alpha
    tweedie_variance_power: float = 1.5
    poisson_max_delta_step: float = 0.7
    boost_from_average: bool = True
    max_position: int = 30      # lambdarank NDCG truncation
    early_stopping_round: int = 0
    metric: Optional[str] = None
    seed: int = 0
    deterministic: bool = True
    categorical_features: Tuple[int, ...] = ()
    verbosity: int = -1
    # "auto" = measure at fit time (grower.resolve_hist_backend);
    # "pallas"/"xla" force a histogram formulation
    hist_backend: str = "auto"
    # distributed tree learner (the reference's parallelism param,
    # LightGBMParams.scala:16-18: "data_parallel or voting_parallel");
    # voting elects voting_top_k features per split (LightGBM top_k)
    # and merges only their histograms — see GrowerParams.voting_top_k
    tree_learner: str = "data_parallel"
    voting_top_k: int = 20

    def grower(self) -> GrowerParams:
        if self.tree_learner not in ("data_parallel", "voting_parallel"):
            raise ValueError(
                f"tree_learner {self.tree_learner!r}: the reference's "
                "parallelism param offers data_parallel or "
                "voting_parallel (LightGBMParams.scala:16-18)")
        return GrowerParams(
            num_leaves=self.num_leaves,
            max_bin=0,  # filled at fit time (device width)
            max_depth=self.max_depth,
            lambda_l1=self.lambda_l1,
            lambda_l2=self.lambda_l2,
            min_data_in_leaf=max(1, self.min_data_in_leaf),
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            min_gain_to_split=self.min_gain_to_split,
            hist_backend=self.hist_backend,
            voting_top_k=(max(1, int(self.voting_top_k))
                          if self.tree_learner == "voting_parallel"
                          else 0),
        )


def _objective_fn(p: BoostParams) -> Callable:
    o = p.objective
    if o in ("binary", "binary_logloss"):
        return partial(obj.binary_logloss_obj, sigmoid=p.sigmoid)
    if o in ("multiclass", "softmax", "multiclassova"):
        return obj.softmax_obj
    if o in ("lambdarank", "rank_xendcg"):
        return None  # handled specially with group ids
    if o == "huber":
        return partial(obj.huber_obj, alpha=p.alpha)
    if o == "quantile":
        return partial(obj.quantile_obj, alpha=p.alpha)
    if o == "tweedie":
        return partial(obj.tweedie_obj, rho=p.tweedie_variance_power)
    if o == "poisson":
        return partial(obj.poisson_obj, max_delta_step=p.poisson_max_delta_step)
    fn = obj.REGRESSION_OBJECTIVES.get(o)
    if fn is None:
        raise ValueError(f"unknown objective {o!r}")
    return fn


def _default_metric(p: BoostParams) -> str:
    if p.metric:
        return p.metric
    if p.objective in ("binary", "binary_logloss"):
        return "binary_logloss"
    if p.objective in ("multiclass", "softmax", "multiclassova"):
        return "multi_logloss"
    if p.objective in ("lambdarank", "rank_xendcg"):
        return "ndcg"
    if p.objective in ("regression_l1", "l1", "mae"):
        return "mae"
    return "rmse"


def _ndcg_score(scores: np.ndarray, labels: np.ndarray, group_ids: np.ndarray,
                at: int, blocks=None) -> float:
    """Mean NDCG@at over query groups — vectorized over [Q, Gmax] query
    blocks (this runs once per boosting iteration in the rank eval path;
    the per-query python loop dominated eval at large Q). Pass ``blocks``
    (from :func:`objectives.build_query_blocks`) to reuse the layout
    across iterations — the group array never changes during a fit."""
    scores = np.asarray(scores)
    labels = np.asarray(labels)
    if len(scores) == 0:
        return 0.0
    if blocks is None:
        blocks = obj.build_query_blocks(np.asarray(group_ids))
    row_index, pad_mask, _ = blocks
    if row_index.size > 8 * len(scores):
        # heavy group-size skew: dense [Q, Gmax] blocks would dwarf the
        # data — per-group loop is cheaper
        return _ndcg_score_loop(scores, labels, np.asarray(group_ids), at)
    s = np.where(pad_mask, scores[row_index], -np.inf)
    rel = np.where(pad_mask, labels[row_index], 0.0)
    gmax = s.shape[1]
    cols = min(at, gmax)
    # pads sort last (score -inf, gain 0): identical to per-group slicing
    order = np.argsort(-s, axis=1, kind="stable")[:, :cols]
    gains = np.take_along_axis(2.0 ** rel - 1.0, order, axis=1)
    disc = 1.0 / np.log2(np.arange(2, cols + 2))
    dcg = (gains * disc).sum(axis=1)
    ideal = -np.sort(-(2.0 ** rel - 1.0), axis=1)[:, :cols]
    idcg = (ideal * disc).sum(axis=1)
    valid = idcg > 0
    if not valid.any():
        return 0.0
    return float((dcg[valid] / idcg[valid]).mean())


def _ndcg_score_loop(scores, labels, group_ids, at: int) -> float:
    """Per-group fallback for pathologically skewed group sizes."""
    total, count = 0.0, 0
    for g in np.unique(group_ids):
        sel = group_ids == g
        rel = labels[sel]
        if len(rel) == 0:
            continue
        order = np.argsort(-scores[sel], kind="stable")[:at]
        discounts = 1.0 / np.log2(np.arange(2, len(order) + 2))
        dcg = float(np.sum((2.0 ** rel[order] - 1.0) * discounts))
        ideal = np.sort(rel)[::-1][:at]
        idcg = float(np.sum((2.0 ** ideal - 1.0)
                            / np.log2(np.arange(2, len(ideal) + 2))))
        if idcg > 0:
            total += dcg / idcg
            count += 1
    return total / max(count, 1)


class _ValidTracker:
    """Validation scoring + early stopping shared by the train loops.

    Tree outputs accumulate as a raw sum; the effective margin at iteration
    ``it`` is ``init + sum * (1/(it+1) if rf else 1)`` so rf metrics are
    computed on averaged scores, matching rf prediction. ``best_iteration``
    is only exported when early stopping is enabled (LightGBM semantics —
    merely supplying eval data must not truncate predictions).
    """

    def __init__(self, p: BoostParams, k: int, init: float, valid_sets):
        self.p, self.k, self.init = p, k, init
        self.metric_name = _default_metric(p)
        self.metric_fn, self.larger_better = obj.METRICS.get(
            self.metric_name, (None, False))
        self.is_rank_metric = self.metric_name == "ndcg"
        if self.is_rank_metric:
            self.larger_better = True
        self.sets = []
        for vs in valid_sets:
            vx, vy = vs[0], vs[1]
            vg = (np.asarray(vs[2]) if len(vs) > 2 and vs[2] is not None
                  else None)
            self.sets.append([
                jnp.asarray(np.asarray(vx, np.float32)),
                jnp.asarray(np.asarray(vy, np.float32)),
                jnp.zeros((len(vy), k), jnp.float32), vg])
        self.enabled = bool(self.sets) and (
            self.metric_fn is not None
            or (self.is_rank_metric and self.sets[0][3] is not None))
        self.best_score = -np.inf if self.larger_better else np.inf
        self.best_iter = -1
        self.history: Dict[str, List[float]] = {self.metric_name: []}
        self._pt = jax.jit(predict_tree)
        # rank eval reuses the query-block layout across every iteration —
        # but only when the padded layout is sane: under heavy group-size
        # skew _ndcg_score's guard takes the per-group loop anyway, and
        # building the blocks here would be the very allocation it avoids
        self.ndcg_blocks = None
        if self.is_rank_metric and self.sets and self.sets[0][3] is not None:
            vg = np.asarray(self.sets[0][3])
            _, counts = np.unique(vg, return_counts=True)
            if len(counts) * counts.max() <= 8 * len(vg):
                self.ndcg_blocks = obj.build_query_blocks(vg)

    def add_tree(self, tree, class_idx: int):
        if not self.enabled:
            return
        # step() only consumes sets[0]; skip accumulating scores nobody reads
        for v in self.sets[:1]:
            vt = self._pt(
                (tree.split_feature, tree.threshold, tree.left_child,
                 tree.right_child, tree.leaf_value), v[0])
            v[2] = v[2].at[:, class_idx].add(vt)

    def step(self, it: int, is_rf: bool) -> bool:
        """Record the metric after iteration ``it``; True = stop early."""
        if not self.enabled:
            return False
        _, vy, vsum, vg = self.sets[0]
        scale = 1.0 / (it + 1.0) if is_rf else 1.0
        vscore = vsum * scale + self.init
        if self.is_rank_metric:
            m = _ndcg_score(np.asarray(vscore[:, 0]), np.asarray(vy), vg,
                            self.p.max_position, blocks=self.ndcg_blocks)
        elif self.k > 1:
            m = float(self.metric_fn(vscore, vy.astype(jnp.int32)))
        else:
            m = float(self.metric_fn(vscore[:, 0], vy))
        return self.record(m, it)

    def record(self, m: float, it: int) -> bool:
        """Record a precomputed metric value; True = stop early."""
        self.history[self.metric_name].append(m)
        tol = self.p.improvement_tolerance
        improved = (m - self.best_score > tol if self.larger_better
                    else m - self.best_score < tol)
        if improved:
            self.best_score, self.best_iter = m, it
            return False
        return (self.p.early_stopping_round > 0
                and it - self.best_iter >= self.p.early_stopping_round)

    def final_best_iter(self) -> int:
        return self.best_iter if self.p.early_stopping_round > 0 else -1


def _dart_select(rng, t: int, cur_weights, p: BoostParams) -> np.ndarray:
    """One DART iteration's drop set (host RNG, lib_lightgbm dart.hpp
    DroppingTrees): skip_drop gates the whole round; uniform mode is
    Bernoulli(drop_rate) per tree, weighted mode drops proportionally to
    each tree's current |weight| (normalized by the mean weight)."""
    if t == 0 or rng.random() < p.skip_drop:
        return np.empty(0, np.int64)
    w = np.abs(np.asarray(cur_weights[:t], np.float64))
    if p.uniform_drop or w.sum() <= 0:
        sel = rng.random(t) < p.drop_rate
    else:
        probs = np.minimum(1.0, p.drop_rate * t * w / w.sum())
        sel = rng.random(t) < probs
    dropped = np.nonzero(sel)[0]
    if p.max_drop > 0:  # LightGBM: max_drop <= 0 = no limit
        dropped = dropped[: p.max_drop]
    return dropped


def _dart_normalize(p: BoostParams, kd: int):
    """(new_tree_weight, dropped_scale) after a round that dropped
    ``kd`` trees: lr/(kd+1) classic, lr/(kd+lr) in xgboost_dart_mode."""
    if kd == 0:
        return p.learning_rate, 1.0
    if p.xgboost_dart_mode:
        return (p.learning_rate / (kd + p.learning_rate),
                kd / (kd + p.learning_rate))
    return p.learning_rate / (kd + 1.0), kd / (kd + 1.0)


def _init_score(p: BoostParams, y: np.ndarray, weight: Optional[np.ndarray]):
    """boost_from_average analogue of LightGBM's ObtainAutomaticInitialScore."""
    if not p.boost_from_average:
        return 0.0
    w = weight if weight is not None else np.ones_like(y, dtype=np.float64)
    if p.objective in ("binary", "binary_logloss"):
        pbar = float(np.clip(np.average(y, weights=w), 1e-12, 1 - 1e-12))
        return float(np.log(pbar / (1 - pbar)) / p.sigmoid)
    if p.objective in ("poisson", "tweedie"):
        mean = max(float(np.average(y, weights=w)), 1e-12)
        return float(np.log(mean))
    if p.objective == "quantile":
        return float(np.quantile(y, p.alpha))
    if p.objective in ("regression_l1", "l1", "mae", "huber", "mape"):
        return float(np.median(y))
    if p.objective in ("multiclass", "softmax", "multiclassova",
                       "lambdarank", "rank_xendcg"):
        return 0.0
    return float(np.average(y, weights=w))


@dataclasses.dataclass
class Booster:
    """Trained model: stacked tree arrays + metadata. Device-scannable."""
    trees_feature: np.ndarray    # [T, M]
    trees_threshold: np.ndarray  # [T, M]
    trees_left: np.ndarray       # [T, M]
    trees_right: np.ndarray      # [T, M]
    trees_value: np.ndarray      # [T, M] (already shrunk by learning rate)
    trees_cover: np.ndarray      # [T, M] training row count per node
    trees_gain: np.ndarray       # [T, M] split gain per internal node
    tree_weights: np.ndarray     # [T] (1.0 for gbdt; 1/T for rf; dart weights)
    params: BoostParams = dataclasses.field(default_factory=BoostParams)
    init_score: float = 0.0
    num_class: int = 1
    best_iteration: int = -1
    num_features: int = -1
    feature_names: Optional[List[str]] = None
    feature_importance_split: Optional[np.ndarray] = None
    feature_importance_gain: Optional[np.ndarray] = None
    eval_history: Dict[str, List[float]] = dataclasses.field(default_factory=dict)
    # categorical splits (native LightGBM interop): trees_cat[t, n] >= 0
    # marks node n of tree t as categorical, indexing into the global
    # bitset pool — int(x) in the set -> left child. None = all numeric.
    trees_cat: Optional[np.ndarray] = None       # [T, M] int32, -1 = numeric
    cat_bitsets: Optional[np.ndarray] = None     # [W] uint32 words
    cat_boundaries: Optional[np.ndarray] = None  # [S+1] int32 word offsets

    @property
    def num_trees(self) -> int:
        return self.trees_feature.shape[0]

    @property
    def num_iterations(self) -> int:
        """Boosting iterations = trees / classes (multiclass stacks K
        class trees per iteration)."""
        return self.num_trees // max(self.num_class, 1)

    def _raw_scores(self, x: np.ndarray, num_iteration: int = -1,
                    start_iteration: int = 0) -> np.ndarray:
        """[N] or [N, K] raw margin scores, computed with a device scan.

        ``start_iteration``/``num_iteration`` select an iteration RANGE
        (lib_lightgbm's predict window, the reference's startIteration /
        numIterations model params) — the init score attaches only when
        the window starts at 0, matching LightGBM."""
        x = np.asarray(x, dtype=np.float32)
        if self.num_features > 0 and x.shape[1] != self.num_features:
            raise ValueError(
                f"feature width mismatch: model trained on "
                f"{self.num_features} features, got {x.shape[1]}")
        k = self.num_class
        if x.shape[0] == 0:
            # zero-row predict: answer the empty shape directly instead
            # of tracing the traversal scan over an empty batch (which
            # used to compile a degenerate program per model)
            out = np.zeros((0, k), np.float32)
            return out if k > 1 else out[:, 0]
        t = self.num_trees
        t0 = max(0, int(start_iteration)) * k
        if num_iteration and num_iteration > 0:
            t = min(t, t0 + num_iteration * k)
        elif self.best_iteration >= 0 and t0 == 0:
            # after early stopping, default to the best iteration — but
            # only for whole-model predicts: an explicit start window
            # with unset num_iteration means "all remaining trees"
            # (lib_lightgbm sets num_iteration=-1 when start > 0)
            t = min(t, (self.best_iteration + 1) * k)
        t = max(t, t0)
        stack = (
            jnp.asarray(self.trees_feature[t0:t]),
            jnp.asarray(self.trees_threshold[t0:t]),
            jnp.asarray(self.trees_left[t0:t]),
            jnp.asarray(self.trees_right[t0:t]),
            jnp.asarray(self.trees_value[t0:t]),
        )
        weights = jnp.asarray(self.tree_weights[t0:t], jnp.float32)
        n_used = t - t0
        if self.params.boosting_type == "rf" and n_used > 0:
            # rf margins are averages over the trees actually used, so a
            # truncated predict (early stopping / num_iteration) must
            # renormalize from 1/T_total to 1/T_kept
            weights = jnp.full((n_used,), 1.0 / max(n_used // k, 1),
                               jnp.float32)
        if self.trees_cat is not None:
            out = _predict_stack_cat(
                stack + (jnp.asarray(self.trees_cat[t0:t]),),
                weights, jnp.asarray(x),
                jnp.asarray(self.cat_bitsets, jnp.uint32),
                jnp.asarray(self.cat_boundaries, jnp.int32), k, n_used)
        else:
            out = _predict_stack_routed(stack, weights, jnp.asarray(x),
                                        k, n_used)
        out = np.asarray(out)
        if t0 == 0:
            out = out + self.init_score
        return out if k > 1 else out[:, 0]

    def predict_raw(self, x, num_iteration: int = -1,
                    start_iteration: int = 0):
        return self._raw_scores(x, num_iteration, start_iteration)

    def predict(self, x, num_iteration: int = -1, start_iteration: int = 0):
        raw = self._raw_scores(x, num_iteration, start_iteration)
        o = self.params.objective
        if o in ("binary", "binary_logloss"):
            return 1.0 / (1.0 + np.exp(-self.params.sigmoid * raw))
        if o in ("multiclass", "softmax"):
            e = np.exp(raw - raw.max(axis=-1, keepdims=True))
            return e / e.sum(axis=-1, keepdims=True)
        if o == "multiclassova":
            s_ = 1.0 / (1.0 + np.exp(-self.params.sigmoid * raw))
            return s_ / s_.sum(axis=-1, keepdims=True)
        if o in ("poisson", "tweedie"):
            return np.exp(raw)
        return raw

    def predict_leaf(self, x) -> np.ndarray:
        """[N, T] leaf index per tree (parity with predictLeaf,
        ref: lightgbm/.../LightGBMModelMethods.scala)."""
        if self.trees_cat is not None:
            raise NotImplementedError(
                "predict_leaf is not implemented for models with "
                "categorical splits (loaded native LightGBM model)")
        x = np.asarray(x, dtype=np.float32)
        stack = (
            jnp.asarray(self.trees_feature),
            jnp.asarray(self.trees_threshold),
            jnp.asarray(self.trees_left),
            jnp.asarray(self.trees_right),
        )
        return np.asarray(_leaf_index_stack(stack, jnp.asarray(x)))

    # -- persistence ---------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "params": dataclasses.asdict(self.params),
            "init_score": self.init_score,
            "num_class": self.num_class,
            "best_iteration": self.best_iteration,
            "num_features": self.num_features,
            "feature_names": self.feature_names,
            "trees": {
                "feature": self.trees_feature.tolist(),
                "threshold": self.trees_threshold.tolist(),
                "left": self.trees_left.tolist(),
                "right": self.trees_right.tolist(),
                "value": self.trees_value.tolist(),
                "cover": self.trees_cover.tolist(),
                "gain": self.trees_gain.tolist(),
                "weights": self.tree_weights.tolist(),
            },
            **({"categorical": {
                "trees_cat": self.trees_cat.tolist(),
                "bitsets": self.cat_bitsets.tolist(),
                "boundaries": self.cat_boundaries.tolist(),
            }} if self.trees_cat is not None else {}),
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Booster":
        t = d["trees"]
        params = d.get("params", {})
        params["categorical_features"] = tuple(params.get("categorical_features", ()))
        return Booster(
            trees_feature=np.asarray(t["feature"], np.int32),
            trees_threshold=np.asarray(t["threshold"], np.float32),
            trees_left=np.asarray(t["left"], np.int32),
            trees_right=np.asarray(t["right"], np.int32),
            trees_value=np.asarray(t["value"], np.float32),
            trees_cover=np.asarray(t.get("cover", np.zeros_like(t["value"])), np.float32),
            trees_gain=np.asarray(t.get("gain", np.zeros_like(t["value"])), np.float32),
            tree_weights=np.asarray(t["weights"], np.float32),
            params=BoostParams(**params),
            init_score=d.get("init_score", 0.0),
            num_class=d.get("num_class", 1),
            best_iteration=d.get("best_iteration", -1),
            num_features=d.get("num_features", -1),
            feature_names=d.get("feature_names"),
            **({} if "categorical" not in d else {
                "trees_cat": np.asarray(
                    d["categorical"]["trees_cat"], np.int32),
                "cat_bitsets": np.asarray(
                    d["categorical"]["bitsets"], np.uint32),
                "cat_boundaries": np.asarray(
                    d["categorical"]["boundaries"], np.int32),
            }),
        )

    def save_string(self) -> str:
        """Serialize in LightGBM's native text model format (interoperable
        with lightgbm-python / SHAP tooling; ref LightGBMBooster.scala:454)."""
        from synapseml_tpu.gbdt.lgbm_format import booster_to_native_string
        return booster_to_native_string(self)

    @staticmethod
    def load_string(s: str) -> "Booster":
        """Parse either the native LightGBM text format or the legacy
        (round-1) JSON format, auto-detected."""
        if s.lstrip().startswith("{"):
            return Booster.from_dict(json.loads(s))
        from synapseml_tpu.gbdt.lgbm_format import booster_from_native_string
        return booster_from_native_string(s)


@partial(jax.jit, static_argnums=(3, 4))
def _predict_stack(stack, weights, x, k: int, t: int):
    n = x.shape[0]

    def body(carry, tree_w):
        (feat, thr, left, right, value), w, idx = tree_w
        pred = predict_tree((feat, thr, left, right, value), x,
                            route=False) * w
        carry = carry.at[:, idx % k].add(pred)
        return carry, None

    out = jnp.zeros((n, k), jnp.float32)
    idxs = jnp.arange(t, dtype=jnp.int32)
    out, _ = jax.lax.scan(body, out, (stack, weights, idxs))
    return out


@partial(jax.jit, static_argnums=(3, 4))
def _predict_stack_pallas(stack, weights, x, k: int, t: int):
    """Fused-kernel twin of :func:`_predict_stack`: the whole ensemble
    walks one Pallas launch (pallas_kernels.predict_forest_tpu), leaf
    sums accumulated in VMEM instead of a T-step scan of gather
    chains. Weights fold into the value plane so the kernel carries
    one fewer operand. Selected per shape class by the measured
    prober (gbdt/predict_route.py), never called directly."""
    from synapseml_tpu.gbdt import pallas_kernels

    feat, thr, left, right, value = stack
    return pallas_kernels.predict_forest_tpu(
        x, feat, thr, left, right, value * weights[:, None], k=k)


def _predict_stack_routed(stack, weights, x, k: int, t: int):
    """Route one ensemble predict through the measured prober: the
    fused Pallas traversal where a verified verdict says it wins, the
    XLA scan everywhere else. A kernel-leg failure at dispatch time
    demotes the shape class (persisted) and silently re-runs XLA —
    scoring never degrades, it just doesn't accelerate."""
    from synapseml_tpu.gbdt import predict_route

    backend = predict_route.route_predict(
        x.shape[0], t, stack[0].shape[1], x.shape[1], k, count=False)
    if backend == "pallas":
        try:
            # materialize INSIDE the try: jax dispatch is async, so an
            # execute-time kernel fault would otherwise surface at the
            # caller's np.asarray — outside the fallback
            out = jax.block_until_ready(
                _predict_stack_pallas(stack, weights, x, k, t))
            predict_route.count("pallas")
            return out
        except Exception:  # noqa: BLE001 - silent fallback is the contract
            predict_route.poison(x.shape[0], t, stack[0].shape[1],
                                 x.shape[1], k)
    # counted by the backend that ACTUALLY served (catalog contract):
    # a kernel-leg failure lands here and counts xla, not pallas
    predict_route.count("xla")
    return _predict_stack(stack, weights, x, k, t)


@partial(jax.jit, static_argnums=(5, 6))
def _predict_stack_cat(stack, weights, x, bitsets, bounds, k: int, t: int):
    """Predict scan for models with categorical splits: a cat node routes
    LEFT iff int(x) is in its bitset (LightGBM FindInBitset semantics);
    NaN, negative, and out-of-range categories go right."""
    n = x.shape[0]
    n_words = bitsets.shape[0]

    def body(carry, tree_w):
        (feat, thr, left, right, value, cat), w, idx = tree_w
        node = jnp.zeros(n, jnp.int32)
        max_depth = feat.shape[0] // 2 + 1

        def step(_, node):
            is_leaf = feat[node] < 0
            xv = x[jnp.arange(n), feat[node].clip(0)]
            ci = cat[node]                       # [n] cat-set id or -1
            num_left = xv <= thr[node]
            v = jnp.nan_to_num(xv, nan=-1.0).astype(jnp.int32)
            start = bounds[ci.clip(0)]
            width = (bounds[ci.clip(0) + 1] - start) * 32
            word = bitsets[jnp.clip(start + jnp.clip(v, 0) // 32, 0,
                                    n_words - 1)]
            in_set = ((word >> (jnp.clip(v, 0) % 32).astype(jnp.uint32))
                      & jnp.uint32(1)).astype(jnp.bool_)
            cat_left = in_set & (v >= 0) & (v < width)
            go_left = jnp.where(ci >= 0, cat_left, num_left)
            nxt = jnp.where(go_left, left[node], right[node])
            return jnp.where(is_leaf, node, nxt)

        node = lax.fori_loop(0, max_depth, step, node)
        pred = value[node] * w
        carry = carry.at[:, idx % k].add(pred)
        return carry, None

    out = jnp.zeros((n, k), jnp.float32)
    idxs = jnp.arange(t, dtype=jnp.int32)
    out, _ = jax.lax.scan(body, out, (stack, weights, idxs))
    return out


@jax.jit
def _leaf_index_stack(stack, x):
    def body(_, tree):
        feat, thr, left, right = tree
        n = x.shape[0]
        node = jnp.zeros(n, jnp.int32)
        max_depth = feat.shape[0] // 2 + 1

        def step(i, node):
            is_leaf = feat[node] < 0
            xv = x[jnp.arange(n), feat[node].clip(0)]
            nxt = jnp.where(xv <= thr[node], left[node], right[node])
            return jnp.where(is_leaf, node, nxt)

        node = jax.lax.fori_loop(0, max_depth, step, node)
        return None, node

    _, leaves = jax.lax.scan(body, None, stack)
    return leaves.T


def _compute_chunk(p: BoostParams, tracker, track_rank: bool,
                   total_iters: int, nv: int) -> int:
    """Chunk sizing shared by the single-chip and mesh loops: one scan when
    nothing can stop early; otherwise chunks so an early exit wastes at most
    one chunk; rank-metric chunks bounded to ~16MB of margin snapshot."""
    esr = p.early_stopping_round
    chunk = max(esr, 16) if (tracker.enabled and esr > 0) else total_iters
    if track_rank:
        chunk = min(chunk, max(1, 4_000_000 // max(1, nv)))
    return max(1, min(chunk, total_iters))


def _prepend_init_trees(init_model: Optional["Booster"], stacked):
    """Prepend init_model's trees so the result is one whole booster
    (the batch-model threading / resume half, shared by the single-chip
    and mesh trainers)."""
    if init_model is None:
        return stacked
    m_new = stacked.split_feature.shape[1]
    m_old = init_model.trees_feature.shape[1]
    m = max(m_new, m_old)

    def padc(a, fill):
        w = m - a.shape[1]
        return a if w == 0 else np.pad(
            a, ((0, 0), (0, w)), constant_values=fill)

    return Tree(
        split_feature=np.concatenate(
            [padc(init_model.trees_feature, -1),
             padc(stacked.split_feature, -1)]),
        threshold=np.concatenate(
            [padc(init_model.trees_threshold, 0),
             padc(stacked.threshold, 0)]),
        threshold_bin=np.concatenate(
            [padc(np.zeros_like(init_model.trees_feature), 0),
             padc(stacked.threshold_bin, 0)]),
        left_child=np.concatenate(
            [padc(init_model.trees_left, 0), padc(stacked.left_child, 0)]),
        right_child=np.concatenate(
            [padc(init_model.trees_right, 0),
             padc(stacked.right_child, 0)]),
        leaf_value=np.concatenate(
            [padc(init_model.trees_value
                  * init_model.tree_weights[:, None], 0),
             padc(stacked.leaf_value, 0)]),
        cover=np.concatenate(
            [padc(init_model.trees_cover, 0), padc(stacked.cover, 0)]),
        gain=np.concatenate(
            [padc(init_model.trees_gain, 0), padc(stacked.gain, 0)]),
    )


def _pad_lr_schedule(lrs: np.ndarray) -> np.ndarray:
    """Double the schedule with its last value: chunked scans read past
    num_iterations on surplus steps of the final chunk."""
    lrs = np.asarray(lrs, np.float32)
    return np.concatenate([lrs, np.repeat(lrs[-1:], len(lrs))])


def _attach_init_categoricals(booster: "Booster",
                              init_model: Optional["Booster"]) -> "Booster":
    """Carry a categorical init_model's split sets into the combined
    booster. The trainer itself only emits numeric splits, so the merge
    is one-sided: old nodes keep their set indices into the init pool
    (copied verbatim), new trees are all -1 (numeric). Parity target:
    lib_lightgbm continues from categorical models transparently
    (ref: lightgbm/.../LightGBMBase.scala:49-61 setModelString)."""
    if init_model is None or init_model.trees_cat is None:
        return booster
    t_old, m_old = init_model.trees_cat.shape
    t_total, m = booster.trees_feature.shape
    cat = np.full((t_total, m), -1, np.int32)
    cat[:t_old, :m_old] = init_model.trees_cat
    booster.trees_cat = cat
    booster.cat_bitsets = np.array(init_model.cat_bitsets, np.uint32)
    booster.cat_boundaries = np.array(init_model.cat_boundaries, np.int32)
    return booster


def _chunk_callbacks(checkpoint_dir, init_model, p, k, init, f,
                     feature_names, tracker, iteration_hook):
    """Compose the per-chunk checkpoint writer and iteration observer —
    shared by the single-chip and mesh trainers so checkpoint semantics
    (init-tree prepending, best_iteration shifting, atomic save) cannot
    drift between them."""
    ckpt = None
    if checkpoint_dir is not None:
        acc: List = []

        def ckpt(chunk_trees, iters_done):
            acc.append(chunk_trees)
            stacked = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(xs, axis=0), *acc)
            booster = _assemble_booster(
                _prepend_init_trees(init_model, stacked), p, k, init, f,
                feature_names, tracker, compute_importances=False,
                init_model=init_model)
            if init_model is not None and booster.best_iteration >= 0:
                booster.best_iteration += init_model.num_iterations
            save_checkpoint(checkpoint_dir, booster, iters_done,
                            p.num_iterations)
    if ckpt is None and iteration_hook is None:
        return None

    def on_chunk(chunk_trees, iters_done):
        if ckpt is not None:
            ckpt(chunk_trees, iters_done)
        if iteration_hook is not None:
            iteration_hook(min(iters_done, p.num_iterations))
    return on_chunk


def _chunked_boost_loop(run, carry, tracker, p: BoostParams, k: int,
                        total_iters: int, chunk: int, track_dev: bool,
                        track_rank: bool, vy_h, vg_h, on_chunk=None,
                        on_stop=None):
    """Drive the jitted chunk scans; metrics/early-stop applied host-side.

    ``run(carry, steps, chunk_start_iter) -> (carry, ys)`` where ``ys[0]``
    is the stacked tree pytree and ``ys[1]`` (when tracking) the per-step
    metric or margin snapshot. Every chunk is full-length — a shorter
    remainder would recompile the scan — and surplus steps are sliced off.
    Returns the stacked trees truncated to the kept steps.
    """
    tree_chunks = []
    stop_steps: Optional[int] = None
    done_iters = 0
    while done_iters < total_iters and stop_steps is None:
        steps = jnp.arange(done_iters * k, (done_iters + chunk) * k)
        carry, ys = run(carry, steps, done_iters)
        # one batched device->host fetch: per-leaf np.asarray pays a full
        # tunnel round trip per array (~8x latency on remote chips); the
        # metric snapshot rides the same fetch when tracking is on
        fetched = jax.device_get(ys if (track_dev or track_rank)
                                 else ys[:1])
        tree_chunks.append(fetched[0])
        n_it = min(chunk, total_iters - done_iters)
        if track_dev:
            per_iter = fetched[1][k - 1::k][:n_it]
        elif track_rank:
            vsnap = fetched[1]  # [chunk, Nv]; k == 1 for ranking
            per_iter = [
                _ndcg_score(vsnap[i], vy_h, vg_h, p.max_position,
                            blocks=tracker.ndcg_blocks)
                for i in range(n_it)
            ]
        else:
            per_iter = []
        for i, m in enumerate(per_iter):
            if tracker.record(float(m), done_iters + i):
                stop_steps = (done_iters + i + 1) * k
                break
        done_iters += chunk
        if on_chunk is not None and stop_steps is None:
            # hand over only this chunk's kept trees; the callback
            # accumulates (keeps checkpoint overhead linear per chunk)
            kept = max(0, min(done_iters, total_iters)
                       - (done_iters - chunk)) * k
            on_chunk(
                jax.tree_util.tree_map(lambda a: a[:kept], tree_chunks[-1]),
                min(done_iters, total_iters))
    if stop_steps is not None and on_stop is not None:
        # early stop skips on_chunk (a stopped run must not checkpoint);
        # iteration observers still need to hear about the kept iterations
        on_stop(stop_steps // k)
    stacked = jax.tree_util.tree_map(
        lambda *xs: np.concatenate(xs, axis=0), *tree_chunks)
    keep = stop_steps if stop_steps is not None else total_iters * k
    return jax.tree_util.tree_map(lambda a: a[:keep], stacked)


def _assemble_booster(stacked, p: BoostParams, k: int, init: float, f: int,
                      feature_names, tracker, dart_w_final=None,
                      compute_importances: bool = True,
                      init_model: Optional["Booster"] = None) -> Booster:
    """``init_model`` (continuation) also carries its categorical split
    sets into the combined booster — attached HERE so every assembly
    site (single-chip, mesh, checkpoint writer) shares the semantics."""
    t_total = stacked.split_feature.shape[0]
    if dart_w_final is not None:
        tree_weights = np.asarray(dart_w_final[:t_total], np.float32)
    else:
        is_rf = p.boosting_type == "rf"
        tree_weights = np.full(
            t_total, 1.0 / (t_total / max(k, 1)) if is_rf else 1.0,
            np.float32)
    booster = Booster(
        trees_feature=stacked.split_feature,
        trees_threshold=stacked.threshold,
        trees_left=stacked.left_child,
        trees_right=stacked.right_child,
        trees_value=stacked.leaf_value,
        trees_cover=stacked.cover,
        trees_gain=stacked.gain,
        tree_weights=tree_weights,
        params=p,
        init_score=init,
        num_class=k,
        best_iteration=tracker.final_best_iter(),
        num_features=f,
        feature_names=feature_names,
        eval_history=tracker.history,
    )
    if compute_importances:
        booster.feature_importance_split, booster.feature_importance_gain = (
            _importances(booster, f))
    return _attach_init_categoricals(booster, init_model)


@lru_cache(maxsize=64)
def _make_scan_fn(p: BoostParams, gp: GrowerParams, k: int, track: bool,
                  track_dev: bool, track_rank: bool,
                  metric_name: Optional[str], blocked_rank: bool = False,
                  use_lr_schedule: bool = False):
    """Build (and cache) the jitted chunked-scan trainer for one static
    config. Data rides in through the ``consts`` argument, so repeated fits
    with the same hyperparameters reuse the compiled executable instead of
    re-tracing a fresh closure per ``fit`` call."""
    obj_fn = _objective_fn(p)
    is_rank = p.objective in ("lambdarank", "rank_xendcg")
    use_goss = p.boosting_type == "goss"
    is_rf = p.boosting_type == "rf"
    strat_bagging = (p.pos_bagging_fraction < 1.0
                     or p.neg_bagging_fraction < 1.0)
    use_bagging = (p.bagging_freq > 0
                   and (p.bagging_fraction < 1.0 or strat_bagging)) or is_rf
    feature_frac = p.feature_fraction
    renew_alpha = None
    if k == 1 and p.objective in ("regression_l1", "l1", "mae"):
        renew_alpha = 0.5
    elif k == 1 and p.objective == "quantile":
        renew_alpha = p.alpha
    metric_fn = (obj.METRICS.get(metric_name, (None, False))[0]
                 if metric_name else None)
    axis_name = None
    bdev = gp.max_bin

    def scan(carry, steps, consts):
        binned, yd, wd = consts["binned"], consts["yd"], consts["wd"]
        group_ids, thresholds = consts["gids"], consts["thr"]
        init = consts["init"]
        vx_d, vy_d = consts["vx"], consts["vy"]
        n, f = binned.shape
        y_onehot = jax.nn.one_hot(yd.astype(jnp.int32), k) if k > 1 else None

        def compute_grad(scores, class_idx):
            if k > 1:
                g, h = obj_fn(scores, y_onehot, wd)
                return g[:, class_idx], h[:, class_idx]
            if is_rank:
                if blocked_rank:
                    # block-diagonal: O(N*Gmax) — the dense pair matrix
                    # would be O(N^2) over the whole dataset
                    g, h = obj.lambdarank_grad_blocked(
                        scores, yd, consts["qidx"], consts["qmask"],
                        consts["qinv"], max_dcg_pos=p.max_position)
                else:  # pathological skew: dense is cheaper
                    g, h = obj.lambdarank_grad(
                        scores, yd, group_ids, max_dcg_pos=p.max_position)
                if wd is not None:
                    g, h = g * wd, h * wd
                return g, h
            return obj_fn(scores, yd, wd)

        def sample_mask_and_weights(grad, hess, key):
            """bagging / GOSS row selection; returns (mask, grad, hess)."""
            if use_goss:
                a, b = p.top_rate, p.other_rate
                n_top = max(1, int(a * n))
                thresh = -jnp.sort(-jnp.abs(grad))[n_top - 1]
                top = jnp.abs(grad) >= thresh
                rand = jax.random.uniform(key, (n,)) < b
                amp = (1.0 - a) / max(b, 1e-12)
                small = (~top) & rand
                mask = top | small
                g = jnp.where(small, grad * amp, grad)
                h = jnp.where(small, hess * amp, hess)
                return mask, g, h
            if use_bagging:
                bkey = (key if p.bagging_seed is None
                        else jax.random.fold_in(key, p.bagging_seed))
                u = jax.random.uniform(bkey, (n,))
                if strat_bagging and not is_rf:
                    # per-class subsampling (LightGBM pos/neg bagging:
                    # binary labels, no gradient amplification)
                    mask = jnp.where(yd > 0,
                                     u < p.pos_bagging_fraction,
                                     u < p.neg_bagging_fraction)
                    return mask, grad, hess
                frac = p.bagging_fraction if not is_rf else (
                    p.bagging_fraction if p.bagging_fraction < 1.0 else 0.632)
                mask = u < frac
                return mask, grad, hess
            return jnp.ones(n, jnp.bool_), grad, hess

        def feature_mask(key):
            if feature_frac >= 1.0:
                return None
            keep = max(1, int(round(feature_frac * f)))
            perm = jax.random.permutation(key, f)
            mask = jnp.zeros(f, jnp.bool_).at[perm[:keep]].set(True)
            return mask

        def iteration(scores, key, class_idx, lr_it=None):
            base = jnp.full_like(scores, init) if is_rf else scores
            g, h = compute_grad(base, class_idx)
            k1, k2 = jax.random.split(key)
            mask, g2, h2 = sample_mask_and_weights(g, h, k1)
            fmask = feature_mask(k2)
            gb = binned
            if fmask is not None:
                # masked-out features get the missing bin -> never split
                gb = jnp.where(fmask[None, :], binned, bdev - 1)
            tree, row_slot, slot_value, slot_node = build_tree(
                gb, g2, h2, mask, thresholds, gp, axis_name)
            if renew_alpha is not None:
                # L1-family leaf renewal (LightGBM RenewTreeOutput): leaf
                # output := alpha-quantile of residuals of rows in the leaf.
                residual = yd - scores

                def leaf_quantile(slot):
                    r = jnp.where(row_slot == slot, residual, jnp.nan)
                    return jnp.nanquantile(r, renew_alpha)

                renewed = jax.vmap(leaf_quantile)(jnp.arange(gp.num_leaves))
                slot_value = jnp.where(jnp.isnan(renewed), slot_value, renewed)
                # rebuild node-level leaf values from renewed slot values
                m_nodes = tree.leaf_value.shape[0]
                nsel = ((slot_node[:, None] == jnp.arange(m_nodes))
                        & (slot_node >= 0)[:, None])
                new_leaf = jnp.sum(nsel * slot_value[:, None], axis=0)
                tree = Tree(
                    split_feature=tree.split_feature, threshold=tree.threshold,
                    threshold_bin=tree.threshold_bin,
                    left_child=tree.left_child,
                    right_child=tree.right_child, leaf_value=new_leaf,
                    cover=tree.cover, gain=tree.gain)
            if is_rf:
                lr = 1.0
            elif lr_it is not None:  # delegate-driven per-iteration LR
                lr = lr_it
            else:
                lr = p.learning_rate
            delta = lr * slot_value[row_slot]
            if k > 1:
                # one-hot column add (a traced-column scatter is a
                # fixed-latency op per call; this is a fused select)
                new_scores = scores + delta[:, None] * jax.nn.one_hot(
                    class_idx, k, dtype=scores.dtype)
            else:
                new_scores = scores + delta
            scaled = Tree(
                split_feature=tree.split_feature,
                threshold=tree.threshold,
                threshold_bin=tree.threshold_bin,
                left_child=tree.left_child,
                right_child=tree.right_child,
                leaf_value=tree.leaf_value * lr,
                cover=tree.cover,
                gain=tree.gain,
            )
            return new_scores, scaled

        def scan_step(carry, step):
            scores, vsum, rng = carry
            rng, key = jax.random.split(rng)
            c = step % k
            it = step // k
            lr_it = consts["lrs"][it] if use_lr_schedule else None
            new_scores, tree = iteration(scores, key, c, lr_it)
            out: Tuple = (tree,)
            if track:
                vt = predict_tree(
                    (tree.split_feature, tree.threshold, tree.left_child,
                     tree.right_child, tree.leaf_value), vx_d)
                vsum = vsum + vt[:, None] * jax.nn.one_hot(
                    c, k, dtype=vsum.dtype)
            if track_dev:
                scale = (1.0 / (it + 1.0)) if is_rf else 1.0
                vscore = vsum * scale + init
                if k > 1:
                    m = metric_fn(vscore, vy_d.astype(jnp.int32))
                else:
                    m = metric_fn(vscore[:, 0], vy_d)
                out = out + (m,)
            elif track_rank:
                out = out + (vsum[:, 0],)
            return (new_scores, vsum, rng), out

        return jax.lax.scan(scan_step, carry, steps)

    return jax.jit(scan, donate_argnums=0)


def train(
    p: BoostParams,
    x: np.ndarray,
    y: np.ndarray,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    valid_sets: Sequence[Tuple[np.ndarray, np.ndarray]] = (),
    feature_names: Optional[List[str]] = None,
    mesh=None,
    init_model: Optional[Booster] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    learning_rates: Optional[np.ndarray] = None,
    iteration_hook=None,
) -> Booster:
    """Train a Booster. ``mesh`` enables dp-sharded histogram training.

    ``init_model`` continues boosting from an existing booster's margins —
    the reference's batch-model threading (``setModelString``,
    ref: lightgbm/.../LightGBMBase.scala:49-61) and the resume half of
    step-level checkpointing. ``checkpoint_dir`` + ``checkpoint_every``
    write a loadable partial model every N iterations (see
    :func:`save_checkpoint`/:func:`load_checkpoint`); a killed run resumes
    via ``load_checkpoint`` + ``init_model``.

    ``learning_rates`` is an optional per-iteration shrinkage schedule
    (the delegate's dynamic-LR hook, ref: LightGBMDelegate.scala
    getLearningRate:57-61); it rides the scan as data so every schedule
    reuses one compiled trainer. ``iteration_hook(iters_done)`` fires at
    every device-chunk boundary — the TPU loop runs whole chunks on
    device, so this is the granularity at which the reference's
    afterTrainIteration callback surfaces here.
    """
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float32)
    n, f = x.shape
    k = p.num_class if p.objective in ("multiclass", "softmax", "multiclassova") else 1
    if ((p.pos_bagging_fraction < 1.0 or p.neg_bagging_fraction < 1.0)
            and p.objective not in ("binary", "binary_logloss")):
        # stratified bagging splits rows by label sign — only meaningful
        # (and only defined by LightGBM) for binary objectives
        raise ValueError(
            "pos_bagging_fraction/neg_bagging_fraction require a binary "
            f"objective, got {p.objective!r}")

    mapper = BinMapper(max_bin=p.max_bin,
                       categorical_features=p.categorical_features,
                       subsample=p.bin_sample_count,
                       seed=p.seed).fit(x)
    binned_np = mapper.transform(x)
    bdev = mapper.total_bins
    gp = dataclasses.replace(p.grower(), max_bin=bdev)
    if gp.hist_backend == "auto":
        # route the hot op on a cached in-context measurement, not a
        # remembered experiment (see grower.resolve_hist_backend). On a
        # dp mesh each shard builds histograms over n/dp rows — probe the
        # shape that actually executes. Fits too small to amortize the
        # probe skip it (the fit_row_visits hint).
        from synapseml_tpu.gbdt.grower import resolve_hist_backend
        n_shard = n
        if mesh is not None and "dp" in mesh.axis_names:
            n_shard = max(1, n // int(mesh.shape["dp"]))
        gp = dataclasses.replace(
            gp, hist_backend=resolve_hist_backend(
                n_shard, f, bdev,
                fit_row_visits=n_shard * p.num_iterations * k
                * p.num_leaves))
    thresholds = jnp.asarray(mapper.threshold_values(), jnp.float32)

    init = _init_score(p, y, weight)
    obj_fn = _objective_fn(p)
    is_rank = p.objective in ("lambdarank", "rank_xendcg")

    # -- distributed (data-parallel) path --------------------------------
    # Rows shard over the mesh's dp axis; per-shard histograms are psum'ed
    # over ICI inside build_tree, after which every rank takes identical
    # split decisions (the TPU-native replacement for the reference's
    # tree_learner=data_parallel socket reduce-scatter, SURVEY.md 2.10).
    # Dispatch happens BEFORE any host->device transfer so the large [N,F]
    # matrix is only placed once, with its mesh sharding.
    # init_model validation + margins, shared by both dispatch paths
    init, init_margins = _resume_state(p, init_model, k, x, init)
    _validate_loop_extras(p, checkpoint_dir)
    learning_rates = _validate_lr_schedule(p, learning_rates)

    if mesh is not None:
        return _train_distributed(
            p, mesh, binned_np, y, weight, k, init, obj_fn, gp, bdev,
            thresholds, valid_sets, feature_names, group=group,
            init_model=init_model, init_margins=init_margins,
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            iteration_hook=iteration_hook, learning_rates=learning_rates)

    binned = jnp.asarray(binned_np)
    yd = jnp.asarray(y)
    wd = jnp.asarray(weight, jnp.float32) if weight is not None else None
    group_ids = jnp.asarray(group, jnp.int32) if group is not None else None
    is_rf = p.boosting_type == "rf"

    if init_margins is not None:
        # continue from the existing margins (validated above)
        scores = jnp.asarray(
            init_margins if k > 1 else init_margins[:, 0], jnp.float32)
    elif k > 1:
        scores = jnp.zeros((n, k), jnp.float32) + init
    else:
        scores = jnp.zeros(n, jnp.float32) + init

    if p.boosting_type == "dart":
        return _train_dart(p, binned, yd, wd, obj_fn, gp, thresholds, init,
                           n, f, valid_sets, feature_names, k=k)

    # -- validation state ----------------------------------------------
    tracker = _ValidTracker(p, k, init, valid_sets)

    # -- device-resident boosting loop ---------------------------------
    # The whole loop runs as lax.scan chunks: trees stream out as stacked
    # arrays, validation margins accumulate in the carry, and the host sees
    # one transfer per chunk — instead of a device->host round trip per tree,
    # which dominates wall-clock when the chip sits behind a network tunnel.
    # (TPU-native replacement for trainCore's per-iteration native calls,
    # ref: lightgbm/.../TrainUtils.scala:92-159.)
    track_dev = tracker.enabled and not tracker.is_rank_metric
    track_rank = tracker.enabled and tracker.is_rank_metric
    if tracker.enabled:
        vg_h = tracker.sets[0][3]
        vsum0 = tracker.sets[0][2]
        vy_h = np.asarray(tracker.sets[0][1])
        if init_model is not None:
            # valid margins must include the resumed model's contribution
            # (full stack, not best_iteration-truncated — see above)
            vraw = init_model.predict_raw(
                np.asarray(tracker.sets[0][0]),
                num_iteration=init_model.num_iterations)
            vsum0 = jnp.asarray(
                vraw.reshape(-1, k) - init, jnp.float32)
    else:
        vsum0 = jnp.zeros((0, k), jnp.float32)

    blocked_rank = False
    qidx = qmask = qinv = None
    if is_rank:
        if group is None:
            raise ValueError("ranking objectives need a group array")
        qidx_np, qmask_np, qinv_np = obj.build_query_blocks(group)
        q, gmax = qidx_np.shape
        # blocked is O(Q*Gmax^2): a skewed group-size distribution (one
        # huge query among many tiny ones) can exceed the dense O(N^2)
        # pair matrix it replaces — use whichever is cheaper
        blocked_rank = q * gmax * gmax <= n * n and q * gmax <= 8 * n
        if blocked_rank:
            qidx, qmask, qinv = (jnp.asarray(qidx_np),
                                 jnp.asarray(qmask_np),
                                 jnp.asarray(qinv_np))
    use_lr_schedule = learning_rates is not None
    lrs_d = None
    if use_lr_schedule:
        # schedule type/shape validated before mesh dispatch above;
        # chunked scans index past num_iterations on the final (surplus)
        # steps, so pad with the last value to keep those reads in range
        lrs_d = jnp.asarray(_pad_lr_schedule(learning_rates))
    consts = dict(
        binned=binned, yd=yd, wd=wd, gids=group_ids, thr=thresholds,
        init=jnp.float32(init), lrs=lrs_d,
        qidx=qidx, qmask=qmask, qinv=qinv,
        vx=tracker.sets[0][0] if tracker.enabled else None,
        vy=tracker.sets[0][1] if tracker.enabled else None)
    # normalize cache-key fields the traced scan never reads (seed, iteration
    # counts, binning/categorical config) so e.g. a 100-seed ensemble reuses
    # one compiled trainer instead of compiling 100
    key_p = dataclasses.replace(
        p, seed=0, num_iterations=1, early_stopping_round=0, verbosity=-1,
        categorical_features=(), metric=None, max_bin=0, bin_sample_count=0,
        deterministic=True,
        # with a schedule the static base LR is never read either
        learning_rate=0.0 if use_lr_schedule else p.learning_rate)
    scan_fn = _make_scan_fn(
        key_p, gp, k, tracker.enabled, track_dev, track_rank,
        tracker.metric_name if tracker.enabled else None,
        blocked_rank=blocked_rank, use_lr_schedule=use_lr_schedule)

    total_iters = p.num_iterations
    chunk = _compute_chunk(p, tracker, track_rank, total_iters,
                           int(vsum0.shape[0]))
    if checkpoint_dir is not None and checkpoint_every > 0:
        chunk = min(chunk, max(1, int(checkpoint_every)))

    on_chunk = _chunk_callbacks(checkpoint_dir, init_model, p, k, init, f,
                                feature_names, tracker, iteration_hook)

    carry = (scores, vsum0, jax.random.PRNGKey(p.seed))
    stacked = _chunked_boost_loop(
        lambda c, steps, start: scan_fn(c, steps, consts),
        carry, tracker, p, k, total_iters, chunk, track_dev, track_rank,
        vy_h if tracker.enabled else None,
        vg_h if tracker.enabled else None, on_chunk=on_chunk,
        on_stop=iteration_hook)
    booster = _assemble_booster(
        _prepend_init_trees(init_model, stacked), p, k, init, f,
        feature_names, tracker, init_model=init_model)
    if init_model is not None and booster.best_iteration >= 0:
        # best_iteration indexes the combined tree stack
        booster.best_iteration += init_model.num_iterations
    return booster


def row_sharded_mesh_ok(mesh) -> bool:
    """Whether :func:`train_row_sharded` can honor ``mesh``: a 1-axis dp
    mesh whose devices are process-contiguous, in process order, with
    equal per-process counts. ``fit_aggregated``'s auto routing falls
    back to the gather path for meshes that fail this (rather than
    breaking callers who relied on the gather path accepting any mesh)."""
    if mesh is None:
        return True
    if ("dp" not in mesh.axis_names
            or mesh.devices.size != int(mesh.shape["dp"])):
        return False
    by_proc: Dict[int, List[int]] = {}
    for i, d in enumerate(mesh.devices.reshape(-1)):
        by_proc.setdefault(d.process_index, []).append(i)
    sizes = {len(v) for v in by_proc.values()}
    if len(sizes) != 1:
        return False
    if not all(v == list(range(v[0], v[0] + len(v)))
               for v in by_proc.values()):
        return False
    per = sizes.pop()
    starts = [min(v) for _, v in sorted(by_proc.items())]
    return starts == [i * per for i in range(len(by_proc))]


def _init_score_sync(p: BoostParams, y, weight):
    """boost_from_average over ALL hosts' rows, from host-local labels.

    Mean-family objectives exchange two float64 sums per host; the
    quantile family (quantile/l1/huber/mape) needs the full label
    distribution, so the 1-D label vector rides DCN once (8 bytes/row —
    the feature matrix never moves)."""
    if not p.boost_from_average:
        return 0.0
    if p.objective in ("multiclass", "softmax", "multiclassova",
                       "lambdarank", "rank_xendcg"):
        return 0.0
    from synapseml_tpu.parallel.distributed import host_allgather_rows

    if p.objective in ("quantile", "regression_l1", "l1", "mae", "huber",
                       "mape"):
        # gather at the train loop's float32 width so the quantile math
        # is bit-identical to the single-host _init_score
        y_g = host_allgather_rows(np.asarray(y, np.float32))
        if p.objective == "quantile":
            return float(np.quantile(y_g, p.alpha))
        return float(np.median(y_g))
    y = np.asarray(y, np.float64)
    w = weight if weight is not None else np.ones_like(y)
    sums = host_allgather_rows(np.asarray(
        [[float(np.sum(np.asarray(w, np.float64) * y)),
          float(np.sum(np.asarray(w, np.float64)))]], np.float64))
    mean = float(sums[:, 0].sum()) / max(float(sums[:, 1].sum()), 1e-300)
    if p.objective in ("binary", "binary_logloss"):
        pbar = float(np.clip(mean, 1e-12, 1 - 1e-12))
        return float(np.log(pbar / (1 - pbar)) / p.sigmoid)
    if p.objective in ("poisson", "tweedie"):
        return float(np.log(max(mean, 1e-12)))
    return mean


def train_row_sharded(
    p: BoostParams,
    x: np.ndarray,
    y: np.ndarray,
    weight: Optional[np.ndarray] = None,
    group: Optional[np.ndarray] = None,
    valid_sets: Sequence[Tuple[np.ndarray, np.ndarray]] = (),
    feature_names: Optional[List[str]] = None,
    mesh=None,
    init_model: Optional[Booster] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    learning_rates: Optional[np.ndarray] = None,
    iteration_hook=None,
    stats_out: Optional[Dict[str, Any]] = None,
) -> Booster:
    """Multi-host data-parallel training where ROWS NEVER LEAVE THEIR HOST.

    The defining property of the reference's ``tree_learner=data_parallel``
    (ref: lightgbm/.../LightGBMBase.scala:482-486 — each Spark task streams
    only its own partition into a local native dataset;
    TrainUtils.scala:279-295 — only fixed-size histograms cross the
    network): ``x``/``y``/``weight``/``group`` here are THIS process's rows
    only. What crosses DCN:

    - a bin-boundary sample capped at ``p.bin_sample_count`` rows *total*
      (LightGBM's ``bin_construct_sample_cnt`` — the native engine also
      constructs distributed bin bounds from a synced sample);
    - two float64 label sums for the init score (or the 1-D label vector,
      for quantile-family objectives);
    - per-iteration ``[F, B, 3]`` histogram psums + split decisions over
      the dp axis — fixed-size, independent of total row count.

    No process ever materializes the global ``[N, F]`` matrix: each host
    bins its rows to uint8 locally and places them on its own devices
    (``jax.make_array_from_single_device_arrays``), so peak per-host
    memory is O(local rows + bin sample), where :func:`fit_aggregated`'s
    gather fallback is O(total rows).

    Identity: when the job's total rows fit the bin-sample budget and
    partitions are in rank order, the gathered sample IS the dataset, bins
    match a single-process fit exactly, and (histograms being placement-
    invariant under psum) the booster is bit-identical to ``train``'s.
    Larger jobs get sample-quantile bins — LightGBM's own distributed
    semantics. ``valid_sets`` must be identical on every host (replicated,
    like the reference's eval partition). Works single-process too (rows
    shard over local devices).

    ``stats_out`` (optional dict) receives layout/traffic accounting so
    callers and tests can assert the no-replication property.
    """
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    from synapseml_tpu.parallel.distributed import host_allgather_rows

    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float32)
    n_local, f = x.shape
    k = (p.num_class
         if p.objective in ("multiclass", "softmax", "multiclassova") else 1)
    if weight is not None and len(weight) != n_local:
        raise ValueError("weight length != row count")
    nproc = jax.process_count()
    pidx = jax.process_index()
    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    if "dp" not in mesh.axis_names or mesh.devices.size != int(
            mesh.shape["dp"]):
        raise ValueError(
            "train_row_sharded needs a 1-axis 'dp' mesh over the job's "
            "devices")

    flat = list(mesh.devices.reshape(-1))
    n_dev = len(flat)
    my_pos = sorted(i for i, d in enumerate(flat)
                    if d.process_index == pidx)
    if not my_pos:
        raise ValueError("this process has no devices in the mesh")
    if my_pos != list(range(my_pos[0], my_pos[0] + len(my_pos))):
        raise ValueError(
            "row-sharded training needs each process's devices contiguous "
            "on the dp axis (the default Mesh over jax.devices() is)")
    n_local_dev = len(my_pos)
    dev_counts = host_allgather_rows(
        np.asarray([n_local_dev], np.int64)).reshape(-1)
    if len({int(c) for c in dev_counts}) != 1:
        raise ValueError("unequal per-process device counts in the mesh")

    # -- bin boundaries from a capped, synced sample ---------------------
    n_all = host_allgather_rows(np.asarray([n_local], np.int64)).reshape(-1)
    n_total = int(n_all.sum())
    if n_total == 0:
        raise ValueError("no rows to fit: every host's partition was empty")
    budget = max(int(p.bin_sample_count), 1)
    if n_total <= budget:
        # the whole (possibly unbalanced) dataset fits the budget: every
        # host contributes ALL its rows, preserving the bit-exact
        # identity with a single-process fit regardless of skew
        sample = x
    else:
        # proportional cap: each host's share of the budget matches its
        # share of the rows (LightGBM's distributed sampling semantics)
        per_host_budget = max(1, int(budget * n_local / n_total))
        srng = np.random.default_rng(p.seed * 1000003 + pidx)
        sample = x[np.sort(srng.choice(n_local,
                                       min(per_host_budget, n_local),
                                       replace=False))]
    sample_g = host_allgather_rows(sample)
    mapper = BinMapper(max_bin=p.max_bin,
                       categorical_features=p.categorical_features,
                       subsample=budget, seed=p.seed).fit(sample_g)
    binned_local = mapper.transform(x)
    bdev = mapper.total_bins
    thresholds = jnp.asarray(mapper.threshold_values(), jnp.float32)

    gp = dataclasses.replace(p.grower(), max_bin=bdev)
    if gp.hist_backend == "auto":
        from synapseml_tpu.gbdt.grower import resolve_hist_backend
        n_shard = max(1, n_total // n_dev)
        gp = dataclasses.replace(gp, hist_backend=resolve_hist_backend(
            n_shard, f, bdev,
            fit_row_visits=n_shard * p.num_iterations * k * p.num_leaves))

    init = _init_score_sync(p, y, weight)
    obj_fn = _objective_fn(p)
    is_rank = p.objective in ("lambdarank", "rank_xendcg")
    init, init_margins = _resume_state(p, init_model, k, x, init)
    _validate_loop_extras(p, checkpoint_dir)
    learning_rates = _validate_lr_schedule(p, learning_rates)

    # -- host-local layout: this host's rows onto its own devices --------
    if is_rank:
        if group is None:
            raise ValueError("ranking objectives need a group array")
        group = np.asarray(group)
        if group.shape[0] != n_local:
            raise ValueError("group length != row count")
        # disjoint per-host dense query ids (groups must not SPAN hosts —
        # the reference's group-aligned partitioning contract)
        uniq, inv = np.unique(group, return_inverse=True)
        q_counts = host_allgather_rows(
            np.asarray([len(uniq)], np.int64)).reshape(-1)
        shard_idx, dense_gid, loads = _pack_queries(inv, n_local_dev)
        dense_gid = dense_gid + int(q_counts[:pidx].sum())
        per_local = int(loads.max()) if len(loads) else 0
        per = max(1, int(host_allgather_rows(
            np.asarray([[per_local]], np.int64)).max()))
        per_host = per * n_local_dev
        (binned_l, y_l, w_l, margins_l, padm_l,
         gids_l) = _layout_shards(shard_idx, dense_gid, per, binned_local,
                                  y, weight, init_margins, bdev,
                                  neg_base=pidx * per_host)
    else:
        per_dev = -(-max(int(n_all.max()), 1) // n_local_dev)  # ceil
        per_host = per_dev * n_local_dev
        pad = per_host - n_local

        def pad_rows(arr, fill=0):
            if arr is None or pad == 0:
                return arr
            return np.concatenate(
                [arr, np.full((pad,) + arr.shape[1:], fill, arr.dtype)])
        binned_l = pad_rows(binned_local)
        y_l, w_l = pad_rows(y), pad_rows(weight)
        margins_l = pad_rows(init_margins)
        padm_l = np.zeros(per_host, bool)
        padm_l[:n_local] = True
        gids_l = None

    n_global = per_host * nproc
    per_dev_g = n_global // n_dev
    if my_pos[0] * per_dev_g != pidx * per_host:
        raise ValueError(
            "mesh device order does not match process order; use the "
            "default Mesh over jax.devices()")

    def make_global(local_np, spec):
        """Assemble the global row-sharded array from THIS host's rows."""
        shards = [
            jax.device_put(local_np[j * per_dev_g:(j + 1) * per_dev_g],
                           flat[i])
            for j, i in enumerate(my_pos)]
        return jax.make_array_from_single_device_arrays(
            (n_global,) + local_np.shape[1:], NamedSharding(mesh, spec),
            shards)

    row_spec, mat_spec = P("dp"), P("dp", None)
    if k > 1:
        yoh_g = make_global(
            np.eye(k, dtype=np.float32)[y_l.astype(np.int32)], mat_spec)
        scores_l = (margins_l.astype(np.float32) if margins_l is not None
                    else np.zeros((per_host, k), np.float32) + init)
        scores_g = make_global(scores_l, mat_spec)
    else:
        yoh_g = None
        scores_l = (margins_l[:, 0].astype(np.float32)
                    if margins_l is not None
                    else np.zeros(per_host, np.float32) + init)
        scores_g = make_global(scores_l, row_spec)
    placed = dict(
        n=n_global, f=f,
        binned=make_global(binned_l, mat_spec),
        yd=make_global(y_l.astype(np.float32), row_spec),
        wd=(make_global(w_l.astype(np.float32), row_spec)
            if w_l is not None else None),
        padm=make_global(padm_l, row_spec),
        gids=(make_global(gids_l.astype(np.int32), row_spec)
              if gids_l is not None else None),
        yoh=yoh_g, scores=scores_g)

    if stats_out is not None:
        stats_out.update(
            path="row_sharded",
            n_local=int(n_local), n_total=n_total, n_global=n_global,
            per_host_rows=int(per_host), n_features=int(f),
            binned_local_shape=tuple(binned_l.shape),
            sample_rows_sent=int(sample.shape[0]),
            sample_rows_gathered=int(sample_g.shape[0]),
            sample_gathered_bytes=int(sample_g.nbytes),
            addressable_row_bytes=sum(
                s.data.nbytes for s in placed["binned"].addressable_shards),
            hist_backend=gp.hist_backend)

    booster = _train_distributed(
        p, mesh, None, None, None, k, init, obj_fn, gp, bdev, thresholds,
        valid_sets, feature_names, group=None, init_model=init_model,
        init_margins=None, checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every, iteration_hook=iteration_hook,
        learning_rates=learning_rates, placed=placed)
    return booster


def save_checkpoint(path: str, booster: Booster, iterations_done: int,
                    total_iterations: int):
    """Atomic step-level checkpoint (the orbax-style step checkpoint
    SURVEY.md §5 calls for; the reference only threads whole batch models).

    One file, one os.replace: metadata and model can never disagree under
    a mid-write kill.
    """
    import os
    import tempfile

    os.makedirs(path, exist_ok=True)
    # serialize the FULL stack: the native writer truncates at
    # best_iteration, which would silently drop trees past the current
    # best during an early-stopping run; best_iteration rides in metadata
    full = dataclasses.replace(booster, best_iteration=-1)
    payload = json.dumps({
        "iterations_done": int(iterations_done),
        "total_iterations": int(total_iterations),
        "best_iteration": int(booster.best_iteration),
        "model": full.save_string(),
    })
    fd, tmp = tempfile.mkstemp(dir=path)
    with os.fdopen(fd, "w") as fh:
        fh.write(payload)
    os.replace(tmp, os.path.join(path, "checkpoint.json"))


def load_checkpoint(path: str) -> Tuple[Booster, Dict[str, int]]:
    """Load a step checkpoint; resume with
    ``train(replace(p, num_iterations=total-done), x, y, init_model=booster)``."""
    import os

    with open(os.path.join(path, "checkpoint.json")) as fh:
        payload = json.load(fh)
    booster = Booster.load_string(payload.pop("model"))
    booster.best_iteration = int(payload.get("best_iteration", -1))
    return booster, payload


def _importances(b: Booster, num_features: int):
    split = np.zeros(num_features, np.float64)
    gain = np.zeros(num_features, np.float64)
    internal = b.trees_feature >= 0
    np.add.at(split, b.trees_feature[internal], 1.0)
    np.add.at(gain, b.trees_feature[internal], b.trees_gain[internal])
    return split, gain


def _resume_state(p, init_model, k, x, default_init):
    """Validate ``init_model`` and return (init score, margins over x's
    rows). Keeps the resumed model's init score so the combined booster's
    folded-init semantics stay consistent; num_iteration is passed
    explicitly because predict_raw would otherwise truncate at
    best_iteration while _prepend_init_trees prepends ALL trees."""
    if init_model is None:
        return default_init, None
    if p.boosting_type in ("dart", "rf"):
        raise NotImplementedError(
            f"init_model continuation is not defined for "
            f"{p.boosting_type} (dart rescales past trees; rf averages)")
    if init_model.num_class != k:
        raise ValueError("init_model num_class mismatch")
    init = float(init_model.init_score)
    n_init_iters = init_model.num_iterations
    margins = init_model.predict_raw(
        x, num_iteration=n_init_iters).reshape(x.shape[0], k)
    return init, margins


def _validate_loop_extras(p, checkpoint_dir):
    if checkpoint_dir is not None and p.boosting_type == "dart":
        raise NotImplementedError(
            "step checkpointing is not defined for dart (past trees "
            "are rescaled every round)")


def _validate_lr_schedule(p, learning_rates):
    """Schedule semantics are boosting-type properties, not device
    properties — identical guards on and off the mesh."""
    if learning_rates is None:
        return None
    if p.boosting_type == "dart":
        raise NotImplementedError(
            "per-iteration learning_rates are not defined for dart "
            "(tree weights are renormalized every round)")
    if p.boosting_type == "rf":
        raise NotImplementedError(
            "rf averages unshrunk trees; a learning-rate schedule "
            "does not apply")
    learning_rates = np.asarray(learning_rates, np.float32)
    if learning_rates.shape != (p.num_iterations,):
        raise ValueError(
            f"learning_rates must have shape ({p.num_iterations},), "
            f"got {learning_rates.shape}")
    return learning_rates


def _pack_queries(group, n_shards):
    """Greedily pack whole queries onto the least-loaded of ``n_shards``
    shards. Returns (shard_idx row-index arrays, dense 0..nq-1 group ids,
    per-shard loads). O(n log n): one stable argsort groups rows."""
    group = np.asarray(group)
    if group.size == 0:  # an empty host still participates in the mesh
        return ([np.zeros(0, np.int64) for _ in range(n_shards)],
                np.zeros(0, np.int64), np.zeros(n_shards, np.int64))
    sort_idx = np.argsort(group, kind="stable")
    sorted_g = group[sort_idx]
    bounds = np.nonzero(sorted_g[1:] != sorted_g[:-1])[0] + 1
    query_rows = np.split(sort_idx, bounds)
    # keep first-appearance query order (matches the reference's
    # repartitionByGroupingColumn stability)
    query_rows.sort(key=lambda rows: int(rows.min()))
    shard_rows: List[List[np.ndarray]] = [[] for _ in range(n_shards)]
    loads = np.zeros(n_shards, np.int64)
    for rows in query_rows:
        tgt = int(np.argmin(loads))
        shard_rows[tgt].append(rows)
        loads[tgt] += len(rows)
    shard_idx = [
        np.concatenate(rs) if rs else np.zeros(0, np.int64)
        for rs in shard_rows
    ]
    # device-side group ids are dense 0..nq-1 (user ids may themselves
    # be negative; pad rows rely on negatives being free)
    _, dense_gid = np.unique(group, return_inverse=True)
    return shard_idx, dense_gid, loads


def _layout_shards(shard_idx, dense_gid, per, binned_np, y, weight,
                   init_margins, bdev, neg_base=0):
    """Materialize a per-shard padded layout: each shard's rows followed
    by pad rows up to ``per``. Pad rows get unique negative group ids
    (no pairs -> zero gradients); ``neg_base`` offsets them so multiple
    hosts' pads stay globally distinct."""
    n_shards = len(shard_idx)
    pad_mask_np = np.ones(per * n_shards, bool)
    gids_np = np.full(per * n_shards, -1, np.int64)
    for s, rows in enumerate(shard_idx):
        base_off = s * per
        gids_np[base_off:base_off + len(rows)] = dense_gid[rows]
        pad_mask_np[base_off + len(rows):base_off + per] = False

    def lay(arr, fill=0):
        out = np.full((per * n_shards,) + arr.shape[1:], fill, arr.dtype)
        for s, rows in enumerate(shard_idx):
            out[s * per: s * per + len(rows)] = arr[rows]
        return out
    binned_np = lay(binned_np, fill=bdev - 1)
    y = lay(y)
    if weight is not None:
        weight = lay(weight)
    if init_margins is not None:
        init_margins = lay(init_margins)
    padidx = np.nonzero(~pad_mask_np)[0]
    gids_np[padidx] = -(np.arange(len(padidx)) + 1 + neg_base)
    return binned_np, y, weight, init_margins, pad_mask_np, gids_np


def _layout_rows(is_rank, dpn, binned_np, y, weight, init_margins, group,
                 bdev):
    """Host-side row layout for the dp mesh: rank fits get group-aligned
    shard packing, everything else pads to a multiple of dpn."""
    n0, f = binned_np.shape
    if is_rank:
        shard_idx, dense_gid, loads = _pack_queries(group, dpn)
        per = int(loads.max())
        (binned_np, y, weight, init_margins, pad_mask_np,
         gids_np) = _layout_shards(shard_idx, dense_gid, per, binned_np, y,
                                   weight, init_margins, bdev)
        n = per * dpn
    else:
        pad = (-n0) % dpn
        pad_mask_np = np.ones(n0 + pad, bool)
        if pad:
            binned_np = np.vstack([binned_np,
                                   np.zeros((pad, f), binned_np.dtype)])
            y = np.concatenate([y, np.zeros(pad, y.dtype)])
            if weight is not None:
                weight = np.concatenate([weight, np.zeros(pad, weight.dtype)])
            if init_margins is not None:
                init_margins = np.vstack(
                    [init_margins,
                     np.zeros((pad, init_margins.shape[1]),
                              init_margins.dtype)])
            pad_mask_np[n0:] = False
        n = n0 + pad
        gids_np = None
    return binned_np, y, weight, init_margins, pad_mask_np, gids_np, n


def _train_distributed(p, mesh, binned_np, y, weight, k, init, obj_fn, gp,
                       bdev, thresholds, valid_sets, feature_names,
                       group=None, init_model=None, init_margins=None,
                       checkpoint_dir=None, checkpoint_every=0,
                       iteration_hook=None, learning_rates=None,
                       placed=None):
    """dp-sharded training: shard_map over the mesh's 'dp' axis, with the
    boosting loop scanned on device (one host sync per chunk, as in the
    single-chip path).

    ``placed`` — row-sharded entry (:func:`train_row_sharded`): a dict of
    ALREADY-SHARDED global jax arrays (``binned, yd, wd, padm, gids, yoh,
    scores``) plus ``n``/``f``; each host contributed only its local rows,
    so the host-side layout below is skipped and no process ever holds the
    global matrix.

    Every boosting mode runs on the mesh:
    - gbdt / rf: per-shard histograms psum'd over ICI (the TPU-native
      replacement for tree_learner=data_parallel's socket reduce-scatter,
      ref: lightgbm/.../TrainUtils.scala networkInit + SURVEY.md §2.10);
    - goss: the top-rate threshold is *global* — a psum'd |grad| histogram
      yields the mesh-wide quantile (512-bin approximation), so row
      selection matches single-device GOSS up to bin granularity;
    - lambdarank / rank_xendcg: group-aligned sharding — whole queries are
      packed onto shards (ref: repartitionByGroupingColumn,
      LightGBMBase.scala prepareDataframe), pairwise gradients stay local;
    - dart: the drop schedule and weight trajectory depend only on host RNG,
      so they are precomputed and the scan carries a per-shard prediction
      stack; dropped-ensemble scores are one einsum per step.

    Validation margins/metrics accumulate on device exactly like the
    single-chip path (valid set replicated on every rank).
    """
    from synapseml_tpu.parallel.distributed import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    is_rank = p.objective in ("lambdarank", "rank_xendcg")
    is_dart = p.boosting_type == "dart"
    use_goss = p.boosting_type == "goss"
    is_rf = p.boosting_type == "rf"
    strat_bagging = (p.pos_bagging_fraction < 1.0
                     or p.neg_bagging_fraction < 1.0)
    use_bagging = (p.bagging_freq > 0
                   and (p.bagging_fraction < 1.0 or strat_bagging)) or is_rf
    if is_rank and group is None and placed is None:
        raise ValueError("ranking objectives need a group array")
    renew_alpha = None
    if k == 1 and not is_dart:
        if p.objective in ("regression_l1", "l1", "mae"):
            renew_alpha = 0.5
        elif p.objective == "quantile":
            renew_alpha = p.alpha

    dpn = mesh.shape["dp"]

    row_spec = P("dp")
    mat_spec = P("dp", None)
    rep = P()
    y_onehot_spec = P("dp", None)

    def put(arr, spec):
        return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))

    if placed is not None:
        n, f = placed["n"], placed["f"]
        binned, yd, wd = placed["binned"], placed["yd"], placed["wd"]
        padm, gids, yoh = placed["padm"], placed["gids"], placed["yoh"]
        scores = placed["scores"]
    else:
        f = binned_np.shape[1]
        (binned_np, y, weight, init_margins, pad_mask_np, gids_np,
         n) = _layout_rows(is_rank, dpn, binned_np, y, weight,
                           init_margins, group, bdev)
        binned = put(binned_np, mat_spec)
        yd = put(y.astype(np.float32), row_spec)
        wd = (put(weight.astype(np.float32), row_spec)
              if weight is not None else None)
        padm = put(pad_mask_np, row_spec)
        gids = put(gids_np, row_spec) if gids_np is not None else None
        if k > 1:
            yoh = put(jax.nn.one_hot(jnp.asarray(y.astype(np.int32)), k),
                      y_onehot_spec)
            scores0 = (init_margins.astype(np.float32)
                       if init_margins is not None
                       else np.zeros((n, k), np.float32) + init)
            scores = put(scores0, y_onehot_spec)
        else:
            yoh = None
            scores0 = (init_margins[:, 0].astype(np.float32)
                       if init_margins is not None
                       else np.zeros(n, np.float32) + init)
            scores = put(scores0, row_spec)

    total_steps = p.num_iterations * k

    # -- dart schedule (host RNG only; fully precomputable) --------------
    # Drop sets + final weights are simulated once; the dense per-step
    # drop-weight rows are materialized per chunk ([chunk*k, total_steps])
    # instead of a replicated [T, T] matrix, which would be O(T^2) device
    # memory at large iteration counts.
    if is_dart:
        # drop granularity: per tree for k=1, per ITERATION for multiclass
        # (LightGBM's convention — a round's k class trees share one
        # weight; mirrors the single-device _train_dart)
        drng = np.random.default_rng(p.seed)
        n_units = p.num_iterations if k > 1 else total_steps
        dart_drops: List[np.ndarray] = []
        cur = np.zeros(n_units, np.float32)
        for t in range(n_units):
            dropped = _dart_select(drng, t, cur, p)
            dart_drops.append(dropped)
            new_w, scale = _dart_normalize(p, len(dropped))
            cur[dropped] *= scale
            cur[t] = new_w
        dart_w_final = np.repeat(cur, k) if k > 1 else cur

        _dart_run = np.zeros(n_units, np.float32)
        _dart_next = [0]
        _dart_row = [None]  # cached per-iteration row (k > 1)

        def dart_wmat_slice(start_step: int, n_steps: int) -> np.ndarray:
            """Replay the schedule incrementally for one chunk's rows;
            steps past total_steps get all-zero rows (their trees are
            sliced off by the chunk loop)."""
            assert start_step == _dart_next[0], "chunks must be sequential"
            out = np.zeros((n_steps, total_steps), np.float32)
            for j in range(n_steps):
                t = start_step + j
                if t >= total_steps:
                    break
                u, c = divmod(t, k)
                if c == 0 or _dart_row[0] is None:
                    w = _dart_run.copy()
                    w[dart_drops[u]] = 0.0
                    _dart_row[0] = np.repeat(w, k) if k > 1 else w
                out[j] = _dart_row[0]
                if c == k - 1:  # iteration complete
                    new_w, scale = _dart_normalize(p, len(dart_drops[u]))
                    _dart_run[dart_drops[u]] *= scale
                    _dart_run[u] = new_w
                _dart_next[0] = t + 1
            if start_step + n_steps > total_steps:
                _dart_next[0] = start_step + n_steps
            return out

        preds0 = put(np.zeros((total_steps, n), np.float32), P(None, "dp"))
        # class of each step, for per-class dart score reconstruction
        dart_class_oh = (np.eye(k, dtype=np.float32)[
            np.arange(total_steps) % k] if k > 1 else None)
    else:
        dart_wmat_slice = None
        preds0 = None
        dart_class_oh = None

    # -- validation state ------------------------------------------------
    tracker = _ValidTracker(p, k, init, valid_sets)
    track = tracker.enabled and not is_dart
    track_dev = track and not tracker.is_rank_metric
    track_rank = track and tracker.is_rank_metric
    if track:
        vx_d = put(np.asarray(tracker.sets[0][0]), rep)
        vy_d = put(np.asarray(tracker.sets[0][1]), rep)
        vg_h = tracker.sets[0][3]
        vy_h = np.asarray(tracker.sets[0][1])
        if init_model is not None:
            # valid margins must include the resumed model's contribution
            vraw = init_model.predict_raw(
                np.asarray(tracker.sets[0][0]),
                num_iteration=init_model.num_iterations)
            vsum0 = put(np.asarray(vraw).reshape(-1, k).astype(np.float32)
                        - init, rep)
        else:
            vsum0 = put(np.zeros((vy_h.shape[0], k), np.float32), rep)
    else:
        vx_d = vy_d = None
        vsum0 = put(np.zeros((0, k), np.float32), rep)
    metric_fn = tracker.metric_fn if track_dev else None

    nbins_goss = 512

    def chunk_fn(binned_l, yd_l, yoh_l, wd_l, padm_l, gids_l, vx_r, vy_r,
                 wmat_r, step_off, lrs_r, carry, steps):
        n_l = binned_l.shape[0]

        def goss_select(g, h, key):
            """Global top-rate threshold from a psum'd |grad| histogram."""
            absg = jnp.where(padm_l, jnp.abs(g), 0.0)
            gmax = lax.pmax(absg.max(), "dp") + 1e-12
            idx = jnp.clip((absg / gmax * nbins_goss).astype(jnp.int32),
                           0, nbins_goss - 1)
            oh = jax.nn.one_hot(idx, nbins_goss, dtype=jnp.float32)
            hist = lax.psum(
                jnp.einsum("nb,n->b", oh, padm_l.astype(jnp.float32)), "dp")
            total = lax.psum(padm_l.sum().astype(jnp.float32), "dp")
            n_top = jnp.maximum(1.0, jnp.floor(p.top_rate * total))
            from_top = jnp.cumsum(hist[::-1])[::-1]
            tbin = jnp.maximum((from_top >= n_top).sum() - 1, 0)
            thresh = tbin.astype(jnp.float32) * gmax / nbins_goss
            top = absg >= thresh
            rkey = jax.random.fold_in(key, lax.axis_index("dp"))
            rand = jax.random.uniform(rkey, (n_l,)) < p.other_rate
            amp = (1.0 - p.top_rate) / max(p.other_rate, 1e-12)
            small = (~top) & rand & padm_l
            mask = (top | small) & padm_l
            g2 = jnp.where(small, g * amp, g)
            h2 = jnp.where(small, h * amp, h)
            return mask, g2, h2

        def step_fn(c_in, st):
            if is_dart and k > 1:
                scores_l, vsum_r, preds_l, rng, d_g, d_h = c_in
            else:
                scores_l, vsum_r, preds_l, rng = c_in
            rng, key = jax.random.split(rng)
            cidx = st % k
            it = st // k

            if is_dart:
                # wmat_r holds only this chunk's schedule rows
                if k > 1:
                    # base + all-class grads are identical across an
                    # iteration's k steps (the iteration's own trees carry
                    # weight 0 in its wmat row): recompute only on the
                    # first class step, carry for the rest
                    def recompute(_):
                        b = init + jnp.einsum(
                            "t,tn,tc->nc", wmat_r[st - step_off], preds_l,
                            jnp.asarray(dart_class_oh))
                        return obj_fn(b, yoh_l, wd_l)

                    d_g, d_h = lax.cond(
                        cidx == 0, recompute, lambda _: (d_g, d_h), None)
                    base = None  # grads already taken below
                else:
                    base = init + jnp.einsum(
                        "t,tn->n", wmat_r[st - step_off], preds_l)
            elif is_rf:
                base = jnp.full_like(scores_l, init)
            else:
                base = scores_l

            if k > 1:
                if is_dart:
                    g, h = d_g[:, cidx], d_h[:, cidx]
                else:
                    g, h = obj_fn(base, yoh_l, wd_l)
                    g, h = g[:, cidx], h[:, cidx]
            elif is_rank:
                g, h = obj.lambdarank_grad(base, yd_l, gids_l,
                                           max_dcg_pos=p.max_position)
                if wd_l is not None:
                    g, h = g * wd_l, h * wd_l
            else:
                g, h = obj_fn(base, yd_l, wd_l)

            # dart fits on the full data / full features, exactly like the
            # single-device _train_dart — same BoostParams must give the
            # same ensemble with or without a mesh
            if use_goss:
                mask, g, h = goss_select(g, h, key)
            elif use_bagging and not is_dart:
                bkey = jax.random.fold_in(key, lax.axis_index("dp"))
                if p.bagging_seed is not None:
                    bkey = jax.random.fold_in(bkey, p.bagging_seed)
                u = jax.random.uniform(bkey, (n_l,))
                if strat_bagging and not is_rf:
                    mask = padm_l & jnp.where(
                        yd_l > 0, u < p.pos_bagging_fraction,
                        u < p.neg_bagging_fraction)
                else:
                    frac = (p.bagging_fraction
                            if p.bagging_fraction < 1.0 else 0.632)
                    mask = padm_l & (u < frac)
            else:
                mask = padm_l

            binned_use = binned_l
            if p.feature_fraction < 1.0 and not is_dart:
                # same key on every rank -> identical feature subset mesh-wide
                keep = max(1, int(round(p.feature_fraction * f)))
                perm = jax.random.permutation(jax.random.fold_in(key, 17), f)
                fmask = jnp.zeros(f, jnp.bool_).at[perm[:keep]].set(True)
                binned_use = jnp.where(fmask[None, :], binned_l, bdev - 1)

            tree, row_slot, slot_value, slot_node = build_tree(
                binned_use, g, h, mask, thresholds, gp, axis_name="dp")

            if renew_alpha is not None:
                # L1-family leaf renewal needs *global* per-leaf quantiles:
                # all_gather the residuals + slots over dp (a [n] f32 vector,
                # cheap next to the per-split histograms), then quantile —
                # the single-device scan path's semantics, exactly
                residual_l = jnp.where(padm_l, yd_l - scores_l, jnp.nan)
                residual_g = lax.all_gather(residual_l, "dp", tiled=True)
                row_slot_g = lax.all_gather(row_slot, "dp", tiled=True)

                def leaf_quantile(slot):
                    r = jnp.where(row_slot_g == slot, residual_g, jnp.nan)
                    return jnp.nanquantile(r, renew_alpha)

                renewed = jax.vmap(leaf_quantile)(jnp.arange(gp.num_leaves))
                slot_value = jnp.where(jnp.isnan(renewed), slot_value, renewed)
                m_nodes = tree.leaf_value.shape[0]
                nsel = ((slot_node[:, None] == jnp.arange(m_nodes))
                        & (slot_node >= 0)[:, None])
                new_leaf = jnp.sum(nsel * slot_value[:, None], axis=0)
                tree = Tree(
                    split_feature=tree.split_feature, threshold=tree.threshold,
                    threshold_bin=tree.threshold_bin,
                    left_child=tree.left_child, right_child=tree.right_child,
                    leaf_value=new_leaf, cover=tree.cover, gain=tree.gain)

            if is_dart:
                pred = slot_value[row_slot]
                preds_l = preds_l.at[st].set(pred)
                new_scores = scores_l
                scaled = tree  # dart leaf values stay raw; weights carry scale
            else:
                if is_rf:
                    lr = 1.0
                elif lrs_r is not None:  # per-iteration schedule (replicated)
                    lr = lrs_r[it]
                else:
                    lr = p.learning_rate
                delta = lr * slot_value[row_slot]
                if k > 1:
                    new_scores = scores_l + delta[:, None] * jax.nn.one_hot(
                        cidx, k, dtype=scores_l.dtype)
                else:
                    new_scores = scores_l + delta
                scaled = Tree(
                    split_feature=tree.split_feature, threshold=tree.threshold,
                    threshold_bin=tree.threshold_bin,
                    left_child=tree.left_child, right_child=tree.right_child,
                    leaf_value=tree.leaf_value * lr, cover=tree.cover,
                    gain=tree.gain)

            out: Tuple = (scaled,)
            if track:
                vt = predict_tree(
                    (scaled.split_feature, scaled.threshold, scaled.left_child,
                     scaled.right_child, scaled.leaf_value), vx_r)
                vsum_r = vsum_r + vt[:, None] * jax.nn.one_hot(
                    cidx, k, dtype=vsum_r.dtype)
            if track_dev:
                scale = (1.0 / (it + 1.0)) if is_rf else 1.0
                vscore = vsum_r * scale + init
                if k > 1:
                    m = metric_fn(vscore, vy_r.astype(jnp.int32))
                else:
                    m = metric_fn(vscore[:, 0], vy_r)
                out = out + (m,)
            elif track_rank:
                out = out + (vsum_r[:, 0],)
            if is_dart and k > 1:
                return (new_scores, vsum_r, preds_l, rng, d_g, d_h), out
            return (new_scores, vsum_r, preds_l, rng), out

        return lax.scan(step_fn, carry, steps)

    carry_spec = (
        y_onehot_spec if k > 1 else row_spec,            # scores
        rep,                                             # vsum
        P(None, "dp") if is_dart else rep,               # preds stack
        rep,                                             # rng
    )
    if is_dart and k > 1:
        # carried all-class dart gradients (recomputed once per iteration)
        carry_spec = carry_spec + (y_onehot_spec, y_onehot_spec)
    in_specs = (
        mat_spec, row_spec,
        (y_onehot_spec if k > 1 else None),
        (row_spec if wd is not None else None),
        row_spec,
        (row_spec if gids is not None else None),
        rep, rep, rep, rep,
        (rep if learning_rates is not None else None),
        carry_spec, rep,
    )
    tree_spec = Tree(*([rep] * 8))
    ys_spec: Tuple = (tree_spec,)
    if track_dev:
        ys_spec = ys_spec + (rep,)
    elif track_rank:
        ys_spec = ys_spec + (rep,)
    out_specs = (carry_spec, ys_spec)

    smapped = shard_map(chunk_fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)
    jitted = jax.jit(smapped, donate_argnums=11)  # the carry

    total_iters = p.num_iterations
    chunk = _compute_chunk(p, tracker, track_rank, total_iters,
                           int(vsum0.shape[0]))
    if is_dart:
        # bound the replicated per-chunk schedule slice ([chunk*k, T])
        chunk = min(chunk, max(1, 256 // max(1, k)))

    lrs_rep = None
    if learning_rates is not None:
        lrs_rep = put(_pad_lr_schedule(learning_rates), rep)

    def run(carry, steps, start_iter):
        if is_dart:
            wm = put(dart_wmat_slice(start_iter * k, len(steps)), rep)
        else:
            wm = None
        off = put(np.int32(start_iter * k), rep)
        return jitted(binned, yd, yoh, wd, padm, gids, vx_d, vy_d,
                      wm, off, lrs_rep, carry, put(np.asarray(steps), rep))

    carry = (scores, vsum0,
             preds0 if is_dart else put(np.zeros((1, 1), np.float32), rep),
             put(jax.random.PRNGKey(p.seed), rep))
    if is_dart and k > 1:
        carry = carry + (
            put(np.zeros((n, k), np.float32), y_onehot_spec),
            put(np.zeros((n, k), np.float32), y_onehot_spec))

    if checkpoint_dir is not None and checkpoint_every > 0:
        chunk = min(chunk, max(1, int(checkpoint_every)))
    on_chunk = _chunk_callbacks(checkpoint_dir, init_model, p, k, init, f,
                                feature_names, tracker, iteration_hook)

    stacked = _chunked_boost_loop(
        run, carry, tracker, p, k, total_iters, chunk, track_dev, track_rank,
        vy_h if track else None, vg_h if track else None, on_chunk=on_chunk,
        on_stop=iteration_hook)
    booster = _assemble_booster(
        _prepend_init_trees(init_model, stacked), p, k, init, f,
        feature_names, tracker,
        dart_w_final=dart_w_final if is_dart else None,
        init_model=init_model)
    if init_model is not None and booster.best_iteration >= 0:
        booster.best_iteration += init_model.num_iterations
    return booster


def _train_dart(p, binned, yd, wd, obj_fn, gp, thresholds, init, n, f,
                valid_sets, feature_names, k: int = 1):
    """DART boosting (Rashmi & Gilad-Bachrach): each round drops a random
    subset of existing iterations, fits the new tree(s) against the
    reduced ensemble, then renormalizes (paper normalization with
    shrinkage: w_new = lr/(|D|+1), dropped *= |D|/(|D|+1)).

    Multiclass fits k class trees per iteration; drops happen at
    iteration granularity, so an iteration's k trees share one weight
    (LightGBM's DART tracks drop candidates per iteration). Per-tree
    train predictions are cached on device so score reconstruction is a
    weighted sum, not a re-traversal.
    """
    y_onehot = (jax.nn.one_hot(yd.astype(jnp.int32), k) if k > 1 else None)

    @jax.jit
    def grads(score_used):
        return obj_fn(score_used, y_onehot if k > 1 else yd, wd)

    @jax.jit
    def fit_tree(g, h, key):
        tree, row_slot, slot_value, _ = build_tree(
            binned, g, h, jnp.ones(n, jnp.bool_), thresholds, gp, None)
        return tree, slot_value[row_slot]

    rng = np.random.default_rng(p.seed)
    jkey = jax.random.PRNGKey(p.seed)
    trees: List[Tree] = []            # class-interleaved, t % k == class
    iter_preds: List[jnp.ndarray] = []  # per iteration: [k, n] unscaled
    weights: List[float] = []           # one weight per ITERATION
    base = (jnp.zeros((n, k), jnp.float32) + init if k > 1
            else jnp.zeros(n, jnp.float32) + init)

    for it in range(p.num_iterations):
        t = len(iter_preds)
        dropped = _dart_select(rng, t, np.asarray(weights, np.float64), p)
        w = np.asarray(weights, np.float32)
        if len(dropped):
            w_used = w.copy()
            w_used[dropped] = 0.0
        else:
            w_used = w
        score_used = base
        if t:
            if k > 1:
                score_used = base + jnp.einsum(
                    "i,ikn->nk", jnp.asarray(w_used),
                    jnp.stack(iter_preds))
            else:
                score_used = base + jnp.einsum(
                    "i,in->n", jnp.asarray(w_used),
                    jnp.stack([pr[0] for pr in iter_preds]))
        g, h = grads(score_used)
        class_preds = []
        iter_trees = []
        for c in range(k):
            jkey, sub = jax.random.split(jkey)
            gc = g[:, c] if k > 1 else g
            hc = h[:, c] if k > 1 else h
            tree, pred = fit_tree(gc, hc, sub)
            iter_trees.append(tree)
            class_preds.append(pred)
        # one batched device->host round trip for the iteration's k trees
        trees.extend(jax.device_get(iter_trees))
        iter_preds.append(jnp.stack(class_preds))
        new_w, scale = _dart_normalize(p, len(dropped))
        for d in dropped:
            weights[d] *= scale
        weights.append(float(new_w))

    # expand iteration weights to the class-interleaved tree stack
    tree_w = np.repeat(np.asarray(weights, np.float32), k)
    booster = Booster(
        trees_feature=np.stack([t.split_feature for t in trees]),
        trees_threshold=np.stack([t.threshold for t in trees]),
        trees_left=np.stack([t.left_child for t in trees]),
        trees_right=np.stack([t.right_child for t in trees]),
        trees_value=np.stack([t.leaf_value for t in trees]),
        trees_cover=np.stack([t.cover for t in trees]),
        trees_gain=np.stack([t.gain for t in trees]),
        tree_weights=tree_w,
        params=p, init_score=init, num_class=k, num_features=f,
        feature_names=feature_names)
    booster.feature_importance_split, booster.feature_importance_gain = (
        _importances(booster, f))
    return booster
