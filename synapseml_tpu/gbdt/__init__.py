from synapseml_tpu.gbdt.boosting import BoostParams, Booster, train
from synapseml_tpu.gbdt.estimators import (
    LightGBMClassificationModel,
    LightGBMDelegate,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRankerModel,
    LightGBMRegressionModel,
    LightGBMRegressor,
)

__all__ = [
    "BoostParams", "Booster", "LightGBMClassificationModel",
    "LightGBMDelegate",
    "LightGBMClassifier", "LightGBMRanker", "LightGBMRankerModel",
    "LightGBMRegressionModel", "LightGBMRegressor", "train",
]
