"""SAR — Smart Adaptive Recommendations — plus ranking evaluation.

Re-design of the reference's recommender
(ref: core/.../recommendation/SAR.scala:36-209, SARModel.scala:22-117,
RecommendationIndexer.scala:18, RankingAdapter.scala:69,
RankingEvaluator.scala:100 + AdvancedRankingMetrics.scala:17,
RankingTrainValidationSplit.scala:25).

TPU-first: the reference computes item-item similarity with a broadcast
sparse matrix multiply per partition (SAR.scala:152-209); here the user-item
matrix lives on device and co-occurrence ``B^T B``, similarity normalization,
affinity x similarity scoring and per-user top-k are all one jitted program —
dense matmuls on the MXU instead of driver-side sparse joins.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.core.param import ComplexParam, Param
from synapseml_tpu.core.pipeline import Estimator, Evaluator, Model, Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.featurize.indexer import ValueIndexer, ValueIndexerModel


class RecommendationIndexer(Estimator):
    """Indexes user and item id columns to dense ints
    (ref: RecommendationIndexer.scala:18)."""

    user_input_col = Param("raw user column", default="user")
    user_output_col = Param("indexed user column", default="userIdx")
    item_input_col = Param("raw item column", default="item")
    item_output_col = Param("indexed item column", default="itemIdx")
    rating_col = Param("rating column", default="rating")

    def _fit(self, table: Table) -> "RecommendationIndexerModel":
        u = ValueIndexer(input_col=self.user_input_col,
                         output_col=self.user_output_col).fit(table)
        i = ValueIndexer(input_col=self.item_input_col,
                         output_col=self.item_output_col).fit(table)
        return RecommendationIndexerModel(user_indexer=u, item_indexer=i)


class RecommendationIndexerModel(Model):
    user_indexer = ComplexParam("fitted user ValueIndexerModel")
    item_indexer = ComplexParam("fitted item ValueIndexerModel")

    def _transform(self, table: Table) -> Table:
        return self.item_indexer.transform(self.user_indexer.transform(table))

    def recover_user(self, idx: np.ndarray) -> List:
        levels = self.user_indexer.levels
        return [levels[i] if 0 <= i < len(levels) else None for i in idx]

    def recover_item(self, idx: np.ndarray) -> List:
        levels = self.item_indexer.levels
        return [levels[i] if 0 <= i < len(levels) else None for i in idx]


@partial(jax.jit, static_argnames=("similarity", "support_threshold"))
def _item_similarity(b, similarity: str, support_threshold: int):
    """b: [U, I] binarized interactions -> [I, I] similarity
    (ref: SAR.calculateItemItemSimilarity:152-209)."""
    c = b.T @ b                                  # co-occurrence counts
    diag = jnp.diag(c)
    if similarity == "jaccard":
        s = c / (diag[:, None] + diag[None, :] - c + 1e-12)
    elif similarity == "lift":
        s = c / (diag[:, None] * diag[None, :] + 1e-12)
    else:  # cooccurrence
        s = c
    return jnp.where(c >= support_threshold, s, 0.0)


@partial(jax.jit, static_argnames=("k", "remove_seen"))
def _recommend(affinity, similarity, seen, k: int, remove_seen: bool):
    """scores = affinity @ similarity; top-k per user
    (ref: SARModel.recommendForAllUsers:53,117)."""
    scores = affinity @ similarity
    if remove_seen:
        scores = jnp.where(seen > 0, -jnp.inf, scores)
    vals, idx = jax.lax.top_k(scores, k)
    return vals, idx


class SAR(Estimator):
    """ref: SAR.scala:36 (fit :66-76). Affinity = time-decayed weighted
    transaction counts (half-life decay UDF :91-96); similarity = normalized
    co-occurrence."""

    user_col = Param("indexed user column", default="userIdx")
    item_col = Param("indexed item column", default="itemIdx")
    rating_col = Param("rating column", default="rating")
    time_col = Param("timestamp column (seconds); None = no decay", default=None)
    time_decay_coeff = Param("half-life in days", default=30)
    support_threshold = Param("min co-occurrence for similarity", default=4)
    similarity_function = Param("jaccard | lift | cooccurrence",
                                default="jaccard")
    start_time = Param("reference time (seconds; default max(time))", default=None)

    def _fit(self, table: Table) -> "SARModel":
        u = np.asarray(table[self.user_col], np.int64)
        i = np.asarray(table[self.item_col], np.int64)
        n_users = int(u.max()) + 1 if len(u) else 0
        n_items = int(i.max()) + 1 if len(i) else 0
        r = (np.asarray(table[self.rating_col], np.float64)
             if self.rating_col and self.rating_col in table
             else np.ones(len(u)))
        if self.time_col and self.time_col in table:
            t = np.asarray(table[self.time_col], np.float64)
            ref = float(self.start_time) if self.start_time else float(t.max())
            half_life_s = float(self.time_decay_coeff) * 86400.0
            decay = np.power(2.0, -(ref - t) / half_life_s)
            r = r * decay
        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (u, i), r)
        binarized = np.zeros((n_users, n_items), np.float32)
        binarized[u, i] = 1.0
        sim = np.asarray(_item_similarity(
            jnp.asarray(binarized), str(self.similarity_function),
            int(self.support_threshold)))
        return SARModel(
            user_item_affinity=affinity, item_similarity=sim,
            seen=binarized, user_col=self.user_col, item_col=self.item_col,
            rating_col=self.rating_col)


class SARModel(Model):
    """ref: SARModel.scala:22."""

    user_item_affinity = ComplexParam("[U, I] affinity matrix")
    item_similarity = ComplexParam("[I, I] similarity matrix")
    seen = ComplexParam("[U, I] binarized seen mask")
    user_col = Param("indexed user column", default="userIdx")
    item_col = Param("indexed item column", default="itemIdx")
    rating_col = Param("rating column", default="rating")
    prediction_col = Param("score output column", default="prediction")

    def recommend_for_all_users(self, k: int, remove_seen: bool = True) -> Table:
        vals, idx = _recommend(
            jnp.asarray(self.user_item_affinity),
            jnp.asarray(self.item_similarity),
            jnp.asarray(self.seen), k, remove_seen)
        vals, idx = np.asarray(vals), np.asarray(idx)
        n_users = vals.shape[0]
        recs = np.empty(n_users, dtype=object)
        ratings = np.empty(n_users, dtype=object)
        for uidx in range(n_users):
            recs[uidx] = [int(j) for j in idx[uidx]]
            ratings[uidx] = [float(v) for v in vals[uidx]]
        return Table({
            self.user_col: np.arange(n_users, dtype=np.int64),
            "recommendations": recs,
            "ratings": ratings,
        })

    def _transform(self, table: Table) -> Table:
        """Score given (user, item) pairs."""
        u = np.asarray(table[self.user_col], np.int64)
        i = np.asarray(table[self.item_col], np.int64)
        scores = np.asarray(
            jnp.asarray(self.user_item_affinity) @ jnp.asarray(self.item_similarity))
        u_ok = (u >= 0) & (u < scores.shape[0])
        i_ok = (i >= 0) & (i < scores.shape[1])
        out = np.zeros(len(u), np.float64)
        m = u_ok & i_ok
        out[m] = scores[u[m], i[m]]
        return table.with_column(self.prediction_col, out)


# ---------------------------------------------------------------------------
# Ranking metrics + adapter + tune/validation split
# ---------------------------------------------------------------------------

def _ranking_metrics(recommended: List[List], actual: List[List], k: int) -> Dict[str, float]:
    """ndcg/map/precision/recall@k over per-user lists
    (ref: AdvancedRankingMetrics.scala:17)."""
    ndcgs, maps, precs, recalls = [], [], [], []
    for rec, act in zip(recommended, actual):
        rec = list(rec)[:k]
        act_set = set(act)
        if not act_set:
            continue
        hits = [1.0 if r in act_set else 0.0 for r in rec]
        # ndcg
        dcg = sum(h / math.log2(j + 2) for j, h in enumerate(hits))
        idcg = sum(1.0 / math.log2(j + 2) for j in range(min(len(act_set), k)))
        ndcgs.append(dcg / idcg if idcg > 0 else 0.0)
        # map
        cum, ap = 0.0, 0.0
        for j, h in enumerate(hits):
            if h:
                cum += 1.0
                ap += cum / (j + 1)
        maps.append(ap / min(len(act_set), k))
        precs.append(sum(hits) / max(len(rec), 1))
        recalls.append(sum(hits) / len(act_set))
    n = max(len(ndcgs), 1)
    return {
        "ndcgAt": sum(ndcgs) / n, "map": sum(maps) / n,
        "precisionAtk": sum(precs) / n, "recallAtK": sum(recalls) / n,
    }


class RankingEvaluator(Evaluator):
    """ref: RankingEvaluator.scala:100."""

    k = Param("cutoff", default=10)
    metric_name = Param("ndcgAt | map | precisionAtk | recallAtK",
                        default="ndcgAt")
    prediction_col = Param("recommendations column", default="recommendations")
    label_col = Param("ground-truth items column", default="label")

    def evaluate(self, table: Table) -> float:
        rec = [list(v) for v in table[self.prediction_col]]
        act = [list(v) for v in table[self.label_col]]
        return _ranking_metrics(rec, act, int(self.k))[self.metric_name]


class RankingAdapter(Estimator):
    """Wraps a recommender so its output evaluates as ranking lists
    (ref: RankingAdapter.scala:69)."""

    recommender = ComplexParam("inner Estimator (e.g. SAR)")
    k = Param("recommendations per user", default=10)
    user_col = Param("indexed user column", default="userIdx")
    item_col = Param("indexed item column", default="itemIdx")

    def _fit(self, table: Table) -> "RankingAdapterModel":
        model = self.recommender.fit(table)
        return RankingAdapterModel(recommender_model=model, k=int(self.k),
                                   user_col=self.user_col,
                                   item_col=self.item_col)


class RankingAdapterModel(Model):
    recommender_model = ComplexParam("fitted recommender")
    k = Param("recommendations per user", default=10)
    user_col = Param("indexed user column", default="userIdx")
    item_col = Param("indexed item column", default="itemIdx")

    def _transform(self, table: Table) -> Table:
        recs = self.recommender_model.recommend_for_all_users(int(self.k))
        rec_by_user = {int(u): r for u, r in
                       zip(recs[self.user_col], recs["recommendations"])}
        groups = table.group_indices(self.user_col)
        users, ground, recommended = [], [], []
        items = table[self.item_col]
        for uval, idx in groups.items():
            users.append(uval)
            ground.append([int(items[j]) for j in idx])
            recommended.append(rec_by_user.get(int(uval), []))
        return Table({
            self.user_col: users,
            "recommendations": np.array(recommended, dtype=object)
            if len({len(r) for r in recommended}) > 1 else _obj(recommended),
            "label": _obj(ground),
        })


def _obj(values):
    arr = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        arr[i] = v
    return arr


class RankingTrainValidationSplit(Estimator):
    """Per-user holdout split + fit + ranking eval
    (ref: RankingTrainValidationSplit.scala:25)."""

    estimator = ComplexParam("RankingAdapter to fit")
    evaluator = ComplexParam("RankingEvaluator")
    train_ratio = Param("per-user train fraction", default=0.75)
    user_col = Param("indexed user column", default="userIdx")
    seed = Param("split seed", default=0)

    def _fit(self, table: Table) -> "RankingTrainValidationSplitModel":
        rng = np.random.default_rng(int(self.seed))
        groups = table.group_indices(self.user_col)
        train_idx, test_idx = [], []
        ratio = float(self.train_ratio)
        for _, idx in groups.items():
            perm = rng.permutation(len(idx))
            cut = max(1, int(len(idx) * ratio))
            train_idx.extend(idx[perm[:cut]])
            test_idx.extend(idx[perm[cut:]])
        train_t = table.take(np.asarray(sorted(train_idx), dtype=int))
        test_t = table.take(np.asarray(sorted(test_idx), dtype=int))
        model = self.estimator.fit(train_t)
        metric = None
        if self.evaluator is not None and test_t.num_rows:
            metric = self.evaluator.evaluate(model.transform(test_t))
        return RankingTrainValidationSplitModel(
            best_model=model, validation_metric=metric)


class RankingTrainValidationSplitModel(Model):
    best_model = ComplexParam("fitted inner model")
    validation_metric = Param("holdout ranking metric", default=None)

    def _transform(self, table: Table) -> Table:
        return self.best_model.transform(table)
