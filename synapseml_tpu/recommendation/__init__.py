from synapseml_tpu.recommendation.sar import (
    RankingAdapter,
    RankingAdapterModel,
    RankingEvaluator,
    RankingTrainValidationSplit,
    RankingTrainValidationSplitModel,
    RecommendationIndexer,
    RecommendationIndexerModel,
    SAR,
    SARModel,
)

__all__ = [
    "RankingAdapter", "RankingAdapterModel", "RankingEvaluator",
    "RankingTrainValidationSplit", "RankingTrainValidationSplitModel",
    "RecommendationIndexer", "RecommendationIndexerModel", "SAR", "SARModel",
]
