"""MiniBatch machinery for model-serving transformers.

TPU-native re-design of the reference's batching stack
(ref: core/.../stages/MiniBatchTransformer.scala:52-238, Batchers.scala:12-152):
``FixedMiniBatchTransformer`` / ``DynamicMiniBatchTransformer`` /
``TimeIntervalMiniBatchTransformer`` pack scalar rows into batched list/array
rows; ``FlattenBatch`` unpacks them. On TPU fixed batch sizes matter more than
on CPU — XLA compiles one program per shape — so ``FixedMiniBatchTransformer``
grows a padded batch (``pad_to_batch``) to keep the jit cache to O(1) programs.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table


def _batch_column(col: np.ndarray, starts: List[int], stops: List[int]) -> np.ndarray:
    out = np.empty(len(starts), dtype=object)
    for i, (a, b) in enumerate(zip(starts, stops)):
        out[i] = col[a:b]
    return out


class _BatcherBase(Transformer):
    def _bounds(self, table: Table) -> List[int]:
        raise NotImplementedError

    def _transform(self, table: Table) -> Table:
        cuts = self._bounds(table)
        starts = cuts[:-1]
        stops = cuts[1:]
        return Table({
            name: _batch_column(table[name], starts, stops)
            for name in table.columns
        })


class FixedMiniBatchTransformer(_BatcherBase):
    """Pack rows into fixed-size batches (ref: MiniBatchTransformer.scala:150)."""

    batch_size = Param("rows per batch", default=32,
                       type_check=lambda v: isinstance(v, int) and v > 0)
    buffered = Param("unused compat flag (reference buffers on a thread)", default=False)
    max_buffer_size = Param("compat", default=2147483647)

    def _bounds(self, table: Table) -> List[int]:
        n = table.num_rows
        bs = int(self.batch_size)
        cuts = list(range(0, n, bs))
        cuts.append(n)
        return cuts


class DynamicMiniBatchTransformer(_BatcherBase):
    """Batch everything currently available (ref: MiniBatchTransformer.scala:52).

    Without a streaming micro-batch boundary the whole input is one batch,
    capped by ``max_batch_size``.
    """

    max_batch_size = Param("maximum rows per batch", default=2147483647)

    def _bounds(self, table: Table) -> List[int]:
        n = table.num_rows
        bs = min(int(self.max_batch_size), max(n, 1))
        cuts = list(range(0, n, bs))
        cuts.append(n)
        return cuts


class TimeIntervalMiniBatchTransformer(_BatcherBase):
    """Batch by wall-clock interval (ref: MiniBatchTransformer.scala:76).

    In the columnar (non-streaming) plane rows carry no arrival time, so this
    degrades to max-size batching; the interval applies in serving mode where
    the queue poll loop enforces it (see synapseml_tpu.io.serving).
    """

    milliseconds = Param("interval in ms", default=1000)
    max_batch_size = Param("maximum rows per batch", default=2147483647)

    def _bounds(self, table: Table) -> List[int]:
        n = table.num_rows
        bs = min(int(self.max_batch_size), max(n, 1))
        cuts = list(range(0, n, bs))
        cuts.append(n)
        return cuts


class FlattenBatch(Transformer):
    """Unpack batched rows back to scalar rows (ref: MiniBatchTransformer.scala:186)."""

    def _transform(self, table: Table) -> Table:
        if table.num_rows == 0:
            return table
        cols: Dict[str, List[Any]] = {name: [] for name in table.columns}
        for row in table.rows():
            lengths = [len(v) for v in row.values()
                       if isinstance(v, (list, np.ndarray))]
            n = max(lengths) if lengths else 1
            for name, value in row.items():
                if isinstance(value, (list, np.ndarray)) and len(value) == n:
                    cols[name].extend(list(value))
                else:
                    cols[name].extend([value] * n)
        return Table(cols)
