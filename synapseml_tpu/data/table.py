"""Columnar Table — the framework's data plane.

The reference rides Spark DataFrames (L1 in SURVEY.md); here the data plane is a
lightweight immutable columnar table backed by numpy, with zero-copy pandas /
pyarrow interop. TPU-first rationale: fixed-width columns (including 2-D
"vector" columns) stay contiguous so host→device transfer of a whole batch is a
single ``jax.device_put`` — the analogue of the reference's chunked SWIG array
ingest (ref: lightgbm/.../dataset/DatasetAggregator.scala:69-180) without the
JVM⇄native marshalling hot loop.

Columns are 1-D numpy arrays (scalars, strings as object dtype) or 2-D numpy
arrays ("vector" columns, the analogue of SparkML VectorUDT). Ragged data
(token lists, variable images) uses 1-D object arrays.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

ColumnLike = Union[np.ndarray, Sequence[Any]]


def _as_column(values: ColumnLike) -> np.ndarray:
    if isinstance(values, np.ndarray):
        return values
    values = list(values)
    if values and isinstance(values[0], np.ndarray) and values[0].ndim >= 1:
        shapes = {v.shape for v in values if isinstance(v, np.ndarray)}
        if len(shapes) == 1:
            return np.stack(values)
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    try:
        arr = np.asarray(values)
    except ValueError:  # ragged nested lists -> object column
        arr = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            arr[i] = v
        return arr
    if arr.dtype.kind in ("U", "S"):
        arr = arr.astype(object)
    return arr


class Table:
    """Immutable columnar table."""

    __slots__ = ("_cols", "_n")

    def __init__(self, columns: Dict[str, ColumnLike]):
        cols: Dict[str, np.ndarray] = {}
        n = None
        for name, values in columns.items():
            arr = _as_column(values)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column {name!r} has length {len(arr)}, expected {n}"
                )
            cols[name] = arr
        self._cols = cols
        self._n = 0 if n is None else n

    # -- construction --------------------------------------------------
    @staticmethod
    def from_pandas(df) -> "Table":
        return Table({c: df[c].to_numpy() for c in df.columns})

    @staticmethod
    def from_rows(rows: Iterable[Dict[str, Any]]) -> "Table":
        rows = list(rows)
        if not rows:
            return Table({})
        names = list(rows[0].keys())
        return Table({n: [r[n] for r in rows] for n in names})

    @staticmethod
    def from_arrow(arrow_table) -> "Table":
        return Table.from_pandas(arrow_table.to_pandas())

    # -- basic accessors ----------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._n

    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    def __len__(self) -> int:
        return self._n

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(f"no column {name!r}; have {self.columns}")
        return self._cols[name]

    def column(self, name: str) -> np.ndarray:
        return self[name]

    def schema(self) -> Dict[str, Tuple[Any, Tuple[int, ...]]]:
        return {k: (v.dtype, v.shape[1:]) for k, v in self._cols.items()}

    def rows(self) -> Iterator[Dict[str, Any]]:
        for i in range(self._n):
            yield {k: v[i] for k, v in self._cols.items()}

    def to_pandas(self):
        import pandas as pd
        out = {}
        for k, v in self._cols.items():
            out[k] = list(v) if v.ndim > 1 else v
        return pd.DataFrame(out)

    # -- relational ops ------------------------------------------------
    def select(self, *names: str) -> "Table":
        return Table({n: self[n] for n in names})

    def drop(self, *names: str) -> "Table":
        return Table({k: v for k, v in self._cols.items() if k not in names})

    def rename(self, mapping: Dict[str, str]) -> "Table":
        return Table({mapping.get(k, k): v for k, v in self._cols.items()})

    def with_column(self, name: str, values: ColumnLike) -> "Table":
        cols = dict(self._cols)
        cols[name] = values
        return Table(cols)

    def with_columns(self, new: Dict[str, ColumnLike]) -> "Table":
        cols = dict(self._cols)
        cols.update(new)
        return Table(cols)

    def filter(self, mask: ColumnLike) -> "Table":
        mask = np.asarray(mask, dtype=bool)
        return Table({k: v[mask] for k, v in self._cols.items()})

    def take(self, indices: ColumnLike) -> "Table":
        idx = np.asarray(indices)
        return Table({k: v[idx] for k, v in self._cols.items()})

    def slice(self, start: int, stop: int) -> "Table":
        return Table({k: v[start:stop] for k, v in self._cols.items()})

    def head(self, n: int = 5) -> "Table":
        return self.slice(0, min(n, self._n))

    def sort(self, by: str, ascending: bool = True) -> "Table":
        order = np.argsort(self[by], kind="stable")
        if not ascending:
            order = order[::-1]
        return self.take(order)

    def shuffle(self, seed: int = 0) -> "Table":
        rng = np.random.default_rng(seed)
        return self.take(rng.permutation(self._n))

    def random_split(self, fractions: Sequence[float], seed: int = 0) -> List["Table"]:
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._n)
        total = float(sum(fractions))
        out, start = [], 0
        for i, f in enumerate(fractions):
            stop = self._n if i == len(fractions) - 1 else start + int(round(self._n * f / total))
            out.append(self.take(perm[start:stop]))
            start = stop
        return out

    def concat(self, *others: "Table") -> "Table":
        tables = [self, *others]
        cols = {}
        for name in self.columns:
            parts = [t[name] for t in tables]
            if any(p.dtype == object for p in parts):
                merged = np.empty(sum(len(p) for p in parts), dtype=object)
                i = 0
                for p in parts:
                    merged[i:i + len(p)] = p
                    i += len(p)
                cols[name] = merged
            else:
                cols[name] = np.concatenate(parts)
        return Table(cols)

    def group_indices(self, by: str) -> Dict[Any, np.ndarray]:
        """Map distinct value -> row indices (stable order)."""
        out: Dict[Any, List[int]] = {}
        col = self[by]
        for i in range(self._n):
            out.setdefault(col[i], []).append(i)
        return {k: np.asarray(v) for k, v in out.items()}

    def iter_batches(self, batch_size: int) -> Iterator["Table"]:
        for start in range(0, self._n, batch_size):
            yield self.slice(start, start + batch_size)

    def map_column(self, name: str, fn: Callable[[Any], Any],
                   output: Optional[str] = None) -> "Table":
        out = output or name
        return self.with_column(out, [fn(v) for v in self[name]])

    def __repr__(self):
        parts = ", ".join(
            f"{k}:{v.dtype}{list(v.shape[1:]) if v.ndim > 1 else ''}"
            for k, v in self._cols.items()
        )
        return f"Table[{self._n} rows]({parts})"


def concat_tables(tables: Sequence[Table]) -> Table:
    tables = list(tables)
    if not tables:
        return Table({})
    return tables[0].concat(*tables[1:])
