"""Executor partition-iterator protocol — the Spark/Arrow data-plane seam.

The reference's training topology is ``df.rdd.barrier().mapPartitions``:
each Spark executor task streams its partition's rows into the native
dataset, then every task fits as one ring
(ref: lightgbm/src/main/scala/com/microsoft/ml/spark/lightgbm/LightGBMBase.scala:482-486,
DatasetAggregator.scala:69-180 for the per-task chunked ingest). This module
is the TPU-native version of that seam: an executor task (a pyspark
``mapPartitions`` closure co-located on a TPU host, a Ray actor, or a plain
process) drives

    agg = PartitionAggregator(feature_cols=[...], label_col="y")
    for batch in partition_iter:          # pyarrow RecordBatch / Table,
        agg.add(batch)                    # pandas DataFrame, dict, Table
    booster = fit_partitions(params, [agg.batches...]) # or fit_aggregated

Per-host aggregation builds ONE contiguous feature matrix (so the
host->device transfer is a single placement, not a row loop); multi-host
jobs join the mesh via :mod:`synapseml_tpu.parallel.distributed`
(``rendezvous=...`` or ambient ``SYNAPSEML_*`` env). By default a
multi-host fit is ROW-SHARDED: each host bins its own rows locally and
only a capped bin-boundary sample plus per-iteration histograms cross
DCN (the reference's ``tree_learner=data_parallel`` property — rows
never leave their partition). ``row_sharded=False`` keeps the legacy
gather fallback for small data.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from synapseml_tpu.data.table import Table


def _as_table(batch: Any) -> Table:
    """Normalize one record batch to a Table (whose constructor validates
    equal column lengths) — one normalization path, shared with the rest
    of the data plane."""
    if isinstance(batch, Table):
        return batch
    if isinstance(batch, dict):
        return Table(batch)
    if getattr(batch, "column_names", None) is not None:
        return Table.from_arrow(batch)  # pyarrow RecordBatch / Table
    if getattr(batch, "columns", None) is not None:
        return Table.from_pandas(batch)  # pandas DataFrame
    raise TypeError(
        f"unsupported record-batch type {type(batch).__name__}: expected "
        "pyarrow RecordBatch/Table, pandas DataFrame, Table, or dict")


class PartitionAggregator:
    """Streams an executor's record batches into contiguous columns.

    The chunked-then-coalesced ingest the reference does natively
    (DatasetAggregator's chunked arrays): ``add`` appends cheap references;
    ``to_arrays`` concatenates ONCE into the (x, y, weight) the trainer
    wants — no per-row marshalling.
    """

    def __init__(self, feature_cols: Sequence[str],
                 label_col: str = "label",
                 weight_col: Optional[str] = None,
                 group_col: Optional[str] = None):
        """``group_col``: ranking query-group ids (LightGBMRanker's
        groupCol) — rows of one group must arrive in one executor's
        stream, as in the reference's group-aligned partitioning."""
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.weight_col = weight_col
        self.group_col = group_col
        self._chunks: List[Dict[str, np.ndarray]] = []
        self.num_rows = 0

    def _needed(self) -> List[str]:
        need = self.feature_cols + [self.label_col]
        if self.weight_col is not None:
            need.append(self.weight_col)
        if self.group_col is not None:
            need.append(self.group_col)
        return need

    def _concat_col(self, col: str, dtype) -> np.ndarray:
        if not self._chunks:
            return np.zeros(0, dtype)
        return np.concatenate([np.asarray(c[col], dtype)
                               for c in self._chunks])

    def group_array(self) -> Optional[np.ndarray]:
        """Query-group ids at their native integer width — a float64
        round trip would merge distinct ids above 2**53."""
        if self.group_col is None:
            return None
        return self._concat_col(self.group_col, np.int64)

    def add(self, batch: Any) -> "PartitionAggregator":
        t = _as_table(batch)  # Table validates equal column lengths
        missing = [c for c in self._needed() if c not in t]
        if missing:
            raise KeyError(f"record batch lacks columns {missing} "
                           f"(has: {sorted(t.columns)})")
        # keep ONLY the columns the fit reads: a wide partition must not
        # pin its unused columns in executor memory until to_arrays
        self._chunks.append({c: t[c] for c in self._needed()})
        self.num_rows += t.num_rows
        return self

    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]:
        """Concatenate once into (x, y, weight). An executor with no rows
        (empty Spark partitions are routine) yields (0, F)-shaped arrays
        so a multi-host job's other ranks aren't left hanging in the
        gather collective."""
        f = len(self.feature_cols)
        if not self._chunks:
            return (np.zeros((0, f)), np.zeros(0),
                    np.zeros(0) if self.weight_col is not None else None)
        x = np.concatenate([
            np.column_stack([np.asarray(c[fc], np.float64)
                             for fc in self.feature_cols])
            for c in self._chunks]) if f else np.zeros((self.num_rows, 0))
        y = self._concat_col(self.label_col, np.float64)
        w = None
        if self.weight_col is not None:
            w = self._concat_col(self.weight_col, np.float64)
        return x, y, w


def fit_aggregated(params, agg: PartitionAggregator, mesh=None,
                   rendezvous: Optional[Dict[str, Any]] = None,
                   row_sharded: Any = "auto",
                   stats_out: Optional[Dict[str, Any]] = None,
                   **train_kw):
    """Fit this host's aggregated rows, joining a multi-host mesh first.

    ``rendezvous``: ``{"driver_host":..., "driver_port":..., "my_host":...,
    "rank_hint":...}`` wires the host into the driver rendezvous and the
    jax.distributed runtime (parallel/distributed.py) — the TPU-native
    replacement of the reference's NetworkInit TCP ring. Without it, the
    ambient ``SYNAPSEML_*`` env (if any) is used.

    ``row_sharded``: ``"auto"`` (default) — multi-process jobs keep every
    host's rows host-local and exchange only a capped bin sample plus
    per-iteration histograms (:func:`~synapseml_tpu.gbdt.boosting.
    train_row_sharded` — the reference's ``tree_learner=data_parallel``
    scaling property, rows never leave their partition). ``False`` forces
    the legacy gather fallback: every host's rows ride DCN once and
    replicate on every host — O(total rows) per-host memory, only
    sensible for small data. ``True`` forces row-sharded even
    single-process (rows shard over local devices).
    """
    import jax

    from synapseml_tpu.gbdt.boosting import train
    from synapseml_tpu.parallel import distributed

    if rendezvous is not None:
        distributed.rendezvous_and_initialize(
            rendezvous["driver_host"], int(rendezvous["driver_port"]),
            my_host=rendezvous.get("my_host"),
            rank_hint=int(rendezvous.get("rank_hint", -1)),
            coordinator_port=int(rendezvous.get(
                "coordinator_port", 26570)))
    else:
        distributed.initialize()

    # validate the group forms BEFORE the O(n) concat (and before peers
    # start waiting on this host's collectives)
    direct_group = train_kw.pop("group", None)
    if direct_group is not None and agg.group_col is not None:
        raise TypeError(
            "pass query groups either via group_col (streamed with "
            "the batches) or via group=, not both")
    x, y, w = agg.to_arrays()
    group = agg.group_array()
    if direct_group is not None:
        group = np.asarray(direct_group)
        if group.shape[0] != x.shape[0]:
            # a short array would silently mis-pair tail rows after the
            # multi-host padding round trip — fail loudly instead
            raise ValueError(
                f"group length {group.shape[0]} != row count {x.shape[0]}")
    multi = jax.process_count() > 1
    use_rs = row_sharded is True or (row_sharded == "auto" and multi)
    if use_rs and row_sharded == "auto":
        # a custom mesh the row-sharded layout can't honor (multi-axis,
        # non-process-contiguous) keeps the gather path it always had;
        # row_sharded=True lets train_row_sharded raise the precise error
        from synapseml_tpu.gbdt.boosting import row_sharded_mesh_ok
        use_rs = row_sharded_mesh_ok(mesh)
    if use_rs:
        from synapseml_tpu.gbdt.boosting import train_row_sharded
        return train_row_sharded(params, x, y, weight=w, group=group,
                                 mesh=mesh, stats_out=stats_out, **train_kw)
    if stats_out is not None:
        # every routing outcome reports where it went and what it held,
        # so a caller asserting the accounting never reads an empty dict
        stats_out.update(path="gather" if multi else "single_process",
                         n_local=int(x.shape[0]))
    if multi:
        # gather fallback: every host materializes the global dataset
        # (per-host memory O(total rows) — small data only)
        from synapseml_tpu.parallel.distributed import host_allgather_rows

        x = host_allgather_rows(np.asarray(x, np.float64))
        y = host_allgather_rows(np.asarray(y, np.float64))
        if w is not None:
            w = host_allgather_rows(np.asarray(w, np.float64))
        if group is not None:
            # hosts commonly number queries locally (0..N each), so raw
            # ids would collide across hosts and lambdarank would pair
            # rows of unrelated queries: relabel into disjoint per-host
            # ranges first (groups must not SPAN hosts — same contract
            # as the reference's group-aligned partitioning). Applies to
            # both the group_col stream and a direct group= array.
            from jax.experimental import multihost_utils
            uniq, inv = np.unique(group, return_inverse=True)
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray([len(uniq)]))).reshape(-1)
            offset = int(counts[:jax.process_index()].sum())
            group = host_allgather_rows((inv + offset).astype(np.int64))
        if mesh is None:
            from jax.sharding import Mesh
            mesh = Mesh(np.array(jax.devices()), ("dp",))
    if x.shape[0] == 0:
        raise ValueError("no rows to fit: every partition stream was empty")
    return train(params, x, y, weight=w, group=group, mesh=mesh, **train_kw)


def fit_partitions(params, partitions: Iterable[Any],
                   feature_cols: Sequence[str], label_col: str = "label",
                   weight_col: Optional[str] = None,
                   group_col: Optional[str] = None, mesh=None,
                   rendezvous: Optional[Dict[str, Any]] = None,
                   row_sharded: Any = "auto",
                   stats_out: Optional[Dict[str, Any]] = None,
                   **train_kw):
    """One-call form: stream ``partitions`` (an iterator of record
    batches — THIS executor's partitions) through a
    :class:`PartitionAggregator` and fit. See :func:`fit_aggregated`."""
    agg = PartitionAggregator(feature_cols, label_col, weight_col,
                              group_col)
    for batch in partitions:
        agg.add(batch)
    return fit_aggregated(params, agg, mesh=mesh, rendezvous=rendezvous,
                          row_sharded=row_sharded, stats_out=stats_out,
                          **train_kw)
