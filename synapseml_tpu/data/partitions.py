"""Executor partition-iterator protocol — the Spark/Arrow data-plane seam.

The reference's training topology is ``df.rdd.barrier().mapPartitions``:
each Spark executor task streams its partition's rows into the native
dataset, then every task fits as one ring
(ref: lightgbm/src/main/scala/com/microsoft/ml/spark/lightgbm/LightGBMBase.scala:482-486,
DatasetAggregator.scala:69-180 for the per-task chunked ingest). This module
is the TPU-native version of that seam: an executor task (a pyspark
``mapPartitions`` closure co-located on a TPU host, a Ray actor, or a plain
process) drives

    agg = PartitionAggregator(feature_cols=[...], label_col="y")
    for batch in partition_iter:          # pyarrow RecordBatch / Table,
        agg.add(batch)                    # pandas DataFrame, dict, Table
    booster = fit_partitions(params, [agg.batches...]) # or fit_aggregated

Per-host aggregation builds ONE contiguous feature matrix (so the
host->device transfer is a single placement, not a row loop); multi-host
jobs join the mesh via :mod:`synapseml_tpu.parallel.distributed`
(``rendezvous=...`` or ambient ``SYNAPSEML_*`` env), after which the
dp-sharded fit psums histograms over ICI/DCN exactly like the single-host
mesh path.
"""
from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from synapseml_tpu.data.table import Table


def _as_table(batch: Any) -> Table:
    """Normalize one record batch to a Table (whose constructor validates
    equal column lengths) — one normalization path, shared with the rest
    of the data plane."""
    if isinstance(batch, Table):
        return batch
    if isinstance(batch, dict):
        return Table(batch)
    if getattr(batch, "column_names", None) is not None:
        return Table.from_arrow(batch)  # pyarrow RecordBatch / Table
    if getattr(batch, "columns", None) is not None:
        return Table.from_pandas(batch)  # pandas DataFrame
    raise TypeError(
        f"unsupported record-batch type {type(batch).__name__}: expected "
        "pyarrow RecordBatch/Table, pandas DataFrame, Table, or dict")


class PartitionAggregator:
    """Streams an executor's record batches into contiguous columns.

    The chunked-then-coalesced ingest the reference does natively
    (DatasetAggregator's chunked arrays): ``add`` appends cheap references;
    ``to_arrays`` concatenates ONCE into the (x, y, weight) the trainer
    wants — no per-row marshalling.
    """

    def __init__(self, feature_cols: Sequence[str],
                 label_col: str = "label",
                 weight_col: Optional[str] = None,
                 group_col: Optional[str] = None):
        """``group_col``: ranking query-group ids (LightGBMRanker's
        groupCol) — rows of one group must arrive in one executor's
        stream, as in the reference's group-aligned partitioning."""
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.weight_col = weight_col
        self.group_col = group_col
        self._chunks: List[Dict[str, np.ndarray]] = []
        self.num_rows = 0

    def _needed(self) -> List[str]:
        need = self.feature_cols + [self.label_col]
        if self.weight_col is not None:
            need.append(self.weight_col)
        if self.group_col is not None:
            need.append(self.group_col)
        return need

    def _concat_col(self, col: str, dtype) -> np.ndarray:
        if not self._chunks:
            return np.zeros(0, dtype)
        return np.concatenate([np.asarray(c[col], dtype)
                               for c in self._chunks])

    def group_array(self) -> Optional[np.ndarray]:
        """Query-group ids at their native integer width — a float64
        round trip would merge distinct ids above 2**53."""
        if self.group_col is None:
            return None
        return self._concat_col(self.group_col, np.int64)

    def add(self, batch: Any) -> "PartitionAggregator":
        t = _as_table(batch)  # Table validates equal column lengths
        missing = [c for c in self._needed() if c not in t]
        if missing:
            raise KeyError(f"record batch lacks columns {missing} "
                           f"(has: {sorted(t.columns)})")
        # keep ONLY the columns the fit reads: a wide partition must not
        # pin its unused columns in executor memory until to_arrays
        self._chunks.append({c: t[c] for c in self._needed()})
        self.num_rows += t.num_rows
        return self

    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]:
        """Concatenate once into (x, y, weight). An executor with no rows
        (empty Spark partitions are routine) yields (0, F)-shaped arrays
        so a multi-host job's other ranks aren't left hanging in the
        gather collective."""
        f = len(self.feature_cols)
        if not self._chunks:
            return (np.zeros((0, f)), np.zeros(0),
                    np.zeros(0) if self.weight_col is not None else None)
        x = np.concatenate([
            np.column_stack([np.asarray(c[fc], np.float64)
                             for fc in self.feature_cols])
            for c in self._chunks]) if f else np.zeros((self.num_rows, 0))
        y = self._concat_col(self.label_col, np.float64)
        w = None
        if self.weight_col is not None:
            w = self._concat_col(self.weight_col, np.float64)
        return x, y, w


def fit_aggregated(params, agg: PartitionAggregator, mesh=None,
                   rendezvous: Optional[Dict[str, Any]] = None,
                   **train_kw):
    """Fit this host's aggregated rows, joining a multi-host mesh first.

    ``rendezvous``: ``{"driver_host":..., "driver_port":..., "my_host":...,
    "rank_hint":...}`` wires the host into the driver rendezvous and the
    jax.distributed runtime (parallel/distributed.py) — the TPU-native
    replacement of the reference's NetworkInit TCP ring. Without it, the
    ambient ``SYNAPSEML_*`` env (if any) is used. Under a multi-process
    runtime, every host's rows are gathered to form the global dataset
    (rows ride DCN once), then the dp-sharded mesh fit psums histograms;
    rows therefore currently replicate per host — the mesh shards the
    *compute*.
    """
    import jax

    from synapseml_tpu.gbdt.boosting import train
    from synapseml_tpu.parallel import distributed

    if rendezvous is not None:
        distributed.rendezvous_and_initialize(
            rendezvous["driver_host"], int(rendezvous["driver_port"]),
            my_host=rendezvous.get("my_host"),
            rank_hint=int(rendezvous.get("rank_hint", -1)),
            coordinator_port=int(rendezvous.get(
                "coordinator_port", 26570)))
    else:
        distributed.initialize()

    # validate the group forms BEFORE the O(n) concat (and before peers
    # start waiting on this host's gather)
    direct_group = train_kw.pop("group", None)
    if direct_group is not None and agg.group_col is not None:
        raise TypeError(
            "pass query groups either via group_col (streamed with "
            "the batches) or via group=, not both")
    x, y, w = agg.to_arrays()
    group = agg.group_array()
    if direct_group is not None:
        # direct group= arrays work single-host; multi-host needs the
        # per-host relabel below, which only the group_col path gets
        group = np.asarray(direct_group)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # per-host row counts differ: pad to the global max, gather, trim
        n_local = np.asarray([x.shape[0]])
        n_all = np.asarray(multihost_utils.process_allgather(n_local)
                           ).reshape(-1)
        n_max = max(int(n_all.max()), 1)  # keep the collective well-shaped
                                          # even when every host is empty

        def gather_64(a):
            """Bit-exact gather of any 8-byte dtype (float64/int64): jax
            would canonicalize them to 32-bit with x64 disabled, and a
            rounding that crosses a bin quantile (or merges two query
            ids) would silently break the single-fit identity — so the
            values ride as uint32 words and come back in their dtype."""
            dt = a.dtype
            a = np.ascontiguousarray(
                np.pad(a, [(0, n_max - a.shape[0])]
                       + [(0, 0)] * (a.ndim - 1)))
            words = a.view(np.uint32).reshape(n_max, -1)
            out = np.asarray(multihost_utils.process_allgather(words))
            out = out.reshape(len(n_all), n_max, -1)
            return np.concatenate([
                out[i, :n_all[i]].reshape(-1).view(dt).reshape(
                    (n_all[i],) + a.shape[1:])
                for i in range(len(n_all))])

        x = gather_64(np.asarray(x, np.float64))
        y = gather_64(np.asarray(y, np.float64))
        if w is not None:
            w = gather_64(np.asarray(w, np.float64))
        if group is not None:
            # hosts commonly number queries locally (0..N each), so raw
            # ids would collide across hosts and lambdarank would pair
            # rows of unrelated queries: relabel into disjoint per-host
            # ranges first (groups must not SPAN hosts — same contract
            # as the reference's group-aligned partitioning)
            uniq, inv = np.unique(group, return_inverse=True)
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray([len(uniq)]))).reshape(-1)
            offset = int(counts[:jax.process_index()].sum())
            group = gather_64((inv + offset).astype(np.int64))
        if mesh is None:
            from jax.sharding import Mesh
            mesh = Mesh(np.array(jax.devices()), ("dp",))
    if x.shape[0] == 0:
        raise ValueError("no rows to fit: every partition stream was empty")
    return train(params, x, y, weight=w, group=group, mesh=mesh, **train_kw)


def fit_partitions(params, partitions: Iterable[Any],
                   feature_cols: Sequence[str], label_col: str = "label",
                   weight_col: Optional[str] = None,
                   group_col: Optional[str] = None, mesh=None,
                   rendezvous: Optional[Dict[str, Any]] = None,
                   **train_kw):
    """One-call form: stream ``partitions`` (an iterator of record
    batches — THIS executor's partitions) through a
    :class:`PartitionAggregator` and fit. See :func:`fit_aggregated`."""
    agg = PartitionAggregator(feature_cols, label_col, weight_col,
                              group_col)
    for batch in partitions:
        agg.add(batch)
    return fit_aggregated(params, agg, mesh=mesh, rendezvous=rendezvous,
                          **train_kw)
