"""Utility pipeline transformers.

TPU-native re-design of the reference's stage zoo
(ref: core/src/main/scala/com/microsoft/ml/spark/stages/ — DropColumns ~40 LoC,
SelectColumns, RenameColumn, Repartition, StratifiedRepartition.scala:31,
EnsembleByKey.scala:152, Explode.scala:43, Lambda.scala:22,
UDFTransformer.scala:112, MultiColumnAdapter.scala:135, TextPreprocessor.scala:98,
UnicodeNormalize.scala:22, ClassBalancer.scala:25, Timer.scala:55,
SummarizeData.scala:101, Cacher.scala:43, udfs.scala:36).

Stages operate on the columnar :class:`Table`; anything numeric is vectorized
numpy/jax rather than per-row UDF dispatch, because a fused columnar op is the
TPU-friendly shape of this work (one host→device transfer per column, not per
row).
"""
from __future__ import annotations

import logging
import time
import unicodedata
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from synapseml_tpu.core.param import (
    ComplexParam,
    HasInputCol,
    HasInputCols,
    HasLabelCol,
    HasOutputCol,
    Param,
)
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table, concat_tables
from synapseml_tpu.runtime.locksan import make_lock

logger = logging.getLogger("synapseml_tpu")


class DropColumns(Transformer):
    """Drop the named columns (ref: stages/DropColumns.scala)."""

    cols = Param("columns to drop", default=())

    def __init__(self, cols: Sequence[str] = (), **kw):
        super().__init__(**kw)
        self.set(cols=list(cols))

    def _transform(self, table: Table) -> Table:
        return table.drop(*self.cols)


class SelectColumns(Transformer):
    """Keep only the named columns (ref: stages/SelectColumns.scala)."""

    cols = Param("columns to keep", default=())

    def __init__(self, cols: Sequence[str] = (), **kw):
        super().__init__(**kw)
        self.set(cols=list(cols))

    def _transform(self, table: Table) -> Table:
        return table.select(*self.cols)


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    """Rename one column (ref: stages/RenameColumn.scala)."""

    def _transform(self, table: Table) -> Table:
        return table.rename({self.input_col: self.output_col})


class Repartition(Transformer):
    """Re-chunk the table into ``n`` near-equal shards.

    The reference reshuffles Spark partitions (ref: stages/Repartition.scala);
    here a Table is one contiguous block, so "repartition" records the shard
    boundaries used downstream by the batched executor and distributed trainers
    (shards become the per-device leading dim).
    """

    n = Param("number of partitions", default=1)
    disable = Param("pass-through when true", default=False)

    def __init__(self, n: int = 1, **kw):
        super().__init__(**kw)
        self.set(n=n)

    def _transform(self, table: Table) -> Table:
        return table

    def shards(self, table: Table) -> List[Table]:
        if self.disable:
            return [table]
        bounds = np.linspace(0, table.num_rows, self.n + 1).astype(int)
        return [table.slice(int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])]


class StratifiedRepartition(Transformer, HasLabelCol):
    """Rebalance rows so each shard sees every label
    (ref: stages/StratifiedRepartition.scala:31 — per-label round-robin)."""

    n = Param("number of partitions", default=1)
    mode = Param("equal | original | mixed", default="mixed")

    def _transform(self, table: Table) -> Table:
        labels = table[self.label_col]
        order: List[int] = []
        groups = [list(idx) for idx in table.group_indices(self.label_col).values()]
        # round-robin interleave so every contiguous shard contains all labels
        i = 0
        while any(groups):
            for g in groups:
                if i < len(g):
                    order.append(g[i])
            i += 1
            groups = [g for g in groups if i <= len(g)]
        del labels
        return table.take(np.asarray(order[: table.num_rows], dtype=int))


class EnsembleByKey(Transformer):
    """Group rows by key columns and average the named vector/scalar columns
    (ref: stages/EnsembleByKey.scala:152)."""

    keys = Param("key columns", default=())
    cols = Param("value columns to ensemble", default=())
    strategy = Param("only 'mean' is supported, as in the reference", default="mean")
    collapse_group = Param("emit one row per key when true", default=True)
    vector_dims = ComplexParam("optional {col: dim} checks", default=None)

    def __init__(self, keys: Sequence[str] = (), cols: Sequence[str] = (), **kw):
        super().__init__(**kw)
        self.set(keys=list(keys), cols=list(cols))

    def _transform(self, table: Table) -> Table:
        keys, cols = list(self.keys), list(self.cols)
        # tuple keys, not concatenated strings: ('x','yz') must not collide
        # with ('xy','z')
        key_col = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            key_col[i] = tuple(table[k][i] for k in keys)
        tmp = table.with_column("__ensemble_key__", key_col)
        groups = tmp.group_indices("__ensemble_key__")
        out_rows: Dict[str, List[Any]] = {k: [] for k in keys}
        means: Dict[str, List[Any]] = {f"mean({c})": [] for c in cols}
        for _, idx in groups.items():
            for k in keys:
                out_rows[k].append(table[k][idx[0]])
            for c in cols:
                means[f"mean({c})"].append(np.mean(np.stack([table[c][i] for i in idx]), axis=0))
        if self.collapse_group:
            return Table({**out_rows, **means})
        # broadcast group means back onto original rows
        expanded = {name: [None] * table.num_rows for name in means}
        for gi, (_, idx) in enumerate(groups.items()):
            for name in means:
                for i in idx:
                    expanded[name][i] = means[name][gi]
        return table.with_columns({n: np.asarray(v) if np.asarray(v).dtype != object else _obj(v)
                                   for n, v in expanded.items()})


def _obj(values: List[Any]) -> np.ndarray:
    arr = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        arr[i] = v
    return arr


class Explode(Transformer, HasInputCol, HasOutputCol):
    """One output row per element of an array column (ref: stages/Explode.scala:43)."""

    def _transform(self, table: Table) -> Table:
        col = table[self.input_col]
        counts = np.asarray([len(v) for v in col], dtype=np.int64)
        rep = np.repeat(np.arange(table.num_rows), counts)
        exploded = _obj([x for v in col for x in v])
        base = table.take(rep)
        if exploded.size and not isinstance(exploded[0], (list, np.ndarray, dict)):
            exploded = np.asarray(list(exploded))
        return base.with_column(self.output_col, exploded)


class Lambda(Transformer):
    """Arbitrary Table -> Table function as a stage (ref: stages/Lambda.scala:22)."""

    fn = ComplexParam("table -> table callable")

    def __init__(self, fn: Optional[Callable[[Table], Table]] = None, **kw):
        super().__init__(**kw)
        if fn is not None:
            self.set(fn=fn)

    def _transform(self, table: Table) -> Table:
        return self.fn(table)


class UDFTransformer(Transformer, HasInputCol, HasInputCols, HasOutputCol):
    """Apply a per-row (or whole-column when ``vectorized``) function
    (ref: stages/UDFTransformer.scala:112)."""

    udf = ComplexParam("row function")
    vectorized = Param("when true, udf receives whole column array(s)", default=False)

    def __init__(self, udf: Optional[Callable] = None, **kw):
        super().__init__(**kw)
        if udf is not None:
            self.set(udf=udf)

    def _transform(self, table: Table) -> Table:
        fn = self.udf
        cols = self.input_cols or [self.input_col]
        arrays = [table[c] for c in cols]
        if self.vectorized:
            out = fn(*arrays)
        else:
            out = [fn(*vals) for vals in zip(*arrays)]
        return table.with_column(self.output_col, out)


class MultiColumnAdapter(Transformer):
    """Apply one single-column transformer across many column pairs
    (ref: stages/MultiColumnAdapter.scala:135)."""

    base_stage = ComplexParam("single-col transformer/estimator to replicate")
    input_cols = Param("input columns", default=())
    output_cols = Param("output columns", default=())

    def __init__(self, base_stage=None, input_cols=(), output_cols=(), **kw):
        super().__init__(**kw)
        if base_stage is not None:
            self.set(base_stage=base_stage)
        self.set(input_cols=list(input_cols), output_cols=list(output_cols))

    def _pairs(self):
        ins, outs = list(self.input_cols), list(self.output_cols)
        if len(ins) != len(outs):
            raise ValueError("input_cols and output_cols must have equal length")
        return list(zip(ins, outs))

    def _transform(self, table: Table) -> Table:
        for i, o in self._pairs():
            stage = self.base_stage.copy(input_col=i, output_col=o)
            table = stage.transform(table)
        return table

    def fit(self, table: Table) -> "MultiColumnAdapterModel":
        fitted = []
        for i, o in self._pairs():
            stage = self.base_stage.copy(input_col=i, output_col=o)
            fitted.append(stage.fit(table) if isinstance(stage, Estimator) else stage)
        return MultiColumnAdapterModel(stages=fitted)


class MultiColumnAdapterModel(Model):
    stages = ComplexParam("fitted per-column stages")

    def __init__(self, stages=None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=stages)

    def _transform(self, table: Table) -> Table:
        for s in self.stages:
            table = s.transform(table)
        return table


class _TrieNode(dict):
    __slots__ = ("value",)

    def __init__(self):
        super().__init__()
        self.value: Optional[str] = None


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Longest-match replacement via a trie over the map keys
    (ref: stages/TextPreprocessor.scala:98 — trie-based normalization)."""

    map = ComplexParam("substring -> replacement map", default=None)
    normalize_pattern = Param("chars-to-strip regex (applied before match)", default=None)

    def __init__(self, map: Optional[Dict[str, str]] = None, **kw):
        super().__init__(**kw)
        if map is not None:
            self.set(map=map)

    def _build_trie(self) -> _TrieNode:
        root = _TrieNode()
        for key, val in (self.map or {}).items():
            node = root
            for ch in key:
                node = node.setdefault(ch, _TrieNode())
            node.value = val
        return root

    def _transform(self, table: Table) -> Table:
        trie = self._build_trie()

        def process(text: str) -> str:
            out, i, n = [], 0, len(text)
            while i < n:
                node, j, best, best_end = trie, i, None, i
                while j < n and text[j] in node:
                    node = node[text[j]]
                    j += 1
                    if node.value is not None:
                        best, best_end = node.value, j
                if best is not None:
                    out.append(best)
                    i = best_end
                else:
                    out.append(text[i])
                    i += 1
            return "".join(out)

        return table.map_column(self.input_col, process, self.output_col)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """NFC/NFD/NFKC/NFKD + optional lower-casing (ref: stages/UnicodeNormalize.scala:22)."""

    form = Param("unicode normal form", default="NFKD")
    lower = Param("lower-case the output", default=True)

    def _transform(self, table: Table) -> Table:
        def norm(s: str) -> str:
            s = unicodedata.normalize(self.form, s)
            return s.lower() if self.lower else s

        return table.map_column(self.input_col, norm, self.output_col)


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Adds a weight column inversely proportional to class frequency
    (ref: stages/ClassBalancer.scala:25)."""

    broadcast_join = Param("kept for API parity; join is columnar here", default=True)

    def __init__(self, input_col: str = "label", output_col: str = "weight", **kw):
        super().__init__(**kw)
        self.set(input_col=input_col, output_col=output_col)

    def _fit(self, table: Table) -> "ClassBalancerModel":
        col = table[self.input_col]
        values, counts = np.unique(col.astype(str), return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        return ClassBalancerModel(
            weights={v: float(w) for v, w in zip(values, weights)},
            input_col=self.input_col, output_col=self.output_col)


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    weights = ComplexParam("class -> weight")

    def __init__(self, weights=None, **kw):
        super().__init__(**kw)
        if weights is not None:
            self.set(weights=weights)

    def _transform(self, table: Table) -> Table:
        w = self.weights
        col = table[self.input_col]
        return table.with_column(
            self.output_col,
            np.asarray([w[str(v)] for v in col], dtype=np.float64))


class Timer(Estimator):
    """Wrap a stage; log wall-clock of its fit/transform
    (ref: stages/Timer.scala:55)."""

    stage = ComplexParam("wrapped stage")
    log_to_scala = Param("kept for parity; logs via python logging", default=True)
    disable = Param("pass-through when true", default=False)

    def __init__(self, stage=None, **kw):
        super().__init__(**kw)
        if stage is not None:
            self.set(stage=stage)

    def _fit(self, table: Table) -> "TimerModel":
        inner = self.stage
        if isinstance(inner, Estimator):
            t0 = time.time()
            fitted = inner.fit(table)
            if not self.disable:
                logger.info("%s took %.3fs to fit", inner, time.time() - t0)
            return TimerModel(stage=fitted, disable=self.disable)
        return TimerModel(stage=inner, disable=self.disable)


class TimerModel(Model):
    stage = ComplexParam("wrapped fitted stage")
    disable = Param("pass-through when true", default=False)

    def __init__(self, stage=None, **kw):
        super().__init__(**kw)
        if stage is not None:
            self.set(stage=stage)

    def _transform(self, table: Table) -> Table:
        t0 = time.time()
        out = self.stage.transform(table)
        if not self.disable:
            logger.info("%s took %.3fs to transform", self.stage, time.time() - t0)
        return out


class SummarizeData(Transformer):
    """Counts / quantiles / missing / basic stats per column
    (ref: stages/SummarizeData.scala:101)."""

    counts = Param("emit count block", default=True)
    basic = Param("emit basic block", default=True)
    sample = Param("emit sample quantile block", default=True)
    percentiles = Param("emit percentile block", default=True)
    error_threshold = Param("quantile error (parity; exact here)", default=0.0)

    _PCTS = (0.005, 0.01, 0.05, 0.25, 0.5, 0.75, 0.95, 0.99, 0.995)

    def _transform(self, table: Table) -> Table:
        rows: Dict[str, List[Any]] = {"Feature": []}

        def put(name: str, val: Any):
            rows.setdefault(name, []).append(val)

        for name in table.columns:
            col = table[name]
            rows["Feature"].append(name)
            is_num = col.dtype.kind in "biufc" and col.ndim == 1
            numeric = col.astype(np.float64) if is_num else None
            if self.counts:
                put("Count", float(len(col)))
                missing = (
                    float(np.isnan(numeric).sum()) if is_num
                    else float(sum(v is None for v in col)))
                put("Missing Value Count", missing)
                uniq = (len(np.unique(col[~np.isnan(numeric)])) if is_num
                        else len({str(v) for v in col}))
                put("Unique Value Count", float(uniq))
            if self.basic:
                put("Min", float(np.nanmin(numeric)) if is_num and len(col) else np.nan)
                put("Max", float(np.nanmax(numeric)) if is_num and len(col) else np.nan)
                put("Mean", float(np.nanmean(numeric)) if is_num and len(col) else np.nan)
                put("Variance", float(np.nanvar(numeric, ddof=1)) if is_num and len(col) > 1 else np.nan)
            if self.sample:
                put("Sample Variance", float(np.nanvar(numeric, ddof=1)) if is_num and len(col) > 1 else np.nan)
                put("Sample Standard Deviation",
                    float(np.nanstd(numeric, ddof=1)) if is_num and len(col) > 1 else np.nan)
            if self.percentiles:
                for p in self._PCTS:
                    put(f"P{p}", float(np.nanquantile(numeric, p)) if is_num and len(col) else np.nan)
        return Table(rows)


class Cacher(Transformer):
    """Materializes/pins the table (ref: stages/Cacher.scala:43).

    Tables are already host-resident numpy; cache here means pre-staging the
    numeric columns onto the TPU device and keeping them alive in
    ``device_cache`` so device-aware consumers (the batched executor,
    trainers) can reuse the staged copy instead of re-transferring.
    """

    disable = Param("pass-through when true", default=False)
    device_put = Param("stage numeric columns onto the default device", default=True)

    @property
    def device_cache(self) -> Dict[str, Any]:
        # Lazy: Params.copy() / PipelineStage.load() construct via __new__ and
        # skip subclass __init__, so the cache must not live in __init__.
        return self.__dict__.setdefault("_device_cache", {})

    def device_column(self, name: str):
        """The staged device array for a column, if cached."""
        return self.device_cache.get(name)

    def _transform(self, table: Table) -> Table:
        if self.disable or not self.device_put:
            return table
        import jax

        for name in table.columns:
            col = table[name]
            if col.dtype.kind in "biuf":
                self.device_cache[name] = jax.device_put(col)
        return table


class PartitionConsolidator(Transformer, HasInputCol, HasOutputCol):
    """Funnel many shards' rows through one worker (rate-limited services)
    (ref: stages/PartitionConsolidator.scala:20-139).

    Reference semantics: every partition feeds its rows into a shared,
    executor-local ``Consolidator``; exactly one partition (the first to
    arrive) is elected the output worker and emits everything, the rest emit
    nothing. Here ``transform`` is called once per shard (possibly from
    concurrent threads, e.g. the per-shard serving workers in
    :mod:`synapseml_tpu.io.serving`): the elected owner's call returns all
    rows buffered so far, non-owners return an empty table. Rows fed after
    the owner's last drain stay buffered; the epoch driver collects them with
    :meth:`flush` (the analogue of the reference's drain-until-complete loop).
    """

    concurrency = Param("number of concurrent consumers after consolidation", default=1)

    @property
    def _state(self):
        import threading

        st = self.__dict__.get("_consolidator_state")
        if st is None:
            st = {"lock": make_lock("st['lock']"), "buffer": [],
                  "owner": None}
            self.__dict__["_consolidator_state"] = st
        return st

    @staticmethod
    def _merge(tables: Sequence[Table], schema_of: Table) -> Table:
        nonempty = [t for t in tables if t.num_rows]
        if not nonempty:
            return Table({c: schema_of[c][:0] for c in schema_of.columns})
        return concat_tables(nonempty)

    def _transform(self, table: Table) -> Table:
        import threading

        st = self._state
        me = threading.get_ident()
        with st["lock"]:
            st["buffer"].append(table)
            if st["owner"] is None:
                st["owner"] = me
            if st["owner"] == me:
                merged = self._merge(st["buffer"], table)
                st["buffer"].clear()
                return merged
        return Table({c: table[c][:0] for c in table.columns})

    def flush(self) -> Optional[Table]:
        """Drain rows buffered since the owner's last call (end of epoch);
        None when nothing is pending."""
        st = self._state
        with st["lock"]:
            pending = [t for t in st["buffer"] if t.num_rows]
            st["buffer"].clear()
        if not pending:
            return None
        return concat_tables(pending)

    def reset(self):
        """Clear buffered rows and the owner election (new epoch)."""
        self.__dict__.pop("_consolidator_state", None)

    def consolidate(self, shards: Sequence[Table]) -> List[Table]:
        """One-shot helper: [shard...] -> [merged, empty...]."""
        if not shards:
            return []
        merged = self._merge(shards, shards[0])
        return [merged] + [
            Table({c: s[c][:0] for c in s.columns}) for s in shards[1:]]
