from synapseml_tpu.data.batching import (
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    TimeIntervalMiniBatchTransformer,
)
from synapseml_tpu.stages.transformers import (
    Cacher,
    ClassBalancer,
    ClassBalancerModel,
    DropColumns,
    EnsembleByKey,
    Explode,
    Lambda,
    MultiColumnAdapter,
    MultiColumnAdapterModel,
    PartitionConsolidator,
    RenameColumn,
    Repartition,
    SelectColumns,
    StratifiedRepartition,
    SummarizeData,
    TextPreprocessor,
    Timer,
    TimerModel,
    UDFTransformer,
    UnicodeNormalize,
)

__all__ = [
    "Cacher", "ClassBalancer", "ClassBalancerModel", "DropColumns",
    "DynamicMiniBatchTransformer", "EnsembleByKey", "Explode",
    "FixedMiniBatchTransformer", "FlattenBatch", "Lambda",
    "MultiColumnAdapter", "MultiColumnAdapterModel", "PartitionConsolidator",
    "RenameColumn", "Repartition", "SelectColumns", "StratifiedRepartition",
    "SummarizeData", "TextPreprocessor", "TimeIntervalMiniBatchTransformer",
    "Timer", "TimerModel", "UDFTransformer", "UnicodeNormalize",
]
