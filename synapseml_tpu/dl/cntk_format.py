"""CNTK v2 binary ``.model`` reader: protobuf Dictionary -> ONNX -> jax.

The reference executes native ``.model`` files through the CNTK 2.4 JNI
runtime (ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/
SerializableFunction.scala:85-143 — ``Function.load`` on broadcast
bytes). That runtime is dead and CUDA/CPU-only, so here the *format*
is parsed directly: CNTK-2.x model files are a serialized ``Dictionary``
protobuf (the CNTKv2LibraryDll ``CNTK.proto`` schema — NDShape/Axis/
NDArrayView/Vector/Dictionary/DictionaryValue messages) holding a
``CompositeFunction``: a vector of primitive functions wired by variable
uids (outputs follow the ``<func_uid>_Output_<k>`` convention) plus the
parameter/constant payloads. The graph is re-emitted as ONNX and lowered
through the standard importer, so every op lands on the same jit path as
user ONNX files.

Format notes (why the reshapes below look reversed): CNTK NDShapes store
dimensions fastest-varying first and tensors column-major; reading the
flat payload row-major with the dims REVERSED yields the numpy/ONNX
layout directly (a conv kernel ``(kW,kH,Cin,Cout)`` becomes
``(Cout,Cin,kH,kW)``). The batch axis is a dynamic axis — absent from
shapes — and maps to the leading "N" dim; a CNTK static axis index k
(0 = fastest) maps to negative numpy axis ``-(k+1)``.

Supported op surface: the feedforward model-zoo diet (Times/Plus/
activation chains, Convolution, Pooling, BatchNormalization, Reshape,
Splice, Slice, TransposeAxes, ReduceElements, Clip, Dropout/NoOp
passthrough, Combine). Recurrent ops (PastValue/OptimizedRNNStack)
raise with the ONNX-export recipe, as before.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.onnx import proto
from synapseml_tpu.onnx.builder import GraphBuilder
from synapseml_tpu.onnx.proto import F, Msg

# ---------------------------------------------------------------------------
# CNTK.proto subset (field numbers frozen by protobuf compatibility)
# ---------------------------------------------------------------------------

_CNTK_SCHEMAS = {
    "CntkNDShape": [F(1, "shape_dim", "int64", repeated=True)],
    "CntkAxis": [
        F(1, "static_axis_idx", "int64"),
        F(2, "name", "string"),
        F(3, "is_ordered_dynamic_axis", "int64"),
    ],
    "CntkFloatValues": [F(1, "value", "float", repeated=True)],
    "CntkDoubleValues": [F(1, "value", "double", repeated=True)],
    "CntkNDArrayView": [
        F(1, "data_type", "int64"),      # 1 = Float, 2 = Double
        F(2, "storage_format", "int64"),  # 0 = Dense
        F(3, "shape", "message", message="CntkNDShape"),
        F(4, "float_values", "message", message="CntkFloatValues"),
        F(5, "double_values", "message", message="CntkDoubleValues"),
    ],
    "CntkVector": [
        F(1, "value", "message", repeated=True,
          message="CntkDictionaryValue"),
    ],
    "CntkDictionary": [
        F(1, "version", "int64"),
        F(2, "data", "message", repeated=True,
          message="CntkDictionaryEntry"),
    ],
    "CntkDictionaryEntry": [  # protobuf map<string, DictionaryValue> entry
        F(1, "key", "string"),
        F(2, "value", "message", message="CntkDictionaryValue"),
    ],
    "CntkDictionaryValue": [
        F(1, "version", "int64"),
        F(2, "bool_value", "int64"),
        F(3, "int_value", "int64"),
        F(4, "size_t_value", "int64"),
        F(5, "float_value", "float"),
        F(6, "double_value", "double"),
        F(7, "string_value", "string"),
        F(8, "nd_shape_value", "message", message="CntkNDShape"),
        F(9, "axis_value", "message", message="CntkAxis"),
        F(10, "vector_value", "message", message="CntkVector"),
        F(11, "dictionary_value", "message", message="CntkDictionary"),
        F(12, "nd_array_view_value", "message", message="CntkNDArrayView"),
    ],
}
proto._SCHEMAS.update(_CNTK_SCHEMAS)

_INFERRED = (1 << 64) - 1  # NDShape::InferredDimension, wraps to -1 signed


class CntkAxisRef:
    __slots__ = ("static_axis_idx", "name")

    def __init__(self, idx: int, name: str = ""):
        self.static_axis_idx = int(idx)
        self.name = name


def _shape_dims(shape_msg: Msg) -> List[int]:
    return [int(d) for d in (shape_msg.shape_dim or [])]


def _ndarray_to_numpy(view: Msg) -> np.ndarray:
    dims = _shape_dims(view.shape) if view.shape is not None else []
    if int(view.storage_format or 0) != 0:
        raise NotImplementedError(
            "sparse NDArrayView payloads are not supported")
    if view.float_values is not None:
        flat = np.asarray(view.float_values.value, np.float32)
    elif view.double_values is not None:
        flat = np.asarray(view.double_values.value, np.float64)
    else:
        flat = np.zeros(0, np.float32)
    # CNTK stores column-major with fastest-varying dim first; reversing
    # the dims makes the row-major read correct
    return flat.reshape(tuple(reversed(dims))) if dims else flat


def _numpy_to_ndarray(arr: np.ndarray) -> Msg:
    view = Msg("CntkNDArrayView")
    view.data_type = 2 if arr.dtype == np.float64 else 1
    view.storage_format = 0
    shp = Msg("CntkNDShape")
    shp.shape_dim = [int(d) for d in reversed(arr.shape)]
    view.shape = shp
    vals = Msg("CntkDoubleValues" if arr.dtype == np.float64
               else "CntkFloatValues")
    vals.value = [float(v) for v in np.asarray(arr).reshape(-1)]
    if arr.dtype == np.float64:
        view.double_values = vals
    else:
        view.float_values = vals
    return view


def value_to_py(v: Msg) -> Any:
    """DictionaryValue -> python (dict / list / ndarray / scalar)."""
    if v.dictionary_value is not None:
        return dict_to_py(v.dictionary_value)
    if v.vector_value is not None:
        return [value_to_py(e) for e in v.vector_value.value]
    if v.nd_array_view_value is not None:
        return _ndarray_to_numpy(v.nd_array_view_value)
    if v.nd_shape_value is not None:
        return _shape_dims(v.nd_shape_value)
    if v.axis_value is not None:
        return CntkAxisRef(v.axis_value.static_axis_idx or 0,
                           v.axis_value.name or "")
    if v.string_value is not None:
        return v.string_value
    if v.float_value is not None:
        return float(v.float_value)
    if v.double_value is not None:
        return float(v.double_value)
    if v.size_t_value is not None:
        return int(v.size_t_value) & ((1 << 64) - 1)
    if v.int_value is not None:
        return int(v.int_value)
    if v.bool_value is not None:
        return bool(v.bool_value)
    return None  # proto3 default (False / 0 / "") never reaches the wire


def dict_to_py(d: Msg) -> Dict[str, Any]:
    return {e.key: value_to_py(e.value) for e in (d.data or [])}


def py_to_value(v: Any) -> Msg:
    out = Msg("CntkDictionaryValue")
    out.version = 1
    if isinstance(v, dict):
        out.dictionary_value = py_to_dict(v)
    elif isinstance(v, (list, tuple)) and not isinstance(v, str):
        if v and all(isinstance(x, (int, np.integer)) for x in v):
            shp = Msg("CntkNDShape")
            shp.shape_dim = [int(x) for x in v]
            out.nd_shape_value = shp
        else:
            vec = Msg("CntkVector")
            vec.value = [py_to_value(x) for x in v]
            out.vector_value = vec
    elif isinstance(v, np.ndarray):
        out.nd_array_view_value = _numpy_to_ndarray(v)
    elif isinstance(v, CntkAxisRef):
        ax = Msg("CntkAxis")
        ax.static_axis_idx = v.static_axis_idx
        ax.name = v.name
        out.axis_value = ax
    elif isinstance(v, bool):
        out.bool_value = int(v)
    elif isinstance(v, (int, np.integer)):
        # CNTK keeps signed attribute ints (slice begin/end) in
        # int_value; size_t_value is unsigned and would mask negatives
        # into 2^64-range garbage on the read side
        if int(v) < 0:
            out.int_value = int(v)
        else:
            out.size_t_value = int(v)
    elif isinstance(v, float):
        out.double_value = v
    elif isinstance(v, str):
        out.string_value = v
    else:
        raise TypeError(f"cannot serialize {type(v)} into a CNTK "
                        f"DictionaryValue")
    return out


def py_to_dict(d: Dict[str, Any]) -> Msg:
    out = Msg("CntkDictionary")
    out.version = 1
    entries = []
    for k, v in d.items():
        e = Msg("CntkDictionaryEntry")
        e.key = k
        e.value = py_to_value(v)
        entries.append(e)
    out.data = entries
    return out


def load_model_dictionary(payload: bytes) -> Dict[str, Any]:
    return dict_to_py(proto.decode("CntkDictionary", payload))


# ---------------------------------------------------------------------------
# PrimitiveOpType (CNTK 2.x PrimitiveOpType.h enum order)
# ---------------------------------------------------------------------------

OP_NEGATE, OP_SIGMOID, OP_TANH, OP_RELU, OP_EXP, OP_LOG, OP_SQRT = range(7)
OP_FLOOR, OP_ABS, OP_RECIPROCAL, OP_SOFTMAX, OP_HARDMAX = 7, 8, 9, 10, 11
OP_TRANSPOSE_AXES, OP_WHERE, OP_SLICE, OP_DROPOUT, OP_RESHAPE = 12, 13, 14, 15, 16
OP_POOLING, OP_SUM_ALL, OP_PLUS, OP_LOG_PLUS, OP_MINUS = 17, 18, 19, 20, 21
OP_ELEMENT_TIMES, OP_EQUAL, OP_NOT_EQUAL, OP_LESS = 22, 23, 24, 25
OP_LESS_EQUAL, OP_GREATER, OP_GREATER_EQUAL = 26, 27, 28
OP_TIMES, OP_TRANSPOSE_TIMES, OP_CONVOLUTION = 32, 33, 34
OP_PAST_VALUE, OP_FUTURE_VALUE, OP_REDUCE_ELEMENTS = 38, 39, 40
OP_BATCH_NORM, OP_CLIP, OP_SELECT, OP_SPLICE, OP_COMBINE = 41, 42, 43, 44, 45
OP_LOG_SOFTMAX, OP_NO_OP, OP_STOP_GRADIENT, OP_ELU = 52, 56, 58, 59

_UNARY = {
    OP_NEGATE: "Neg", OP_SIGMOID: "Sigmoid", OP_TANH: "Tanh",
    OP_RELU: "Relu", OP_EXP: "Exp", OP_LOG: "Log", OP_SQRT: "Sqrt",
    OP_FLOOR: "Floor", OP_ABS: "Abs", OP_RECIPROCAL: "Reciprocal",
    OP_ELU: "Elu",
}
_BINARY = {OP_PLUS: "Add", OP_MINUS: "Sub", OP_ELEMENT_TIMES: "Mul"}


class _Var:
    __slots__ = ("uid", "kind", "shape", "value", "name")

    def __init__(self, d: Dict[str, Any]):
        self.uid = d["uid"]
        self.kind = int(d.get("kind", 0))
        self.shape = [int(s) for s in d.get("shape", [])]
        self.value = d.get("value")
        self.name = d.get("name", "")


VAR_INPUT, VAR_OUTPUT, VAR_PARAMETER, VAR_CONSTANT, VAR_PLACEHOLDER = range(5)


def cntk_to_onnx(payload: bytes,
                 parsed: Optional[Dict[str, Any]] = None) -> bytes:
    """Parse ``.model`` bytes and re-emit the graph as ONNX bytes.
    ``parsed`` skips the (pure-Python, weight-heavy) protobuf decode when
    the caller already holds the Dictionary from the sniff."""
    top = parsed if parsed is not None else load_model_dictionary(payload)
    if top.get("type") != "CompositeFunction":
        raise ValueError(
            f"not a CNTK v2 CompositeFunction dictionary "
            f"(type={top.get('type')!r})")
    variables = {v["uid"]: _Var(v) for v in top.get("inputs", [])}
    functions = top.get("primitive_functions", [])
    root = top.get("root")

    g = GraphBuilder(name=top.get("name") or "cntk_model", opset=17)
    names: Dict[str, str] = {}   # cntk variable uid -> onnx tensor name

    def resolve(uid: str, transpose_param: bool = False) -> str:
        # a shared parameter may be consumed in BOTH orientations
        # (weight tying): the cache key carries the flip
        key = (uid, transpose_param)
        if key in names:
            return names[key]
        var = variables.get(uid)
        if var is None:
            raise KeyError(f"dangling variable uid {uid!r}")
        if var.kind in (VAR_PARAMETER, VAR_CONSTANT):
            arr = np.asarray(var.value)
            if transpose_param:
                arr = np.ascontiguousarray(arr.T)
            nm = g.add_initializer(g.fresh(var.name or uid), arr)
        elif var.kind == VAR_INPUT:
            if transpose_param:
                raise NotImplementedError(
                    "Times with a non-parameter weight operand needs a "
                    "runtime transpose; export to ONNX with the cntk "
                    "package for this graph")
            nm = g.add_input(var.name or uid, np.float32,
                             ["N"] + list(reversed(var.shape)))
        else:
            raise ValueError(f"unresolvable variable {uid!r} "
                             f"(kind={var.kind})")
        names[key] = nm
        return nm

    def np_axis(attr) -> int:
        k = attr.static_axis_idx if isinstance(attr, CntkAxisRef) \
            else int(attr)
        return -(k + 1)

    def is_param(uid: str) -> bool:
        v = variables.get(uid)
        return v is not None and v.kind in (VAR_PARAMETER, VAR_CONSTANT)

    last_output = None
    for fd in functions:
        op = int(fd["op"])
        uid = fd["uid"]
        ins: List[str] = list(fd.get("inputs", []))
        attrs: Dict[str, Any] = fd.get("attributes", {}) or {}
        out_name = f"{uid}_Output_0"

        if op in _UNARY:
            y = g.add_node(_UNARY[op], [resolve(ins[0])])
        elif op in _BINARY:
            y = g.add_node(_BINARY[op], [resolve(ins[0]), resolve(ins[1])])
        elif op in (OP_SOFTMAX, OP_LOG_SOFTMAX):
            y = g.add_node("Softmax" if op == OP_SOFTMAX else "LogSoftmax",
                           [resolve(ins[0])], axis=-1)
        elif op in (OP_TIMES, OP_TRANSPOSE_TIMES):
            # Times(x, W): y[o] = sum_i x[i] W[i,o]; the reversed-dims
            # numpy read gives W_np[o,i], so the initializer transposes
            # back. Times(W, x) (C++ arg order, W (out,in) -> W_np (in,
            # out)) multiplies directly. TransposeTimes flips once more.
            if int(attrs.get("outputRank", 1)) != 1:
                raise NotImplementedError("Times with outputRank != 1")
            p_right = is_param(ins[1]) and not is_param(ins[0])
            if p_right:
                x_uid, w_uid = ins[0], ins[1]
            else:
                w_uid, x_uid = ins[0], ins[1]
            flip = p_right != (op == OP_TRANSPOSE_TIMES)
            y = g.add_node("MatMul", [resolve(x_uid),
                                      resolve(w_uid, transpose_param=flip)])
        elif op == OP_CONVOLUTION:
            w_uid, x_uid = ins[0], ins[1]
            strides = list(reversed(attrs.get("strides", [1, 1])))
            auto = attrs.get("autoPadding", [True])
            kern = np.asarray(variables[w_uid].value)  # (Cout,Cin,kH,kW)
            kw = dict(strides=[int(s) for s in strides[-2:]] or [1, 1],
                      kernel_shape=[int(k) for k in kern.shape[2:]])
            if any(bool(a) for a in auto):
                kw["auto_pad"] = "SAME_UPPER"
            y = g.add_node("Conv", [resolve(x_uid), resolve(w_uid)], **kw)
        elif op == OP_POOLING:
            window = list(reversed(attrs.get("poolingWindowShape", [])))
            strides = list(reversed(attrs.get("strides", window)))
            auto = attrs.get("autoPadding", [False])
            kw = dict(kernel_shape=[int(k) for k in window],
                      strides=[int(s) for s in strides] or None)
            if kw["strides"] is None:
                kw.pop("strides")
            if any(bool(a) for a in auto):
                kw["auto_pad"] = "SAME_UPPER"
            pool = "MaxPool" if int(attrs.get("poolingType", 0)) == 0 \
                else "AveragePool"
            y = g.add_node(pool, [resolve(ins[0])], **kw)
        elif op == OP_BATCH_NORM:
            # CNTK input order: (x, scale, bias, runMean, runVar[, count])
            y = g.add_node(
                "BatchNormalization",
                [resolve(ins[0]), resolve(ins[1]), resolve(ins[2]),
                 resolve(ins[3]), resolve(ins[4])],
                epsilon=float(attrs.get("epsilon", 1e-5)))
        elif op == OP_RESHAPE:
            new_shape = [int(s) for s in attrs.get("newShape", [])]
            tgt = [0] + [(-1 if s in (_INFERRED, -1) else s)
                         for s in reversed(new_shape)]
            shp = g.add_initializer(
                g.fresh("reshape_target"), np.asarray(tgt, np.int64))
            y = g.add_node("Reshape", [resolve(ins[0]), shp])
        elif op == OP_SPLICE:
            y = g.add_node("Concat", [resolve(i) for i in ins],
                           axis=np_axis(attrs.get("axis", 0)))
        elif op == OP_SLICE:
            ax = np_axis(attrs.get("axis", 0))
            end = int(attrs.get("endIndex", 0))
            # CNTK convention: endIndex 0 means "through the end of the
            # axis" (negative ends count from the end, like ONNX)
            if end == 0:
                end = np.iinfo(np.int64).max
            starts = g.add_initializer(g.fresh("sl_s"), np.asarray(
                [int(attrs.get("beginIndex", 0))], np.int64))
            ends = g.add_initializer(g.fresh("sl_e"), np.asarray(
                [end], np.int64))
            axes = g.add_initializer(g.fresh("sl_a"), np.asarray(
                [ax], np.int64))
            y = g.add_node("Slice", [resolve(ins[0]), starts, ends, axes])
        elif op == OP_TRANSPOSE_AXES:
            a1 = np_axis(attrs.get("axis1", 0))
            a2 = np_axis(attrs.get("axis2", 1))
            var = variables.get(ins[0])
            if var is None:
                raise NotImplementedError(
                    "TransposeAxes on intermediate tensors needs shape "
                    "propagation; re-export via ONNX for this graph")
            # only data INPUTS carry the implicit leading batch dim;
            # parameters/constants are emitted at their own rank
            rank = len(var.shape) + (1 if var.kind == VAR_INPUT else 0)
            perm = list(range(rank))
            perm[a1 % rank], perm[a2 % rank] = perm[a2 % rank], perm[a1 % rank]
            y = g.add_node("Transpose", [resolve(ins[0])], perm=perm)
        elif op == OP_REDUCE_ELEMENTS:
            red = {"Sum": "ReduceSum", "Mean": "ReduceMean",
                   "Max": "ReduceMax", "Min": "ReduceMin"}.get(
                str(attrs.get("reductionOpName", "Sum")))
            if red is None:
                raise NotImplementedError(
                    f"ReduceElements op "
                    f"{attrs.get('reductionOpName')!r}")
            axes = g.add_initializer(g.fresh("red_axes"), np.asarray(
                [np_axis(attrs.get("axis", 0))], np.int64))
            y = g.add_node(
                red, [resolve(ins[0]), axes],
                keepdims=int(bool(attrs.get("reductionKeepDimensions",
                                            True))))
        elif op == OP_CLIP:
            y = g.add_node("Clip", [resolve(ins[0]), resolve(ins[1]),
                                    resolve(ins[2])])
        elif op in (OP_DROPOUT, OP_NO_OP, OP_STOP_GRADIENT):
            y = g.add_node("Identity", [resolve(ins[0])])
        elif op == OP_COMBINE:
            for j, i_uid in enumerate(ins):
                names[(f"{uid}_Output_{j}", False)] = resolve(i_uid)
            last_output = names[(f"{uid}_Output_0", False)]
            continue
        elif op in (OP_PAST_VALUE, OP_FUTURE_VALUE):
            raise NotImplementedError(
                "recurrent CNTK graphs (PastValue/FutureValue) are not "
                "supported by the direct reader; export the model to "
                "ONNX with the cntk package and load that file")
        else:
            raise NotImplementedError(
                f"CNTK primitive op code {op} ({fd.get('name') or uid}) "
                f"is outside the supported feedforward surface; export "
                f"to ONNX with the cntk package for full coverage")
        names[(out_name, False)] = y
        last_output = y

    out_uid = f"{root}_Output_0" if root else None
    out_name = names.get((out_uid, False), last_output)
    if out_name is None:
        raise ValueError("model has no computable output")
    g.add_output(out_name, np.float32, None)
    return g.to_bytes(producer="synapseml_tpu.dl.cntk_format")


def sniff_cntk_v2(payload: bytes) -> Optional[Dict[str, Any]]:
    """Decode-and-sniff: the parsed Dictionary when the bytes are a v2
    CompositeFunction, else None. Returning the dict lets the caller
    skip a second full (pure-Python, weight-heavy) decode."""
    try:
        top = load_model_dictionary(payload)
    except Exception:  # noqa: BLE001 - any parse failure means "not cntk"
        return None
    return top if top.get("type") == "CompositeFunction" else None


def looks_like_cntk_v2(payload: bytes) -> bool:
    return sniff_cntk_v2(payload) is not None


# ---------------------------------------------------------------------------
# Authoring half (the publishing/export story + test vectors)
# ---------------------------------------------------------------------------

class CntkModelBuilder:
    """Compose a CNTK v2 ``.model`` byte blob (the serialization
    conventions the reader consumes: uid-wired primitive functions,
    ``_Output_k`` naming, reversed-dim NDShapes, column-major payloads).
    Used by the round-trip tests and available as an export target."""

    def __init__(self, name: str = "model"):
        self.name = name
        self._vars: List[Dict[str, Any]] = []
        self._funcs: List[Dict[str, Any]] = []
        self._n = 0

    def _uid(self, tag: str) -> str:
        self._n += 1
        return f"{tag}{self._n}"

    def add_input(self, sample_shape_np: Tuple[int, ...],
                  name: str = "features") -> str:
        uid = self._uid("Input")
        self._vars.append({
            "version": 1, "uid": uid, "kind": VAR_INPUT,
            "data_type": 1, "is_sparse": False, "name": name,
            "needs_gradient": False,
            "shape": [int(s) for s in reversed(sample_shape_np)],
        })
        return uid

    def add_parameter(self, arr_np: np.ndarray, name: str = "") -> str:
        """``arr_np`` in numpy layout; stored reversed/column-major."""
        uid = self._uid("Parameter")
        self._vars.append({
            "version": 1, "uid": uid, "kind": VAR_PARAMETER,
            "data_type": 1, "is_sparse": False,
            "name": name or uid, "needs_gradient": True,
            "shape": [int(s) for s in reversed(arr_np.shape)],
            "value": np.asarray(arr_np, np.float32),
        })
        return uid

    def add_op(self, op: int, inputs: List[str],
               attributes: Optional[Dict[str, Any]] = None,
               name: str = "") -> str:
        uid = self._uid("Func")
        self._funcs.append({
            "version": 1, "uid": uid, "op": int(op),
            "inputs": list(inputs),
            "attributes": dict(attributes or {}), "name": name,
        })
        return f"{uid}_Output_0"

    def to_bytes(self, root_output: str) -> bytes:
        root = root_output.rsplit("_Output_", 1)[0]
        top = {
            "version": 1,
            "type": "CompositeFunction",
            "root": root,
            "uid": self._uid("Composite"),
            "name": self.name,
            "inputs": self._vars,
            "primitive_functions": self._funcs,
        }
        return proto.encode(py_to_dict(top))
