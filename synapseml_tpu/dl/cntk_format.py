"""CNTK v2 binary ``.model`` reader: protobuf Dictionary -> ONNX -> jax.

The reference executes native ``.model`` files through the CNTK 2.4 JNI
runtime (ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/
SerializableFunction.scala:85-143 — ``Function.load`` on broadcast
bytes). That runtime is dead and CUDA/CPU-only, so here the *format*
is parsed directly: CNTK-2.x model files are a serialized ``Dictionary``
protobuf (the CNTKv2LibraryDll ``CNTK.proto`` schema — NDShape/Axis/
NDArrayView/Vector/Dictionary/DictionaryValue messages) holding a
``CompositeFunction``: a vector of primitive functions wired by variable
uids (outputs follow the ``<func_uid>_Output_<k>`` convention) plus the
parameter/constant payloads. The graph is re-emitted as ONNX and lowered
through the standard importer, so every op lands on the same jit path as
user ONNX files.

Format notes (why the reshapes below look reversed): CNTK NDShapes store
dimensions fastest-varying first and tensors column-major; reading the
flat payload row-major with the dims REVERSED yields the numpy/ONNX
layout directly (a conv kernel ``(kW,kH,Cin,Cout)`` becomes
``(Cout,Cin,kH,kW)``). The batch axis is a dynamic axis — absent from
shapes — and maps to the leading "N" dim; a CNTK static axis index k
(0 = fastest) maps to negative numpy axis ``-(k+1)``.

Supported op surface: the feedforward model-zoo diet (Times/Plus/
activation chains, Convolution, Pooling, BatchNormalization, Reshape,
Splice, Slice, TransposeAxes, ReduceElements, Clip, Dropout/NoOp
passthrough, Combine) plus RECURRENT graphs: PastValue/FutureValue
cycles lower to ONNX Scan -> ``lax.scan`` with everything outside the
cycle vectorized over the sequence (see :func:`_recurrent_to_onnx`;
bidirectional = two cycles = two Scans), and OptimizedRNNStack (the
fused cuDNN op GPU-trained models carry) unpacks its packed weight blob
into standard ONNX LSTM/GRU/RNN nodes (:func:`_emit_optimized_rnn`).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from synapseml_tpu.onnx import proto
from synapseml_tpu.onnx.builder import GraphBuilder
from synapseml_tpu.onnx.proto import F, Msg

# ---------------------------------------------------------------------------
# CNTK.proto subset (field numbers frozen by protobuf compatibility)
# ---------------------------------------------------------------------------

_CNTK_SCHEMAS = {
    "CntkNDShape": [F(1, "shape_dim", "int64", repeated=True)],
    "CntkAxis": [
        F(1, "static_axis_idx", "int64"),
        F(2, "name", "string"),
        F(3, "is_ordered_dynamic_axis", "int64"),
    ],
    "CntkFloatValues": [F(1, "value", "float", repeated=True)],
    "CntkDoubleValues": [F(1, "value", "double", repeated=True)],
    "CntkNDArrayView": [
        F(1, "data_type", "int64"),      # 1 = Float, 2 = Double
        F(2, "storage_format", "int64"),  # 0 = Dense
        F(3, "shape", "message", message="CntkNDShape"),
        F(4, "float_values", "message", message="CntkFloatValues"),
        F(5, "double_values", "message", message="CntkDoubleValues"),
    ],
    "CntkVector": [
        F(1, "value", "message", repeated=True,
          message="CntkDictionaryValue"),
    ],
    "CntkDictionary": [
        F(1, "version", "int64"),
        F(2, "data", "message", repeated=True,
          message="CntkDictionaryEntry"),
    ],
    "CntkDictionaryEntry": [  # protobuf map<string, DictionaryValue> entry
        F(1, "key", "string"),
        F(2, "value", "message", message="CntkDictionaryValue"),
    ],
    "CntkDictionaryValue": [
        F(1, "version", "int64"),
        F(2, "bool_value", "int64"),
        F(3, "int_value", "int64"),
        F(4, "size_t_value", "int64"),
        F(5, "float_value", "float"),
        F(6, "double_value", "double"),
        F(7, "string_value", "string"),
        F(8, "nd_shape_value", "message", message="CntkNDShape"),
        F(9, "axis_value", "message", message="CntkAxis"),
        F(10, "vector_value", "message", message="CntkVector"),
        F(11, "dictionary_value", "message", message="CntkDictionary"),
        F(12, "nd_array_view_value", "message", message="CntkNDArrayView"),
    ],
}
proto._SCHEMAS.update(_CNTK_SCHEMAS)

_INFERRED = (1 << 64) - 1  # NDShape::InferredDimension, wraps to -1 signed


class CntkAxisRef:
    __slots__ = ("static_axis_idx", "name")

    def __init__(self, idx: int, name: str = ""):
        self.static_axis_idx = int(idx)
        self.name = name


def _shape_dims(shape_msg: Msg) -> List[int]:
    return [int(d) for d in (shape_msg.shape_dim or [])]


def _ndarray_to_numpy(view: Msg) -> np.ndarray:
    dims = _shape_dims(view.shape) if view.shape is not None else []
    if int(view.storage_format or 0) != 0:
        raise NotImplementedError(
            "sparse NDArrayView payloads are not supported")
    if view.float_values is not None:
        flat = np.asarray(view.float_values.value, np.float32)
    elif view.double_values is not None:
        flat = np.asarray(view.double_values.value, np.float64)
    else:
        flat = np.zeros(0, np.float32)
    # CNTK stores column-major with fastest-varying dim first; reversing
    # the dims makes the row-major read correct
    return flat.reshape(tuple(reversed(dims))) if dims else flat


def _numpy_to_ndarray(arr: np.ndarray) -> Msg:
    view = Msg("CntkNDArrayView")
    view.data_type = 2 if arr.dtype == np.float64 else 1
    view.storage_format = 0
    shp = Msg("CntkNDShape")
    shp.shape_dim = [int(d) for d in reversed(arr.shape)]
    view.shape = shp
    vals = Msg("CntkDoubleValues" if arr.dtype == np.float64
               else "CntkFloatValues")
    vals.value = [float(v) for v in np.asarray(arr).reshape(-1)]
    if arr.dtype == np.float64:
        view.double_values = vals
    else:
        view.float_values = vals
    return view


def value_to_py(v: Msg) -> Any:
    """DictionaryValue -> python (dict / list / ndarray / scalar)."""
    if v.dictionary_value is not None:
        return dict_to_py(v.dictionary_value)
    if v.vector_value is not None:
        return [value_to_py(e) for e in v.vector_value.value]
    if v.nd_array_view_value is not None:
        return _ndarray_to_numpy(v.nd_array_view_value)
    if v.nd_shape_value is not None:
        return _shape_dims(v.nd_shape_value)
    if v.axis_value is not None:
        return CntkAxisRef(v.axis_value.static_axis_idx or 0,
                           v.axis_value.name or "")
    if v.string_value is not None:
        return v.string_value
    if v.float_value is not None:
        return float(v.float_value)
    if v.double_value is not None:
        return float(v.double_value)
    if v.size_t_value is not None:
        return int(v.size_t_value) & ((1 << 64) - 1)
    if v.int_value is not None:
        return int(v.int_value)
    if v.bool_value is not None:
        return bool(v.bool_value)
    return None  # proto3 default (False / 0 / "") never reaches the wire


def dict_to_py(d: Msg) -> Dict[str, Any]:
    return {e.key: value_to_py(e.value) for e in (d.data or [])}


def py_to_value(v: Any) -> Msg:
    out = Msg("CntkDictionaryValue")
    out.version = 1
    if isinstance(v, dict):
        out.dictionary_value = py_to_dict(v)
    elif isinstance(v, (list, tuple)) and not isinstance(v, str):
        if v and all(isinstance(x, (int, np.integer)) for x in v):
            shp = Msg("CntkNDShape")
            shp.shape_dim = [int(x) for x in v]
            out.nd_shape_value = shp
        else:
            vec = Msg("CntkVector")
            vec.value = [py_to_value(x) for x in v]
            out.vector_value = vec
    elif isinstance(v, np.ndarray):
        out.nd_array_view_value = _numpy_to_ndarray(v)
    elif isinstance(v, CntkAxisRef):
        ax = Msg("CntkAxis")
        ax.static_axis_idx = v.static_axis_idx
        ax.name = v.name
        out.axis_value = ax
    elif isinstance(v, bool):
        out.bool_value = int(v)
    elif isinstance(v, (int, np.integer)):
        # CNTK keeps signed attribute ints (slice begin/end) in
        # int_value; size_t_value is unsigned and would mask negatives
        # into 2^64-range garbage on the read side
        if int(v) < 0:
            out.int_value = int(v)
        else:
            out.size_t_value = int(v)
    elif isinstance(v, float):
        out.double_value = v
    elif isinstance(v, str):
        out.string_value = v
    else:
        raise TypeError(f"cannot serialize {type(v)} into a CNTK "
                        f"DictionaryValue")
    return out


def py_to_dict(d: Dict[str, Any]) -> Msg:
    out = Msg("CntkDictionary")
    out.version = 1
    entries = []
    for k, v in d.items():
        e = Msg("CntkDictionaryEntry")
        e.key = k
        e.value = py_to_value(v)
        entries.append(e)
    out.data = entries
    return out


def load_model_dictionary(payload: bytes) -> Dict[str, Any]:
    return dict_to_py(proto.decode("CntkDictionary", payload))


# ---------------------------------------------------------------------------
# PrimitiveOpType (CNTK 2.x PrimitiveOpType.h enum order)
# ---------------------------------------------------------------------------

OP_NEGATE, OP_SIGMOID, OP_TANH, OP_RELU, OP_EXP, OP_LOG, OP_SQRT = range(7)
OP_FLOOR, OP_ABS, OP_RECIPROCAL, OP_SOFTMAX, OP_HARDMAX = 7, 8, 9, 10, 11
OP_TRANSPOSE_AXES, OP_WHERE, OP_SLICE, OP_DROPOUT, OP_RESHAPE = 12, 13, 14, 15, 16
OP_POOLING, OP_SUM_ALL, OP_PLUS, OP_LOG_PLUS, OP_MINUS = 17, 18, 19, 20, 21
OP_ELEMENT_TIMES, OP_EQUAL, OP_NOT_EQUAL, OP_LESS = 22, 23, 24, 25
OP_LESS_EQUAL, OP_GREATER, OP_GREATER_EQUAL = 26, 27, 28
OP_TIMES, OP_TRANSPOSE_TIMES, OP_CONVOLUTION = 32, 33, 34
OP_PAST_VALUE, OP_FUTURE_VALUE, OP_REDUCE_ELEMENTS = 38, 39, 40
OP_BATCH_NORM, OP_CLIP, OP_SELECT, OP_SPLICE, OP_COMBINE = 41, 42, 43, 44, 45
OP_OPTIMIZED_RNN = 50
OP_LOG_SOFTMAX, OP_NO_OP, OP_STOP_GRADIENT, OP_ELU = 52, 56, 58, 59

_UNARY = {
    OP_NEGATE: "Neg", OP_SIGMOID: "Sigmoid", OP_TANH: "Tanh",
    OP_RELU: "Relu", OP_EXP: "Exp", OP_LOG: "Log", OP_SQRT: "Sqrt",
    OP_FLOOR: "Floor", OP_ABS: "Abs", OP_RECIPROCAL: "Reciprocal",
    OP_ELU: "Elu",
}
_BINARY = {OP_PLUS: "Add", OP_MINUS: "Sub", OP_ELEMENT_TIMES: "Mul"}


class _Var:
    __slots__ = ("uid", "kind", "shape", "value", "name")

    def __init__(self, d: Dict[str, Any]):
        self.uid = d["uid"]
        self.kind = int(d.get("kind", 0))
        self.shape = [int(s) for s in d.get("shape", [])]
        self.value = d.get("value")
        self.name = d.get("name", "")


VAR_INPUT, VAR_OUTPUT, VAR_PARAMETER, VAR_CONSTANT, VAR_PLACEHOLDER = range(5)


class _Emitter:
    """Lowers CNTK primitive functions into one GraphBuilder.

    Reused by the recurrent path for Scan bodies: ``alias`` pre-maps
    tensor uids onto existing onnx names (state inputs / per-timestep
    scan slices), and ``seq_inputs`` marks model inputs that carry a
    sequence axis (declared ``[N, T, ...]`` instead of ``[N, ...]``)."""

    def __init__(self, g: GraphBuilder, variables: Dict[str, "_Var"],
                 seq_inputs: frozenset = frozenset()):
        self.g = g
        self.variables = variables
        self.seq_inputs = seq_inputs
        self.names: Dict[Any, str] = {}
        self.last_output: Optional[str] = None

    def alias(self, tensor_uid: str, onnx_name: str):
        self.names[(tensor_uid, False)] = onnx_name

    def resolve(self, uid: str, transpose_param: bool = False) -> str:
        # a shared parameter may be consumed in BOTH orientations
        # (weight tying): the cache key carries the flip
        key = (uid, transpose_param)
        if key in self.names:
            return self.names[key]
        var = self.variables.get(uid)
        if var is None:
            raise KeyError(f"dangling variable uid {uid!r}")
        g = self.g
        if var.kind in (VAR_PARAMETER, VAR_CONSTANT):
            arr = np.asarray(var.value)
            if transpose_param:
                arr = np.ascontiguousarray(arr.T)
            nm = g.add_initializer(g.fresh(var.name or uid), arr)
        elif var.kind == VAR_INPUT:
            if transpose_param:
                raise NotImplementedError(
                    "Times with a non-parameter weight operand needs a "
                    "runtime transpose; export to ONNX with the cntk "
                    "package for this graph")
            dyn = ["N", "T"] if uid in self.seq_inputs else ["N"]
            nm = g.add_input(var.name or uid, np.float32,
                             dyn + list(reversed(var.shape)))
        else:
            raise ValueError(f"unresolvable variable {uid!r} "
                             f"(kind={var.kind})")
        self.names[key] = nm
        return nm

    @staticmethod
    def np_axis(attr) -> int:
        k = attr.static_axis_idx if isinstance(attr, CntkAxisRef) \
            else int(attr)
        return -(k + 1)

    def is_param(self, uid: str) -> bool:
        v = self.variables.get(uid)
        return v is not None and v.kind in (VAR_PARAMETER, VAR_CONSTANT)

    def emit(self, fd: Dict[str, Any]) -> Optional[str]:
        g, names = self.g, self.names
        resolve, np_axis, is_param = self.resolve, self.np_axis, self.is_param
        variables = self.variables
        op = int(fd["op"])
        uid = fd["uid"]
        ins: List[str] = list(fd.get("inputs", []))
        attrs: Dict[str, Any] = fd.get("attributes", {}) or {}
        out_name = f"{uid}_Output_0"

        if op in _UNARY:
            y = g.add_node(_UNARY[op], [resolve(ins[0])])
        elif op in _BINARY:
            y = g.add_node(_BINARY[op], [resolve(ins[0]), resolve(ins[1])])
        elif op in (OP_SOFTMAX, OP_LOG_SOFTMAX):
            y = g.add_node("Softmax" if op == OP_SOFTMAX else "LogSoftmax",
                           [resolve(ins[0])], axis=-1)
        elif op in (OP_TIMES, OP_TRANSPOSE_TIMES):
            # Times(x, W): y[o] = sum_i x[i] W[i,o]; the reversed-dims
            # numpy read gives W_np[o,i], so the initializer transposes
            # back. Times(W, x) (C++ arg order, W (out,in) -> W_np (in,
            # out)) multiplies directly. TransposeTimes flips once more.
            if int(attrs.get("outputRank", 1)) != 1:
                raise NotImplementedError("Times with outputRank != 1")
            p_right = is_param(ins[1]) and not is_param(ins[0])
            if p_right:
                x_uid, w_uid = ins[0], ins[1]
            else:
                w_uid, x_uid = ins[0], ins[1]
            flip = p_right != (op == OP_TRANSPOSE_TIMES)
            y = g.add_node("MatMul", [resolve(x_uid),
                                      resolve(w_uid, transpose_param=flip)])
        elif op == OP_CONVOLUTION:
            w_uid, x_uid = ins[0], ins[1]
            strides = list(reversed(attrs.get("strides", [1, 1])))
            auto = attrs.get("autoPadding", [True])
            kern = np.asarray(variables[w_uid].value)  # (Cout,Cin,kH,kW)
            kw = dict(strides=[int(s) for s in strides[-2:]] or [1, 1],
                      kernel_shape=[int(k) for k in kern.shape[2:]])
            if any(bool(a) for a in auto):
                kw["auto_pad"] = "SAME_UPPER"
            y = g.add_node("Conv", [resolve(x_uid), resolve(w_uid)], **kw)
        elif op == OP_POOLING:
            window = list(reversed(attrs.get("poolingWindowShape", [])))
            strides = list(reversed(attrs.get("strides", window)))
            auto = attrs.get("autoPadding", [False])
            kw = dict(kernel_shape=[int(k) for k in window],
                      strides=[int(s) for s in strides] or None)
            if kw["strides"] is None:
                kw.pop("strides")
            if any(bool(a) for a in auto):
                kw["auto_pad"] = "SAME_UPPER"
            pool = "MaxPool" if int(attrs.get("poolingType", 0)) == 0 \
                else "AveragePool"
            y = g.add_node(pool, [resolve(ins[0])], **kw)
        elif op == OP_BATCH_NORM:
            # CNTK input order: (x, scale, bias, runMean, runVar[, count])
            y = g.add_node(
                "BatchNormalization",
                [resolve(ins[0]), resolve(ins[1]), resolve(ins[2]),
                 resolve(ins[3]), resolve(ins[4])],
                epsilon=float(attrs.get("epsilon", 1e-5)))
        elif op == OP_RESHAPE:
            new_shape = [int(s) for s in attrs.get("newShape", [])]
            tgt = [0] + [(-1 if s in (_INFERRED, -1) else s)
                         for s in reversed(new_shape)]
            shp = g.add_initializer(
                g.fresh("reshape_target"), np.asarray(tgt, np.int64))
            y = g.add_node("Reshape", [resolve(ins[0]), shp])
        elif op == OP_SPLICE:
            y = g.add_node("Concat", [resolve(i) for i in ins],
                           axis=np_axis(attrs.get("axis", 0)))
        elif op == OP_SLICE:
            ax = np_axis(attrs.get("axis", 0))
            end = int(attrs.get("endIndex", 0))
            # CNTK convention: endIndex 0 means "through the end of the
            # axis" (negative ends count from the end, like ONNX)
            if end == 0:
                end = np.iinfo(np.int64).max
            starts = g.add_initializer(g.fresh("sl_s"), np.asarray(
                [int(attrs.get("beginIndex", 0))], np.int64))
            ends = g.add_initializer(g.fresh("sl_e"), np.asarray(
                [end], np.int64))
            axes = g.add_initializer(g.fresh("sl_a"), np.asarray(
                [ax], np.int64))
            y = g.add_node("Slice", [resolve(ins[0]), starts, ends, axes])
        elif op == OP_TRANSPOSE_AXES:
            a1 = np_axis(attrs.get("axis1", 0))
            a2 = np_axis(attrs.get("axis2", 1))
            var = variables.get(ins[0])
            if var is None:
                raise NotImplementedError(
                    "TransposeAxes on intermediate tensors needs shape "
                    "propagation; re-export via ONNX for this graph")
            # only data INPUTS carry the implicit leading batch dim;
            # parameters/constants are emitted at their own rank
            rank = len(var.shape) + (1 if var.kind == VAR_INPUT else 0)
            perm = list(range(rank))
            perm[a1 % rank], perm[a2 % rank] = perm[a2 % rank], perm[a1 % rank]
            y = g.add_node("Transpose", [resolve(ins[0])], perm=perm)
        elif op == OP_REDUCE_ELEMENTS:
            red = {"Sum": "ReduceSum", "Mean": "ReduceMean",
                   "Max": "ReduceMax", "Min": "ReduceMin"}.get(
                str(attrs.get("reductionOpName", "Sum")))
            if red is None:
                raise NotImplementedError(
                    f"ReduceElements op "
                    f"{attrs.get('reductionOpName')!r}")
            axes = g.add_initializer(g.fresh("red_axes"), np.asarray(
                [np_axis(attrs.get("axis", 0))], np.int64))
            y = g.add_node(
                red, [resolve(ins[0]), axes],
                keepdims=int(bool(attrs.get("reductionKeepDimensions",
                                            True))))
        elif op == OP_CLIP:
            y = g.add_node("Clip", [resolve(ins[0]), resolve(ins[1]),
                                    resolve(ins[2])])
        elif op in (OP_DROPOUT, OP_NO_OP, OP_STOP_GRADIENT):
            y = g.add_node("Identity", [resolve(ins[0])])
        elif op == OP_OPTIMIZED_RNN:
            y = _emit_optimized_rnn(self, ins, attrs)
        elif op == OP_COMBINE:
            for j, i_uid in enumerate(ins):
                names[(f"{uid}_Output_{j}", False)] = resolve(i_uid)
            self.last_output = names[(f"{uid}_Output_0", False)]
            return self.last_output
        elif op in (OP_PAST_VALUE, OP_FUTURE_VALUE):
            raise AssertionError(
                "recurrent state nodes must be handled by the Scan "
                "lowering, never emitted directly")
        else:
            raise NotImplementedError(
                f"CNTK primitive op code {op} ({fd.get('name') or uid}) "
                f"is outside the supported feedforward surface; export "
                f"to ONNX with the cntk package for full coverage")
        names[(out_name, False)] = y
        self.last_output = y
        return y


def _emit_optimized_rnn(em: "_Emitter", ins: List[str],
                        attrs: Dict[str, Any]) -> str:
    """OptimizedRNNStack: the fused cuDNN RNN op GPU-trained CNTK models
    carry (the zoo BiLSTM family). The single packed weight Parameter is
    unpacked per the cuDNN canonical layout — all gate matrices for every
    pseudo-layer (layer-major, direction-minor; W blocks then R blocks,
    gate order i,f,c,o for LSTM / r,u,c for GRU), followed by all bias
    vectors (bW then bR per pseudo-layer) — and re-emitted as standard
    ONNX LSTM/GRU/RNN nodes per layer, which the importer lowers to
    ``lax.scan`` (gate reorder to ONNX's i,o,f,c / z,r,h; cuDNN's
    recurrent-side GRU reset placement maps to linear_before_reset=1).
    The blob size must factor exactly as that layout demands — a
    mismatch raises rather than mis-slicing weights.
    """
    g = em.g
    if em.is_param(ins[0]) and not em.is_param(ins[1]):
        w_uid, x_uid = ins[0], ins[1]
    else:
        x_uid, w_uid = ins[0], ins[1]
    wv = em.variables.get(w_uid)
    if wv is None or wv.value is None:
        raise NotImplementedError(
            "OptimizedRNNStack needs its weights as a stored Parameter")
    blob = np.asarray(wv.value, np.float32).reshape(-1)
    H = int(attrs.get("hiddenSize", 0))
    L = int(attrs.get("numLayers", 1))
    bidir = bool(attrs.get("bidirectional", False))
    rec_op = str(attrs.get("recurrentOp", "lstm"))
    dirs = 2 if bidir else 1
    G = {"lstm": 4, "gru": 3, "rnnTanh": 1, "rnnReLU": 1}.get(rec_op)
    if G is None:
        raise NotImplementedError(
            f"OptimizedRNNStack recurrentOp {rec_op!r}")
    if H <= 0:
        raise ValueError("OptimizedRNNStack without hiddenSize")
    # solve the input width E from the blob size (layer 0 consumes E,
    # deeper layers consume H*dirs)
    rest = (L - 1) * dirs * G * H * (H * dirs + H + 2)
    den = dirs * G * H
    num = blob.size - rest
    if num <= 0 or num % den or num // den - H - 2 <= 0:
        raise ValueError(
            f"OptimizedRNNStack weight blob of {blob.size} floats does "
            f"not factor for hiddenSize={H} numLayers={L} dirs={dirs} "
            f"op={rec_op!r} under the cuDNN canonical layout")
    E = num // den - H - 2

    # onnx gate order from cudnn order
    reorder = {"lstm": [0, 3, 1, 2],   # i,f,c,o -> i,o,f,c
               "gru": [1, 0, 2]}.get(rec_op, [0])  # r,u,c -> z,r,h
    onnx_op = {"lstm": "LSTM", "gru": "GRU"}.get(rec_op, "RNN")

    pos = 0

    def take(n):
        nonlocal pos
        out = blob[pos:pos + n]
        pos += n
        return out

    mats = []   # per pseudo-layer: (W [G,H,in], R [G,H,H])
    for layer in range(L):
        in_l = E if layer == 0 else H * dirs
        for _ in range(dirs):
            wg = np.stack([take(H * in_l).reshape(H, in_l)
                           for _ in range(G)])
            rg = np.stack([take(H * H).reshape(H, H) for _ in range(G)])
            mats.append((wg, rg))
    biases = []  # per pseudo-layer: (bW [G,H], bR [G,H])
    for _ in range(L * dirs):
        bw = np.stack([take(H) for _ in range(G)])
        br = np.stack([take(H) for _ in range(G)])
        biases.append((bw, br))
    assert pos == blob.size

    # [N, T, E] -> [T, N, E] once; stay [T, N, *] between layers
    x = g.add_node("Transpose", [em.resolve(x_uid)], perm=[1, 0, 2])
    for layer in range(L):
        W = np.stack([mats[layer * dirs + d][0][reorder].reshape(
            G * H, -1) for d in range(dirs)])
        R = np.stack([mats[layer * dirs + d][1][reorder].reshape(
            G * H, H) for d in range(dirs)])
        B = np.stack([np.concatenate(
            [biases[layer * dirs + d][0][reorder].reshape(-1),
             biases[layer * dirs + d][1][reorder].reshape(-1)])
            for d in range(dirs)])
        kw: Dict[str, Any] = dict(
            hidden_size=H,
            direction="bidirectional" if bidir else "forward")
        if rec_op == "gru":
            kw["linear_before_reset"] = 1
        if rec_op == "rnnReLU":
            kw["activations"] = ["Relu"] * dirs
        y = g.add_node(
            onnx_op,
            [x,
             g.add_initializer(g.fresh("rnn_w"), W.astype(np.float32)),
             g.add_initializer(g.fresh("rnn_r"), R.astype(np.float32)),
             g.add_initializer(g.fresh("rnn_b"), B.astype(np.float32))],
            **kw)
        # Y [T, dirs, N, H] -> [T, N, dirs*H] for the next layer
        y = g.add_node("Transpose", [y], perm=[0, 2, 1, 3])
        shp = g.add_initializer(g.fresh("rnn_shape"),
                                np.asarray([0, 0, dirs * H], np.int64))
        x = g.add_node("Reshape", [y, shp])
    # back to the [N, T, feat] convention
    return g.add_node("Transpose", [x], perm=[1, 0, 2])


def cntk_to_onnx(payload: bytes,
                 parsed: Optional[Dict[str, Any]] = None) -> bytes:
    """Parse ``.model`` bytes and re-emit the graph as ONNX bytes.
    ``parsed`` skips the (pure-Python, weight-heavy) protobuf decode when
    the caller already holds the Dictionary from the sniff. Recurrent
    graphs (PastValue/FutureValue cycles) lower through ONNX Scan — see
    :func:`_recurrent_to_onnx`."""
    top = parsed if parsed is not None else load_model_dictionary(payload)
    if top.get("type") != "CompositeFunction":
        raise ValueError(
            f"not a CNTK v2 CompositeFunction dictionary "
            f"(type={top.get('type')!r})")
    variables = {v["uid"]: _Var(v) for v in top.get("inputs", [])}
    functions = top.get("primitive_functions", [])
    root = top.get("root")

    g = GraphBuilder(name=top.get("name") or "cntk_model", opset=17)
    if any(int(fd["op"]) in (OP_PAST_VALUE, OP_FUTURE_VALUE,
                             OP_OPTIMIZED_RNN)
           for fd in functions):
        # sequence-model path: inputs feeding recurrences carry [N, T]
        return _recurrent_to_onnx(g, variables, functions, root)

    em = _Emitter(g, variables)
    for fd in functions:
        em.emit(fd)
    out_uid = f"{root}_Output_0" if root else None
    out_name = em.names.get((out_uid, False), em.last_output)
    if out_name is None:
        raise ValueError("model has no computable output")
    g.add_output(out_name, np.float32, None)
    return g.to_bytes(producer="synapseml_tpu.dl.cntk_format")


def _recurrent_to_onnx(g: GraphBuilder, variables: Dict[str, _Var],
                       functions: List[Dict[str, Any]],
                       root: Optional[str]) -> bytes:
    """Lower a CNTK v2 graph whose PastValue/FutureValue nodes form
    recurrence cycles.

    TPU-native design: each cycle becomes ONE ONNX ``Scan`` node, which
    the importer lowers to ``lax.scan`` (one compiled body — no
    per-timestep Python); everything OUTSIDE the cycles stays vectorized
    over the whole ``[N, T, ...]`` sequence, so the input projection
    ``x_t @ W`` for all t is a single MXU matmul instead of T small ones.
    The reference executes these graphs natively via ``Function.load``
    (deep-learning/.../cntk/SerializableFunction.scala:85-143 — the
    BiLSTM zoo); here the sequence convention is: every model INPUT that
    (transitively) feeds a recurrence carries CNTK's default
    [batch, time] dynamic-axis pair, other inputs just [batch].

    Supported: offset-1 Past/FutureValue, any number of state variables
    per cycle (LSTM h+c merge into one body), stacked and backward
    recurrences. A cycle mixing Past and Future (a true bidirectional
    loop, not two separate cycles) cannot be a single scan and raises.
    """
    fns = {fd["uid"]: fd for fd in functions}
    producer: Dict[str, str] = {}
    for fd in functions:
        n_out = len(fd.get("inputs", [])) \
            if int(fd["op"]) == OP_COMBINE else 1
        for j in range(n_out):
            producer[f"{fd['uid']}_Output_{j}"] = fd["uid"]
    consumers: Dict[str, List[str]] = {}
    for fd in functions:
        for i in fd.get("inputs", []):
            p = producer.get(i)
            if p is not None:
                consumers.setdefault(p, []).append(fd["uid"])

    def ancestors_of(tensor: str) -> set:
        out: set = set()
        stack = [producer[tensor]] if tensor in producer else []
        while stack:
            u = stack.pop()
            if u in out:
                continue
            out.add(u)
            for i in fns[u].get("inputs", []):
                p = producer.get(i)
                if p is not None and p not in out:
                    stack.append(p)
        return out

    def descendants_of(uid: str) -> set:
        out: set = set()
        stack = [uid]
        while stack:
            for c in consumers.get(stack.pop(), []):
                if c not in out:
                    out.add(c)
                    stack.append(c)
        return out

    pvs = [fd for fd in functions
           if int(fd["op"]) in (OP_PAST_VALUE, OP_FUTURE_VALUE)]
    for pv in pvs:
        if int((pv.get("attributes") or {}).get("offset", 1)) != 1:
            raise NotImplementedError(
                "PastValue/FutureValue with offset != 1 is not supported")

    # one group per recurrence cycle; overlapping cycles merge (LSTM's
    # h and c share a body). ``order`` fixes the serialization order so
    # set iteration can never leak into the emitted bytes.
    order = {fd["uid"]: i for i, fd in enumerate(functions)}
    groups: List[Dict[str, Any]] = []
    for pv in pvs:
        cyc = descendants_of(pv["uid"]) & ancestors_of(pv["inputs"][0])
        cyc.add(pv["uid"])
        groups.append({"nodes": cyc, "pvs": [pv], "order": order})
    merged = True
    while merged:
        merged = False
        for i in range(len(groups)):
            for j in range(i + 1, len(groups)):
                if groups[i]["nodes"] & groups[j]["nodes"]:
                    groups[i]["nodes"] |= groups[j]["nodes"]
                    groups[i]["pvs"] += groups[j]["pvs"]
                    del groups[j]
                    merged = True
                    break
            if merged:
                break
    in_group: Dict[str, Dict[str, Any]] = {}
    for grp in groups:
        for u in grp["nodes"]:
            in_group[u] = grp

    # model inputs feeding any cycle (or a fused cuDNN RNN stack) carry
    # the sequence axis
    seq_inputs: set = set()
    rnn_stacks = [fd["uid"] for fd in functions
                  if int(fd["op"]) == OP_OPTIMIZED_RNN]
    for grp in groups + ([{"nodes": set(rnn_stacks)}] if rnn_stacks
                         else []):
        seen: set = set()
        stack = list(grp["nodes"])
        while stack:
            u = stack.pop()
            if u in seen:
                continue
            seen.add(u)
            for i in fns[u].get("inputs", []):
                p = producer.get(i)
                if p is not None:
                    stack.append(p)
                else:
                    v = variables.get(i)
                    if v is not None and v.kind == VAR_INPUT:
                        seq_inputs.add(i)

    outer = _Emitter(g, variables, seq_inputs=frozenset(seq_inputs))

    def infer_last_dim(tensor: str,
                       _seen: Optional[set] = None) -> Optional[int]:
        """Static trailing dim (state width) — needed when a scalar
        initial_state must Expand to [N, H]. ``_seen`` breaks the
        recurrence back-edge (the walk re-enters the cycle through the
        state node and must answer from a sibling operand instead)."""
        _seen = set() if _seen is None else _seen
        if tensor in _seen:
            return None
        _seen.add(tensor)
        v = variables.get(tensor)
        if v is not None:
            shape = tuple(reversed(v.shape))
            return int(shape[-1]) if shape else None
        u = producer.get(tensor)
        if u is None:
            return None
        fd = fns[u]
        op, ins = int(fd["op"]), list(fd.get("inputs", []))
        if op in _UNARY or op in (OP_PAST_VALUE, OP_FUTURE_VALUE,
                                  OP_DROPOUT, OP_NO_OP, OP_STOP_GRADIENT,
                                  OP_SOFTMAX, OP_LOG_SOFTMAX):
            return infer_last_dim(ins[0], _seen)
        if op in _BINARY:
            for i in ins:
                d = infer_last_dim(i, _seen)
                if d is not None and d != 1:
                    return d
            return None
        if op in (OP_TIMES, OP_TRANSPOSE_TIMES):
            p_right = (variables.get(ins[1]) is not None
                       and variables[ins[1]].kind in (VAR_PARAMETER,
                                                      VAR_CONSTANT)
                       and not (variables.get(ins[0]) is not None
                                and variables[ins[0]].kind in
                                (VAR_PARAMETER, VAR_CONSTANT)))
            w_uid = ins[1] if p_right else ins[0]
            wv = variables.get(w_uid)
            if wv is None or wv.value is None:
                return None
            w = np.asarray(wv.value)
            flip = p_right != (op == OP_TRANSPOSE_TIMES)
            w = w.T if flip else w
            return int(w.shape[-1])
        return None

    def resolvable(tensor: str) -> bool:
        return tensor in variables or (tensor, False) in outer.names

    root_tensor = f"{root}_Output_0" if root else None
    pending_fns = [fd for fd in functions if fd["uid"] not in in_group]
    pending_groups = list(groups)
    while pending_fns or pending_groups:
        progress = False
        for fd in list(pending_fns):
            if all(resolvable(i) for i in fd.get("inputs", [])):
                outer.emit(fd)
                pending_fns.remove(fd)
                progress = True
        for grp in list(pending_groups):
            if _group_ready(grp, fns, producer, variables, outer,
                            in_group):
                _emit_scan_group(g, outer, grp, fns, functions, producer,
                                 consumers, variables, infer_last_dim,
                                 root_tensor, in_group)
                pending_groups.remove(grp)
                progress = True
        if not progress:
            raise NotImplementedError(
                "could not schedule the recurrent graph: a dependency "
                "cycle crosses recurrence bodies in an unsupported way")

    out_name = outer.names.get((root_tensor, False), outer.last_output)
    if out_name is None:
        raise ValueError("model has no computable output")
    g.add_output(out_name, np.float32, None)
    return g.to_bytes(producer="synapseml_tpu.dl.cntk_format")


def _has_seq_ancestry(tensor: str, fns, producer, variables,
                      in_group) -> bool:
    """True when ``tensor`` transitively depends on a model INPUT or on
    another recurrence's output — i.e. it carries the [N, T] axes. A
    purely parameter-derived tensor (e.g. a bias combined outside the
    cycle) does NOT, and scanning it would slice its feature axis as if
    it were time."""
    seen: set = set()
    stack = [tensor]
    while stack:
        t = stack.pop()
        if t in seen:
            continue
        seen.add(t)
        v = variables.get(t)
        if v is not None:
            if v.kind == VAR_INPUT:
                return True
            continue  # parameter/constant
        u = producer.get(t)
        if u is None:
            continue
        if u in in_group:
            return True  # another cycle's scan output: [N, T, ...]
        stack.extend(fns[u].get("inputs", []))
    return False


def _group_crossing(grp, fns, producer, variables,
                    in_group) -> Tuple[List[str], List[str]]:
    """Split tensors consumed inside the cycle but produced outside it
    into (per-timestep scan inputs, static outer-scope captures).
    Parameters/constants resolve inside the body; state-node inputs are
    handled separately. Static tensors (param-derived, no [N, T] axes)
    ride as outer-scope name captures — ONNX subgraphs see enclosing
    names, and the importer's body env carries them."""
    crossing: List[str] = []
    captured: List[str] = []
    nodes = grp["nodes"]
    pv_uids = {pv["uid"] for pv in grp["pvs"]}
    # deterministic order (serialization order, not set order): scan-input
    # ordering decides the emitted bytes and the Shape source tensor
    ordered = sorted(nodes, key=grp["order"].__getitem__)
    for fd in (fns[u] for u in ordered):
        if fd["uid"] in pv_uids:
            continue
        for i in fd.get("inputs", []):
            p = producer.get(i)
            if p is not None and p in nodes:
                continue  # internal to the body
            v = variables.get(i)
            if v is not None and v.kind in (VAR_PARAMETER, VAR_CONSTANT):
                continue  # body-local initializer
            if i in crossing or i in captured:
                continue
            if _has_seq_ancestry(i, fns, producer, variables, in_group):
                crossing.append(i)
            else:
                captured.append(i)
    return crossing, captured


def _group_ready(grp, fns, producer, variables, outer, in_group) -> bool:
    crossing, captured = _group_crossing(grp, fns, producer, variables,
                                         in_group)
    return all(t in variables or (t, False) in outer.names
               for t in crossing + captured)


def _emit_scan_group(g, outer, grp, fns, functions, producer, consumers,
                     variables, infer_last_dim, root_tensor, in_group):
    """Emit one recurrence cycle as an ONNX Scan node."""
    pvs = grp["pvs"]
    nodes = grp["nodes"]
    pv_ops = {int(pv["op"]) for pv in pvs}
    if len(pv_ops) > 1:
        raise NotImplementedError(
            "a single recurrence cycle mixes PastValue and FutureValue "
            "(a true bidirectional loop); split the graph or export via "
            "ONNX")
    backward = OP_FUTURE_VALUE in pv_ops
    pv_uids = {pv["uid"] for pv in pvs}
    body_fns = [fd for fd in functions
                if fd["uid"] in nodes and fd["uid"] not in pv_uids]
    crossing, captured = _group_crossing(grp, fns, producer, variables,
                                         in_group)
    if not crossing:
        raise NotImplementedError(
            "autonomous recurrence (no sequence input feeds the cycle) "
            "has no scan length; not supported")

    # -- body graph: inputs [states..., x_t slices...] -------------------
    body_name = g.fresh("scan_body")
    # prefix namespaces body tensor names: a bare body-local name (e.g.
    # 'add_3') could shadow an identically-named captured outer tensor
    body_g = GraphBuilder(name=body_name, opset=17,
                          name_prefix=f"{body_name}__")
    body_em = _Emitter(body_g, variables)
    for k, pv in enumerate(pvs):
        st = body_g.add_input(f"state_{k}")
        body_em.alias(f"{pv['uid']}_Output_0", st)
    for j, t in enumerate(crossing):
        xt = body_g.add_input(f"xt_{j}")
        body_em.alias(t, xt)
    for t in captured:
        # static (param-derived) outer tensor: reference the OUTER name
        # from inside the body — ONNX outer-scope capture, which the
        # importer's body env provides
        body_em.alias(t, outer.resolve(t) if t in variables
                      else outer.names[(t, False)])
    remaining = list(body_fns)
    while remaining:
        progress = False
        for fd in list(remaining):
            if all((i, False) in body_em.names or i in variables
                   for i in fd.get("inputs", [])):
                body_em.emit(fd)
                remaining.remove(fd)
                progress = True
        if not progress:
            raise NotImplementedError(
                "unschedulable recurrence body (unexpected internal "
                "dependency shape)")

    # outputs: next-state per pv, then the tensors consumed downstream
    for pv in pvs:
        nm = body_em.names.get((pv["inputs"][0], False))
        if nm is None:
            raise NotImplementedError(
                f"recurrent input {pv['inputs'][0]!r} was not computed "
                "inside the cycle body")
        body_g.add_output(body_g.add_node("Identity", [nm]),
                          np.float32, None)
    scan_out_tensors: List[str] = []
    for fd in body_fns + pvs:
        n_out = len(fd.get("inputs", [])) \
            if int(fd["op"]) == OP_COMBINE else 1
        for j in range(n_out):
            t = f"{fd['uid']}_Output_{j}"
            used_outside = any(c not in nodes
                               for c in consumers.get(fd["uid"], []))
            if (used_outside or t == root_tensor) \
                    and t not in scan_out_tensors:
                scan_out_tensors.append(t)
    for t in scan_out_tensors:
        nm = body_em.names.get((t, False))
        if nm is None:
            raise NotImplementedError(
                f"cycle tensor {t!r} consumed downstream was not emitted")
        body_g.add_output(body_g.add_node("Identity", [nm]),
                          np.float32, None)

    # -- outer: initial states broadcast to [N, H] -----------------------
    def outer_name(t: str) -> str:
        return outer.resolve(t) if t in variables \
            else outer.names[(t, False)]

    first_seq = outer_name(crossing[0])
    init_names = []
    for pv in pvs:
        init_uid = pv["inputs"][1] if len(pv["inputs"]) > 1 else None
        iv = variables.get(init_uid) if init_uid else None
        if iv is None or iv.value is None:
            raise NotImplementedError(
                "PastValue initial state must be a constant/parameter")
        arr = np.asarray(iv.value, np.float32)
        declared = tuple(reversed(iv.shape))  # the DECLARED cntk shape:
        # scalar values decode as (1,) arrays, so arr.ndim can't tell
        # a scalar init apart from a genuine width-1 state
        if not declared:
            h = infer_last_dim(pv["inputs"][0])
            if h is None:
                raise NotImplementedError(
                    "cannot infer the state width for a scalar "
                    "initial_state; save the model with a full-shape "
                    "initial state")
            feat = np.asarray([h], np.int64)
            arr = arr.reshape(())  # Expand needs the scalar rank
        else:
            feat = np.asarray(list(declared), np.int64)
        init_c = g.add_initializer(g.fresh("rec_init"), arr)
        shp = g.add_node("Shape", [first_seq])
        n0 = g.add_node("Gather", [shp, g.add_initializer(
            g.fresh("idx0"), np.asarray([0], np.int64))], axis=0)
        tgt = g.add_node("Concat", [n0, g.add_initializer(
            g.fresh("rec_shape"), feat)], axis=0)
        init_names.append(g.add_node("Expand", [init_c, tgt]))

    scan_ins = [outer_name(t) for t in crossing]
    m, k_out = len(crossing), len(scan_out_tensors)
    node_outs = [g.fresh("rec_final") for _ in pvs] \
        + [g.fresh("rec_seq") for _ in scan_out_tensors]
    d = 1 if backward else 0
    g.add_node("Scan", init_names + scan_ins, outputs=node_outs,
               body=body_g.build().graph,
               num_scan_inputs=m,
               scan_input_axes=[1] * m,
               scan_output_axes=[1] * k_out,
               scan_input_directions=[d] * m,
               scan_output_directions=[d] * k_out)
    for t, nm in zip(scan_out_tensors, node_outs[len(pvs):]):
        outer.alias(t, nm)
        outer.last_output = nm


def sniff_cntk_v2(payload: bytes) -> Optional[Dict[str, Any]]:
    """Decode-and-sniff: the parsed Dictionary when the bytes are a v2
    CompositeFunction, else None. Returning the dict lets the caller
    skip a second full (pure-Python, weight-heavy) decode."""
    try:
        top = load_model_dictionary(payload)
    except Exception:  # noqa: BLE001 - any parse failure means "not cntk"
        return None
    return top if top.get("type") == "CompositeFunction" else None


def looks_like_cntk_v2(payload: bytes) -> bool:
    return sniff_cntk_v2(payload) is not None


# ---------------------------------------------------------------------------
# Authoring half (the publishing/export story + test vectors)
# ---------------------------------------------------------------------------

class CntkModelBuilder:
    """Compose a CNTK v2 ``.model`` byte blob (the serialization
    conventions the reader consumes: uid-wired primitive functions,
    ``_Output_k`` naming, reversed-dim NDShapes, column-major payloads).
    Used by the round-trip tests and available as an export target."""

    def __init__(self, name: str = "model"):
        self.name = name
        self._vars: List[Dict[str, Any]] = []
        self._funcs: List[Dict[str, Any]] = []
        self._n = 0

    def _uid(self, tag: str) -> str:
        self._n += 1
        return f"{tag}{self._n}"

    def add_input(self, sample_shape_np: Tuple[int, ...],
                  name: str = "features") -> str:
        uid = self._uid("Input")
        self._vars.append({
            "version": 1, "uid": uid, "kind": VAR_INPUT,
            "data_type": 1, "is_sparse": False, "name": name,
            "needs_gradient": False,
            "shape": [int(s) for s in reversed(sample_shape_np)],
        })
        return uid

    def add_parameter(self, arr_np: np.ndarray, name: str = "") -> str:
        """``arr_np`` in numpy layout; stored reversed/column-major."""
        uid = self._uid("Parameter")
        self._vars.append({
            "version": 1, "uid": uid, "kind": VAR_PARAMETER,
            "data_type": 1, "is_sparse": False,
            "name": name or uid, "needs_gradient": True,
            "shape": [int(s) for s in reversed(arr_np.shape)],
            "value": np.asarray(arr_np, np.float32),
        })
        return uid

    def add_op(self, op: int, inputs: List[str],
               attributes: Optional[Dict[str, Any]] = None,
               name: str = "") -> str:
        uid = self._uid("Func")
        self._funcs.append({
            "version": 1, "uid": uid, "op": int(op),
            "inputs": list(inputs),
            "attributes": dict(attributes or {}), "name": name,
        })
        return f"{uid}_Output_0"

    def set_input(self, func_output: str, idx: int, new_input: str):
        """Patch a function's input after the fact — how a recurrence
        cycle is closed (CNTK builds PastValue against a placeholder and
        rewires it to the step output; the serialized file stores the
        cyclic uid reference)."""
        uid = func_output.rsplit("_Output_", 1)[0]
        for f in self._funcs:
            if f["uid"] == uid:
                f["inputs"][idx] = new_input
                return
        raise KeyError(f"no function {uid!r}")

    def to_bytes(self, root_output: str) -> bytes:
        root = root_output.rsplit("_Output_", 1)[0]
        top = {
            "version": 1,
            "type": "CompositeFunction",
            "root": root,
            "uid": self._uid("Composite"),
            "name": self.name,
            "inputs": self._vars,
            "primitive_functions": self._funcs,
        }
        return proto.encode(py_to_dict(top))


def build_optimized_rnn_model(input_dim: int, hidden: int,
                              num_layers: int = 1,
                              bidirectional: bool = True,
                              cell: str = "lstm", seed: int = 0,
                              scale: float = 0.2,
                              bias_scale: float = 0.05) -> bytes:
    """Random-initialized OptimizedRNNStack ``.model`` bytes.

    Packs seeded weights in the cuDNN canonical blob layout (all (W, R)
    gate matrices per pseudo-layer first, then all (bW, bR) biases —
    the layout torch-oracle-verified in tests/test_cntk_format.py) and
    wraps them in a one-op CNTK v2 graph. The demo/e2e helper behind the
    speech scenario's recurrent stage; for real models, load the bytes
    CNTK wrote.
    """
    gates = {"lstm": 4, "gru": 3, "rnnTanh": 1, "rnnReLU": 1}[cell]
    rng = np.random.default_rng(seed)
    dirs = 2 if bidirectional else 1
    mats: List[np.ndarray] = []
    biases: List[np.ndarray] = []
    in_w = input_dim
    for _layer in range(num_layers):
        for _d in range(dirs):
            mats.append((rng.normal(size=(gates * hidden, in_w))
                         * scale).astype(np.float32).ravel())
            mats.append((rng.normal(size=(gates * hidden, hidden))
                         * scale).astype(np.float32).ravel())
            biases.append((rng.normal(size=gates * hidden)
                           * bias_scale).astype(np.float32))
            biases.append((rng.normal(size=gates * hidden)
                           * bias_scale).astype(np.float32))
        in_w = hidden * dirs
    b = CntkModelBuilder("optimized_rnn")
    x = b.add_input((input_dim,))
    y = b.add_op(OP_OPTIMIZED_RNN,
                 [x, b.add_parameter(np.concatenate(mats + biases))],
                 {"hiddenSize": hidden, "numLayers": num_layers,
                  "bidirectional": bidirectional, "recurrentOp": cell})
    return b.to_bytes(y)
