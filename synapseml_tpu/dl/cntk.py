"""CNTKModel: legacy-CNTK model inference on TPU.

Rebuild of the reference's CNTKModel
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/CNTKModel.scala:147-517
— broadcast serialized ``Function``, per-partition clone, feed/fetch dicts
by node NAME or INDEX (:196-338), minibatched transform :470-515;
SerializableFunction.scala:85-143).

Design decision (TPU-first, not a port): CNTK's binary ``.model`` format is
executed in the reference by the CNTK 2.4 native runtime — dead since 2019
and CUDA/CPU-only. CNTK's own supported interchange path is its ONNX
export (``cntk.Function.save(..., format=ModelFormat.ONNX)``), so this
transformer consumes that artifact and lowers it through the same
ONNX->jax importer as everything else, while keeping CNTKModel's API
surface: ``feed_dict``/``fetch_dict`` accept node names OR integer
indices, ``set_output_node`` selects/truncates by name or index (the
``cutOutputLayers`` sibling), and minibatching matches the reference.
Raw ``.model`` bytes are detected and rejected with the conversion recipe
instead of failing deep in a parser.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from synapseml_tpu.core.param import Param
from synapseml_tpu.onnx.model import ONNXModel


_NATIVE_CNTK_MSG = (
    "this is a native CNTK v2 .model file; its runtime (CNTK 2.4 JNI) has "
    "no TPU port. Export it to ONNX once with the CNTK python package — "
    "z.save('model.onnx', format=cntk.ModelFormat.ONNX) — and load that "
    "file here")


def _looks_like_onnx(payload: bytes) -> bool:
    # ONNX files are a protobuf ModelProto: field 1 (ir_version) varint or
    # field 7/8; CNTK v2 binary models start with the magic "B\x00C\x00N\x00"
    # UTF-16 header ("BCNTK...") or legacy "CNTK" tags.
    head = payload[:64]
    if b"C\x00N\x00T\x00K" in head or head.startswith(b"CNTK"):
        return False
    return True


class CNTKModel(ONNXModel):
    """Runs a CNTK-lineage network (exported to ONNX) as a transformer.

    Extends :class:`ONNXModel` with the reference CNTKModel's
    name-or-index port selection: ``set_input_node(1)`` /
    ``set_output_node("z")`` etc. (ref: CNTKModel.scala setInputNode /
    setOutputNode / setOutputNodeIndex :196-338).
    """

    cut_layers = Param("trailing graph nodes dropped (headless "
                       "featurization; persists across serde)", default=0)

    def __init__(self, model_path: Optional[str] = None,
                 model_bytes: Optional[bytes] = None, **kw):
        if model_path is not None:
            with open(model_path, "rb") as fh:
                model_bytes = fh.read()
            model_path = None
        if model_bytes is not None and not _looks_like_onnx(model_bytes):
            raise ValueError(_NATIVE_CNTK_MSG)
        super().__init__(model_bytes=model_bytes, **kw)

    # -- truncation-aware graph (param-backed: survives save/load/copy) --
    @property
    def graph(self):
        cut = int(self.cut_layers or 0)
        payload = self.model_payload
        cache = self.__dict__.get("_cntk_graph")
        # `is` on the retained payload object (not id(): reuse-safe)
        if (cache is not None and cache[0] == cut
                and cache[1] is payload):
            return cache[2]
        if payload is not None and not _looks_like_onnx(bytes(payload)):
            # covers every assignment path (model_payload=... via set(),
            # the generated R wrapper, load) — not just __init__ kwargs
            raise ValueError(_NATIVE_CNTK_MSG)
        g = ONNXModel.graph.fget(self)
        if cut:
            g = g.truncated(cut)
        self.__dict__["_cntk_graph"] = (cut, payload, g)
        return g

    def _post_copy(self, src):
        super()._post_copy(src)
        self.__dict__.pop("_cntk_graph", None)

    def _load_extra(self, path: str):
        super()._load_extra(path)
        self.__dict__.pop("_cntk_graph", None)

    # -- name-or-index port selection ----------------------------------
    def _input_name(self, node: Union[int, str]) -> str:
        names = self.graph.input_names
        if isinstance(node, int):
            return names[node]
        if node not in names:
            raise KeyError(f"no input node {node!r}; have {names}")
        return node

    def _output_name(self, node: Union[int, str]) -> str:
        names = self.graph.output_names
        if isinstance(node, int):
            return names[node]
        if node not in names:
            raise KeyError(f"no output node {node!r}; have {names}")
        return node

    def set_input_node(self, node: Union[int, str],
                       column: str = "input") -> "CNTKModel":
        """Bind a table column to a graph input by name or index (merges —
        multi-input graphs chain calls)."""
        self.set(feed_dict={**(self.feed_dict or {}),
                            self._input_name(node): column})
        return self

    def set_output_node(self, node: Union[int, str],
                        column: str = "output") -> "CNTKModel":
        """Fetch one graph output by name or index (merges)."""
        self.set(fetch_dict={**(self.fetch_dict or {}),
                             column: self._output_name(node)})
        return self

    def cut_output_layers(self, n: int) -> "CNTKModel":
        """Headless featurization hook (ref: ImageFeaturizer.scala:100
        cutOutputLayers) — drops the trailing ``n`` graph nodes. Stored as
        the ``cut_layers`` param so serde round-trips stay headless."""
        self.set(cut_layers=int(n))
        self.__dict__.pop("_cntk_graph", None)
        self.__dict__["_executor_cache"] = {}
        return self
