"""CNTKModel: legacy-CNTK model inference on TPU.

Rebuild of the reference's CNTKModel
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/CNTKModel.scala:147-517
— broadcast serialized ``Function``, per-partition clone, feed/fetch dicts
by node NAME or INDEX (:196-338), minibatched transform :470-515;
SerializableFunction.scala:85-143).

Design decision (TPU-first, not a port): CNTK's native runtime (CNTK 2.4
JNI) is dead since 2019 and CUDA/CPU-only, so nothing here executes it.
Raw v2 ``.model`` bytes are parsed DIRECTLY — the CNTKv2 protobuf
Dictionary format (dl/cntk_format.py: CompositeFunction layout,
column-major NDShapes, uid-wired primitive functions) converts to ONNX
and lowers through the same ONNX->jax importer as everything else.
CNTK's own ONNX export is equally accepted (and remains the recipe for
v1 binaries or recurrent graphs outside the direct reader's surface).
CNTKModel's API surface is kept: ``feed_dict``/``fetch_dict`` accept
node names OR integer indices, ``set_output_node`` selects/truncates by
name or index (the ``cutOutputLayers`` sibling), minibatching matches
the reference.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from synapseml_tpu.core.param import Param
from synapseml_tpu.onnx.model import ONNXModel


_NATIVE_CNTK_MSG = (
    "this CNTK .model file could not be parsed: the direct reader covers "
    "CNTK v2 feedforward graphs (dl/cntk_format.py); v1/BrainScript-era "
    "binaries and recurrent v2 graphs need a one-time ONNX export with "
    "the CNTK python package — z.save('model.onnx', "
    "format=cntk.ModelFormat.ONNX) — load that file here instead")


def _coerce_payload(payload: bytes) -> bytes:
    """ONNX bytes pass through; CNTK v2 Dictionary bytes convert via the
    direct reader (dl/cntk_format.py); anything else (v1 binaries,
    unsupported graphs) raises with the export recipe."""
    if _looks_like_onnx(payload):
        return payload
    from synapseml_tpu.dl.cntk_format import cntk_to_onnx, sniff_cntk_v2

    parsed = sniff_cntk_v2(payload)  # one decode, reused for conversion
    if parsed is not None:
        try:
            return cntk_to_onnx(payload, parsed=parsed)
        except (NotImplementedError, KeyError, ValueError, TypeError) as e:
            # the class contract is "raises ValueError with the export
            # recipe" — malformed composites must not leak bare KeyErrors
            raise ValueError(f"{_NATIVE_CNTK_MSG} (reader said: {e})") \
                from e
    raise ValueError(_NATIVE_CNTK_MSG)


def _looks_like_onnx(payload: bytes) -> bool:
    # Both ONNX ModelProto and CNTK v2 Dictionary bytes open with a
    # field-1 varint, so magic sniffing is not enough — but a FULL decode
    # just to sniff would parse every weight tensor (and run up to three
    # times on first use). Instead, skim the TOP-LEVEL wire fields only:
    # ModelProto has graph at field 7 / opset_import at 8; the Dictionary
    # has nothing above field 2. Sub-messages are skipped, not decoded.
    head = payload[:64]
    if b"C\x00N\x00T\x00K" in head or head.startswith(b"CNTK"):
        return False
    from synapseml_tpu.onnx.proto import _read_varint, _skip

    pos, end = 0, len(payload)
    try:
        while pos < end:
            tag, pos = _read_varint(payload, pos)
            num, wire = tag >> 3, tag & 7
            if num == 0 or num > 1000:
                return False  # not a sane proto field
            if num in (7, 8) and wire == 2:  # graph / opset_import
                return True
            pos = _skip(payload, pos, wire)
        return False
    except Exception:  # noqa: BLE001 - undecodable -> not ONNX
        return False


class CNTKModel(ONNXModel):
    """Runs a CNTK-lineage network (exported to ONNX) as a transformer.

    Extends :class:`ONNXModel` with the reference CNTKModel's
    name-or-index port selection: ``set_input_node(1)`` /
    ``set_output_node("z")`` etc. (ref: CNTKModel.scala setInputNode /
    setOutputNode / setOutputNodeIndex :196-338).
    """

    cut_layers = Param("trailing graph nodes dropped (headless "
                       "featurization; persists across serde)", default=0)

    def __init__(self, model_path: Optional[str] = None,
                 model_bytes: Optional[bytes] = None, **kw):
        if model_path is not None:
            with open(model_path, "rb") as fh:
                model_bytes = fh.read()
            model_path = None
        if model_bytes is not None:
            model_bytes = _coerce_payload(bytes(model_bytes))
        super().__init__(model_bytes=model_bytes, **kw)

    # -- truncation-aware graph (param-backed: survives save/load/copy) --
    @property
    def graph(self):
        cut = int(self.cut_layers or 0)
        payload = self.model_payload
        cache = self.__dict__.get("_cntk_graph")
        # `is` on the retained payload object (not id(): reuse-safe)
        if (cache is not None and cache[0] == cut
                and cache[1] is payload):
            return cache[2]
        if payload is not None and not _looks_like_onnx(bytes(payload)):
            # covers every assignment path (model_payload=... via set(),
            # the generated R wrapper, load) — not just __init__ kwargs
            payload = _coerce_payload(bytes(payload))
            self.set(model_payload=payload)
        g = ONNXModel.graph.fget(self)
        if cut:
            g = g.truncated(cut)
        self.__dict__["_cntk_graph"] = (cut, payload, g)
        return g

    def _post_copy(self, src):
        super()._post_copy(src)
        self.__dict__.pop("_cntk_graph", None)

    def _load_extra(self, path: str):
        super()._load_extra(path)
        self.__dict__.pop("_cntk_graph", None)

    # -- name-or-index port selection ----------------------------------
    def _input_name(self, node: Union[int, str]) -> str:
        names = self.graph.input_names
        if isinstance(node, int):
            return names[node]
        if node not in names:
            raise KeyError(f"no input node {node!r}; have {names}")
        return node

    def _output_name(self, node: Union[int, str]) -> str:
        names = self.graph.output_names
        if isinstance(node, int):
            return names[node]
        if node not in names:
            raise KeyError(f"no output node {node!r}; have {names}")
        return node

    def set_input_node(self, node: Union[int, str],
                       column: str = "input") -> "CNTKModel":
        """Bind a table column to a graph input by name or index (merges —
        multi-input graphs chain calls)."""
        self.set(feed_dict={**(self.feed_dict or {}),
                            self._input_name(node): column})
        return self

    def set_output_node(self, node: Union[int, str],
                        column: str = "output") -> "CNTKModel":
        """Fetch one graph output by name or index (merges)."""
        self.set(fetch_dict={**(self.fetch_dict or {}),
                             column: self._output_name(node)})
        return self

    def cut_output_layers(self, n: int) -> "CNTKModel":
        """Headless featurization hook (ref: ImageFeaturizer.scala:100
        cutOutputLayers) — drops the trailing ``n`` graph nodes. Stored as
        the ``cut_layers`` param so serde round-trips stay headless."""
        self.set(cut_layers=int(n))
        self.__dict__.pop("_cntk_graph", None)
        self.__dict__["_executor_cache"] = {}
        return self
