"""Sequence tagger — the pod-scale sequence-model family.

The reference's sequence model is a CNTK BiLSTM run one batch at a time for
medical entity extraction (SURVEY.md §5 long-context: "absent";
BASELINE.json config #5 "pod-scale"). The TPU-native design replaces it with
a transformer encoder tagger built to shard over the full 5-axis mesh:

  dp — batch          sp — sequence (ring attention over ICI)
  tp — heads / ffn    ep — MoE experts      pp — stacked pipeline stages

Parameters are plain pytrees with explicit ``NamedSharding`` trees (GSPMD
inserts collectives); attention optionally runs through the manual
shard_map ring kernel (:mod:`synapseml_tpu.parallel.ring_attention`).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from synapseml_tpu.parallel.moe import moe_ffn
from synapseml_tpu.parallel.ring_attention import (
    dense_attention, make_ring_attention, make_ulysses_attention)


@dataclasses.dataclass(frozen=True)
class TaggerConfig:
    vocab_size: int = 4096
    num_tags: int = 16
    d_model: int = 64
    num_heads: int = 4
    head_dim: int = 16
    ffn_dim: int = 128
    num_stages: int = 2          # pipeline stages (stacked, sharded over pp)
    layers_per_stage: int = 1
    num_experts: int = 4
    top_k: int = 2
    max_seq_len: int = 512
    attention: str = "ring"      # ring | ulysses | dense
    dtype: Any = jnp.bfloat16

    @staticmethod
    def for_mesh(mesh: Mesh, **overrides) -> "TaggerConfig":
        """Smallest config whose dims are divisible by the mesh axes."""
        def up(n, m):
            return ((n + m - 1) // m) * m

        ax = dict(mesh.shape)
        pp, tp, ep = ax.get("pp", 1), ax.get("tp", 1), ax.get("ep", 1)
        base = dict(
            num_stages=up(max(2, pp), pp),
            num_heads=up(max(4, tp), tp),
            num_experts=up(max(2, ep), ep),
        )
        base.update(overrides)
        cfg = TaggerConfig(**base)
        # round sharded dims up to mesh divisibility (tp shards d_model/ffn,
        # ep shards experts, pp shards the stage stack)
        fixed = dataclasses.replace(
            cfg,
            num_stages=up(cfg.num_stages, pp),
            num_heads=up(cfg.num_heads, tp),
            num_experts=up(cfg.num_experts, ep),
            d_model=up(cfg.d_model, tp),
            ffn_dim=up(cfg.ffn_dim, tp),
        )
        return fixed


def _init(rng: np.random.Generator, shape, scale=None):
    scale = scale if scale is not None else 1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1])
    return (rng.standard_normal(shape) * scale).astype(np.float32)


def init_params(cfg: TaggerConfig, seed: int = 0) -> Dict[str, Any]:
    r = np.random.default_rng(seed)
    s, l = cfg.num_stages, cfg.layers_per_stage
    d, h, dh, f, e = (cfg.d_model, cfg.num_heads, cfg.head_dim,
                      cfg.ffn_dim, cfg.num_experts)
    return {
        "embed": _init(r, (cfg.vocab_size, d), scale=0.02),
        "stages": {
            "ln1": np.ones((s, l, d), np.float32),
            "ln2": np.ones((s, l, d), np.float32),
            "wq": _init(r, (s, l, d, h, dh)),
            "wk": _init(r, (s, l, d, h, dh)),
            "wv": _init(r, (s, l, d, h, dh)),
            "wo": _init(r, (s, l, h, dh, d), scale=1.0 / np.sqrt(h * dh)),
            "gate": _init(r, (s, l, d, e)),
            "w1": _init(r, (s, l, e, d, f)),
            "w2": _init(r, (s, l, e, f, d), scale=1.0 / np.sqrt(f)),
        },
        "ln_f": np.ones((d,), np.float32),
        "head": _init(r, (d, cfg.num_tags)),
    }


def param_specs(cfg: TaggerConfig) -> Dict[str, Any]:
    """PartitionSpec tree mirroring :func:`init_params`."""
    return {
        "embed": P(None, "tp"),
        "stages": {
            "ln1": P("pp"),
            "ln2": P("pp"),
            "wq": P("pp", None, None, "tp", None),
            "wk": P("pp", None, None, "tp", None),
            "wv": P("pp", None, None, "tp", None),
            "wo": P("pp", None, "tp", None, None),
            "gate": P("pp", None, None, "ep"),
            "w1": P("pp", None, "ep", None, "tp"),
            "w2": P("pp", None, "ep", "tp", None),
        },
        "ln_f": P(),
        "head": P(),
    }


def shard_params(params, mesh: Mesh):
    specs = param_specs(TaggerConfig())  # structure-only; sizes irrelevant
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params, specs)


def _layer_norm(x, scale):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _rope(x, positions):
    """Rotary embedding. x: [B, S, H, D], positions: [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (10000.0 ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def make_apply(cfg: TaggerConfig, mesh: Optional[Mesh] = None) -> Callable:
    """Build the forward function. With a mesh, activations carry sharding
    constraints and attention uses the requested sequence-parallel kernel."""

    if mesh is not None and cfg.attention == "ring":
        attn_fn = make_ring_attention(mesh)
    elif mesh is not None and cfg.attention == "ulysses":
        attn_fn = make_ulysses_attention(mesh)
    else:
        attn_fn = partial(dense_attention, causal=False)

    def wsc(x, *spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))

    def block(x, w, positions):
        # attention
        y = _layer_norm(x, w["ln1"])
        q = jnp.einsum("bsd,dhe->bshe", y, w["wq"].astype(y.dtype))
        k = jnp.einsum("bsd,dhe->bshe", y, w["wk"].astype(y.dtype))
        v = jnp.einsum("bsd,dhe->bshe", y, w["wv"].astype(y.dtype))
        q, k = _rope(q, positions), _rope(k, positions)
        q = wsc(q, "dp", "sp", "tp", None)
        k = wsc(k, "dp", "sp", "tp", None)
        v = wsc(v, "dp", "sp", "tp", None)
        a = attn_fn(q, k, v)
        a = jnp.einsum("bshe,hed->bsd", a, w["wo"].astype(a.dtype))
        x = x + wsc(a, "dp", "sp", None)
        # MoE FFN
        y = _layer_norm(x, w["ln2"])
        expert_spec = (NamedSharding(mesh, P("dp", "sp", "ep", None))
                       if mesh is not None else None)
        m, aux = moe_ffn(y, w["gate"].astype(y.dtype),
                         w["w1"].astype(y.dtype), w["w2"].astype(y.dtype),
                         top_k=cfg.top_k, expert_spec=expert_spec)
        x = x + wsc(m, "dp", "sp", None)
        return x, aux

    def apply(params, tokens):
        # tokens: [B, S] int32
        positions = jnp.arange(tokens.shape[1])
        x = params["embed"].astype(cfg.dtype)[tokens]
        x = wsc(x, "dp", "sp", None)
        aux_total = jnp.zeros((), jnp.float32)

        def layer_step(carry, w):
            x, aux = carry
            x, a = block(x, w, positions)
            return (x, aux + a), None

        def stage_step(carry, stage_w):
            # scan over the layers of one pipeline stage
            (x, aux), _ = jax.lax.scan(layer_step, carry, stage_w)
            return (x, aux), None

        (x, aux_total), _ = jax.lax.scan(
            stage_step, (x, aux_total), params["stages"])
        x = _layer_norm(x, params["ln_f"])
        logits = jnp.einsum("bsd,dt->bst", x.astype(jnp.float32),
                            params["head"])
        return logits, aux_total

    return apply


def tagging_loss(logits, labels, mask, aux, aux_weight=0.01):
    """Token-level cross entropy. labels: [B,S] int, mask: [B,S] bool."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    return (nll * mask).sum() / denom + aux_weight * aux


def make_train_step(cfg: TaggerConfig, mesh: Mesh, learning_rate: float = 1e-3):
    """Jitted sharded train step: (params, opt_state, batch) -> (params, opt_state, loss)."""
    apply = make_apply(cfg, mesh)
    tx = optax.adamw(learning_rate)

    def loss_fn(params, tokens, labels, mask):
        logits, aux = apply(params, tokens)
        return tagging_loss(logits, labels, mask, aux)

    def train_step(params, opt_state, tokens, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels, mask)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    batch_shard = NamedSharding(mesh, P("dp", "sp"))

    def init_state(seed: int = 0):
        params = shard_params(init_params(cfg, seed), mesh)
        opt_state = tx.init(params)
        return params, opt_state

    jitted = jax.jit(train_step, donate_argnums=(0, 1))
    return jitted, init_state, batch_shard
