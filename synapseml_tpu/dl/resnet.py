"""ResNet family in flax — the flagship inference model.

The reference runs ResNet-50 through onnxruntime-CUDA
(ref: deep-learning/.../onnx/ONNXModel.scala:422-684, notebook
"ONNX - Inference on Spark"). Here the flagship path is a native flax
implementation compiled by XLA onto the MXU: NHWC layout (TPU-preferred),
bf16 compute with f32 batch-norm statistics, and an optional truncation
point so :class:`synapseml_tpu.image.featurizer.ImageFeaturizer` can reuse
the same network headless (the CNTK ``cutOutputLayers`` analogue,
ref: deep-learning/.../cntk/ImageFeaturizer.scala:100-125).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet. ``num_classes=None`` -> pooled features (headless)."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: Optional[int] = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, capture: Optional[list] = None):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, strides,
                                   conv=conv, norm=norm,
                                   name=f"stage{i}_block{j}")(x)
            if capture is not None:
                capture.append(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        features = x.astype(jnp.float32)
        if self.num_classes is None:
            return features
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          name="head")(features)
        return logits.astype(jnp.float32)


def resnet18(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes, dtype=dtype)


def resnet34(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet([3, 4, 6, 3], BasicBlock, num_classes, dtype=dtype)


def resnet50(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes, dtype=dtype)


def resnet101(num_classes=1000, dtype=jnp.bfloat16):
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes, dtype=dtype)


def init_resnet(model: ResNet, rng: jax.Array, image_size: int = 224):
    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3),
                                          jnp.float32), train=False)
    return variables


def make_forward(model: ResNet, variables) -> Callable[[jnp.ndarray], jnp.ndarray]:
    def forward(images):
        return model.apply(variables, images, train=False)
    return forward
