"""ModelDownloader: pretrained-model repository with hash verification.

Rebuild of the reference's downloader
(ref: deep-learning/src/main/scala/com/microsoft/ml/spark/cntk/downloader/ModelDownloader.scala:197-265
— remote repo (DefaultModelRepo:112) + local/HDFS repo (HDFSRepo:42),
hash-verified download :233-260; Schema.scala:53-72 ``ModelSchema``
carrying the input node + layer names the ImageFeaturizer needs).

Repos here are a directory (or base URL) containing ``manifest.json``:
``{"models": [{"name", "file", "sha256", "format", "input_name",
"image_size", ...}]}``. Downloads verify sha256 before the artifact is
admitted to the local cache; corrupt bytes never land.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional

from synapseml_tpu.io.http import (HandlingUtils, HTTPRequestData,
                                   SingleThreadedHTTPClient)


@dataclasses.dataclass(frozen=True)
class ModelSchema:
    """(ref: downloader/Schema.scala:53-72)."""
    name: str
    file: str
    sha256: str
    format: str = "onnx"
    input_name: Optional[str] = None
    image_size: Optional[int] = None
    num_layers: Optional[int] = None
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ModelSchema":
        known = {f.name for f in dataclasses.fields(ModelSchema)} - {"extra"}
        return ModelSchema(
            **{k: v for k, v in d.items() if k in known},
            extra={k: v for k, v in d.items() if k not in known})


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class ModelDownloader:
    """Fetch models from a repo (dir or http(s) base URL) into a local
    cache, verifying hashes (ref: ModelDownloader.scala downloadModel
    :233-260)."""

    def __init__(self, local_cache: str,
                 repo: Optional[str] = None):
        self.local_cache = local_cache
        self.repo = repo
        os.makedirs(local_cache, exist_ok=True)
        self._client = SingleThreadedHTTPClient(
            HandlingUtils.advanced(100, 500, 1000))

    # -- repo IO --------------------------------------------------------
    def _is_remote(self) -> bool:
        return bool(self.repo) and self.repo.startswith(("http://",
                                                         "https://"))

    def _fetch(self, rel: str) -> bytes:
        if self.repo is None:
            raise ValueError("no repo configured")
        if self._is_remote():
            resp = self._client.send(HTTPRequestData(
                url=f"{self.repo.rstrip('/')}/{rel}", method="GET"))
            if not 200 <= resp.status_code < 300:
                raise FileNotFoundError(
                    f"{rel}: HTTP {resp.status_code} from {self.repo}")
            return resp.entity or b""
        with open(os.path.join(self.repo, rel), "rb") as fh:
            return fh.read()

    # -- public surface -------------------------------------------------
    def list_models(self) -> List[ModelSchema]:
        """(ref: ModelDownloader.remoteModels)."""
        manifest = json.loads(self._fetch("manifest.json").decode("utf-8"))
        return [ModelSchema.from_dict(m) for m in manifest["models"]]

    def local_models(self) -> List[ModelSchema]:
        """Models already admitted to the cache."""
        out = []
        for name in sorted(os.listdir(self.local_cache)):
            if name.endswith(".json"):
                with open(os.path.join(self.local_cache, name)) as fh:
                    out.append(ModelSchema.from_dict(json.load(fh)))
        return out

    def download_by_name(self, name: str) -> str:
        """Returns the local path; verifies sha256 before admitting
        (a corrupt or tampered artifact raises and is discarded)."""
        schema = next((m for m in self.list_models() if m.name == name),
                      None)
        if schema is None:
            raise KeyError(f"model {name!r} not in repo manifest")
        target = os.path.join(self.local_cache, schema.file)
        if os.path.exists(target) and _sha256(target) == schema.sha256:
            return target
        data = self._fetch(schema.file)
        fd, tmp = tempfile.mkstemp(dir=self.local_cache)
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(data)
            got = _sha256(tmp)
            if got != schema.sha256:
                raise IOError(
                    f"hash mismatch for {name}: manifest {schema.sha256}, "
                    f"downloaded {got}")
            os.replace(tmp, target)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        with open(os.path.join(self.local_cache,
                               f"{schema.name}.json"), "w") as fh:
            json.dump(dataclasses.asdict(schema), fh)
        return target

    def get_bytes(self, name: str) -> bytes:
        with open(self.download_by_name(name), "rb") as fh:
            return fh.read()

    def load_onnx_model(self, name: str, **kw):
        """Straight to an ONNXModel transformer."""
        from synapseml_tpu.onnx.model import ONNXModel

        return ONNXModel(model_bytes=self.get_bytes(name), **kw)

    def load_image_featurizer(self, name: str, **kw):
        """Straight to an ImageFeaturizer, schema-informed."""
        from synapseml_tpu.image.featurizer import ImageFeaturizer

        schema = next((m for m in self.list_models() if m.name == name))
        if schema.image_size is not None:
            kw.setdefault("image_size", schema.image_size)
        return ImageFeaturizer(model_bytes=self.get_bytes(name), **kw)


def make_repo(path: str, models: Dict[str, bytes],
              schemas: Optional[Dict[str, Dict[str, Any]]] = None) -> str:
    """Author a repo directory from model bytes (the publishing half;
    tests and airgapped deployments build repos this way)."""
    os.makedirs(path, exist_ok=True)
    entries = []
    for name, blob in models.items():
        fname = f"{name}.onnx"
        with open(os.path.join(path, fname), "wb") as fh:
            fh.write(blob)
        entry = {"name": name, "file": fname,
                 "sha256": hashlib.sha256(blob).hexdigest(),
                 "format": "onnx"}
        entry.update((schemas or {}).get(name, {}))
        entries.append(entry)
    with open(os.path.join(path, "manifest.json"), "w") as fh:
        json.dump({"models": entries}, fh, indent=1)
    return path
