"""Codegen: reflection-driven R wrappers + API reference generation.

Rebuild of the reference's codegen layer
(ref: core/src/main/scala/com/microsoft/ml/spark/codegen/CodeGen.scala:22-199
— reflects over the compiled jar and emits .py/.R wrapper files per
Wrappable stage; Wrappable.scala:19-515 param-type -> wrapper-type mapping;
GenerationUtils.scala camelToSnake helpers).

Python is this framework's source of truth (the reference's single source
is Scala, SURVEY.md §2.1), so the generated surface is:
- sparklyr-style R wrappers calling through ``reticulate`` (one .R file
  per stage, roxygen docs from Param docstrings, defaults preserved);
- a markdown API reference over every registered stage.

Run: ``python -m synapseml_tpu.codegen [out_dir]`` (writes ``generated/``).
"""
from __future__ import annotations

import importlib
import os
import pkgutil
from typing import Any, Dict, List, Optional, Tuple

from synapseml_tpu.core.param import ComplexParam, Param
from synapseml_tpu.core.pipeline import (Estimator, Evaluator, Transformer,
                                         _STAGE_REGISTRY)


def import_all_modules() -> None:
    """Load every submodule so the stage registry is complete
    (JarLoadingUtils reflection-scan analogue)."""
    import synapseml_tpu as pkg

    for m in pkgutil.walk_packages(pkg.__path__, pkg.__name__ + "."):
        try:
            importlib.import_module(m.name)
        except Exception:  # noqa: BLE001 - optional deps must not break codegen
            continue


def public_stages() -> Dict[str, type]:
    """Concrete public library stages, qualified-name keyed."""
    import_all_modules()
    out = {}
    for qual, cls in sorted(_STAGE_REGISTRY.items()):
        if not qual.startswith("synapseml_tpu."):
            continue
        name = qual.rsplit(".", 1)[1]
        if name.startswith("_"):
            continue
        if name in ("Estimator", "Transformer", "Model", "Evaluator",
                    "Pipeline", "PipelineModel", "PipelineStage"):
            continue
        out[qual] = cls
    return out


def stage_params(cls: type) -> List[Tuple[str, Param]]:
    return sorted(cls.params().items())


def _camel(snake: str) -> str:
    parts = snake.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _r_default(p: Param) -> str:
    if not p.has_default() or isinstance(p, ComplexParam):
        return "NULL"
    d = p.default
    if d is None:
        return "NULL"
    if isinstance(d, bool):
        return "TRUE" if d else "FALSE"
    if isinstance(d, (int, float)):
        return repr(d)
    if isinstance(d, str):
        return f'"{d}"'
    if isinstance(d, (tuple, list)):
        inner = ", ".join(_r_default_value(v) for v in d)
        return f"c({inner})" if inner else "NULL"
    return "NULL"


def _r_default_value(v: Any) -> str:
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, float)):
        return repr(v)
    return f'"{v}"'


def _snake_r(name: str) -> str:
    """CamelCase -> snake, keeping acronym runs together (LightGBMRanker ->
    light_gbm_ranker, OCR -> ocr) — camelToSnake, GenerationUtils.scala."""
    import re

    s = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name)
    s = re.sub(r"(?<=[A-Z])(?=[A-Z][a-z])", "_", s)
    return s.lower()


def generate_r_wrapper(qual: str, cls: type) -> str:
    """One sparklyr-style wrapper function (ref: Wrappable.scala RWrappable)."""
    name = qual.rsplit(".", 1)[1]
    fn_name = f"smt_{_snake_r(name)}"
    params = stage_params(cls)
    kind = ("estimator" if issubclass(cls, Estimator)
            else "evaluator" if issubclass(cls, Evaluator)
            else "transformer")

    lines = [f"#' {name}", "#'"]
    doc = (cls.__doc__ or "").strip().splitlines()
    if doc:
        lines.append(f"#' {doc[0]}")
        lines.append("#'")
    for pname, p in params:
        lines.append(f"#' @param {pname} {p.doc or pname}")
    lines.append(f"#' @return a synapseml_tpu {kind} handle")
    lines.append("#' @export")
    args = ", ".join(f"{pname} = {_r_default(p)}" for pname, p in params)
    lines.append(f"{fn_name} <- function({args}) {{")
    lines.append('  mod <- reticulate::import("' +
                 qual.rsplit(".", 1)[0] + '")')
    lines.append("  kwargs <- Filter(Negate(is.null), list(")
    lines.append(",\n".join(f"    {pname} = {pname}"
                            for pname, _ in params))
    lines.append("  ))")
    lines.append(f'  do.call(mod${name}, kwargs)')
    lines.append("}")
    return "\n".join(lines) + "\n"


def generate_r(out_dir: str) -> List[str]:
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for qual, cls in public_stages().items():
        name = qual.rsplit(".", 1)[1]
        path = os.path.join(out_dir, f"smt_{_snake_r(name)}.R")
        with open(path, "w") as fh:
            fh.write(generate_r_wrapper(qual, cls))
        written.append(path)
    return written


def generate_api_reference(out_path: str) -> str:
    """Markdown API reference over every registered stage."""
    stages = public_stages()
    by_module: Dict[str, List[Tuple[str, type]]] = {}
    for qual, cls in stages.items():
        mod = qual.rsplit(".", 2)[0]
        by_module.setdefault(mod, []).append((qual, cls))
    lines = ["# synapseml_tpu API reference", "",
             f"{len(stages)} pipeline stages (generated by "
             "`python -m synapseml_tpu.codegen`).", ""]
    for mod in sorted(by_module):
        lines.append(f"## {mod}")
        lines.append("")
        for qual, cls in by_module[mod]:
            name = qual.rsplit(".", 1)[1]
            kind = ("Estimator" if issubclass(cls, Estimator)
                    else "Evaluator" if issubclass(cls, Evaluator)
                    else "Transformer")
            doc = (cls.__doc__ or "").strip().splitlines()
            head = doc[0] if doc else ""
            lines.append(f"### {name} ({kind})")
            lines.append("")
            if head:
                lines.append(head)
                lines.append("")
            params = stage_params(cls)
            if params:
                lines.append("| param | default | doc |")
                lines.append("|---|---|---|")
                for pname, p in params:
                    d = (repr(p.default)
                         if p.has_default() and not isinstance(p, ComplexParam)
                         else "—")
                    lines.append(f"| `{pname}` | `{d}` | {p.doc} |")
                lines.append("")
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    content = "\n".join(lines)
    with open(out_path, "w") as fh:
        fh.write(content)
    return content


def main(out_dir: str = "generated"):
    r_files = generate_r(os.path.join(out_dir, "R"))
    generate_api_reference(os.path.join(out_dir, "api.md"))
    print(f"wrote {len(r_files)} R wrappers + api.md under {out_dir}/")


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else "generated")
