from synapseml_tpu.codegen import main

if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else "generated")
