"""Featurization layer (ref inventory: SURVEY.md §2.4 featurize/)."""
from synapseml_tpu.featurize.assemble import (
    Featurize,
    FeaturizeModel,
    OneHotEncoder,
    VectorAssembler,
)
from synapseml_tpu.featurize.clean import (
    CleanMissingData,
    CleanMissingDataModel,
    CountSelector,
    CountSelectorModel,
    DataConversion,
)
from synapseml_tpu.featurize.indexer import (
    IndexToValue,
    ValueIndexer,
    ValueIndexerModel,
)
from synapseml_tpu.featurize.text import (
    IDF,
    HashingTF,
    IDFModel,
    MultiNGram,
    NGram,
    PageSplitter,
    StopWordsRemover,
    TextFeaturizer,
    TextFeaturizerModel,
    Tokenizer,
)

__all__ = [
    "CleanMissingData", "CleanMissingDataModel", "CountSelector",
    "CountSelectorModel", "DataConversion", "Featurize", "FeaturizeModel",
    "HashingTF", "IDF", "IDFModel", "IndexToValue", "MultiNGram", "NGram",
    "OneHotEncoder", "PageSplitter", "StopWordsRemover", "TextFeaturizer",
    "TextFeaturizerModel", "Tokenizer", "ValueIndexer", "ValueIndexerModel",
    "VectorAssembler",
]
