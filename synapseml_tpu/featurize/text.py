"""Text featurization: tokenize → stopwords → n-grams → hashing TF → IDF.

Re-design of the reference's TextFeaturizer pipeline estimator
(ref: core/.../featurize/text/TextFeaturizer.scala:196-405), MultiNGram
(ref: core/.../featurize/text/MultiNGram.scala:26) and PageSplitter
(ref: core/.../featurize/text/PageSplitter.scala:23).

TPU-first: token hashing uses memoized murmur3 so each distinct token is hashed
once; the TF matrix is built as one dense (rows × num_features) float32 array —
a single contiguous buffer ready for ``device_put`` — and IDF scaling is a
vectorized multiply.
"""
from __future__ import annotations

import re
from typing import List, Optional, Sequence

import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasInputCol, HasOutputCol, Param
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.utils.hashing import hash_index

# Default English stopword list (short; matches the spirit of Spark's remover).
ENGLISH_STOPWORDS = frozenset("""a about above after again against all am an and
any are as at be because been before being below between both but by could did
do does doing down during each few for from further had has have having he her
here hers herself him himself his how i if in into is it its itself just me
more most my myself no nor not now of off on once only or other our ours
ourselves out over own same she should so some such than that the their theirs
them themselves then there these they this those through to too under until up
very was we were what when where which while who whom why will with you your
yours yourself yourselves""".split())


class Tokenizer(Transformer, HasInputCol, HasOutputCol):
    """Regex tokenizer (default: split on non-word chars, lowercase)."""

    pattern = Param("token regex", default=r"[A-Za-z0-9_']+")
    to_lowercase = Param("lowercase before tokenizing", default=True)
    min_token_length = Param("drop shorter tokens", default=1)

    def _transform(self, table: Table) -> Table:
        rx = re.compile(self.pattern)
        lower = self.to_lowercase
        min_len = self.min_token_length
        out = np.empty(table.num_rows, dtype=object)
        for i, text in enumerate(table[self.input_col]):
            s = str(text).lower() if lower else str(text)
            out[i] = [t for t in rx.findall(s) if len(t) >= min_len]
        return table.with_column(self.output_col, out)


class StopWordsRemover(Transformer, HasInputCol, HasOutputCol):
    stop_words = ComplexParam("words to remove", default=None)

    def _transform(self, table: Table) -> Table:
        stop = frozenset(self.stop_words) if self.stop_words else ENGLISH_STOPWORDS
        out = np.empty(table.num_rows, dtype=object)
        for i, toks in enumerate(table[self.input_col]):
            out[i] = [t for t in toks if t not in stop]
        return table.with_column(self.output_col, out)


def _ngrams(tokens: Sequence[str], n: int) -> List[str]:
    if n <= 1:
        return list(tokens)
    return [" ".join(tokens[i:i + n]) for i in range(len(tokens) - n + 1)]


class NGram(Transformer, HasInputCol, HasOutputCol):
    n = Param("gram size", default=2)

    def _transform(self, table: Table) -> Table:
        out = np.empty(table.num_rows, dtype=object)
        for i, toks in enumerate(table[self.input_col]):
            out[i] = _ngrams(list(toks), self.n)
        return table.with_column(self.output_col, out)


class MultiNGram(Transformer, HasInputCol, HasOutputCol):
    """All n-gram sizes in one output list (ref: MultiNGram.scala:26)."""

    lengths = Param("gram sizes to include", default=(1, 2, 3))

    def _transform(self, table: Table) -> Table:
        sizes = list(self.lengths)
        out = np.empty(table.num_rows, dtype=object)
        for i, toks in enumerate(table[self.input_col]):
            toks = list(toks)
            merged: List[str] = []
            for n in sizes:
                merged.extend(_ngrams(toks, n))
            out[i] = merged
        return table.with_column(self.output_col, out)


class PageSplitter(Transformer, HasInputCol, HasOutputCol):
    """Splits long strings into pages within [min,max] bytes, preferring
    whitespace boundaries (ref: PageSplitter.scala:23)."""

    maximum_page_length = Param("max page chars", default=5000)
    minimum_page_length = Param("min page chars before forced split", default=4500)
    boundary_regex = Param("split-preferred boundary", default=r"\s")

    def _transform(self, table: Table) -> Table:
        lo, hi = self.minimum_page_length, self.maximum_page_length
        rx = re.compile(self.boundary_regex)
        out = np.empty(table.num_rows, dtype=object)
        for i, text in enumerate(table[self.input_col]):
            s = str(text)
            pages: List[str] = []
            while len(s) > hi:
                cut = hi
                for m in rx.finditer(s, lo, hi):
                    cut = m.end()  # end(): boundary consumed, cut always > 0
                pages.append(s[:cut])
                s = s[cut:]
            pages.append(s)
            out[i] = pages
        return table.with_column(self.output_col, out)


class _CopyColumn(Transformer, HasInputCol, HasOutputCol):
    def _transform(self, table: Table) -> Table:
        return table.with_column(self.output_col, table[self.input_col])


class HashingTF(Transformer, HasInputCol, HasOutputCol):
    """Token lists → dense hashed term-frequency matrix (murmur3 slots)."""

    num_features = Param("hash space size", default=1 << 12)
    binary = Param("presence instead of counts", default=False)

    def _transform(self, table: Table) -> Table:
        d = self.num_features
        mat = np.zeros((table.num_rows, d), dtype=np.float32)
        for i, toks in enumerate(table[self.input_col]):
            for t in toks:
                mat[i, hash_index(t, d)] += 1.0
        if self.binary:
            mat = (mat > 0).astype(np.float32)
        return table.with_column(self.output_col, mat)


class IDFModel(Model, HasInputCol, HasOutputCol):
    idf = ComplexParam("per-slot inverse document frequencies")

    def _transform(self, table: Table) -> Table:
        tf = np.asarray(table[self.input_col], dtype=np.float32)
        return table.with_column(self.output_col, tf * np.asarray(self.idf, dtype=np.float32))


class IDF(Estimator, HasInputCol, HasOutputCol):
    min_doc_freq = Param("slots below this doc-freq get idf 0", default=0)

    def _fit(self, table: Table) -> IDFModel:
        tf = np.asarray(table[self.input_col], dtype=np.float32)
        n = tf.shape[0]
        df = np.count_nonzero(tf, axis=0).astype(np.float32)
        idf = np.log((n + 1.0) / (df + 1.0))
        if self.min_doc_freq > 0:
            idf = np.where(df >= self.min_doc_freq, idf, 0.0)
        return IDFModel(idf=idf.astype(np.float32),
                        input_col=self.input_col, output_col=self.output_col)


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    """One-stop text pipeline (ref: TextFeaturizer.scala:196): tokenize →
    optional stopword removal → n-grams → hashing TF → optional IDF."""

    use_tokenizer = Param("run tokenizer", default=True)
    tokenizer_pattern = Param("token regex", default=r"[A-Za-z0-9_']+")
    to_lowercase = Param("lowercase", default=True)
    use_stop_words_remover = Param("remove stopwords", default=False)
    use_ngram = Param("emit n-grams", default=False)
    n_gram_length = Param("gram size", default=2)
    num_features = Param("hash space size", default=1 << 12)
    binary = Param("binary TF", default=False)
    use_idf = Param("apply IDF rescaling", default=True)
    min_doc_freq = Param("IDF min doc freq", default=1)

    def _build_pipeline(self):
        from synapseml_tpu.core.pipeline import Pipeline
        stages: list = []
        if self.use_tokenizer:
            stages.append(Tokenizer(
                input_col=self.input_col, output_col="__tokens",
                pattern=self.tokenizer_pattern, to_lowercase=self.to_lowercase))
        else:
            # work on a scratch copy so the caller's pre-tokenized column
            # is never overwritten by downstream stages
            stages.append(_CopyColumn(
                input_col=self.input_col, output_col="__tokens"))
        col = "__tokens"
        if self.use_stop_words_remover:
            stages.append(StopWordsRemover(input_col=col, output_col=col))
        if self.use_ngram:
            stages.append(NGram(input_col=col, output_col=col, n=self.n_gram_length))
        tf_out = "__tf" if self.use_idf else self.output_col
        stages.append(HashingTF(
            input_col=col, output_col=tf_out,
            num_features=self.num_features, binary=self.binary))
        if self.use_idf:
            stages.append(IDF(input_col=tf_out, output_col=self.output_col,
                              min_doc_freq=self.min_doc_freq))
        return Pipeline(stages)

    def _fit(self, table: Table) -> "TextFeaturizerModel":
        inner = self._build_pipeline().fit(table)
        return TextFeaturizerModel(
            inner=inner, input_col=self.input_col, output_col=self.output_col)


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    inner = ComplexParam("fitted internal pipeline")

    def _transform(self, table: Table) -> Table:
        out = self.inner.transform(table)
        return out.drop("__tokens", "__tf")
