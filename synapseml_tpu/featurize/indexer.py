"""Categorical value indexing.

TPU-native re-design of the reference's ValueIndexer/IndexToValue
(ref: core/.../featurize/ValueIndexer.scala:56-203, IndexToValue.scala:29):
instead of per-row UDFs, the whole column is indexed in one vectorized
``np.searchsorted`` pass over the sorted level table, which keeps the output a
flat int32 column ready for a single host→device transfer.

Null ordering matches the reference: missing values (None / NaN) map to the
last index (level count), so downstream one-hot can reserve a slot for them.
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasInputCol, HasOutputCol, Param
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    """Maps raw categorical values to dense int32 indices."""

    levels = ComplexParam("ordered distinct levels (missing excluded)")
    data_type = Param("original value kind: 'string'|'int'|'float'|'bool'", default="string")

    def __init__(self, levels: Optional[List[Any]] = None, **kw):
        super().__init__(**kw)
        if levels is not None:
            self.set(levels=list(levels))

    def _transform(self, table: Table) -> Table:
        col = table[self.input_col]
        levels = list(self.levels or [])
        lut = {v: i for i, v in enumerate(levels)}
        missing_idx = len(levels)
        if col.dtype == object:
            idx = np.fromiter(
                (missing_idx if _is_missing(v) else lut.get(v, missing_idx) for v in col),
                dtype=np.int32, count=len(col))
        elif not levels:
            idx = np.full(len(col), missing_idx, dtype=np.int32)
        else:
            # numeric path: vectorized searchsorted over sorted levels
            lv = np.asarray(levels)
            order = np.argsort(lv)
            pos = np.searchsorted(lv[order], col)
            pos = np.clip(pos, 0, len(levels) - 1)
            hit = lv[order][pos] == col
            idx = np.where(hit, order[pos], missing_idx).astype(np.int32)
            if np.issubdtype(col.dtype, np.floating):
                idx = np.where(np.isnan(col), missing_idx, idx).astype(np.int32)
        return table.with_column(self.output_col, idx)


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Learns distinct levels of a column (ref: ValueIndexer.scala:56).

    Levels are sorted for determinism; missing values get the trailing index.
    """

    def _fit(self, table: Table) -> ValueIndexerModel:
        col = table[self.input_col]
        if col.dtype == object:
            seen = {v for v in col if not _is_missing(v)}
            levels: List[Any] = sorted(seen, key=lambda v: (str(type(v)), v))
            kind = "string"
        else:
            vals = col[~np.isnan(col)] if np.issubdtype(col.dtype, np.floating) else col
            levels = np.unique(vals).tolist()
            kind = "float" if np.issubdtype(col.dtype, np.floating) else (
                "bool" if col.dtype == bool else "int")
        return ValueIndexerModel(
            levels=levels, input_col=self.input_col,
            output_col=self.output_col, data_type=kind)


class IndexToValue(Transformer, HasInputCol, HasOutputCol):
    """Inverse map: indices back to original levels (ref: IndexToValue.scala:29)."""

    levels = ComplexParam("ordered distinct levels")
    default_value = Param("value emitted for the missing index", default=None)

    def _transform(self, table: Table) -> Table:
        idx = np.asarray(table[self.input_col], dtype=np.int64)
        levels = list(self.levels or [])
        out = np.empty(len(idx), dtype=object)
        for i, j in enumerate(idx):
            out[i] = levels[j] if 0 <= j < len(levels) else self.default_value
        return table.with_column(self.output_col, out)
