"""Missing-value imputation, dtype conversion, zero-variance pruning.

Re-designs of the reference's CleanMissingData (ref:
core/.../featurize/CleanMissingData.scala:48-182), DataConversion
(ref: core/.../featurize/DataConversion.scala:21-173) and CountSelector
(ref: core/.../featurize/CountSelector.scala:23) as vectorized columnar ops.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import ComplexParam, Param, Params
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table


class CleanMissingDataModel(Model):
    fill_values = ComplexParam("column -> replacement value")
    input_cols = Param("columns to clean", default=None)
    output_cols = Param("output column names (default: in place)", default=None)

    def _transform(self, table: Table) -> Table:
        fills: Dict[str, float] = self.fill_values or {}
        ins: List[str] = self.input_cols or list(fills)
        outs: List[str] = self.output_cols or ins
        new = {}
        for cin, cout in zip(ins, outs):
            col = table[cin]
            if np.issubdtype(col.dtype, np.floating):
                new[cout] = np.where(np.isnan(col), fills[cin], col)
            elif col.dtype == object:
                new[cout] = np.array(
                    [fills[cin] if v is None else v for v in col], dtype=object)
            else:
                new[cout] = col
        return table.with_columns(new)


class CleanMissingData(Estimator):
    """Impute missing values per column: mean / median / custom constant
    (ref: CleanMissingData.scala:48)."""

    input_cols = Param("columns to clean", default=None)
    output_cols = Param("output column names", default=None)
    cleaning_mode = Param("'Mean' | 'Median' | 'Custom'", default="Mean")
    custom_value = Param("replacement for Custom mode", default=None)

    def _fit(self, table: Table) -> CleanMissingDataModel:
        mode = self.cleaning_mode
        ins = self.input_cols or [
            c for c, arr in ((c, table[c]) for c in table.columns)
            if np.issubdtype(arr.dtype, np.number)
        ]
        fills: Dict[str, float] = {}
        for c in ins:
            col = table[c]
            if mode == "Custom":
                fills[c] = self.custom_value
            else:
                vals = col[~np.isnan(col)] if np.issubdtype(col.dtype, np.floating) else col
                fills[c] = float(np.mean(vals)) if mode == "Mean" else float(np.median(vals))
        return CleanMissingDataModel(
            fill_values=fills, input_cols=ins,
            output_cols=self.output_cols or ins)


_CONVERSIONS = {
    "boolean": np.bool_,
    "byte": np.int8,
    "short": np.int16,
    "integer": np.int32,
    "long": np.int64,
    "float": np.float32,
    "double": np.float64,
    "string": object,
}


class DataConversion(Transformer):
    """Cast listed columns to a target type (ref: DataConversion.scala:21).

    ``convert_to='toCategorical'`` indexes in place via ValueIndexer;
    ``'clearCategorical'`` is a no-op here (no MLlib metadata to strip).
    """

    cols = Param("columns to convert", default=None)
    convert_to = Param("target type name", default="double")
    date_format = Param("strftime format for date→string", default="yyyy-MM-dd HH:mm:ss")
    categorical_models = ComplexParam(
        "per-column fitted indexers, learned on first transform so repeated "
        "batches map values consistently", default=None)

    def _post_copy(self, src: Params):
        super()._post_copy(src)
        # the fit-on-first-use indexer cache must not be shared by reference
        # across copies: one copy's transform would mutate another's mapping
        if self._paramMap.get("categorical_models"):
            self._paramMap["categorical_models"] = dict(
                self._paramMap["categorical_models"])

    def _transform(self, table: Table) -> Table:
        target = self.convert_to
        new = {}
        for c in self.cols or []:
            col = table[c]
            if target == "toCategorical":
                from synapseml_tpu.featurize.indexer import ValueIndexer
                cache = self.categorical_models
                if cache is None:
                    cache = {}
                    self.set(categorical_models=cache)
                if c not in cache:
                    cache[c] = ValueIndexer(input_col=c, output_col=c).fit(table)
                new[c] = cache[c].transform(table)[c]
            elif target == "clearCategorical":
                new[c] = col
            elif target == "string":
                new[c] = np.array([str(v) for v in col], dtype=object)
            else:
                np_t = _CONVERSIONS[target]
                if col.dtype == object:
                    col = np.array([float(v) for v in col])
                new[c] = col.astype(np_t)
        return table.with_columns(new)


class CountSelectorModel(Model):
    indices = ComplexParam("slot indices to keep")
    input_col = Param("vector input column", default="features")
    output_col = Param("output column", default="features")

    def _transform(self, table: Table) -> Table:
        idx = np.asarray(self.indices)
        mat = np.asarray(table[self.input_col])
        return table.with_column(self.output_col, mat[:, idx])


class CountSelector(Estimator):
    """Drops vector slots that are zero for every row (ref: CountSelector.scala:23)."""

    input_col = Param("vector input column", default="features")
    output_col = Param("output column", default="features")

    def _fit(self, table: Table) -> CountSelectorModel:
        mat = np.asarray(table[self.input_col])
        nonzero = np.flatnonzero(np.any(mat != 0, axis=0))
        return CountSelectorModel(
            indices=nonzero, input_col=self.input_col, output_col=self.output_col)
