"""Feature assembly: VectorAssembler, OneHotEncoder, and the auto-Featurize
estimator that turns a raw table into a single dense features matrix.

Re-design of the reference's Featurize (ref: core/.../featurize/Featurize.scala:36-238,
FeaturizeUtilities policy constants) and FastVectorAssembler
(ref: core/src/main/scala/org/apache/spark/ml/feature/FastVectorAssembler.scala).

TPU-first: the assembled features column is a 2-D float32 array (not a sparse
VectorUDT) — one contiguous block per batch, which is what the MXU wants.
String columns with small cardinality are one-hot encoded; high-cardinality
strings are murmur-hashed into a bounded slot space; text columns go through
hashing TF.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import ComplexParam, HasOutputCol, Param
from synapseml_tpu.core.pipeline import Estimator, Model, Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.featurize.clean import CleanMissingData
from synapseml_tpu.featurize.indexer import ValueIndexer
from synapseml_tpu.utils.hashing import hash_index


class VectorAssembler(Transformer, HasOutputCol):
    """Concatenates scalar and vector columns into one 2-D float32 matrix."""

    input_cols = Param("columns to assemble", default=None)

    def _transform(self, table: Table) -> Table:
        parts: List[np.ndarray] = []
        for c in self.input_cols or []:
            col = table[c]
            if col.ndim == 1:
                col = col.reshape(-1, 1)
            parts.append(np.asarray(col, dtype=np.float32))
        mat = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        return table.with_column(self.output_col, np.ascontiguousarray(mat))


class OneHotEncoder(Transformer):
    """Index column → one-hot rows. ``size`` must cover the missing slot."""

    input_col = Param("index input column", default="input")
    output_col = Param("one-hot output column", default="output")
    size = Param("number of slots", default=None)
    drop_last = Param("drop the last (missing) slot", default=True)

    def _transform(self, table: Table) -> Table:
        idx = np.asarray(table[self.input_col], dtype=np.int64)
        size = int(self.size)
        width = size - 1 if self.drop_last else size
        mat = np.zeros((len(idx), width), dtype=np.float32)
        valid = (idx >= 0) & (idx < width)
        mat[np.flatnonzero(valid), idx[valid]] = 1.0
        return table.with_column(self.output_col, mat)


class _HashedColumn(Transformer):
    """High-cardinality string column → hashed indicator slots."""

    input_col = Param("string input column", default="input")
    output_col = Param("output column", default="output")
    num_features = Param("hash slots", default=256)

    def _transform(self, table: Table) -> Table:
        d = self.num_features
        mat = np.zeros((table.num_rows, d), dtype=np.float32)
        for i, v in enumerate(table[self.input_col]):
            if v is not None:
                mat[i, hash_index(str(v), d)] = 1.0
        return table.with_column(self.output_col, mat)


class Featurize(Estimator, HasOutputCol):
    """Auto-featurization (ref: Featurize.scala:36): per input column pick a
    policy by dtype —

    - numeric scalar: impute mean, pass through
    - numeric 2-D (vector): pass through
    - bool: cast to float
    - string, cardinality ≤ ``one_hot_encode_categoricals`` threshold: index + one-hot
    - string, high cardinality: murmur-hash indicator slots
    - list-of-tokens (object of lists): hashing TF

    then assemble everything into one dense float32 features column.
    """

    input_cols = Param("columns to featurize (default: all but output)", default=None)
    one_hot_encode_categoricals = Param("one-hot if cardinality below this", default=64)
    num_features = Param("hash slots for high-cardinality/text columns", default=256)
    impute_missing = Param("mean-impute numeric NaNs", default=True)

    def _fit(self, table: Table) -> "FeaturizeModel":
        ins = self.input_cols or [c for c in table.columns if c != self.output_col]
        stages: List = []
        assemble_cols: List[str] = []
        numeric_cols = []
        for c in ins:
            col = table[c]
            if col.ndim == 2 and col.dtype.kind in "biuf":
                assemble_cols.append(c)
            elif col.ndim == 2:
                # uniform-length token rows stack into a 2-D object/str array
                from synapseml_tpu.featurize.text import HashingTF
                stages.append(HashingTF(input_col=c, output_col=f"__f_{c}",
                                        num_features=self.num_features))
                assemble_cols.append(f"__f_{c}")
            elif col.dtype == bool:
                stages.append(_BoolToFloat(input_col=c, output_col=f"__f_{c}"))
                assemble_cols.append(f"__f_{c}")
            elif np.issubdtype(col.dtype, np.number):
                numeric_cols.append(c)
                assemble_cols.append(f"__f_{c}")
            elif col.dtype == object and len(col) and all(
                    isinstance(v, (list, tuple, np.ndarray))
                    for v in col if v is not None) and any(
                    v is not None for v in col):
                from synapseml_tpu.featurize.text import HashingTF
                stages.append(HashingTF(input_col=c, output_col=f"__f_{c}",
                                        num_features=self.num_features))
                assemble_cols.append(f"__f_{c}")
            else:  # string-ish object column
                card = len({v for v in col if v is not None})
                if card <= self.one_hot_encode_categoricals:
                    idx_col, oh_col = f"__i_{c}", f"__f_{c}"
                    indexer = ValueIndexer(input_col=c, output_col=idx_col).fit(table)
                    stages.append(indexer)
                    stages.append(OneHotEncoder(
                        input_col=idx_col, output_col=oh_col,
                        size=len(indexer.levels) + 1, drop_last=False))
                    assemble_cols.append(oh_col)
                else:
                    stages.append(_HashedColumn(
                        input_col=c, output_col=f"__f_{c}",
                        num_features=self.num_features))
                    assemble_cols.append(f"__f_{c}")
        if numeric_cols:
            if self.impute_missing:
                stages.insert(0, CleanMissingData(
                    input_cols=numeric_cols,
                    output_cols=[f"__f_{c}" for c in numeric_cols]).fit(table))
            else:
                stages.insert(0, _Rename(
                    mapping={c: f"__f_{c}" for c in numeric_cols}))
        stages.append(VectorAssembler(
            input_cols=assemble_cols, output_col=self.output_col))
        # every stage above is already fitted — wrap directly, skipping the
        # needless full-table transform a Pipeline.fit would run
        from synapseml_tpu.core.pipeline import PipelineModel
        inner = PipelineModel(stages)
        return FeaturizeModel(inner=inner, output_col=self.output_col)


class _BoolToFloat(Transformer):
    input_col = Param("input", default="input")
    output_col = Param("output", default="output")

    def _transform(self, table: Table) -> Table:
        return table.with_column(
            self.output_col, np.asarray(table[self.input_col], dtype=np.float32))


class _Rename(Transformer):
    mapping = Param("old -> new copies", default=None)

    def _transform(self, table: Table) -> Table:
        return table.with_columns(
            {new: table[old] for old, new in (self.mapping or {}).items()})


class FeaturizeModel(Model, HasOutputCol):
    inner = ComplexParam("fitted internal pipeline")

    def _transform(self, table: Table) -> Table:
        out = self.inner.transform(table)
        scratch = [c for c in out.columns
                   if c.startswith("__f_") or c.startswith("__i_")]
        return out.drop(*scratch)
