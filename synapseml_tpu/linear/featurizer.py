"""VW-style namespace feature hashing.

Re-design of the reference's VowpalWabbitFeaturizer family
(ref: vw/src/main/scala/com/microsoft/ml/spark/vw/featurizer/*.scala — 11
per-type featurizers; murmur-with-namespace-prefix in
VowpalWabbitMurmurWithPrefix.scala) for the TPU data plane:

instead of a JVM sparse vector per row, the featurizer emits two fixed-width
columns — ``<out>_idx`` int32 [N, K] and ``<out>_val`` float32 [N, K] (K =
max nnz, padded with index 0 / value 0) — so a whole batch ships to the
device as two contiguous blocks and the learner consumes them with gathers
(no per-row JVM⇄native marshalling, SURVEY §3.1 HOT LOOP #1).
"""
from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import numpy as np

from synapseml_tpu.core.param import HasOutputCol, Param
from synapseml_tpu.core.pipeline import Transformer
from synapseml_tpu.data.table import Table
from synapseml_tpu.utils.hashing import hash_token


def _hash_feature(name: str, num_bits: int, seed: int) -> int:
    return hash_token(name, seed) & ((1 << num_bits) - 1)


# the reference StringSplitFeaturizer tokenizes with the unicode word
# regex (?U)\w+ — punctuation is stripped, not kept attached to tokens
_WORD_RE = re.compile(r"\w+", re.UNICODE)


class VowpalWabbitFeaturizer(Transformer, HasOutputCol):
    """Hash scalar/string/token columns into (idx, val) pairs.

    Per-type policy (mirrors the reference featurizers):
    - numeric column ``c``: feature name ``c`` with the numeric value
    - string column ``c``: feature name ``c=value`` with value 1.0
    - token-list column ``c``: one feature per token, value 1.0
    - numeric 2-D column ``c``: feature ``c_<j>`` per slot with the value
    """

    input_cols = Param("columns to featurize", default=None)
    string_split_input_cols = Param(
        "string columns split into unicode word tokens (punctuation "
        "stripped) — one feature per BARE token, never column-prefixed "
        "(reference stringSplitInputCols / StringSplitFeaturizer.scala)",
        default=None)
    num_bits = Param("hash space = 2^num_bits", default=18)
    seed = Param("murmur seed (namespace analogue)", default=0)
    sum_collisions = Param("sum colliding values (vs overwrite)", default=True)
    prefix_strings_with_column_name = Param(
        "hash string features as 'col=value' (reference default); False "
        "hashes the bare value, letting equal values in different "
        "columns share weights", default=True)

    def _str_name(self, c: str, tok) -> str:
        if self.prefix_strings_with_column_name:
            return f"{c}={tok}"
        return str(tok)

    def _row_features(self, table: Table, i: int) -> List[Tuple[int, float]]:
        bits, seed = int(self.num_bits), int(self.seed)
        feats: List[Tuple[int, float]] = []
        for c in self.input_cols or []:
            col = table[c]
            v = col[i]
            if col.ndim == 2 and col.dtype != object:
                for j, x in enumerate(np.asarray(v, np.float64)):
                    if x != 0 and not np.isnan(x):  # null slots emit nothing
                        feats.append((_hash_feature(f"{c}_{j}", bits, seed), float(x)))
            elif isinstance(v, (list, tuple, np.ndarray)):
                for tok in v:
                    feats.append((_hash_feature(
                        self._str_name(c, tok), bits, seed), 1.0))
            elif isinstance(v, str):
                feats.append((_hash_feature(
                    self._str_name(c, v), bits, seed), 1.0))
            elif v is not None:
                x = float(v)
                if x != 0 and not np.isnan(x):  # null/NaN emits nothing
                    feats.append((_hash_feature(c, bits, seed), x))
        for c in self.string_split_input_cols or []:
            v = table[c][i]
            if v is None or (isinstance(v, float) and np.isnan(v)):
                continue  # nulls emit nothing, as in the input_cols path
            # reference parity (StringSplitFeaturizer.scala): unicode-word
            # tokenization and the BARE token hashed — the column-name
            # prefix never applies on the string-split path, so equal
            # tokens share a weight slot across columns
            for tok in _WORD_RE.findall(str(v)):
                feats.append((_hash_feature(tok, bits, seed), 1.0))
        return feats

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        rows = [self._row_features(table, i) for i in range(n)]
        if self.sum_collisions:
            rows = [_sum_collisions(r) for r in rows]
        k = max((len(r) for r in rows), default=1) or 1
        idx = np.zeros((n, k), np.int32)
        val = np.zeros((n, k), np.float32)
        for i, r in enumerate(rows):
            for j, (h, x) in enumerate(r):
                idx[i, j] = h
                val[i, j] = x
        out = self.output_col
        return table.with_columns({f"{out}_idx": idx, f"{out}_val": val})


def _sum_collisions(feats: List[Tuple[int, float]]) -> List[Tuple[int, float]]:
    acc = {}
    for h, x in feats:
        acc[h] = acc.get(h, 0.0) + x
    return list(acc.items())


class VowpalWabbitInteractions(Transformer, HasOutputCol):
    """Quadratic interaction features over already-hashed (idx, val) columns
    (ref: vw/.../VowpalWabbitInteractions.scala — VW's -q namespace pairs).

    For each row, every index pair (a from left, b from right) hashes to
    ``murmur-combine(a, b) & mask`` with value ``val_a * val_b``, appended to
    the base features.
    """

    left_col = Param("first hashed column prefix", default=None)
    right_col = Param("second hashed column prefix", default=None)
    num_bits = Param("hash space = 2^num_bits", default=18)

    def _transform(self, table: Table) -> Table:
        mask = (1 << int(self.num_bits)) - 1
        li, lv = table[f"{self.left_col}_idx"], table[f"{self.left_col}_val"]
        ri, rv = table[f"{self.right_col}_idx"], table[f"{self.right_col}_val"]
        n, ka = li.shape
        kb = ri.shape[1]
        # vectorized pair hashing: (a * 0x9E3779B1 + b) & mask, VW-style
        # multiply-combine (ref: hashing in VowpalWabbitMurmurWithPrefix)
        with np.errstate(over="ignore"):
            pair = ((li[:, :, None].astype(np.uint32) * np.uint32(0x9E3779B1))
                    + ri[:, None, :].astype(np.uint32)) & np.uint32(mask)
        pval = lv[:, :, None] * rv[:, None, :]
        pair = pair.reshape(n, ka * kb).astype(np.int32)
        pval = pval.reshape(n, ka * kb).astype(np.float32)
        live = pval != 0
        pair = np.where(live, pair, 0)
        out = self.output_col
        return table.with_columns({
            f"{out}_idx": np.concatenate([li, pair], axis=1),
            f"{out}_val": np.concatenate([lv, pval], axis=1),
        })


class VectorZipper(Transformer, HasOutputCol):
    """Zip several columns into one sequence column
    (ref: vw/.../VectorZipper.scala)."""

    input_cols = Param("columns to zip", default=None)

    def _transform(self, table: Table) -> Table:
        cols = [table[c] for c in self.input_cols or []]
        out = np.empty(table.num_rows, dtype=object)
        for i in range(table.num_rows):
            out[i] = [c[i] for c in cols]
        return table.with_column(self.output_col, out)
