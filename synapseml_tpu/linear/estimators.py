"""VW-equivalent estimators: classifier, regressor, contextual bandit.

Re-design of the reference's learners
(ref: vw/.../VowpalWabbitClassifier.scala, VowpalWabbitRegressor.scala,
VowpalWabbitContextualBandit.scala; base at VowpalWabbitBase.scala:71) on the
jitted sparse learner in :mod:`synapseml_tpu.linear.learner`. Per-partition
perf stats mirror the reference's stats DataFrame
(ref: VowpalWabbitBase.scala:294-328,480-489).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from synapseml_tpu.core.param import (
    ComplexParam,
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    Param,
)
from synapseml_tpu.core.pipeline import Estimator, Model
from synapseml_tpu.data.table import Table
from synapseml_tpu.linear.learner import (
    VWParams,
    VWState,
    init_state,
    predict_batch,
    train,
)

import jax.numpy as jnp


class _VWBaseParams(HasLabelCol, HasWeightCol, HasPredictionCol):
    features_col = Param("hashed features column prefix (expects _idx/_val)",
                         default="features")
    num_bits = Param("hash space = 2^num_bits", default=18)
    learning_rate = Param("initial learning rate", default=0.5)
    power_t = Param("lr decay exponent", default=0.5)
    initial_t = Param("lr schedule offset", default=0.0)
    l1 = Param("L1 regularization", default=0.0)
    l2 = Param("L2 regularization", default=0.0)
    num_passes = Param("passes over the data", default=1)
    optimizer = Param("sgd | adagrad | ftrl", default="adagrad")
    batch_size = Param("minibatch size", default=256)
    seed = Param("shuffle seed", default=0)
    initial_model = ComplexParam("warm-start state (ref: initialModel bytes)",
                                 default=None)
    use_mesh = Param("psum gradients over the dp mesh axis", default=False)

    def _vw_params(self, loss: str) -> VWParams:
        return VWParams(
            num_bits=int(self.num_bits), loss=loss,
            learning_rate=float(self.learning_rate),
            power_t=float(self.power_t), initial_t=float(self.initial_t),
            l1=float(self.l1), l2=float(self.l2),
            num_passes=int(self.num_passes), optimizer=str(self.optimizer),
            batch_size=int(self.batch_size), seed=int(self.seed))

    def _sparse(self, table: Table):
        f = self.features_col
        return (np.asarray(table[f"{f}_idx"], np.int32),
                np.asarray(table[f"{f}_val"], np.float32))

    def _mesh(self):
        if not self.use_mesh:
            return None
        import jax
        from synapseml_tpu.parallel.mesh import build_mesh
        try:
            return build_mesh(want={"dp": len(jax.devices())})
        except Exception:
            return None

    def _train(self, p: VWParams, table: Table, y: np.ndarray):
        idx, val = self._sparse(table)
        weight = (np.asarray(table[self.weight_col], np.float32)
                  if self.weight_col and self.weight_col in table else None)
        t0 = time.time()
        init = self.initial_model
        state, losses = train(p, idx, val, y, weight=weight, initial=init,
                              mesh=self._mesh())
        stats = {
            "rows": len(y),
            "train_s": round(time.time() - t0, 4),
            "passes": p.num_passes,
            "final_loss": losses[-1] if losses else None,
        }
        return state, losses, stats


class VowpalWabbitClassifier(Estimator, _VWBaseParams, HasProbabilityCol,
                             HasRawPredictionCol):
    """Binary classifier, logistic loss (ref: VowpalWabbitClassifier.scala)."""

    loss_function = Param("logistic | hinge", default="logistic")

    def _fit(self, table: Table) -> "VowpalWabbitClassificationModel":
        y_raw = np.asarray(table[self.label_col], np.float64)
        y = np.where(y_raw > 0, 1.0, -1.0).astype(np.float32)  # VW ±1 labels
        p = self._vw_params(str(self.loss_function))
        state, losses, stats = self._train(p, table, y)
        return VowpalWabbitClassificationModel(
            state=state, train_params=p, performance_statistics=stats,
            features_col=self.features_col,
            prediction_col=self.prediction_col,
            probability_col=self.probability_col,
            raw_prediction_col=self.raw_prediction_col)


class _VWModelBase(Model):
    state = ComplexParam("trained VWState")
    train_params = ComplexParam("VWParams used at fit time")
    performance_statistics = ComplexParam("training perf stats", default=None)
    features_col = Param("hashed features column prefix", default="features")

    def _margins(self, table: Table) -> np.ndarray:
        f = self.features_col
        idx = np.asarray(table[f"{f}_idx"], np.int32)
        val = np.asarray(table[f"{f}_val"], np.float32)
        st: VWState = self.state
        return np.asarray(predict_batch(st.w, st.bias, jnp.asarray(idx),
                                        jnp.asarray(val)))

    def get_performance_statistics(self) -> Dict:
        return dict(self.performance_statistics or {})

    # serde: VWState arrays to an npz side file
    def _save_extra(self, path: str):
        import os
        st: VWState = getattr(self, "_stashed_state", None) or self.state
        np.savez_compressed(
            os.path.join(path, "vw_state.npz"),
            w=np.asarray(st.w), g2=np.asarray(st.g2), z=np.asarray(st.z),
            bias=np.asarray(st.bias), t=np.asarray(st.t))

    def _load_extra(self, path: str):
        import os
        d = np.load(os.path.join(path, "vw_state.npz"))
        self.set(state=VWState(
            w=jnp.asarray(d["w"]), g2=jnp.asarray(d["g2"]),
            z=jnp.asarray(d["z"]), bias=jnp.asarray(d["bias"]),
            t=jnp.asarray(d["t"])))

    def save(self, path: str):
        # state is stored via the npz side file, not pickled with params
        st = self._paramMap.pop("state", None)
        self._stashed_state = st
        try:
            super().save(path)
        finally:
            self._stashed_state = None
            if st is not None:
                self._paramMap["state"] = st


class VowpalWabbitClassificationModel(_VWModelBase, HasPredictionCol,
                                      HasProbabilityCol, HasRawPredictionCol):
    def _transform(self, table: Table) -> Table:
        margin = self._margins(table)
        prob = 1.0 / (1.0 + np.exp(-margin))
        return table.with_columns({
            self.raw_prediction_col: np.column_stack([-margin, margin]),
            self.probability_col: np.column_stack([1 - prob, prob]),
            self.prediction_col: (margin > 0).astype(np.float64),
        })


class VowpalWabbitRegressor(Estimator, _VWBaseParams):
    """Squared / quantile loss regressor (ref: VowpalWabbitRegressor.scala)."""

    loss_function = Param("squared | quantile", default="squared")
    quantile_tau = Param("quantile loss tau", default=0.5)

    def _fit(self, table: Table) -> "VowpalWabbitRegressionModel":
        y = np.asarray(table[self.label_col], np.float32)
        p = self._vw_params(str(self.loss_function))
        p = VWParams(**{**p.__dict__, "quantile_tau": float(self.quantile_tau)})
        state, losses, stats = self._train(p, table, y)
        return VowpalWabbitRegressionModel(
            state=state, train_params=p, performance_statistics=stats,
            features_col=self.features_col,
            prediction_col=self.prediction_col)


class VowpalWabbitRegressionModel(_VWModelBase, HasPredictionCol):
    def _transform(self, table: Table) -> Table:
        return table.with_column(
            self.prediction_col, self._margins(table).astype(np.float64))


class VowpalWabbitContextualBandit(Estimator, _VWBaseParams):
    """Contextual bandit with action-dependent features
    (ref: vw/.../VowpalWabbitContextualBandit.scala — CB-ADF).

    Rows carry: ``shared_col`` hashed shared context, ``action_features_col``
    (object column: list of (idx, val) pairs per action — produce it with
    VowpalWabbitFeaturizer + VectorZipper), ``chosen_action_col`` (1-based,
    as in VW), ``cost_col`` (lower better), ``probability_col`` (logging
    policy prob of the chosen action). Trains an IPS-weighted cost regressor
    over shared+action features; predict scores every action.
    """

    shared_col = Param("hashed shared-context column prefix", default="shared")
    action_features_col = Param("per-action hashed features column",
                                default="action_features")
    chosen_action_col = Param("1-based chosen action index column",
                              default="chosenAction")
    cost_col = Param("cost column (lower is better)", default="cost")
    probability_col = Param("logging-policy probability column",
                            default="probability")
    epsilon = Param(
        "epsilon-greedy exploration at prediction: greedy action gets "
        "1-eps+eps/K, others eps/K (reference epsilon / VW "
        "--cb_explore_adf)", default=0.05)

    def _fit(self, table: Table) -> "VowpalWabbitContextualBanditModel":
        p = self._vw_params("squared")
        sh_idx = np.asarray(table[f"{self.shared_col}_idx"], np.int32)
        sh_val = np.asarray(table[f"{self.shared_col}_val"], np.float32)
        actions = table[self.action_features_col]
        chosen = np.asarray(table[self.chosen_action_col], np.int64) - 1
        cost = np.asarray(table[self.cost_col], np.float32)
        prob = np.asarray(table[self.probability_col], np.float32)
        # assemble (shared ++ chosen-action) rows, IPS weight = 1/prob
        rows_idx, rows_val = [], []
        for i in range(table.num_rows):
            a_idx, a_val = actions[i][chosen[i]]
            rows_idx.append(np.concatenate([sh_idx[i], np.asarray(a_idx, np.int32)]))
            rows_val.append(np.concatenate([sh_val[i], np.asarray(a_val, np.float32)]))
        k = max(len(r) for r in rows_idx)
        idx = np.zeros((len(rows_idx), k), np.int32)
        val = np.zeros((len(rows_val), k), np.float32)
        for i, (ri, rv) in enumerate(zip(rows_idx, rows_val)):
            idx[i, :len(ri)] = ri
            val[i, :len(rv)] = rv
        weight = 1.0 / np.clip(prob, 1e-3, None)
        state, losses = train(p, idx, val, cost, weight=weight,
                              initial=self.initial_model, mesh=self._mesh())
        return VowpalWabbitContextualBanditModel(
            state=state, train_params=p,
            performance_statistics={"rows": table.num_rows,
                                    "final_loss": losses[-1] if losses else None},
            shared_col=self.shared_col,
            action_features_col=self.action_features_col,
            prediction_col=self.prediction_col,
            epsilon=self.epsilon)


class VowpalWabbitContextualBanditModel(_VWModelBase, HasPredictionCol):
    shared_col = Param("hashed shared-context column prefix", default="shared")
    action_features_col = Param("per-action hashed features column",
                                default="action_features")
    epsilon = Param("epsilon-greedy exploration pmf parameter",
                    default=0.05)

    def _transform(self, table: Table) -> Table:
        st: VWState = self.state
        w = np.asarray(st.w)
        bias = float(np.asarray(st.bias))
        sh_idx = table[f"{self.shared_col}_idx"]
        sh_val = table[f"{self.shared_col}_val"]
        actions = table[self.action_features_col]
        scores_out = np.empty(table.num_rows, dtype=object)
        pmf_out = np.empty(table.num_rows, dtype=object)
        best = np.zeros(table.num_rows, np.float64)
        eps = float(self.epsilon)
        for i in range(table.num_rows):
            shared_score = float(np.sum(w[np.asarray(sh_idx[i], np.int64)]
                                        * np.asarray(sh_val[i])))
            scores = []
            for a_idx, a_val in actions[i]:
                s = shared_score + bias + float(
                    np.sum(w[np.asarray(a_idx, np.int64)]
                           * np.asarray(a_val, np.float32)))
                scores.append(s)
            scores_out[i] = scores
            greedy = int(np.argmin(scores))
            best[i] = greedy + 1                  # 1-based, min cost
            # epsilon-greedy exploration pmf (VW --cb_explore_adf):
            # greedy action 1-eps+eps/K, every action eps/K
            pmf = np.full(len(scores), eps / len(scores))
            pmf[greedy] += 1.0 - eps
            pmf_out[i] = pmf
        return (table
                .with_column(self.prediction_col, best)
                .with_column("scores", scores_out)
                .with_column("probabilities", pmf_out))
