"""Hashed linear learner core: jitted SGD / AdaGrad / FTRL over sparse
(idx, val) batches.

Re-design of the reference's native VW training path
(ref: vw/.../VowpalWabbitBase.scala:71-489 — per-partition native learners,
spanning-tree AllReduce sync) as a single jax train step:

- the weight table w [2^bits] lives on device; a minibatch is (idx [B,K],
  val [B,K], y [B]) so predictions are gathers + a segment sum and gradients
  are one ``scatter-add`` — the sparse-SGD shape XLA/TPU handles well
- adaptive (AdaGrad) updates mirror VW's default ``--adaptive`` mode with
  ``power_t`` decay; FTRL-proximal covers ``--ftrl``
- distributed: gradients/weights sync with ``psum`` over a dp mesh axis
  (shard_map), replacing VW's host spanning-tree AllReduce
  (ref: VowpalWabbitBase.trainInternalDistributed:434-462)
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class VWParams:
    num_bits: int = 18
    loss: str = "logistic"          # logistic | squared | hinge | quantile
    learning_rate: float = 0.5
    power_t: float = 0.5            # lr decay exponent (VW default)
    initial_t: float = 0.0
    l1: float = 0.0
    l2: float = 0.0
    num_passes: int = 1
    optimizer: str = "adagrad"      # sgd | adagrad | ftrl
    quantile_tau: float = 0.5
    batch_size: int = 256
    seed: int = 0


def _loss_grad(loss: str, tau: float):
    """Returns fn(pred, y, weight) -> (loss, dpred). Labels: logistic/hinge
    use {-1, +1}; squared/quantile use real values."""
    if loss == "logistic":
        def f(p, y, w):
            z = p * y
            l = jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(-z, 0.0)
            g = -y / (1.0 + jnp.exp(z))
            return w * l, w * g
    elif loss == "hinge":
        def f(p, y, w):
            m = 1.0 - p * y
            return w * jnp.maximum(m, 0.0), w * jnp.where(m > 0, -y, 0.0)
    elif loss == "quantile":
        def f(p, y, w):
            e = y - p
            return (w * jnp.where(e >= 0, tau * e, (tau - 1.0) * e),
                    w * jnp.where(e >= 0, -tau, 1.0 - tau))
    else:  # squared
        def f(p, y, w):
            e = p - y
            return w * 0.5 * e * e, w * e
    return f


@dataclasses.dataclass
class VWState:
    """Device-resident training state (pytree)."""
    w: jnp.ndarray          # [2^bits] weights
    g2: jnp.ndarray         # [2^bits] adagrad accumulator / ftrl n
    z: jnp.ndarray          # [2^bits] ftrl z
    bias: jnp.ndarray       # []
    t: jnp.ndarray          # [] example counter


jax.tree_util.register_dataclass(
    VWState, data_fields=["w", "g2", "z", "bias", "t"], meta_fields=[])


def init_state(p: VWParams) -> VWState:
    d = 1 << p.num_bits
    return VWState(
        w=jnp.zeros(d, jnp.float32), g2=jnp.zeros(d, jnp.float32),
        z=jnp.zeros(d, jnp.float32), bias=jnp.zeros((), jnp.float32),
        t=jnp.zeros((), jnp.float32))


def predict_batch(w, bias, idx, val):
    """Margin predictions: sum_k w[idx]*val + bias. idx [B,K], val [B,K]."""
    return jnp.sum(w[idx] * val, axis=1) + bias


@partial(jax.jit, static_argnames=("p", "axis_name"))
def train_step(state: VWState, idx, val, y, weight, p: VWParams,
               axis_name: Optional[str] = None):
    """One minibatch update. With ``axis_name`` set (under shard_map), the
    gradient is psum-averaged across the dp axis — the ICI analogue of VW's
    spanning-tree AllReduce."""
    lf = _loss_grad(p.loss, p.quantile_tau)
    b = idx.shape[0]
    pred = predict_batch(state.w, state.bias, idx, val)
    loss, dpred = lf(pred, y, weight)
    # normalize by total example weight, not batch size: zero-weight padding
    # rows (tail batches) must not dilute the update
    wsum = jnp.maximum(jnp.sum(weight), 1e-9)
    # sparse grad: scatter-add dpred * val into the weight table
    flat_idx = idx.reshape(-1)
    flat_g = (dpred[:, None] * val).reshape(-1)
    grad = jnp.zeros_like(state.w).at[flat_idx].add(flat_g) / wsum
    gbias = jnp.sum(dpred) / wsum
    if p.l2 > 0:
        grad = grad + p.l2 * state.w
    if axis_name is not None:
        grad = jax.lax.pmean(grad, axis_name)
        gbias = jax.lax.pmean(gbias, axis_name)
        loss = jax.lax.pmean(jnp.sum(loss) / wsum, axis_name)
    else:
        loss = jnp.sum(loss) / wsum
    t = state.t + wsum
    if p.optimizer == "ftrl":
        # FTRL-proximal (McMahan et al.): per-coord adaptive z/n updates
        n_new = state.g2 + grad * grad
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(state.g2)) / p.learning_rate
        z_new = state.z + grad - sigma * state.w
        w_new = jnp.where(
            jnp.abs(z_new) <= p.l1,
            0.0,
            -(z_new - jnp.sign(z_new) * p.l1)
            / ((1e-6 + jnp.sqrt(n_new)) / p.learning_rate + p.l2))
        state = VWState(w=w_new, g2=n_new, z=z_new,
                        bias=state.bias - p.learning_rate * gbias, t=t)
    elif p.optimizer == "adagrad":
        # VW --adaptive: per-coordinate decay only, no global (1+t)^power_t
        g2 = state.g2 + grad * grad
        lr = p.learning_rate
        upd = lr * grad / (jnp.sqrt(g2) + 1e-6)
        w = state.w - upd
        if p.l1 > 0:  # truncated-gradient L1 (VW --l1)
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * p.l1, 0.0)
        state = VWState(w=w, g2=g2, z=state.z,
                        bias=state.bias - lr * gbias / jnp.sqrt(1.0 + t / b),
                        t=t)
    else:  # plain sgd
        lr = p.learning_rate / jnp.power(1.0 + p.initial_t + t, p.power_t)
        w = state.w - lr * grad
        if p.l1 > 0:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * p.l1, 0.0)
        state = VWState(w=w, g2=state.g2, z=state.z,
                        bias=state.bias - lr * gbias, t=t)
    return state, loss


def train(p: VWParams, idx: np.ndarray, val: np.ndarray, y: np.ndarray,
          weight: Optional[np.ndarray] = None,
          initial: Optional[VWState] = None,
          mesh=None, axis: str = "dp") -> Tuple[VWState, list]:
    """Multi-pass minibatch training. With ``mesh`` given, each step shards
    the batch over the mesh's dp axis via shard_map and psum-averages
    gradients (one optimizer step per global batch, gang semantics —
    ref: VowpalWabbitBase barrier mode :420-423)."""
    n = len(y)
    if n == 0:
        raise RuntimeError("no optimizer step executed (empty input)")
    w_arr = (np.ones(n, np.float32) if weight is None
             else np.asarray(weight, np.float32))
    state = initial if initial is not None else init_state(p)
    losses = []
    rng = np.random.default_rng(p.seed)
    bs = min(p.batch_size, n)
    step_fn = train_step
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        ndev = mesh.shape[axis]
        bs = max(bs // ndev * ndev, ndev)  # divisible global batch

        def sharded_step(state, bidx, bval, by, bw):
            from synapseml_tpu.parallel.distributed import shard_map
            fn = shard_map(
                lambda s, i2, v2, y2, w2: train_step(s, i2, v2, y2, w2, p, axis),
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis), P(axis), P(axis)),
                out_specs=(P(), P()),
                check_rep=False)
            return fn(state, bidx, bval, by, bw)

        step_fn = lambda s, i2, v2, y2, w2, _p: sharded_step(s, i2, v2, y2, w2)  # noqa: E731
    for _ in range(p.num_passes):
        order = rng.permutation(n)
        for start in range(0, n, bs):
            sl = order[start:start + bs]
            bw = w_arr[sl]
            if len(sl) < bs:
                # VW consumes every example: pad the tail batch to the jit
                # cache's batch shape with zero-weight rows (no-op updates)
                pad = bs - len(sl)
                sl = np.concatenate([sl, np.zeros(pad, sl.dtype)])
                bw = np.concatenate([bw, np.zeros(pad, np.float32)])
            # one batched host->device put per step, not four round trips
            bidx, bval, by, bwd = jax.device_put(
                (idx[sl], val[sl], y[sl], bw))
            if mesh is not None:
                state, loss = step_fn(state, bidx, bval, by, bwd, p)
                loss = jnp.mean(loss)
            else:
                state, loss = train_step(state, bidx, bval, by, bwd, p)
            # keep the scalar on device: float(loss) here would block the
            # dispatch pipeline with one host round trip per step
            losses.append(loss)
    if not losses:
        raise RuntimeError("no optimizer step executed (empty input)")
    return state, [float(l) for l in jax.device_get(losses)]
