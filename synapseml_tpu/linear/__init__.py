from synapseml_tpu.linear.estimators import (
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitContextualBandit,
    VowpalWabbitContextualBanditModel,
    VowpalWabbitRegressionModel,
    VowpalWabbitRegressor,
)
from synapseml_tpu.linear.featurizer import (
    VectorZipper,
    VowpalWabbitFeaturizer,
    VowpalWabbitInteractions,
)
from synapseml_tpu.linear.learner import VWParams, VWState, init_state, train

__all__ = [
    "VWParams", "VWState", "VectorZipper", "VowpalWabbitClassificationModel",
    "VowpalWabbitClassifier", "VowpalWabbitContextualBandit",
    "VowpalWabbitContextualBanditModel", "VowpalWabbitFeaturizer",
    "VowpalWabbitInteractions", "VowpalWabbitRegressionModel",
    "VowpalWabbitRegressor", "init_state", "train",
]
