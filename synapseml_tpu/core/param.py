"""Typed parameter system for pipeline stages.

TPU-native re-design of the reference's SparkML param plumbing:
- ``ComplexParam`` side-file serialization (ref: core/src/main/scala/com/microsoft/ml/spark/core/serialize/ComplexParam.scala:13-34)
- typed param zoo (ref: core/src/main/scala/org/apache/spark/ml/param/*.scala)
- shared column traits (ref: core/.../core/contracts/Params.scala:9-101)

Instead of JVM reflection + codegen, params are plain Python descriptors carrying
name/doc/type/default plus JSON codecs; complex (non-JSON) values are written to
side files next to ``metadata.json`` at save time.
"""
from __future__ import annotations

import json
import pickle
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

import numpy as np

T = TypeVar("T")

_UNSET = object()


class Param(Generic[T]):
    """A typed parameter attached to a :class:`Params` subclass.

    Acts as a descriptor: ``stage.num_leaves`` reads the current value (or
    default), ``stage.set(num_leaves=31)`` / ``stage.num_leaves = 31`` writes it.
    """

    __slots__ = ("name", "doc", "default", "type_check", "is_complex", "owner_cls")

    def __init__(
        self,
        doc: str = "",
        default: Any = _UNSET,
        type_check: Optional[Callable[[Any], bool]] = None,
        is_complex: bool = False,
    ):
        self.doc = doc
        self.default = default
        self.type_check = type_check
        self.is_complex = is_complex
        self.name: str = ""
        self.owner_cls: Optional[type] = None

    def __set_name__(self, owner, name):
        self.name = name
        self.owner_cls = owner

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get(self.name)

    def __set__(self, obj, value):
        obj.set(**{self.name: value})

    def has_default(self) -> bool:
        return self.default is not _UNSET

    def validate(self, value):
        if self.type_check is not None and value is not None:
            if not self.type_check(value):
                raise TypeError(
                    f"Param {self.name!r} got invalid value {value!r}"
                )
        return value

    def __repr__(self):
        return f"Param({self.name!r})"


class ComplexParam(Param):
    """Param holding non-JSON-serializable values (models, arrays, callables).

    Saved to a side file (``params/<name>.pkl`` or ``.npz``) at save time,
    mirroring the reference's ComplexParam side-file scheme
    (ref: core/.../core/serialize/ComplexParam.scala:13-34).
    """

    def __init__(self, doc: str = "", default: Any = _UNSET,
                 type_check: Optional[Callable[[Any], bool]] = None):
        super().__init__(doc, default, type_check, is_complex=True)


def _json_default(o):
    if isinstance(o, np.bool_):  # before np.integer: bool_ is not integer,
        return bool(o)           # but keep the explicit order regardless
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    raise TypeError(f"not JSON serializable: {type(o)}")


class Params:
    """Base class holding a bag of :class:`Param` values.

    Unlike the reference's JVM reflection, param discovery is plain class-dict
    walking; JSON round-trip covers simple params and side files cover complex
    ones (see :mod:`synapseml_tpu.core.serde`).
    """

    def __init__(self, **kwargs):
        self._paramMap: Dict[str, Any] = {}
        if kwargs:
            self.set(**kwargs)

    # -- introspection -------------------------------------------------
    @classmethod
    def params(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[k] = v
        return out

    @classmethod
    def param(cls, name: str) -> Param:
        p = cls.params().get(name)
        if p is None:
            raise KeyError(f"{cls.__name__} has no param {name!r}")
        return p

    # -- get/set -------------------------------------------------------
    def set(self, **kwargs) -> "Params":
        for name, value in kwargs.items():
            p = self.param(name)
            self._paramMap[name] = p.validate(value)
        return self

    def get(self, name: str, default: Any = _UNSET) -> Any:
        p = self.param(name)
        if name in self._paramMap:
            return self._paramMap[name]
        if p.has_default():
            return p.default
        if default is not _UNSET:
            return default
        return None

    def is_set(self, name: str) -> bool:
        return name in self._paramMap

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self.param(name).has_default()

    def clear(self, name: str) -> "Params":
        self._paramMap.pop(name, None)
        return self

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            cur = self._paramMap.get(name, p.default if p.has_default() else "<unset>")
            lines.append(f"{name}: {p.doc} (current: {cur!r})")
        return "\n".join(lines)

    def copy(self, **overrides) -> "Params":
        other = self.__class__.__new__(self.__class__)
        Params.__init__(other)
        other._paramMap = dict(self._paramMap)
        other._post_copy(self)
        if overrides:
            other.set(**overrides)
        return other

    def _post_copy(self, src: "Params"):
        """Hook for subclasses carrying non-param state (e.g. fitted models)."""

    # -- serde ---------------------------------------------------------
    def simple_param_json(self) -> str:
        simple = {
            k: v for k, v in self._paramMap.items()
            if not self.param(k).is_complex
        }
        return json.dumps(simple, default=_json_default, sort_keys=True)

    def complex_param_values(self) -> Dict[str, Any]:
        return {
            k: v for k, v in self._paramMap.items()
            if self.param(k).is_complex
        }

    def load_simple_params(self, payload: str):
        self._paramMap.update(json.loads(payload))

    def save_complex_value(self, path: str, value: Any):
        with open(path, "wb") as f:
            pickle.dump(value, f)

    def load_complex_value(self, path: str) -> Any:
        with open(path, "rb") as f:
            return pickle.load(f)


# ---------------------------------------------------------------------------
# Shared column traits (ref: core/.../core/contracts/Params.scala:9-101)
# ---------------------------------------------------------------------------

class HasInputCol(Params):
    input_col = Param("name of the input column", default="input")


class HasInputCols(Params):
    input_cols = Param("names of the input columns", default=None)


class HasOutputCol(Params):
    output_col = Param("name of the output column", default="output")


class HasOutputCols(Params):
    output_cols = Param("names of the output columns", default=None)


class HasLabelCol(Params):
    label_col = Param("name of the label column", default="label")


class HasFeaturesCol(Params):
    features_col = Param("name of the features column", default="features")


class HasWeightCol(Params):
    weight_col = Param("name of the sample-weight column", default=None)


class HasPredictionCol(Params):
    prediction_col = Param("name of the prediction column", default="prediction")


class HasProbabilityCol(Params):
    probability_col = Param("probability column name", default="probability")


class HasRawPredictionCol(Params):
    raw_prediction_col = Param("raw prediction (margin) column", default="rawPrediction")
