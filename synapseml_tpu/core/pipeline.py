"""Pipeline kernel: Transformer / Estimator / Pipeline with save/load.

Re-design of the reference's SparkML Estimator/Transformer surface so existing
SynapseML-style pipelines translate 1:1, with:
- save/load via ``metadata.json`` + complex-param side files
  (ref: core/src/main/scala/org/apache/spark/ml/Serializer.scala,
  ComplexParamsSerializer.scala)
- telemetry wrapping of fit/transform
  (ref: core/.../logging/BasicLogging.scala:26-75)

Stages operate on :class:`synapseml_tpu.data.table.Table` instead of Spark
DataFrames; heavy numerics inside stages run through jax/XLA.
"""
from __future__ import annotations

import importlib
import json
import logging
import os
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

from synapseml_tpu.core.param import ComplexParam, Param, Params
from synapseml_tpu.data.table import Table

logger = logging.getLogger("synapseml_tpu")

_STAGE_REGISTRY: Dict[str, type] = {}


def _qualified_name(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


class PipelineStage(Params):
    """Base of every pipeline stage. Carries a uid and save/load machinery."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        _STAGE_REGISTRY[_qualified_name(cls)] = cls

    # -- persistence ---------------------------------------------------
    def save(self, path: str):
        os.makedirs(path, exist_ok=True)
        meta = {
            "class": _qualified_name(type(self)),
            "uid": self.uid,
            "timestamp": time.time(),
            "simpleParams": json.loads(self.simple_param_json()),
            "complexParams": list(self.complex_param_values()),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        complex_vals = self.complex_param_values()
        if complex_vals:
            cdir = os.path.join(path, "params")
            os.makedirs(cdir, exist_ok=True)
            for name, value in complex_vals.items():
                self.save_complex_value(os.path.join(cdir, f"{name}.pkl"), value)
        self._save_extra(path)

    def _post_copy(self, src: "Params"):
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"

    def _save_extra(self, path: str):
        """Hook for subclasses with non-param state (fitted artifacts)."""

    def _load_extra(self, path: str):
        pass

    @staticmethod
    def load(path: str) -> "PipelineStage":
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        cls_name = meta["class"]
        cls = _STAGE_REGISTRY.get(cls_name)
        if cls is None:
            module, _, qualname = cls_name.rpartition(".")
            mod = importlib.import_module(module)
            cls = getattr(mod, qualname)
        stage: PipelineStage = cls.__new__(cls)
        Params.__init__(stage)
        stage.uid = meta["uid"]
        stage._paramMap.update(meta["simpleParams"])
        cdir = os.path.join(path, "params")
        for name in meta.get("complexParams", []):
            stage._paramMap[name] = stage.load_complex_value(
                os.path.join(cdir, f"{name}.pkl"))
        stage._load_extra(path)
        return stage

    def _log_call(self, method: str, start: float):
        # JSON telemetry line per public call (ref: BasicLogging.scala:26-75)
        logger.info(json.dumps({
            "uid": self.uid,
            "class": _qualified_name(type(self)),
            "method": method,
            "wall_s": round(time.time() - start, 4),
        }))

    def __repr__(self):
        return f"{type(self).__name__}(uid={self.uid})"


class Transformer(PipelineStage):
    """Stateless (or fitted) table -> table map."""

    def transform(self, table: Table) -> Table:
        start = time.time()
        out = self._transform(table)
        self._log_call("transform", start)
        return out

    def _transform(self, table: Table) -> Table:
        raise NotImplementedError

    def __call__(self, table: Table) -> Table:
        return self.transform(table)


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""


class Estimator(PipelineStage):
    def fit(self, table: Table) -> Model:
        start = time.time()
        model = self._fit(table)
        self._log_call("fit", start)
        return model

    def _fit(self, table: Table) -> Model:
        raise NotImplementedError


class Evaluator(PipelineStage):
    """Scores a transformed table with a single metric."""

    def evaluate(self, table: Table) -> float:
        raise NotImplementedError

    @property
    def is_larger_better(self) -> bool:
        return True


class Pipeline(Estimator):
    """Sequence of stages; estimators are fit in order, transformers pass through."""

    stages = ComplexParam("ordered pipeline stages")

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _fit(self, table: Table) -> "PipelineModel":
        fitted: List[Transformer] = []
        current = table
        for stage in self.stages or []:
            if isinstance(stage, Estimator):
                model = stage.fit(current)
                fitted.append(model)
                current = model.transform(current)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                current = stage.transform(current)
            else:
                raise TypeError(f"not a pipeline stage: {stage!r}")
        return PipelineModel(fitted)

    # persistence: each stage saved in its own subdir (not pickled wholesale)
    def save(self, path: str):
        _save_staged(self, path)

    def _load_extra(self, path: str):
        _load_staged(self, path)


class PipelineModel(Model):
    stages = ComplexParam("fitted pipeline stages")

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self.set(stages=list(stages))

    def _transform(self, table: Table) -> Table:
        current = table
        for stage in self.stages or []:
            current = stage.transform(current)
        return current

    def save(self, path: str):
        _save_staged(self, path)

    def _load_extra(self, path: str):
        _load_staged(self, path)


def _save_staged(stage: PipelineStage, path: str):
    """Save a stage whose 'stages' complex param is a list of substages, each
    persisted in its own subdirectory rather than pickled wholesale."""
    os.makedirs(path, exist_ok=True)
    stages = stage._paramMap.pop("stages", None)
    try:
        PipelineStage.save(stage, path)
    finally:
        if stages is not None:
            stage._paramMap["stages"] = stages
    with open(os.path.join(path, "stages.json"), "w") as f:
        json.dump({"n": len(stages or [])}, f)
    for i, sub in enumerate(stages or []):
        sub.save(os.path.join(path, f"stage_{i:03d}"))


def _load_staged(stage: PipelineStage, path: str):
    sfile = os.path.join(path, "stages.json")
    if os.path.exists(sfile):
        with open(sfile) as f:
            n = json.load(f)["n"]
        stage._paramMap["stages"] = [
            PipelineStage.load(os.path.join(path, f"stage_{i:03d}"))
            for i in range(n)
        ]
