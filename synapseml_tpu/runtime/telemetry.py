"""Runtime telemetry: metrics registry + per-request trace spans.

The reference only ever had StopWatch-based per-component timing (VW
per-partition perf DataFrames, StopWatch.scala); the runtime built in
PRs 1-4 — host-staging pool -> ordered dispatch -> device compute -> D2H
drain -> reply — was a black box on top of that. This module is the
signal layer the SLO-aware serving scheduler (ROADMAP) will act on:

- **Counters / gauges / fixed-bucket histograms** in one process-wide
  registry, *lock-free on the hot path*: every metric stripes its state
  per writer thread (a thread only ever mutates its own cell, claimed
  once via an atomic ``dict.setdefault``), so ``inc()``/``observe()``
  never contend on a lock and never lose updates. Aggregation happens at
  read time (``snapshot()`` / ``prometheus_text()``), off the hot path.
- **Per-request trace spans**: a request id minted at
  ``WorkerServer._enqueue`` rides ``CachedRequest`` through the serving
  stages and — via :func:`set_current_spans` around the scorer's
  ``pipeline_fn`` call — into ``BatchedExecutor``'s pipeline units, so a
  completed request yields a ``queue_wait -> batch_form -> stage ->
  compute -> drain -> reply`` breakdown (:meth:`Span.breakdown`,
  ``GET /span/<rid>`` on the serving port).
- **Three read surfaces**: ``GET /metrics`` Prometheus text exposition
  on every :class:`~synapseml_tpu.io.serving.WorkerServer`,
  :func:`snapshot` dicts (bench.py embeds one per run), and — while a
  ``utils.profiling.trace`` is live — :func:`trace_annotation` regions
  that land the executor's pipeline stages on the TensorBoard timeline.

Round 16 makes a span one **leg of a distributed trace**: W3C
``traceparent`` context (:func:`parse_traceparent` /
:func:`format_traceparent`) threads ``trace_id``/``parent_span_id``
through :class:`Span`, :func:`trace_spans` answers "every leg this
process holds for one trace" (``GET /trace/<trace_id>``, stitched
fleet-wide by the controller's ``/fleet/trace``), histograms carry
last-write-wins OpenMetrics **exemplars** linking latency buckets to
trace ids, and the completed-span ring depth is operator-tunable
(``SYNAPSEML_SPAN_RING``). Tail-based retention lives in
:mod:`~synapseml_tpu.runtime.tracearchive`.

Recording stays cheap enough for the dispatch/drain hot paths (no host
syncs, no locks, a handful of dict/list operations per *batch*, not per
row); ``SYNAPSEML_TELEMETRY=0`` (or :func:`set_enabled`) turns every
record call into a single flag test for A/B overhead runs
(docs/observability.md records the methodology and numbers).
"""
from __future__ import annotations

import bisect
import contextlib
import contextvars
import os
import re
import threading
import time
import uuid
from collections import deque

from synapseml_tpu.runtime.locksan import make_lock
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

__all__ = [
    "Counter", "Gauge", "Histogram", "Span", "counter", "gauge", "gauge_fn",
    "histogram", "series", "unregister", "snapshot", "prometheus_text",
    "reset",
    "enabled", "set_enabled", "start_span", "get_span", "completed_spans",
    "trace_spans", "configure_span_ring", "span_ring_depth",
    "parse_traceparent", "format_traceparent", "mint_trace_id",
    "mint_span_id",
    "set_current_spans", "reset_current_spans", "current_spans",
    "trace_annotation", "LATENCY_BUCKETS", "SIZE_BUCKETS",
    "DEFAULT_SPAN_RING",
]

# log-spaced latency ladder, 100us .. 30s — covers the sub-ms serving
# roundtrip floor and a cold multi-second XLA compile in one histogram
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# pow2 ladder for batch/bucket size distributions
SIZE_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "synapseml_"


class _State:
    """Module switchboard. A single attribute read gates every hot-path
    record call; the env knob is captured once at import and
    :func:`set_enabled` flips it for A/B runs and tests."""

    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = os.environ.get("SYNAPSEML_TELEMETRY", "") != "0"


_STATE = _State()


def enabled() -> bool:
    return _STATE.enabled


def set_enabled(on: bool) -> bool:
    """Flip recording globally; returns the previous value."""
    prev = _STATE.enabled
    _STATE.enabled = bool(on)
    return prev


def _qualify(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    return name if name.startswith(_PREFIX) else _PREFIX + name


class _Cell:
    """One writer thread's private slice of a metric. Only the owning
    thread ever writes it (claimed via ``dict.setdefault``), so the
    read-modify-write increments need no lock and lose nothing; readers
    may observe a value mid-update, which only makes a snapshot a few
    nanoseconds stale — never wrong."""

    __slots__ = ("n", "total", "count", "counts")

    def __init__(self, n_buckets: int = 0):
        self.n = 0.0
        self.total = 0.0
        self.count = 0
        self.counts = [0] * n_buckets if n_buckets else None


class _Metric:
    """Base: per-thread striped cells."""

    kind = "untyped"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.labels = labels
        self._cells: Dict[int, _Cell] = {}

    def _cell(self, n_buckets: int = 0) -> _Cell:
        tid = threading.get_ident()
        cell = self._cells.get(tid)
        if cell is None:
            # setdefault is atomic under the GIL: exactly one cell per
            # thread id ever wins, and the loser (there is none in
            # practice — a thread races only itself here) is dropped
            cell = self._cells.setdefault(tid, _Cell(n_buckets))
        return cell


class Counter(_Metric):
    """Monotonic counter. ``inc`` is the hot-path call: one dict get,
    one float add on a thread-private cell."""

    kind = "counter"

    def inc(self, n: float = 1.0):
        if not _STATE.enabled:
            return
        self._cell().n += n

    @property
    def value(self) -> float:
        return sum(c.n for c in list(self._cells.values()))


class Gauge(_Metric):
    """Last-write-wins gauge (``set``) with optional striped ``add`` for
    up/down tracking; a callable gauge (see :func:`gauge_fn`) is sampled
    at read time instead."""

    kind = "gauge"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 fn: Optional[Callable[[], float]] = None):
        super().__init__(name, labels)
        self._set_value: Optional[float] = None
        self._fn = fn

    def set(self, v: float):
        if not _STATE.enabled:
            return
        self._set_value = float(v)  # ref assignment: atomic

    def add(self, n: float = 1.0):
        if not _STATE.enabled:
            return
        self._cell().n += n

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 - a dead sampler reads as 0
                return 0.0
        base = self._set_value if self._set_value is not None else 0.0
        return base + sum(c.n for c in list(self._cells.values()))


class Histogram(_Metric):
    """Fixed-bucket histogram with p50/p95/p99 readout.

    ``observe`` is hot-path: a bisect over ~17 bounds plus three
    thread-private writes. Percentiles are estimated at read time by
    linear interpolation inside the covering bucket (the usual
    Prometheus ``histogram_quantile`` math, done host-side)."""

    kind = "histogram"

    def __init__(self, name: str, labels: Tuple[Tuple[str, str], ...],
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        super().__init__(name, labels)
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in buckets)
        # per-bucket OpenMetrics exemplars, last-write-wins: each slot
        # holds one (trace_id, value, wall_ts) tuple. A single list-item
        # assignment per stamped observe — atomic under the GIL, no
        # lock, and losing a race just means the OTHER request's trace
        # becomes the bucket's exemplar (the sampling policy IS
        # last-write-wins, docs/observability.md "Distributed tracing")
        self._exemplars: List[Optional[Tuple[str, float, float]]] = \
            [None] * (len(self.bounds) + 1)

    def observe(self, v: float, exemplar: Optional[str] = None):
        """``exemplar``: a trace id to stamp on the covering bucket —
        surfaced on the OpenMetrics exposition so a dashboard's latency
        bucket links straight to the trace that landed in it."""
        if not _STATE.enabled:
            return
        idx = bisect.bisect_left(self.bounds, v)
        cell = self._cell(len(self.bounds) + 1)
        cell.counts[idx] += 1
        cell.total += v
        cell.count += 1
        if exemplar:
            self._exemplars[idx] = (exemplar, v, time.time())

    def _aggregate(self) -> Tuple[List[int], float, int]:
        counts = [0] * (len(self.bounds) + 1)
        total = 0.0
        n = 0
        for cell in list(self._cells.values()):
            if cell.counts is None:
                continue
            for i, c in enumerate(cell.counts):
                counts[i] += c
            total += cell.total
            n += cell.count
        return counts, total, n

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) from the bucket counts."""
        counts, _total, n = self._aggregate()
        if n == 0:
            return 0.0
        rank = q * n
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i] if i < len(self.bounds) \
                    else self.bounds[-1]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.bounds[-1]

    def summary(self) -> Dict[str, float]:
        counts, total, n = self._aggregate()
        out = {"count": n, "sum": round(total, 6)}
        if n:
            for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                out[key] = round(self.percentile(q), 6)
        return out

    @property
    def count(self) -> int:
        return self._aggregate()[2]


# -- registry ---------------------------------------------------------------

_REG_LOCK = make_lock("telemetry:_REG_LOCK")
_METRICS: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], _Metric] = {}


def _labels_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _get_or_make(cls, name: str, labels: Dict[str, Any], **kw) -> Any:
    name = _qualify(name)
    key = (name, _labels_key(labels))
    # synlint: disable=DS001 - _REG_LOCK is a leaf: metric get-or-create
    # may nest under any caller lock and acquires nothing further
    with _REG_LOCK:
        m = _METRICS.get(key)
        if m is None or not isinstance(m, cls):
            m = cls(name, key[1], **kw)
            _METRICS[key] = m
        return m


def counter(name: str, **labels: Any) -> Counter:
    """Get-or-create a counter; memoized per (name, labels). Resolve the
    handle once (module/instance init), then ``inc()`` on the hot path."""
    return _get_or_make(Counter, name, labels)


def gauge(name: str, **labels: Any) -> Gauge:
    return _get_or_make(Gauge, name, labels)


def gauge_fn(name: str, fn: Callable[[], float], **labels: Any) -> Gauge:
    """Callable gauge, sampled at scrape/snapshot time (queue depths
    etc. — nothing on the hot path). Re-registering the same series
    replaces the sampler, so a restarted server takes over its gauge."""
    name = _qualify(name)
    key = (name, _labels_key(labels))
    with _REG_LOCK:
        g = Gauge(name, key[1], fn=fn)
        _METRICS[key] = g
        return g


def histogram(name: str, buckets: Sequence[float] = LATENCY_BUCKETS,
              **labels: Any) -> Histogram:
    return _get_or_make(Histogram, name, labels, buckets=buckets)


def series(name: str) -> List[Tuple[Dict[str, str], _Metric]]:
    """Every registered label set of one family:
    ``[({label: value}, metric), ...]``. The read-side lookup derived
    views use (e.g. the duty-cycle attribution in
    ``runtime/perfwatch.py`` walks ``executor_dispatch_total``) —
    registry-lock cost, never on a hot path."""
    name = _qualify(name)
    with _REG_LOCK:
        return [(dict(k[1]), m) for k, m in _METRICS.items()
                if k[0] == name]


def unregister(name: str, **labels: Any) -> bool:
    """Drop one series (stopped servers unhook their queue-depth
    samplers here so a scrape never calls into a dead object)."""
    key = (_qualify(name), _labels_key(labels))
    with _REG_LOCK:
        return _METRICS.pop(key, None) is not None


def reset():
    """Tests only: zero every metric and drop every span. Registrations
    (and module-level metric handles cached by instrumented code) stay
    valid — cells are cleared, so the next write starts from zero. A
    writer mid-increment on another thread may land one count in an
    orphaned cell; tests that assert exact values quiesce their threads
    first."""
    with _REG_LOCK:
        for m in _METRICS.values():
            m._cells.clear()
            if isinstance(m, Gauge):
                m._set_value = None
            elif isinstance(m, Histogram):
                m._exemplars = [None] * (len(m.bounds) + 1)
    with _SPAN_LOCK:
        _ACTIVE_SPANS.clear()
        _DONE_SPANS.clear()


# -- trace context (W3C traceparent) ----------------------------------------

# grammar per https://www.w3.org/TR/trace-context/:
#   version "-" trace-id "-" parent-id "-" trace-flags
# (2 / 32 / 16 / 2 lowercase hex). Version ff and all-zero ids are
# invalid; a well-formed header with an unknown version is still
# usable, INCLUDING trailing "-suffixed" data a future version may
# append (the spec's forward-compat rule: parse the first four
# fields, ignore the rest — but only for versions above 00, whose
# grammar is exactly four fields). One fullmatch on the request
# path — no lock, no allocation beyond the match object.
_TRACEPARENT_RE = re.compile(
    r"([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})(-.*)?")


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent``
    header, or None when absent/malformed (the caller mints a fresh
    context then — a bad header must never reject a request)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.fullmatch(header.strip())
    if m is None:
        return None
    version, trace_id, parent_id, _flags, tail = m.groups()
    if version == "ff":
        return None  # forbidden by the spec
    if version == "00" and tail is not None:
        return None  # version 00 is EXACTLY four fields
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None  # all-zero ids are explicitly invalid
    return trace_id, parent_id


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    """Version-00 traceparent naming OUR span as the parent — what
    every reply path echoes so the caller's next hop (or its logs)
    continues the same trace."""
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def mint_trace_id() -> str:
    return uuid.uuid4().hex  # 32 hex, never all-zero


def mint_span_id() -> str:
    return uuid.uuid4().hex[:16]


# -- trace spans ------------------------------------------------------------

_SPAN_LOCK = make_lock("telemetry:_SPAN_LOCK")
_ACTIVE_SPANS: Dict[str, "Span"] = {}
_MAX_ACTIVE = 4096

DEFAULT_SPAN_RING = 1024


def _ring_depth_from_env() -> int:
    """``SYNAPSEML_SPAN_RING`` (0/unset = default 1024), validated at
    first use: a malformed or non-positive value degrades to the
    default — a bad env var must never crash a server at import."""
    raw = os.environ.get("SYNAPSEML_SPAN_RING", "").strip()
    if not raw:
        return DEFAULT_SPAN_RING
    try:
        depth = int(raw)
    except ValueError:
        return DEFAULT_SPAN_RING
    return depth if depth > 0 else DEFAULT_SPAN_RING


_DONE_SPANS: "deque[Span]" = deque(maxlen=_ring_depth_from_env())


def span_ring_depth() -> int:
    """Current completed-span ring capacity."""
    with _SPAN_LOCK:
        return _DONE_SPANS.maxlen or DEFAULT_SPAN_RING


def configure_span_ring(depth: Optional[int] = None) -> int:
    """Resize the completed-span ring, keeping the newest spans.
    ``None`` re-reads ``SYNAPSEML_SPAN_RING``; an explicit non-positive
    or non-int ``depth`` raises (the env path degrades instead).
    Returns the new capacity."""
    global _DONE_SPANS
    if depth is None:
        depth = _ring_depth_from_env()
    else:
        depth = int(depth)
        if depth <= 0:
            raise ValueError(f"span ring depth must be positive, "
                             f"got {depth}")
    with _SPAN_LOCK:
        _DONE_SPANS = deque(_DONE_SPANS, maxlen=depth)
    return depth

_STAGE_ORDER = ("queue_wait", "batch_form", "stage", "compute", "drain",
                "reply")


class Span:
    """One request's stage breakdown through the serving + executor
    pipeline. ``note`` appends to a thread-safe-enough list (appends are
    atomic under the GIL and each stage notes once); ``finish`` moves
    the span to the completed ring and feeds the per-stage histograms.

    Round 16: a span is one LEG of a distributed trace — ``trace_id``
    (shared across every process the request touched, accepted from or
    minted for the W3C ``traceparent`` header), ``span_id`` (this
    leg), ``parent_span_id`` (the caller's leg, "" at the trace root)
    and ``origin`` (which server created it) are what
    ``GET /fleet/trace/<trace_id>`` stitches legs together on."""

    __slots__ = ("rid", "start", "wall", "events", "status", "finished",
                 "trace_id", "span_id", "parent_span_id", "origin",
                 "output_digest")

    def __init__(self, rid: str, trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 origin: str = ""):
        self.rid = rid
        self.start = time.monotonic()
        self.wall = time.time()  # orders legs across processes
        self.events: List[Tuple[str, float]] = []
        self.status = "active"
        self.finished = 0.0
        self.trace_id = trace_id or mint_trace_id()
        self.span_id = span_id or mint_span_id()
        self.parent_span_id = parent_span_id or ""
        self.origin = origin
        # sha256 of the reply bytes, stamped by the serving reply path
        # (the X-Output-Digest header's value): /span/<rid> and the
        # trace archive then carry the determinism fingerprint replay
        # diffs against, without storing the output itself
        self.output_digest = ""

    def note(self, stage: str, seconds: float):
        # finished spans drop late notes: a request replayed through
        # recover() after its first reply would otherwise double its
        # stage breakdown (and disagree with the histograms, which are
        # fed once at finish)
        if not _STATE.enabled or self.status != "active":
            return
        self.events.append((stage, seconds))

    def finish(self, status: str = "ok"):
        # first-finisher-wins under the span lock: the reply thread and
        # a shutdown-path _fail_batch can race the same span
        with _SPAN_LOCK:
            if self.status != "active":
                return
            self.status = status
            self.finished = time.monotonic()
            _ACTIVE_SPANS.pop(self.rid, None)
            _DONE_SPANS.append(self)
        for stage, secs in self.breakdown()["stages"].items():
            _span_stage_hist(stage).observe(secs)

    def breakdown(self) -> Dict[str, Any]:
        stages: Dict[str, float] = {}
        for stage, secs in list(self.events):
            stages[stage] = stages.get(stage, 0.0) + secs
        ordered = {s: round(stages[s], 6) for s in _STAGE_ORDER
                   if s in stages}
        for s in sorted(stages):
            ordered.setdefault(s, round(stages[s], 6))
        end = self.finished if self.finished else time.monotonic()
        out = {"rid": self.rid, "status": self.status,
               "trace_id": self.trace_id, "span_id": self.span_id,
               "parent_span_id": self.parent_span_id,
               "origin": self.origin, "ts": round(self.wall, 6),
               "total_seconds": round(end - self.start, 6),
               "stages": ordered}
        if self.output_digest:
            out["output_digest"] = self.output_digest
        return out


class _NoopSpan(Span):
    """Returned when telemetry is disabled: every call is a no-op."""

    def __init__(self):  # noqa: D107 - trivially empty
        self.rid = ""
        self.start = 0.0
        self.wall = 0.0
        self.events = []
        self.status = "disabled"
        self.finished = 0.0
        self.trace_id = ""
        self.span_id = ""
        self.parent_span_id = ""
        self.origin = ""
        self.output_digest = ""

    def note(self, stage: str, seconds: float):
        pass

    def finish(self, status: str = "ok"):
        pass


_NOOP_SPAN = _NoopSpan()

_STAGE_HISTS: Dict[str, Histogram] = {}


def _span_stage_hist(stage: str) -> Histogram:
    h = _STAGE_HISTS.get(stage)
    if h is None or (h.name, h.labels) not in _METRICS:
        h = histogram("request_stage_seconds", stage=stage)
        _STAGE_HISTS[stage] = h
    return h


def start_span(rid: str, trace_id: Optional[str] = None,
               parent_span_id: Optional[str] = None,
               span_id: Optional[str] = None,
               origin: str = "") -> Span:
    """Mint a span for one request id (the serving enqueue path).
    ``trace_id``/``parent_span_id`` thread an accepted W3C traceparent
    through (both minted when absent); ``origin`` names the server so
    a stitched trace tells its legs apart."""
    if not _STATE.enabled:
        return _NOOP_SPAN
    span = Span(rid, trace_id=trace_id, parent_span_id=parent_span_id,
                span_id=span_id, origin=origin)
    with _SPAN_LOCK:
        _ACTIVE_SPANS[rid] = span
        while len(_ACTIVE_SPANS) > _MAX_ACTIVE:
            # insertion-ordered dict: evict the oldest straggler (a
            # request that never reached a reply path) instead of
            # growing without bound
            _ACTIVE_SPANS.pop(next(iter(_ACTIVE_SPANS)))
    return span


def get_span(rid: str) -> Optional[Span]:
    """Look a span up by request id — active first, then the completed
    ring (newest wins)."""
    with _SPAN_LOCK:
        span = _ACTIVE_SPANS.get(rid)
        if span is not None:
            return span
        for span in reversed(_DONE_SPANS):
            if span.rid == rid:
                return span
    return None


def completed_spans(limit: int = 64) -> List[Dict[str, Any]]:
    with _SPAN_LOCK:
        spans = list(_DONE_SPANS)[-limit:]
    return [s.breakdown() for s in spans]


def trace_spans(trace_id: str, limit: int = 64) -> List[Dict[str, Any]]:
    """Every span this PROCESS holds for one trace id — active and
    completed, oldest first. The per-replica half of distributed-trace
    stitching (``GET /trace/<trace_id>`` on the serving port; the
    fleet controller merges these across replicas). The lock hold is a
    bare snapshot copy — the O(ring) filter runs OUTSIDE it, so a
    polled trace surface over an operator-deepened ring
    (``SYNAPSEML_SPAN_RING``) never stalls ``start_span``/``finish``
    on the request path."""
    with _SPAN_LOCK:
        done = list(_DONE_SPANS)
        active = list(_ACTIVE_SPANS.values())
    spans = [s for s in done if s.trace_id == trace_id]
    spans += [s for s in active if s.trace_id == trace_id]
    spans.sort(key=lambda s: s.wall)
    return [s.breakdown() for s in spans[:limit]]


# ambient span context: the serving scorer sets the micro-batch's spans
# around its pipeline_fn call; BatchedExecutor.submit (same thread)
# captures them into the pipeline units so the stage/dispatch/drain
# threads can annotate per-request breakdowns without any API change
_CURRENT_SPANS: "contextvars.ContextVar[Optional[Tuple[Span, ...]]]" = \
    contextvars.ContextVar("synapseml_current_spans", default=None)


def set_current_spans(spans: Iterable[Span]):
    """Returns a token for :func:`reset_current_spans`."""
    return _CURRENT_SPANS.set(tuple(spans))


def reset_current_spans(token):
    _CURRENT_SPANS.reset(token)


def current_spans() -> Optional[Tuple[Span, ...]]:
    if not _STATE.enabled:
        return None
    return _CURRENT_SPANS.get()


# -- TensorBoard timeline bridge -------------------------------------------

# one shared nullcontext: contextlib.nullcontext is stateless and
# reusable, and the no-trace fast path runs per pipeline batch — a
# fresh (generator-based) context manager per call measured ~2.2us vs
# ~0.2us for returning this singleton
_NULL_CTX = contextlib.nullcontext()

_PROFILING = None  # lazily-cached utils.profiling module (import cycle)


def trace_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` region WHEN a
    ``utils.profiling.trace`` is live (and telemetry + tracing are
    enabled); a no-op context otherwise. The executor wraps its pipeline
    stages in this, which is what lands span stages on the TensorBoard
    timeline next to the XLA ops — retroactive injection into a profile
    is impossible, so the bridge annotates live instead."""
    global _PROFILING
    if not _STATE.enabled:
        return _NULL_CTX
    profiling = _PROFILING
    if profiling is None:
        from synapseml_tpu.utils import profiling  # deferred: no cycle
        _PROFILING = profiling
    if not profiling.trace_active():
        return _NULL_CTX
    try:
        return profiling.annotate(name)
    except Exception:  # noqa: BLE001 - profiling must never break the job
        return _NULL_CTX


# -- read surfaces ----------------------------------------------------------

def _sorted_metrics() -> List[_Metric]:
    with _REG_LOCK:
        return [m for _k, m in sorted(_METRICS.items())]


def snapshot(compact: bool = False) -> Dict[str, Any]:
    """One dict of every series: counters/gauges as numbers, histograms
    as ``{count, sum, p50, p95, p99}`` summaries (plus raw bucket counts
    unless ``compact``). bench.py embeds ``snapshot(compact=True)`` in
    its JSON detail so each round's queue/latency series are diffable."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for m in _sorted_metrics():
        key = m.name + _labels_text(m.labels)
        if isinstance(m, Histogram):
            s = m.summary()
            if not compact:
                counts, _total, _n = m._aggregate()
                s["buckets"] = {
                    (str(b) if i < len(m.bounds) else "+Inf"): c
                    for i, (b, c) in enumerate(
                        zip(list(m.bounds) + [float("inf")], counts))}
            hists[key] = s
        elif isinstance(m, Counter):
            counters[key] = round(m.value, 6)
        else:
            gauges[key] = round(m.value, 6)
    with _SPAN_LOCK:
        n_done = len(_DONE_SPANS)
        n_active = len(_ACTIVE_SPANS)
    return {"counters": counters, "gauges": gauges, "histograms": hists,
            "spans": {"active": n_active, "completed_ring": n_done}}


def _labels_text(labels: Tuple[Tuple[str, str], ...],
                 extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    items = labels + extra
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in items)
    return "{%s}" % body


def prometheus_text(openmetrics: bool = False) -> str:
    """Prometheus text exposition (format 0.0.4): counters and gauges as
    single samples, histograms as cumulative ``_bucket{le=}`` series
    plus ``_sum``/``_count`` — what ``GET /metrics`` serves.

    ``openmetrics=True`` emits the OpenMetrics-flavored variant the
    serving port negotiates on ``Accept: application/openmetrics-text``
    (or ``SYNAPSEML_OPENMETRICS=1``): identical samples, plus
    ``# {trace_id="..."} value timestamp`` **exemplars** on histogram
    bucket lines that have one, and the terminating ``# EOF``. Honesty
    caveat: series names keep their registered ``_total`` suffixes
    rather than the OpenMetrics family/suffix split — tolerant parsers
    (Prometheus's openmetrics scrape mode included) accept it; the
    default exposition is unchanged, so format-0.0.4 consumers never
    see an exemplar."""
    seen_types: Dict[str, str] = {}
    lines: List[str] = []
    for m in _sorted_metrics():
        if seen_types.get(m.name) != m.kind:
            seen_types[m.name] = m.kind
            lines.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            counts, total, n = m._aggregate()
            exemplars = list(m._exemplars) if openmetrics else None
            cum = 0
            for i, (b, c) in enumerate(
                    zip(list(m.bounds) + [float("inf")], counts)):
                cum += c
                le = "+Inf" if b == float("inf") else repr(b)
                line = "%s_bucket%s %d" % (
                    m.name, _labels_text(m.labels, (("le", le),)), cum)
                ex = exemplars[i] if exemplars else None
                if ex is not None:
                    tid, v, ts = ex
                    line += ' # {trace_id="%s"} %.9g %.3f' % (tid, v, ts)
                lines.append(line)
            lines.append("%s_sum%s %.9g" % (
                m.name, _labels_text(m.labels), total))
            lines.append("%s_count%s %d" % (
                m.name, _labels_text(m.labels), n))
        else:
            v = m.value
            text = "%d" % v if float(v).is_integer() else "%.9g" % v
            lines.append("%s%s %s" % (m.name, _labels_text(m.labels), text))
    if openmetrics:
        lines.append("# EOF")
    return "\n".join(lines) + "\n"
