"""Roofline-driven autotuner: ONE prober for every measured lane.

PR 15 proved the verify-then-time routing pattern twice over — the
fused predict traversal (gbdt/predict_route.py) and the true-int8 lane
(onnx/quant_route.py) each carried their own copy of the same loop:
kill switch -> cached verdict -> compile all formulations -> verify
bit-tolerantly against the production reference -> min-of-N timing ->
persist the winner -> silently fall back on mismatch, regression, or
crash. This module is that loop, once, as a registry any op can join:

    lane = register_lane(
        "my_op",
        key_fn=...,        # *route_args -> versioned shape-class key
        candidates={...},  # choice -> make(rargs, args) -> callable
        verify_fn=...,     # (got, want) -> bool, reference-relative
        reference="...",   # the production formulation (always safe)
        args_fn=...,       # *route_args -> concrete probe inputs
    )
    choice = lane.route(*route_args)

The first route of a new shape class probes (compiles every candidate,
verifies each against the reference output, times the survivors with
``proberoute.best_of`` — ``block_until_ready`` forcing, no D2H in the
timed region) and persists the verdict through :class:`RouteTable`,
so the fleet shares it via the cache volume exactly like the PR-15
lanes (the neg-TTL surfaces sibling verdicts without a restart).

Failure contract (the silent-fallback half):

- candidate BUILD crash        -> reference, memoized in-process ONLY
  (a transient compile failure must not be remembered fleet-wide);
- candidate verify mismatch or
  run failure                  -> candidate disqualified; if none
  survive, the reference verdict IS persisted (a deterministic
  mismatch should not re-pay the probe after restart);
- timing regression            -> reference persisted (same reason);
- anything else in routing     -> reference served, never raised.

``SYNAPSEML_AUTOTUNE=0`` kills every lane at once: the reference
serves with zero probes and zero table I/O.

Legacy adapter: the two PR-15 routers keep their module-level
``_probe*`` functions as monkeypatchable seams (their test suites stub
them), so a lane may pass ``probe_hook`` — a whole-probe callable
returning the verdict string — instead of the decomposed
candidates/args_fn/verify_fn form. Either way the routing loop,
crash-memo semantics, persistence, and telemetry live HERE only.

Telemetry: ``autotune_route_total{lane=,choice=}`` counts every routed
decision; ``autotune_probe_seconds{lane=}`` observes full probe cost
(compile + verify + timing), the number the amortization math in
docs/perf.md divides by.
"""
from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

from synapseml_tpu.runtime.proberoute import RouteTable, best_of

_LANES: Dict[str, "Lane"] = {}


def enabled() -> bool:
    """Global kill switch — ``SYNAPSEML_AUTOTUNE=0`` serves every
    lane's reference with zero probes."""
    return os.environ.get("SYNAPSEML_AUTOTUNE", "1") != "0"


def key_prefix(tag: str) -> str:
    """Versioned key prefix for NEW lanes (the PR-15 lanes keep their
    ``pv1|``/``q1|`` schemas so fleet verdicts stay valid): a jax,
    package, or device change must re-probe, not remember."""
    import jax
    import synapseml_tpu as _pkg

    kind = jax.devices()[0].device_kind
    pkg_v = getattr(_pkg, "__version__", "0")
    return f"at1|jax{jax.__version__}|pkg{pkg_v}|{kind}|{tag}"


def pow2(v: int, lo: int = 1, hi: int = 65536) -> int:
    """Shared shape-bucketing helper: next power of two, clamped."""
    return 1 << (int(min(max(v, lo), hi)) - 1).bit_length()


def aot(fn, *args):
    """Concrete inputs in, compiled executable out — escapes any
    ambient trace (the pallas_kernels.available pattern)."""
    import jax

    return jax.jit(fn).lower(*args).compile()


def _fetch(out):
    """Value-fetch ONE leg's output for the verify comparison — the
    only place a probe is allowed to pay D2H."""
    import numpy as np

    if isinstance(out, (tuple, list)):
        return tuple(np.asarray(o) for o in out)
    return np.asarray(out)


def _count(lane: str, choice: str) -> None:
    try:
        from synapseml_tpu.runtime import telemetry

        telemetry.counter("autotune_route_total",
                          lane=lane, choice=choice).inc()
    except Exception:  # noqa: BLE001 - telemetry must never gate serving
        pass


def _observe_probe(lane: str, seconds: float) -> None:
    try:
        from synapseml_tpu.runtime import telemetry

        telemetry.histogram("autotune_probe_seconds",
                            lane=lane).observe(seconds)
    except Exception:  # noqa: BLE001
        pass


class Lane:
    """One registered op with N formulations. Instances come from
    :func:`register_lane`; callers use :meth:`route` (may probe),
    :meth:`cached` (lookup-only, trace-safe), :meth:`poison`
    (persist a demotion after a runtime failure of the routed leg)."""

    def __init__(self, name: str, key_fn: Callable[..., str],
                 candidates: Dict[str, Optional[Callable]],
                 verify_fn: Optional[Callable[[Any, Any], bool]],
                 reference: str,
                 args_fn: Optional[Callable[..., Tuple]] = None,
                 probe_hook: Optional[Callable[..., str]] = None,
                 time_fn: Optional[Callable] = None,
                 table: Optional[RouteTable] = None,
                 groups: Iterable[str] = (), reps: int = 2):
        if reference not in candidates:
            raise ValueError(
                f"lane {name!r}: reference {reference!r} not a candidate")
        if probe_hook is None and args_fn is None:
            raise ValueError(
                f"lane {name!r}: needs args_fn (or a probe_hook)")
        self.name = name
        self.key_fn = key_fn
        self.candidates = dict(candidates)
        self.verify_fn = verify_fn
        self.reference = reference
        self.args_fn = args_fn
        self.probe_hook = probe_hook
        self.time_fn = time_fn or (
            lambda fn, args, reps: best_of(fn, args, reps))
        self.table = table or RouteTable(f"autotune_{name}.json")
        self.groups = tuple(groups)
        self.reps = reps
        self.probes = 0  # probes RUN by this process, this lane
        self.decisions: Dict[str, str] = {}  # key -> served choice

    # -- routing ----------------------------------------------------

    def route(self, *rargs) -> str:
        """Cached verdict, else probe-and-persist. Never raises; the
        reference serves on any routing failure."""
        if not enabled():
            _count(self.name, self.reference)
            return self.reference
        try:
            key = self.key_fn(*rargs)
            got = self.table.lookup(key)
            if got is None:
                got, persist = self._probe_guarded(rargs)
                self.table.record(key, got, persist=persist)
            choice = got if got in self.candidates else self.reference
            self.decisions[key] = choice
        except Exception:  # noqa: BLE001 - routing never fails the op
            choice = self.reference
        _count(self.name, choice)
        return choice

    def cached(self, *rargs) -> Optional[str]:
        """Lookup-only (trace-safe): the persisted choice, or None
        when nothing is measured yet. Never probes, never counts."""
        if not enabled():
            return None
        try:
            key = self.key_fn(*rargs)
            got = self.table.lookup(key)
        except Exception:  # noqa: BLE001
            return None
        if got is None or got not in self.candidates:
            return None
        self.decisions[key] = got
        return got

    def poison(self, *rargs) -> None:
        """Persist a demotion to the reference after the routed leg
        failed at runtime — the failure is not re-paid after restart."""
        try:
            key = self.key_fn(*rargs)
            self.table.record(key, self.reference)
            self.decisions[key] = self.reference
        except Exception:  # noqa: BLE001
            pass

    # -- probing ----------------------------------------------------

    def _probe_guarded(self, rargs) -> Tuple[str, bool]:
        t0 = time.perf_counter()
        try:
            got = (self.probe_hook(*rargs) if self.probe_hook is not None
                   else self.probe(rargs))
            persist = True
        except Exception:  # noqa: BLE001 - probe crash = reference leg
            # memoized in-process ONLY (never persisted): a transient
            # crash must not be remembered fleet-wide, but a
            # deterministic one costs one probe per process, not one
            # per dispatch
            got, persist = self.reference, False
        self.probes += 1
        _observe_probe(self.name, time.perf_counter() - t0)
        return got, persist

    def probe(self, rargs) -> str:
        """Decomposed-lane probe: build every candidate at the probe
        args, then hand off to the shared verify-then-time core."""
        args = tuple(self.args_fn(*rargs))
        fns = {}
        for choice, make in self.candidates.items():
            # a reference build failure propagates (crash semantics)
            fns[choice] = make(rargs, args)
        return verify_then_time(fns, args, self.reference,
                                verify_fn=self.verify_fn,
                                time_fn=self.time_fn, reps=self.reps)

    def reset(self) -> None:
        """Test hook: drop table memos and in-process decisions."""
        self.table.clear()
        self.decisions.clear()
        self.probes = 0


def verify_then_time(fns, args, reference: str, verify_fn=None,
                     time_fn=None, reps: int = 2) -> str:
    """THE verify-then-time core — the one prober implementation every
    lane shares (Lane.probe and the legacy routers' ``_probe*`` seams
    both land here): run the reference, value-fetch its output ONCE
    for the comparison, disqualify candidates that mismatch or fail,
    min-of-N time reference + survivors (``best_of`` forcing — no D2H
    in the timed region), return the winner. A candidate wins ties:
    it would not have survived verification unless interchangeable,
    and equal-time preference for the new formulation is what lets a
    lane actually move. No survivors -> the reference verdict (the
    caller persists it: a deterministic mismatch should not re-pay
    the probe after restart)."""
    tf = time_fn or (lambda fn, a, r: best_of(fn, a, r))
    vf = verify_fn or _default_verify
    want = _fetch(fns[reference](*args))
    survivors = []
    for choice, fn in fns.items():
        if choice == reference:
            continue
        try:
            ok = vf(_fetch(fn(*args)), want)
        except Exception:  # noqa: BLE001 - candidate run/verify failure
            ok = False
        if ok:
            survivors.append(choice)
    if not survivors:
        return reference
    best_c = reference
    best_t = tf(fns[reference], args, reps)
    for choice in survivors:
        t = tf(fns[choice], args, reps)
        if t <= best_t:
            best_c, best_t = choice, t
    return best_c


def _default_verify(got, want) -> bool:
    """Exact dtype + allclose — lanes with looser contracts pass
    their own verify_fn (measured tolerances, bit-exactness, ...)."""
    import numpy as np

    if isinstance(want, tuple) != isinstance(got, tuple):
        return False
    gs = got if isinstance(got, tuple) else (got,)
    ws = want if isinstance(want, tuple) else (want,)
    if len(gs) != len(ws):
        return False
    for g, w in zip(gs, ws):
        if g.shape != w.shape:
            return False
        if not np.allclose(g, w, rtol=1e-4, atol=1e-5, equal_nan=True):
            return False
    return True


def register_lane(name: str, key_fn: Callable[..., str],
                  candidates, verify_fn=None, *, reference: str,
                  args_fn=None, probe_hook=None, time_fn=None,
                  table: Optional[RouteTable] = None,
                  groups: Iterable[str] = (), reps: int = 2) -> Lane:
    """Register (or replace) a lane. ``candidates`` is either
    {choice: make(rargs, args) -> callable} for the decomposed form,
    or an iterable of choice names when a legacy ``probe_hook``
    computes the verdict itself."""
    if not isinstance(candidates, dict):
        candidates = {c: None for c in candidates}
    lane = Lane(name, key_fn, candidates, verify_fn, reference,
                args_fn=args_fn, probe_hook=probe_hook, time_fn=time_fn,
                table=table, groups=groups, reps=reps)
    _LANES[name] = lane
    return lane


def lane(name: str) -> Optional[Lane]:
    return _LANES.get(name)


def lanes() -> Dict[str, Lane]:
    return dict(_LANES)


def route(name: str, *rargs) -> str:
    return _LANES[name].route(*rargs)


def cached(name: str, *rargs) -> Optional[str]:
    return _LANES[name].cached(*rargs)


def poison(name: str, *rargs) -> None:
    _LANES[name].poison(*rargs)


def snapshot() -> dict:
    """Bench/report hook: every lane's decisions so far — which
    formulation serves which shape class (perf_report.py joins this
    against the roofline rows via each lane's ``groups``)."""
    return {
        "enabled": enabled(),
        "lanes": {
            n: {
                "reference": ln.reference,
                "candidates": sorted(ln.candidates),
                "groups": list(ln.groups),
                "probes": ln.probes,
                "decisions": dict(ln.decisions),
                "table": ln.table.filename,
            }
            for n, ln in sorted(_LANES.items())
        },
    }


def clear() -> None:
    """Test hook: reset every registered lane's memo state (the
    registrations themselves persist — modules register at import)."""
    for ln in _LANES.values():
        ln.reset()
