"""Roofline cost observatory: per-signature HLO cost capture + attribution.

PRs 6-11 made the runtime observable (telemetry, flight recorder,
duty-cycle, HBM gauges) but none of it answers the question the flat
bench line keeps asking: *which compiled program is the bottleneck and
what bound is it at?* The reference stack ships per-engine perf
accounting at the native layer; our equivalent is XLA's own compiled
cost model — ``compiled.cost_analysis()`` / ``memory_analysis()`` —
which docs/perf.md already uses by hand. This module wires it into the
telemetry plane:

- **Cost table** (:func:`record`): at ``warmup()``/AOT-compile time —
  zero hot-path cost; the capture rides a code path that just paid a
  multi-second XLA compile — every (bucket, arity, layout, device-kind)
  signature lands one entry: flops, bytes accessed, transcendentals,
  argument/output/temp bytes. Tolerant of every cost-model shape jax
  has shipped (list-of-dicts or dict, missing keys, a deserialized
  executable that refuses analysis): a signature that cannot be
  analyzed degrades to ``bound="unknown"``, never a crash.
- **Roofline math** (pure, unit-tested): per device kind a peak
  (FLOP/s, HBM bytes/s) pair from a small table —
  ``SYNAPSEML_PEAK_FLOPS`` / ``SYNAPSEML_PEAK_BW`` override it, and
  the snapshot records which source won — gives each signature an
  arithmetic intensity, a compute-/memory-bound classification
  (vs the ridge point), and an attainable roofline
  ``min(peak_flops, AI * peak_bw)``.
- **Achieved attribution** (:func:`achieved`): the PR-10 duty-cycle
  pattern over the counters the executor already records — between
  scrapes, ``executor_bucket_total{bucket=}`` deltas are attributed to
  the cost entries at that bucket (proportional split when several
  programs share one; the snapshot says so) and multiplied by each
  entry's flops over the wall window: achieved FLOP/s per device kind,
  and per entry an achieved-vs-attainable fraction. No new hot-path
  instrumentation — the attribution is a scrape-time derivative.
- **Read surfaces**: ``executor_signature_{flops,bytes}{signature=}``
  and ``executor_achieved_flops_per_sec`` /
  ``executor_roofline_fraction{device=}`` gauges (registered through
  the same :func:`~synapseml_tpu.runtime.perfwatch.ensure_registered`
  path as the memory gauges), ``GET /debug/cost`` (io/serving.py,
  behind the ``SYNAPSEML_DEBUG_ENDPOINTS`` gate), cost snapshots in
  flight-recorder dumps (runtime/blackbox.py), ``bench.py --out``'s
  ``detail.cost``, and the offline ``tools/perf_report.py`` bottleneck
  report.

Honesty note (docs/perf.md "Roofline methodology"): XLA's cost model
is a pre-fusion *estimate* — it counts the HLO the compiler planned,
not the bytes the chip moved. It ranks bottlenecks and classifies
bounds; it is not a profiler. For ground truth open a
``profiling.trace``.
"""
from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.locksan import make_lock

__all__ = [
    "record", "ensure_registered", "snapshot", "achieved", "reset",
    "entries", "peak_for", "classify_bound", "arithmetic_intensity",
    "attainable_flops", "parse_cost_analysis", "parse_memory_analysis",
    "tag_scope", "current_tag", "MAX_ENTRIES",
]

# -- peak table -------------------------------------------------------------
# (peak FLOP/s dense bf16/f32-accum, HBM bytes/s) per device kind —
# matched by lowercased substring so "TPU v5 lite" and "TPU v5e" both
# land on the v5e row. Provenance: published per-chip specs (v4 275TF
# 1.2TB/s; v5e 197TF 819GB/s; v5p 459TF 2.765TB/s; v6e 918TF
# 1.64TB/s) — the same 197 TF/s docs/perf.md has always used for MFU.
# The cpu row is a deliberately round placeholder for the forced-CPU
# test platform: fractions against it mean nothing, which the
# ``peak_source: "default"`` marker makes machine-checkable.
_PEAK_TABLE: Tuple[Tuple[str, float, float], ...] = (
    ("v5 lite", 197e12, 8.19e11),
    ("v5e", 197e12, 8.19e11),
    ("v5p", 459e12, 2.765e12),
    ("v6e", 918e12, 1.64e12),
    ("v6", 918e12, 1.64e12),
    ("v4", 275e12, 1.2e12),
    ("cpu", 1e11, 5e10),
)
_DEFAULT_PEAK = (1e11, 5e10)

_ENV_FLOPS = "SYNAPSEML_PEAK_FLOPS"
_ENV_BW = "SYNAPSEML_PEAK_BW"

# the cost table is process-global and append-only; a runaway test
# suite warming thousands of distinct signatures must not grow gauges
# without bound — past the cap, entries are counted but not stored
MAX_ENTRIES = 4096


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def peak_for(device_kind: str) -> Dict[str, Any]:
    """``{flops_per_sec, bytes_per_sec, source}`` for one device kind.
    Env overrides win (both knobs are independent — override only the
    one you measured), then the kind table, then the default row."""
    kind = (device_kind or "").lower()
    flops = bw = None
    source = "default"
    for key, f, b in _PEAK_TABLE:
        if key in kind:
            flops, bw, source = f, b, "table"
            break
    if flops is None:
        flops, bw = _DEFAULT_PEAK
    env_f, env_b = _env_float(_ENV_FLOPS), _env_float(_ENV_BW)
    if env_f is not None:
        flops, source = env_f, "env"
    if env_b is not None:
        bw, source = env_b, "env"
    return {"flops_per_sec": float(flops), "bytes_per_sec": float(bw),
            "source": source}


# -- pure roofline math -----------------------------------------------------

def arithmetic_intensity(flops: float, bytes_accessed: float) -> float:
    """FLOPs per byte moved; 0 when either side is unknown/zero (the
    classification handles the degenerate cases explicitly)."""
    if flops <= 0 or bytes_accessed <= 0:
        return 0.0
    return flops / bytes_accessed


def classify_bound(flops: float, bytes_accessed: float,
                   peak_flops: float, peak_bw: float) -> str:
    """``"compute"`` / ``"memory"`` / ``"unknown"`` against the ridge
    point ``peak_flops / peak_bw``. Degenerate programs classify by
    whichever side exists: pure-flops (bytes 0) is compute-bound,
    pure-movement (flops 0) is memory-bound, neither is unknown —
    never an exception (the capture path must not be able to crash a
    warmup)."""
    if flops <= 0 and bytes_accessed <= 0:
        return "unknown"
    if bytes_accessed <= 0:
        return "compute"
    if flops <= 0:
        return "memory"
    if peak_flops <= 0 or peak_bw <= 0:
        return "unknown"
    ridge = peak_flops / peak_bw
    return "compute" if flops / bytes_accessed >= ridge else "memory"


def attainable_flops(flops: float, bytes_accessed: float,
                     peak_flops: float, peak_bw: float) -> float:
    """The roofline ceiling for this program's arithmetic intensity:
    ``min(peak_flops, AI * peak_bw)`` — what a perfectly-scheduled
    execution of the same HLO could sustain."""
    if peak_flops <= 0:
        return 0.0
    ai = arithmetic_intensity(flops, bytes_accessed)
    if ai <= 0:
        # no byte count to bound by: the flat compute roof is all we know
        return peak_flops
    return min(peak_flops, ai * peak_bw)


# -- tolerant cost/memory-analysis parsing ----------------------------------

def parse_cost_analysis(ca: Any) -> Dict[str, float]:
    """``{flops, bytes_accessed, transcendentals, output_bytes}`` from
    whatever ``compiled.cost_analysis()`` returned — a list of
    per-computation dicts (jax<=0.4.x) or one dict (newer), any key
    missing. A shape this can't read yields zeros — the entry then
    classifies ``unknown``, never raises."""
    out = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0,
           "output_bytes": 0.0}
    try:
        dicts = ca if isinstance(ca, (list, tuple)) else [ca]
        for d in dicts:
            if not isinstance(d, dict):
                continue
            for key, field in (("flops", "flops"),
                               ("bytes accessed", "bytes_accessed"),
                               ("transcendentals", "transcendentals"),
                               ("bytes accessedout{}", "output_bytes")):
                try:
                    v = float(d.get(key, 0.0) or 0.0)
                except (TypeError, ValueError):
                    v = 0.0
                if v > 0:
                    out[field] += v
    except Exception:  # noqa: BLE001 - capture is best-effort
        pass
    return out


def parse_memory_analysis(ma: Any) -> Dict[str, float]:
    """``{argument_bytes, output_bytes, temp_bytes, code_bytes}`` from a
    ``CompiledMemoryStats`` (attribute names pinned since jaxlib 0.4);
    zeros wherever the surface is missing."""
    out = {"argument_bytes": 0.0, "output_bytes": 0.0, "temp_bytes": 0.0,
           "code_bytes": 0.0}
    for attr, field in (("argument_size_in_bytes", "argument_bytes"),
                        ("output_size_in_bytes", "output_bytes"),
                        ("temp_size_in_bytes", "temp_bytes"),
                        ("generated_code_size_in_bytes", "code_bytes")):
        try:
            v = float(getattr(ma, attr))
        except Exception:  # noqa: BLE001 - field moved/absent
            v = 0.0
        if v > 0:
            out[field] = v
    return out


# -- attribution tags -------------------------------------------------------
# bench.py wraps each bench group in tag_scope(group) so the cost
# entries its warmups create carry the group name — what lets
# tools/perf_report.py join "bench group" to "compiled program" offline
# from one artifact. Contextvar, not a global: warmups can run on
# serving scorer threads concurrently.

_TAG: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "synapseml_cost_tag", default="")


def current_tag() -> str:
    return _TAG.get()


@contextlib.contextmanager
def tag_scope(tag: str):
    """Attribute every cost entry recorded inside the block to ``tag``."""
    token = _TAG.set(str(tag))
    try:
        yield
    finally:
        _TAG.reset(token)


# -- the table --------------------------------------------------------------

_LOCK = make_lock("costmodel:_LOCK")
_T0 = time.monotonic()


class _State:
    def __init__(self):
        self.entries: Dict[str, Dict[str, Any]] = {}
        self.overflow = 0  # entries dropped past MAX_ENTRIES
        self.kinds_registered: set = set()
        # achieved-attribution window state (the duty-cycle pattern):
        # previous (wall, per-bucket counts) plus the evaluated values
        # served to every gauge read inside one scrape (1s TTL)
        self.prev: Optional[Dict[str, Any]] = None
        self.vals: Optional[Dict[str, Any]] = None
        self.vals_ts = 0.0


_S = _State()


def _sig_label(bucket: int, arity: int, layout: str, device_kind: str,
               sig_repr: str, tag: str) -> str:
    """Stable, human-scannable gauge label for one signature:
    ``[tag/]b<bucket>-a<arity>-<layout>-<hash6>`` — the hash keeps two
    different programs at the same (bucket, arity, layout) distinct."""
    h = hashlib.sha256(
        f"{sig_repr}|{layout}|{device_kind}|{tag}".encode()).hexdigest()[:6]
    prefix = f"{tag}/" if tag else ""
    return f"{prefix}b{bucket}-a{arity}-{layout}-{h}"


def record(compiled: Any, *, bucket: int, arity: int, layout: str,
           device_kind: str, sig: Any = None,
           tag: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Capture one compiled signature into the cost table; returns the
    entry (or the already-recorded one — dedup by label). Called from
    ``BatchedExecutor.warmup`` for every ``compiled``/``loaded``
    disposition; never raises — a signature whose analysis fails is
    recorded with ``captured=False`` and classifies ``unknown``."""
    try:
        tag = current_tag() if tag is None else str(tag)
        label = _sig_label(int(bucket), int(arity), str(layout),
                           str(device_kind), repr(sig), tag)
        with _LOCK:
            got = _S.entries.get(label)
        if got is not None:
            return got

        cost = {"flops": 0.0, "bytes_accessed": 0.0,
                "transcendentals": 0.0, "output_bytes": 0.0}
        mem = {"argument_bytes": 0.0, "output_bytes": 0.0,
               "temp_bytes": 0.0, "code_bytes": 0.0}
        captured = False
        try:
            cost = parse_cost_analysis(compiled.cost_analysis())
            captured = cost["flops"] > 0 or cost["bytes_accessed"] > 0
        except Exception:  # noqa: BLE001 - e.g. a store-deserialized
            pass           # executable that refuses analysis
        try:
            mem = parse_memory_analysis(compiled.memory_analysis())
        except Exception:  # noqa: BLE001
            pass

        peak = peak_for(device_kind)
        entry = {
            "signature": label,
            "tag": tag,
            "bucket": int(bucket),
            "arity": int(arity),
            "layout": str(layout),
            "device_kind": str(device_kind),
            "captured": captured,
            "flops": cost["flops"],
            "bytes_accessed": cost["bytes_accessed"],
            "transcendentals": cost["transcendentals"],
            "argument_bytes": mem["argument_bytes"],
            "output_bytes": mem["output_bytes"] or cost["output_bytes"],
            "temp_bytes": mem["temp_bytes"],
            "arithmetic_intensity": round(arithmetic_intensity(
                cost["flops"], cost["bytes_accessed"]), 4),
            "bound": (classify_bound(
                cost["flops"], cost["bytes_accessed"],
                peak["flops_per_sec"], peak["bytes_per_sec"])
                if captured else "unknown"),
            "attainable_flops_per_sec": (attainable_flops(
                cost["flops"], cost["bytes_accessed"],
                peak["flops_per_sec"], peak["bytes_per_sec"])
                if captured else 0.0),
        }
        with _LOCK:
            if label in _S.entries:  # lost a benign race: keep the first
                return _S.entries[label]
            if len(_S.entries) >= MAX_ENTRIES:
                _S.overflow += 1
                return None
            _S.entries[label] = entry
        _register_entry_gauges(label)
        _register_kind_gauges(str(device_kind))
        return entry
    except Exception:  # noqa: BLE001 - the observatory must never
        return None    # break a warmup


def _entry_field(label: str, field: str) -> float:
    with _LOCK:
        e = _S.entries.get(label)
    return float(e.get(field, 0.0)) if e else 0.0


def _register_entry_gauges(label: str):
    _tm.gauge_fn("executor_signature_flops",
                 lambda l=label: _entry_field(l, "flops"),
                 signature=label)
    _tm.gauge_fn("executor_signature_bytes",
                 lambda l=label: _entry_field(l, "bytes_accessed"),
                 signature=label)


def _register_kind_gauges(kind: str):
    with _LOCK:
        if kind in _S.kinds_registered:
            return
        _S.kinds_registered.add(kind)
    _tm.gauge_fn("executor_achieved_flops_per_sec",
                 lambda k=kind: achieved().get(
                     k, {}).get("achieved_flops_per_sec", 0.0),
                 device=kind)
    _tm.gauge_fn("executor_roofline_fraction",
                 lambda k=kind: achieved().get(
                     k, {}).get("roofline_fraction", 0.0),
                 device=kind)


def ensure_registered() -> int:
    """Re-register every recorded entry's and device kind's gauges —
    idempotent (``gauge_fn`` replaces samplers); called from
    :func:`perfwatch.ensure_registered` so the cost series ride the
    same registration path as the memory gauges. Returns the entry
    count."""
    # synlint: disable=DS001 - leaf snapshot guard: registration rides
    # scrape/registry paths that already hold their caller's lock
    with _LOCK:
        labels = list(_S.entries)
        kinds = {e["device_kind"] for e in _S.entries.values()}
        _S.kinds_registered -= kinds  # force re-register below
    for label in labels:
        _register_entry_gauges(label)
    for kind in kinds:
        _register_kind_gauges(kind)
    return len(labels)


# -- achieved attribution (the duty-cycle window pattern) -------------------

def _bucket_counts() -> Dict[str, float]:
    """Cumulative ``executor_bucket_total`` per bucket label — the
    series the executor's dispatch path already counts; registry-lock
    cost only, scrape-time only."""
    counts: Dict[str, float] = {}
    for labels, m in _tm.series("executor_bucket_total"):
        b = labels.get("bucket", "")
        counts[b] = counts.get(b, 0.0) + m.value
    return counts


def _attribute(prev: Dict[str, Any], cur: Dict[str, Any],
               table: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure window math: per-bucket dispatch deltas over the wall
    window, split evenly across the cost entries recorded at that
    bucket (several programs can share a bucket — the split is the
    documented approximation), times each entry's flops. Returns
    ``{"per_kind": {kind: {...}}, "per_entry": {label: {...}}}``."""
    d_wall = max(1e-9, cur["t"] - prev["t"])
    deltas = {b: max(0.0, v - prev["counts"].get(b, 0.0))
              for b, v in cur["counts"].items()}
    by_bucket: Dict[str, List[Dict[str, Any]]] = {}
    for e in table:
        by_bucket.setdefault(str(e["bucket"]), []).append(e)
    per_entry: Dict[str, Dict[str, float]] = {}
    per_kind: Dict[str, Dict[str, float]] = {}
    for b, delta in deltas.items():
        group = by_bucket.get(b)
        if not group or delta <= 0:
            continue
        share = delta / len(group)
        for e in group:
            rate = share / d_wall
            ach = e["flops"] * rate
            attainable = e.get("attainable_flops_per_sec", 0.0)
            per_entry[e["signature"]] = {
                "dispatch_rate_per_sec": round(rate, 4),
                "achieved_flops_per_sec": ach,
                "achieved_fraction": (round(ach / attainable, 6)
                                      if attainable > 0 else 0.0),
            }
            kind = per_kind.setdefault(e["device_kind"], {
                "achieved_flops_per_sec": 0.0,
                "achieved_bytes_per_sec": 0.0})
            kind["achieved_flops_per_sec"] += ach
            kind["achieved_bytes_per_sec"] += e["bytes_accessed"] * rate
    for kind, vals in per_kind.items():
        peak = peak_for(kind)
        vals["roofline_fraction"] = (
            round(vals["achieved_flops_per_sec"]
                  / peak["flops_per_sec"], 6)
            if peak["flops_per_sec"] > 0 else 0.0)
    return {"per_kind": per_kind, "per_entry": per_entry,
            "window_seconds": round(d_wall, 3)}


def achieved(force: bool = False) -> Dict[str, Any]:
    """``{device_kind: {achieved_flops_per_sec, achieved_bytes_per_sec,
    roofline_fraction}}`` over the window since the previous
    evaluation — TTL-cached (1s) so the many gauge reads of one scrape
    share a single window, and the whole check-evaluate-advance runs
    under the lock (two racing TTL-missed readers must not both
    advance the window — the perfwatch duty-cycle comment applies
    verbatim)."""
    with _LOCK:
        now = time.monotonic()
        if not force and _S.vals is not None and now - _S.vals_ts < 1.0:
            return _S.vals["per_kind"]
        cur = {"t": now, "counts": _bucket_counts()}
        prev = _S.prev or {"t": _T0, "counts": {}}
        table = list(_S.entries.values())
        vals = _attribute(prev, cur, table)
        _S.prev = cur
        _S.vals = vals
        _S.vals_ts = now
        return vals["per_kind"]


def entries() -> List[Dict[str, Any]]:
    with _LOCK:
        return [dict(e) for e in _S.entries.values()]


def snapshot(force: bool = False) -> Dict[str, Any]:
    """The ``GET /debug/cost`` payload (and the shape ``bench.py
    --out`` embeds under ``detail.cost``): the per-signature table with
    the current window's achieved attribution folded in, the peak
    provenance per device kind, and the attribution caveats spelled
    out so an offline reader (tools/perf_report.py) needs no other
    context."""
    achieved(force=force)  # refresh/advance the shared window
    with _LOCK:
        window = _S.vals or {"per_kind": {}, "per_entry": {},
                             "window_seconds": 0.0}
        table = [dict(e) for e in _S.entries.values()]
        overflow = _S.overflow
    per_entry = window["per_entry"]
    for e in table:
        e.update(per_entry.get(e["signature"], {
            "dispatch_rate_per_sec": 0.0,
            "achieved_flops_per_sec": 0.0,
            "achieved_fraction": 0.0}))
    kinds = sorted({e["device_kind"] for e in table})
    return {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "entries": sorted(table, key=lambda e: e["signature"]),
        "per_kind": window["per_kind"],
        "window_seconds": window["window_seconds"],
        "peaks": {k: peak_for(k) for k in kinds},
        "attribution": "bucket-proportional",  # even split per bucket
        "overflow_dropped": overflow,
        "note": ("XLA cost model: pre-fusion estimate, not measured "
                 "hardware counters (docs/perf.md 'Roofline "
                 "methodology')"),
    }


def reset() -> int:
    """Tests/teardown: drop every entry and unregister every gauge this
    module registered, so a scrape after reset carries no cost series.
    Returns the number of entries dropped."""
    with _LOCK:
        labels = list(_S.entries)
        kinds = set(_S.kinds_registered)
        _S.entries.clear()
        _S.kinds_registered.clear()
        _S.overflow = 0
        _S.prev = None
        _S.vals = None
        _S.vals_ts = 0.0
    for label in labels:
        _tm.unregister("executor_signature_flops", signature=label)
        _tm.unregister("executor_signature_bytes", signature=label)
    for kind in kinds:
        _tm.unregister("executor_achieved_flops_per_sec", device=kind)
        _tm.unregister("executor_roofline_fraction", device=kind)
    return len(labels)
