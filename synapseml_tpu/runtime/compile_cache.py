"""Persistent compile cache + serialized-executable store: cold-start removal.

The reference SynapseML ships prebuilt native engines inside jars, so a
Spark Serving replica scores the moment the jar loads. The JAX
reproduction instead pays full XLA compilation per process, per bucket
shape, per device layout — tens of seconds of dead time on every
container restart or autoscale event. This module takes that compile off
the serving path with two independent layers:

1. **JAX's persistent compilation cache** (:func:`enable_persistent_cache`)
   — wired behind one framework knob (``SYNAPSEML_COMPILE_CACHE`` env var
   or ``compile_cache_dir=``). XLA-level: any jit in the process whose
   fingerprint matches a prior run deserializes instead of compiling.

2. **Serialized-executable store** (:class:`ExecutableStore`) — the AOT
   layer under :meth:`BatchedExecutor.warmup`: every (bucket, arity,
   donation-mask, device-layout) signature is ``.lower().compile()``-ed up
   front, serialized via ``jax.experimental.serialize_executable``, and
   keyed by (caller content hash — graph/weights config —, input
   signature, mesh shape, device kind, jax+jaxlib version). A restarted
   replica deserializes the executable directly — no tracing, no XLA.

Both layers degrade gracefully: any miss, version skew, or corrupt entry
falls back to today's fresh-compile behavior — a broken cache can slow a
restart down, never break it.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import pickle
import tempfile
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.locksan import make_lock

_ENV_KNOB = "SYNAPSEML_COMPILE_CACHE"
_FORMAT_VERSION = 1
_MAGIC = b"SMTXC1\n"

# store traffic counters (docs/observability.md): hits split memo vs
# disk; misses/skews/deserialize-failures are distinct — a volume full
# of entries another runtime wrote looks like "misses" without the
# skew/failure split, and that distinction is exactly what an operator
# debugging a cold restart needs
_M_HIT = _tm.counter("compile_cache_store_hits_total")
_M_MISS = _tm.counter("compile_cache_store_misses_total")
_M_SKEW = _tm.counter("compile_cache_store_skew_total")
_M_DESER_FAIL = _tm.counter("compile_cache_deserialize_failures_total")
_M_SAVE = _tm.counter("compile_cache_saves_total")
_M_SAVE_FAIL = _tm.counter("compile_cache_save_failures_total")
# recompile-sentinel companion (runtime/executor.py registers the
# warmup/dispatch phases): how long a warm restart spends turning a
# store entry back into a runnable executable — the cost a "loaded"
# warmup disposition actually paid
_M_DESER_S = _tm.histogram("executor_compile_seconds",
                           phase="deserialize")

_STATE_LOCK = make_lock("compile_cache:_STATE_LOCK")
_PERSISTENT_WIRED: Optional[str] = None
# every live store, so JitCache.clear() (runtime/executor.py) can drop
# memoized executables without each test knowing which stores exist
_OPEN_STORES: "weakref.WeakSet[ExecutableStore]" = weakref.WeakSet()


def default_cache_dir() -> Optional[str]:
    """The framework knob: ``SYNAPSEML_COMPILE_CACHE`` names the cache
    directory; unset/empty means both layers stay off unless a caller
    passes an explicit ``compile_cache_dir=``."""
    path = os.environ.get(_ENV_KNOB, "").strip()
    return path or None


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Wire JAX's own persistent compilation cache at ``path`` (layer 1).

    Idempotent; returns the directory actually wired, or None when no
    path is configured. Thresholds are dropped to zero so the serving
    buckets — many small programs — all persist, not just the slow ones
    (jax's defaults skip sub-second compiles, which is exactly the shape
    a warmed bucket ladder has)."""
    global _PERSISTENT_WIRED
    path = path or default_cache_dir()
    if not path:
        return None
    with _STATE_LOCK:
        if _PERSISTENT_WIRED == path:
            return path
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        for knob, val in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 - knob renamed across versions
                pass
        _PERSISTENT_WIRED = path
        return path


def env_fingerprint() -> str:
    """Version skew guard baked into every executable key: a cache dir
    surviving a jax/jaxlib upgrade or a backend change must MISS (a
    deserialized executable from another runtime would crash or, worse,
    silently miscompute)."""
    import jax
    import jaxlib

    return "|".join((
        f"jax={jax.__version__}",
        f"jaxlib={jaxlib.__version__}",
        f"backend={jax.default_backend()}",
    ))


def executable_key(cache_key: str, *, bucket: int, sig: Any, layout: str,
                   mesh_shape: Tuple[int, ...], device_kind: str,
                   fingerprint: Optional[str] = None) -> str:
    """Content-addressed key for one compiled signature.

    Anatomy (docs/perf.md "cold start"): ``cache_key`` is the caller's
    content hash — for ONNXModel the sha256 of the raw model bytes plus
    the compute-dtype/normalization config, i.e. *graph and weights*;
    ``sig`` is the staged input signature (shapes+dtypes, bucket-padded);
    ``layout``/``mesh_shape``/``device_kind`` pin the device topology;
    the env fingerprint pins jax+jaxlib+backend versions. Change any
    ingredient and the key misses — fresh compile, never a stale hit."""
    blob = json.dumps({
        "v": _FORMAT_VERSION,
        "cache_key": cache_key,
        "bucket": bucket,
        "sig": repr(sig),
        "layout": layout,
        "mesh_shape": list(mesh_shape),
        "device_kind": device_kind,
        "env": fingerprint if fingerprint is not None else env_fingerprint(),
    }, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def content_hash(*parts: Any) -> str:
    """Stable sha256 over heterogeneous key parts (bytes hashed raw, the
    rest by repr) — the helper model wrappers use to build ``cache_key``
    from payload bytes + config."""
    h = hashlib.sha256()
    for p in parts:
        if isinstance(p, (bytes, bytearray)):
            h.update(b"b:")
            h.update(p)
        else:
            h.update(repr(p).encode())
        h.update(b"\x00")
    return h.hexdigest()


class ExecutableStore:
    """Directory of serialized XLA executables, one file per key.

    ``save``/``load`` never raise for cache problems: a failed save is
    dropped (compilation already happened — nothing is lost), a failed
    load (missing file, truncation, version skew, pickle drift) returns
    None so the caller compiles fresh. ``load`` memoizes per key so a
    process that warms the same signature twice deserializes once;
    :meth:`invalidate` drops the memo (JitCache.clear() calls it through
    :func:`invalidate_open_stores` so cleared tests re-read disk)."""

    def __init__(self, directory: str):
        self.directory = str(directory)
        self._memo: Dict[str, Any] = {}
        self._lock = make_lock("ExecutableStore._lock")
        self.closed = False
        _OPEN_STORES.add(self)

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.xc")

    def save(self, key: str, compiled: Any) -> bool:
        if self.closed:
            return False
        try:
            from jax.experimental import serialize_executable as _se

            payload, in_tree, out_tree = _se.serialize(compiled)
            buf = io.BytesIO()
            buf.write(_MAGIC)
            meta = json.dumps({"v": _FORMAT_VERSION,
                               "env": env_fingerprint()}).encode()
            buf.write(len(meta).to_bytes(4, "big"))
            buf.write(meta)
            pickle.dump((payload, in_tree, out_tree), buf,
                        protocol=pickle.HIGHEST_PROTOCOL)
            os.makedirs(self.directory, exist_ok=True)
            # atomic publish: a concurrent reader (another replica on the
            # same cache volume) sees either the full entry or nothing —
            # never a truncated file
            fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(buf.getvalue())
                os.replace(tmp, self._path(key))
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            _M_SAVE.inc()
            return True
        except Exception:  # noqa: BLE001 - cache write is best-effort
            _M_SAVE_FAIL.inc()
            return False

    def load(self, key: str) -> Optional[Any]:
        if self.closed:
            return None
        with self._lock:
            if key in self._memo:
                _M_HIT.inc()
                return self._memo[key]
        try:
            with open(self._path(key), "rb") as fh:
                raw = fh.read()
        except OSError:  # no such entry: the plain miss
            _M_MISS.inc()
            return None
        try:
            if not raw.startswith(_MAGIC):
                _M_DESER_FAIL.inc()  # truncated/foreign bytes
                return None
            off = len(_MAGIC)
            mlen = int.from_bytes(raw[off:off + 4], "big")
            off += 4
            meta = json.loads(raw[off:off + mlen].decode())
            off += mlen
            if meta.get("v") != _FORMAT_VERSION:
                _M_SKEW.inc()
                return None
            if meta.get("env") != env_fingerprint():
                # version/backend skew: the executable was built by a
                # different runtime — unusable, compile fresh
                _M_SKEW.inc()
                return None
            from jax.experimental import serialize_executable as _se

            t0 = time.monotonic()
            payload, in_tree, out_tree = pickle.loads(raw[off:])
            compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
            _M_DESER_S.observe(time.monotonic() - t0)
        except Exception:  # noqa: BLE001 - any corruption = miss
            _M_DESER_FAIL.inc()
            return None
        with self._lock:
            self._memo[key] = compiled
        _M_HIT.inc()
        return compiled

    def invalidate(self):
        """Drop memoized executables so the next load re-reads disk."""
        with self._lock:
            self._memo.clear()

    def close(self):
        """Invalidate and refuse further traffic (JitCache.clear() path:
        a cleared cache must not resurrect stale executables)."""
        self.invalidate()
        self.closed = True


def invalidate_open_stores(close: bool = False) -> int:
    """Invalidate (or close) every live :class:`ExecutableStore`.

    ``JitCache.clear()`` calls this so tests that clear jit caches cannot
    read back memoized, possibly-stale executables afterward. Returns the
    number of stores touched."""
    stores = list(_OPEN_STORES)
    for st in stores:
        if close:
            st.close()
        else:
            st.invalidate()
    return len(stores)


class WarmupReport:
    """Outcome of one :meth:`BatchedExecutor.warmup` sweep.

    ``entries`` lists one dict per (bucket, layout, device) signature with
    its disposition: ``"loaded"`` (deserialized from the store — no XLA
    compile), ``"compiled"`` (fresh compile, persisted when a store is
    configured), or ``"error"`` (that signature fell back to lazy jit;
    the error rides in ``errors``). Loaded/compiled entries also carry
    ``cost_captured``: whether XLA's compiled cost model yielded a
    flops/bytes ledger for the roofline cost table
    (runtime/costmodel.py) — False for e.g. a store-deserialized
    executable that refuses analysis, which lands an ``unknown``-bound
    entry instead. Warmup itself never raises for cache
    or compile problems — a failed signature just compiles on first use,
    today's behavior."""

    def __init__(self):
        self.entries: List[Dict[str, Any]] = []
        self.errors: List[str] = []

    @property
    def compiled(self) -> int:
        return sum(1 for e in self.entries if e["status"] == "compiled")

    @property
    def loaded(self) -> int:
        return sum(1 for e in self.entries if e["status"] == "loaded")

    def __repr__(self):
        return (f"WarmupReport(signatures={len(self.entries)}, "
                f"compiled={self.compiled}, loaded={self.loaded}, "
                f"errors={len(self.errors)})")
