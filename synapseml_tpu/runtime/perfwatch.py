"""Performance observatory: device-memory telemetry + utilization attribution.

PRs 6 and 9 made *requests* and *incidents* observable; the performance
plane stayed dark — HBM pressure was never measured and "are the chips
busy or starved" had no answer short of attaching a profiler. This
module is the scrape-time half of the performance-observability layer
(the recompile sentinel in :mod:`~synapseml_tpu.runtime.executor` is
the dispatch-path half):

- **Device-memory gauges** (``device_hbm_bytes_in_use{device=}``,
  ``device_hbm_bytes_limit``, ``device_hbm_peak_bytes``,
  ``device_live_buffer_count``), sampled at scrape time via
  ``device.memory_stats()`` where the backend provides it (TPU/GPU)
  with a ``jax.live_arrays()`` aggregation fallback (CPU, including
  the forced-8-device test platform). One real sample serves a whole
  scrape (short TTL cache) — many gauges, one walk. A per-process
  **peak high-water mark** is tracked across samples, so a transient
  allocation spike between scrapes that the backend's own peak counter
  caught is never lost.
- **HBM high-water events**: a device crossing
  ``SYNAPSEML_HBM_HIGH_WATER`` (fraction of ``bytes_limit``, default
  0.9; 0 disables) lands one ``hbm_high_water`` event in the flight
  recorder ring + structured log per *crossing* (re-armed only after
  usage falls 15% below the threshold — a device hovering at the line
  produces one breadcrumb, not one per scrape).
- **Utilization attribution** (``executor_duty_cycle{device=}``):
  per-dispatch-target compute duty-cycle gauges derived from series the
  executor already records — no new hot-path instrumentation. Between
  consecutive scrapes, the delta of ``executor_compute_seconds``'s sum
  is attributed to dispatch targets proportionally to their
  ``executor_dispatch_total`` deltas and divided by the wall-clock
  window: the fraction of the window each target spent with a batch in
  flight. A dp-sharded mesh counts under its ``dp<N>`` label — one
  batch keeps *all N chips* busy for its window, so the value is the
  per-chip busy fraction of the mesh, not 1/N of it. Because "compute"
  is the overlap-inclusive dispatch-end → drain-pickup bound
  (docs/observability.md), overlapping in-flight batches can push the
  raw ratio past 1; the gauge clamps at 1.0 — saturated means
  saturated. Low duty with a deep queue = the chips are starved
  (host staging or H2D bound); high duty with low throughput = the
  program itself is slow.

Everything here is scrape-time only: nothing records on the submit/
dispatch/drain hot paths, and a process that never scrapes pays one
``ensure_registered()`` flag test per server/executor construction.
``GET /debug/memory`` (io/serving.py) serves :func:`memory_snapshot`
live beside ``/debug/flight``.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from synapseml_tpu.runtime import blackbox as _bb
from synapseml_tpu.runtime import costmodel as _cm
from synapseml_tpu.runtime.locksan import make_lock
from synapseml_tpu.runtime import telemetry as _tm

__all__ = [
    "ensure_registered", "ensure_process_registered",
    "register_duty_gauge", "device_memory",
    "memory_snapshot", "duty_cycles", "check_high_water",
    "high_water_fraction", "set_high_water_fraction", "process_stats",
    "record_tp_param_bytes", "clear_tp_param_bytes", "tp_param_bytes",
]

_LOCK = make_lock("perfwatch:_LOCK")
_T0 = time.monotonic()

# one real device walk serves every gauge of a scrape: /metrics reads
# 4 gauges per device back to back, and memory_stats()/live_arrays()
# are not free — the TTL is well under any sane scrape interval
_MEM_TTL_S = 0.5


class _State:
    def __init__(self):
        self.registered = False
        self.process_registered = False
        # TTL memo for process_stats(): the four process_* gauges all
        # read inside one scrape, and the fd-directory listing is
        # O(open fds) — one /proc walk serves them all
        self.proc_cache: Optional[Dict[str, float]] = None
        self.proc_cache_ts = 0.0
        # process-lifetime high-water per device key (bytes): the max of
        # every sampled bytes_in_use and the backend's own peak counter
        self.peak: Dict[str, int] = {}
        # per-device "already above the line" latch for the high-water
        # event debounce (one event per crossing, not per scrape)
        self.high: Dict[str, bool] = {}
        self.mem_cache: Optional[List[Dict[str, Any]]] = None
        self.mem_cache_ts = 0.0
        frac = os.environ.get("SYNAPSEML_HBM_HIGH_WATER", "0.9")
        try:
            self.high_water = float(frac)
        except ValueError:
            self.high_water = 0.9
        # duty-cycle window state: the raw (wall, compute_sum, counts)
        # snapshot the previous evaluation ended on, plus the evaluated
        # values served to every gauge read inside one scrape
        self.duty_prev: Optional[Dict[str, Any]] = None
        self.duty_vals: Dict[str, float] = {}
        self.duty_vals_ts = 0.0
        self.duty_registered: set = set()
        # device keys whose hbm gauges are live — unregister_all() must
        # tear down exactly the label sets ensure_registered() created
        self.device_keys: set = set()
        # per-owner {device_key: bytes} of executor-placed parameter
        # shards (parallel/onnx_tp.param_bytes_per_device) — owners are
        # tokens handed out by record_tp_param_bytes, cleared when an
        # executor closes/drops; the tp_param_bytes{device=} gauges sum
        # across live owners at scrape time
        self.tp_bytes: Dict[int, Dict[str, int]] = {}
        self.tp_bytes_next = 0


_S = _State()


def high_water_fraction() -> float:
    return _S.high_water


def set_high_water_fraction(frac: float) -> float:
    """Retune the high-water threshold (tests, serving entry); returns
    the previous value. 0 disables the event."""
    prev = _S.high_water
    _S.high_water = float(frac)
    return prev


# -- tensor-parallel parameter residency ------------------------------------

def record_tp_param_bytes(per_device: Dict[str, int]) -> int:
    """Record one executor's placed parameter-shard bytes per device
    key; returns an owner token for :func:`clear_tp_param_bytes`. The
    value set is whatever ``param_bytes_per_device`` measured off the
    actual placed arrays — under tensor parallelism each device holds
    ~sharded/tp + the replicated remainder, and the gauges make that
    claim scrapeable instead of anecdotal."""
    with _LOCK:
        token = _S.tp_bytes_next
        _S.tp_bytes_next += 1
        _S.tp_bytes[token] = {str(k): int(v)
                              for k, v in per_device.items()}
    return token


def clear_tp_param_bytes(token: int) -> None:
    """Drop one owner's record (executor close/GC finalizer)."""
    with _LOCK:
        _S.tp_bytes.pop(token, None)


def tp_param_bytes(device_key: Optional[str] = None):
    """Parameter bytes resident per device across live executors —
    the whole dict, or one device's total."""
    totals: Dict[str, int] = {}
    with _LOCK:
        for per in _S.tp_bytes.values():
            for k, v in per.items():
                totals[k] = totals.get(k, 0) + v
    if device_key is None:
        return totals
    return totals.get(device_key, 0)


# -- device memory ----------------------------------------------------------

def _stats_record(d, stats: Dict[str, Any]) -> Dict[str, Any]:
    def _int(key) -> int:
        try:
            return int(stats.get(key) or 0)
        except (TypeError, ValueError):
            return 0

    return {
        "device": str(d.id), "platform": str(d.platform),
        "source": "memory_stats",
        "bytes_in_use": _int("bytes_in_use"),
        "bytes_limit": _int("bytes_limit"),
        "peak_bytes_in_use": _int("peak_bytes_in_use"),
        "live_buffers": _int("num_allocs"),
    }


def _live_array_totals() -> Dict[int, Tuple[int, int]]:
    """{device_id: (bytes, buffer_count)} aggregated from
    ``jax.live_arrays()`` — the fallback where the backend exposes no
    allocator stats (CPU, incl. the forced-8-device test platform).
    Per-device bytes come from each array's ``addressable_shards``
    (``shard.data.nbytes`` on ``shard.device``), so a REPLICATED array
    counts its full size on every device holding a copy — an even
    split of ``a.nbytes`` would read N× low exactly for the
    weights-replicated layouts the executor uses. Fallback for arrays
    whose shards are unreadable mid-walk: even split."""
    import jax

    totals: Dict[int, List[int]] = {}

    def _add(dev_id: int, nbytes: int):
        ent = totals.setdefault(dev_id, [0, 0])
        ent[0] += nbytes
        ent[1] += 1

    try:
        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 - introspection is best-effort
        return {}
    for a in arrays:
        try:
            for shard in a.addressable_shards:
                _add(shard.device.id, int(shard.data.nbytes))
        except Exception:  # noqa: BLE001 - deleted/donated mid-walk
            try:
                nbytes = int(a.nbytes)
                devs = list(a.devices())
            except Exception:  # noqa: BLE001
                continue
            if not devs:
                continue
            for d in devs:
                _add(d.id, nbytes // len(devs))
    return {k: (v[0], v[1]) for k, v in totals.items()}


def device_memory() -> List[Dict[str, Any]]:
    """One record per local device: ``memory_stats()`` where available,
    the ``live_arrays`` aggregation otherwise. Pure sample — no peak
    update, no events (that is :func:`_sampled`'s job)."""
    import jax

    out: List[Dict[str, Any]] = []
    live: Optional[Dict[int, Tuple[int, int]]] = None
    for d in jax.local_devices():
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - backend without allocator stats
            stats = None
        if stats:
            out.append(_stats_record(d, stats))
            continue
        if live is None:
            live = _live_array_totals()
        used, count = live.get(d.id, (0, 0))
        out.append({
            "device": str(d.id), "platform": str(d.platform),
            "source": "live_arrays",
            "bytes_in_use": used, "bytes_limit": 0,
            "peak_bytes_in_use": 0, "live_buffers": count,
        })
    return out


def _apply_peaks(devices: List[Dict[str, Any]]) -> None:
    """Fold a sample into the process-lifetime peaks and annotate each
    record with ``process_peak_bytes``."""
    with _LOCK:
        for rec in devices:
            key = rec["device"]
            peak = max(_S.peak.get(key, 0), rec["bytes_in_use"],
                       rec["peak_bytes_in_use"])
            _S.peak[key] = peak
            rec["process_peak_bytes"] = peak


def check_high_water(devices: List[Dict[str, Any]],
                     fraction: Optional[float] = None) -> List[str]:
    """Latch-debounced high-water detection over one sample: a device
    whose ``bytes_in_use / bytes_limit`` crosses ``fraction`` records
    ONE ``hbm_high_water`` flight-recorder event (which also emits the
    structured log line); the latch re-arms when usage falls below 85%
    of the threshold. Devices with no known limit (the live_arrays
    fallback) never fire. Returns the device keys that fired."""
    frac = _S.high_water if fraction is None else fraction
    fired: List[str] = []
    if frac <= 0:
        return fired
    for rec in devices:
        limit = rec.get("bytes_limit") or 0
        if limit <= 0:
            continue
        key = rec["device"]
        ratio = rec["bytes_in_use"] / limit
        with _LOCK:
            was = _S.high.get(key, False)
            if ratio >= frac and not was:
                _S.high[key] = True
                fire = True
            else:
                fire = False
                if was and ratio < frac * 0.85:
                    _S.high[key] = False
        if fire:
            # leaf call: blackbox.record takes only its own ring lock
            _bb.record("hbm_high_water", level="warn", device=key,
                       platform=rec.get("platform"),
                       bytes_in_use=rec["bytes_in_use"],
                       bytes_limit=limit,
                       fraction=round(ratio, 4), threshold=frac)
            fired.append(key)
    return fired


def _sampled(force: bool = False) -> List[Dict[str, Any]]:
    """TTL-cached sample with the peak/high-water side effects applied —
    what the gauges read. ``force`` bypasses the cache (the
    ``/debug/memory`` surface: an operator asking wants *now*)."""
    now = time.monotonic()
    if not force:
        with _LOCK:
            if (_S.mem_cache is not None
                    and now - _S.mem_cache_ts < _MEM_TTL_S):
                return _S.mem_cache
    devices = device_memory()  # jax walk outside the lock
    _apply_peaks(devices)
    check_high_water(devices)
    with _LOCK:
        _S.mem_cache = devices
        _S.mem_cache_ts = now
    return devices


def _mem_field(device_key: str, field: str) -> float:
    for rec in _sampled():
        if rec["device"] == device_key:
            return float(rec.get(field, 0))
    return 0.0


def memory_snapshot(force: bool = True) -> Dict[str, Any]:
    """The ``GET /debug/memory`` payload: per-device records (each
    annotated with its executor-placed parameter-shard bytes) plus
    process totals. ``force=True`` (the default) takes a fresh
    sample."""
    tpb = tp_param_bytes()
    # annotate copies — _sampled()'s records are TTL-cached and shared
    devices = [dict(d, tp_param_bytes=tpb.get(d["device"], 0))
               for d in _sampled(force=force)]
    return {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "high_water_fraction": _S.high_water,
        "devices": devices,
        "totals": {
            "bytes_in_use": sum(d["bytes_in_use"] for d in devices),
            "live_buffers": sum(d["live_buffers"] for d in devices),
            "process_peak_bytes": sum(
                d.get("process_peak_bytes", 0) for d in devices),
            "tp_param_bytes": sum(tpb.values()),
        },
    }


def process_stats() -> Dict[str, float]:
    """One process self-telemetry sample: RSS bytes, open fd count,
    live thread count, uptime seconds. Linux-first (/proc), degrading
    per field to 0 where the surface is missing — a gauge reading 0 on
    an exotic platform beats an exception in a scrape. TTL-memoized
    (same pattern as the device-memory cache) so the four gauges of
    one scrape share a single /proc walk."""
    import threading as _threading

    now = time.monotonic()
    with _LOCK:
        if (_S.proc_cache is not None
                and now - _S.proc_cache_ts < _MEM_TTL_S):
            return _S.proc_cache

    rss = 0.0
    try:
        with open("/proc/self/statm", "rb") as fh:
            # field 2 = resident pages
            rss = float(int(fh.read().split()[1])) * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 - no /proc: try rusage
        try:
            import resource

            # ru_maxrss is KiB on Linux (a peak, not current — still
            # the honest fallback where /proc is absent)
            rss = float(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss) * 1024.0
        except Exception:  # noqa: BLE001
            rss = 0.0
    try:
        fds = float(len(os.listdir("/proc/self/fd")))
    except Exception:  # noqa: BLE001
        fds = 0.0
    stats = {
        "rss_bytes": rss,
        "open_fds": fds,
        "thread_count": float(_threading.active_count()),
        "uptime_seconds": time.monotonic() - _T0,
    }
    with _LOCK:
        _S.proc_cache = stats
        _S.proc_cache_ts = now
    return stats


def ensure_process_registered() -> bool:
    """Register the ``process_*`` self-telemetry gauges once per
    process — scrape-time samplers over :func:`process_stats`, no jax
    required (the fleet controller and jax-free serving front-ends
    register these too; the replica-leak alerts and the fleet
    controller's own /fleet/metrics read them). Idempotent."""
    # synlint: disable=DS001 - leaf once-guard: ensure_* registration is
    # invoked under the serving registry lock and acquires nothing inside
    with _LOCK:
        if _S.process_registered:
            return True
        _S.process_registered = True
    _tm.gauge_fn("process_rss_bytes",
                 lambda: process_stats()["rss_bytes"])
    _tm.gauge_fn("process_open_fds",
                 lambda: process_stats()["open_fds"])
    _tm.gauge_fn("process_thread_count",
                 lambda: process_stats()["thread_count"])
    _tm.gauge_fn("process_uptime_seconds",
                 lambda: process_stats()["uptime_seconds"])
    return True


def _jax_initialized() -> bool:
    """Whether a jax backend already exists WITHOUT creating one —
    best-effort over a private surface; False when undetectable."""
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return False
        from jax._src import xla_bridge as _xb

        return bool(getattr(_xb, "_backends", None))
    except Exception:  # noqa: BLE001 - private surface moved
        return False


def ensure_registered(lazy: bool = False) -> bool:
    """Register the per-device memory gauges once per process.
    ``BatchedExecutor`` construction calls this eagerly (the backend is
    in use by definition); ``WorkerServer`` passes ``lazy=True`` so a
    jax-free serving front-end (a pure-numpy echo/proxy pipeline, or a
    router process sharing a TPU host with a separate scorer that needs
    exclusive libtpu access) never force-initializes the backend just
    by binding a port — registration then happens when the first
    executor appears. (``/debug/memory`` still samples on demand: an
    operator explicitly asking pays the init.) Idempotent and cheap
    after the first call; returns True once registered.

    The ``process_*`` self-telemetry gauges register unconditionally —
    they read /proc, not jax, so even a jax-free front-end (and the
    fleet controller watching it) gets RSS/fd/thread/uptime series.
    The roofline cost series (runtime/costmodel.py) re-register here
    too — same registration path, so a process that re-enters after a
    telemetry reset gets its ``executor_signature_*`` /
    ``executor_roofline_fraction`` samplers back."""
    ensure_process_registered()
    _cm.ensure_registered()
    if _S.registered:
        return True
    if lazy and not _jax_initialized():
        return False
    with _LOCK:
        if _S.registered:
            return True
        _S.registered = True
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 - no backend: stay unregistered
        with _LOCK:
            _S.registered = False
        return False
    for d in devices:
        key = str(d.id)
        with _LOCK:
            _S.device_keys.add(key)
        _tm.gauge_fn("device_hbm_bytes_in_use",
                     lambda k=key: _mem_field(k, "bytes_in_use"),
                     device=key)
        _tm.gauge_fn("device_hbm_bytes_limit",
                     lambda k=key: _mem_field(k, "bytes_limit"),
                     device=key)
        _tm.gauge_fn("device_hbm_peak_bytes",
                     lambda k=key: _mem_field(k, "process_peak_bytes"),
                     device=key)
        _tm.gauge_fn("device_live_buffer_count",
                     lambda k=key: _mem_field(k, "live_buffers"),
                     device=key)
        _tm.gauge_fn("tp_param_bytes",
                     lambda k=key: float(tp_param_bytes(k)),
                     device=key)
    return True


# -- utilization attribution ------------------------------------------------

def _duty_raw() -> Dict[str, Any]:
    """Current raw totals the attribution differentiates: wall clock,
    the summed ``executor_compute_seconds`` across all label sets, and
    per-target ``executor_dispatch_total`` values."""
    compute = 0.0
    for _labels, m in _tm.series("executor_compute_seconds"):
        compute += m._aggregate()[1]
    counts: Dict[str, float] = {}
    for labels, m in _tm.series("executor_dispatch_total"):
        dev = labels.get("device", "default")
        counts[dev] = counts.get(dev, 0.0) + m.value
    return {"t": time.monotonic(), "compute": compute, "counts": counts}


def _attribute(prev: Dict[str, Any],
               cur: Dict[str, Any]) -> Dict[str, float]:
    """Pure window math: the compute-seconds delta split across targets
    by their dispatch-count deltas, over the wall window, clamped to
    [0, 1]. Targets with no batches in the window read 0."""
    d_wall = max(1e-9, cur["t"] - prev["t"])
    d_compute = max(0.0, cur["compute"] - prev["compute"])
    deltas = {k: max(0.0, v - prev["counts"].get(k, 0.0))
              for k, v in cur["counts"].items()}
    total = sum(deltas.values())
    if total <= 0 or d_compute <= 0:
        return {k: 0.0 for k in cur["counts"]}
    return {k: min(1.0, (d / total) * d_compute / d_wall)
            for k, d in deltas.items()}


def duty_cycles(force: bool = False) -> Dict[str, float]:
    """{dispatch target: duty cycle in [0,1]} over the window since the
    previous evaluation. TTL-cached (1s) so the many per-label gauge
    reads of one scrape share a single window; each scrape's window is
    scrape-to-scrape, the first one is process-start-to-scrape.

    The whole check-evaluate-advance runs under the state lock: two
    concurrent TTL-missing readers (a /metrics scrape racing a
    /debug/flight telemetry snapshot) must not BOTH advance the
    window, or the loser attributes over a microsecond wall and every
    gauge reads a spurious 0 for busy chips. ``_duty_raw``'s registry
    walk under the lock is fine — scrape-time only, and the lock order
    (perfwatch lock → registry lock) is taken nowhere in reverse."""
    with _LOCK:
        now = time.monotonic()
        if not force and now - _S.duty_vals_ts < 1.0 and _S.duty_vals:
            return _S.duty_vals
        cur = _duty_raw()
        prev = _S.duty_prev or {"t": _T0, "compute": 0.0, "counts": {}}
        vals = _attribute(prev, cur)
        _S.duty_prev = cur
        _S.duty_vals = vals
        _S.duty_vals_ts = cur["t"]
        return vals


def register_duty_gauge(label: str):
    """Register ``executor_duty_cycle{device=<label>}`` once per
    dispatch target — called by ``BatchedExecutor`` construction for
    each label it will count dispatches under, so the gauge set always
    matches the counter set."""
    with _LOCK:
        if label in _S.duty_registered:
            return
        _S.duty_registered.add(label)
    _tm.gauge_fn("executor_duty_cycle",
                 lambda l=label: duty_cycles().get(l, 0.0),
                 device=label)


def unregister_all() -> None:
    """Tear down every gauge_fn this module registered and reset the
    registration latches, so a process that stops its executors (or a
    test tearing down a fixture) leaves no live callbacks in the
    telemetry registry — a leaked sampler pins this module's state and
    keeps exporting values for devices the process no longer drives.
    The next ensure_* call re-registers from scratch."""
    with _LOCK:
        device_keys = sorted(_S.device_keys)
        duty_labels = sorted(_S.duty_registered)
        _S.device_keys.clear()
        _S.duty_registered.clear()
        _S.registered = False
        _S.process_registered = False
    _tm.unregister("process_rss_bytes")
    _tm.unregister("process_open_fds")
    _tm.unregister("process_thread_count")
    _tm.unregister("process_uptime_seconds")
    for key in device_keys:
        _tm.unregister("device_hbm_bytes_in_use", device=key)
        _tm.unregister("device_hbm_bytes_limit", device=key)
        _tm.unregister("device_hbm_peak_bytes", device=key)
        _tm.unregister("device_live_buffer_count", device=key)
        _tm.unregister("tp_param_bytes", device=key)
    for label in duty_labels:
        _tm.unregister("executor_duty_cycle", device=label)
