"""Iteration-level continuous batching for autoregressive decode (the
Orca OSDI'22 scheduling discipline over this repo's executor stack).

The serving layers built in PRs 6-18 batch *stateless* requests: every
request is one executor call. Autoregressive decode is the opposite
workload — a sequence is hundreds of tiny dependent steps — and static
request batching wastes most of the machine on it: a batch formed at
admission time runs until its LONGEST member finishes, so every short
sequence's slot decodes dead air. This module schedules at the
*iteration* level instead: each loop pass assembles one mixed batch —
prefill chunks for newly admitted sequences, single-token steps for
running ones — so a finished sequence's slot is refilled on the very
next iteration.

Fixed compile geometry
----------------------
Every executor call has a warmup-time shape signature, so the PR-10
recompile sentinel stays silent in steady state:

- one **decode signature** per KV bucket: ``ids [B, 1]``,
  ``seqlens [B]``, per-layer KV buffers ``[B, Hkv, T, D]``;
- one **prefill signature** per KV bucket: the same with
  ``ids [B, S_pre]`` (``S_pre`` = the fixed prefill chunk);
- ``T`` walks a pow2-of-pages ladder (``page_size * 2^k`` capped at
  ``max_seq``), growing only when the longest live row crosses a
  bucket.

The model graph must use the share-buffer attention layout
(``GroupQueryAttention`` with ``past_present_share_buffer=1`` — see
onnx/importer.py): past buffers keep their max-bucket shape across
steps, new K/V scatter in place at each row's ``seqlens_k``-derived
write position, and per-row frontier masks keep junk slots (batch
padding, right-padded prefill tails, evicted predecessors' leftovers)
out of every softmax. Prompts longer than one chunk prefill chunk by
chunk; the final partial chunk re-feeds the tail of the previous chunk
(left-overlap) so its write position stays exact — recomputing a
suffix writes bit-identical keys, so overlap is free.

Both phases run through ONE :class:`BatchedExecutor` whose
``device_outputs`` keeps every present-KV leaf on device — only the
logits row crosses to host per step. Rows not participating in a call
(idle slots during prefill, prefilling slots during decode) get their
buffer rows restored by a jitted per-row merge select, because the
graph's scatter writes all B rows unconditionally.

Eviction = recompute
--------------------
KV capacity is policy, not hope: a :class:`PagedKVCache`
(runtime/kvcache.py) accounts fixed-size pages per sequence against a
budget sized off the perfwatch HBM gauges. When admission or growth
does not fit — or while the ``hbm_high_water`` latch holds — the LRU
resident sequence is evicted whole: its pages free, its slot clears,
and it re-enters the admission queue carrying prompt + everything
generated so far. Re-prefilling that history reproduces the same
greedy token stream (argmax over well-separated logits absorbs the
chunk-vs-step float formulation difference; the decode-smoke replay
asserts the digests), so eviction costs recompute time, never
correctness.

Static-batching A/B: ``static_batching=True`` runs the same machinery
under the admission-time discipline (admit only into an empty batch,
hold every slot until the whole batch finishes) — the honest baseline
``bench.py --only decode_serving`` compares against.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from synapseml_tpu.runtime import blackbox as _bb
from synapseml_tpu.runtime import structlog as _slog
from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.executor import BatchedExecutor
from synapseml_tpu.runtime.locksan import make_condition
from synapseml_tpu.runtime import kvcache as _kvc

__all__ = ["DecodeScheduler", "DecodeHandle"]

# token buckets for the per-step histograms: decode steps are small and
# fast; the default latency buckets top out too coarse at the low end
_STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5)


class DecodeHandle:
    """Caller's end of one sequence: a token queue plus final state.

    Iterate to stream tokens as the scheduler emits them, or call
    :meth:`result` to block for the whole generation. Thread-safe for
    one consumer."""

    def __init__(self, seq_id: str, prompt_len: int):
        self.seq_id = seq_id
        self.prompt_len = prompt_len
        self._q: "queue.Queue[Tuple[str, Any]]" = queue.Queue()
        self._tokens: List[int] = []
        self._finish_reason: Optional[str] = None
        self._error: Optional[BaseException] = None

    # scheduler side -----------------------------------------------------
    def _emit(self, token: int) -> None:
        self._q.put(("tok", int(token)))

    def _finish(self, reason: str) -> None:
        self._q.put(("done", reason))

    def _fail(self, exc: BaseException) -> None:
        self._q.put(("err", exc))

    # consumer side ------------------------------------------------------
    # The queue is the synchronization point: the scheduler only ever
    # puts, the one consumer only ever gets, and these fields belong to
    # the consumer's side of that handoff.
    def __iter__(self):
        while True:
            kind, val = self._q.get()
            if kind == "tok":
                self._tokens.append(val)  # synlint: disable=CC001
                yield val
            elif kind == "done":
                self._finish_reason = val  # synlint: disable=CC001
                return
            else:
                self._error = val  # synlint: disable=CC001
                raise val

    def result(self, timeout: Optional[float] = None
               ) -> Tuple[List[int], str]:
        """Block until the sequence finishes; returns
        ``(generated_tokens, finish_reason)``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._finish_reason is None and self._error is None:
            left = None if deadline is None \
                else max(0.0, deadline - time.monotonic())
            try:
                kind, val = self._q.get(timeout=left)
            except queue.Empty:
                raise TimeoutError(
                    f"decode sequence {self.seq_id} did not finish in "
                    f"{timeout}s") from None
            if kind == "tok":
                self._tokens.append(val)  # synlint: disable=CC001
            elif kind == "done":
                self._finish_reason = val  # synlint: disable=CC001
            else:
                self._error = val  # synlint: disable=CC001
        if self._error is not None:
            raise self._error
        return list(self._tokens), self._finish_reason or "completed"

    @property
    def finish_reason(self) -> Optional[str]:
        return self._finish_reason


class _Seq:
    __slots__ = ("id", "tokens", "prompt_len", "max_new", "deadline",
                 "handle", "state", "cached", "produced", "slot",
                 "arrival", "admitted_at", "recomputes")

    def __init__(self, seq_id: str, tokens: List[int], max_new: int,
                 deadline: Optional[float], handle: DecodeHandle):
        self.id = seq_id
        self.tokens = tokens          # prompt + everything generated
        self.prompt_len = len(tokens)
        self.max_new = max_new
        self.deadline = deadline      # absolute time.monotonic(), or None
        self.handle = handle
        self.state = "waiting"        # waiting -> prefill -> decode
        self.cached = 0               # tokens covered by the KV buffer
        self.produced = 0             # generated tokens emitted
        self.slot: Optional[int] = None
        self.arrival = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.recomputes = 0


class DecodeScheduler:
    """Continuous-batching decode over one imported decoder graph.

    ``graph``: an ``ImportedGraph`` (onnx/importer.py) in the
    share-buffer layout — inputs ``input_ids [B,S]``, ``seqlens_k [B]``
    and per-layer ``past_key_*/past_value_* [B, Hkv, T, D]`` pairs,
    outputs logits first then the matching present pairs (the shape
    ``tiny_decoder`` in onnx/zoo.py builds and ORT-GenAI exports
    carry). Geometry, capacity, and policy knobs default from the
    ``SYNAPSEML_DECODE_*`` / ``SYNAPSEML_KV_*`` environment
    (docs/knobs.md)."""

    def __init__(self, graph, *, name: str = "decode",
                 max_batch: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 page_size: Optional[int] = None,
                 max_seq: Optional[int] = None,
                 capacity_bytes: Optional[int] = None,
                 max_waiting: Optional[int] = None,
                 static_batching: bool = False,
                 devices=None, cache_key: Optional[str] = None,
                 cache_dir: Optional[str] = None):
        self.name = name
        self.B = int(max_batch if max_batch is not None
                     else os.environ.get(
                         "SYNAPSEML_DECODE_MAX_BATCH", "4"))
        self.S_pre = int(prefill_chunk if prefill_chunk is not None
                         else os.environ.get(
                             "SYNAPSEML_DECODE_PREFILL_CHUNK", "16"))
        self.page = int(page_size if page_size is not None
                        else os.environ.get("SYNAPSEML_KV_PAGE", "16"))
        self.max_seq = int(max_seq if max_seq is not None
                           else os.environ.get(
                               "SYNAPSEML_DECODE_MAX_SEQ", "128"))
        self.max_waiting = int(
            max_waiting if max_waiting is not None
            else os.environ.get("SYNAPSEML_DECODE_MAX_WAITING", "256"))
        self.wait_slo_s = float(os.environ.get(
            "SYNAPSEML_DECODE_WAIT_SLO_MS", "500")) / 1e3
        self.static_batching = bool(static_batching)
        if self.B < 1 or self.S_pre < 1 or self.page < 1:
            raise ValueError("max_batch, prefill_chunk and page_size "
                             "must be positive")
        if self.max_seq < self.S_pre:
            raise ValueError(f"max_seq={self.max_seq} below the prefill "
                             f"chunk {self.S_pre}")

        self._g = graph
        (self._ids_name, self._seqlens_name, self._kv_names,
         self._kv_shapes) = self._introspect(graph)
        self.n_layers = len(self._kv_names) // 2
        _, self.kv_heads, _, self.head_dim = self._kv_shapes[0]
        kv_itemsize = 4  # f32 buffers (graph dtype)
        bytes_per_token = (len(self._kv_names) * self.kv_heads
                           * self.head_dim * kv_itemsize)
        self.kv = _kvc.PagedKVCache(self.page, bytes_per_token,
                                    capacity_bytes=capacity_bytes,
                                    name=name)
        # KV bucket ladder: page * 2^k, capped at (and always including)
        # max_seq — every compiled T the scheduler can ever run
        ladder = []
        t = self.page
        while t < self.max_seq:
            ladder.append(t)
            t <<= 1
        ladder.append(self.max_seq)
        self.t_ladder = ladder

        import jax
        import jax.numpy as jnp

        def _apply(p, ids, seqlens, *kv):
            named = {self._ids_name: ids, self._seqlens_name: seqlens}
            named.update(dict(zip(self._kv_names, kv)))
            return self._g.apply(p, **named)

        n_out = 1 + len(self._kv_names)
        self._ex = BatchedExecutor(
            _apply, static_batch=self.B, bound_args=(graph.params,),
            devices=devices, cache_key=cache_key, cache_dir=cache_dir,
            device_outputs=range(1, n_out))

        # per-row merge select: the graph scatters every row of the
        # shared buffers, so rows that did not participate in a call
        # are restored from the pre-call buffers. One compile per T
        # bucket (warmed); kv lists are pytrees, mask is [B] bool
        def _merge(mask, new_kv, old_kv):
            m = mask[:, None, None, None]
            return [jnp.where(m, n, o) for n, o in zip(new_kv, old_kv)]

        self._merge = jax.jit(_merge)
        # bucket growth: zero-extend every buffer's T axis. One compile
        # per (T_from -> T_to) ladder step (warmed)
        def _grow(kv, pad):
            return [jnp.pad(a, ((0, 0), (0, 0), (0, pad), (0, 0)))
                    for a in kv]

        self._grow = jax.jit(_grow, static_argnums=1)
        self._zeros = jax.jit(
            lambda t: [jnp.zeros((self.B, self.kv_heads, t,
                                  self.head_dim), jnp.float32)
                       for _ in range(len(self._kv_names))],
            static_argnums=0)

        # live batch state (loop thread only)
        self._slots: List[Optional[_Seq]] = [None] * self.B
        self._kv_bufs: Optional[List[Any]] = None
        self._t_bucket = self.t_ladder[0]
        self._seqs: Dict[str, _Seq] = {}

        self._cv = make_condition("DecodeScheduler._cv")
        self._waiting: deque = deque()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._seq_counter = 0
        self._warmed = False

        # telemetry (docs/observability.md "Decode serving")
        self._m_seqs = _tm.counter("decode_sequences_total", server=name)
        self._m_tokens = _tm.counter("decode_tokens_total", server=name)
        self._m_steps = {
            ph: _tm.counter("decode_steps_total", server=name, phase=ph)
            for ph in ("prefill", "decode")}
        self._m_step_s = {
            ph: _tm.histogram("decode_step_seconds",
                              buckets=_STEP_BUCKETS, server=name,
                              phase=ph)
            for ph in ("prefill", "decode")}
        self._m_ttft = _tm.histogram("decode_ttft_seconds", server=name)
        self._m_wait = _tm.histogram("decode_queue_wait_seconds",
                                     server=name)
        self._m_finished: Dict[str, _tm.Counter] = {}
        _tm.gauge_fn("decode_active_sequences",
                     lambda: float(sum(s is not None
                                       for s in self._slots)),
                     server=name)
        _tm.gauge_fn("decode_waiting_sequences",
                     lambda: float(len(self._waiting)), server=name)
        # the autoscaler's starvation signal: recent admission wait as
        # a burn rate against the wait SLO — duty-cycle alone misreads
        # a decode fleet whose short steps keep chips busy while the
        # admission queue ages out (runtime/autoscale.py)
        self._wait_window: deque = deque()  # (ts, wait_s)
        _tm.gauge_fn("decode_queue_wait_burn", self._wait_burn,
                     server=name)

    # -- graph introspection --------------------------------------------
    @staticmethod
    def _introspect(graph):
        ids_name = seqlens_name = None
        kv: List[Tuple[str, List[Optional[int]]]] = []
        for nm in graph.input_names:
            dtype, shape = graph.input_info.get(nm, (None, []))
            low = nm.lower()
            if "past" in low and ("key" in low or "value" in low):
                kv.append((nm, shape))
            elif seqlens_name is None and "seqlens" in low:
                seqlens_name = nm
            elif ids_name is None and len(shape) == 2:
                ids_name = nm
        if ids_name is None or seqlens_name is None or not kv:
            raise ValueError(
                "DecodeScheduler needs a share-buffer decoder graph: "
                "token ids [B,S], seqlens_k [B], and past_key/past_value "
                f"buffer pairs — got inputs {graph.input_names}. "
                "Graphs without seqlens_k (plain concat KV exports) "
                "serve through ONNXModel, not the decode scheduler.")
        if len(kv) % 2:
            raise ValueError(f"unpaired past KV inputs: {[n for n, _ in kv]}")
        shapes = []
        for nm, shape in kv:
            if len(shape) != 4 or shape[1] is None or shape[3] is None:
                raise ValueError(
                    f"past buffer {nm} must be [B, Hkv, T, D] with "
                    f"concrete Hkv/D, got {shape}")
            shapes.append(shape)
        if len({(s[1], s[3]) for s in shapes}) != 1:
            raise ValueError("past buffers disagree on [Hkv, D]: "
                             f"{shapes}")
        return ids_name, seqlens_name, [n for n, _ in kv], shapes

    # -- lifecycle -------------------------------------------------------
    def warmup(self) -> Dict[str, Any]:
        """AOT-compile every (phase, T-bucket) signature plus the merge/
        grow/zeros helpers, then arm the recompile sentinel — after
        this, any lazy compile on the step path is a counted bug."""
        import jax.numpy as jnp

        report: Dict[str, Any] = {"signatures": []}
        kv_specs_t = {}
        for t in self.t_ladder:
            kv_specs_t[t] = [((self.kv_heads, t, self.head_dim),
                              np.float32)] * len(self._kv_names)
        for t in self.t_ladder:
            for s, phase in ((self.S_pre, "prefill"), (1, "decode")):
                args_like = ([((s,), np.int64), ((), np.int32)]
                             + kv_specs_t[t])
                rep = self._ex.warmup(args_like)
                report["signatures"].append(
                    {"phase": phase, "S": s, "T": t,
                     "entries": [e.get("status") for e in rep.entries]})
            # helper jits at this bucket: merge + zeros (+ grow into the
            # next rung) — outside the executor, warmed here so the
            # steady-state loop never compiles
            bufs = self._zeros(t)
            mask = jnp.zeros((self.B,), bool)
            self._merge(mask, bufs, bufs)
        for t_from, t_to in zip(self.t_ladder, self.t_ladder[1:]):
            self._grow(self._zeros(t_from), t_to - t_from)
        self._warmed = True
        return report

    def start(self) -> None:
        if self._thread is None:
            # synlint: disable=RL001 - _loop is its own supervision
            # boundary: every iteration runs under a catch-all that
            # fails the live handles and resets batch state, so an
            # escaped exception surfaces to callers, never dies silent
            self._thread = threading.Thread(
                target=self._loop, name=f"decode-{self.name}",
                daemon=True)
            self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        self._ex.close()
        # drop the instance-scope gauges so a closed scheduler neither
        # leaks through the registry nor exports stale series
        for series in ("decode_active_sequences",
                       "decode_waiting_sequences",
                       "decode_queue_wait_burn"):
            _tm.unregister(series, server=self.name)
        self.kv.close()

    def drain(self, timeout_s: float) -> bool:
        """Wait for every admitted sequence to finish (SIGTERM path);
        new submits are refused once ``close`` flips the stop flag, so
        callers shed first, then drain."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if not self._seqs:
                    return True
            time.sleep(0.01)
        return False

    # -- submission ------------------------------------------------------
    def submit(self, prompt_tokens: Sequence[int], max_new_tokens: int,
               deadline_s: Optional[float] = None,
               seq_id: Optional[str] = None) -> DecodeHandle:
        """Admit one sequence; returns a :class:`DecodeHandle` streaming
        its generated tokens. ``deadline_s`` is a relative budget — a
        sequence still unfinished then stops with reason ``deadline``
        (partial output, never an error). Raises ``RuntimeError`` when
        the admission queue is full (serving maps it to 429) and
        ``ValueError`` for prompts the geometry cannot hold."""
        toks = [int(t) for t in prompt_tokens]
        if not toks:
            raise ValueError("empty prompt")
        if len(toks) + max(1, int(max_new_tokens)) > self.max_seq:
            raise ValueError(
                f"prompt ({len(toks)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_seq={self.max_seq}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with self._cv:
            if self._stop:
                raise RuntimeError("decode scheduler is stopped")
            if len(self._waiting) >= self.max_waiting:
                raise RuntimeError("decode admission queue full")
            if seq_id is None:
                self._seq_counter += 1
                seq_id = f"{self.name}-{self._seq_counter}"
            handle = DecodeHandle(seq_id, len(toks))
            seq = _Seq(seq_id, toks, int(max_new_tokens),
                       None if deadline_s is None
                       else time.monotonic() + float(deadline_s), handle)
            self._seqs[seq_id] = seq
            self._waiting.append(seq)
            self._m_seqs.inc()
            self._cv.notify_all()
        self.start()
        return handle

    # -- scheduler loop --------------------------------------------------
    def _loop(self) -> None:
        import jax.numpy as jnp

        while True:
            with self._cv:
                while (not self._stop and not self._waiting
                       and not any(self._slots)):
                    # synlint: disable=CC003 - Condition.wait releases
                    # the lock while blocked; submitters are not held out
                    self._cv.wait(0.5)
                if self._stop and not self._waiting \
                        and not any(self._slots):
                    return
            try:
                self._iteration()
            except Exception as e:  # noqa: BLE001 - fail sequences, not thread
                _bb.record("decode_loop_error", level="error",
                           server=self.name, error=repr(e))
                _slog.log("error", "decode_loop_error",
                          server=self.name, error=repr(e))
                with self._cv:
                    for seq in list(self._seqs.values()):
                        seq.handle._fail(e)
                        self._seqs.pop(seq.id, None)
                        self.kv.release(seq.id)
                    self._waiting.clear()
                    self._slots = [None] * self.B
                    self._kv_bufs = None

    def _iteration(self) -> None:
        self._expire_deadlines()
        # HBM backpressure: while any device holds above the high-water
        # line, pause admission and shed one LRU resident per iteration
        pressure = _kvc.under_pressure()
        if pressure:
            victim = self.kv.evict_lru(reason="hbm_high_water")
            if victim is not None:
                self._evict_seq(victim)
        if not pressure:
            self._admit()
        did = False
        if any(s is not None and s.state == "prefill"
               for s in self._slots):
            self._prefill_step()
            did = True
        if any(s is not None and s.state == "decode"
               for s in self._slots):
            self._decode_step()
            did = True
        if not did:
            # nothing runnable (e.g. everything waiting under pressure):
            # don't spin
            time.sleep(0.001)

    def _expire_deadlines(self) -> None:
        now = time.monotonic()
        with self._cv:
            expired = [s for s in list(self._waiting)
                       if s.deadline is not None and now > s.deadline]
            for s in expired:
                self._waiting.remove(s)
                self._seqs.pop(s.id, None)
                s.handle._finish("deadline")
                self._finished_counter("deadline").inc()
        for i, s in enumerate(self._slots):
            if s is not None and s.deadline is not None \
                    and now > s.deadline:
                self._retire(s, "deadline")

    def _admit(self) -> None:
        if self.static_batching and any(self._slots):
            # admission-time batching baseline: a new batch forms only
            # once the previous one fully drained
            return
        while True:
            with self._cv:
                if not self._waiting:
                    return
                free = [i for i, s in enumerate(self._slots)
                        if s is None]
                if not free:
                    return
                seq = self._waiting[0]
            if (self.kv.pages_for(len(seq.tokens) + 1)
                    > self.kv.capacity_pages):
                # can never fit, even alone — fail it now instead of
                # retrying forever at the head of the queue
                with self._cv:
                    self._waiting.popleft()
                    self._seqs.pop(seq.id, None)
                seq.handle._finish("kv_capacity")
                self._finished_counter("kv_capacity").inc()
                continue
            # admission NEVER evicts a running sequence: an evicted row
            # lands at the queue front and the next admission pass would
            # evict someone for it in turn — a livelock that admits
            # forever and steps never. Waiting sequences enter only on
            # free pages; capacity pressure flows the other way (decode
            # growth + the HBM latch evict INTO the queue, and the
            # grown row always steps next, so progress is guaranteed).
            if not self.kv.fits(len(seq.tokens) + 1):
                return  # does not fit yet — retry next iteration
            self.kv.acquire(seq.id, len(seq.tokens) + 1)
            with self._cv:
                self._waiting.popleft()
                slot = next(i for i, s in enumerate(self._slots)
                            if s is None)
                seq.slot = slot
                seq.state = "prefill"
                seq.cached = 0
                seq.admitted_at = time.monotonic()
                self._slots[slot] = seq
            wait = seq.admitted_at - seq.arrival
            if seq.recomputes == 0:
                self._m_wait.observe(wait)
                # the burn-rate window is read from scrape threads
                # (_wait_burn): every touch holds the scheduler lock
                with self._cv:
                    self._wait_window.append((seq.admitted_at, wait))
            self._ensure_bucket(min(len(seq.tokens) + 1, self.max_seq))
            if self.static_batching and len(
                    [s for s in self._slots if s is not None]) >= self.B:
                return

    def _evict_seq(self, seq_id: str) -> None:
        """Evicted by the cache: clear the slot, push the sequence —
        full history intact — back to the FRONT of the admission queue
        for recompute."""
        seq = self._seqs.get(seq_id)
        if seq is None or seq.slot is None:
            return
        with self._cv:
            self._slots[seq.slot] = None
            seq.slot = None
            seq.state = "waiting"
            seq.cached = 0
            seq.recomputes += 1
            self._waiting.appendleft(seq)
        self.kv.note_recompute(seq_id)
        _slog.log("info", "decode_evicted", server=self.name,
                  seq=seq_id, tokens=len(seq.tokens),
                  produced=seq.produced)

    def _retire(self, seq: _Seq, reason: str) -> None:
        with self._cv:
            if seq.slot is not None:
                self._slots[seq.slot] = None
                seq.slot = None
            self._seqs.pop(seq.id, None)
        self.kv.release(seq.id)
        seq.handle._finish(reason)
        self._finished_counter(reason).inc()

    def _finished_counter(self, reason: str) -> _tm.Counter:
        c = self._m_finished.get(reason)
        if c is None:
            c = _tm.counter("decode_finished_total", server=self.name,
                            reason=reason)
            self._m_finished[reason] = c
        return c

    # -- geometry --------------------------------------------------------
    def _ensure_bucket(self, need_t: int) -> None:
        """Grow the live buffers to the first ladder rung >= need_t.
        Never shrinks — re-bucketing down would change active rows'
        signatures for no memory win (the buffers are already paid)."""
        target = self._t_bucket
        for t in self.t_ladder:
            if t >= need_t:
                target = max(target, t)
                break
        else:
            target = self.t_ladder[-1]
        # the KV buffers and T-bucket are live batch state owned by the
        # loop thread alone (no reader elsewhere): lock-free by design
        if self._kv_bufs is None:
            # synlint: disable=CC001
            self._kv_bufs = self._zeros(target)
            self._t_bucket = target
            return
        while self._t_bucket < target:
            nxt = self.t_ladder[self.t_ladder.index(self._t_bucket) + 1]
            # synlint: disable=CC001
            self._kv_bufs = self._grow(self._kv_bufs,
                                       nxt - self._t_bucket)
            self._t_bucket = nxt

    # -- steps -----------------------------------------------------------
    def _prefill_step(self) -> None:
        import jax.numpy as jnp

        rows = [(i, s) for i, s in enumerate(self._slots)
                if s is not None and s.state == "prefill"]
        ids = np.zeros((self.B, self.S_pre), np.int64)
        seqlens = np.zeros((self.B,), np.int32)
        mask = np.zeros((self.B,), bool)
        plan: List[Tuple[_Seq, int, int]] = []  # (seq, s1, last_row)
        for i, seq in rows:
            n = len(seq.tokens)
            s0 = seq.cached
            if n - s0 >= self.S_pre:
                # one full chunk at [s0, s0 + S_pre)
                s1 = s0 + self.S_pre
                ids[i] = seq.tokens[s0:s1]
                last = self.S_pre - 1
            elif n <= self.S_pre:
                # short prompt: single right-padded chunk at position 0
                s1 = n
                ids[i, :n] = seq.tokens
                last = n - 1
            else:
                # final partial chunk: left-overlap the previous chunk's
                # tail so the write position stays exact — re-fed
                # positions recompute bit-identical keys
                s1 = n
                ids[i] = seq.tokens[n - self.S_pre:n]
                last = self.S_pre - 1
            seqlens[i] = s1 - 1
            mask[i] = True
            plan.append((seq, s1, last))
            self._ensure_bucket(min(s1 + 1, self.max_seq))
        t0 = time.monotonic()
        out = self._ex.submit(ids, seqlens, *self._kv_bufs).result()
        logits, new_kv = out[0], list(out[1:])
        # loop-thread-only batch state (see _ensure_bucket)
        # synlint: disable=CC001
        self._kv_bufs = self._merge(jnp.asarray(mask), new_kv,
                                    self._kv_bufs)
        dt = time.monotonic() - t0
        self._m_steps["prefill"].inc()
        self._m_step_s["prefill"].observe(dt)
        for seq, s1, last in plan:
            seq.cached = s1  # synlint: disable=CC001
            self.kv.touch(seq.id)
            if seq.cached >= len(seq.tokens):
                # prompt (or recompute history) fully cached: the last
                # valid row's logits predict the next token
                tok = int(np.argmax(logits[seq.slot, last]))
                seq.state = "decode"  # synlint: disable=CC001
                if seq.produced == 0 and seq.admitted_at is not None \
                        and seq.recomputes == 0:
                    self._m_ttft.observe(time.monotonic() - seq.arrival)
                self._emit_token(seq, tok)

    def _decode_step(self) -> None:
        import jax.numpy as jnp

        rows = [(i, s) for i, s in enumerate(self._slots)
                if s is not None and s.state == "decode"]
        if not rows:
            return
        # page accounting + bucket growth BEFORE the step: row i writes
        # at position cached, needing cached+1 slots
        for i, seq in list(rows):
            need = seq.cached + 1
            evicted = self.kv.acquire(seq.id, need)
            if evicted is None:
                # cannot fit even after evicting everything else — the
                # sequence outgrew total capacity; stop it with what it
                # has rather than thrash
                self._retire(seq, "kv_capacity")
                rows.remove((i, seq))
                continue
            for v in evicted:
                self._evict_seq(v)
                rows = [(j, s) for j, s in rows if s.id != v]
            self._ensure_bucket(need)
        if not rows:
            return
        ids = np.zeros((self.B, 1), np.int64)
        seqlens = np.zeros((self.B,), np.int32)
        mask = np.zeros((self.B,), bool)
        for i, seq in rows:
            ids[i, 0] = seq.tokens[seq.cached]
            seqlens[i] = seq.cached  # total valid = cached + 1
            mask[i] = True
        t0 = time.monotonic()
        out = self._ex.submit(ids, seqlens, *self._kv_bufs).result()
        logits, new_kv = out[0], list(out[1:])
        # loop-thread-only batch state (see _ensure_bucket)
        # synlint: disable=CC001
        self._kv_bufs = self._merge(jnp.asarray(mask), new_kv,
                                    self._kv_bufs)
        dt = time.monotonic() - t0
        self._m_steps["decode"].inc()
        self._m_step_s["decode"].observe(dt)
        for i, seq in rows:
            seq.cached += 1  # synlint: disable=CC001
            self.kv.touch(seq.id)
            tok = int(np.argmax(logits[i, 0]))
            self._emit_token(seq, tok)

    def _emit_token(self, seq: _Seq, tok: int) -> None:
        seq.tokens.append(tok)
        seq.produced += 1
        seq.handle._emit(tok)
        self._m_tokens.inc()
        if seq.produced >= seq.max_new:
            self._retire(seq, "completed")
        elif len(seq.tokens) >= self.max_seq:
            self._retire(seq, "max_seq")

    # -- autoscaler signal ----------------------------------------------
    def _wait_burn(self) -> float:
        """Mean admission wait over the trailing 60s as a burn rate
        against the wait SLO — >1 means sequences wait longer than the
        target before their first prefill (a starved decode fleet)."""
        now = time.monotonic()
        with self._cv:
            while (self._wait_window
                   and now - self._wait_window[0][0] > 60.0):
                self._wait_window.popleft()
            if not self._wait_window or self.wait_slo_s <= 0:
                return 0.0
            mean = (sum(w for _, w in self._wait_window)
                    / len(self._wait_window))
        return mean / self.wait_slo_s

    # introspection for tests / debug endpoints
    def stats(self) -> Dict[str, Any]:
        with self._cv:
            return {
                "active": sum(s is not None for s in self._slots),
                "waiting": len(self._waiting),
                "t_bucket": self._t_bucket,
                "pages_in_use": self.kv.pages_in_use(),
                "capacity_pages": self.kv.capacity_pages,
                "warmed": self._warmed,
            }
