"""Incident capture: tail-based payload retention for deterministic replay.

The trace archive (``runtime/tracearchive.py``) keeps the *timing* of
the requests worth keeping; the flight recorder keeps the *process
state* around an incident. Neither keeps the request itself — so
"does this 500 reproduce?" and "did the rollout change scores?" were
unanswerable the moment the reply left the socket. This module is the
missing forensic surface: at reply time, when the outcome is known
(the same Dapper-style tail decision the trace archive makes), the
request's **exact input bytes** land in a JSONL capture file that
``tools/replay.py`` can re-score offline and diff bit-for-bit.

Retention policy (:func:`classify`):

- **every SLO-breaching request is kept**: a 5xx reply
  (``error_5xx``), an admission/drain shed (429/503, ``shed``), a
  deadline expiry or reply timeout (504, ``deadline``), a poison
  payload the bisection isolated (400, ``poison``), or a roundtrip
  over the latency threshold (``SYNAPSEML_SLO_LATENCY_MS``,
  ``slo_latency``);
- **a head-sampled healthy fraction** rides along
  (``SYNAPSEML_CAPTURE_HEAD_SAMPLE``, default 0.01 — every Nth healthy
  reply), so a replay run can assert what *normal* scoring looks like
  next to the breaches;
- everything else takes the lock-free drop path
  (``capture_dropped_total``) — the healthy hot path pays a handful of
  integer compares, and with ``SYNAPSEML_CAPTURE=0`` a single flag
  test.

Each record is **self-contained** for replay: the payload bytes (utf-8
text inline, else base64), best-effort shapes/dtypes of the JSON
feature lists, rid/trace_id/span_id, the model content hash (the same
``content_hash`` ingredient the compile-cache key uses — replay
verifies it against the model file it was handed), the ``/debug/build``
git sha, the reply status, and the sha256 **output digest** computed
from the reply bytes (also echoed to clients as ``X-Output-Digest``
and stamped on the span). The reply body itself is retained up to
``SYNAPSEML_CAPTURE_REPLY_BYTES`` (default 4096; ``SYNAPSEML_CAPTURE_
OUTPUTS=0`` disables) so replay can report a max-abs-diff, not just a
digest mismatch.

Files: ``<dump_dir>/capture-<pid>.jsonl`` beside the flight dumps —
one volume holds the replica's whole forensic story. Size-capped
(``SYNAPSEML_CAPTURE_MAX_BYTES``, default 16 MiB) with atomic
``os.replace`` rotation to ``.1``; appends are single writes and
:func:`scan` tolerates one torn tail line after a crash. Writes happen
at capture RATE on the reply handler thread AFTER the response is
committed — a slow dump volume delays forensics, never a reply.
"""
from __future__ import annotations

import base64
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.locksan import make_lock

__all__ = [
    "maybe_capture", "classify", "capture_path", "scan",
    "tail_summaries", "configure", "reset", "enabled", "set_enabled",
    "set_model_hash", "model_hash", "DEFAULT_MAX_BYTES",
    "REASON_5XX", "REASON_SHED", "REASON_DEADLINE", "REASON_POISON",
    "REASON_LATENCY", "REASON_HEAD",
]

DEFAULT_MAX_BYTES = 16 * 1024 * 1024
DEFAULT_REPLY_BYTES = 4096
DEFAULT_PAYLOAD_BYTES = 64 * 1024

REASON_5XX = "error_5xx"
REASON_SHED = "shed"
REASON_DEADLINE = "deadline"
REASON_POISON = "poison"
REASON_LATENCY = "slo_latency"
REASON_HEAD = "head_sample"

# pre-register every reason series at import (the recompile-sentinel
# pattern): a scrape sees all classes at 0 before the first incident,
# so CI can assert a labeled VALUE delta instead of a substring
_REASONS = (REASON_5XX, REASON_SHED, REASON_DEADLINE, REASON_POISON,
            REASON_LATENCY, REASON_HEAD)
_M_RECORDS = {r: _tm.counter("capture_records_total", reason=r)
              for r in _REASONS}
_M_DROPPED = _tm.counter("capture_dropped_total")
_M_ROTATIONS = _tm.counter("capture_rotations_total")
_M_WRITE_FAIL = _tm.counter("capture_write_failures_total")


def _head_every_from_env() -> int:
    """Healthy-reply sampling stride from ``SYNAPSEML_CAPTURE_HEAD_
    SAMPLE`` (a fraction; 0.01 -> every 100th healthy reply; 0 or
    malformed -> no healthy sampling)."""
    raw = os.environ.get("SYNAPSEML_CAPTURE_HEAD_SAMPLE", "0.01").strip()
    try:
        frac = float(raw)
    except ValueError:
        return 0
    if not 0.0 < frac <= 1.0:
        return 0
    return max(1, round(1.0 / frac))


def _max_bytes_from_env() -> int:
    """Malformed or non-positive degrades to the default (the trace
    archive's policy: a bad env var must never crash a server at
    import, and a negative cap would rotate on every append)."""
    raw = os.environ.get("SYNAPSEML_CAPTURE_MAX_BYTES", "").strip()
    try:
        n = int(raw) if raw else DEFAULT_MAX_BYTES
    except ValueError:
        return DEFAULT_MAX_BYTES
    return max(4096, n) if n > 0 else DEFAULT_MAX_BYTES


def _payload_cap_from_env() -> int:
    """Per-record payload byte cap (``SYNAPSEML_CAPTURE_PAYLOAD_
    BYTES``): a 100 MB breaching POST must not blow past the file's
    own size cap in one record, nor serialize every handler thread
    behind a multi-second append under the module lock. An over-cap
    payload is NOTED (``payload_truncated``), never stored truncated —
    a half payload would replay to a meaningless divergence."""
    raw = os.environ.get("SYNAPSEML_CAPTURE_PAYLOAD_BYTES", "").strip()
    try:
        n = int(raw) if raw else DEFAULT_PAYLOAD_BYTES
    except ValueError:
        return DEFAULT_PAYLOAD_BYTES
    return max(1024, n)


def _reply_cap_from_env() -> int:
    """Per-record retained-reply byte cap; ``SYNAPSEML_CAPTURE_
    OUTPUTS=0`` disables reply retention entirely (digests alone still
    gate determinism — retained bodies only add the max-abs-diff)."""
    if os.environ.get("SYNAPSEML_CAPTURE_OUTPUTS", "") == "0":
        return 0
    raw = os.environ.get("SYNAPSEML_CAPTURE_REPLY_BYTES", "").strip()
    try:
        n = int(raw) if raw else DEFAULT_REPLY_BYTES
    except ValueError:
        return DEFAULT_REPLY_BYTES
    return max(0, n)


def _threshold_from_env() -> float:
    raw = os.environ.get("SYNAPSEML_SLO_LATENCY_MS", "").strip()
    try:
        ms = float(raw) if raw else 250.0
    except ValueError:
        ms = 250.0
    return ms / 1e3


class _State:
    """Module switchboard (the tracearchive pattern): env knobs
    captured once, all tolerant; :func:`configure` retunes for tests
    and embedding callers."""

    def __init__(self):
        self.enabled = os.environ.get("SYNAPSEML_CAPTURE", "") != "0"
        self.dir: Optional[str] = None  # None = beside the flight dumps
        self.max_bytes = _max_bytes_from_env()
        self.head_every = _head_every_from_env()
        self.reply_cap = _reply_cap_from_env()
        self.payload_cap = _payload_cap_from_env()
        self.lock = make_lock("_State.lock")
        self.head_counter = itertools.count(1)
        self.default_threshold_s = _threshold_from_env()
        # the serving entry stamps the scoring model's content hash
        # here (None = no model, e.g. the echo pipeline) — every
        # record carries it so replay can refuse the wrong model
        self.model_hash: Optional[str] = None


_S = _State()


def enabled() -> bool:
    return _S.enabled


def set_enabled(on: bool) -> bool:
    prev = _S.enabled
    _S.enabled = bool(on)
    return prev


def set_model_hash(h: Optional[str]) -> Optional[str]:
    """Stamp the scoring model's content hash (the compile-cache
    ``content_hash`` over the raw model bytes) into every subsequent
    record; returns the previous value. The serving entry calls this
    when it builds the model pipeline."""
    prev = _S.model_hash
    _S.model_hash = h
    return prev


def model_hash() -> Optional[str]:
    return _S.model_hash


def configure(directory: Optional[str] = None,
              max_bytes: Optional[int] = None,
              head_every: Optional[int] = None,
              reply_cap: Optional[int] = None,
              payload_cap: Optional[int] = None):
    """Repoint/retune the sink (tests, embedding callers).
    ``head_every=0`` disables healthy sampling; ``reply_cap=0``
    disables reply-body retention; ``directory=None`` keeps the
    current one (the flight dump dir by default)."""
    with _S.lock:
        if directory is not None:
            _S.dir = directory
        if max_bytes is not None:
            _S.max_bytes = max(4096, int(max_bytes))
        if head_every is not None:
            _S.head_every = max(0, int(head_every))
        if reply_cap is not None:
            _S.reply_cap = max(0, int(reply_cap))
        if payload_cap is not None:
            _S.payload_cap = max(1024, int(payload_cap))


def reset():
    """Tests only: drop the current capture files and restart the
    head-sample stride."""
    with _S.lock:
        _S.head_counter = itertools.count(1)
        path = _capture_path()
        for p in (path, path + ".1"):
            try:
                os.remove(p)
            except OSError:
                pass


def _capture_path() -> str:
    # lock-free: reads only the GIL-atomic _S.dir reference. The
    # scrape-time capture_bytes gauge stats this path — taking the
    # module lock here would park every /metrics scrape behind the
    # dump-volume file writes maybe_capture does under it, degrading
    # the monitoring surface exactly during the incidents it exists
    # for
    d = _S.dir
    if d is None:
        # beside the flight dumps — resolved per call because the
        # serving entry's --dump-dir lands after import
        from synapseml_tpu.runtime import blackbox as _bb

        d = _bb.dump_dir()
    return os.path.join(d, f"capture-{os.getpid()}.jsonl")


def capture_path() -> str:
    """The live capture file's path (rotated sibling: ``<path>.1``)."""
    return _capture_path()


def _size() -> float:
    """Scrape-time gauge sampler: live capture file size in bytes."""
    try:
        return float(os.path.getsize(capture_path()))
    except OSError:
        return 0.0


_tm.gauge_fn("capture_bytes", _size)


def classify(status: int, latency_s: float,
             threshold_s: Optional[float] = None) -> Optional[str]:
    """The breach half of the retention decision, pure and exported
    for tests: the retention reason for one completed reply, or None
    when it is healthy (the head-sample stride then gets its say in
    :func:`maybe_capture`). Order matters: 504 is a deadline before it
    is a 5xx, 429/503 are deliberate sheds, any other 5xx is an error,
    400 is the poison-bisection verdict, and a healthy status over the
    latency threshold still breached the SLO."""
    if threshold_s is None:
        threshold_s = _S.default_threshold_s
    if status == 504:
        return REASON_DEADLINE
    if status in (429, 503):
        return REASON_SHED
    if status >= 500:
        return REASON_5XX
    if status == 400:
        return REASON_POISON
    if threshold_s > 0 and latency_s > threshold_s:
        return REASON_LATENCY
    return None


def _payload_fields(entity: bytes) -> Dict[str, Any]:
    """Self-containment for replay: the payload bytes (utf-8 text
    inline — the JSON-body common case stays grep-able — else base64)
    plus best-effort shapes/dtypes of top-level JSON list fields (the
    feature vectors a replay report names without re-parsing)."""
    out: Dict[str, Any] = {}
    try:
        out["payload"] = entity.decode("utf-8")
    except UnicodeDecodeError:
        out["payload_b64"] = base64.b64encode(entity).decode("ascii")
        return out
    try:
        doc = json.loads(out["payload"])
    except json.JSONDecodeError:
        return out
    if isinstance(doc, dict):
        shapes: Dict[str, List[int]] = {}
        dtypes: Dict[str, str] = {}
        for key, val in doc.items():
            shape: List[int] = []
            leaf = val
            while isinstance(leaf, list):
                shape.append(len(leaf))
                leaf = leaf[0] if leaf else None
            if shape:
                shapes[key] = shape
                dtypes[key] = type(leaf).__name__
        if shapes:
            out["payload_shapes"] = shapes
            out["payload_dtypes"] = dtypes
    return out


def _build_sha() -> Optional[str]:
    """The /debug/build git sha, resolved once (lazy import: serving
    imports this module at its own import time, so the reverse edge
    must stay deferred — and by the first capture, serving is
    loaded)."""
    global _BUILD_SHA
    if _BUILD_SHA is _UNRESOLVED:
        try:
            from synapseml_tpu.io.serving import _build_static

            _BUILD_SHA = _build_static().get("git_sha")
        except Exception:  # noqa: BLE001 - best-effort provenance
            _BUILD_SHA = None
    return _BUILD_SHA


_UNRESOLVED = object()
_BUILD_SHA: Any = _UNRESOLVED


def _rotate_locked(path: str):
    """Atomic rotation: the live file becomes ``.1`` (replacing the
    previous one); a concurrent reader sees the old file or the new
    pair, never a torn state."""
    try:
        os.replace(path, path + ".1")
        _M_ROTATIONS.inc()
    except OSError:
        _M_WRITE_FAIL.inc()


def maybe_capture(request: Any, status: int, latency_s: float, *,
                  rid: str = "", trace_id: str = "", span_id: str = "",
                  origin: str = "", digest: str = "",
                  reply_entity: Optional[bytes] = None,
                  threshold_s: Optional[float] = None) -> Optional[str]:
    """The retention decision for one completed request: capture when
    it breached (:func:`classify`) or when the head-sample stride
    picked this healthy one. ``request`` is the
    :class:`~synapseml_tpu.io.http.HTTPRequestData` in hand at reply
    time; ``digest`` the sha256 of the reply bytes (what
    ``X-Output-Digest`` carried); ``reply_entity`` the reply body,
    retained up to the configured cap so replay can diff values, not
    just digests. Returns the retention reason when a record was
    written, else None. Never raises — capture must not make a reply
    path worse."""
    if not _S.enabled or not _tm.enabled():
        return None
    reason = classify(status, latency_s, threshold_s)
    if reason is None:
        if not (_S.head_every
                and next(_S.head_counter) % _S.head_every == 0):
            _M_DROPPED.inc()
            return None
        reason = REASON_HEAD
    try:
        record: Dict[str, Any] = {
            "rid": rid,
            "trace_id": trace_id,
            "span_id": span_id,
            "origin": origin,
            "reason": reason,
            "status_code": int(status),
            "latency_s": round(latency_s, 6),
            "method": getattr(request, "method", None),
            "path": getattr(request, "url", None),
            "model_hash": _S.model_hash,
            "build_sha": _build_sha(),
            "output_digest": digest,
            "captured_ts": round(time.time(), 6),
            "pid": os.getpid(),
        }
        headers = getattr(request, "headers", None) or {}
        ctype = next((v for k, v in headers.items()
                      if k.lower() == "content-type"), None)
        if ctype:
            record["content_type"] = ctype
        entity = getattr(request, "entity", b"") or b""
        if len(entity) <= _S.payload_cap:
            record.update(_payload_fields(entity))
        else:
            # noted, never stored truncated: a half payload would
            # replay to a meaningless divergence, and one giant record
            # must not blow the file cap or convoy handler threads
            # behind a multi-second append under the module lock
            record["payload_truncated"] = len(entity)
        if reply_entity is not None and _S.reply_cap:
            if len(reply_entity) <= _S.reply_cap:
                try:
                    record["reply"] = reply_entity.decode("utf-8")
                except UnicodeDecodeError:
                    record["reply_b64"] = base64.b64encode(
                        reply_entity).decode("ascii")
            else:
                # a truncated body is useless for value diffing and
                # actively misleading for digest checks: note the
                # elision instead of storing a lie
                record["reply_truncated"] = len(reply_entity)
        line = json.dumps(record, separators=(",", ":"), default=repr)
        with _S.lock:
            path = _capture_path()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                if os.path.getsize(path) >= _S.max_bytes:
                    _rotate_locked(path)
            except OSError:
                pass  # no file yet: first append creates it
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
    except Exception:  # noqa: BLE001 - never worsen a reply path
        _M_WRITE_FAIL.inc()
        return None
    _M_RECORDS[reason].inc()
    return reason


def payload_bytes(record: Dict[str, Any]) -> Optional[bytes]:
    """A scanned record's request body back as bytes (inline utf-8 or
    base64) — the replay harness's input."""
    if "payload" in record:
        return record["payload"].encode("utf-8")
    if "payload_b64" in record:
        try:
            return base64.b64decode(record["payload_b64"])
        except (ValueError, TypeError):
            return None
    return None


def reply_bytes(record: Dict[str, Any]) -> Optional[bytes]:
    """A scanned record's retained reply body back as bytes, or None
    when it was not retained (cap, kill switch, or truncation)."""
    if "reply" in record:
        return record["reply"].encode("utf-8")
    if "reply_b64" in record:
        try:
            return base64.b64decode(record["reply_b64"])
        except (ValueError, TypeError):
            return None
    return None


def scan(path: Optional[str] = None,
         limit: int = 100_000) -> List[Dict[str, Any]]:
    """Every record in one capture file (default: this process's live
    file), oldest first. Torn/corrupt lines are skipped — a crash can
    tear at most the tail line, and replay must shrug at it."""
    if path is None:
        path = capture_path()
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line
                if isinstance(rec, dict):
                    out.append(rec)
                    if len(out) >= limit:
                        break
    except OSError:
        pass
    return out


def tail_summaries(n: int = 32) -> List[Dict[str, Any]]:
    """The last ``n`` records' summaries (payload/reply bodies elided)
    — what ``GET /debug/capture`` serves. Reads only the file tail
    (bounded), so a polled debug surface never re-parses a full
    capture file on the handler thread."""
    path = capture_path()
    try:
        with open(path, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 256 * 1024))
            tail = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return []
    out: List[Dict[str, Any]] = []
    for line in tail.splitlines()[-max(1, n):]:
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        out.append({k: rec.get(k) for k in (
            "rid", "trace_id", "reason", "status_code", "latency_s",
            "output_digest", "model_hash", "captured_ts",
            "payload_shapes")})
    return out
