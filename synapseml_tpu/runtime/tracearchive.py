"""Tail-based trace retention: a JSONL archive of the traces worth
keeping.

The completed-span ring (``runtime/telemetry.py``, default 1024 deep)
is a great live surface and a terrible forensic one: under any real
request rate the one trace an incident review needs has been evicted
long before anyone asks. This module is the durable tail — the
Dapper-style retention decision made at trace COMPLETION, when the
outcome is known:

- **every SLO-breaching trace is kept**: a 5xx reply (the 504
  deadline/timeout sheds included), a span that finished
  ``error``/``shed``, or a roundtrip over the latency threshold
  (``SYNAPSEML_SLO_LATENCY_MS``, the same knob the SLO gauges use);
- **a small head-sampled fraction of healthy ones** rides along
  (``SYNAPSEML_TRACE_HEAD_SAMPLE``, default 0.01 — every Nth healthy
  reply), so the archive shows what *normal* looked like next to the
  breaches;
- everything else is dropped — tail-based sampling's whole point is
  that the healthy 99.x% costs nothing.

Records are JSON lines (one :meth:`Span.breakdown` per line, plus the
reply status, latency, retention class, and pid) appended to
``<dump_dir>/trace_archive-<pid>.jsonl`` — beside the flight-recorder
dumps, so one volume holds a replica's whole forensic story and the
fleet controller can stitch a SIGKILLed replica's legs from disk
(``GET /fleet/trace/<trace_id>`` merges live ``/trace`` legs with
archive scans). The file is size-capped (``SYNAPSEML_TRACE_ARCHIVE_
MAX_BYTES``, default 8 MiB): past the cap the live file rotates to
``.1`` via atomic ``os.replace`` (tmp-then-rename discipline — readers
never see a half-rotated pair) and the previous ``.1`` is dropped.
Appends are single ``write()`` calls; a reader tolerates one torn tail
line after a crash (:func:`scan` skips lines that fail to parse).

Archive writes happen at archive RATE (breaches + the sampled few),
never per request, on the reply handler thread after the response is
already committed — a slow disk delays nothing client-visible. The
decision itself (:func:`maybe_archive`'s breach test + the head-sample
counter) is lock-free; only an actual write takes the file lock.
``SYNAPSEML_TRACE_ARCHIVE=0`` disables the sink entirely.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.locksan import make_lock

__all__ = [
    "maybe_archive", "archive_path", "scan", "configure", "reset",
    "enabled", "set_enabled", "DEFAULT_MAX_BYTES", "CLASS_BREACH",
    "CLASS_HEAD_SAMPLE",
]

DEFAULT_MAX_BYTES = 8 * 1024 * 1024
CLASS_BREACH = "slo_breach"
CLASS_HEAD_SAMPLE = "head_sample"


def _head_every_from_env() -> int:
    """Healthy-trace sampling stride from ``SYNAPSEML_TRACE_HEAD_
    SAMPLE`` (a fraction; 0.01 -> every 100th healthy reply; 0 or
    malformed -> no healthy sampling)."""
    raw = os.environ.get("SYNAPSEML_TRACE_HEAD_SAMPLE", "0.01").strip()
    try:
        frac = float(raw)
    except ValueError:
        return 0
    if not 0.0 < frac <= 1.0:
        return 0
    return max(1, round(1.0 / frac))


def _max_bytes_from_env() -> int:
    """``SYNAPSEML_TRACE_ARCHIVE_MAX_BYTES``: malformed or
    non-positive degrades to the default — a bad env var must never
    crash a server at import (the telemetry ring's policy), and a
    negative cap would rotate on every append, destroying the very
    forensics the archive exists to keep."""
    raw = os.environ.get("SYNAPSEML_TRACE_ARCHIVE_MAX_BYTES",
                         "").strip()
    try:
        n = int(raw) if raw else DEFAULT_MAX_BYTES
    except ValueError:
        return DEFAULT_MAX_BYTES
    return max(4096, n) if n > 0 else DEFAULT_MAX_BYTES


def _threshold_from_env() -> float:
    raw = os.environ.get("SYNAPSEML_SLO_LATENCY_MS", "").strip()
    try:
        ms = float(raw) if raw else 250.0
    except ValueError:
        ms = 250.0
    return ms / 1e3


class _State:
    """Module switchboard (the telemetry/blackbox pattern): env knobs
    captured once (all tolerant — degrade, never crash an import),
    :func:`configure` retunes for tests and entries."""

    def __init__(self):
        self.enabled = os.environ.get("SYNAPSEML_TRACE_ARCHIVE",
                                      "") != "0"
        self.dir: Optional[str] = None  # None = beside the flight dumps
        self.max_bytes = _max_bytes_from_env()
        self.head_every = _head_every_from_env()
        self.lock = make_lock("_State.lock")
        self.head_counter = itertools.count(1)
        self.default_threshold_s = _threshold_from_env()


_S = _State()


def enabled() -> bool:
    return _S.enabled


def set_enabled(on: bool) -> bool:
    prev = _S.enabled
    _S.enabled = bool(on)
    return prev


def configure(directory: Optional[str] = None,
              max_bytes: Optional[int] = None,
              head_every: Optional[int] = None):
    """Repoint/retune the sink (tests, embedding callers).
    ``head_every=0`` disables healthy sampling; ``directory=None``
    keeps the current one (the flight dump dir by default)."""
    with _S.lock:
        if directory is not None:
            _S.dir = directory
        if max_bytes is not None:
            _S.max_bytes = max(4096, int(max_bytes))
        if head_every is not None:
            _S.head_every = max(0, int(head_every))


def reset():
    """Tests only: drop the current archive files and restart the
    head-sample stride."""
    with _S.lock:
        _S.head_counter = itertools.count(1)
        path = _archive_path_locked()
        for p in (path, path + ".1"):
            try:
                os.remove(p)
            except OSError:
                pass


def _archive_path_locked() -> str:
    d = _S.dir
    if d is None:
        # beside the flight dumps — resolved per call because the
        # serving entry's --dump-dir lands after import
        from synapseml_tpu.runtime import blackbox as _bb

        d = _bb.dump_dir()
    return os.path.join(d, f"trace_archive-{os.getpid()}.jsonl")


def archive_path() -> str:
    """The live archive file's path (rotated sibling: ``<path>.1``)."""
    with _S.lock:
        return _archive_path_locked()


def _records_counter(cls: str) -> "_tm.Counter":
    return _tm.counter("trace_archive_records_total", retention=cls)


def _rotate_locked(path: str):
    """Atomic rotation: the live file becomes ``.1`` (replacing the
    previous one) and the next append starts a fresh file. One
    ``os.replace`` — a concurrent reader sees the old file or the new
    pair, never a torn state."""
    try:
        os.replace(path, path + ".1")
        _tm.counter("trace_archive_rotations_total").inc()
    except OSError:
        _tm.counter("trace_archive_write_failures_total").inc()


def _size() -> float:
    """Scrape-time gauge sampler: live archive file size in bytes."""
    try:
        return float(os.path.getsize(archive_path()))
    except OSError:
        return 0.0


_tm.gauge_fn("trace_archive_bytes", _size)


def maybe_archive(span: "_tm.Span", status: int, latency_s: float,
                  threshold_s: Optional[float] = None) -> Optional[str]:
    """The retention decision for one completed request: archive when
    it breached (5xx status, an ``error``/``shed`` span, or latency
    over ``threshold_s`` — default ``SYNAPSEML_SLO_LATENCY_MS``), or
    when the head-sample stride picked this healthy one. Returns the
    retention class when a record was written, else None. Never
    raises — the archive must not make a reply path worse."""
    if not _S.enabled or not _tm.enabled():
        return None
    if threshold_s is None:
        threshold_s = _S.default_threshold_s
    if (status >= 500 or span.status in ("error", "shed")
            or (threshold_s > 0 and latency_s > threshold_s)):
        cls = CLASS_BREACH
    elif _S.head_every and next(_S.head_counter) % _S.head_every == 0:
        cls = CLASS_HEAD_SAMPLE
    else:
        return None
    record = dict(span.breakdown())
    record.update({
        "status_code": int(status),
        "latency_s": round(latency_s, 6),
        "retention": cls,
        "archived_ts": round(time.time(), 6),
        "pid": os.getpid(),
    })
    line = json.dumps(record, separators=(",", ":"), default=repr)
    try:
        with _S.lock:
            path = _archive_path_locked()
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                if os.path.getsize(path) >= _S.max_bytes:
                    _rotate_locked(path)
            except OSError:
                pass  # no file yet: first append creates it
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
    except Exception:  # noqa: BLE001 - never worsen a reply path
        _tm.counter("trace_archive_write_failures_total").inc()
        return None
    _records_counter(cls).inc()
    return cls


def scan(trace_id: str, directory: Optional[str] = None,
         limit: int = 64) -> List[Dict[str, Any]]:
    """Every archived record for one trace id across ALL archive files
    in ``directory`` (default: this process's archive dir) — live and
    rotated, any pid. The durable half of trace stitching: a SIGKILLed
    replica's archived legs are still here. Torn/corrupt lines are
    skipped (a crash can tear at most the tail line)."""
    import glob as _glob

    if directory is None:
        directory = os.path.dirname(archive_path())
    out: List[Dict[str, Any]] = []
    needle = f'"{trace_id}"'
    paths = sorted(_glob.glob(os.path.join(directory,
                                           "trace_archive-*.jsonl*")))
    for path in paths:
        try:
            with open(path, encoding="utf-8") as fh:
                for line in fh:
                    if needle not in line:
                        continue  # cheap pre-filter before json parse
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line
                    if rec.get("trace_id") == trace_id:
                        out.append(rec)
                        if len(out) >= limit:
                            return out
        except OSError:
            continue  # rotated away mid-scan
    return out
