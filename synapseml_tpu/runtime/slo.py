"""SLO accounting: availability + latency-threshold burn rates.

The math behind the scrape-time ``serving_slo_*`` gauges every
:class:`~synapseml_tpu.io.serving.WorkerServer` registers (catalog +
methodology in docs/observability.md, "SLO accounting"). Pure
functions over data the telemetry registry already holds — the
per-status reply counters and the roundtrip latency histogram — so
nothing new is recorded on the request path; the SLO view is computed
when a scrape asks for it.

Definitions (the standard error-budget formulation):

- **availability** = 1 - (5xx replies / all replies). Client-caused
  4xx (400 poison payloads) and admission-control 429s are *not*
  availability losses — the replica answered deliberately; 500/503/504
  are (a shed 503/504 is capacity the caller asked for and did not
  get). No replies yet = 1.0 (no data is not an outage).
- **latency good fraction** = fraction of roundtrips at or under the
  threshold, estimated from the fixed histogram buckets with linear
  interpolation inside the covering bucket (the same
  ``histogram_quantile`` math the percentile readout uses, inverted).
- **burn rate** = (observed bad fraction) / (allowed bad fraction);
  1.0 burns the error budget exactly at the rate the SLO allows, 14.4
  sustained for an hour eats a 30-day 99.9% budget's month in ~2 days
  — the classic fast-burn alert threshold shipped in the chart's
  Prometheus rules (tools/k8s/chart/templates/alerts.yaml).

Targets come from ``SYNAPSEML_SLO_AVAILABILITY`` (default 0.999) and
``SYNAPSEML_SLO_LATENCY_MS`` (default 250) — read once per server at
construction, overridable per WorkerServer.
"""
from __future__ import annotations

import math
from typing import Mapping, Sequence

__all__ = ["availability", "fraction_le", "burn_rate",
           "DEFAULT_AVAILABILITY_TARGET", "DEFAULT_LATENCY_MS"]

DEFAULT_AVAILABILITY_TARGET = 0.999
DEFAULT_LATENCY_MS = 250.0


def availability(replies_by_code: Mapping[object, float]) -> float:
    """Good-reply fraction from a ``{status_code: count}`` map.

    Bad = 5xx. Codes that do not parse as ints count as bad (an
    ``"error"`` bucket is a failure, not a reply). Empty map = 1.0."""
    total = 0.0
    bad = 0.0
    for code, n in replies_by_code.items():
        if n <= 0:
            continue
        total += n
        try:
            c = int(code)
        except (TypeError, ValueError):
            bad += n
            continue
        if c >= 500:
            bad += n
    if total <= 0:
        return 1.0
    return 1.0 - bad / total


def fraction_le(bounds: Sequence[float], counts: Sequence[int],
                threshold: float) -> float:
    """Fraction of observations <= ``threshold`` from fixed-bucket
    histogram state: ``bounds`` are the bucket upper bounds and
    ``counts`` the per-bucket (NON-cumulative) counts, one extra for
    the overflow bucket (``len(counts) == len(bounds) + 1`` — the
    layout :class:`~synapseml_tpu.runtime.telemetry.Histogram`
    aggregates to). Inside the bucket that straddles the threshold,
    observations are assumed uniform (linear interpolation); the
    unbounded overflow bucket contributes nothing below the threshold
    (conservative: overflow observations count as bad). No data =
    1.0."""
    n = sum(counts)
    if n <= 0:
        return 1.0
    good = 0.0
    for i, c in enumerate(counts):
        if not c:
            continue
        lo = bounds[i - 1] if i > 0 else 0.0
        hi = bounds[i] if i < len(bounds) else math.inf
        if hi <= threshold:
            good += c
        elif lo < threshold and not math.isinf(hi):
            good += c * (threshold - lo) / (hi - lo)
    return min(1.0, good / n)


def burn_rate(good_fraction: float, target: float) -> float:
    """Error-budget burn rate: observed bad fraction over the allowed
    bad fraction. 0 when nothing is bad; with a degenerate 100% target
    (zero budget), any badness is an infinite burn."""
    bad = max(0.0, 1.0 - good_fraction)
    budget = 1.0 - target
    if budget <= 0.0:
        return 0.0 if bad <= 0.0 else math.inf
    return bad / budget
