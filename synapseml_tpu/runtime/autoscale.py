"""Fleet autoscaling: the pure decision math behind the fleet controller.

PRs 6-10 built the telemetry — ``executor_duty_cycle``,
``serving_slo_*_burn_rate``, the recompile sentinel, ``cache_skew`` —
and every one of those gauges was still only a dashboard. This module
turns them into a **control signal**: given one scrape sample per
replica, :func:`decide` answers "scale up, scale down, or hold" with
hysteresis (consecutive-breach streaks), per-direction cooldowns,
min/max clamps, and hard safety rails around missing telemetry. The
controller that acts on the decision (``tools/fleet/controller.py``)
owns the I/O — subprocesses, HTTP, signals; everything here is a pure
function over plain data, which is what makes the policy unit-testable
(tests/test_fleet.py) without a single process spawn.

Safety rails (docs/deployment.md, "Fleet operations"):

- **A scrape failure must never scale the fleet down.** An
  unreachable or stale replica removes the *evidence*, not the
  *capacity*; scaling down on blindness is how autoscalers cause the
  outage they exist to prevent. ``decide`` refuses ``down`` unless a
  fresh sample exists for EVERY live replica — and with zero fresh
  samples it holds outright (``no_fresh_telemetry``).
- **Scale-down waits for a fully hydrated fleet**: a replica still
  warming (not ready) blocks ``down`` — capacity in flight counts.
- **Hysteresis + cooldown**: one hot scrape never scales; the breach
  must persist ``up_consecutive`` evaluations, and each direction has
  its own cooldown so the fleet cannot flap faster than replicas
  hydrate.

Signals:

- **duty cycle**: mean of each ready replica's busiest dispatch target
  (``executor_duty_cycle{device=}``, runtime/perfwatch.py). Above
  ``duty_high`` the chips are saturated — add capacity; below
  ``duty_low`` the fleet idles — shed it.
- **SLO burn rate**: max over replicas of the availability/latency
  error-budget burn computed over the controller's OWN scrape window
  (:func:`window_availability` + :func:`~synapseml_tpu.runtime.slo.
  burn_rate` — windowed, not cumulative, so a recovered fleet stops
  signalling). Burn at/above ``burn_high`` scales up even at low duty:
  an SLO on fire is a capacity problem until proven otherwise.
- **decode starvation**: max over replicas of
  ``decode_queue_wait_burn`` (runtime/decode.py) — recent decode
  admission wait as a burn rate against the replica's wait SLO. At or
  above ``decode_burn_high`` the fleet scales up and scale-down is
  blocked: decode steps are short and latency-critical, so a starved
  decode fleet shows MODERATE duty while sequences age out in the
  admission queue — duty cycle alone misreads it.

**Warm hydration audit** (:func:`hydration_audit`): a replica that
booted from the shared ``ExecutableStore`` must show ZERO
post-warmup recompiles (``executor_recompiles_total``, all reasons —
``cache_skew`` included) and zero store-skew counts; the controller
records every new replica's audit as ``fleet_hydrations_total
{outcome=}`` and a ``fleet_hydration`` flight event, so "capacity
arrives in seconds" is a measured claim, not a hope.

The ``fleet_*`` metric series are registered HERE (the controller
calls the helpers) so the doc-drift gate's AST scan over the package
sees the literal names exactly like every other catalogued series.
"""
from __future__ import annotations

import re
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from synapseml_tpu.runtime import slo as _slo
from synapseml_tpu.runtime import telemetry as _tm

__all__ = [
    "FleetPolicy", "FleetState", "ReplicaSample", "Decision",
    "decide", "aggregate", "parse_prometheus", "sample_from_scrape",
    "window_availability", "hydration_audit",
    "scale_event_counter", "hydration_counter",
    "scrape_failure_counter", "trace_stitch_counter",
    "register_fleet_gauges",
    "register_replica_gauges", "unregister_replica_gauges",
]


class FleetPolicy:
    """The knobs one fleet scales by (CLI flags / chart values map 1:1;
    defaults are production-shaped — CI tightens them)."""

    __slots__ = ("min_replicas", "max_replicas", "duty_high", "duty_low",
                 "burn_high", "decode_burn_high", "up_consecutive",
                 "down_consecutive", "up_cooldown_s", "down_cooldown_s",
                 "stale_after_s")

    def __init__(self, min_replicas: int = 1, max_replicas: int = 8,
                 duty_high: float = 0.75, duty_low: float = 0.20,
                 burn_high: float = 2.0, decode_burn_high: float = 1.0,
                 up_consecutive: int = 2,
                 down_consecutive: int = 4, up_cooldown_s: float = 15.0,
                 down_cooldown_s: float = 60.0,
                 stale_after_s: float = 10.0):
        if min_replicas < 1:
            # the zero-floor is a policy error, not a runtime surprise:
            # this fleet serves traffic, and 0 replicas is an outage
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if duty_low >= duty_high:
            raise ValueError("duty_low must be < duty_high "
                             "(the hysteresis band)")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.duty_high = float(duty_high)
        self.duty_low = float(duty_low)
        self.burn_high = float(burn_high)
        # decode starvation threshold: decode_queue_wait_burn is
        # already normalized to the replica's wait SLO, so >= 1.0
        # MEANS sequences wait longer than the target before their
        # first prefill — a starved decode fleet. Duty cycle cannot
        # see this: decode steps are short and keep the chips "busy"
        # at modest duty while the admission queue ages out.
        self.decode_burn_high = float(decode_burn_high)
        self.up_consecutive = max(1, int(up_consecutive))
        self.down_consecutive = max(1, int(down_consecutive))
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.stale_after_s = float(stale_after_s)


class ReplicaSample:
    """One replica's scrape, reduced to the control inputs. ``ts`` is
    the monotonic instant the scrape *succeeded*; ``reachable=False``
    means this poll failed (ts then carries the attempt time).
    ``duty`` is the busiest dispatch target's duty cycle; burn values
    are None when the window carried no signal (no new replies)."""

    __slots__ = ("name", "url", "ts", "reachable", "ready", "duty",
                 "avail_burn", "latency_burn", "decode_wait_burn",
                 "recompiles", "store_skew", "replies_by_code",
                 "store_hits")

    def __init__(self, name: str, url: str = "", ts: float = 0.0,
                 reachable: bool = False, ready: bool = False,
                 duty: float = 0.0,
                 avail_burn: Optional[float] = None,
                 latency_burn: Optional[float] = None,
                 decode_wait_burn: Optional[float] = None,
                 recompiles: Optional[Dict[str, float]] = None,
                 store_skew: float = 0.0,
                 store_hits: float = 0.0,
                 replies_by_code: Optional[Dict[str, float]] = None):
        self.name = name
        self.url = url
        self.ts = ts
        self.reachable = reachable
        self.ready = ready
        self.duty = duty
        self.avail_burn = avail_burn
        self.latency_burn = latency_burn
        # decode admission-wait burn against the replica's wait SLO
        # (synapseml_decode_queue_wait_burn, runtime/decode.py); None
        # when the replica serves no decode traffic
        self.decode_wait_burn = decode_wait_burn
        self.recompiles = dict(recompiles or {})
        self.store_skew = store_skew
        self.store_hits = store_hits
        self.replies_by_code = dict(replies_by_code or {})

    @property
    def recompiles_total(self) -> float:
        return sum(self.recompiles.values())

    def burn_max(self) -> float:
        return max(self.avail_burn or 0.0, self.latency_burn or 0.0)


class FleetState:
    """Mutable controller-side memory between evaluations: the breach
    streaks (hysteresis) and the last scale action (cooldowns).
    :func:`decide` updates it in place."""

    __slots__ = ("up_streak", "down_streak", "last_scale_ts",
                 "last_direction")

    def __init__(self):
        self.up_streak = 0
        self.down_streak = 0
        self.last_scale_ts: Optional[float] = None
        self.last_direction = ""

    def mark_scaled(self, now: float, direction: str):
        self.last_scale_ts = now
        self.last_direction = direction
        self.up_streak = 0
        self.down_streak = 0


class Decision:
    """One evaluation's verdict. ``direction`` is ``up`` / ``down`` /
    ``hold``; ``reason`` names the signal (``duty_cycle`` /
    ``burn_rate``) or the rail that blocked one (``cooldown``,
    ``at_max``, ``stale_telemetry``, ...); ``aggregates`` is the fleet
    view the decision was made from (served on /fleet/status)."""

    __slots__ = ("direction", "target", "reason", "aggregates")

    def __init__(self, direction: str, target: int, reason: str,
                 aggregates: Dict[str, Any]):
        self.direction = direction
        self.target = target
        self.reason = reason
        self.aggregates = aggregates

    def as_dict(self) -> Dict[str, Any]:
        return {"direction": self.direction, "target": self.target,
                "reason": self.reason, "aggregates": self.aggregates}


def aggregate(samples: List[ReplicaSample], now: float,
              policy: FleetPolicy) -> Dict[str, Any]:
    """The fleet-level view one evaluation acts on: freshness split,
    mean duty over ready+fresh replicas, max burn over fresh ones."""
    fresh = [s for s in samples
             if s.reachable and now - s.ts <= policy.stale_after_s]
    ready = [s for s in fresh if s.ready]
    duty_mean = (sum(s.duty for s in ready) / len(ready)) if ready else 0.0
    burn_max = max([s.burn_max() for s in fresh], default=0.0)
    decode_burn_max = max([s.decode_wait_burn for s in fresh
                           if s.decode_wait_burn is not None],
                          default=0.0)
    return {
        "replicas": len(samples),
        "fresh": len(fresh),
        "stale": len(samples) - len(fresh),
        "ready": len(ready),
        "duty_mean": round(duty_mean, 6),
        "burn_max": round(burn_max, 6),
        "decode_burn_max": round(decode_burn_max, 6),
    }


def decide(now: float, samples: List[ReplicaSample], state: FleetState,
           policy: FleetPolicy) -> Decision:
    """One pure evaluation of the scaling policy over the fleet's
    samples. Mutates ``state`` (streaks, never the cooldown stamp —
    the controller calls ``state.mark_scaled`` only once it actually
    acted, so a failed spawn does not eat the cooldown)."""
    n = len(samples)
    agg = aggregate(samples, now, policy)

    if agg["fresh"] == 0:
        # total blindness: hold, whatever the streaks said before. A
        # fleet the controller cannot see has UNKNOWN load — scaling
        # it (to zero, especially) on no evidence is the one move the
        # rails exist to forbid.
        state.up_streak = 0
        state.down_streak = 0
        return Decision("hold", n, "no_fresh_telemetry", agg)

    duty = agg["duty_mean"]
    burn = agg["burn_max"]
    decode_burn = agg["decode_burn_max"]
    up_reason = ""
    if burn >= policy.burn_high:
        up_reason = "burn_rate"
    elif decode_burn >= policy.decode_burn_high:
        # a starved decode fleet: admission waits exceed the wait SLO
        # even though short decode steps keep duty moderate
        up_reason = "decode_starvation"
    elif agg["ready"] > 0 and duty >= policy.duty_high:
        up_reason = "duty_cycle"
    down_ok = (agg["ready"] > 0 and duty <= policy.duty_low
               and burn < policy.burn_high
               and decode_burn < policy.decode_burn_high)

    if up_reason:
        state.up_streak += 1
        state.down_streak = 0
    elif down_ok:
        state.down_streak += 1
        state.up_streak = 0
    else:
        state.up_streak = 0
        state.down_streak = 0

    def _cooled(window: float) -> bool:
        return (state.last_scale_ts is None
                or now - state.last_scale_ts >= window)

    if state.up_streak >= policy.up_consecutive:
        if n >= policy.max_replicas:
            return Decision("hold", n, "at_max", agg)
        if not _cooled(policy.up_cooldown_s):
            return Decision("hold", n, "cooldown", agg)
        return Decision("up", min(n + 1, policy.max_replicas),
                        up_reason, agg)

    if state.down_streak >= policy.down_consecutive:
        if n <= policy.min_replicas:
            return Decision("hold", n, "at_min", agg)
        if agg["stale"] > 0:
            # capacity without evidence: a replica that stopped
            # answering scrapes may still be serving — down requires a
            # fresh sample for EVERY live replica
            return Decision("hold", n, "stale_telemetry", agg)
        if agg["ready"] < agg["fresh"]:
            return Decision("hold", n, "replicas_warming", agg)
        if not _cooled(policy.down_cooldown_s):
            return Decision("hold", n, "cooldown", agg)
        return Decision("down", max(n - 1, policy.min_replicas),
                        "duty_cycle", agg)

    return Decision("hold", n, "steady", agg)


# -- scrape parsing ---------------------------------------------------------

_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{([^}]*)\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str,
                                        List[Tuple[Dict[str, str],
                                                   float]]]:
    """Prometheus text exposition -> ``{name: [(labels, value), ...]}``.
    Tolerant: comment/TYPE lines and malformed samples are skipped —
    the controller must keep flying on a partially garbled scrape."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group(4))
        except ValueError:
            continue
        labels = {k: v for k, v in _LABEL_RE.findall(m.group(3) or "")}
        out.setdefault(m.group(1), []).append((labels, value))
    return out


def _series_sum(metrics: Mapping[str, List[Tuple[Dict[str, str], float]]],
                name: str) -> float:
    return sum(v for _l, v in metrics.get(name, ()))


def sample_from_scrape(name: str, url: str, now: float,
                       metrics_text: Optional[str],
                       ready: bool) -> ReplicaSample:
    """Reduce one replica's ``/metrics`` text (None = unreachable) to a
    :class:`ReplicaSample`. Burn values are left None — the controller
    fills them from its own scrape-window reply deltas
    (:func:`window_availability`), not the replica's cumulative
    gauges, so the signal decays when the fleet recovers."""
    if metrics_text is None:
        return ReplicaSample(name, url, ts=now, reachable=False)
    metrics = parse_prometheus(metrics_text)
    duty = max([v for _l, v in
                metrics.get("synapseml_executor_duty_cycle", ())],
               default=0.0)
    decode_burn_series = [
        v for _l, v in
        metrics.get("synapseml_decode_queue_wait_burn", ())]
    decode_wait_burn = (max(decode_burn_series)
                        if decode_burn_series else None)
    recompiles = {
        labels.get("reason", ""): v for labels, v in
        metrics.get("synapseml_executor_recompiles_total", ())
        if v > 0}
    replies = {}
    for labels, v in metrics.get("synapseml_serving_replies_total", ()):
        code = labels.get("code", "")
        replies[code] = replies.get(code, 0.0) + v
    return ReplicaSample(
        name, url, ts=now, reachable=True, ready=ready, duty=duty,
        decode_wait_burn=decode_wait_burn,
        recompiles=recompiles,
        store_skew=_series_sum(
            metrics, "synapseml_compile_cache_store_skew_total"),
        store_hits=_series_sum(
            metrics, "synapseml_compile_cache_store_hits_total"),
        replies_by_code=replies)


def window_availability(prev_replies: Mapping[str, float],
                        cur_replies: Mapping[str, float]
                        ) -> Optional[float]:
    """Availability over ONE controller scrape window: the per-code
    reply deltas between two cumulative snapshots, run through the
    standard availability policy (non-5xx = good). None when the
    window carried no replies — idle is *no signal*, not 100% good
    (and not an outage either)."""
    deltas = {code: max(0.0, cur - prev_replies.get(code, 0.0))
              for code, cur in cur_replies.items()}
    if sum(deltas.values()) <= 0:
        return None
    return _slo.availability(deltas)


def hydration_audit(sample: ReplicaSample) -> Dict[str, Any]:
    """The warm-boot verdict for a freshly ready replica: clean means
    the recompile sentinel never fired post-warmup (``cache_skew``
    reason included — the shared-volume poison case) and the
    ExecutableStore reported zero skew. ``store_hits`` > 0 is the
    positive proof capacity came FROM the shared store rather than a
    fresh compile."""
    clean = (sample.recompiles_total == 0 and sample.store_skew == 0)
    return {
        "replica": sample.name,
        "clean": clean,
        "recompiles": dict(sample.recompiles),
        "store_skew": sample.store_skew,
        "store_hits": sample.store_hits,
        "outcome": "warm" if clean and sample.store_hits > 0
        else ("clean_cold" if clean else "dirty"),
    }


# -- fleet telemetry registration -------------------------------------------
# The literal series names live here (inside the package) so the
# doc-drift gate's AST scan ties them to docs/observability.md rows;
# the controller resolves handles through these helpers.

def scale_event_counter(direction: str, reason: str) -> "_tm.Counter":
    """``fleet_scale_events_total{direction=,reason=}`` — one count per
    scaling ACTION the controller actually took (spawn/terminate),
    never per evaluation."""
    return _tm.counter("fleet_scale_events_total", direction=direction,
                       reason=reason)


def hydration_counter(outcome: str) -> "_tm.Counter":
    """``fleet_hydrations_total{outcome=}`` — warm-boot audits of
    newly ready replicas: ``warm`` (zero recompiles, served from the
    shared store), ``clean_cold`` (zero recompiles, fresh compiles —
    the seed replica), ``dirty`` (the sentinel fired)."""
    return _tm.counter("fleet_hydrations_total", outcome=outcome)


def scrape_failure_counter() -> "_tm.Counter":
    """``fleet_scrape_failures_total`` — replica polls that returned
    no usable /metrics (the blindness the down-rail guards against)."""
    return _tm.counter("fleet_scrape_failures_total")


def trace_stitch_counter(result: str) -> "_tm.Counter":
    """``fleet_trace_stitch_total{result=}`` — ``/fleet/trace``
    stitches by outcome: ``found`` (>=1 leg merged from live replicas
    and/or the trace archive) / ``not_found``."""
    return _tm.counter("fleet_trace_stitch_total", result=result)


_REPLICA_STATES = ("ready", "warming", "unreachable")


def register_fleet_gauges(counts_fn: Callable[[], Dict[str, int]],
                          aggregates_fn: Callable[[], Dict[str, Any]]):
    """Register the fleet-level scrape-time gauges:
    ``fleet_replicas{state=}`` off ``counts_fn`` (state -> count) and
    the aggregate signal gauges off ``aggregates_fn`` (the dict
    :func:`aggregate` builds)."""
    for st in _REPLICA_STATES:
        _tm.gauge_fn("fleet_replicas",
                     lambda s=st: float(counts_fn().get(s, 0)),
                     state=st)
    _tm.gauge_fn("fleet_duty_cycle_mean",
                 lambda: float(aggregates_fn().get("duty_mean", 0.0)))
    _tm.gauge_fn("fleet_burn_rate_max",
                 lambda: float(aggregates_fn().get("burn_max", 0.0)))


def register_replica_gauges(name: str,
                            sample_fn: Callable[[], ReplicaSample]):
    """Per-replica series under the controller's own registry, so
    ``/fleet/metrics`` carries the fleet AND each member:
    ``fleet_replica_duty_cycle{replica=}``,
    ``fleet_replica_burn_rate{replica=}``,
    ``fleet_replica_up{replica=}`` (1 = last scrape succeeded)."""
    _tm.gauge_fn("fleet_replica_duty_cycle",
                 lambda: float(sample_fn().duty), replica=name)
    _tm.gauge_fn("fleet_replica_burn_rate",
                 lambda: float(sample_fn().burn_max()), replica=name)
    _tm.gauge_fn("fleet_replica_up",
                 lambda: 1.0 if sample_fn().reachable else 0.0,
                 replica=name)


def unregister_replica_gauges(name: str):
    """Drop a reaped/terminated replica's series — a scrape must never
    keep reading a ghost."""
    for series in ("fleet_replica_duty_cycle", "fleet_replica_burn_rate",
                   "fleet_replica_up"):
        _tm.unregister(series, replica=name)


def now_monotonic() -> float:
    """Injection seam for tests (decide() itself never reads clocks)."""
    return time.monotonic()
