"""Fault injection: named failure points for the executor/serving stacks.

The reference system gets its fault-tolerance story from Spark — a task
that dies is replayed by the scheduler, and ``WorkerServer.recover``
mirrors the request-replay half of that (HTTPSourceV2.scala:488-505).
Our runtime's pipeline THREADS (stage/dispatch/drain in
runtime/executor.py, collect/score/reply in io/serving.py) have no
scheduler above them, so every degradation path has to be built — and
*proved* — in-process. This module is the proving half: a registry of
named injection points the runtime code is permanently instrumented
with, activatable per-point via API or the ``SYNAPSEML_FAULTS`` env var,
so tests and chaos CI can make any stage fail deterministically (or
probabilistically, under load) and assert the supervision/shedding/
isolation machinery actually recovers.

Design constraints:

- **Zero hot-path cost when inactive.** An instrumentation site holds a
  module-level :class:`FaultPoint` handle; ``fire()`` is a single
  attribute test (``self._spec is None``) when nothing is injected —
  the same degrade-to-nothing pattern runtime/telemetry.py uses for its
  kill switch. No dict lookups, no env reads, no locks on the hot path.
- **No jax import.** Serving imports this module and must stay
  importable without a device runtime; :class:`PipelineBrokenError`
  lives here for the same reason (both executor and serving raise it,
  and serving must not import the executor module).

Points (catalog in docs/robustness.md):

====================  =====================================================
``staging``           host coerce+pad worker (executor ``_stage_worker``)
``h2d``               host->device placement (executor ``_dispatch``)
``compute``           compiled-program call (executor ``_dispatch``);
                      scopes ``channel<N>`` hit ONE serving channel's
                      scoring path (``DistributedServer``) — the failure
                      domain the channel circuit breakers quarantine
``drain``             device->host fetch (executor ``_drain_loop``)
``reply``             reply serialization/send (serving ``_reply_scored``)
``latency``           injected sleep — scopes ``dispatch``, ``score``,
                      ``channel_stall`` (per-channel scoring stall: the
                      breaker's slow-channel trip condition)
``thread_kill``       raises :class:`ThreadKilled` (a BaseException) at a
                      pipeline-loop top so the THREAD dies, not the batch
                      — scopes ``stage``, ``dispatch``, ``drain``,
                      ``scorer``, ``reply``, ``collector``,
                      ``distributor``
====================  =====================================================

Env grammar (parsed once at import; :func:`configure` re-parses)::

    SYNAPSEML_FAULTS=point[.scope]:prob[:detail],...
    SYNAPSEML_FAULTS=compute:0.15                 # 15% of dispatches raise
    SYNAPSEML_FAULTS=thread_kill.drain:1          # kill the drain thread
    SYNAPSEML_FAULTS=compute:0.5:ValueError       # raise ValueError instead
    SYNAPSEML_FAULTS=latency.score:1:25           # 25ms sleep per score

``detail`` is an exception name (builtins or this module) — except for
``latency`` points, where it is a sleep duration in milliseconds.
A point name without a scope activates every scope of that family.
"""
from __future__ import annotations

import builtins
import os
import random
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from synapseml_tpu.runtime import structlog as _slog
from synapseml_tpu.runtime.locksan import make_lock
from synapseml_tpu.runtime import telemetry as _tm

__all__ = [
    "FaultInjected", "ThreadKilled", "PipelineBrokenError", "FaultPoint",
    "point", "activate", "deactivate", "configure", "active",
    "POINT_NAMES", "POINT_SCOPES", "POINT_SCOPE_PATTERNS",
]

POINT_NAMES = ("staging", "h2d", "compute", "drain", "reply",
               "thread_kill", "latency")

# the full scope catalog per family (docs/robustness.md). Validated in
# activate(): a typo'd scope would otherwise arm a spec no
# instrumentation site ever resolves — a chaos run that silently
# injects NOTHING and proves nothing. Families absent here take no
# scope at all.
POINT_SCOPES: Dict[str, Tuple[str, ...]] = {
    "latency": ("dispatch", "score", "channel_stall"),
    "thread_kill": ("stage", "dispatch", "drain", "scorer", "reply",
                    "collector", "distributor"),
}

# open-ended scope families: serving channels are numbered at runtime
# (``compute.channel0``, ``compute.channel7``, ...), so the catalog
# validates them by pattern instead of enumeration — ``channelX`` is
# still a loud ValueError
POINT_SCOPE_PATTERNS: Dict[str, "re.Pattern[str]"] = {
    "compute": re.compile(r"^channel\d+$"),
}


class FaultInjected(RuntimeError):
    """Default exception an active fault point raises."""


class ThreadKilled(BaseException):
    """Raised by ``thread_kill`` points at a pipeline-loop top.

    Deliberately a ``BaseException``: every per-batch handler in the
    runtime catches ``Exception`` (or ``BaseException`` scoped to one
    unit) and converts it into a failed future / 500 reply — a kill
    must escape all of them and terminate the THREAD, because that is
    the failure mode supervision exists to catch."""


class PipelineBrokenError(RuntimeError):
    """A pipeline thread died; everything in flight was failed with this.

    Raised on every in-flight future (and from ``submit`` in the narrow
    window before supervision swaps the pipeline) when an executor
    stage/dispatch/drain thread dies unexpectedly. The supervision
    contract: no future ever hangs on a dead thread, and the NEXT submit
    gets a freshly restarted pipeline. The serving layer treats it as
    transient (one bounded retry re-submits against the restarted
    pipeline) before falling back to the 500 path."""


class _FaultSpec:
    """One activation: probability, effect, and an optional firing cap."""

    __slots__ = ("prob", "exc", "latency_s", "remaining", "lock")

    def __init__(self, prob: float, exc: Optional[type],
                 latency_s: float, times: Optional[int]):
        self.prob = float(prob)
        self.exc = exc
        self.latency_s = float(latency_s)
        self.remaining = times  # None = unlimited
        self.lock = make_lock("_FaultSpec.lock")

    def describe(self) -> Dict[str, Any]:
        return {"prob": self.prob,
                "exc": self.exc.__name__ if self.exc else None,
                "latency_ms": self.latency_s * 1e3,
                "remaining": self.remaining}


class FaultPoint:
    """One named injection site. Sites resolve their handle once at
    module import (like telemetry metric handles) and call :meth:`fire`
    on the hot path — a single attribute test when inactive."""

    __slots__ = ("name", "scope", "_spec")

    def __init__(self, name: str, scope: Optional[str]):
        self.name = name
        self.scope = scope
        self._spec: Optional[_FaultSpec] = None  # synlint: shared

    @property
    def full_name(self) -> str:
        return self.name if self.scope is None \
            else f"{self.name}.{self.scope}"

    def fire(self):
        """Hot-path call: no-op unless this point has an active spec."""
        spec = self._spec
        if spec is None:
            return
        self._fire(spec)

    def _fire(self, spec: _FaultSpec):
        if spec.prob < 1.0 and random.random() >= spec.prob:
            return
        if spec.remaining is not None:
            # times-bounded faults (tests/chaos inject "exactly one
            # kill"): the decrement is guarded so concurrent hot paths
            # cannot overfire
            with spec.lock:
                if spec.remaining <= 0:
                    return
                spec.remaining -= 1
        _tm.counter("faults_injected_total", point=self.full_name).inc()
        # structured breadcrumb for chaos-run log correlation (debug:
        # probabilistic injections under load are high-volume); only
        # reached when a fault actually fires, so the disarmed hot
        # path stays a single attribute test
        _slog.log("debug", "fault_injected", point=self.full_name)
        if spec.latency_s > 0.0:
            time.sleep(spec.latency_s)
            if spec.exc is None:
                return
        exc = spec.exc or FaultInjected
        raise exc(f"injected fault at {self.full_name!r}")


_LOCK = make_lock("faults:_LOCK")
_POINTS: Dict[Tuple[str, Optional[str]], FaultPoint] = {}
# active specs keyed the same way; (name, None) applies to every scope
# of the family, including points registered AFTER activation
_SPECS: Dict[Tuple[str, Optional[str]], _FaultSpec] = {}


def point(name: str, scope: Optional[str] = None) -> FaultPoint:
    """Get-or-create the injection point for an instrumentation site.
    Resolve once at module import; ``fire()`` on the hot path."""
    key = (name, scope)
    with _LOCK:
        p = _POINTS.get(key)
        if p is None:
            p = FaultPoint(name, scope)
            _POINTS[key] = p
            spec = _SPECS.get(key) or _SPECS.get((name, None))
            if spec is not None:
                p._spec = spec
        return p


def _split(point_name: str) -> Tuple[str, Optional[str]]:
    name, _, scope = point_name.partition(".")
    return name, (scope or None)


def activate(point_name: str, prob: float = 1.0,
             exc: Optional[type] = None, latency_ms: float = 0.0,
             times: Optional[int] = None) -> None:
    """Arm one point (``"compute"``) or one scope (``"thread_kill.drain"``).

    ``prob`` fires per call; ``times`` caps total firings (exhausted
    specs stay armed but inert); ``latency_ms`` sleeps instead of (or,
    combined with ``exc``, before) raising. ``exc=None`` raises
    :class:`FaultInjected` — except pure-latency points, which return
    normally after the sleep."""
    name, scope = _split(point_name)
    if name not in POINT_NAMES:
        raise ValueError(
            f"unknown fault point {point_name!r} (families: "
            f"{', '.join(POINT_NAMES)})")
    known_scopes = POINT_SCOPES.get(name, ())
    pattern = POINT_SCOPE_PATTERNS.get(name)
    if scope is not None and scope not in known_scopes and not (
            pattern is not None and pattern.match(scope)):
        hints = list(known_scopes)
        if pattern is not None:
            hints.append(pattern.pattern)
        raise ValueError(
            f"unknown scope {scope!r} for fault point {name!r}"
            + (f" (scopes: {', '.join(hints)})" if hints
               else " (this family takes no scope)"))
    if name == "latency" and latency_ms == 0.0:
        latency_ms = 10.0
    if name == "thread_kill" and exc is None:
        # the whole point of the family: a BaseException no per-batch
        # handler converts into a failed future / 500 reply
        exc = ThreadKilled
    spec = _FaultSpec(prob, exc, latency_ms / 1e3, times)
    with _LOCK:
        _SPECS[(name, scope)] = spec
        for (pn, ps), p in _POINTS.items():
            if pn == name and (scope is None or ps == scope):
                p._spec = spec


def deactivate(point_name: Optional[str] = None) -> None:
    """Disarm one point/scope, or everything (``None``) — the hot path
    returns to its single-attribute-test no-op."""
    with _LOCK:
        if point_name is None:
            _SPECS.clear()
            for p in _POINTS.values():
                p._spec = None
            return
        name, scope = _split(point_name)
        _SPECS.pop((name, scope), None)
        for (pn, ps), p in _POINTS.items():
            if pn == name and (scope is None or ps == scope):
                p._spec = (_SPECS.get((pn, ps))
                           or _SPECS.get((pn, None)))


def active() -> Dict[str, Dict[str, Any]]:
    """Currently armed specs, keyed by ``point[.scope]``."""
    with _LOCK:
        return {(n if s is None else f"{n}.{s}"): spec.describe()
                for (n, s), spec in _SPECS.items()}


def _resolve_exc(name: str) -> type:
    exc = globals().get(name) or getattr(builtins, name, None)
    if not (isinstance(exc, type) and issubclass(exc, BaseException)):
        raise ValueError(f"SYNAPSEML_FAULTS: {name!r} is not an exception")
    return exc


def configure(spec: str) -> List[str]:
    """Parse an env-grammar string (``point[.scope]:prob[:detail],...``)
    and arm each entry; returns the armed point names. Called once at
    import with ``SYNAPSEML_FAULTS``; tests/chaos may re-call it."""
    armed: List[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        pname = fields[0].strip()
        prob = float(fields[1]) if len(fields) > 1 and fields[1] else 1.0
        exc: Optional[type] = None
        latency_ms = 0.0
        if len(fields) > 2 and fields[2]:
            if _split(pname)[0] == "latency":
                latency_ms = float(fields[2])
            else:
                exc = _resolve_exc(fields[2].strip())
        activate(pname, prob=prob, exc=exc, latency_ms=latency_ms)
        armed.append(pname)
    return armed


_ENV_SPEC = os.environ.get("SYNAPSEML_FAULTS", "")
if _ENV_SPEC:
    configure(_ENV_SPEC)
