"""Paged KV-cache accounting for decode serving (vLLM SOSP'23 shape).

The decode scheduler (:mod:`~synapseml_tpu.runtime.decode`) keeps the
actual key/value tensors device-resident inside fixed-geometry batch
buffers — ``[B, Hkv, T, D]`` per layer, one compiled program per
(S, T) signature. What is NOT fixed is how much of that geometry a
replica can afford to keep live: sequences arrive with unknown output
lengths, and a cache that only ever grows walks the chip into an OOM
the serving layer can neither predict nor survive. This module is the
capacity/policy half of the cache:

- **pages**: every sequence's cache footprint is accounted in fixed
  ``page_size``-token pages (``ceil(len / page_size)``), so capacity
  arithmetic is exact under growth and never fragments — freeing a
  sequence returns whole pages.
- **capacity**: sized off the perfwatch HBM gauges —
  ``SYNAPSEML_KV_HBM_FRACTION`` (default 0.3) of the smallest
  ``device_hbm_bytes_limit`` across local devices. Backends without
  allocator stats (the forced-CPU test platform) report limit 0 and
  fall back to a fixed default; ``SYNAPSEML_KV_CAPACITY_BYTES``
  overrides everything (how CI induces eviction deterministically).
- **LRU evict-then-recompute**: when an allocation does not fit, the
  least-recently-stepped *other* resident sequence is evicted whole.
  Eviction frees pages only — the evicted sequence keeps its full
  token history (prompt + everything generated) and re-enters the
  scheduler's admission queue to be *re-prefilled*; the recompute is
  bit-identical because greedy decode over the same tokens and weights
  is deterministic (the decode-smoke replay asserts the digests).
- **HBM backpressure**: the scheduler calls
  :meth:`under_pressure` each iteration; while perfwatch's
  ``hbm_high_water`` latch is set for any device, admission pauses and
  one LRU eviction per iteration sheds load until the device falls
  back under the line.

Nothing here touches device memory: eviction *decisions* live here,
the buffers (and the act of zeroing a freed row) live in the
scheduler. Telemetry: ``kv_capacity_bytes`` / ``kv_pages_in_use`` /
``kv_bytes_in_use`` / ``kv_sequences_resident`` gauges and
``kv_evictions_total{reason=}`` / ``kv_recomputes_total`` /
``kv_evicted_tokens_total`` counters (docs/observability.md).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from synapseml_tpu.runtime import blackbox as _bb
from synapseml_tpu.runtime.locksan import make_lock
from synapseml_tpu.runtime import telemetry as _tm

__all__ = ["PagedKVCache", "kv_capacity_bytes", "under_pressure"]

# capacity fallback when no backend reports an HBM limit (CPU test
# platform) and no explicit override is set
_DEFAULT_CAPACITY_BYTES = 256 << 20


def kv_capacity_bytes() -> int:
    """Resolve the cache byte budget: explicit override, else the HBM
    fraction of the tightest device limit, else the fixed default."""
    explicit = os.environ.get("SYNAPSEML_KV_CAPACITY_BYTES", "")
    if explicit:
        try:
            return max(0, int(explicit))
        except ValueError:
            pass
    try:
        frac = float(os.environ.get("SYNAPSEML_KV_HBM_FRACTION", "0.3"))
    except ValueError:
        frac = 0.3
    from synapseml_tpu.runtime import perfwatch as _pw

    limits = [rec.get("bytes_limit") or 0 for rec in _pw.device_memory()]
    limits = [l for l in limits if l > 0]
    if not limits:
        return _DEFAULT_CAPACITY_BYTES
    return int(min(limits) * frac)


class PagedKVCache:
    """Page allocator + residency tracker for one decode scheduler.

    Thread-safe; every mutation happens under one lock (the scheduler
    loop is the only writer in practice, the gauges read at scrape
    time)."""

    def __init__(self, page_size: int, bytes_per_token: int,
                 capacity_bytes: Optional[int] = None,
                 name: str = "decode"):
        if page_size <= 0:
            raise ValueError(f"page_size={page_size} must be positive")
        if bytes_per_token <= 0:
            raise ValueError(
                f"bytes_per_token={bytes_per_token} must be positive")
        self.page_size = int(page_size)
        self.bytes_per_token = int(bytes_per_token)
        self.page_bytes = self.page_size * self.bytes_per_token
        cap = kv_capacity_bytes() if capacity_bytes is None \
            else int(capacity_bytes)
        # at least one max-footprint sequence must fit or the scheduler
        # would evict forever without progress; capacity_pages >= 1
        self.capacity_pages = max(1, cap // self.page_bytes)
        self.name = name
        self._lock = make_lock("PagedKVCache._lock")
        self._pages: Dict[str, int] = {}      # seq id -> pages held
        self._tokens: Dict[str, int] = {}     # seq id -> tokens covered
        self._clock = 0
        self._last_used: Dict[str, int] = {}  # seq id -> LRU stamp
        self._m_evict = {
            reason: _tm.counter("kv_evictions_total", cache=name,
                                reason=reason)
            for reason in ("capacity", "hbm_high_water")}
        self._m_recompute = _tm.counter("kv_recomputes_total", cache=name)
        self._m_evicted_tokens = _tm.counter("kv_evicted_tokens_total",
                                             cache=name)
        _tm.gauge_fn("kv_capacity_bytes",
                     lambda: float(self.capacity_pages * self.page_bytes),
                     cache=name)
        _tm.gauge_fn("kv_pages_in_use",
                     lambda: float(self.pages_in_use()), cache=name)
        _tm.gauge_fn("kv_bytes_in_use",
                     lambda: float(self.pages_in_use() * self.page_bytes),
                     cache=name)
        _tm.gauge_fn("kv_sequences_resident",
                     lambda: float(len(self._pages)), cache=name)

    def close(self) -> None:
        """Unregister the instance-scope gauges (scheduler shutdown) so
        a dead cache neither leaks through the registry nor keeps
        exporting its last values."""
        for series in ("kv_capacity_bytes", "kv_pages_in_use",
                       "kv_bytes_in_use", "kv_sequences_resident"):
            _tm.unregister(series, cache=self.name)

    # -- queries --------------------------------------------------------
    def pages_for(self, n_tokens: int) -> int:
        return max(1, -(-int(n_tokens) // self.page_size))

    def pages_in_use(self) -> int:
        with self._lock:
            return sum(self._pages.values())

    def resident(self, seq_id: str) -> bool:
        with self._lock:
            return seq_id in self._pages

    def fits(self, n_tokens: int) -> bool:
        """Would a fresh sequence of ``n_tokens`` fit without evicting?"""
        with self._lock:
            free = self.capacity_pages - sum(self._pages.values())
        return self.pages_for(n_tokens) <= free

    # -- mutations ------------------------------------------------------
    def touch(self, seq_id: str) -> None:
        """LRU bump — the scheduler marks every sequence it stepped."""
        with self._lock:
            self._clock += 1
            self._last_used[seq_id] = self._clock

    def acquire(self, seq_id: str, n_tokens: int,
                reason: str = "capacity") -> Optional[List[str]]:
        """Grow (or admit) ``seq_id`` to cover ``n_tokens``; evict LRU
        *other* sequences as needed. Returns the evicted sequence ids
        (often empty), or ``None`` when the allocation cannot fit even
        after evicting everything else — the caller must queue the
        sequence instead of admitting it."""
        need = self.pages_for(n_tokens)
        if need > self.capacity_pages:
            return None
        evicted: List[str] = []
        with self._lock:
            held = self._pages.get(seq_id, 0)
            while (sum(self._pages.values()) - held + need
                   > self.capacity_pages):
                victim = self._lru_locked(exclude=seq_id)
                if victim is None:
                    return None
                evicted.append(victim)
                self._evict_locked(victim, reason)
            self._pages[seq_id] = need
            self._tokens[seq_id] = int(n_tokens)
            self._clock += 1
            self._last_used[seq_id] = self._clock
        return evicted

    def evict_lru(self, reason: str = "hbm_high_water",
                  exclude: Optional[str] = None) -> Optional[str]:
        """Evict the least-recently-stepped resident sequence (the HBM
        backpressure path). Returns its id, or None if nothing to
        evict."""
        with self._lock:
            victim = self._lru_locked(exclude=exclude)
            if victim is not None:
                self._evict_locked(victim, reason)
            return victim

    def release(self, seq_id: str) -> None:
        """Free a finished sequence's pages (not an eviction)."""
        with self._lock:
            self._pages.pop(seq_id, None)
            self._tokens.pop(seq_id, None)
            self._last_used.pop(seq_id, None)

    def note_recompute(self, seq_id: str) -> None:
        """The scheduler re-prefilled an evicted sequence — the other
        half of the evict-then-recompute contract."""
        self._m_recompute.inc()

    # -- internals ------------------------------------------------------
    def _lru_locked(self, exclude: Optional[str]) -> Optional[str]:
        candidates = [(stamp, sid) for sid, stamp in
                      self._last_used.items()
                      if sid != exclude and sid in self._pages]
        if not candidates:
            return None
        return min(candidates)[1]

    def _evict_locked(self, seq_id: str, reason: str) -> None:
        pages = self._pages.pop(seq_id, 0)
        tokens = self._tokens.pop(seq_id, 0)
        self._last_used.pop(seq_id, None)
        m = self._m_evict.get(reason)
        if m is None:
            m = _tm.counter("kv_evictions_total", cache=self.name,
                            reason=reason)
            self._m_evict[reason] = m
        m.inc()
        self._m_evicted_tokens.inc(tokens)
        _bb.record("kv_evicted", level="info", cache=self.name,
                   seq=seq_id, pages=pages, tokens=tokens, reason=reason)


def under_pressure() -> bool:
    """True while any local device sits above the perfwatch high-water
    line — the scheduler's pause-admission / shed-one-LRU signal. Uses
    the same TTL-cached sample the gauges read, so polling every
    iteration costs one dict walk, not a device walk."""
    from synapseml_tpu.runtime import perfwatch as _pw

    try:
        frac = _pw.high_water_fraction()
        if frac <= 0:
            return False
        for rec in _pw._sampled():
            limit = rec.get("bytes_limit") or 0
            if limit > 0 and rec["bytes_in_use"] / limit >= frac:
                return True
    except Exception:  # noqa: BLE001 - telemetry must never break decode
        return False
    return False
