"""Structured logging: one JSON-lines event schema for the runtime.

Until round 12 the serving stack was silent — ``io/serving.py`` had
zero logger calls, so a breaker trip or a drained replica left nothing
to grep. This module is the logging half of the incident-diagnosis
layer (the flight recorder in :mod:`~synapseml_tpu.runtime.blackbox`
is the in-memory half): every emitted line is ONE schema::

    {"ts": 1754236800.123, "level": "info", "event": "failover",
     "rid": "3f2a...", "channel": 0, "to_channel": 1, ...}

``ts`` is epoch seconds (float), ``level`` one of debug/info/warn/
error, ``event`` a stable snake_case name, ``rid``/``channel`` the
correlation keys (omitted when not applicable), and everything else
event-specific fields. Because the rid in the log IS the rid in the
``X-Request-Id`` header, the trace span, and the flight-recorder ring,
``grep <rid>`` over the log reconstructs a request's life end to end
(docs/observability.md, "Structured log schema").

Off by default — emission is opt-in via ``SYNAPSEML_LOG``:

- ``SYNAPSEML_LOG=json``  JSON lines (machines / log pipelines)
- ``SYNAPSEML_LOG=text``  ``ts level event k=v ...`` (humans)
- ``SYNAPSEML_LOG=0`` / unset  silent — :func:`log` is a single
  attribute test, the same degrade-to-nothing discipline the
  telemetry and fault-injection hot paths use.

``SYNAPSEML_LOG_LEVEL`` (default ``info``) gates per-request ``debug``
events (request accepted / replied) separately from the incident-grade
``info``+ events, so a production replica can log every breaker
transition without paying a line per request.

Lines go to stderr (stdout carries the serving entry's protocol lines
the chaos CI parses); the stream is injectable for tests.

**Emission never blocks the caller.** Several call sites log while
holding serving-critical locks (the breaker lock, the channel map
lock), and a stalled stderr consumer fills the pipe — a synchronous
``write`` there would wedge every channel's scoring behind one slow
log collector. Production lines (stderr) are therefore handed to a
bounded queue drained by one writer thread (oldest-wins: a full queue
DROPS the new line and counts it in :func:`dropped_lines` — losing a
log line beats losing the serving plane), flushed at interpreter exit.
An injected test stream writes synchronously under a small lock, so
tests read their buffer deterministically.
"""
from __future__ import annotations

import atexit
import json
import os
import queue as _queue
import sys
import threading
import time
from typing import Any, Dict, Optional, TextIO

from synapseml_tpu.runtime.locksan import make_lock

__all__ = ["log", "enabled", "mode", "set_mode", "dropped_lines",
           "LEVELS"]

LEVELS: Dict[str, int] = {"debug": 10, "info": 20, "warn": 30,
                          "error": 40}


class _Cfg:
    """Module switchboard (the telemetry ``_State`` pattern): ``mode``
    gates every :func:`log` call with one attribute read; the env knobs
    are captured once at import and :func:`set_mode` flips them for
    tests and the serving entry."""

    __slots__ = ("mode", "min_level", "stream", "dropped")

    def __init__(self):
        raw = os.environ.get("SYNAPSEML_LOG", "").strip().lower()
        self.mode = raw if raw in ("json", "text") else ""
        lvl = os.environ.get("SYNAPSEML_LOG_LEVEL", "info").strip().lower()
        self.min_level = LEVELS.get(lvl, LEVELS["info"])
        # None = async writer to sys.stderr (resolved at write time so
        # pytest capture and late redirection keep working); tests
        # inject a StringIO, which writes synchronously instead
        self.stream: Optional[TextIO] = None
        self.dropped = 0  # lines lost to a full queue (bounded cost)


_CFG = _Cfg()
_WRITE_LOCK = make_lock("structlog:_WRITE_LOCK")

# bounded hand-off to the stderr writer thread: log() never blocks,
# whatever the pipe's consumer is doing
_Q_MAX = 4096
_LOG_Q: "_queue.Queue[str]" = _queue.Queue(maxsize=_Q_MAX)
_WRITER_LOCK = make_lock("structlog:_WRITER_LOCK")
_WRITER: Optional[threading.Thread] = None


def dropped_lines() -> int:
    """Lines dropped because the writer queue was full."""
    return _CFG.dropped


def _writer_loop():
    while True:
        line = _LOG_Q.get()
        try:
            stream = sys.stderr
            stream.write(line + "\n")
            stream.flush()
        except Exception:  # noqa: BLE001 - logging must never break the job
            pass


def _ensure_writer():
    global _WRITER
    if _WRITER is not None and _WRITER.is_alive():
        return
    with _WRITER_LOCK:
        if _WRITER is None or not _WRITER.is_alive():
            # synlint: disable=RL001 - self-healing singleton: every
            # enqueue re-checks is_alive() and respawns a dead writer
            _WRITER = threading.Thread(target=_writer_loop,
                                       name="structlog-writer",
                                       daemon=True)
            _WRITER.start()


@atexit.register
def _drain_at_exit():
    """Best-effort flush of queued lines while stderr still works —
    the writer is a daemon thread and may be frozen by interpreter
    teardown with lines still queued."""
    deadline = time.monotonic() + 2.0
    while not _LOG_Q.empty() and time.monotonic() < deadline:
        time.sleep(0.01)
    while True:
        try:
            line = _LOG_Q.get_nowait()
        except _queue.Empty:
            return
        try:
            sys.stderr.write(line + "\n")
            sys.stderr.flush()
        except Exception:  # noqa: BLE001
            return


def mode() -> str:
    """Current emission mode: ``"json"``, ``"text"``, or ``""`` (off)."""
    return _CFG.mode


def enabled(level: str = "info") -> bool:
    """True when a :func:`log` call at ``level`` would emit — the guard
    callers use before building expensive field dicts."""
    return bool(_CFG.mode) and LEVELS.get(level, 20) >= _CFG.min_level


def set_mode(new_mode: str, level: Optional[str] = None,
             stream: Optional[TextIO] = None):
    """Reconfigure emission; returns ``(prev_mode, prev_level_name)``
    so tests can restore. ``new_mode``: ``"json"``/``"text"``/``""``
    (or ``"0"``) — anything else raises."""
    if new_mode in ("0", "off", None):
        new_mode = ""
    if new_mode not in ("json", "text", ""):
        raise ValueError(
            f"unknown log mode {new_mode!r} (json, text, or '' = off)")
    prev = (_CFG.mode,
            next(k for k, v in LEVELS.items() if v == _CFG.min_level))
    _CFG.mode = new_mode
    if level is not None:
        if level not in LEVELS:
            raise ValueError(f"unknown log level {level!r} "
                             f"(levels: {', '.join(LEVELS)})")
        _CFG.min_level = LEVELS[level]
    if stream is not None:
        _CFG.stream = stream
    return prev


def _json_safe(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    return repr(v)


def log(level: str, event: str, rid: Optional[str] = None,
        channel: Optional[int] = None, trace: Optional[str] = None,
        **fields: Any):
    """Emit one structured event. A no-op (single attribute test) when
    logging is off or the level is below the configured floor — safe on
    any path, including under locks: production lines are enqueued to
    the writer thread (full queue drops + counts, never blocks), so a
    stalled stderr consumer cannot wedge a caller holding the breaker
    or channel-map lock. ``trace`` is the distributed-trace id
    (``rid``'s fleet-wide sibling, docs/observability.md "Distributed
    tracing"): the same 32-hex value rides the ``traceparent``
    headers, the span store, and the flight ring, so grep-by-trace
    reconstructs a request across REPLICAS the way grep-by-rid does
    within one."""
    if not _CFG.mode:
        return
    if LEVELS.get(level, 20) < _CFG.min_level:
        return
    rec: Dict[str, Any] = {"ts": round(time.time(), 6), "level": level,
                           "event": event}
    if rid is not None:
        rec["rid"] = rid
    if channel is not None:
        rec["channel"] = channel
    if trace is not None:
        rec["trace"] = trace
    for k, v in fields.items():
        if v is not None:
            rec[k] = _json_safe(v)
    if _CFG.mode == "json":
        line = json.dumps(rec, separators=(",", ":"), default=repr)
    else:
        head = f"{rec['ts']:.3f} {level:<5} {event}"
        tail = " ".join(f"{k}={rec[k]}" for k in rec
                        if k not in ("ts", "level", "event"))
        line = f"{head} {tail}".rstrip()
    stream = _CFG.stream
    if stream is not None:
        # injected stream (tests): synchronous under the lock so the
        # caller can read its buffer deterministically
        with _WRITE_LOCK:
            try:
                stream.write(line + "\n")
                stream.flush()
            except Exception:  # noqa: BLE001 - logging never breaks the job
                pass
        return
    _ensure_writer()
    try:
        _LOG_Q.put_nowait(line)
    except _queue.Full:
        _CFG.dropped += 1  # losing a line beats blocking the caller
