"""locksan: runtime lock-order & blocking-call sanitizer (synsan).

The dynamic half of the concurrency-tooling story: synlint's CC pack
(tools/analysis/rules_concurrency.py) reasons *statically* about lock
order and blocking calls, but it is lexical — it cannot see lock
aliasing, callback indirection, or the scrape-thread interleavings
chaos CI actually produces. locksan watches the real execution:

- every lock in the package is built through :func:`make_lock` /
  :func:`make_rlock` / :func:`make_condition` with a creation-site
  label equal to the lock's *static CC002 identity* (``modstem:NAME``
  for module-level locks, ``Class.attr`` for instance fields), so the
  static model and the observed graph share one vocabulary and
  tools/analysis/rules_dynsan.py can diff them;
- per-thread acquire/release events land in lock-free per-thread
  rings (each thread appends to its own deque; the registry is only
  touched once per thread);
- acquisition-order edges feed an observed graph; a cycle on edge
  insert is a *lock-order inversion* finding;
- ``sleep`` / ``queue.get`` / ``Future.result`` / socket I/O while a
  sanitized lock is held is a *blocking-under-lock* finding (the
  dynamic twin of CC003);
- a watchdog thread spots a thread parked longer than
  ``SYNAPSEML_LOCKSAN_WATCHDOG_S`` on a lock whose holder is itself
  parked and emits a ``locksan_deadlock`` flight-recorder event with
  both stacks (runtime/blackbox.py dump path).

Off by default: ``SYNAPSEML_LOCKSAN=1`` enables it. The disabled hot
path is ONE attribute test (``_STATE.tracer is None``), the same
discipline as ``faults.fire()``; see docs/analysis.md "Dynamic
sanitizer" for the measured A/B.

This module is imported by telemetry/structlog/faults/blackbox, so it
must import NOTHING from the package at module level — telemetry and
blackbox are reached lazily, the idiom blackbox.py uses for costmodel.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["make_lock", "make_rlock", "make_condition", "enable",
           "disable", "enabled", "reset", "findings", "edges",
           "snapshot", "dump"]

# knobs (docs/knobs.md) — read once at import, like faults/blackbox
_ENV_ON = os.environ.get("SYNAPSEML_LOCKSAN", "") == "1"
_WATCHDOG_S = float(os.environ.get("SYNAPSEML_LOCKSAN_WATCHDOG_S", "2"))
_RING = int(os.environ.get("SYNAPSEML_LOCKSAN_RING", "512"))
_OUT_DIR = os.environ.get("SYNAPSEML_LOCKSAN_OUT", "")


class _Switch:
    """Enable switchboard: the disabled hot path reads ONE attribute."""

    __slots__ = ("tracer",)

    def __init__(self):
        self.tracer: Optional["_Tracer"] = None


_STATE = _Switch()
_MET: Optional[Dict[str, Any]] = None


def _metrics() -> Optional[Dict[str, Any]]:
    """Telemetry counters, resolved lazily (telemetry imports us for
    make_lock, so a module-level import would be circular). Returns
    None until telemetry has finished importing."""
    global _MET
    m = _MET
    if m is None:
        try:
            from synapseml_tpu.runtime import telemetry as _tm
            if getattr(_tm, "counter", None) is None:
                return None  # telemetry mid-import
            m = {
                "events": _tm.counter("locksan_events_total"),
                "inversion": _tm.counter("locksan_findings_total",
                                         kind="inversion"),
                "blocking": _tm.counter("locksan_findings_total",
                                        kind="blocking"),
                "deadlock": _tm.counter("locksan_findings_total",
                                        kind="deadlock"),
            }
        except Exception:
            return None
        _MET = m
    return m


_SKIP_FILES = (os.sep + "threading.py", os.sep + "queue.py",
               os.sep + "socket.py", os.sep + "contextlib.py",
               os.sep + "subprocess.py",
               "concurrent" + os.sep + "futures")


def _caller_site() -> str:
    """``path:line`` of the nearest frame outside locksan and the
    stdlib synchronization machinery — the application line that did
    the acquire/blocking call."""
    f = sys._getframe(1)
    here = __file__
    for _ in range(30):
        if f is None:
            break
        fn = f.f_code.co_filename
        if fn != here and not any(s in fn for s in _SKIP_FILES):
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>:0"


class _Tls(threading.local):
    def __init__(self):
        self.held: List[Tuple[str, Any]] = []   # (name, lock) stack
        self.ring: collections.deque = collections.deque(maxlen=_RING)
        self.internal = False    # reentrancy guard for tracer innards
        self.registered = False


def _set_guard(tls: _Tls, on: bool) -> None:
    """Single write site for the reentrancy guard: ``internal`` lives
    on a ``threading.local`` subclass, per-thread by construction."""
    tls.internal = on


class _Tracer:
    """All sanitizer state. One instance while enabled; internal
    bookkeeping uses a RAW threading.Lock (it must stay invisible to
    itself) and per-thread rings that only their owner writes."""

    def __init__(self, watchdog_s: float):
        self.watchdog_s = watchdog_s
        self.tls = _Tls()
        self._glock = threading.Lock()  # guards graph/findings/registry
        # observed graph: outer name -> inner name -> [count, site]
        self.graph: Dict[str, Dict[str, List[Any]]] = {}
        self.locks: Dict[str, int] = {}          # name -> acquire count
        self.events_total = 0                    # plain tally; see _publish
        self.kind_counts: Dict[str, int] = {"inversion": 0,
                                            "blocking": 0, "deadlock": 0}
        self._published: Dict[str, int] = {}     # watchdog-thread-only
        self.findings: List[Dict[str, Any]] = []
        self._seen: set = set()                  # finding dedup keys
        self.rings: List[Tuple[int, str, collections.deque]] = []
        self.waiting: Dict[int, Tuple[Any, float, str]] = {}  # tid -> (lock, t0, park site)
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None

    # -- per-thread plumbing ------------------------------------------

    def _state(self) -> _Tls:
        tls = self.tls
        if not tls.registered:
            tls.registered = True
            t = threading.current_thread()
            with self._glock:
                self.rings.append((t.ident or 0, t.name, tls.ring))
        return tls

    def _publish(self):
        """Push the int tallies into telemetry counters as deltas.
        Called ONLY from the watchdog thread (and final stop()): event
        paths must never call ``telemetry.counter`` themselves — the
        triggering thread may already hold the sanitized (non-reentrant)
        registry lock, so the call would self-deadlock. The watchdog
        holds no sanitized locks, and the guard keeps its own registry
        acquire out of the tracer."""
        tls = self.tls
        _set_guard(tls, True)
        try:
            m = _metrics()
            if m is None:
                return
            with self._glock:
                counts = dict(self.kind_counts)
            counts["events"] = self.events_total
            for key, val in counts.items():
                delta = val - self._published.get(key, 0)
                if delta > 0:
                    m[key].inc(delta)
                    self._published[key] = val
        finally:
            _set_guard(tls, False)

    def _event(self, tls: _Tls, op: str, name: str):
        tls.ring.append((time.monotonic(), op, name))
        self.events_total += 1

    # -- acquisition tracking -----------------------------------------

    def acquire(self, lock: "SanLock", blocking: bool, timeout: float
                ) -> bool:
        raw = lock._raw
        tls = self._state()
        if tls.internal:
            ok = raw.acquire(blocking, timeout)
            if ok:
                lock._owner = threading.get_ident()
            return ok
        ok = raw.acquire(False)
        if not ok:
            if not blocking:
                return False
            me = threading.get_ident()
            self.waiting[me] = (lock, time.monotonic(), _caller_site())
            self._event(tls, "park", lock.name)
            try:
                ok = raw.acquire(True, timeout)
            finally:
                self.waiting.pop(me, None)
        if ok:
            lock._owner = threading.get_ident()
            self._acquired(tls, lock)
        return ok

    def _acquired(self, tls: _Tls, lock: "SanLock"):
        held = tls.held
        if held and held[-1][0] != lock.name:
            self._edge(tls, held[-1][0], lock.name)
        held.append((lock.name, lock))
        self._event(tls, "acq", lock.name)
        self.locks[lock.name] = self.locks.get(lock.name, 0) + 1

    def release(self, lock: "SanLock"):
        tls = self._state()
        if not tls.internal:
            held = tls.held
            for i in range(len(held) - 1, -1, -1):
                if held[i][1] is lock:
                    del held[i]
                    break
            self._event(tls, "rel", lock.name)
        lock._owner = None
        lock._raw.release()

    # -- observed graph + inversion detection -------------------------

    def _edge(self, tls: _Tls, outer: str, inner: str):
        _set_guard(tls, True)
        try:
            cycle = None
            with self._glock:
                d = self.graph.setdefault(outer, {})
                rec = d.get(inner)
                if rec is not None:
                    rec[0] += 1
                    return
                site = _caller_site()
                d[inner] = [1, site]
                cycle = self._path(inner, outer)
            if cycle:
                other = self.graph.get(cycle[0], {}).get(cycle[1])
                self._finding(
                    "inversion",
                    key=("inversion", frozenset((outer, inner))),
                    outer=outer, inner=inner, site=site,
                    other_site=other[1] if other else "<unknown>:0",
                    cycle=[outer] + cycle,
                    detail=f"lock-order inversion: {outer} -> {inner} "
                           f"observed here but a {' -> '.join(cycle)} "
                           "path was already observed")
        finally:
            _set_guard(tls, False)

    def _path(self, start: str, goal: str) -> Optional[List[str]]:
        """DFS path start => goal in the observed graph (caller holds
        ``_glock``); the graph is dozens of nodes, so plain DFS."""
        stack: List[Tuple[str, List[str]]] = [(start, [start])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == goal:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.graph.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    # -- findings ------------------------------------------------------

    def _finding(self, kind: str, key: tuple, **fields: Any):
        with self._glock:
            if key in self._seen:
                return
            self._seen.add(key)
            rec = {"kind": kind, "ts": time.time()}
            rec.update(fields)
            self.findings.append(rec)
            self.kind_counts[kind] = self.kind_counts.get(kind, 0) + 1
        try:
            from synapseml_tpu.runtime import blackbox
            blackbox.record("locksan_finding", channel="locksan",
                            level="error", kind=kind,
                            detail=str(fields.get("detail", kind)))
        except Exception:  # reporting must never take the guarded code down
            pass

    # -- blocking-call hook (installed patches call this) -------------

    def blocked(self, what: str):
        tls = self._state()
        if tls.internal or not tls.held:
            return
        name = tls.held[-1][0]
        _set_guard(tls, True)
        try:
            site = _caller_site()
            self._event(tls, "blk", name)
            self._finding(
                "blocking", key=("blocking", what, name, site),
                what=what, lock=name, site=site,
                detail=f"blocking call {what} while holding {name}")
        finally:
            _set_guard(tls, False)

    # -- deadlock watchdog --------------------------------------------

    def start_watchdog(self):
        # synlint: disable=RL001 - the watchdog IS the supervisor of
        # last resort: daemon, self-terminating via _stop, and its only
        # job is to report threads nothing else can see
        self._watchdog = threading.Thread(
            target=self._watch, name="locksan-watchdog", daemon=True)
        self._watchdog.start()

    def _watch(self):
        tick = min(0.25, max(0.05, self.watchdog_s / 4.0))
        while not self._stop.wait(tick):
            self._publish()
            now = time.monotonic()
            for tid, (lock, t0, site) in list(self.waiting.items()):
                if now - t0 < self.watchdog_s:
                    continue
                holder = lock._owner
                if holder is None or holder == tid:
                    continue
                if holder not in self.waiting:
                    continue  # holder is running — slow, not deadlocked
                self._deadlock(tid, holder, lock, site)

    def _deadlock(self, waiter: int, holder: int, lock: "SanLock",
                  site: str):
        frames = sys._current_frames()
        stacks = {}
        for label, tid in (("waiter", waiter), ("holder", holder)):
            f = frames.get(tid)
            stacks[label] = "".join(traceback.format_stack(f)) if f \
                else "<gone>"
        names = {t.ident: t.name for t in threading.enumerate()}
        hlock = self.waiting.get(holder, (None, 0.0, ""))[0]
        self._finding(
            "deadlock", key=("deadlock", lock.name, waiter, holder),
            lock=lock.name, waiter=names.get(waiter, str(waiter)),
            holder=names.get(holder, str(holder)),
            holder_waits_on=getattr(hlock, "name", "<unknown>"),
            site=site,
            waiter_stack=stacks["waiter"], holder_stack=stacks["holder"],
            detail=f"thread {names.get(waiter, waiter)} parked "
                   f">{self.watchdog_s:g}s on {lock.name} whose holder "
                   f"{names.get(holder, holder)} is itself parked on "
                   f"{getattr(hlock, 'name', '<unknown>')}")
        try:
            from synapseml_tpu.runtime import blackbox
            blackbox.record("locksan_deadlock", channel="locksan",
                            level="error", lock=lock.name,
                            waiter=names.get(waiter, str(waiter)),
                            holder=names.get(holder, str(holder)),
                            waiter_stack=stacks["waiter"],
                            holder_stack=stacks["holder"])
            blackbox.trigger("locksan_deadlock")
        except Exception:  # a failed dump must not wedge the watchdog
            pass

    def stop(self):
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2)
        self._publish()  # final flush of the metric tallies


# -- lock wrappers --------------------------------------------------------

class SanLock:
    """``threading.Lock`` shim. When the sanitizer is off, every method
    is ONE attribute test (``_STATE.tracer``) ahead of the raw op."""

    __slots__ = ("_raw", "name", "_owner")

    def __init__(self, name: str):
        self._raw = threading.Lock()
        self.name = name
        self._owner: Optional[int] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tr = _STATE.tracer
        if tr is None:
            return self._raw.acquire(blocking, timeout)
        return tr.acquire(self, blocking, timeout)

    def release(self):
        tr = _STATE.tracer
        if tr is None:
            return self._raw.release()
        tr.release(self)

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        # inlined disabled path: `with lock:` is the dominant idiom, so
        # it gets the one-attribute test without an extra call frame
        tr = _STATE.tracer
        if tr is None:
            self._raw.acquire()
            return self
        tr.acquire(self, True, -1)
        return self

    def __exit__(self, *exc):
        tr = _STATE.tracer
        if tr is None:
            self._raw.release()
            return
        tr.release(self)

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<SanLock {self.name} raw={self._raw!r}>"


class SanRLock:
    """Reentrant variant: re-acquisition by the owner records neither
    edges nor park state (matching RLock semantics)."""

    __slots__ = ("_raw", "name", "_owner", "_count")

    def __init__(self, name: str):
        self._raw = threading.RLock()
        self.name = name
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        tr = _STATE.tracer
        if tr is None:
            return self._raw.acquire(blocking, timeout)
        if self._owner == threading.get_ident():
            ok = self._raw.acquire(blocking, timeout)
            if ok:
                self._count += 1
            return ok
        ok = tr.acquire(self, blocking, timeout)  # type: ignore[arg-type]
        if ok:
            self._count = 1
        return ok

    def release(self):
        tr = _STATE.tracer
        if tr is None:
            return self._raw.release()
        if self._count > 1:
            self._count -= 1
            return self._raw.release()
        self._count = 0
        tr.release(self)  # type: ignore[arg-type]

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()


def make_lock(name: str) -> SanLock:
    """Factory every ``threading.Lock()`` site migrated to. ``name``
    MUST be the lock's static CC002 identity (``modstem:NAME`` for a
    module-level lock, ``Class.attr`` for an instance field) so the
    observed graph and the static model share one vocabulary."""
    return SanLock(name)


def make_rlock(name: str) -> SanRLock:
    return SanRLock(name)


def make_condition(name: str) -> threading.Condition:
    """Condition over a sanitized lock: ``wait()`` releases/reacquires
    through the SanLock wrapper, so the held-set stays truthful across
    the wait window."""
    return threading.Condition(make_lock(name))


# -- blocking-call patches ------------------------------------------------

_PATCHES: List[Tuple[Any, str, Any]] = []


def _hook(owner: Any, attr: str, what: str, pred: Any = None):
    orig = getattr(owner, attr)

    def wrapper(*args: Any, **kwargs: Any):
        tr = _STATE.tracer
        if tr is not None and (pred is None or pred(args, kwargs)):
            tr.blocked(what)
        return orig(*args, **kwargs)

    wrapper.__name__ = getattr(orig, "__name__", attr)
    wrapper._locksan_orig = orig
    _PATCHES.append((owner, attr, orig))
    setattr(owner, attr, wrapper)


def _install_patches():
    if _PATCHES:
        return
    import queue as _queue
    import socket as _socket
    from concurrent.futures import Future as _Future
    _hook(time, "sleep", "time.sleep")
    # get_nowait() routes through get(block=False) — only a call that
    # can actually park the thread counts as blocking
    _hook(_queue.Queue, "get", "queue.Queue.get",
          pred=lambda a, k: (a[1] if len(a) > 1
                             else k.get("block", True)))
    _hook(_Future, "result", "Future.result")
    for meth in ("accept", "connect", "recv", "sendall"):
        _hook(_socket.socket, meth, f"socket.{meth}")


def _remove_patches():
    while _PATCHES:
        owner, attr, orig = _PATCHES.pop()
        setattr(owner, attr, orig)


# -- public control surface -----------------------------------------------

def enable(watchdog_s: Optional[float] = None) -> None:
    """Turn the sanitizer on (idempotent). Tests call this directly;
    production turns it on with ``SYNAPSEML_LOCKSAN=1``."""
    if _STATE.tracer is not None:
        return
    tracer = _Tracer(_WATCHDOG_S if watchdog_s is None else watchdog_s)
    _install_patches()
    _STATE.tracer = tracer
    tracer.start_watchdog()


def disable() -> None:
    tracer = _STATE.tracer
    if tracer is None:
        return
    _STATE.tracer = None
    _remove_patches()
    tracer.stop()


def enabled() -> bool:
    return _STATE.tracer is not None


def reset() -> None:
    """Tests: drop observed state but keep the sanitizer running."""
    tracer = _STATE.tracer
    if tracer is not None:
        with tracer._glock:
            tracer.graph.clear()
            tracer.locks.clear()
            tracer.findings.clear()
            tracer._seen.clear()


def findings() -> List[Dict[str, Any]]:
    tracer = _STATE.tracer
    if tracer is None:
        return []
    with tracer._glock:
        return [dict(f) for f in tracer.findings]


def edges() -> List[Dict[str, Any]]:
    tracer = _STATE.tracer
    if tracer is None:
        return []
    out = []
    with tracer._glock:
        for outer, inners in tracer.graph.items():
            for inner, (count, site) in inners.items():
                out.append({"outer": outer, "inner": inner,
                            "count": count, "site": site})
    return out


def snapshot() -> Dict[str, Any]:
    """The observed-graph artifact tools/analysis/rules_dynsan.py
    ingests (``--observed``)."""
    tracer = _STATE.tracer
    base: Dict[str, Any] = {
        "version": 1, "tool": "locksan", "pid": os.getpid(),
        "enabled": tracer is not None,
    }
    if tracer is None:
        base.update({"edges": [], "locks": {}, "findings": [],
                     "events_total": 0, "threads": 0})
        return base
    with tracer._glock:
        rings = list(tracer.rings)
    base.update({
        "edges": edges(),
        "locks": dict(tracer.locks),
        "findings": findings(),
        "events_total": tracer.events_total,
        "threads": len(rings),
        "watchdog_s": tracer.watchdog_s,
    })
    return base


def dump(path: Optional[str] = None) -> str:
    """Write the observed-graph artifact. With no ``path``, writes
    ``locksan-<pid>.json`` under ``SYNAPSEML_LOCKSAN_OUT`` (each
    process in a multi-process smoke gets its own file; the analyzer
    merges a directory)."""
    if path is None:
        out = _OUT_DIR or "."
        os.makedirs(out, exist_ok=True)
        path = os.path.join(out, f"locksan-{os.getpid()}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(snapshot(), fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _atexit_dump():  # pragma: no cover - exercised by the smokes
    if _OUT_DIR and _STATE.tracer is not None:
        try:
            dump()
        except Exception:  # interpreter tearing down; losing the artifact is fine
            pass


if _ENV_ON:
    enable()
if _OUT_DIR:
    atexit.register(_atexit_dump)
