"""Persisted measured-routing verdicts shared by the kernel probers.

The GBDT histogram router (grower.cached_hist_route) established the
pattern: a backend choice is a MEASURED verdict keyed by shape class,
memoized in-process and persisted under ``SYNAPSEML_TPU_CACHE_DIR`` so
one probe cost covers all later runs. This module is that pattern as a
reusable table for the round-15 lanes (the fused predict traversal
kernel and the ONNX int8 lane), with the staleness fix built in from
the start: the negative memo ("no verdict on disk for this key") holds
a TTL, so a verdict landed by ANOTHER worker on a shared cache volume
becomes visible within ``neg_ttl_s`` instead of only after a restart.

Lookups are trace-safe (pure host-side dict/file reads — shapes are
static at trace time); probing and persistence are the caller's job.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

from synapseml_tpu.runtime.locksan import make_lock

# default negative-memo TTL: long enough that a shape with no verdict
# does not re-open the cache file on every trace, short enough that a
# sibling worker's probe verdict lands without a process restart
_DEFAULT_NEG_TTL_S = 60.0


def cache_dir() -> str:
    return os.environ.get("SYNAPSEML_TPU_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "synapseml_tpu")


def neg_ttl_s() -> float:
    try:
        return float(os.environ.get("SYNAPSEML_ROUTE_NEG_TTL_S",
                                    _DEFAULT_NEG_TTL_S))
    except ValueError:
        return _DEFAULT_NEG_TTL_S


def _force(out) -> None:
    """Block until ``out`` is computed WITHOUT fetching its value —
    ``np.asarray`` here would drag a full D2H copy into the timed
    region and mis-penalize device-resident formulations. Value
    fetches belong in the verify leg only."""
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 - plain host outputs: nothing to wait on
        import numpy as np

        np.asarray(out)


def best_of(fn, args, reps: int = 2) -> float:
    """min-of-N wall time of one compiled probe leg, completion
    forced with ``block_until_ready`` (no D2H in the timed region) —
    the shared timing half of every measured prober (routers alias it
    as a module-level ``_best_of`` so tests can stub the clock out of
    a verify-only probe)."""
    import time

    _force(fn(*args))  # warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _force(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


class RouteTable:
    """One lane's verdict table: {key: verdict-string} with an
    in-process memo, best-effort JSON persistence, and a TTL'd
    negative memo. Thread-safe; file I/O happens OUTSIDE the lock
    (a slow shared volume must not park other lookups)."""

    def __init__(self, filename: str):
        self.filename = filename
        self._memo: Dict[str, str] = {}
        self._neg: Dict[str, float] = {}  # key -> monotonic expiry
        self._lock = make_lock("RouteTable._lock")
        self._read_lock = make_lock("RouteTable._read_lock")  # single-flight disk reads
        self._read_gen = 0  # bumped after every merged disk read

    def path(self) -> str:
        return os.path.join(cache_dir(), self.filename)

    def _load_disk(self) -> Dict[str, str]:
        try:
            with open(self.path()) as fh:
                got = json.load(fh)
            return got if isinstance(got, dict) else {}
        except Exception:  # noqa: BLE001 - cache is best-effort
            return {}

    def lookup(self, key: str) -> Optional[str]:
        """Memoized verdict for ``key``; None = nothing measured yet.
        A disk re-read happens on first sight and again whenever the
        negative memo's TTL expires — the shared-volume visibility
        window."""
        now = time.monotonic()
        with self._lock:
            got = self._memo.get(key)
            if got is not None:
                return got
            exp = self._neg.get(key)
            if exp is not None and now < exp:
                return None
            gen = self._read_gen
        # single-flight: concurrent missers share ONE disk read. The
        # loser parks on _read_lock while the winner reads; when it
        # gets in and sees the generation advanced past its sample, the
        # winner's merge already covers it — no duplicate open() on the
        # shared volume. A SEQUENTIAL misser samples the post-merge
        # generation and still re-reads, which is the TTL contract.
        with self._read_lock:
            with self._lock:
                merged = self._read_gen != gen
            if not merged:
                disk = self._load_disk()
                with self._lock:
                    for k, v in disk.items():
                        self._memo.setdefault(k, str(v))
                    self._read_gen += 1
        with self._lock:
            got = self._memo.get(key)
            if got is None:
                self._neg[key] = now + neg_ttl_s()
            else:
                self._neg.pop(key, None)
            return got

    def record(self, key: str, verdict: str,
               persist: bool = True) -> None:
        """Land a verdict: memo immediately (retiring THIS key's
        negative), merge-write the disk file when ``persist``, then
        retire only the negatives the merged snapshot actually
        satisfies — blanket-clearing here forced a disk re-read for
        every unrelated pending key on every record."""
        with self._lock:
            self._memo[key] = verdict
            self._neg.pop(key, None)
        if not persist:
            return
        path = self.path()
        try:
            # merge-then-atomic-replace: re-read immediately before the
            # write (narrowing the lost-update window against sibling
            # workers on a shared volume) and land via tmp-then-rename
            # so a crashed writer can never leave a torn file for
            # _load_disk to choke on. Best-effort by design — a lost
            # race costs one re-probe, not correctness.
            disk = self._load_disk()
            disk[key] = verdict
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as fh:
                json.dump(disk, fh, indent=0)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 - persistence is best-effort
            return
        with self._lock:
            # the pre-write merge may have surfaced sibling verdicts:
            # fold them into the memo and retire exactly the negatives
            # they satisfy; fresh negatives for still-absent keys keep
            # their TTL untouched
            for k, v in disk.items():
                self._memo.setdefault(k, str(v))
            for k in [k for k in self._neg if k in self._memo]:
                self._neg.pop(k, None)

    def clear(self) -> None:
        with self._lock:
            self._memo.clear()
            self._neg.clear()
