"""Batched-inference executor: the TPU device runtime.

This replaces the reference's per-partition native-session pattern — ONNX
``initializeOrt`` + NIO tensor marshalling (ref: deep-learning/.../onnx/ONNXModel.scala:173-193,357-402)
and CNTK ``applyModel`` (ref: deep-learning/.../cntk/CNTKModel.scala:89-141) —
with a jit-cache-aware executor:

- **Shape bucketing**: XLA compiles one program per input shape. Batches are
  padded up to power-of-two buckets so an arbitrary row stream triggers O(log n)
  compilations, then runs hot.
- **dtype coercion**: host columns are coerced once (e.g. f64→f32→bf16) before
  a single contiguous ``device_put`` — no per-row marshalling hot loop.
- **Async submit/drain pipeline**: every call rides a per-executor pipeline of
  (a) a bounded host-staging worker pool (coerce + pad off the dispatch
  thread), (b) an ordered dispatch thread that starts the async H2D copy and
  compute, and (c) a dedicated drain thread whose blocking ``device_get``
  never stalls the next batch's staging or dispatch. :meth:`submit` returns a
  future; :meth:`stream` pipelines an iterable with ``pipeline_depth`` batches
  in flight; ``__call__`` is submit+drain — so overlap now happens *across*
  calls and callers, the role ORT's IOBinding plays for the reference, not
  just within one multi-batch call. Inputs are donated to XLA on non-CPU
  backends so same-bucket batches reuse device buffers instead of allocating
  — but only the inputs whose shape/dtype an output can actually alias (see
  :meth:`BatchedExecutor._donate_mask_for`).
- **Multi-device data parallelism**: ``devices=`` fans each padded bucket out
  over a 1-axis ``dp`` mesh via ``NamedSharding`` — ONE jitted program whose
  batch dimension XLA splits across the chips, no collectives for
  per-row programs — the embarrassingly-parallel scoring fan-out the
  reference gets from Spark partitions (ref: ONNXModel.scala:497-508, one
  session per executor). Buckets a topology cannot split evenly (non-pow2
  device counts) fall back to round-robin per-device dispatch: successive
  buckets land whole on successive chips, so the submit/drain pipeline still
  keeps every chip busy. Both layouts sit UNDER the async pipeline — staging,
  H2D, compute, and D2H keep overlapping while compute fans out — and both
  produce bit-identical outputs, in submission order, versus the
  single-device path.
"""
from __future__ import annotations

import atexit
import math
import os
import queue as _queue
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple, Union)

import jax
import jax.numpy as jnp
import numpy as np

from synapseml_tpu.runtime import blackbox as _bb
from synapseml_tpu.runtime import compile_cache as _cc
from synapseml_tpu.runtime import costmodel as _cm
from synapseml_tpu.runtime import faults as _flt
from synapseml_tpu.runtime.locksan import make_lock
from synapseml_tpu.runtime import perfwatch as _pw
from synapseml_tpu.runtime import telemetry as _tm
from synapseml_tpu.runtime.faults import PipelineBrokenError

# module-level metric handles: resolved ONCE (the registry lookup takes
# a lock; inc()/observe() on the handle is lock-free thread-striped —
# see runtime/telemetry.py). Stage semantics for the async pipeline,
# documented in docs/observability.md: "stage" is host coerce+pad wall
# time, "dispatch" the host-side cost of starting H2D+compute,
# "compute" dispatch-end -> drain-pickup (overlap-inclusive: the only
# host-observable bound without a forbidden device sync on the hot
# path), "drain" the blocking device_get.
_M_SUBMIT = _tm.counter("executor_submit_total")
_M_STAGE_S = _tm.histogram("executor_stage_seconds")
_M_DISPATCH_S = _tm.histogram("executor_dispatch_seconds")
_M_COMPUTE_S = _tm.histogram("executor_compute_seconds")
_M_DRAIN_S = _tm.histogram("executor_drain_seconds")
_M_AOT_HIT = _tm.counter("executor_aot_hits_total")
_M_AOT_MISS = _tm.counter("executor_aot_misses_total")
_M_AOT_RETIRED = _tm.counter("executor_aot_retired_total")
_M_DONATE_FB = _tm.counter("executor_donation_fallback_total")
_M_PIPE_RESTARTS = _tm.counter("executor_pipeline_restarts_total")

# -- recompile sentinel (docs/observability.md "Recompile sentinel") --------
# After warmup() has AOT-compiled the executor's full signature set, any
# trace/compile on the dispatch path is an INCIDENT — a mystery latency
# spike with a name. Reasons: "shape_drift" (a signature outside the
# warmed set — usually an unwarmed bucket or feature-width change),
# "arity" (a call arity warmup never saw), "donation_mask" (same
# shapes, different donation annotation — a distinct XLA program),
# "cache_skew" (a warmed executable retired after failing to run — a
# shared cache volume written by a different host). Handles resolved at
# import so the series exist (at 0) on every scrape.
RECOMPILE_REASONS = ("shape_drift", "arity", "donation_mask",
                     "cache_skew")
_M_RECOMPILE = {r: _tm.counter("executor_recompiles_total", reason=r)
                for r in RECOMPILE_REASONS}
# XLA trace+compile wall time by phase: "warmup" = AOT precompiles
# (off the serving path), "dispatch" = a first-call lazy compile ON the
# dispatch path (post-warmup these are exactly the recompiles above),
# "deserialize" = store loads (runtime/compile_cache.py)
_M_COMPILE_WARM_S = _tm.histogram("executor_compile_seconds",
                                  phase="warmup")
_M_COMPILE_DISP_S = _tm.histogram("executor_compile_seconds",
                                  phase="dispatch")

# fault-injection points (runtime/faults.py, docs/robustness.md):
# resolved once at import, fire() is a single attribute test when no
# fault is armed — the hot path pays nothing. The thread_kill points
# sit at the pipeline-loop tops OUTSIDE every per-unit handler, so an
# armed kill terminates the THREAD (the failure mode supervision
# exists to catch), never just one batch.
_F_STAGING = _flt.point("staging")
_F_H2D = _flt.point("h2d")
_F_COMPUTE = _flt.point("compute")
_F_DRAIN = _flt.point("drain")
_F_LAT_DISPATCH = _flt.point("latency", "dispatch")
_F_KILL_STAGE = _flt.point("thread_kill", "stage")
_F_KILL_DISPATCH = _flt.point("thread_kill", "dispatch")
_F_KILL_DRAIN = _flt.point("thread_kill", "drain")


def round_up_pow2(n: int, minimum: int = 8) -> int:
    if n <= minimum:
        return minimum
    return 1 << math.ceil(math.log2(n))


_COERCE = {
    np.dtype(np.float64): np.float32,
    np.dtype(np.int64): np.int32,
    np.dtype(np.uint64): np.uint32,
}


def coerce_host_array(arr: np.ndarray, compute_dtype: Optional[Any] = None) -> np.ndarray:
    """Coerce a host column to a TPU-friendly dtype (f64→f32, i64→i32)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype in _COERCE:
        arr = arr.astype(_COERCE[arr.dtype])
    if compute_dtype is not None and np.issubdtype(arr.dtype, np.floating):
        arr = arr.astype(compute_dtype)
    return arr


def resolve_devices(spec) -> Optional[Tuple[jax.Device, ...]]:
    """Normalize a user-facing device spec to a tuple of devices.

    ``None`` -> None (single default device); ``"all"`` -> every local
    device; an int ``n`` -> the first n local devices; a sequence of
    devices passes through. Raises on anything else so a typo'd spec
    fails at construction, not as a silent single-device run.
    """
    if spec is None:
        return None
    if isinstance(spec, str):
        if spec != "all":
            raise ValueError(
                f"devices spec {spec!r} not understood (use None, 'all', "
                "an int, or a sequence of jax devices)")
        return tuple(jax.local_devices())
    if isinstance(spec, int) and not isinstance(spec, bool):
        local = jax.local_devices()
        if not 0 < spec <= len(local):
            raise ValueError(
                f"devices={spec} but {len(local)} local devices exist")
        return tuple(local[:spec])
    if isinstance(spec, bool):
        # devices=True would satisfy the int branch and silently resolve
        # to ONE device — the opposite of what the caller meant
        raise ValueError("devices=True/False is ambiguous — use 'all', "
                         "an int, or a device sequence")
    devs = tuple(spec)
    if not devs:
        raise ValueError("devices sequence is empty")
    return devs


_SHUTDOWN = object()


class ExecutorFuture:
    """Future-like handle for one :meth:`BatchedExecutor.submit`.

    Resolves to the exact tuple ``__call__`` returns. Assembly (gathering
    per-bucket chunks, slicing padding, concatenating) happens in the
    *waiter's* thread, so the pipeline's drain thread never blocks on
    host-side concatenation of someone else's result.
    """

    __slots__ = ("_chunks",)

    def __init__(self, chunk_futs: Sequence[Future]):
        self._chunks = list(chunk_futs)

    def result(self, timeout: Optional[float] = None):
        """Block until every chunk lands; ``timeout`` is ONE overall
        monotonic deadline across all chunks — waiting n_chunks slow
        chunks can never stretch the total wait past ``timeout``."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        outs = [f.result(
            None if deadline is None
            else max(0.0, deadline - time.monotonic()))
            for f in self._chunks]
        if len(outs) == 1:
            return outs[0]
        return tuple(
            np.concatenate([o[i] for o in outs]) for i in range(len(outs[0]))
        )

    def done(self) -> bool:
        return all(f.done() for f in self._chunks)

    def exception(self, timeout: Optional[float] = None):
        """First chunk error, or None; ``timeout`` is one overall
        deadline, same as :meth:`result`."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        for f in self._chunks:
            exc = f.exception(
                None if deadline is None
                else max(0.0, deadline - time.monotonic()))
            if exc is not None:
                return exc
        return None

    def add_done_callback(self, fn: Callable[["ExecutorFuture"], None]):
        """Invoke ``fn(self)`` once the LAST chunk completes."""
        remaining = [len(self._chunks)]
        lock = make_lock("executor:lock")

        def _one(_f):
            with lock:
                remaining[0] -= 1
                if remaining[0]:
                    return
            fn(self)

        for f in self._chunks:
            f.add_done_callback(_one)


class _Unit:
    """One staging unit: a callable producing 1+ dispatch-ready chunks.

    A plain chunk stages one bucket; a super-chunk (``transfer_batches``)
    stages one grouped H2D copy that fans out into several bucket
    dispatches on device-side slices.
    """

    __slots__ = ("stage", "futs", "staged", "error", "ready", "ex",
                 "spans")

    def __init__(self, n_chunks: int,
                 spans: Optional[Tuple["_tm.Span", ...]] = None):
        self.stage: Callable[[], List[tuple]] = None  # set by _plan
        self.futs = [Future() for _ in range(n_chunks)]
        self.staged: Optional[List[tuple]] = None
        self.error: Optional[BaseException] = None
        self.ready = threading.Event()
        # strong ref while work is pending: 'fut = ex.submit(x); del ex;
        # fut.result()' must complete, not die to a mid-flight GC. The
        # ref is dropped as each stage finishes, so an IDLE executor is
        # still collectable (and its threads reaped via the finalizer).
        self.ex: Optional["BatchedExecutor"] = None
        # trace spans of the requests riding this unit (captured from the
        # submitting thread's ambient context — telemetry.current_spans);
        # written ONLY here at construction, read by the pipeline threads
        self.spans = spans


class _PipelineState:
    """Shared queues/threads of one executor's pipeline.

    Lives OUTSIDE the executor so worker threads can hold it strongly
    while holding the executor itself only weakly — a dropped executor is
    then garbage-collected and its threads reaped via ``weakref.finalize``
    instead of leaking a parked thread set per evicted jit cache entry.
    """

    __slots__ = ("stage_q", "dispatch_q", "inflight_q", "depth_sem",
                 "stage_slots", "lock", "closed", "broken", "pending",
                 "threads", "__weakref__")

    def __init__(self, depth: int, stage_workers: int):
        self.stage_q: "_queue.Queue" = _queue.Queue()
        self.dispatch_q: "_queue.Queue" = _queue.Queue()
        # unbounded queue + explicit semaphore: "in flight" counts
        # dispatched-but-unfetched batches exactly (a bounded queue would
        # let one extra batch hide inside a blocked put)
        self.inflight_q: "_queue.Queue" = _queue.Queue()
        self.depth_sem = threading.Semaphore(depth)
        # backpressure on submit: at most depth + workers staging units
        # may be pending host-side, so a fast producer cannot pin
        # unbounded host memory behind a slow device
        self.stage_slots = threading.Semaphore(depth + stage_workers)
        self.lock = make_lock("_PipelineState.lock")
        self.closed = False
        # supervision: set (under lock) to the PipelineBrokenError when a
        # pipeline thread dies unexpectedly; read by every loop and by
        # submit/_ensure_pipeline (restart trigger)
        self.broken: Optional["PipelineBrokenError"] = None  # synlint: shared
        # every submitted-but-unresolved chunk Future, so a dying thread
        # can fail ALL in-flight work — wherever it sits in the pipeline
        # (stage_q, dispatch_q, inflight_q, or a thread's hands). Futures
        # untrack themselves via done-callback on resolution, so the set
        # is always bounded by the staging window.
        self.pending: set = set()  # synlint: shared
        self.threads: List[threading.Thread] = []


def _untrack_future(state: _PipelineState, fut: Future):
    """Done-callback: a resolved chunk future leaves the supervision
    registry (runs on whichever thread resolved it)."""
    with state.lock:
        state.pending.discard(fut)


def _acquire_or_broken(sem: threading.Semaphore,
                       state: _PipelineState) -> bool:
    """Acquire ``sem``, polling the supervisor's broken flag: a dead
    drain thread (its releases gone with it) must never park the
    dispatch thread forever. False = the pipeline broke while waiting.

    Re-checks ``broken`` AFTER a successful acquire: the permit may be
    the wake-up one :func:`_break_pipeline` released, and dispatching a
    chunk whose future is already failed would burn real device work —
    the permit goes back so the cascade keeps waking other waiters."""
    while True:
        if sem.acquire(timeout=0.2):
            if state.broken is not None:
                sem.release()
                return False
            return True
        if state.broken is not None:
            return False


def _fut_resolve(fut: Future, result=None, error: Optional[BaseException] = None):
    """Resolve a chunk future, tolerating one already failed by
    :func:`_break_pipeline` (the drain/dispatch thread may race the
    supervisor on a unit both hold)."""
    try:
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass


def _stage_worker(state: _PipelineState):
    while True:
        unit = state.stage_q.get()
        if unit is _SHUTDOWN:
            state.stage_q.put(_SHUTDOWN)  # propagate to sibling workers
            return
        # kill point AFTER the get, OUTSIDE the per-unit handler: the
        # armed kill dies with a unit in hand — exactly the failure mode
        # supervision must turn into failed futures, never a hang
        _F_KILL_STAGE.fire()
        t0 = time.monotonic()
        killed = False
        try:
            with _tm.trace_annotation("synapseml/executor/stage"):
                _F_STAGING.fire()
                unit.staged = unit.stage()
        except Exception as e:  # noqa: BLE001 - delivered via futures
            # Exception, not BaseException: a kill (ThreadKilled) must
            # escape to the supervisor and terminate the THREAD — the
            # per-unit handler only converts per-batch errors
            unit.error = e
        except BaseException:
            # dying with the unit in hand: leave ready UNSET — setting
            # it here (staged=None, error=None) would let the dispatch
            # thread race ahead of the supervisor and die on a
            # secondary TypeError, masking the real cause. Dispatch's
            # bounded ready-poll sees state.broken instead.
            killed = True
            raise
        finally:
            unit.stage = None  # drop array refs promptly
            dt = time.monotonic() - t0
            _M_STAGE_S.observe(dt)
            if unit.spans:
                for sp in unit.spans:
                    sp.note("stage", dt)
            if not killed:
                unit.ready.set()


def _dispatch_loop(state: _PipelineState):
    while True:
        unit = state.dispatch_q.get()
        if unit is _SHUTDOWN:
            state.inflight_q.put(_SHUTDOWN)
            return
        _F_KILL_DISPATCH.fire()
        # bounded wait: a stage worker that DIED holding this unit never
        # sets ready — poll the supervisor's broken flag so this thread
        # exits instead of parking forever on a dead handshake
        while not unit.ready.wait(0.2):
            if state.broken is not None:
                break
        try:
            if state.broken is not None:
                # _break_pipeline already failed every pending future;
                # just drop refs and free the slot
                continue
            if unit.error is not None:
                for f in unit.futs:
                    _fut_resolve(f, error=unit.error)
                continue
            ex = unit.ex
            for (arrays, n, bucket, internal), fut in zip(
                    unit.staged, unit.futs):
                if not _acquire_or_broken(state.depth_sem, state):
                    break  # broke while waiting; futures already failed
                t0 = time.monotonic()
                try:
                    # instance-attribute lookup: tests (and tracing
                    # wrappers) may patch ex._dispatch per instance
                    with _tm.trace_annotation(
                            "synapseml/executor/dispatch"):
                        out, n, bucket = (
                            ex._dispatch(arrays, n, bucket, internal=True)
                            if internal else
                            ex._dispatch(arrays, n, bucket))
                except Exception as e:  # noqa: BLE001
                    state.depth_sem.release()
                    _fut_resolve(fut, error=e)
                    continue
                t1 = time.monotonic()
                _M_DISPATCH_S.observe(t1 - t0)
                # the record carries the strong executor ref until the
                # fetch resolves its future (t1 lets the drain side
                # derive the overlap-inclusive compute window without
                # any device sync here)
                state.inflight_q.put(
                    (out, n, bucket, fut, ex, unit.spans, t1))
            del ex
        finally:
            unit.staged = None
            unit.ex = None
            state.stage_slots.release()
            del unit


def _drain_loop(state: _PipelineState):
    while True:
        rec = state.inflight_q.get()
        if rec is _SHUTDOWN:
            return
        _F_KILL_DRAIN.fire()
        out, n, bucket, fut, ex, spans, t_disp = rec
        del rec
        t0 = time.monotonic()
        try:
            err: Optional[BaseException] = None
            try:
                with _tm.trace_annotation("synapseml/executor/drain"):
                    _F_DRAIN.fire()
                    res = ex._fetch(out, n, bucket)
            except Exception as e:  # noqa: BLE001
                err = e
            t1 = time.monotonic()
            # "compute": dispatch-end -> drain-pickup. Overlap-inclusive
            # (in-flight queueing rides along) — the tightest bound a
            # host can observe without a device sync on the hot path.
            # Span notes land BEFORE the future resolves: resolving
            # first would let the reply path finish() the span while
            # these stages are still unrecorded
            _M_COMPUTE_S.observe(t0 - t_disp)
            _M_DRAIN_S.observe(t1 - t0)
            if spans:
                for sp in spans:
                    sp.note("compute", t0 - t_disp)
                    sp.note("drain", t1 - t0)
            if err is not None:
                _fut_resolve(fut, error=err)
            else:
                _fut_resolve(fut, res)
        finally:
            state.depth_sem.release()
            del ex, out, fut, spans


def _shutdown_pipeline(state: _PipelineState):
    """Idempotent: wake every pipeline thread with sentinels. Pending
    units already queued ahead of the sentinels still complete."""
    with state.lock:
        if state.closed:
            return
        state.closed = True
    state.stage_q.put(_SHUTDOWN)
    state.dispatch_q.put(_SHUTDOWN)


def _break_pipeline(state: _PipelineState, exc: BaseException):
    """Supervision: a pipeline thread died unexpectedly. Fail EVERY
    in-flight future with a descriptive :class:`PipelineBrokenError`
    (the contract: no future ever hangs on a dead thread), mark the
    state broken so the owning executor's next submit builds a fresh
    pipeline, and wake the surviving threads so they exit instead of
    parking on dead queues."""
    err = PipelineBrokenError(
        f"executor pipeline thread "
        f"{threading.current_thread().name!r} died: {exc!r}; all "
        "in-flight work failed — the pipeline restarts on the next "
        "submit")
    err.__cause__ = exc
    with state.lock:
        if state.broken is not None:
            return  # a sibling thread already broke the pipeline
        state.broken = err
        state.closed = True
        pending = list(state.pending)
        state.pending.clear()
    _M_PIPE_RESTARTS.inc()
    # incident trigger (runtime/blackbox.py): the break lands in the
    # flight-recorder ring and — debounced — snapshots ring + gauges +
    # thread stacks to the dump dir, so "which thread died holding how
    # much in flight" survives the restart. Runs on the dying thread,
    # no locks held, and never raises back into supervision.
    _bb.trigger("pipeline_break",
                thread=threading.current_thread().name,
                n_inflight=len(pending), error=repr(exc)[:200])
    for fut in pending:
        try:
            fut.set_exception(err)
        except InvalidStateError:
            pass  # resolved in the race window — even better
    # sentinels for every loop; surviving threads drain to them and exit
    state.stage_q.put(_SHUTDOWN)
    state.dispatch_q.put(_SHUTDOWN)
    state.inflight_q.put(_SHUTDOWN)
    # wake anything parked on backpressure: ONE extra permit cascades —
    # each blocked submitter wakes, sees closed, releases it back, and
    # raises; the dispatch loop likewise never waits on a dead drain
    state.stage_slots.release()
    state.depth_sem.release()
    _reap_broken_pipeline(state)


def _reap_broken_pipeline(state: _PipelineState):
    """Post-break cleanup, run on the dying thread (cold path): wait for
    the surviving loops to drain to their sentinels, then empty the dead
    queues. Stranded ``inflight_q`` records would otherwise pin device
    output buffers (and the executor, via their ``ex`` field) for the
    life of the process — the superseded state stays strongly reachable
    — and permanently inflate the scrape-time depth gauges. Sentinels
    are re-put afterwards so a straggler that outlived the join timeout
    still exits instead of parking on an emptied queue."""
    me = threading.current_thread()
    for t in state.threads:
        if t is not me:
            t.join(timeout=5)
    for q in (state.stage_q, state.dispatch_q, state.inflight_q):
        while True:
            try:
                q.get_nowait()
            except _queue.Empty:
                break
    state.stage_q.put(_SHUTDOWN)
    state.dispatch_q.put(_SHUTDOWN)
    state.inflight_q.put(_SHUTDOWN)


def _pipeline_thread(target, state: _PipelineState):
    """Thread entry for every pipeline loop: an escaped exception —
    including an injected :class:`~synapseml_tpu.runtime.faults.ThreadKilled`
    — breaks the pipeline instead of dying silently with every in-flight
    future deadlocked."""
    try:
        target(state)
    except BaseException as e:  # noqa: BLE001 - supervision boundary
        _break_pipeline(state, e)


# Pipeline threads still parked inside the XLA runtime at interpreter
# shutdown abort the process ("terminate called without an active
# exception" from the PJRT client destructor racing frozen daemon
# threads). Drain every live pipeline while threading still works.
_LIVE_PIPELINES: "weakref.WeakSet[_PipelineState]" = weakref.WeakSet()

# pipeline-depth gauges, sampled at scrape time (never the hot path):
# dispatched-but-unfetched batches and staged-but-undispatched units
# across every live executor pipeline in the process
_tm.gauge_fn(
    "executor_inflight_batches",
    lambda: sum(s.inflight_q.qsize() for s in list(_LIVE_PIPELINES)))
_tm.gauge_fn(
    "executor_staging_queue_depth",
    lambda: sum(s.stage_q.qsize() for s in list(_LIVE_PIPELINES)))


@atexit.register
def _shutdown_all_pipelines():
    states = list(_LIVE_PIPELINES)
    for state in states:
        _shutdown_pipeline(state)
    for state in states:
        for t in state.threads:
            t.join(timeout=10)


class BatchedExecutor:
    """Runs ``fn(*arrays) -> arrays`` over row batches with a bucketed jit cache.

    ``fn`` must treat axis 0 of every argument as the batch axis. The executor
    pads the batch to a bucket size, runs the compiled program, and slices the
    padding off the outputs.

    Execution rides an async submit/drain pipeline (host staging pool →
    ordered dispatch thread → drain thread) shared by all callers of this
    executor, with up to ``pipeline_depth`` batches in flight at once:

    - :meth:`submit` — non-blocking-ish; returns an :class:`ExecutorFuture`.
    - :meth:`stream` — generator over an iterable of inputs, yielding
      results in order with ``pipeline_depth`` batches in flight.
    - ``__call__`` — submit + drain: identical outputs and donation/
      bucketing semantics to the historical synchronous path.
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        device: Optional[jax.Device] = None,
        compute_dtype: Any = None,
        min_bucket: int = 8,
        max_bucket: Optional[int] = None,
        static_batch: Optional[int] = None,
        bound_args: Tuple[Any, ...] = (),
        pipeline_depth: Optional[int] = None,
        donate: Optional[bool] = None,
        transfer_batches: Union[int, str, None] = None,
        stage_workers: int = 2,
        devices: Union[None, str, int, Sequence[jax.Device]] = None,
        cache_key: Optional[str] = None,
        cache_dir: Optional[str] = None,
        tensor_parallel: int = 1,
        bound_specs: Optional[Tuple[Any, ...]] = None,
        tp_compute: str = "gather",
        device_outputs: Optional[Sequence[int]] = None,
    ):
        """``bound_args`` are prepended to every call unpadded — use for a
        weights pytree so it is device-resident and *shared* across all shape
        buckets instead of baked into each compiled program as constants.

        ``donate=None`` donates batch inputs to XLA whenever the target
        backend is not CPU (CPU ignores donation and would warn). Only
        inputs whose shape/dtype some output can alias are annotated —
        see :meth:`_donate_mask_for`.

        ``transfer_batches`` groups that many compute buckets into ONE
        explicit host->device copy (compute then runs per bucket on
        device-side slices); ``"auto"`` sizes the group to ~32MB per
        copy. Default 1 — measured on the tunneled v5e, per-bucket
        numpy arg-staging through the pipelined jit dispatch beats
        explicit grouped device_put for BOTH large image batches
        (100 vs 77 img/s) and small tabular rows (34k vs 26k rows/s);
        the option exists for co-located topologies where explicit DMA
        grouping can win (docs/perf.md records the A/Bs).

        ``stage_workers`` bounds the host-staging pool: that many batches'
        coerce+pad host work can proceed concurrently with dispatch and
        fetch of earlier batches.

        ``devices`` turns on multi-device data parallelism: ``"all"``,
        an int, or an explicit device sequence (:func:`resolve_devices`).
        Buckets divisible by the device count are sharded over a 1-axis
        ``dp`` mesh (one jit, batch dim split); indivisible buckets
        dispatch round-robin, one whole bucket per device. A one-element
        ``devices`` degenerates to the pinned single-device path.

        ``cache_dir`` (default: the ``SYNAPSEML_COMPILE_CACHE`` env var)
        wires JAX's persistent compilation cache and — together with
        ``cache_key``, the caller's content hash over graph/weights
        config — enables the serialized-executable store that
        :meth:`warmup` persists AOT-compiled buckets into, so a
        restarted process deserializes instead of recompiling
        (runtime/compile_cache.py). Any miss, version skew, or corrupt
        entry silently degrades to a fresh compile.

        ``tensor_parallel`` > 1 splits ``devices`` into a 2-axis
        ``dp×tp`` mesh (``dp = len(devices) // tensor_parallel``): the
        batch still shards over ``dp`` only, while ``bound_specs`` — a
        tuple aligned with ``bound_args`` holding a PartitionSpec
        pytree per bound arg (or None to replicate one) — places the
        weights over ``tp`` by the partition-rule registry's matched
        specs (parallel/partition_rules.py). GSPMD carries the layouts
        through the program; the mesh shape is folded into both the
        AOT warmup keys and the executable-store keys, so tp=2 and
        tp=4 restarts of the same model never collide and the
        recompile sentinel stays silent across resharding.

        ``tp_compute`` picks the compute formulation under tp > 1:

        - ``"gather"`` (default): weights live tp-sharded AT REST (the
          per-device HBM and /debug/memory story) but are all-gathered
          at function entry via a replicate sharding constraint, so
          every matmul runs the exact single-device formulation —
          replies are BITWISE identical to tp=1 (the capture/replay
          digest contract), because an all-gather is a concatenation,
          not a reduction.
        - ``"sharded"``: true tensor-parallel compute — GSPMD keeps the
          weights sharded through the matmuls. Minimum peak memory,
          but cross-shard partial sums reassociate float adds:
          measured ~1e-6 drift vs tp=1 on the transformer zoo model,
          which breaks digest stability across reshardings. Opt in
          when capacity matters more than replay equality.

        ``device_outputs`` lists output-leaf indices (position in the
        flattened output tuple) the fetch stage must NOT copy to host:
        those leaves resolve as live ``jax.Array``s, ready to be fed
        straight back into the next ``submit`` — the decode scheduler's
        KV-cache contract, where per-step device->host->device round
        trips of the whole cache would drown the step itself. The fetch
        still blocks until the leaf is computed, so futures keep their
        "resolved means done" meaning."""
        devices = resolve_devices(devices)
        if devices is not None and device is not None:
            raise ValueError("pass either device= or devices=, not both")
        tp = max(1, int(tensor_parallel))
        if tp_compute not in ("gather", "sharded"):
            raise ValueError(
                f"tp_compute={tp_compute!r} (expected 'gather' or "
                "'sharded')")
        if tp > 1:
            if devices is None:
                raise ValueError(
                    f"tensor_parallel={tp} requires devices= (a multi-"
                    "device topology to partition over)")
            if len(devices) % tp:
                raise ValueError(
                    f"tensor_parallel={tp} does not divide the "
                    f"{len(devices)}-device topology")
        if devices is not None and len(devices) == 1:
            device, devices = devices[0], None
        self._device = device
        self._devices = devices
        self._tp = tp if devices is not None else 1
        self._dp = (len(devices) // self._tp if devices is not None else 1)
        self._tp_compute = tp_compute if self._tp > 1 else "gather"
        if devices is not None:
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec)  # local: cheap import
            if self._tp > 1:
                # batch over dp, params over tp: P("dp") on a 2-axis
                # mesh replicates the batch across tp ranks, which each
                # hold their registry-matched weight shard
                self._mesh = Mesh(
                    np.asarray(devices).reshape(self._dp, self._tp),
                    ("dp", "tp"))
            else:
                self._mesh = Mesh(np.asarray(devices), ("dp",))
            self._shard_data = NamedSharding(self._mesh, PartitionSpec("dp"))
            self._shard_repl = NamedSharding(self._mesh, PartitionSpec())
        else:
            self._mesh = self._shard_data = self._shard_repl = None
        self._compute_dtype = compute_dtype
        self._min_bucket = min_bucket
        self._max_bucket = max_bucket
        self._static_batch = static_batch
        if pipeline_depth is None:
            # multi-device default: the round-robin layout parallelizes
            # ACROSS in-flight buckets, so the depth must cover the
            # topology (+1 so drain of the oldest overlaps dispatch of
            # the newest) or at most `depth` chips ever compute at once;
            # single-device keeps the measured default of 2
            pipeline_depth = 2 if devices is None else len(devices) + 1
        self._depth = max(1, int(pipeline_depth))
        self._stage_workers = max(1, int(stage_workers))
        if devices is not None:
            # weights placed once across the mesh: by their matched
            # PartitionSpecs when the caller passed bound_specs (the
            # tensor-parallel layout), replicated otherwise — every
            # shard of a dp-split batch (and the sharded jit) reads its
            # local copy/shard either way
            from jax.sharding import NamedSharding as _NS
            specs = tuple(bound_specs or ())
            placed = []
            for i, b in enumerate(bound_args):
                spec_tree = specs[i] if i < len(specs) else None
                if spec_tree is None:
                    placed.append(jax.tree_util.tree_map(
                        lambda a: jax.device_put(a, self._shard_repl), b))
                else:
                    # PartitionSpec is a pytree leaf, so a dict of specs
                    # zips against a params dict directly
                    placed.append(jax.tree_util.tree_map(
                        lambda a, s: jax.device_put(
                            a, _NS(self._mesh, s)), b, spec_tree))
            self._bound = tuple(placed)
        else:
            self._bound = tuple(
                jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, device) if device
                    else jnp.asarray(a), b) for b in bound_args)
        # round-robin fallback state: per-device bound-arg replicas (lazy,
        # also touched by warmup on the caller's thread) and the
        # next-device cursor — both under _tables_lock (set just below)
        self._bound_rr: Dict[int, tuple] = {}  # synlint: shared
        self._rr_next = 0  # synlint: shared
        plat = (device.platform if device is not None
                else devices[0].platform if devices is not None
                else jax.default_backend())
        if donate is None:
            donate = plat not in ("cpu",)
        self._donate = bool(donate)
        if transfer_batches is None:
            transfer_batches = 1
        elif transfer_batches != "auto":
            transfer_batches = max(1, int(transfer_batches))
        self._transfer_batches = transfer_batches  # "auto" = ~32MB groups
        self._device_outputs = (frozenset(int(i) for i in device_outputs)
                                if device_outputs is not None
                                else frozenset())
        if self._tp > 1 and self._tp_compute == "gather":
            # bitwise contract: constrain every bound leaf back to
            # replicated INSIDE the program — XLA all-gathers the
            # tp-sharded weights at entry (exact concatenation, no
            # reduction) and the matmuls run the proven dp-only
            # formulation. GSPMD is otherwise free to keep activations
            # sharded through row-parallel contractions, and the psum
            # it inserts reassociates float adds (measured 1e-6 drift)
            _nb = len(bound_args)
            _repl = self._shard_repl

            def _gathered(*a, _inner=fn, _nb=_nb, _repl=_repl):
                gathered = tuple(
                    jax.tree_util.tree_map(
                        lambda x: jax.lax.with_sharding_constraint(
                            x, _repl), t)
                    for t in a[:_nb])
                return _inner(*gathered, *a[_nb:])
            self._fn = _gathered
        else:
            self._fn = fn
        # donation indices depend on the call arity AND on which inputs an
        # output can alias (shape/dtype match) — one jitted callable per
        # (arity, donate-mask); jax itself caches executables per input
        # sharding/placement under each callable, which keeps per-bucket
        # compiles separate per layout (single / dp-sharded / per-device)
        #
        # _tables_lock guards every compiled-artifact table below: they
        # are written from caller threads (submit's eager mask prewarm,
        # warmup) AND from the dispatch thread, and an unguarded
        # check-then-set loses one thread's jit wrapper — with its
        # per-executable cache — to the other's overwrite. Slow work
        # (eval_shape, device_put, .lower().compile()) always happens
        # OUTSIDE the lock; only the dict get/setdefault is guarded.
        self._tables_lock = make_lock("BatchedExecutor._tables_lock")
        self._jits: Dict[Tuple[int, Tuple[bool, ...]], Callable] = {}  # synlint: shared
        self._donate_masks: Dict[tuple, Tuple[bool, ...]] = {}  # synlint: shared
        self._pipeline: Optional[_PipelineState] = None
        self._pipeline_init_lock = make_lock("BatchedExecutor._pipeline_init_lock")
        # user-initiated close(): permanent, unlike a supervision break
        # (which only closes ONE _PipelineState and restarts on submit)
        self._closed = False  # synlint: shared
        self._finalizer = None
        # -- persistent compile cache / AOT warmup state ----------------
        resolved_dir = cache_dir if cache_dir is not None \
            else _cc.default_cache_dir()
        self._cache_key = cache_key
        self._store: Optional[_cc.ExecutableStore] = None
        if resolved_dir:
            _cc.enable_persistent_cache(resolved_dir)  # layer 1: XLA cache
            if cache_key:
                self._store = _cc.ExecutableStore(
                    os.path.join(resolved_dir, "executables"))
        # AOT-compiled executables from warmup(), keyed by
        # (input sig, donate mask, layout, rr device index) — consulted
        # by _dispatch before the lazy jit path; written by warmup
        # (caller thread) and retired by _dispatch (dispatch thread),
        # so access rides _tables_lock too
        self._aot: Dict[tuple, Any] = {}  # synlint: shared
        self._aot_hits = 0  # synlint: shared
        # -- recompile-sentinel state (under _tables_lock too) ----------
        # warmup() flips _warmed and records what it compiled so a
        # post-warmup lazy compile on the dispatch path can be counted
        # AND classified: _warm_masks maps each warmed input signature
        # to its donation masks, _warm_arities the call arities warmup
        # covered, _lazy_seen every (sig, mask, layout, device) the lazy
        # jit path has already compiled (so only FIRST calls — the ones
        # that actually trace+compile — are timed and counted)
        self._warmed = False  # synlint: shared
        self._warm_masks: Dict[tuple, set] = {}  # synlint: shared
        self._warm_arities: set = set()  # synlint: shared
        self._lazy_seen: set = set()  # synlint: shared
        # -- telemetry handles (resolved here, off the hot path) --------
        # per-device dispatch counters: one series per target the
        # dispatch thread can route a bucket to — rr/single layouts
        # count per chip, a dp-sharded bucket counts ONCE under its
        # mesh label, so the sum across series is always total batches
        if devices is not None and self._tp > 1:
            # tp×dp mesh: every bucket rides the one sharded jit (or its
            # replicated-input variant) — no round-robin lane, one mesh
            # label so the series sum stays total batches
            self._mesh_label = f"dp{self._dp}xtp{self._tp}"
            self._m_disp_rr = ()
            self._m_disp_one = _tm.counter(
                "executor_dispatch_total", device=self._mesh_label)
        elif devices is not None:
            self._mesh_label = f"dp{len(devices)}"
            self._m_disp_rr = tuple(
                _tm.counter("executor_dispatch_total", device=str(d.id))
                for d in devices)
            self._m_disp_one = _tm.counter(
                "executor_dispatch_total", device=self._mesh_label)
        else:
            self._mesh_label = (str(device.id) if device is not None
                                else "default")
            self._m_disp_rr = ()
            self._m_disp_one = _tm.counter(
                "executor_dispatch_total", device=self._mesh_label)
        self._m_bucket: Dict[int, _tm.Counter] = {}
        # performance observatory (runtime/perfwatch.py): per-device
        # memory gauges once per process, plus a duty-cycle gauge per
        # dispatch target this executor counts under — both sampled at
        # scrape time only, nothing on the hot path
        _pw.ensure_registered()
        if devices is not None and self._tp > 1:
            _pw.register_duty_gauge(self._mesh_label)
        elif devices is not None:
            for d in devices:
                _pw.register_duty_gauge(str(d.id))
            _pw.register_duty_gauge(self._mesh_label)
        else:
            _pw.register_duty_gauge(self._mesh_label)
        # per-device parameter residency: the placed bound args' actual
        # shard bytes feed the tp_param_bytes{device=} gauges — the
        # checkable form of "the model no longer fits on one chip"
        # (cleared when the executor is dropped; close() clears eagerly)
        self._tp_bytes_owner: Optional[int] = None
        if devices is not None and self._bound:
            from synapseml_tpu.parallel.onnx_tp import param_bytes_per_device
            per_dev = param_bytes_per_device(self._bound)
            self._tp_bytes_owner = _pw.record_tp_param_bytes(
                {str(d.id): int(n) for d, n in per_dev.items()})
            weakref.finalize(self, _pw.clear_tp_param_bytes,
                             self._tp_bytes_owner)

    @property
    def pipeline_depth(self) -> int:
        return self._depth

    @property
    def devices(self) -> Optional[Tuple[jax.Device, ...]]:
        return self._devices

    @property
    def n_devices(self) -> int:
        return len(self._devices) if self._devices is not None else 1

    def _jit_for(self, n_args: int,
                 mask: Tuple[bool, ...] = ()) -> Callable:
        # wrapper construction is cheap (no trace/compile), so it can sit
        # inside the lock — an unguarded check-then-set here let warmup
        # (caller thread) and _dispatch (dispatch thread) each build a
        # wrapper and one overwrite the other, orphaning every executable
        # jax had cached under the loser
        with self._tables_lock:
            got = self._jits.get((n_args, mask))
            if got is None:
                donate = tuple(len(self._bound) + i
                               for i, m in enumerate(mask) if m)
                got = jax.jit(self._fn, donate_argnums=donate)
                self._jits[(n_args, mask)] = got
        return got

    def _donate_mask_for(self, padded: Sequence[Any]) -> Tuple[bool, ...]:
        """Which batch inputs to donate: only those whose (shape, dtype)
        some output leaf can actually alias. Donating a buffer no output
        matches makes XLA warn "Some donated buffers were not usable" per
        compile and donates nothing — the annotation must match the real
        buffer layouts. Greedy multiset matching on abstract shapes via
        ``eval_shape`` (no compile, no execution), cached per input
        signature. ``padded`` may hold arrays or ShapeDtypeStructs."""
        if not self._donate or not padded:
            return (False,) * len(padded)
        return self._donate_mask_for_sig(tuple(
            (tuple(a.shape), jnp.dtype(a.dtype).name) for a in padded))

    def _donate_mask_for_sig(self, sig: tuple) -> Tuple[bool, ...]:
        """Sig-keyed body of :meth:`_donate_mask_for` — also called
        EAGERLY from :meth:`submit` (the caller's thread) and from
        :meth:`warmup`, so the dispatch thread normally just reads the
        cache: platform plugins whose trace hooks misbehave off the main
        thread (the residual bench-tail donation warnings) never get a
        chance to poison the mask."""
        if not self._donate or not sig:
            return (False,) * len(sig)
        with self._tables_lock:
            got = self._donate_masks.get(sig)
        if got is None:
            try:
                specs = [jax.ShapeDtypeStruct(s, jnp.dtype(d))
                         for s, d in sig]
                out = jax.eval_shape(self._fn, *self._bound, *specs)
                avail: Dict[tuple, int] = {}
                for l in jax.tree_util.tree_leaves(out):
                    k = (tuple(l.shape), jnp.dtype(l.dtype).name)
                    avail[k] = avail.get(k, 0) + 1
                mask = []
                for k in sig:
                    if avail.get(k, 0) > 0:
                        avail[k] -= 1
                        mask.append(True)
                    else:
                        mask.append(False)
                got = tuple(mask)
            except Exception:  # noqa: BLE001 - eval_shape is best-effort
                # donate NOTHING when the outputs can't be verified: an
                # unverifiable donate-all annotation is what produced the
                # per-compile "Some donated buffers were not usable"
                # warning spam in the bench tails — donation is an
                # optimization, silence + correctness beat a blind bet
                got = (False,) * len(sig)
                _M_DONATE_FB.inc()
            # eval_shape ran OUTSIDE the lock (it traces self._fn);
            # setdefault keeps concurrent computers consistent — every
            # thread returns the first writer's mask
            with self._tables_lock:
                got = self._donate_masks.setdefault(sig, got)
        return got

    def _staged_dtype(self, dt: Any, device_rules: bool = False):
        """The dtype staging will hand ``_dispatch`` for an input of host
        dtype ``dt`` — mirrors :func:`coerce_host_array` (host inputs) or
        :meth:`_stage_device_array` (``device_rules=True``), so ahead-of-
        time signatures match what the pipeline actually dispatches."""
        dt = np.dtype(dt)
        if not device_rules and dt in _COERCE:
            dt = np.dtype(_COERCE[dt])
        if self._compute_dtype is not None:
            is_float = (jnp.issubdtype(dt, jnp.floating) if device_rules
                        else np.issubdtype(dt, np.floating))
            if is_float:
                dt = jnp.dtype(self._compute_dtype)
        return jnp.dtype(dt)

    def _staged_sig(self, host_arrays: Sequence[Any],
                    bucket: int) -> Optional[tuple]:
        """Input signature (shapes+dtypes) the staged bucket will have,
        computed WITHOUT staging; None when an input carries no
        shape/dtype (lists etc. — the dispatch-side path still covers
        those)."""
        sig = []
        for a in host_arrays:
            if not (hasattr(a, "shape") and hasattr(a, "dtype")):
                return None
            sig.append((
                (bucket,) + tuple(a.shape)[1:],
                self._staged_dtype(
                    a.dtype, device_rules=isinstance(a, jax.Array)).name))
        return tuple(sig)

    def _stage_device_array(self, a: jax.Array, target_rows: int,
                            placement: Any = None):
        """Pad/coerce/place an already-device-resident array entirely on
        device. ``placement`` is a device, a sharding, or None (leave
        where it is). Returns ``(array, fresh)`` — ``fresh`` is True when
        a new buffer was definitely created (safe to donate)."""
        fresh = False
        if len(a) != target_rows:
            pad = [(0, target_rows - len(a))] + [(0, 0)] * (a.ndim - 1)
            a = jnp.pad(a, pad)
            fresh = True
        if (self._compute_dtype is not None
                and jnp.issubdtype(a.dtype, jnp.floating)
                and a.dtype != jnp.dtype(self._compute_dtype)):
            a = a.astype(self._compute_dtype)
            fresh = True
        if placement is not None:
            try:
                if isinstance(placement, jax.Device):
                    misplaced = a.device != placement
                else:  # a NamedSharding: reshard unless already identical
                    misplaced = a.sharding != placement
            except Exception:  # multi-device/sharded array
                misplaced = True
            if misplaced:
                a = jax.device_put(a, placement)
                fresh = True
        return a, fresh

    # -- multi-device layout --------------------------------------------
    def _layout(self, bucket: int) -> str:
        """Sharding layout for one bucket: ``"shard"`` when the batch
        dimension splits evenly over the dp mesh (single jit, no
        collectives for per-row programs), ``"rr"`` (round-robin whole
        buckets onto successive devices) when it cannot — non-pow2
        topologies, or buckets smaller than the device count — and
        ``"single"`` without ``devices``.

        Under ``tensor_parallel`` > 1 the round-robin fallback is
        unsound — the weights live sharded across ALL devices, so no
        single chip can run a whole bucket — and a dp-indivisible
        bucket instead rides ``"tp_rep"``: the same mesh-wide jit with
        the batch replicated (every tp rank still computes only its
        weight shard; GSPMD inserts the collectives either way)."""
        if self._devices is None:
            return "single"
        if self._tp > 1:
            return "shard" if bucket % self._dp == 0 else "tp_rep"
        return "shard" if bucket % len(self._devices) == 0 else "rr"

    def _bound_for_device(self, dev: jax.Device) -> tuple:
        """Per-device bound-arg replicas for the round-robin path. Lazily
        extracted from the mesh-replicated copies (each chip already holds
        a shard-local replica; device_put pins a committed single-device
        view for the per-device jit)."""
        with self._tables_lock:
            got = self._bound_rr.get(dev.id)
        if got is None:
            # the H2D replica transfer stays outside the lock; a racing
            # warmup/dispatch pair may both transfer, setdefault picks
            # one winner so every caller shares the same device buffers
            got = tuple(
                jax.tree_util.tree_map(lambda a: jax.device_put(a, dev), b)
                for b in self._bound)
            with self._tables_lock:
                got = self._bound_rr.setdefault(dev.id, got)
        return got

    def _bucket(self, n: int) -> int:
        if self._static_batch is not None:
            return self._static_batch
        b = round_up_pow2(n, self._min_bucket)
        if self._max_bucket is not None:
            b = min(b, self._max_bucket)
        return b

    # -- pipeline plumbing ----------------------------------------------
    def _ensure_pipeline(self) -> _PipelineState:
        state = self._pipeline
        if state is not None and state.broken is None:
            return state
        with self._pipeline_init_lock:
            state = self._pipeline
            if (state is not None and state.broken is not None
                    and not self._closed):
                # supervision restart: the broken state already failed
                # its in-flight futures and its threads are exiting —
                # drop it so subsequent submits ride a fresh pipeline.
                # Detach the superseded finalizer: its registry entry
                # would otherwise hold the dead state strongly for the
                # life of the executor (leaked queues + phantom gauges)
                if self._finalizer is not None:
                    self._finalizer.detach()
                self._pipeline = state = None
            if state is None:
                state = _PipelineState(self._depth, self._stage_workers)
                threads = [threading.Thread(
                    target=_pipeline_thread, args=(_stage_worker, state),
                    name=f"executor-stage-{i}", daemon=True)
                    for i in range(self._stage_workers)]
                threads.append(threading.Thread(
                    target=_pipeline_thread, args=(_dispatch_loop, state),
                    name="executor-dispatch", daemon=True))
                threads.append(threading.Thread(
                    target=_pipeline_thread, args=(_drain_loop, state),
                    name="executor-drain", daemon=True))
                state.threads = threads
                _LIVE_PIPELINES.add(state)
                for t in threads:
                    t.start()
                self._pipeline = state
                # reap the threads when the executor is dropped (e.g. jit
                # cache eviction) without requiring an explicit close()
                self._finalizer = weakref.finalize(
                    self, _shutdown_pipeline, state)
        return state

    def close(self, wait: bool = True):
        """Shut the pipeline down. Batches already submitted complete
        (their futures resolve); later :meth:`submit` calls raise.
        Idempotent; ``wait=True`` joins the pipeline threads."""
        with self._pipeline_init_lock:
            # under the init lock: _ensure_pipeline must never rebuild a
            # broken pipeline after (or while) close() marks the
            # executor permanently closed
            self._closed = True
        if self._tp_bytes_owner is not None:
            _pw.clear_tp_param_bytes(self._tp_bytes_owner)
            self._tp_bytes_owner = None
        state = self._pipeline
        if state is None:
            with self._pipeline_init_lock:
                # never-started pipeline: mark closed so submit refuses
                if self._pipeline is None:
                    self._pipeline = state = _PipelineState(
                        self._depth, self._stage_workers)
                    state.closed = True
                    return
                state = self._pipeline
        _shutdown_pipeline(state)
        if wait:
            for t in state.threads:
                t.join(timeout=60)

    def _resolve_transfer_batches(self, host_arrays, bucket: int):
        tb = self._transfer_batches
        if self._devices is not None:
            # multi-device: per-bucket staging only — a grouped device_put
            # would pin the super-chunk to one chip and every bucket slice
            # would reshard off it, serializing the fan-out
            return 1
        if tb != "auto":
            return tb
        # group buckets up to ~32MB per explicit copy (shape/dtype
        # only — np.asarray on a device array would force a D2H copy)
        row_bytes = 0
        for a in host_arrays:
            a0 = a if hasattr(a, "shape") and hasattr(a, "dtype") \
                else np.asarray(a)
            itemsize = 2 if (self._compute_dtype is not None
                             and jnp.issubdtype(a0.dtype, jnp.floating)) \
                else min(a0.dtype.itemsize, 4)
            row_bytes += int(np.prod(a0.shape[1:], dtype=np.int64)) \
                * itemsize
        return max(1, (32 << 20) // max(1, bucket * row_bytes))

    def _stage_host_chunk(self, arrays, n: int, bucket: int):
        """Host-side staging (the work the pool does off the dispatch
        thread): coerce + bucket-pad numpy inputs. Device-resident inputs
        pass through untouched so ``_dispatch`` applies its external-array
        rules (on-device pad/coerce, defensive copy before donation)."""
        staged = []
        for a in arrays:
            if isinstance(a, jax.Array):
                staged.append(a)
                continue
            a = coerce_host_array(np.asarray(a), self._compute_dtype)
            if n < bucket and len(a) < bucket:  # never re-pad a padded tail
                pad = [(0, bucket - n)] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            staged.append(a)
        return staged

    def _stage_superchunk(self, host_arrays, sc_start: int, sc_stop: int,
                          bucket: int):
        """super-chunk: ONE coerce+pad+copy for transfer_batches buckets,
        then per-bucket compute on device-side slices. device_put is
        unconditional here — with device=None it targets the default
        device; leaving host numpy would quietly re-copy per bucket
        and void the whole point of grouping."""
        sc_n = sc_stop - sc_start
        rows = -(-sc_n // bucket) * bucket
        devs = []
        for a in host_arrays:
            sl = a[sc_start:sc_stop]
            if isinstance(sl, jax.Array):
                # already device-resident: pad/coerce on device, no
                # host round trip
                devs.append(
                    self._stage_device_array(sl, rows, self._device)[0])
                continue
            sl = coerce_host_array(np.asarray(sl), self._compute_dtype)
            if rows > sc_n:
                sl = np.pad(sl,
                            [(0, rows - sc_n)] + [(0, 0)] * (sl.ndim - 1))
            devs.append(jax.device_put(sl, self._device))
        return [([d[b:b + bucket] for d in devs],
                 min(bucket, sc_n - b), bucket, True)
                for b in range(0, sc_n, bucket)]

    def _plan(self, host_arrays, n: int, bucket: int,
              spans: Optional[tuple] = None) -> List[_Unit]:
        """Split one logical call into ordered staging units."""
        if n == 0:
            # run one padded batch to learn output structure; slice to empty
            unit = _Unit(1, spans)
            unit.ex = self
            arrays = list(host_arrays)
            unit.stage = lambda: [(self._stage_host_chunk(arrays, 0, bucket),
                                   0, bucket, False)]
            return [unit]
        units: List[_Unit] = []
        tb = self._resolve_transfer_batches(host_arrays, bucket)
        super_rows = bucket * tb
        for sc_start in range(0, n, super_rows):
            sc_stop = min(sc_start + super_rows, n)
            sc_n = sc_stop - sc_start
            if tb == 1 or sc_n <= bucket:
                unit = _Unit(1, spans)
                unit.stage = (
                    lambda s=sc_start, e=sc_stop, m=sc_n:
                    [(self._stage_host_chunk(
                        [a[s:e] for a in host_arrays], m, bucket),
                      m, bucket, False)])
            else:
                unit = _Unit(-(-sc_n // bucket), spans)
                unit.stage = (
                    lambda s=sc_start, e=sc_stop:
                    self._stage_superchunk(host_arrays, s, e, bucket))
            unit.ex = self
            units.append(unit)
        return units

    # -- public API -----------------------------------------------------
    def submit(self, *host_arrays: np.ndarray) -> ExecutorFuture:
        """Enqueue one logical batch; returns a future resolving to the
        same tuple ``__call__`` returns. Safe to call from any number of
        threads concurrently — staging, device dispatch, and D2H fetch of
        different submissions overlap through the shared pipeline. Blocks
        only when the staging window (``pipeline_depth + stage_workers``
        units) is full — backpressure, not serialization.

        Staging reads the input arrays asynchronously: do not mutate
        them until the returned future resolves."""
        state = self._ensure_pipeline()
        _M_SUBMIT.inc()
        n = len(host_arrays[0])
        bucket = self._bucket(max(n, 1))
        # ambient trace spans (the serving scorer's micro-batch) ride the
        # units so the pipeline threads can annotate per-request stages
        spans = _tm.current_spans()
        if self._donate:
            # resolve the donate mask on the CALLER's thread (cached per
            # sig): the dispatch thread then only reads the cache — see
            # _donate_mask_for_sig
            sig = self._staged_sig(host_arrays, bucket)
            if sig is not None:
                try:
                    self._donate_mask_for_sig(sig)
                except Exception:  # noqa: BLE001 - best-effort prewarm
                    pass
        units = self._plan(host_arrays, n, bucket, spans)
        futs: List[Future] = []
        for unit in units:
            # slot acquisition happens OUTSIDE the lock: a large
            # multi-unit submission waiting for the pipeline to drain
            # must not convoy other callers' submits behind it.
            # Concurrent submitters may interleave units — harmless,
            # since every unit's chunks resolve through its own futures;
            # only the stage_q/dispatch_q pair must agree on order,
            # which the per-unit lock below guarantees
            state.stage_slots.acquire()
            with state.lock:
                if state.closed:
                    state.stage_slots.release()
                    if state.broken is not None and not self._closed:
                        # the narrow window between a thread dying and
                        # supervision swapping the pipeline: surface the
                        # transient error (serving retries it) rather
                        # than a permanent-sounding "closed"
                        raise PipelineBrokenError(
                            "submitted during the pipeline-restart "
                            f"window: {state.broken}") from state.broken
                    raise RuntimeError("executor pipeline is closed")
                state.stage_q.put(unit)
                state.dispatch_q.put(unit)
                state.pending.update(unit.futs)
            for f in unit.futs:
                f.add_done_callback(
                    lambda f, s=state: _untrack_future(s, f))
            futs.extend(unit.futs)
        return ExecutorFuture(futs)

    def stream(self, items: Iterable) -> Iterator[Tuple[np.ndarray, ...]]:
        """Pipeline an iterable of inputs; yield result tuples in order.

        Each item is a tuple/list of host arrays (or a single array).
        ``pipeline_depth`` items stay in flight: item k+1's host staging
        and H2D copy overlap item k's compute and D2H fetch, and the
        iterable itself is advanced lazily so a generator's per-item host
        work (decode, resize) overlaps device time too."""
        pending: deque = deque()
        for item in items:
            arrays = tuple(item) if isinstance(item, (tuple, list)) \
                else (item,)
            pending.append(self.submit(*arrays))
            while len(pending) > self._depth:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()

    def __call__(self, *host_arrays: np.ndarray) -> Tuple[np.ndarray, ...]:
        return self.submit(*host_arrays).result()

    # -- AOT warmup / persistent executables ----------------------------
    def _bucket_ladder(self) -> List[int]:
        """Every bucket size this executor can route a batch to: the
        pow2 ladder from ``min_bucket`` up to the (possibly non-pow2)
        ``max_bucket`` cap, or the single static batch."""
        if self._static_batch is not None:
            return [self._static_batch]
        if self._max_bucket is None:
            raise ValueError(
                "warmup(buckets=None) needs a bounded executor "
                "(max_bucket= or static_batch=) to derive the bucket "
                "ladder — pass buckets= explicitly")
        top = self._bucket(self._max_bucket)
        out: List[int] = []
        b = self._min_bucket
        while b < top:
            out.append(b)
            b <<= 1
        out.append(top)
        return out

    def _mesh_shape(self) -> Tuple[Any, ...]:
        """Folded into every AOT/store key (runtime/compile_cache.py):
        a tp resharding changes the key, so tp=2 and tp=4 executables
        never collide across restarts. tp=1 keeps the 1-tuple shape so
        pre-tp store entries stay warm. Under tp the compute mode
        rides along too — gather and sharded formulations compile
        different HLO and must never deserialize into each other."""
        if self._devices is None:
            return (1,)
        if self._tp > 1:
            return (self._dp, self._tp, self._tp_compute)
        return (len(self._devices),)

    def _device_kind(self) -> str:
        dev = (self._device if self._device is not None
               else self._devices[0] if self._devices is not None
               else jax.devices()[0])
        return str(getattr(dev, "device_kind", dev.platform))

    def warmup(self, args_like: Sequence[Any],
               buckets: Optional[Sequence[int]] = None) -> "_cc.WarmupReport":
        """AOT-compile every (bucket, arity, donation-mask, device-layout)
        signature this executor will serve, so no caller ever lands on a
        compiling chip — the reference's ship-prebuilt-engines-in-the-jar
        property, rebuilt for XLA (runtime/compile_cache.py).

        ``args_like``: one entry per batch argument — an example array
        (leading dim = batch, any size; only shape[1:] and dtype are
        read) or a ``(row_shape, dtype)`` pair. ``buckets`` defaults to
        the executor's full bucket ladder.

        Each signature is ``.lower().compile()``-d through the same jit
        cache ``_dispatch`` uses; with a configured store (``cache_dir``
        + ``cache_key``) compiled executables are serialized to disk and
        a restarted process DESERIALIZES them instead of recompiling.
        Dp-sharded buckets compile once against the mesh; round-robin
        buckets compile once per device (each executable is pinned).
        Never raises for cache or compile problems — a failed signature
        just compiles lazily on first use, and the returned
        :class:`~synapseml_tpu.runtime.compile_cache.WarmupReport`
        records each signature's disposition (loaded / compiled /
        error)."""
        from jax.sharding import SingleDeviceSharding

        report = _cc.WarmupReport()
        specs: List[Tuple[Tuple[int, ...], Any]] = []
        for a in args_like:
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                specs.append((tuple(a.shape)[1:], self._staged_dtype(
                    a.dtype, device_rules=isinstance(a, jax.Array))))
            else:
                row, dt = a
                specs.append((tuple(int(d) for d in row),
                              self._staged_dtype(dt)))
        buckets = (self._bucket_ladder() if buckets is None
                   else sorted({int(b) for b in buckets}))
        for bucket in buckets:
            layout = self._layout(bucket)
            sig = tuple(((bucket,) + row, jnp.dtype(dt).name)
                        for row, dt in specs)
            if len(sig) > 1 and layout != "shard":
                # probe the H2D staging formulation for this signature
                # NOW — warmup is the pay-once moment; _dispatch only
                # ever reads the persisted verdict (route() is a table
                # hit when a sibling already landed it)
                _h2d_lane().route(sig)
            mask = self._donate_mask_for_sig(sig)
            if layout == "shard":
                targets = [(None, self._shard_data, self._bound, "shard")]
            elif layout == "tp_rep":
                targets = [(None, self._shard_repl, self._bound, "tp_rep")]
            elif layout == "rr":
                targets = [
                    (i, SingleDeviceSharding(d), self._bound_for_device(d),
                     f"rr{i}")
                    for i, d in enumerate(self._devices)]
            else:
                sh = (SingleDeviceSharding(self._device)
                      if self._device is not None else None)
                targets = [(None, sh, self._bound, "single")]
            for rr_idx, sharding, bound, store_layout in targets:
                aot_key = (sig, mask, layout, rr_idx)
                entry = {"bucket": bucket, "layout": store_layout,
                         "sig": sig}
                with self._tables_lock:
                    warm = aot_key in self._aot
                if warm:
                    entry["status"] = "warm"
                    self._note_warm_sig(sig, mask)
                    report.entries.append(entry)
                    continue
                skey = None
                try:
                    if self._store is not None:
                        skey = _cc.executable_key(
                            self._cache_key, bucket=bucket, sig=sig,
                            layout=store_layout,
                            mesh_shape=self._mesh_shape(),
                            device_kind=self._device_kind())
                        compiled = self._store.load(skey)
                        if compiled is not None:
                            with self._tables_lock:
                                self._aot[aot_key] = compiled
                            entry["status"] = "loaded"
                            self._note_warm_sig(sig, mask)
                            entry["cost_captured"] = self._record_cost(
                                compiled, bucket, sig, store_layout)
                            report.entries.append(entry)
                            continue
                    sds = [jax.ShapeDtypeStruct(s, jnp.dtype(d),
                                                sharding=sharding)
                           if sharding is not None
                           else jax.ShapeDtypeStruct(s, jnp.dtype(d))
                           for s, d in sig]
                    # the XLA compile deliberately runs OUTSIDE the
                    # tables lock: holding it here would stall the
                    # dispatch thread's AOT lookups behind a multi-second
                    # compile (the CC003 shape synlint exists to catch)
                    t0c = time.monotonic()
                    compiled = self._jit_for(len(sds), mask).lower(
                        *bound, *sds).compile()
                    _M_COMPILE_WARM_S.observe(time.monotonic() - t0c)
                    with self._tables_lock:
                        self._aot[aot_key] = compiled
                    entry["status"] = "compiled"
                    self._note_warm_sig(sig, mask)
                    entry["cost_captured"] = self._record_cost(
                        compiled, bucket, sig, store_layout)
                    if skey is not None:
                        entry["persisted"] = self._store.save(skey, compiled)
                except Exception as e:  # noqa: BLE001 - degrade to lazy jit
                    entry["status"] = "error"
                    report.errors.append(
                        f"bucket={bucket} {store_layout}: {e!r}")
                report.entries.append(entry)
        # verdicts may have landed above: drop any dispatch-path H2D
        # memo taken before they did
        self._h2d_choice = {}
        # the sentinel arms HERE: from now on, any trace/compile the
        # dispatch path performs is a counted, classified, ring-recorded
        # recompile incident (signatures warmup failed on — status
        # "error" — surface as shape_drift when they compile lazily)
        with self._tables_lock:
            self._warmed = True
        return report

    def _h2d_choice_for(self, hostp) -> str:
        """Dispatch-path verdict for this host-arg signature: memoized
        per executor, filled from the lane's persisted table (cached
        lookup only — a missing verdict serves per_arg, it never probes
        under a live dispatch)."""
        hkey = tuple((tuple(a.shape), a.dtype.name) for a in hostp)
        try:
            memo = self._h2d_choice
        except AttributeError:
            memo = self._h2d_choice = {}
        got = memo.get(hkey)
        if got is None:
            got = memo[hkey] = _h2d_lane().cached(hkey) or "per_arg"
        return got

    def _record_cost(self, compiled: Any, bucket: int, sig: tuple,
                     store_layout: str) -> bool:
        """Fold one warmed executable into the roofline cost table
        (runtime/costmodel.py) — flops/bytes from XLA's own compiled
        cost model, captured HERE because warmup is the one moment the
        ``Compiled`` object is in hand and the serving path is not yet
        live (zero hot-path cost; the capture is trivial next to the
        compile that just happened). Store-deserialized executables
        are captured too — they may refuse analysis, which degrades to
        an ``unknown``-bound entry, never an error."""
        rec = _cm.record(compiled, bucket=bucket, arity=len(sig),
                         layout=store_layout,
                         device_kind=self._device_kind(), sig=sig)
        return bool(rec and rec.get("captured"))

    def _note_warm_sig(self, sig: tuple, mask: Tuple[bool, ...]):
        """Record one warmed signature for the recompile sentinel's
        post-warmup classification (shape vs arity vs donation drift)."""
        with self._tables_lock:
            self._warm_masks.setdefault(sig, set()).add(mask)
            self._warm_arities.add(len(sig))

    def _classify_recompile(self, sig: tuple, mask: Tuple[bool, ...],
                            retired: bool) -> str:
        """Why is the dispatch path compiling after warmup? Called with
        ``_tables_lock`` held (reads the warm tables only)."""
        if retired:
            return "cache_skew"
        masks = self._warm_masks.get(sig)
        if masks and mask not in masks:
            return "donation_mask"
        if self._warm_arities and len(sig) not in self._warm_arities:
            return "arity"
        return "shape_drift"

    # -- pipeline stages (overridable/patchable per instance) ------------
    def _dispatch(self, arrays, n: int, bucket: int, internal: bool = False):
        """Coerce+pad on host (device-resident slices pass through), start
        the H2D copy and the compute; returns device futures without
        blocking. ``internal`` marks super-chunk slices the executor
        staged itself (safe to donate). Idempotent over pre-staged host
        chunks: the staging pool already coerced+padded them, so the
        re-coerce here is a no-op passthrough.

        With ``devices=``, the bucket either rides ONE sharded jit call
        (batch dim dp-split across the mesh) or — when the bucket does
        not divide over the topology — lands whole on the next device in
        round-robin order. Either way this method stays ordered and
        non-blocking, so the surrounding pipeline semantics (submission
        order, depth backpressure) are untouched."""
        _F_LAT_DISPATCH.fire()
        layout = self._layout(bucket)
        rr_idx: Optional[int] = None
        if layout == "shard":
            placement: Any = self._shard_data
            bound = self._bound
            self._m_disp_one.inc()
        elif layout == "tp_rep":
            # dp-indivisible bucket under tensor parallelism: replicate
            # the batch over the mesh, weights stay tp-sharded
            placement = self._shard_repl
            bound = self._bound
            self._m_disp_one.inc()
        elif layout == "rr":
            with self._tables_lock:
                rr_idx = self._rr_next % len(self._devices)
                self._rr_next += 1
            dev = self._devices[rr_idx]
            placement = dev
            bound = self._bound_for_device(dev)
            self._m_disp_rr[rr_idx].inc()
        else:
            placement = self._device
            bound = self._bound
            self._m_disp_one.inc()
        mc = self._m_bucket.get(bucket)
        if mc is None:  # first batch at this bucket: register the series
            mc = self._m_bucket.setdefault(bucket, _tm.counter(
                "executor_bucket_total", bucket=str(bucket)))
        mc.inc()
        _F_H2D.fire()
        padded = []
        guard: List[int] = []  # external device arrays we did not copy
        host_idx: List[int] = []  # host args awaiting their H2D put
        for i, a in enumerate(arrays):
            if isinstance(a, jax.Array):
                # super-chunk slices pass through; an *external* device
                # array is padded/coerced on device so it lines up with
                # bucket-padded host args
                a, fresh = self._stage_device_array(a, bucket, placement)
                if self._donate and not (fresh or internal):
                    guard.append(i)
                padded.append(a)
                continue
            a = coerce_host_array(np.asarray(a), self._compute_dtype)
            if n < bucket and len(a) < bucket:  # never re-pad a padded tail
                pad = [(0, bucket - n)] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            host_idx.append(i)
            padded.append(a)
        if host_idx:
            hostp = [padded[i] for i in host_idx]
            # the routed H2D formulation (lane "executor_h2d"): verdict
            # consulted from a per-executor memo / the persisted table —
            # NEVER probed here; warmup() is where the probe runs
            if (len(hostp) > 1 and layout != "shard"
                    and self._h2d_choice_for(hostp) == "coalesced"):
                staged = _coalesced_put(hostp, placement)
            else:
                staged = [jax.device_put(a, placement)
                          if placement is not None else a for a in hostp]
            for i, a in zip(host_idx, staged):
                padded[i] = a
        sig = tuple((tuple(a.shape), jnp.dtype(a.dtype).name)
                    for a in padded)
        mask = self._donate_mask_for_sig(sig)
        for i in guard:
            if mask[i]:
                # donation would delete the caller's own buffer
                padded[i] = jnp.copy(padded[i])
        _F_COMPUTE.fire()
        aot_key = (sig, mask, layout, rr_idx)
        retired = False
        with self._tables_lock:
            compiled = self._aot.get(aot_key)
        if compiled is not None:
            # warmup()-precompiled (or store-deserialized) executable:
            # no trace, no XLA compile on the serving path
            try:
                out = compiled(*bound, *padded)
                with self._tables_lock:
                    self._aot_hits += 1
                _M_AOT_HIT.inc()
                return out, n, bucket
            except Exception:  # noqa: BLE001 - degrade, never error
                # aval/sharding drift, or a store-deserialized executable
                # that loads but won't run here (the env fingerprint can't
                # cover every host difference on a shared cache volume):
                # retire the entry and fall back to the lazy jit path — a
                # genuine program error will re-raise from the jit call
                with self._tables_lock:
                    self._aot.pop(aot_key, None)
                _M_AOT_RETIRED.inc()
                retired = True
        else:
            _M_AOT_MISS.inc()
        # -- recompile sentinel (docs/observability.md): the lazy jit
        # call below traces+compiles exactly when this (sig, mask,
        # layout, device) is NEW to this executor. First calls are
        # timed into executor_compile_seconds{phase="dispatch"}; after
        # warmup() they are additionally counted by reason, recorded in
        # the flight-recorder ring (which emits the matching structlog
        # line), and carry the offending signature — a post-warmup
        # recompile is an incident, not a mystery latency spike. Note
        # the timed wall includes the (non-blocking) dispatch start;
        # on a first call the trace+compile dominates it.
        with self._tables_lock:
            unseen = aot_key not in self._lazy_seen
            if unseen:
                self._lazy_seen.add(aot_key)
            reason = (self._classify_recompile(sig, mask, retired)
                      if unseen and self._warmed else None)
        t0 = time.monotonic() if unseen else 0.0
        try:
            out = self._jit_for(len(padded), mask)(*bound, *padded)
        except BaseException:
            if unseen:
                # a first attempt that RAISED (transient XLA error,
                # injected fault) did not cache an executable — un-see
                # the key so the retry's real compile is still counted
                # and timed instead of slipping past the sentinel
                with self._tables_lock:
                    self._lazy_seen.discard(aot_key)
            raise
        if unseen:
            dt = time.monotonic() - t0
            _M_COMPILE_DISP_S.observe(dt)
            if reason is not None:
                _M_RECOMPILE[reason].inc()
                _bb.record(
                    "recompile", level="warn", reason=reason,
                    bucket=bucket, layout=layout,
                    device=(None if rr_idx is None
                            else str(self._devices[rr_idx].id)),
                    seconds=round(dt, 6), signature=repr(sig)[:240])
        return out, n, bucket

    def _fetch(self, out, n: int, bucket: int):
        """Block on one batch's device->host copy. One batched fetch —
        per-leaf np.asarray pays a transfer round trip per output on
        remote chips. Padding is sliced off per leaf; a leaf whose
        leading dim is NOT the batch axis cannot be row-sliced, and
        doing it silently would mis-assign rows (the round-5 NMS-through-
        ONNXModel repro) — fail with a recipe instead.

        Leaves listed in ``device_outputs`` skip the host copy: they
        block until computed (so the future's resolution still means
        "done") and resolve as device-resident ``jax.Array``s, row-
        sliced lazily on device when the bucket padded."""
        leaves = jax.tree_util.tree_leaves(out)
        if self._device_outputs:
            host_idx = [i for i in range(len(leaves))
                        if i not in self._device_outputs]
            fetched = jax.device_get([leaves[i] for i in host_idx])
            pulled = dict(zip(host_idx, fetched))
            for i in range(len(leaves)):
                if i in pulled:
                    leaves[i] = pulled[i]
                else:
                    leaves[i].block_until_ready()
        else:
            leaves = jax.device_get(leaves)
        trimmed = []
        for l in leaves:
            if np.ndim(l) == 0:
                raise ValueError(
                    "executor outputs must carry a batch axis: a scalar "
                    "output aggregates over the PADDING rows of the "
                    f"bucket ({bucket} padded vs {n} real) — keep a "
                    "leading batch dim and reduce outside the executor")
            if len(l) == bucket:
                trimmed.append(l[:n])
            elif len(l) <= n:
                # smaller-than-batch outputs were never sliced before;
                # keep the pass-through (no row mis-assignment occurs)
                trimmed.append(l)
            else:
                raise ValueError(
                    f"executor output with leading dim {len(l)} is not "
                    f"batch-aligned (batch bucket {bucket}, {n} real "
                    "rows): per-row slicing would silently mis-assign "
                    "rows. Batch-align it in-graph — e.g. Reshape "
                    "NonMaxSuppression's [B*C*max_out, 3] output to "
                    "[B, C*max_out, 3] before the graph output.")
        return tuple(trimmed)


class JitCache:
    """Explicit cache of jitted callables keyed by a user key.

    Mirrors the reference's broadcast-model + per-partition-session reuse
    (ref: ONNXModel.scala:497-508) — one compiled executable shared by all
    batches on a host.
    """

    def __init__(self):
        self._cache: Dict[Any, Callable] = {}  # synlint: shared
        self._lock = make_lock("JitCache._lock")

    def get(self, key: Any, build: Callable[[], Callable]) -> Callable:
        # models call this from arbitrary scorer threads: the historical
        # unguarded check-then-set let two threads build two executors
        # for one key and RETURN DIFFERENT ONES (each with its own
        # pipeline + jit cache). build() runs outside the lock — it may
        # trace/compile — and setdefault crowns one winner for everyone.
        with self._lock:
            got = self._cache.get(key)
        if got is None:
            built = build()
            with self._lock:
                got = self._cache.setdefault(key, built)
        return got

    def clear(self):
        """Drop cached callables AND invalidate every open persistent-
        executable store: a test that clears jit caches must not read
        back a memoized (possibly stale) deserialized executable — the
        next load re-reads disk, where a rewritten/deleted entry is
        visible."""
        with self._lock:
            self._cache.clear()
        _cc.invalidate_open_stores()


GLOBAL_JIT_CACHE = JitCache()


def default_device() -> jax.Device:
    return jax.devices()[0]


def local_device_count() -> int:
    return jax.local_device_count()


# -- autotuned H2D staging lane ---------------------------------------------
#
# Lane "executor_h2d": whether a multi-argument bucket's host arrays ride
# one contiguous transfer (concatenate per dtype group, a single
# device_put, device-side slice+reshape back out) or the per-arg
# device_put loop. Per-arg pays one transfer launch per argument; the
# coalesced form pays one host memcpy into a contiguous staging buffer +
# one launch + cheap on-device slices — which side wins is a property of
# arg count, sizes, and the box's transfer path, so it is a MEASURED
# verdict keyed by the full staged signature. Probed from warmup() only
# (the pay-once moment); _dispatch consults the persisted verdict via a
# per-executor memo and never probes on the serving path. Verification
# is bit-exact per element and dtype — pure data movement. The timing
# contrast is honest only because best_of forces with block_until_ready:
# both candidates' results are device-resident, and a D2H fetch in the
# timed region would drown the transfer-launch difference being measured.

def _coalesced_put(arrays, placement):
    """One contiguous transfer per dtype group; singleton groups go
    direct. Device-side slices materialize fresh buffers, so donation
    of any output never aliases a sibling."""
    out = [None] * len(arrays)
    groups: Dict[str, List[int]] = {}
    for i, a in enumerate(arrays):
        groups.setdefault(a.dtype.str, []).append(i)
    for idxs in groups.values():
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = (jax.device_put(arrays[i], placement)
                      if placement is not None else jnp.asarray(arrays[i]))
            continue
        flat = np.concatenate([arrays[i].ravel() for i in idxs])
        packed = (jax.device_put(flat, placement)
                  if placement is not None else jnp.asarray(flat))
        off = 0
        for i in idxs:
            size = arrays[i].size
            out[i] = packed[off:off + size].reshape(arrays[i].shape)
            off += size
    return out


def _h2d_args(sig):
    rng = np.random.default_rng(0)
    out = []
    for shape, dt in sig:
        d = np.dtype(dt)
        if np.issubdtype(d, np.floating):
            out.append(rng.standard_normal(shape).astype(d))
        else:
            out.append(rng.integers(0, 2, shape).astype(d))
    return (out,)


def _h2d_verify(got, want):
    if len(got) != len(want):
        return False
    for g, w in zip(got, want):
        if (g.dtype != w.dtype or g.shape != w.shape
                or not np.array_equal(g, w)):
            return False
    return True


_H2D_LANE = None


def _h2d_lane():
    """Lazy registration — the lane costs nothing until an executor
    with a multi-arg signature warms up."""
    global _H2D_LANE
    if _H2D_LANE is None:
        from synapseml_tpu.runtime import autotune as _at

        dev = default_device()
        _H2D_LANE = _at.register_lane(
            "executor_h2d",
            key_fn=lambda sig: (
                _at.key_prefix("h2d") + "|" + ";".join(
                    f"{'x'.join(str(d) for d in s)}:{t}"
                    for s, t in sig)),
            candidates={
                "per_arg": lambda rargs, args: (
                    lambda arrs: tuple(jax.device_put(a, dev)
                                       for a in arrs)),
                "coalesced": lambda rargs, args: (
                    lambda arrs: tuple(_coalesced_put(arrs, dev))),
            },
            verify_fn=_h2d_verify,
            reference="per_arg",
            args_fn=_h2d_args,
        )
    return _H2D_LANE
