"""Batched-inference executor: the TPU device runtime.

This replaces the reference's per-partition native-session pattern — ONNX
``initializeOrt`` + NIO tensor marshalling (ref: deep-learning/.../onnx/ONNXModel.scala:173-193,357-402)
and CNTK ``applyModel`` (ref: deep-learning/.../cntk/CNTKModel.scala:89-141) —
with a jit-cache-aware executor:

- **Shape bucketing**: XLA compiles one program per input shape. Batches are
  padded up to power-of-two buckets so an arbitrary row stream triggers O(log n)
  compilations, then runs hot.
- **dtype coercion**: host columns are coerced once (e.g. f64→f32→bf16) before
  a single contiguous ``device_put`` — no per-row marshalling hot loop.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def round_up_pow2(n: int, minimum: int = 8) -> int:
    if n <= minimum:
        return minimum
    return 1 << math.ceil(math.log2(n))


_COERCE = {
    np.dtype(np.float64): np.float32,
    np.dtype(np.int64): np.int32,
    np.dtype(np.uint64): np.uint32,
}


def coerce_host_array(arr: np.ndarray, compute_dtype: Optional[Any] = None) -> np.ndarray:
    """Coerce a host column to a TPU-friendly dtype (f64→f32, i64→i32)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype in _COERCE:
        arr = arr.astype(_COERCE[arr.dtype])
    if compute_dtype is not None and np.issubdtype(arr.dtype, np.floating):
        arr = arr.astype(compute_dtype)
    return arr


class BatchedExecutor:
    """Runs ``fn(*arrays) -> arrays`` over row batches with a bucketed jit cache.

    ``fn`` must treat axis 0 of every argument as the batch axis. The executor
    pads the batch to a bucket size, runs the compiled program, and slices the
    padding off the outputs.
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        device: Optional[jax.Device] = None,
        compute_dtype: Any = None,
        min_bucket: int = 8,
        max_bucket: Optional[int] = None,
        static_batch: Optional[int] = None,
        bound_args: Tuple[Any, ...] = (),
    ):
        """``bound_args`` are prepended to every call unpadded — use for a
        weights pytree so it is device-resident and *shared* across all shape
        buckets instead of baked into each compiled program as constants."""
        self._device = device
        self._compute_dtype = compute_dtype
        self._min_bucket = min_bucket
        self._max_bucket = max_bucket
        self._static_batch = static_batch
        self._bound = tuple(
            jax.tree_util.tree_map(
                lambda a: jax.device_put(a, device) if device else jnp.asarray(a),
                b) for b in bound_args)
        self._jit = jax.jit(fn)

    def _bucket(self, n: int) -> int:
        if self._static_batch is not None:
            return self._static_batch
        b = round_up_pow2(n, self._min_bucket)
        if self._max_bucket is not None:
            b = min(b, self._max_bucket)
        return b

    def __call__(self, *host_arrays: np.ndarray) -> Tuple[np.ndarray, ...]:
        n = len(host_arrays[0])
        bucket = self._bucket(max(n, 1))
        if n == 0:
            # run one padded batch to learn output structure; slice to empty
            return self._run_padded(list(host_arrays), 0, bucket)
        outs = []
        for start in range(0, n, bucket):
            stop = min(start + bucket, n)
            outs.append(self._run_padded(
                [a[start:stop] for a in host_arrays], stop - start, bucket))
        if len(outs) == 1:
            return outs[0]
        return tuple(
            np.concatenate([o[i] for o in outs]) for i in range(len(outs[0]))
        )

    def _run_padded(self, arrays, n: int, bucket: int):
        padded = []
        for a in arrays:
            a = coerce_host_array(np.asarray(a), self._compute_dtype)
            if n < bucket:
                pad = [(0, bucket - n)] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            padded.append(
                jax.device_put(a, self._device) if self._device else a)
        out = self._jit(*self._bound, *padded)
        # one batched device->host fetch — per-leaf np.asarray pays a
        # transfer round trip per output on remote chips
        leaves = jax.device_get(jax.tree_util.tree_leaves(out))
        return tuple(l[:n] for l in leaves)


class JitCache:
    """Explicit cache of jitted callables keyed by a user key.

    Mirrors the reference's broadcast-model + per-partition-session reuse
    (ref: ONNXModel.scala:497-508) — one compiled executable shared by all
    batches on a host.
    """

    def __init__(self):
        self._cache: Dict[Any, Callable] = {}

    def get(self, key: Any, build: Callable[[], Callable]) -> Callable:
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]

    def clear(self):
        self._cache.clear()


GLOBAL_JIT_CACHE = JitCache()


def default_device() -> jax.Device:
    return jax.devices()[0]


def local_device_count() -> int:
    return jax.local_device_count()
