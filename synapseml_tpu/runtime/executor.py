"""Batched-inference executor: the TPU device runtime.

This replaces the reference's per-partition native-session pattern — ONNX
``initializeOrt`` + NIO tensor marshalling (ref: deep-learning/.../onnx/ONNXModel.scala:173-193,357-402)
and CNTK ``applyModel`` (ref: deep-learning/.../cntk/CNTKModel.scala:89-141) —
with a jit-cache-aware executor:

- **Shape bucketing**: XLA compiles one program per input shape. Batches are
  padded up to power-of-two buckets so an arbitrary row stream triggers O(log n)
  compilations, then runs hot.
- **dtype coercion**: host columns are coerced once (e.g. f64→f32→bf16) before
  a single contiguous ``device_put`` — no per-row marshalling hot loop.
- **Pipelined feed**: jax dispatch is asynchronous, so the executor keeps
  ``pipeline_depth`` batches in flight — batch N+1's host→device copy and
  compute are dispatched *before* blocking on batch N's device→host fetch,
  hiding transfer latency behind compute (the role ORT's IOBinding plays
  for the reference). Inputs are donated to XLA on non-CPU backends so
  same-bucket batches reuse device buffers instead of allocating.
"""
from __future__ import annotations

import math
from collections import deque
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np


def round_up_pow2(n: int, minimum: int = 8) -> int:
    if n <= minimum:
        return minimum
    return 1 << math.ceil(math.log2(n))


_COERCE = {
    np.dtype(np.float64): np.float32,
    np.dtype(np.int64): np.int32,
    np.dtype(np.uint64): np.uint32,
}


def coerce_host_array(arr: np.ndarray, compute_dtype: Optional[Any] = None) -> np.ndarray:
    """Coerce a host column to a TPU-friendly dtype (f64→f32, i64→i32)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype in _COERCE:
        arr = arr.astype(_COERCE[arr.dtype])
    if compute_dtype is not None and np.issubdtype(arr.dtype, np.floating):
        arr = arr.astype(compute_dtype)
    return arr


class BatchedExecutor:
    """Runs ``fn(*arrays) -> arrays`` over row batches with a bucketed jit cache.

    ``fn`` must treat axis 0 of every argument as the batch axis. The executor
    pads the batch to a bucket size, runs the compiled program, and slices the
    padding off the outputs. Multi-batch calls are pipelined: up to
    ``pipeline_depth`` batches are in flight at once.
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        device: Optional[jax.Device] = None,
        compute_dtype: Any = None,
        min_bucket: int = 8,
        max_bucket: Optional[int] = None,
        static_batch: Optional[int] = None,
        bound_args: Tuple[Any, ...] = (),
        pipeline_depth: int = 2,
        donate: Optional[bool] = None,
        transfer_batches: Union[int, str, None] = None,
    ):
        """``bound_args`` are prepended to every call unpadded — use for a
        weights pytree so it is device-resident and *shared* across all shape
        buckets instead of baked into each compiled program as constants.

        ``donate=None`` donates batch inputs to XLA whenever the target
        backend is not CPU (CPU ignores donation and would warn).

        ``transfer_batches`` groups that many compute buckets into ONE
        explicit host->device copy (compute then runs per bucket on
        device-side slices); ``"auto"`` sizes the group to ~32MB per
        copy. Default 1 — measured on the tunneled v5e, per-bucket
        numpy arg-staging through the pipelined jit dispatch beats
        explicit grouped device_put for BOTH large image batches
        (100 vs 77 img/s) and small tabular rows (34k vs 26k rows/s);
        the option exists for co-located topologies where explicit DMA
        grouping can win (docs/perf.md records the A/Bs)."""
        self._device = device
        self._compute_dtype = compute_dtype
        self._min_bucket = min_bucket
        self._max_bucket = max_bucket
        self._static_batch = static_batch
        self._depth = max(1, int(pipeline_depth))
        self._bound = tuple(
            jax.tree_util.tree_map(
                lambda a: jax.device_put(a, device) if device else jnp.asarray(a),
                b) for b in bound_args)
        plat = (device.platform if device is not None
                else jax.default_backend())
        if donate is None:
            donate = plat not in ("cpu",)
        self._donate = bool(donate)
        if transfer_batches is None:
            transfer_batches = 1
        elif transfer_batches != "auto":
            transfer_batches = max(1, int(transfer_batches))
        self._transfer_batches = transfer_batches  # "auto" = ~32MB groups
        self._fn = fn
        # donation indices depend on the call arity, which is only known at
        # call time — one jitted callable per arity
        self._jits: Dict[int, Callable] = {}

    def _jit_for(self, n_args: int) -> Callable:
        got = self._jits.get(n_args)
        if got is None:
            donate = tuple(range(len(self._bound), len(self._bound) + n_args)) \
                if self._donate else ()
            got = jax.jit(self._fn, donate_argnums=donate)
            self._jits[n_args] = got
        return got

    def _stage_device_array(self, a: jax.Array, target_rows: int):
        """Pad/coerce/place an already-device-resident array entirely on
        device. Returns ``(array, fresh)`` — ``fresh`` is True when a new
        buffer was definitely created (safe to donate)."""
        fresh = False
        if len(a) != target_rows:
            pad = [(0, target_rows - len(a))] + [(0, 0)] * (a.ndim - 1)
            a = jnp.pad(a, pad)
            fresh = True
        if (self._compute_dtype is not None
                and jnp.issubdtype(a.dtype, jnp.floating)
                and a.dtype != jnp.dtype(self._compute_dtype)):
            a = a.astype(self._compute_dtype)
            fresh = True
        if self._device is not None:
            try:
                misplaced = a.device != self._device
            except Exception:  # multi-device/sharded array
                misplaced = True
            if misplaced:
                a = jax.device_put(a, self._device)
                fresh = True
        return a, fresh

    def _bucket(self, n: int) -> int:
        if self._static_batch is not None:
            return self._static_batch
        b = round_up_pow2(n, self._min_bucket)
        if self._max_bucket is not None:
            b = min(b, self._max_bucket)
        return b

    def __call__(self, *host_arrays: np.ndarray) -> Tuple[np.ndarray, ...]:
        n = len(host_arrays[0])
        bucket = self._bucket(max(n, 1))
        if n == 0:
            # run one padded batch to learn output structure; slice to empty
            return self._fetch(*self._dispatch(list(host_arrays), 0, bucket))
        outs = []
        pending: deque = deque()

        def push(item):
            pending.append(item)
            if len(pending) >= self._depth:
                outs.append(self._fetch(*pending.popleft()))

        tb = self._transfer_batches
        if tb == "auto":
            # group buckets up to ~32MB per explicit copy (shape/dtype
            # only — np.asarray on a device array would force a D2H copy)
            row_bytes = 0
            for a in host_arrays:
                a0 = a if hasattr(a, "shape") and hasattr(a, "dtype") \
                    else np.asarray(a)
                itemsize = 2 if (self._compute_dtype is not None
                                 and jnp.issubdtype(a0.dtype, jnp.floating)) \
                    else min(a0.dtype.itemsize, 4)
                row_bytes += int(np.prod(a0.shape[1:], dtype=np.int64)) \
                    * itemsize
            tb = max(1, (32 << 20) // max(1, bucket * row_bytes))
        super_rows = bucket * tb
        for sc_start in range(0, n, super_rows):
            sc_stop = min(sc_start + super_rows, n)
            sc_n = sc_stop - sc_start
            if tb == 1 or sc_n <= bucket:
                # dispatch is async: this batch's H2D copy and compute are
                # in flight before an earlier batch's fetch blocks below
                push(self._dispatch(
                    [a[sc_start:sc_stop] for a in host_arrays], sc_n, bucket))
                continue
            # super-chunk: ONE coerce+pad+copy for transfer_batches buckets,
            # then per-bucket compute on device-side slices. device_put is
            # unconditional here — with device=None it targets the default
            # device; leaving host numpy would quietly re-copy per bucket
            # and void the whole point of grouping
            rows = -(-sc_n // bucket) * bucket
            devs = []
            for a in host_arrays:
                sl = a[sc_start:sc_stop]
                if isinstance(sl, jax.Array):
                    # already device-resident: pad/coerce on device, no
                    # host round trip
                    devs.append(self._stage_device_array(sl, rows)[0])
                    continue
                sl = coerce_host_array(np.asarray(sl), self._compute_dtype)
                if rows > sc_n:
                    sl = np.pad(sl,
                                [(0, rows - sc_n)] + [(0, 0)] * (sl.ndim - 1))
                devs.append(jax.device_put(sl, self._device))
            for b in range(0, sc_n, bucket):
                push(self._dispatch(
                    [d[b:b + bucket] for d in devs],
                    min(bucket, sc_n - b), bucket, internal=True))
        while pending:
            outs.append(self._fetch(*pending.popleft()))
        if len(outs) == 1:
            return outs[0]
        return tuple(
            np.concatenate([o[i] for o in outs]) for i in range(len(outs[0]))
        )

    def _dispatch(self, arrays, n: int, bucket: int, internal: bool = False):
        """Coerce+pad on host (device-resident slices pass through), start
        the H2D copy and the compute; returns device futures without
        blocking. ``internal`` marks super-chunk slices the executor
        staged itself (safe to donate)."""
        padded = []
        for a in arrays:
            if isinstance(a, jax.Array):
                # super-chunk slices pass through; an *external* device
                # array is padded/coerced on device so it lines up with
                # bucket-padded host args
                a, fresh = self._stage_device_array(a, bucket)
                if self._donate and not (fresh or internal):
                    # donation would delete the caller's own buffer
                    a = jnp.copy(a)
                padded.append(a)
                continue
            a = coerce_host_array(np.asarray(a), self._compute_dtype)
            if n < bucket and len(a) < bucket:  # never re-pad a padded tail
                pad = [(0, bucket - n)] + [(0, 0)] * (a.ndim - 1)
                a = np.pad(a, pad)
            padded.append(
                jax.device_put(a, self._device) if self._device else a)
        out = self._jit_for(len(padded))(*self._bound, *padded)
        return out, n, bucket

    def _fetch(self, out, n: int, bucket: int):
        """Block on one batch's device->host copy. One batched fetch —
        per-leaf np.asarray pays a transfer round trip per output on
        remote chips. Padding is sliced off per leaf; a leaf whose
        leading dim is NOT the batch axis cannot be row-sliced, and
        doing it silently would mis-assign rows (the round-5 NMS-through-
        ONNXModel repro) — fail with a recipe instead."""
        leaves = jax.device_get(jax.tree_util.tree_leaves(out))
        trimmed = []
        for l in leaves:
            if np.ndim(l) == 0:
                raise ValueError(
                    "executor outputs must carry a batch axis: a scalar "
                    "output aggregates over the PADDING rows of the "
                    f"bucket ({bucket} padded vs {n} real) — keep a "
                    "leading batch dim and reduce outside the executor")
            if len(l) == bucket:
                trimmed.append(l[:n])
            elif len(l) <= n:
                # smaller-than-batch outputs were never sliced before;
                # keep the pass-through (no row mis-assignment occurs)
                trimmed.append(l)
            else:
                raise ValueError(
                    f"executor output with leading dim {len(l)} is not "
                    f"batch-aligned (batch bucket {bucket}, {n} real "
                    "rows): per-row slicing would silently mis-assign "
                    "rows. Batch-align it in-graph — e.g. Reshape "
                    "NonMaxSuppression's [B*C*max_out, 3] output to "
                    "[B, C*max_out, 3] before the graph output.")
        return tuple(trimmed)


class JitCache:
    """Explicit cache of jitted callables keyed by a user key.

    Mirrors the reference's broadcast-model + per-partition-session reuse
    (ref: ONNXModel.scala:497-508) — one compiled executable shared by all
    batches on a host.
    """

    def __init__(self):
        self._cache: Dict[Any, Callable] = {}

    def get(self, key: Any, build: Callable[[], Callable]) -> Callable:
        if key not in self._cache:
            self._cache[key] = build()
        return self._cache[key]

    def clear(self):
        self._cache.clear()


GLOBAL_JIT_CACHE = JitCache()


def default_device() -> jax.Device:
    return jax.devices()[0]


def local_device_count() -> int:
    return jax.local_device_count()
