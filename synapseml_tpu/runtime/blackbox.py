"""Flight recorder: a bounded ring of structured incident events.

PRs 7-8 built a failure machine — circuit breakers, one-shot failover,
redisperse, graceful drain, fault injection — that fired into the
dark: when a channel tripped, counters moved, but there was no record
of *what the process was doing at that moment*. This module is the
in-memory half of the incident-diagnosis layer (structured logging in
:mod:`~synapseml_tpu.runtime.structlog` is the emitted half):

- :func:`record` appends one structured event — breaker transition,
  failover, redisperse, pipeline break, shed, drain phase, poison
  bisection, slow batch — to a **bounded ring** (default 2048 events;
  the oldest evict). Each event carries a monotone ``seq``, wall +
  monotonic timestamps, and the ``rid``/``channel`` correlation keys
  the spans, logs, and ``X-Request-Id`` headers share. Recording is
  lock-cheap: one uncontended lock around a ``deque.append`` per
  *incident event* — never on the per-request hot path — and a single
  attribute test when disabled (``SYNAPSEML_BLACKBOX=0``).
- :func:`snapshot` returns the ring plus the live telemetry gauges and
  **per-thread stack traces** — the "what was every pipeline thread
  doing" picture. Served live as ``GET /debug/flight`` on every
  serving port.
- :func:`trigger` is the incident hook: it records the trigger event
  and (debounced, default 10s) **dumps the snapshot to a timestamped
  JSON file** in the dump dir. Wired to breaker trips
  (``DistributedServer._record_channel_failure``), executor pipeline
  breaks (``_break_pipeline``), and — via
  :func:`install_signal_trigger` in the serving entry — SIGUSR2, so an
  operator can snapshot a live replica with ``kill -USR2 <pid>``.

Dump dir: ``SYNAPSEML_DUMP_DIR`` (the serving chart points it at a
volume) or ``<tmpdir>/synapseml_flight``. Dumps never raise into the
triggering code path — a failed write is counted and swallowed; the
flight recorder must never make an incident worse.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import tempfile
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional

from synapseml_tpu.runtime import structlog as _slog
from synapseml_tpu.runtime.locksan import make_lock
from synapseml_tpu.runtime import telemetry as _tm

__all__ = [
    "record", "trigger", "snapshot", "dump", "thread_stacks",
    "dump_dir", "set_dump_dir", "last_dump_path", "configure", "reset",
    "enabled", "set_enabled", "install_signal_trigger",
    "DEFAULT_CAPACITY",
]

DEFAULT_CAPACITY = 2048


class _State:
    """Module switchboard + ring. The ring and its metadata are guarded
    by one small lock; a record is one append under it (incident-rate
    events only, so contention is nil), a snapshot copies under it."""

    def __init__(self):
        self.enabled = os.environ.get("SYNAPSEML_BLACKBOX", "") != "0"
        self.lock = make_lock("_State.lock")
        self.ring: "deque[Dict[str, Any]]" = deque(maxlen=DEFAULT_CAPACITY)
        self.seq = itertools.count()
        self.dump_dir: Optional[str] = os.environ.get(
            "SYNAPSEML_DUMP_DIR") or None
        self.min_dump_interval_s = float(os.environ.get(
            "SYNAPSEML_DUMP_MIN_INTERVAL_S", "10"))
        self.last_dump_ts = 0.0
        self.last_dump_path: Optional[str] = None


_S = _State()


def enabled() -> bool:
    return _S.enabled


def set_enabled(on: bool) -> bool:
    """Flip recording globally; returns the previous value."""
    prev = _S.enabled
    _S.enabled = bool(on)
    return prev


def configure(capacity: Optional[int] = None,
              min_dump_interval_s: Optional[float] = None):
    """Resize the ring / retune the dump debounce (tests, serving
    entry). Resizing keeps the newest events."""
    with _S.lock:
        if capacity is not None:
            _S.ring = deque(_S.ring, maxlen=max(1, int(capacity)))
        if min_dump_interval_s is not None:
            _S.min_dump_interval_s = float(min_dump_interval_s)


def reset():
    """Tests only: clear the ring and the dump debounce."""
    with _S.lock:
        _S.ring.clear()
        _S.last_dump_ts = 0.0
        _S.last_dump_path = None


def dump_dir() -> str:
    """Where dumps (and on-demand profiles) land; created lazily."""
    d = _S.dump_dir or os.path.join(tempfile.gettempdir(),
                                    "synapseml_flight")
    return d


def set_dump_dir(path: Optional[str]):
    _S.dump_dir = path


def last_dump_path() -> Optional[str]:
    return _S.last_dump_path


def record(event: str, rid: Optional[str] = None,
           channel: Optional[int] = None, level: str = "info",
           trace: Optional[str] = None,
           **fields: Any) -> None:
    """Append one structured event to the ring and (when logging is on)
    emit it as a structured log line — ONE instrumentation call per
    site keeps the ring and the log telling the same story. Safe under
    locks: the ring lock is a leaf (this module acquires nothing else
    while holding it) and the log emission never blocks the caller.
    The log line is emitted even with the ring disabled
    (``SYNAPSEML_BLACKBOX=0``) — the two layers are independent, and
    turning off the in-memory recorder must not silence the operator's
    incident log. ``trace`` is the distributed-trace correlation key
    (``rid``'s fleet-wide sibling): grep one trace id across any
    replica's ring, log, span store, and the stitched
    ``/fleet/trace`` view and they tell one story."""
    _slog.log(level, event, rid=rid, channel=channel, trace=trace,
              **fields)
    if not _S.enabled:
        return
    ev: Dict[str, Any] = {"seq": next(_S.seq),
                          "ts": round(time.time(), 6),
                          "mono": time.monotonic(),
                          "event": event, "level": level}
    if rid is not None:
        ev["rid"] = rid
    if channel is not None:
        ev["channel"] = channel
    if trace is not None:
        ev["trace"] = trace
    for k, v in fields.items():
        if v is not None:
            ev[k] = v
    # synlint: disable=DS001 - the ring lock is a leaf: record() is the
    # flight recorder and may be called under any lock in the system
    with _S.lock:
        _S.ring.append(ev)


def thread_stacks() -> List[Dict[str, Any]]:
    """Every live thread's name + current stack — the forensic "what
    was the process doing". Pure host-side introspection
    (``sys._current_frames``), no device sync, safe to call from any
    thread including a signal handler."""
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    out: List[Dict[str, Any]] = []
    for ident, frame in sorted(sys._current_frames().items()):
        name, daemon = names.get(ident, (f"thread-{ident}", True))
        stack = [{"file": fs.filename, "line": fs.lineno,
                  "func": fs.name, "code": (fs.line or "").strip()}
                 for fs in traceback.extract_stack(frame)]
        out.append({"name": name, "ident": ident, "daemon": daemon,
                    "stack": stack})
    return out


def snapshot(max_events: Optional[int] = None,
             stacks: bool = True) -> Dict[str, Any]:
    """The full flight picture: ring events (oldest first), live
    telemetry gauges/counters (compact), and per-thread stacks — what
    ``GET /debug/flight`` serves and what a dump file contains."""
    with _S.lock:
        events = list(_S.ring)
        capacity = _S.ring.maxlen
    if max_events is not None:
        events = events[-max_events:]
    snap: Dict[str, Any] = {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "capacity": capacity,
        "n_events": len(events),
        "events": events,
        "telemetry": _tm.snapshot(compact=True),
        # the last 32 completed span breakdowns (trace ids included):
        # a forensic file alone answers "what was in flight, and which
        # traces were those requests" without a live replica to query
        "spans": _tm.completed_spans(32),
    }
    # roofline cost table (runtime/costmodel.py): folded into every
    # dump/flight view so an incident snapshot says what the warmed
    # programs COST, not just what they did. Lazy import (costmodel is
    # upstream of perfwatch, not of the recorder) and best-effort — a
    # forensic snapshot must never fail on its garnish.
    try:
        from synapseml_tpu.runtime import costmodel as _cm

        snap["cost"] = _cm.snapshot()
    except Exception:  # noqa: BLE001
        pass
    if stacks:
        snap["threads"] = thread_stacks()
    return snap


def _dump_target(reason: str) -> tuple:
    """``(path, safe_reason)`` for a new dump file. The seq suffix
    keeps two same-reason dumps inside one wall-clock second (debounce
    tuned low, or distinct triggers) from ``os.replace()``-ing each
    other's forensic file."""
    stamp = (time.strftime("%Y%m%dT%H%M%S", time.gmtime())
             + f"-{next(_S.seq):06d}")
    safe = "".join(c if c.isalnum() or c in "._-" else "_"
                   for c in reason)[:48]
    return (os.path.join(
        dump_dir(), f"flight-{stamp}-{safe}-{os.getpid()}.json"), safe)


def _write_dump(snap: Dict[str, Any], path: str, safe: str,
                reason: str) -> Optional[str]:
    """Atomic tmp-then-rename write; counts, never raises.
    ``last_dump_path`` is set only AFTER the file exists, so a reader
    polling it can open the path immediately."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(snap, fh, default=repr)
        os.replace(tmp, path)  # readers never see a torn dump
    except Exception:  # noqa: BLE001 - the recorder must not worsen incidents
        _tm.counter("blackbox_dump_failures_total").inc()
        return None
    with _S.lock:  # dumpers race (trigger thread vs sigusr2 thread)
        _S.last_dump_path = path
    _tm.counter("blackbox_dumps_total", trigger=safe).inc()
    _slog.log("info", "flight_dump", reason=reason, path=path)
    return path


def dump(reason: str, **fields: Any) -> Optional[str]:
    """Snapshot + write to ``<dump_dir>/flight-<utc>-<seq>-<reason>-
    <pid>.json`` NOW, synchronously (no debounce — :func:`trigger` is
    the debounced entry). Returns the path, or None when disabled or
    the write failed."""
    if not _S.enabled:
        return None
    snap = snapshot()
    snap["trigger"] = {"reason": reason, **fields}
    path, safe = _dump_target(reason)
    return _write_dump(snap, path, safe, reason)


def trigger(reason: str, rid: Optional[str] = None,
            channel: Optional[int] = None,
            **fields: Any) -> Optional[str]:
    """The incident hook: record the trigger as a ring event, then dump
    — debounced (``min_dump_interval_s``, default 10s) so a flapping
    breaker or a kill-storm produces one forensic file per window, not
    a dump per failure.

    The SNAPSHOT (ring + gauges + thread stacks) is taken inline —
    forensics must show the process AT the incident — but the file
    write happens on a background thread: triggers sit on failure
    paths (a breaker trip mid-failover, a pipeline break before its
    futures are failed), and a slow dump volume must not stretch the
    client-visible recovery it interrupts. Returns the destination
    path when a dump was started (``last_dump_path`` flips to it once
    the file is fully written)."""
    record(reason, rid=rid, channel=channel, level="warn", **fields)
    if not _S.enabled:
        return None
    now = time.monotonic()
    with _S.lock:
        if (_S.last_dump_ts
                and now - _S.last_dump_ts < _S.min_dump_interval_s):
            return None
        _S.last_dump_ts = now
    snap = snapshot()
    snap["trigger"] = {k: v for k, v in
                       {"reason": reason, "rid": rid,
                        "channel": channel, **fields}.items()
                       if v is not None}
    path, safe = _dump_target(reason)
    # synlint: disable=RL001 - one-shot dump writer, not a loop; a
    # failed dump must never take the serving process with it
    threading.Thread(target=_write_dump, args=(snap, path, safe, reason),
                     name="blackbox-dump", daemon=True).start()
    return path


def install_signal_trigger(signum: Optional[int] = None) -> bool:
    """Install a SIGUSR2 (or ``signum``) handler that dumps a flight
    snapshot — the operator's ``kill -USR2 <pid>`` surface. Main-thread
    only (signal module restriction); returns False where unsupported
    (e.g. Windows has no SIGUSR2) instead of raising, so the serving
    entry stays portable.

    The handler HANDS OFF to a fresh thread instead of dumping inline:
    Python signal handlers interrupt the main thread between bytecodes,
    so an inline dump could re-acquire a non-reentrant lock the
    interrupted frame already holds (the ring lock mid-``record``, the
    log write lock, the telemetry registry lock mid-snapshot) and
    deadlock the process — the one outcome a debugging surface must
    never cause."""
    import signal as _signal

    if signum is None:
        signum = getattr(_signal, "SIGUSR2", None)
        if signum is None:
            return False

    def _handler(*_):
        # synlint: disable=RL001 - one-shot signal handoff (see the
        # docstring): inline dumping could deadlock the main thread
        threading.Thread(target=trigger, args=("sigusr2",),
                         name="blackbox-sigusr2", daemon=True).start()

    try:
        _signal.signal(signum, _handler)
        return True
    except (ValueError, OSError):  # not the main thread / unsupported
        return False
