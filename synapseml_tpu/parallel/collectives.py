"""Thin wrappers over XLA collectives used throughout the framework.

The reference's per-iteration data plane is TCP: lib_lightgbm's internal
socket collectives and VW's spanning-tree AllReduce (SURVEY.md §2.10).
Here every collective is an XLA op riding ICI (intra-slice) / DCN
(multi-slice), inserted either explicitly inside ``shard_map`` regions or
automatically by GSPMD from sharding annotations.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def all_reduce_sum(x, axis: AxisName):
    return lax.psum(x, axis)


def all_reduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis)


def all_gather(x, axis: AxisName, *, gather_axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def axis_size(axis: str):
    """Concrete size of a mapped axis. ``lax.axis_size`` only exists on
    newer jax; ``psum(1, axis)`` constant-folds to the same python int on
    every version (the pre-axis_size idiom), so use it as the fallback."""
    got = getattr(lax, "axis_size", None)
    if got is not None:
        return got(axis)
    return lax.psum(1, axis)


def ring_permute(x, axis: str, shift: int = 1):
    """Send this shard to the next rank on ``axis`` (a ring step)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def barrier_sum(axis: AxisName):
    """Cheap gang barrier: psum of a scalar. The TPU analogue of the
    reference's BarrierTaskContext.barrier() gang scheduling
    (ref: lightgbm/.../LightGBMBase.scala:482-483)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)


# ---------------------------------------------------------------------------
# Topology-aware strategies
# ---------------------------------------------------------------------------

def two_level_all_reduce(x, inner_axis: str, outer_axis: str,
                         scatter_axis: int = 0):
    """All-reduce over ``inner_axis`` x ``outer_axis`` that minimizes
    traffic on the *outer* (slow) links — the multi-slice schedule for a
    mesh whose inner axis rides ICI and outer axis rides DCN.

    A flat ``psum`` over both axes moves the full payload across DCN per
    step; this sends only ``1/|inner|`` of it: reduce-scatter inside the
    slice (ICI), all-reduce the shard across slices (DCN), all-gather
    back inside the slice (ICI). Equivalent to
    ``psum(x, (inner, outer))`` — the reference's analogue is
    lib_lightgbm's single-level socket allreduce, which has no topology
    tiering at all (SURVEY.md §2.10).

    ``x``'s ``scatter_axis`` dimension must be divisible by the inner
    axis size (pad if needed).
    """
    shard = lax.psum_scatter(x, inner_axis, scatter_dimension=scatter_axis,
                             tiled=True)                     # ICI
    shard = lax.psum(shard, outer_axis)                      # DCN, 1/|inner|
    return lax.all_gather(shard, inner_axis, axis=scatter_axis,
                          tiled=True)                        # ICI


def ring_all_reduce(x, axis: str, chunk_axis: int = 0):
    """Explicit bidirectional-free ring all-reduce: 2(n-1) ``ppermute``
    steps (n-1 reduce-scatter, n-1 all-gather), each moving ``1/n`` of
    the payload to the ring neighbor.

    XLA's own psum lowers to an equivalent schedule on an ICI ring; the
    explicit form exists for fusion with per-chunk compute (the ring-
    attention pattern, parallel/ring_attention.py) and as the measured
    reference when validating psum performance. Requires
    ``x.shape[chunk_axis] % n == 0``.
    """
    n = axis_size(axis)
    if n == 1:
        return x
    me = lax.axis_index(axis)
    chunks = list(jnp.split(x, n, axis=chunk_axis))
    perm = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter phase: at step t rank r forwards its partial of
    # chunk (r - t) and folds its local copy into the incoming partial of
    # chunk (r - t - 1); after n-1 steps rank r holds the FULL sum of
    # chunk (r + 1) % n  (me is traced -> dynamic chunk select)
    acc = _select_chunk(chunks, me % n)
    for t in range(n - 1):
        acc = lax.ppermute(acc, axis, perm)
        acc = acc + _select_chunk(chunks, (me - t - 1) % n)

    # all-gather phase: circulate the finished chunk n-1 times
    out_chunks = [acc]
    cur = acc
    for _ in range(n - 1):
        cur = lax.ppermute(cur, axis, perm)
        out_chunks.append(cur)
    # after the gather phase, out_chunks[j] is the chunk finished by rank
    # (me - j) % n, i.e. chunk id (me - j + 1) % n — reassemble in chunk
    # order with a rank-dependent (traced) inverse permutation
    stacked = jnp.stack(out_chunks, axis=0)  # [n, ...] j-th = chunk(me-j+1)
    chunk_ids = (me - jnp.arange(n) + 1) % n
    inv = jnp.zeros((n,), jnp.int32).at[chunk_ids].set(
        jnp.arange(n, dtype=jnp.int32))
    gathered = jnp.take(stacked, inv, axis=0)
    return jnp.concatenate(
        [jnp.squeeze(c, 0) for c in jnp.split(gathered, n, axis=0)],
        axis=chunk_axis)


def _select_chunk(chunks, idx):
    """chunks[idx] with a traced idx: stack once, dynamic-index."""
    return jnp.take(jnp.stack(chunks, axis=0), idx, axis=0)
