"""Thin wrappers over XLA collectives used throughout the framework.

The reference's per-iteration data plane is TCP: lib_lightgbm's internal
socket collectives and VW's spanning-tree AllReduce (SURVEY.md §2.10).
Here every collective is an XLA op riding ICI (intra-slice) / DCN
(multi-slice), inserted either explicitly inside ``shard_map`` regions or
automatically by GSPMD from sharding annotations.
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

AxisName = Union[str, Sequence[str]]


def all_reduce_sum(x, axis: AxisName):
    return lax.psum(x, axis)


def all_reduce_mean(x, axis: AxisName):
    return lax.pmean(x, axis)


def all_gather(x, axis: AxisName, *, gather_axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, scatter_axis: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def ring_permute(x, axis: str, shift: int = 1):
    """Send this shard to the next rank on ``axis`` (a ring step)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)


def barrier_sum(axis: AxisName):
    """Cheap gang barrier: psum of a scalar. The TPU analogue of the
    reference's BarrierTaskContext.barrier() gang scheduling
    (ref: lightgbm/.../LightGBMBase.scala:482-483)."""
    return lax.psum(jnp.ones((), jnp.int32), axis)
